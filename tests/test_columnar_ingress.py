"""Socket→columnar composition (VERDICT r4 missing #5): N real client
sockets aggregate into batched ``ingest_planes`` dispatches through the
binary columnar front door, with oracle parity from the durable log."""

import numpy as np
import pytest

from fluidframework_tpu.server import native_deli
from fluidframework_tpu.server.columnar_ingress import (
    ColumnarAlfred, ColumnarClient, _OP_DTYPE,
)
from fluidframework_tpu.server.serving import StringServingEngine

pytestmark = pytest.mark.skipif(not native_deli.available(),
                                reason="native sequencer unavailable")


def _mk(n_docs=32, window_min_rows=8, window_ms=5.0):
    eng = StringServingEngine(n_docs=n_docs, capacity=256,
                              batch_window=10 ** 9, sequencer="native")
    srv = ColumnarAlfred(eng, window_min_rows=window_min_rows,
                         window_ms=window_ms).start_in_thread()
    return eng, srv


def _ops(rows, kinds, a0s, a1s, tidxs, cseqs, refs):
    ops = np.zeros(len(rows), _OP_DTYPE)
    ops["row"] = rows
    ops["kind"] = kinds
    ops["a0"] = a0s
    ops["a1"] = a1s
    ops["tidx"] = tidxs
    ops["cseq"] = cseqs
    ops["ref"] = refs
    return ops


def test_sockets_compose_into_columnar_windows():
    eng, srv = _mk()
    try:
        n_clients, docs_per, waves = 3, 4, 6
        clients = []
        for c in range(n_clients):
            cl = ColumnarClient("127.0.0.1", srv.port)
            docs = [f"c{c}-d{j}" for j in range(docs_per)]
            cl.join(docs)
            clients.append((cl, docs))
        for w in range(waves):
            for cl, docs in clients:
                rows = [cl.rows[d] for d in docs]
                ops = _ops(rows, [0] * docs_per, [0] * docs_per,
                           [0] * docs_per, [0] * docs_per,
                           [w + 1] * docs_per, [0] * docs_per)
                cl.send_ops([f"t{w}."], ops)
        # every op acks with a positive seq
        for cl, docs in clients:
            acked = 0
            while acked < docs_per * waves:
                resp = cl.recv_json()
                assert resp["t"] == "acks", resp
                for cs, seq in resp["acks"]:
                    assert seq > 0, (cs, seq)
                    acked += 1
        assert srv.ops_ingested == n_clients * docs_per * waves
        # aggregation happened: far fewer windows than ops
        assert srv.windows_flushed <= waves * n_clients
        # oracle parity from the durable log on sampled docs
        from fluidframework_tpu.models.shared_string import SharedString
        for cl, docs in clients[:2]:
            d = docs[1]
            oracle = SharedString(d, 999)
            for m in eng._doc_log_messages(d):
                oracle.process_core(m, local=False)
            assert eng.read_text(d) == oracle.get_text(), d
        for cl, _ in clients:
            cl.close()
    finally:
        srv.stop()


def test_mixed_inserts_and_removes_share_one_doc():
    eng, srv = _mk(window_min_rows=1, window_ms=2.0)
    try:
        a = ColumnarClient("127.0.0.1", srv.port)
        b = ColumnarClient("127.0.0.1", srv.port)
        a.join(["shared"])
        b.join(["shared"])
        row = a.rows["shared"]
        a.send_ops(["hello"], _ops([row], [0], [0], [0], [0], [1], [0]))
        s1 = a.recv_json()["acks"][0][1]
        assert s1 > 0
        # b inserts at pos 2 AT THE PERSPECTIVE of a's op (ref = its seq)
        b.send_ops(["XY"], _ops([row], [0], [2], [0], [0], [1], [s1]))
        s2 = b.recv_json()["acks"][0][1]
        assert s2 > 0
        a.send_ops([], _ops([row], [1], [0], [1], [0], [2], [s2]))
        assert a.recv_json()["acks"][0][1] > 0
        from fluidframework_tpu.models.shared_string import SharedString
        oracle = SharedString("shared", 999)
        for m in eng._doc_log_messages("shared"):
            oracle.process_core(m, local=False)
        assert eng.read_text("shared") == oracle.get_text()
        a.close()
        b.close()
    finally:
        srv.stop()


def test_malformed_op_frames_rejected_whole():
    """tidx out of table range / ragged record sections reject the WHOLE
    frame with an error frame (no half-enqueued batch)."""
    from fluidframework_tpu.server.columnar_ingress import encode_frame
    eng, srv = _mk()
    try:
        cl = ColumnarClient("127.0.0.1", srv.port)
        cl.join(["d0"])
        row = cl.rows["d0"]
        cl.send_ops(["only-one"], _ops([row, row], [0, 0], [0, 0],
                                       [0, 0], [0, 7], [1, 2], [0, 0]))
        resp = cl.recv_json()
        assert resp["t"] == "error" and "tidx" in resp["message"]
        cl.close()
        c2 = ColumnarClient("127.0.0.1", srv.port)
        c2.join(["d1"])
        c2.sock.sendall(encode_frame(b"B", bytes([0]) + b"\x01" * 17))
        resp = c2.recv_json()
        assert resp["t"] == "error" and "record" in resp["message"]
        c2.close()
        assert srv.ops_ingested == 0 and srv._pending_ops == 0
    finally:
        srv.stop()


def test_bad_row_and_bad_crc_handling():
    eng, srv = _mk()
    try:
        cl = ColumnarClient("127.0.0.1", srv.port)
        cl.join(["d0"])
        cl.send_ops(["x"], _ops([999], [0], [0], [0], [0], [1], [0]))
        resp = cl.recv_json()
        assert resp["t"] == "error" and "out of range" in resp["message"]
        cl.close()
        # a second client still works after the first one's bad frame
        c2 = ColumnarClient("127.0.0.1", srv.port)
        c2.join(["d1"])
        row = c2.rows["d1"]
        c2.send_ops(["ok"], _ops([row], [0], [0], [0], [0], [1], [0]))
        while True:
            resp = c2.recv_json()
            if resp["t"] == "acks":
                break
        assert resp["acks"][0][1] > 0
        c2.close()
    finally:
        srv.stop()


def test_pipelined_front_door_parity_and_stats():
    """The depth-3 pipelined front door must produce the same final doc
    texts as a depth-0 (serial round-trip per window) server on the same
    deterministic op stream, while actually engaging the executor
    (waves flushed through it, acks only after durable append)."""
    def _run_stream(pipeline_depth):
        eng = StringServingEngine(n_docs=32, capacity=256,
                                  batch_window=10 ** 9,
                                  sequencer="native")
        srv = ColumnarAlfred(eng, window_min_rows=4, window_ms=1.0,
                             pipeline_depth=pipeline_depth
                             ).start_in_thread()
        texts = {}
        try:
            n_clients, docs_per, waves = 2, 3, 12
            clients = []
            for c in range(n_clients):
                cl = ColumnarClient("127.0.0.1", srv.port)
                docs = [f"c{c}-d{j}" for j in range(docs_per)]
                cl.join(docs)
                clients.append((cl, docs))
            for w in range(waves):
                for ci, (cl, docs) in enumerate(clients):
                    rows = [cl.rows[d] for d in docs]
                    # deterministic per-doc content: each doc's final
                    # text is independent of cross-client interleaving
                    cl.send_ops([f"w{w}c{ci}."],
                                _ops(rows, [0] * docs_per, [0] * docs_per,
                                     [0] * docs_per, [0] * docs_per,
                                     [w + 1] * docs_per, [0] * docs_per))
            for cl, docs in clients:
                acked = 0
                while acked < docs_per * waves:
                    resp = cl.recv_json()
                    assert resp["t"] == "acks", resp
                    for _cs, seq in resp["acks"]:
                        assert seq > 0
                        acked += 1
            stats = srv.pipeline_stats()
            windows = srv.windows_flushed
            for cl, docs in clients:
                for d in docs:
                    texts[d] = eng.read_text(d)
                cl.close()
        finally:
            srv.stop()
        return texts, stats, windows

    serial_texts, serial_stats, _ = _run_stream(0)
    pipe_texts, pipe_stats, pipe_windows = _run_stream(3)
    assert serial_stats is None           # depth 0 = no executor
    assert pipe_texts == serial_texts     # front doors agree op-for-op
    assert pipe_stats is not None
    assert pipe_stats["depth"] == 3
    assert pipe_stats["waves"] == pipe_windows  # every window pipelined
    assert pipe_stats["waves"] > 0
    assert pipe_stats["max_inflight"] >= 1
