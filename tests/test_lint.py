"""Doc-registry lints (ISSUE 19 satellite): the AST sweeps that keep
docs/OBSERVABILITY.md honest, as a tier-1 gate.

Two lints:

* metric-name lint — every metric name used anywhere in the tree
  (``.inc(`` / ``.set_gauge(`` / ``.observe(`` with a literal name)
  must appear backtick-quoted in the doc's metric registry table. A
  counter nobody documented is a counter nobody reads.
* route lint — every ``/debug/*`` route registered in
  ``server/opsd.py`` must appear backtick-quoted in the doc's routes
  table. An undocumented debug route is a debug route nobody curls.
"""

import ast
import pathlib

import pytest

pytestmark = pytest.mark.telemetry

PKG_ROOT = pathlib.Path(__file__).resolve().parent.parent \
    / "fluidframework_tpu"
DOC = PKG_ROOT.parent / "docs" / "OBSERVABILITY.md"


# ------------------------------------------------------- metric-name lint

def metric_names_in_tree():
    """AST sweep of every ``.inc(`` / ``.set_gauge(`` / ``.observe(``
    call whose first argument names a metric: string literals verbatim,
    f-strings as their literal prefix + ``*`` (the per-reason counter
    families), and both arms of a literal conditional. ``observe``
    calls with a non-string first arg are ``Histogram.observe(value)``
    — not a name site. Returns ``{name: "file:line"}``."""
    roots = [PKG_ROOT,
             PKG_ROOT.parent / "bench.py",
             PKG_ROOT.parent / "tools"]
    files = []
    for r in roots:
        files += sorted(r.rglob("*.py")) if r.is_dir() else [r]
    kinds = {"inc", "set_gauge", "observe"}
    names = {}

    def literal_names(node):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return [node.value]
        if isinstance(node, ast.JoinedStr) and node.values and \
                isinstance(node.values[0], ast.Constant):
            return [str(node.values[0].value) + "*"]
        if isinstance(node, ast.IfExp):
            return literal_names(node.body) + literal_names(node.orelse)
        return []

    for path in files:
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in kinds and node.args):
                continue
            for name in literal_names(node.args[0]):
                names.setdefault(name, f"{path.name}:{node.lineno}")
    return names


def test_metric_names_all_in_observability_doc():
    doc = DOC.read_text()
    names = metric_names_in_tree()
    assert names, "AST sweep found no metric call sites — lint is broken"
    assert len(names) > 20, f"sweep saw too few sites: {sorted(names)}"
    missing = [f"{n} ({where})" for n, where in sorted(names.items())
               if f"`{n}`" not in doc]
    assert not missing, (
        "metric names missing from docs/OBSERVABILITY.md's registry "
        f"table: {missing}")


# ------------------------------------------------------------- route lint

def debug_routes_in_opsd():
    """AST sweep of ``server/opsd.py`` for ``.route("<path>", ...)``
    registrations. Returns ``{path: line}`` for every literal route."""
    src = (PKG_ROOT / "server" / "opsd.py").read_text()
    routes = {}
    for node in ast.walk(ast.parse(src)):
        if (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "route" and node.args
                and isinstance(node.args[0], ast.Constant)
                and isinstance(node.args[0].value, str)):
            routes.setdefault(node.args[0].value, node.lineno)
    return routes


def test_all_debug_routes_documented():
    doc = DOC.read_text()
    routes = debug_routes_in_opsd()
    assert routes, "route sweep found nothing — lint is broken"
    assert any(r.startswith("/debug/") for r in routes), \
        f"no /debug routes found: {sorted(routes)}"
    missing = [f"{r} (opsd.py:{line})"
               for r, line in sorted(routes.items())
               if r.startswith("/debug/") and f"`{r}`" not in doc]
    assert not missing, (
        "/debug routes missing from docs/OBSERVABILITY.md's routes "
        f"table: {missing}")
