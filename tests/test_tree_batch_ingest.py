"""Batched tree ingest (TreeServingEngine.ingest_batch): parity with the
per-op submit path, nacks, and recovery of family="tree" batch records."""

import numpy as np
import pytest

from fluidframework_tpu.server import native_deli
from fluidframework_tpu.server.serving import TreeServingEngine

pytestmark = pytest.mark.skipif(not native_deli.available(),
                                reason="native sequencer unavailable")


def _ops_wave(docs, wave):
    """One edit per doc: insert a node under root, then set its value."""
    doc_ids, ops = [], []
    for d in docs:
        doc_ids.append(d)
        if wave == 0:
            ops.append({"op": "insert", "parent": "root", "field": "kids",
                        "after": None,
                        "nodes": [{"id": f"{d}-n0", "type": "item",
                                   "value": 0}]})
        else:
            prev = f"{d}-n{wave - 1}"
            ops.append({"op": "transaction",
                        "constraints": [{"nodeExists": prev}],
                        "edits": [
                            {"op": "insert", "parent": "root",
                             "field": "kids", "after": prev,
                             "nodes": [{"id": f"{d}-n{wave}",
                                        "type": "item", "value": wave}]},
                            {"op": "setValue", "id": prev,
                             "value": wave * 10}]})
    return doc_ids, ops


def _mk(R=16):
    eng = TreeServingEngine(n_docs=R, capacity=128, batch_window=10 ** 9,
                            sequencer="native")
    ora = TreeServingEngine(n_docs=R, capacity=128, batch_window=10 ** 9)
    docs = [f"t-{i}" for i in range(R)]
    for e in (eng, ora):
        for d in docs:
            e.connect(d, 1)
    return eng, ora, docs


def test_tree_batch_matches_per_op_engine():
    eng, ora, docs = _mk()
    for wave in range(4):
        doc_ids, ops = _ops_wave(docs, wave)
        res = eng.ingest_batch(doc_ids, [1] * len(ops),
                               [wave + 1] * len(ops), [0] * len(ops), ops)
        assert res["nacked"] == 0
        for d, op in zip(doc_ids, ops):
            _, nack = ora.submit(d, 1, wave + 1, 0, op)
            assert nack is None
    for d in docs:
        assert eng.to_dict(d) == ora.to_dict(d), d
    assert np.array_equal(eng.store.digests(), ora.store.digests())


def test_tree_batch_nack_skipped():
    eng, _, docs = _mk(R=4)
    doc_ids, ops = _ops_wave(docs, 0)
    cseqs = [1, 99, 1, 1]  # doc 1's clientSeq gap nacks
    res = eng.ingest_batch(doc_ids, [1] * 4, cseqs, [0] * 4, ops)
    assert res["nacked"] == 1
    assert res["seq"][1] < 0
    assert not eng.has_node(docs[1], f"{docs[1]}-n0")
    assert eng.has_node(docs[0], f"{docs[0]}-n0")


def test_tree_batch_recovery_through_log_replay():
    eng, _, docs = _mk(R=8)
    doc_ids, ops = _ops_wave(docs, 0)
    eng.ingest_batch(doc_ids, [1] * len(ops), [1] * len(ops),
                     [0] * len(ops), ops)
    summary = eng.summarize()
    for wave in (1, 2):
        doc_ids, ops = _ops_wave(docs, wave)
        assert eng.ingest_batch(doc_ids, [1] * len(ops),
                                [wave + 1] * len(ops), [0] * len(ops),
                                ops)["nacked"] == 0
    want = {d: eng.to_dict(d) for d in docs}
    revived = TreeServingEngine.load(summary, eng.log)
    assert {d: revived.to_dict(d) for d in docs} == want
    _, nack = revived.submit(docs[0], 1, 4, 0,
                             {"op": "setValue", "id": f"{docs[0]}-n0",
                              "value": "tail"})
    assert nack is None
    assert revived.node_value(docs[0], f"{docs[0]}-n0") == "tail"


def test_tree_batch_overflow_recovery_expands_columnar():
    """A doc rebuilt from the log must replay ops logged as whole-batch
    tree records (the rebuild path expands family='tree')."""
    eng, _, docs = _mk(R=4)
    d = docs[0]
    # many sibling inserts via batches until the doc overflows cap 128
    cseq = 1
    for wave in range(3):
        ids = [d] * 50
        ops = []
        for k in range(50):
            ops.append({"op": "insert", "parent": "root", "field": "kids",
                        "after": None,
                        "nodes": [{"id": f"{d}-w{wave}-{k}",
                                   "type": "x", "value": k}]})
        res = eng.ingest_batch(ids, [1] * 50,
                               list(range(cseq, cseq + 50)), [0] * 50, ops)
        assert res["nacked"] == 0
        cseq += 50
    assert eng.store.overflowed()[eng.doc_row(d)]
    report = eng.recover_overflowed()
    assert report.get(d) == "graduated", report
    assert eng.node_count(d) == 151  # root + 150 inserts, none lost


def test_tree_leaves_matches_per_op_engine():
    """ingest_leaves (the vectorized flat-insert path) must match the
    per-op submit path node for node, including sibling order."""
    eng, ora, docs = _mk(R=8)
    for wave in range(3):
        ids = list(docs)
        parents = ["root"] * len(ids)
        fields = ["kids"] * len(ids)
        nodes = [f"{d}-L{wave}" for d in ids]
        vals = [{"w": wave, "d": d} for d in ids]
        typs = ["leaf"] * len(ids)
        afters = [None if wave == 0 else f"{d}-L{wave - 1}" for d in ids]
        res = eng.ingest_leaves(ids, [1] * len(ids),
                                [wave + 1] * len(ids), [0] * len(ids),
                                parents, fields, nodes, vals, typs,
                                afters)
        assert res["nacked"] == 0
        for i, d in enumerate(ids):
            _, nack = ora.submit(d, 1, wave + 1, 0,
                                 {"op": "insert", "parent": "root",
                                  "field": "kids", "after": afters[i],
                                  "nodes": [{"id": nodes[i],
                                             "type": "leaf",
                                             "value": vals[i]}]})
            assert nack is None
    for d in docs:
        assert eng.to_dict(d) == ora.to_dict(d), d
    assert np.array_equal(eng.store.digests(), ora.store.digests())


def test_tree_leaves_recovery_and_mixing():
    """Leaves batches must replay from the log (family tree_flat) and
    interleave with per-op submits and general batches."""
    eng, _, docs = _mk(R=4)
    summary = eng.summarize()
    ids = list(docs)
    res = eng.ingest_leaves(ids, [1] * 4, [1] * 4, [0] * 4,
                            ["root"] * 4, ["kids"] * 4,
                            [f"{d}-a" for d in ids], [1] * 4)
    assert res["nacked"] == 0
    # general batch and per-op submit continue the same seq space
    doc_ids, ops = _ops_wave(docs, 1)
    ops = [{"op": "setValue", "id": f"{d}-a", "value": "set"}
           for d in doc_ids]
    assert eng.ingest_batch(doc_ids, [1] * 4, [2] * 4, [0] * 4,
                            ops)["nacked"] == 0
    _, nack = eng.submit(docs[0], 1, 3, 0,
                         {"op": "insert", "parent": f"{docs[0]}-a",
                          "field": "sub", "after": None,
                          "nodes": [{"id": f"{docs[0]}-b",
                                     "type": None, "value": 9}]})
    assert nack is None
    want = {d: eng.to_dict(d) for d in docs}
    revived = TreeServingEngine.load(summary, eng.log)
    assert {d: revived.to_dict(d) for d in docs} == want


def test_tree_leaves_validation_and_nacks():
    eng, _, docs = _mk(R=2)
    seq_before = {d: eng.deli.doc_seq(d) for d in docs}
    with pytest.raises(ValueError, match="non-empty str"):
        eng.ingest_leaves([docs[0]], [1], [1], [0], [""], ["f"],
                          ["n"], [1])
    with pytest.raises(ValueError, match="unserializable"):
        eng.ingest_leaves([docs[0]], [1], [1], [0], ["root"], ["f"],
                          ["n"], [set()])
    for d in docs:
        assert eng.deli.doc_seq(d) == seq_before[d]
    # a clientSeq gap nacks just that op; the rest apply
    res = eng.ingest_leaves([docs[0], docs[0], docs[1]], [1] * 3,
                            [1, 99, 1], [0] * 3, ["root"] * 3,
                            ["kids"] * 3, ["x0", "x1", "y0"],
                            [0, 1, 2])
    assert res["nacked"] == 1 and res["seq"][1] < 0
    assert eng.has_node(docs[0], "x0")
    assert not eng.has_node(docs[0], "x1")
    assert eng.has_node(docs[1], "y0")


def test_tree_leaves_bad_types_afters_values_rejected_pre_seq():
    """Review r4: malformed types/afters/unsortable values must be
    rejected BEFORE sequencing (a post-sequencing crash poisons)."""
    eng, _, docs = _mk(R=2)
    d = docs[0]
    before = eng.deli.doc_seq(d)
    with pytest.raises(ValueError, match="type"):
        eng.ingest_leaves([d], [1], [1], [0], ["root"], ["f"], ["n"],
                          [1], types=[["x"]])
    with pytest.raises(ValueError, match="after"):
        eng.ingest_leaves([d], [1], [1], [0], ["root"], ["f"], ["n"],
                          [1], afters=[7])
    with pytest.raises(ValueError, match="unserializable"):
        eng.ingest_leaves([d], [1], [1], [0], ["root"], ["f"], ["n"],
                          [{"a": 1, 2: 3}])   # unsortable mixed keys
    assert eng.deli.doc_seq(d) == before
    eng.summarize()  # not poisoned
