"""Columnar ingest path (ingest_planes): the per-op submit pipeline and the
columnar pipeline must produce identical serving state — same sequencing
policies (C++ vs Python Deli), same device merge, same durable-log recovery.
"""

import numpy as np
import pytest

from fluidframework_tpu.ops.schema import OpKind
from fluidframework_tpu.server import native_deli
from fluidframework_tpu.server.oplog import PartitionedLog
from fluidframework_tpu.server.serving import ColumnarOps, StringServingEngine
from fluidframework_tpu.testing.synthetic import typing_storm

pytestmark = pytest.mark.skipif(not native_deli.available(),
                                reason="native sequencer unavailable")

TEXT = "abcd"  # typing_storm INS_LEN


def _engines(R=8, O=16):
    a = StringServingEngine(n_docs=R, capacity=256, batch_window=10 ** 9,
                            sequencer="native")
    b = StringServingEngine(n_docs=R, capacity=256, batch_window=10 ** 9)
    docs = [f"doc-{i}" for i in range(R)]
    for eng in (a, b):
        for d in docs:
            eng.connect(d, 1)
    rows = np.array([a.doc_row(d) for d in docs], np.int32)
    return a, b, docs, rows


def _batches(R, O, n_batches):
    """(kind, a0, a1) per batch from the typing-storm generator, plus the
    per-doc client_seq planes continuing across batches."""
    out = []
    seq = 1
    for bi in range(n_batches):
        planes, seq = typing_storm(R, O, seed=bi, start_seq=seq)
        cseq = np.broadcast_to(
            np.arange(bi * O + 1, (bi + 1) * O + 1, dtype=np.int32), (R, O))
        out.append((planes["kind"], planes["a0"], planes["a1"], cseq))
    return out


def test_columnar_matches_per_op_engine():
    R, O = 8, 16
    a, b, docs, rows = _engines(R, O)
    client = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)
    for kind, a0, a1, cseq in _batches(R, O, 3):
        res = a.ingest_planes(rows, client, cseq, ref, kind, a0, a1, TEXT)
        assert res["nacked"] == 0
        assert (res["seq"] > 0).all()
        for d in range(R):  # same ops through the per-op submit path
            for o in range(O):
                if kind[d, o] == OpKind.STR_INSERT:
                    contents = {"mt": "insert", "kind": 0,
                                "pos": int(a0[d, o]), "text": TEXT}
                else:
                    contents = {"mt": "remove", "start": int(a0[d, o]),
                                "end": int(a1[d, o])}
                msg, nack = b.submit(docs[d], 1, int(cseq[d, o]), 0, contents)
                assert nack is None
    for d in docs:
        assert a.read_text(d) == b.read_text(d), d
    # C++ and Python sequencers stamped identical seqs
    for d in docs:
        assert a.deli.doc_seq(d) == b.deli.doc_seq(d)


def test_columnar_nacks_are_skipped_everywhere():
    R, O = 4, 8
    a, _, docs, rows = _engines(R, O)
    (kind, a0, a1, cseq), = _batches(R, O, 1)
    cseq = cseq.copy()
    cseq[2, 5] = 99  # clientSeq gap mid-batch for doc 2
    client = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)
    res = a.ingest_planes(rows, client, cseq, ref, kind, a0, a1, TEXT)
    # the gap cascades: ops 5, 6, 7 of doc 2 all nack (expected cseq stays 6)
    assert res["nacked"] == 3
    assert (res["seq"][2, 5:] < 0).all()
    assert (res["seq"][:2] > 0).all() and (res["seq"][3] > 0).all()
    # nacked ops are in no log record
    logged = 0
    for p in range(a.log.n_partitions):
        for rec in a.log.read(p):
            if isinstance(rec, ColumnarOps):
                assert (rec.seq > 0).all()
                logged += len(rec.seq)
    assert logged == R * O - 3


def test_columnar_recovery_through_log_replay():
    R, O = 8, 16
    a, _, docs, rows = _engines(R, O)
    client = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)
    batches = _batches(R, O, 3)
    kind, a0, a1, cseq = batches[0]
    a.ingest_planes(rows, client, cseq, ref, kind, a0, a1, TEXT)
    summary = a.summarize()
    for kind, a0, a1, cseq in batches[1:]:
        a.ingest_planes(rows, client, cseq, ref, kind, a0, a1, TEXT)
    want = {d: a.read_text(d) for d in docs}

    restored = StringServingEngine.load(summary, a.log)
    for d in docs:
        assert restored.read_text(d) == want[d], d
    # sequencing resumes correctly after recovery (native checkpoint blob)
    msg, nack = restored.submit(
        docs[0], 1, 3 * O + 1, 0,
        {"mt": "insert", "kind": 0, "pos": 0, "text": "Z"})
    assert nack is None
    assert msg.seq == a.deli.doc_seq(docs[0]) + 1
    assert restored.read_text(docs[0]) == "Z" + want[docs[0]]


def test_columnar_then_per_op_interleave():
    """Per-op submits after columnar batches continue the same seq space."""
    R, O = 8, 8
    a, _, docs, rows = _engines(R, O)
    client = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)
    (kind, a0, a1, cseq), = _batches(R, O, 1)
    a.ingest_planes(rows, client, cseq, ref, kind, a0, a1, TEXT)
    before = a.read_text(docs[3])
    msg, nack = a.submit(docs[3], 1, O + 1, 0,
                         {"mt": "insert", "kind": 0, "pos": 0, "text": "XY"})
    assert nack is None
    assert a.read_text(docs[3]) == "XY" + before


def test_columnar_requires_native_sequencer():
    eng = StringServingEngine(n_docs=8, capacity=256)  # python deli
    with pytest.raises(RuntimeError, match="native"):
        eng.ingest_planes(np.arange(8, dtype=np.int32),
                          np.ones((8, 4), np.int32),
                          np.ones((8, 4), np.int32),
                          np.zeros((8, 4), np.int32),
                          np.zeros((8, 4), np.int32),
                          np.zeros((8, 4), np.int32),
                          np.zeros((8, 4), np.int32), TEXT)


def test_native_adapter_full_parity_with_python_deli():
    """Join/leave/sequence/noop/nack parity, op by op, on a multi-client
    interleaving."""
    import random
    from fluidframework_tpu.core.protocol import MessageType
    from fluidframework_tpu.server.serving import make_sequencer
    py = make_sequencer("python")
    nat = make_sequencer("native")
    assert type(nat).__name__ == "NativeDeliAdapter"
    rng = random.Random(7)
    cseq = {}
    for c in (1, 2, 3):
        m1, m2 = py.client_join("d", c), nat.client_join("d", c)
        assert (m1.seq, m1.min_seq) == (m2.seq, m2.min_seq)
        cseq[c] = 0
    for i in range(200):
        c = rng.choice([1, 2, 3])
        if rng.random() < 0.1:
            t, cs = MessageType.NOOP, 0
        else:
            t = MessageType.OP
            cseq[c] += 1
            cs = cseq[c] + (5 if rng.random() < 0.05 else 0)  # rare gap
        ref = rng.randint(0, max(py.doc_seq("d"), 0))
        m1, n1 = py.sequence("d", c, cs, ref, t, {"i": i})
        m2, n2 = nat.sequence("d", c, cs, ref, t, {"i": i})
        assert (m1 is None) == (m2 is None)
        if m1 is None:
            assert n1.reason == n2.reason
            if t == MessageType.OP:
                cseq[c] -= 1  # nacked: python-side counter rolls back
        else:
            assert (m1.seq, m1.min_seq, m1.ref_seq) == \
                (m2.seq, m2.min_seq, m2.ref_seq), i
    m1, m2 = py.client_leave("d", 2), nat.client_leave("d", 2)
    assert (m1.seq, m1.min_seq) == (m2.seq, m2.min_seq)
    assert py.client_leave("d", 99) is None
    assert nat.client_leave("d", 99) is None


def test_columnar_replay_clamps_inflated_ref():
    """An accepted op with an absurd ref_seq is logged CLAMPED; recovery
    replay must not push the client's ref past doc.seq (which would MSN-
    nack every later op forever) — code-review r2 finding."""
    R, O = 8, 8
    a, _, docs, rows = _engines(R, O)
    summary0 = a.summarize()  # tail = everything after this
    client = np.ones((R, O), np.int32)
    (kind, a0, a1, cseq), = _batches(R, O, 1)
    ref = np.full((R, O), 10 ** 6, np.int32)  # way past doc.seq
    res = a.ingest_planes(rows, client, cseq, ref, kind, a0, a1, TEXT)
    assert res["nacked"] == 0
    restored = StringServingEngine.load(summary0, a.log)
    for d in docs:
        assert restored.read_text(d) == a.read_text(d)
    msg, nack = restored.submit(
        docs[0], 1, O + 1, restored.deli.doc_seq(docs[0]),
        {"mt": "insert", "kind": 0, "pos": 0, "text": "ok"})
    assert nack is None, nack


def test_stale_native_handle_nacks_not_crashes():
    """Handles do not survive restore; a stale one must nack (C++ bounds
    guard), not dereference garbage."""
    from fluidframework_tpu.server.native_deli import NativeDeli
    n = NativeDeli()
    n.client_join("d", 1)
    h = n.doc_handle("d")
    restored = NativeDeli.restore(n.checkpoint())
    seqs, mins = restored.sequence_batch_rows(
        np.array([h], np.int32), np.array([1], np.int32),
        np.array([1], np.int32), np.array([0], np.int32))
    assert seqs[0] < 0


def test_columnar_rejects_duplicate_rows():
    R, O = 4, 4
    a, _, docs, rows = _engines(R, O)
    rows = rows.copy()
    rows[1] = rows[0]
    client = np.ones((R, O), np.int32)
    z = np.zeros((R, O), np.int32)
    with pytest.raises(ValueError, match="duplicate"):
        a.ingest_planes(rows, client, client, z, z, z, z, TEXT)


def test_columnar_spill_is_lossless(tmp_path):
    """ColumnarOps in a spill-enabled log must serialize full arrays (the
    default str() repr elides long ones)."""
    import json
    R, O = 8, 130  # > numpy's 1000-element print threshold in one record
    eng = StringServingEngine(n_docs=R, capacity=1024,
                              batch_window=10 ** 9, sequencer="native",
                              log=PartitionedLog(2, spill_dir=str(tmp_path)),
                              n_partitions=2)
    docs = [f"doc-{i}" for i in range(R)]
    for d in docs:
        eng.connect(d, 1)
    rows = np.array([eng.doc_row(d) for d in docs], np.int32)
    kind = np.zeros((R, O), np.int32)  # all inserts
    a0 = np.zeros((R, O), np.int32)
    cseq = np.broadcast_to(np.arange(1, O + 1, dtype=np.int32), (R, O))
    eng.ingest_planes(rows, np.ones((R, O), np.int32), cseq,
                      np.zeros((R, O), np.int32), kind, a0, a0, TEXT)
    eng.log.close()
    total_ops = 0
    for f in tmp_path.iterdir():
        if f.suffix != ".jsonl":
            continue
        for line in f.read_text().splitlines():
            # chained spill grammar: `<8-hex chain word> <json>`
            rec = json.loads(line if line.startswith("{")
                             else line.split(" ", 1)[1])
            if isinstance(rec, dict) and rec.get("__type__") == "ColumnarOps":
                assert "..." not in json.dumps(rec["seq"])
                total_ops += len(rec["seq"])
    assert total_ops == R * O


# --------------------------- per-op payloads + annotates (VERDICT r2 #4)


def _rich_batch(R, O, bi, lengths):
    """Mixed insert(distinct text)/remove/annotate planes + tables.
    ``lengths`` (R,) visible-length tracker, updated in place."""
    rng = np.random.default_rng(1000 + bi)
    texts = [f"w{bi}-{k}" * (1 + k % 3) for k in range(O)]   # distinct runs
    props = [{"bold": True}, {"bold": None}, {"color": f"c{bi}"},
             {"font": 12 + bi}]
    kind = np.zeros((R, O), np.int32)
    a0 = np.zeros((R, O), np.int32)
    a1 = np.zeros((R, O), np.int32)
    tidx = np.zeros((R, O), np.int32)
    for d in range(R):
        for o in range(O):
            roll = rng.random()
            if lengths[d] < 8 or roll < 0.6:
                kind[d, o] = OpKind.STR_INSERT
                tidx[d, o] = o
                a0[d, o] = rng.integers(0, lengths[d] + 1)
                lengths[d] += len(texts[o])
            elif roll < 0.8:
                kind[d, o] = OpKind.STR_REMOVE
                a0[d, o] = rng.integers(0, lengths[d] - 2)
                a1[d, o] = a0[d, o] + 2
                lengths[d] -= 2
            else:
                kind[d, o] = OpKind.STR_ANNOTATE
                tidx[d, o] = rng.integers(0, len(props))
                a0[d, o] = rng.integers(0, lengths[d] - 2)
                a1[d, o] = a0[d, o] + rng.integers(1, 3)
    return kind, a0, a1, tidx, texts, props


def _contents_of(kind, a0, a1, tidx, texts, props, d, o):
    if kind[d, o] == OpKind.STR_INSERT:
        return {"mt": "insert", "kind": 0, "pos": int(a0[d, o]),
                "text": texts[int(tidx[d, o])]}
    if kind[d, o] == OpKind.STR_ANNOTATE:
        return {"mt": "annotate", "start": int(a0[d, o]),
                "end": int(a1[d, o]), "props": props[int(tidx[d, o])]}
    return {"mt": "remove", "start": int(a0[d, o]), "end": int(a1[d, o])}


def test_columnar_per_op_payloads_and_annotates_match_per_op_engine():
    R, O = 6, 16
    a, b, docs, rows = _engines(R, O)
    client = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)
    lengths = np.zeros(R, np.int64)
    for bi in range(3):
        kind, a0, a1, tidx, texts, props = _rich_batch(R, O, bi, lengths)
        cseq = np.broadcast_to(
            np.arange(bi * O + 1, (bi + 1) * O + 1, dtype=np.int32), (R, O))
        res = a.ingest_planes(rows, client, cseq, ref, kind, a0, a1,
                              texts=texts, tidx=tidx, props=props)
        assert res["nacked"] == 0
        for d in range(R):
            for o in range(O):
                _, nack = b.submit(
                    docs[d], 1, int(cseq[d, o]), 0,
                    _contents_of(kind, a0, a1, tidx, texts, props, d, o))
                assert nack is None
    for d in docs:
        assert a.read_text(d) == b.read_text(d), d
        n = len(a.read_text(d))
        for pos in range(0, n, max(1, n // 7)):
            assert a.get_properties(d, pos) == b.get_properties(d, pos), \
                (d, pos)


def test_columnar_rich_recovery_through_log_replay():
    """Distinct-payload + annotate columnar batches must survive summary +
    log-tail replay (the ColumnarOps v2 fields round the log)."""
    R, O = 4, 16
    a, b, docs, rows = _engines(R, O)
    client = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)
    lengths = np.zeros(R, np.int64)
    summary = a.summarize()  # batches land in the tail
    for bi in range(2):
        kind, a0, a1, tidx, texts, props = _rich_batch(R, O, bi, lengths)
        cseq = np.broadcast_to(
            np.arange(bi * O + 1, (bi + 1) * O + 1, dtype=np.int32), (R, O))
        assert a.ingest_planes(rows, client, cseq, ref, kind, a0, a1,
                               texts=texts, tidx=tidx,
                               props=props)["nacked"] == 0
    want = {d: a.read_text(d) for d in docs}
    revived = StringServingEngine.load(summary, a.log)
    assert {d: revived.read_text(d) for d in docs} == want
    for d in docs:
        n = len(want[d])
        for pos in range(0, n, max(1, n // 5)):
            assert revived.get_properties(d, pos) == \
                a.get_properties(d, pos), (d, pos)


def test_columnar_rich_native_log_crash_recovery(tmp_path):
    from fluidframework_tpu.server.native_oplog import (
        NativePartitionedLog, available as oplog_available)
    if not oplog_available():
        pytest.skip("native oplog not built")
    R, O = 4, 12
    log = NativePartitionedLog(str(tmp_path), 4)
    eng = StringServingEngine(n_docs=R, capacity=256, batch_window=10 ** 9,
                              sequencer="native", log=log)
    docs = [f"doc-{i}" for i in range(R)]
    for d in docs:
        eng.connect(d, 1)
    rows = np.array([eng.doc_row(d) for d in docs], np.int32)
    client = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)
    lengths = np.zeros(R, np.int64)
    summary = eng.summarize()
    for bi in range(2):
        kind, a0, a1, tidx, texts, props = _rich_batch(R, O, bi, lengths)
        cseq = np.broadcast_to(
            np.arange(bi * O + 1, (bi + 1) * O + 1, dtype=np.int32), (R, O))
        assert eng.ingest_planes(rows, client, cseq, ref, kind, a0, a1,
                                 texts=texts, tidx=tidx,
                                 props=props)["nacked"] == 0
    want = {d: eng.read_text(d) for d in docs}
    log.sync()
    log.close()  # the crash
    revived = StringServingEngine.load(
        summary, NativePartitionedLog(str(tmp_path), 4))
    assert {d: revived.read_text(d) for d in docs} == want


def test_columnar_annotate_without_props_table_rejected():
    R, O = 2, 4
    a, _, docs, rows = _engines(R, O)
    kind = np.full((R, O), int(OpKind.STR_ANNOTATE), np.int32)
    z = np.zeros((R, O), np.int32)
    with pytest.raises(ValueError, match="insert/remove"):
        a.ingest_planes(rows, np.ones((R, O), np.int32),
                        np.arange(1, O + 1, dtype=np.int32) * np.ones(
                            (R, 1), np.int32), z, kind, z, z, "x")
    # multi-key props are the per-op path's job
    with pytest.raises(ValueError, match="single-key"):
        a.ingest_planes(rows, np.ones((R, O), np.int32),
                        np.arange(1, O + 1, dtype=np.int32) * np.ones(
                            (R, 1), np.int32), z, kind, z, z,
                        texts=["t"], tidx=z,
                        props=[{"a": 1, "b": 2}])


# ------------------------- ingest-side tidx validation (ADVICE r3 medium)


def test_columnar_rejects_bad_tidx_before_sequencing():
    """A negative tidx would wrap to the wrong payload; an out-of-range one
    would raise AFTER the native sequencer consumed seqs (doc.seq ahead of
    the durable log). Both must be rejected before sequencing."""
    R, O = 2, 4
    a, _, docs, rows = _engines(R, O)
    client = np.ones((R, O), np.int32)
    cseq = np.broadcast_to(np.arange(1, O + 1, dtype=np.int32), (R, O))
    ref = np.zeros((R, O), np.int32)
    kind = np.zeros((R, O), np.int32)  # inserts
    z = np.zeros((R, O), np.int32)
    texts = ["aa", "bb"]
    seq_before = {d: a.deli.doc_seq(d) for d in docs}

    neg = z.copy()
    neg[1, 2] = -1
    with pytest.raises(ValueError, match="negative tidx"):
        a.ingest_planes(rows, client, cseq, ref, kind, z, z,
                        texts=texts, tidx=neg)
    big = z.copy()
    big[0, 1] = 2  # == len(texts)
    with pytest.raises(ValueError, match="payload table"):
        a.ingest_planes(rows, client, cseq, ref, kind, z, z,
                        texts=texts, tidx=big)
    with pytest.raises(ValueError, match="require the tidx"):
        a.ingest_planes(rows, client, cseq, ref, kind, z, z, texts=texts)
    ann = np.full((R, O), int(OpKind.STR_ANNOTATE), np.int32)
    span = np.broadcast_to(np.array([1], np.int32), (R, O))
    bigp = z.copy()
    bigp[0, 0] = 5  # beyond the 1-entry props table
    with pytest.raises(ValueError, match="props table"):
        a.ingest_planes(rows, client, cseq, ref, ann, z, span,
                        texts=texts, tidx=bigp, props=[{"b": 1}])
    # nothing was sequenced or logged by any rejected batch
    for d in docs:
        assert a.deli.doc_seq(d) == seq_before[d]
    assert sum(a.log.size(p) for p in range(a.log.n_partitions)) == len(docs)


# ----------------------- append-failure poisoning (VERDICT r3 weak #4)


class _FailingLog(PartitionedLog):
    """Durable log whose append starts failing on command (full disk)."""

    def __init__(self, n_partitions):
        super().__init__(n_partitions)
        self.fail = False
        self._appends_until_fail = 0

    def arm(self, appends_until_fail: int) -> None:
        self.fail = True
        self._appends_until_fail = appends_until_fail

    def append(self, p, rec, epoch=None):
        if self.fail:
            if self._appends_until_fail <= 0:
                raise IOError("disk full")
            self._appends_until_fail -= 1
        super().append(p, rec, epoch=epoch)


def test_append_failure_poisons_engine_and_blocks_summary():
    """If the durable-log append fails AFTER the device merge was
    dispatched, the engine must refuse further ingest and summaries: a
    summary taken now would durably persist ops the log never recorded.
    A clean batch is ONE whole-batch record, so the failure is
    all-or-nothing: recovery must see exactly the pre-failure state."""
    R, O = 4, 8
    log = _FailingLog(4)
    eng = StringServingEngine(n_docs=R, capacity=256, batch_window=10 ** 9,
                              sequencer="native", log=log, n_partitions=4)
    docs = [f"doc-{i}" for i in range(R)]
    for d in docs:
        eng.connect(d, 1)
    rows = np.array([eng.doc_row(d) for d in docs], np.int32)
    client = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)
    batches = _batches(R, O, 2)
    kind, a0, a1, cseq = batches[0]
    eng.ingest_planes(rows, client, cseq, ref, kind, a0, a1, TEXT)
    good_summary = eng.summarize()
    good_text = {d: eng.read_text(d) for d in docs}

    log.arm(0)  # the batch's (single) whole-batch append explodes
    kind, a0, a1, cseq = batches[1]
    with pytest.raises(IOError):
        eng.ingest_planes(rows, client, cseq, ref, kind, a0, a1, TEXT)

    # poisoned: no more ingest (either path), no summary — summarizing now
    # would durably persist the device-applied-but-unlogged ops
    with pytest.raises(RuntimeError, match="poisoned"):
        eng.ingest_planes(rows, client, cseq, ref, kind, a0, a1, TEXT)
    with pytest.raises(RuntimeError, match="poisoned"):
        eng.submit(docs[0], 1, 99, 0,
                   {"mt": "insert", "kind": 0, "pos": 0, "text": "x"})
    with pytest.raises(RuntimeError, match="poisoned"):
        eng.summarize()

    # recovery from the pre-failure summary + log: the failed batch's ops
    # are GONE — the device had applied them, the rebuilt engine never
    # sees them, and resubmission continues the sequence space
    log.fail = False
    revived = StringServingEngine.load(good_summary, log)
    assert {d: revived.read_text(d) for d in docs} == good_text
    msg, nack = revived.submit(
        docs[0], 1, O + 1, 0,
        {"mt": "insert", "kind": 0, "pos": 0, "text": "Z"})
    assert nack is None
    assert revived.read_text(docs[0]) == "Z" + good_text[docs[0]]


def test_partial_append_failure_with_nacks_poisons():
    """The nacked-batch path appends one record per partition; a failure
    partway through leaves a PARTIAL batch in the log. The engine must
    poison, and recovery must replay exactly the logged prefix: unlogged
    partitions' docs read the pre-failure text, logged partitions' docs
    match a reference engine fed the same accepted ops."""
    R, O = 4, 8
    log = _FailingLog(4)
    eng = StringServingEngine(n_docs=R, capacity=256, batch_window=10 ** 9,
                              sequencer="native", log=log, n_partitions=4)
    docs = [f"doc-{i}" for i in range(R)]
    for d in docs:
        eng.connect(d, 1)
    rows = np.array([eng.doc_row(d) for d in docs], np.int32)
    client = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)
    batches = _batches(R, O, 2)
    kind, a0, a1, cseq = batches[0]
    eng.ingest_planes(rows, client, cseq, ref, kind, a0, a1, TEXT)
    good_summary = eng.summarize()
    good_text = {d: eng.read_text(d) for d in docs}

    sizes_before = [log.size(p) for p in range(4)]
    kind, a0, a1, cseq = batches[1]
    cseq = cseq.copy()
    cseq[2, 5] = 10 ** 6   # nack cascade → per-partition append path
    log.arm(1)             # second partition append explodes
    with pytest.raises(IOError):
        eng.ingest_planes(rows, client, cseq, ref, kind, a0, a1, TEXT)
    with pytest.raises(RuntimeError, match="poisoned"):
        eng.summarize()

    log.fail = False
    logged_parts = {p for p in range(4) if log.size(p) > sizes_before[p]}
    assert logged_parts and logged_parts != set(range(4))  # genuine partial
    revived = StringServingEngine.load(good_summary, log)
    from fluidframework_tpu.server.oplog import partition_of
    logged = [d for d in docs if partition_of(d, 4) in logged_parts]
    unlogged = [d for d in docs if partition_of(d, 4) not in logged_parts]
    assert unlogged
    for d in unlogged:
        assert revived.read_text(d) == good_text[d], d
    # parity for the logged docs: a reference engine fed batch 1 plus the
    # ACCEPTED batch-2 ops of those docs must agree
    ref_eng = StringServingEngine(n_docs=R, capacity=256,
                                  batch_window=10 ** 9)
    for d in docs:
        ref_eng.connect(d, 1)
    k1, x0, x1, c1 = batches[0]
    for b_kind, b_a0, b_a1, b_cseq, only in (
            (k1, x0, x1, c1, None), (kind, a0, a1, cseq, logged)):
        for di, d in enumerate(docs):
            if only is not None and d not in only:
                continue
            for o in range(O):
                if b_kind[di, o] == OpKind.STR_INSERT:
                    c = {"mt": "insert", "kind": 0,
                         "pos": int(b_a0[di, o]), "text": TEXT}
                else:
                    c = {"mt": "remove", "start": int(b_a0[di, o]),
                         "end": int(b_a1[di, o])}
                _, nack = ref_eng.submit(d, 1, int(b_cseq[di, o]), 0, c)
                if only is None:
                    assert nack is None  # batch 1 is clean; batch 2's
                    # gap doc may legitimately nack its cascade tail
    for d in docs:
        assert revived.read_text(d) == ref_eng.read_text(d), d


def test_props_without_tidx_rejected_before_sequencing():
    """Review r4 finding: annotate batch with props but tidx=None must be
    rejected up front, not explode in apply_planes after seqs were spent."""
    R, O = 2, 4
    a, _, docs, rows = _engines(R, O)
    ann = np.full((R, O), int(OpKind.STR_ANNOTATE), np.int32)
    z = np.zeros((R, O), np.int32)
    span = np.ones((R, O), np.int32)
    cseq = np.broadcast_to(np.arange(1, O + 1, dtype=np.int32), (R, O))
    seq_before = {d: a.deli.doc_seq(d) for d in docs}
    with pytest.raises(ValueError, match="tidx"):
        a.ingest_planes(rows, np.ones((R, O), np.int32), cseq, z,
                        ann, z, span, props=[{"b": 1}])
    for d in docs:
        assert a.deli.doc_seq(d) == seq_before[d]


def test_post_sequencing_failure_before_append_poisons():
    """Review r4 finding: a failure AFTER the native sequencer consumed
    seqs but BEFORE the log append (e.g. the device store refusing the
    batch) must poison — doc.seq is ahead of the durable log.

    (Interval-holding docs used to be the natural in-tree trigger; they
    now ride the columnar path — see docs/INTERVALS.md — so the store
    failure is injected directly.)"""
    R, O = 2, 4
    a, _, docs, rows = _engines(R, O)
    a.submit(docs[0], 1, 1, 0,
             {"mt": "insert", "kind": 0, "pos": 0, "text": "hello"})

    def _boom(*_a, **_k):
        raise ValueError("device store refused the batch")

    a.store.apply_planes = _boom
    kind = np.zeros((R, O), np.int32)
    z = np.zeros((R, O), np.int32)
    cseq = np.broadcast_to(np.arange(2, O + 2, dtype=np.int32), (R, O))
    with pytest.raises(ValueError, match="refused"):
        a.ingest_planes(rows, np.ones((R, O), np.int32), cseq, z,
                        kind, z, z, TEXT)
    with pytest.raises(RuntimeError, match="poisoned"):
        a.summarize()
