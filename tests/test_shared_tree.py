"""SharedTree DDS: id-anchored edits, merge rules, pending-op replay,
convergence fuzz. Reference behaviors per SURVEY.md §2.6."""

import random

import pytest

from fluidframework_tpu.models import SharedTree, TreeSchema
from fluidframework_tpu.testing.mocks import MockSequencer, \
    create_connected_dds


def make_trees(n=2):
    seqr = MockSequencer()
    trees = [create_connected_dds(seqr, SharedTree, "t") for _ in range(n)]
    return seqr, trees


def digests(trees):
    return {t.digest() for t in trees}


# -------------------------------------------------------------- basic edits

class TestBasicEdits:
    def test_insert_children_and_values(self):
        seqr, (a, b) = make_trees()
        n1 = a.insert("root", "items", node_type=None, value="first")
        n2 = a.insert("root", "items", value="second", after=n1)
        seqr.process_all_messages()
        assert b.children("root", "items") == [n1, n2]
        assert b.value_of(n2) == "second"

    def test_remove_subtree(self):
        seqr, (a, b) = make_trees()
        parent = a.insert("root", "items", value="p")
        child = a.insert(parent, "kids", value="c")
        seqr.process_all_messages()
        b.remove(parent)
        seqr.process_all_messages()
        assert not a.has_node(parent) and not a.has_node(child)
        assert len(a) == len(b) == 1   # just root

    def test_move_between_parents(self):
        seqr, (a, b) = make_trees()
        p1 = a.insert("root", "items", value="p1")
        p2 = a.insert("root", "items", value="p2", after=p1)
        x = a.insert(p1, "kids", value="x")
        seqr.process_all_messages()
        b.move(x, p2, "kids")
        seqr.process_all_messages()
        assert a.children(p1, "kids") == []
        assert a.children(p2, "kids") == [x]
        assert digests((a, b)) == {a.digest()}

    def test_set_value_lww(self):
        seqr, (a, b) = make_trees()
        n = a.insert("root", "items", value=0)
        seqr.process_all_messages()
        a.set_value(n, "from-a")
        b.set_value(n, "from-b")
        seqr.process_all_messages()
        # b's op sequenced second → wins on both replicas
        assert a.value_of(n) == b.value_of(n) == "from-b"


# -------------------------------------------------------------- merge rules

class TestMergeRules:
    def test_concurrent_inserts_same_anchor_later_seq_closer(self):
        seqr, (a, b) = make_trees()
        anchor = a.insert("root", "items", value="anchor")
        seqr.process_all_messages()
        na = a.insert("root", "items", value="A", after=anchor)
        nb = b.insert("root", "items", value="B", after=anchor)
        seqr.process_all_messages()   # a's sequenced first
        # later-sequenced (b) lands closer to the anchor
        assert a.children("root", "items") == [anchor, nb, na]
        assert digests((a, b)) == {a.digest()}

    def test_edit_under_concurrently_removed_subtree_dropped(self):
        seqr, (a, b) = make_trees()
        p = a.insert("root", "items", value="p")
        seqr.process_all_messages()
        a.remove(p)
        nb = b.insert(p, "kids", value="orphan")   # concurrent with removal
        seqr.process_all_messages()
        assert not a.has_node(nb) and not b.has_node(nb)
        assert digests((a, b)) == {a.digest()}

    def test_concurrent_moves_last_sequenced_wins(self):
        seqr, (a, b) = make_trees()
        p1 = a.insert("root", "items", value="p1")
        p2 = a.insert("root", "items", value="p2", after=p1)
        x = a.insert("root", "items", value="x", after=p2)
        seqr.process_all_messages()
        a.move(x, p1, "kids")
        b.move(x, p2, "kids")
        seqr.process_all_messages()
        assert a.children(p2, "kids") == [x]     # b sequenced last → wins
        assert a.children(p1, "kids") == []
        assert digests((a, b)) == {a.digest()}

    def test_cycle_creating_move_dropped(self):
        seqr, (a, b) = make_trees()
        p = a.insert("root", "items", value="p")
        c = a.insert(p, "kids", value="c")
        seqr.process_all_messages()
        # concurrently: a moves c under root, b moves p under c (cycle if
        # both applied naively)
        a.move(c, "root", "items")
        b.move(p, c, "kids")
        seqr.process_all_messages()
        assert digests((a, b)) == {a.digest()}
        # p under c applied after c moved to root: no cycle, both survive
        assert a.children(c, "kids") == [p]

    def test_direct_self_cycle_dropped(self):
        seqr, (a, b) = make_trees()
        p = a.insert("root", "items", value="p")
        c = a.insert(p, "kids", value="c")
        seqr.process_all_messages()
        b.move(p, c, "kids")       # p under its own child, sequenced alone
        seqr.process_all_messages()
        assert a.children("root", "items") == [p]   # dropped
        assert digests((a, b)) == {a.digest()}

    def test_missing_anchor_degrades_to_field_start(self):
        seqr, (a, b) = make_trees()
        s1 = a.insert("root", "items", value="s1")
        s2 = a.insert("root", "items", value="s2", after=s1)
        seqr.process_all_messages()
        a.remove(s1)
        nb = b.insert("root", "items", value="n", after=s1)  # anchor dying
        seqr.process_all_messages()
        assert a.children("root", "items") == [nb, s2]
        assert digests((a, b)) == {a.digest()}


# ------------------------------------------------------------------- schema

class TestSchema:
    def test_schema_validates_types_and_fields(self):
        seqr, (a, b) = make_trees()
        schema = TreeSchema({"list": ["items"], "item": []})
        a.set_schema(schema)
        lst = a.insert("root", "items", node_type=None)  # untyped parent ok
        with pytest.raises(ValueError):
            a.insert("root", "items", node_type="nosuch")
        n = a.insert(lst, "x", node_type="item")  # untyped parent: any field
        seqr.process_all_messages()
        assert b.has_node(n)

    def test_schema_rejects_bad_field_on_typed_parent(self):
        seqr, (a, _) = make_trees()
        a.set_schema(TreeSchema({"list": ["items"]}))
        lst = a.insert("root", "x", node_type="list")
        with pytest.raises(ValueError):
            a.insert(lst, "wrong", value=1)
        a.insert(lst, "items", value=1)   # allowed


# -------------------------------------------------------- summaries + fuzz

class TestSummariesAndFuzz:
    def test_summary_roundtrip(self):
        seqr, (a, b) = make_trees()
        p = a.insert("root", "items", value="p")
        a.insert(p, "kids", value="k")
        seqr.process_all_messages()
        fresh = SharedTree("t", 99)
        fresh.load_core(a.summarize())
        assert fresh.digest() == a.digest()

    @pytest.mark.parametrize("seed", range(12))
    def test_convergence_fuzz(self, seed):
        rng = random.Random(seed)
        seqr, trees = make_trees(3)
        for t in trees:
            t._fuzz_nodes = ["root"]

        def random_edit(t):
            kind = rng.choice(["insert", "insert", "insert", "remove",
                               "move", "setValue"])
            live = [n for n in t._fuzz_nodes if t.has_node(n)]
            if not live:
                live = ["root"]
            if kind == "insert":
                parent = rng.choice(live)
                sibs = t.children(parent, "f")
                after = rng.choice([None] + sibs) if sibs else None
                nid = t.insert(parent, "f", value=rng.randint(0, 99),
                               after=after)
                t._fuzz_nodes.append(nid)
            elif kind == "remove":
                target = rng.choice(live)
                if target != "root":
                    t.remove(target)
            elif kind == "move":
                target, dest = rng.choice(live), rng.choice(live)
                if target != "root":
                    t.move(target, dest, "f")
            else:
                t.set_value(rng.choice(live), rng.randint(0, 99))

        for _ in range(30):
            for t in trees:
                if rng.random() < 0.7:
                    random_edit(t)
            # partial sequencing so ops cross in flight
            seqr.process_some(rng.randint(0, 4))
        seqr.process_all_messages()
        assert len(digests(trees)) == 1, f"diverged at seed {seed}"


# ----------------------------------------------------- transactions & undo

class TestTransactions:
    def test_transaction_applies_atomically(self):
        seqr, (a, b) = make_trees()
        def edits(t):
            p = t.insert("root", "items", value="p")
            t.insert(p, "kids", value="c1")
            t.insert(p, "kids", value="c2")
            return p
        p = a.run_transaction(edits)
        # one op on the wire; before drain b sees nothing
        assert b.children("root", "items") == []
        seqr.process_all_messages()
        assert len(b.children(p, "kids")) == 2
        assert digests([a, b]) and len(digests([a, b])) == 1

    def test_transaction_rollback_on_exception(self):
        seqr, (a, b) = make_trees()
        with pytest.raises(RuntimeError, match="boom"):
            def bad(t):
                t.insert("root", "items", value="x")
                raise RuntimeError("boom")
            a.run_transaction(bad)
        seqr.process_all_messages()
        assert a.children("root", "items") == []
        assert len(a.kernel.view.nodes) == len(b.kernel.view.nodes) == 1

    def test_transaction_reads_its_own_writes(self):
        seqr, (a, _) = make_trees()
        def edits(t):
            p = t.insert("root", "items", value="p")
            assert t.value_of(p) == "p"          # visible inside the txn
            t.set_value(p, "p2")
            assert t.value_of(p) == "p2"
            return p
        p = a.run_transaction(edits)
        seqr.process_all_messages()
        assert a.value_of(p) == "p2"

    def test_constraint_drops_whole_group_on_every_replica(self):
        seqr, (a, b) = make_trees()
        target = a.insert("root", "items", value="t")
        seqr.process_all_messages()
        b.remove(target)                      # concurrent with a's txn
        a.run_transaction(
            lambda t: t.insert("root", "items", value="depends"),
            constraints=[{"nodeExists": target}])
        seqr.process_all_messages()
        # b's remove sequenced first -> constraint fails everywhere
        assert a.children("root", "items") == []
        assert len(digests([a, b])) == 1

    def test_constraint_holds_group_applies(self):
        seqr, (a, b) = make_trees()
        target = a.insert("root", "items", value="t")
        seqr.process_all_messages()
        a.run_transaction(
            lambda t: t.set_value(target, "updated"),
            constraints=[{"nodeExists": target}])
        seqr.process_all_messages()
        assert b.value_of(target) == "updated"


class TestSchemaChildTypes:
    def test_child_type_enforced(self):
        seqr, (a, _) = make_trees()
        a.set_schema(TreeSchema({
            "list": {"items": ["item"]},   # items accepts only "item"
            "item": {},
        }))
        lst = a.insert("root", "items", node_type=None)  # untyped root field
        # root is untyped: anything goes
        l2 = a.insert("root", "items", node_type="list")
        a.insert(l2, "items", node_type="item")
        with pytest.raises(ValueError, match="not allowed"):
            a.insert(l2, "items", node_type="list")
        with pytest.raises(ValueError, match="not allowed"):
            a.insert(l2, "items", node_type=None)

    def test_move_checks_child_types(self):
        seqr, (a, _) = make_trees()
        a.set_schema(TreeSchema({
            "list": {"items": ["item"]}, "item": {}, "other": {}}))
        lst = a.insert("root", "f", node_type="list")
        other = a.insert("root", "f", node_type="other")
        with pytest.raises(ValueError, match="not allowed"):
            a.move(other, lst, "items")


class TestTreeUndoRedo:
    def _undo_tree(self, tree):
        from fluidframework_tpu.framework.undo_redo import (
            SharedTreeUndoRedoHandler, UndoRedoStackManager)
        stack = UndoRedoStackManager()
        SharedTreeUndoRedoHandler(stack).attach(tree)
        return stack

    def test_undo_remove_restores_subtree(self):
        seqr, (a, b) = make_trees()
        p = a.insert("root", "items", value="p")
        c1 = a.insert(p, "kids", value="c1")
        a.insert(c1, "kids", value="g1")
        seqr.process_all_messages()
        stack = self._undo_tree(a)
        a.remove(p)
        stack.close_current_operation()
        seqr.process_all_messages()
        assert not b.has_node(p)
        assert stack.undo_operation()
        seqr.process_all_messages()
        # the whole subtree is back, same ids, same shape
        assert b.value_of(p) == "p"
        assert b.children(p, "kids") == [c1]
        assert len(digests([a, b])) == 1

    def test_undo_redo_move_and_set_value(self):
        seqr, (a, b) = make_trees()
        x = a.insert("root", "items", value=1)
        y = a.insert("root", "items", value=2, after=x)
        seqr.process_all_messages()
        stack = self._undo_tree(a)
        a.move(y, "root", "items")            # y to front
        stack.close_current_operation()
        a.set_value(x, 99)
        stack.close_current_operation()
        seqr.process_all_messages()
        assert b.children("root", "items") == [y, x]
        stack.undo_operation()                 # undo set_value
        stack.undo_operation()                 # undo move
        seqr.process_all_messages()
        assert b.children("root", "items") == [x, y]
        assert b.value_of(x) == 1
        stack.redo_operation()
        stack.redo_operation()
        seqr.process_all_messages()
        assert b.children("root", "items") == [y, x]
        assert b.value_of(x) == 99
        assert len(digests([a, b])) == 1

    def test_undo_transaction_is_atomic(self):
        seqr, (a, b) = make_trees()
        stack = self._undo_tree(a)
        def edits(t):
            p = t.insert("root", "items", value="p")
            t.insert(p, "kids", value="c")
            t.set_value(p, "p2")
            return p
        p = a.run_transaction(edits)
        stack.close_current_operation()
        seqr.process_all_messages()
        assert stack.undo_operation()
        seqr.process_all_messages()
        assert not a.has_node(p) and not b.has_node(p)
        assert stack.redo_operation()
        seqr.process_all_messages()
        assert b.value_of(p) == "p2" and len(b.children(p, "kids")) == 1
        assert len(digests([a, b])) == 1

    def test_undo_against_concurrent_edit_degrades(self):
        """Undo of an insert whose node a remote replica already removed:
        the inverse remove drops quietly; replicas stay converged."""
        seqr, (a, b) = make_trees()
        stack = self._undo_tree(a)
        n = a.insert("root", "items", value="n")
        stack.close_current_operation()
        seqr.process_all_messages()
        b.remove(n)
        seqr.process_all_messages()
        assert stack.undo_operation()
        seqr.process_all_messages()
        assert not a.has_node(n)
        assert len(digests([a, b])) == 1


# --------------------------------------------------------------- fuzz (txn)

@pytest.mark.parametrize("seed", range(4))
def test_tree_fuzz_with_transactions_and_undo(seed):
    rng = random.Random(seed)
    seqr, trees = make_trees(3)
    from fluidframework_tpu.framework.undo_redo import (
        SharedTreeUndoRedoHandler, UndoRedoStackManager)
    stack = UndoRedoStackManager()
    SharedTreeUndoRedoHandler(stack).attach(trees[0])

    def random_node(t):
        return rng.choice(sorted(t.kernel.view.nodes))

    for _ in range(80):
        t = rng.choice(trees)
        r = rng.random()
        try:
            if r < 0.35:
                t.insert(random_node(t), rng.choice("fg"),
                         value=rng.randint(0, 9))
            elif r < 0.5:
                t.remove(random_node(t))
            elif r < 0.65:
                t.move(random_node(t), random_node(t), rng.choice("fg"))
            elif r < 0.75:
                t.set_value(random_node(t), rng.randint(0, 99))
            elif r < 0.85:
                def edits(tr):
                    p = tr.insert(random_node(tr), "f", value="txn")
                    tr.set_value(p, rng.randint(0, 9))
                t.run_transaction(edits)
            elif r < 0.93 and t is trees[0]:
                stack.undo_operation()
            elif t is trees[0]:
                stack.redo_operation()
        except (KeyError, ValueError, RuntimeError):
            pass  # local-validity errors (move-into-self etc.) are fine
        if t is trees[0]:
            stack.close_current_operation()
        if rng.random() < 0.3:
            seqr.process_some(rng.randint(0, seqr.outstanding))
    seqr.process_all_messages()
    assert len(digests(trees)) == 1


def test_undo_subtree_remove_with_child_moved_out():
    """Undo of a subtree remove whose nested child was concurrently moved
    out must NOT re-create the child's id (confirmed review repro: the
    duplicate id corrupted sibling lists and crashed digest())."""
    from fluidframework_tpu.framework.undo_redo import (
        SharedTreeUndoRedoHandler, UndoRedoStackManager)
    seqr, (a, b) = make_trees()
    p = a.insert("root", "f", value="p")
    c = a.insert(p, "g", value="c")
    seqr.process_all_messages()
    stack = UndoRedoStackManager()
    SharedTreeUndoRedoHandler(stack).attach(a)
    b.move(c, "root", "f")   # sequenced FIRST: c escapes the subtree
    a.remove(p)              # a's pre-state still nests c under p
    stack.close_current_operation()
    seqr.process_all_messages()
    assert a.has_node(c) and not a.has_node(p)
    assert stack.undo_operation()
    seqr.process_all_messages()
    # p is back WITHOUT a duplicate c; c still lives at root
    assert a.has_node(p) and a.children(p, "g") == []
    assert a.kernel.view.nodes[c]["parent"] == "root"
    assert len(digests([a, b])) == 1
    # the crash path from the repro: removing p again must stay clean
    a.remove(p)
    seqr.process_all_messages()
    assert a.has_node(c) and len(digests([a, b])) == 1
    a.digest(); b.digest()
