"""SharedTree DDS: id-anchored edits, merge rules, pending-op replay,
convergence fuzz. Reference behaviors per SURVEY.md §2.6."""

import random

import pytest

from fluidframework_tpu.models import SharedTree, TreeSchema
from fluidframework_tpu.testing.mocks import MockSequencer, \
    create_connected_dds


def make_trees(n=2):
    seqr = MockSequencer()
    trees = [create_connected_dds(seqr, SharedTree, "t") for _ in range(n)]
    return seqr, trees


def digests(trees):
    return {t.digest() for t in trees}


# -------------------------------------------------------------- basic edits

class TestBasicEdits:
    def test_insert_children_and_values(self):
        seqr, (a, b) = make_trees()
        n1 = a.insert("root", "items", node_type=None, value="first")
        n2 = a.insert("root", "items", value="second", after=n1)
        seqr.process_all_messages()
        assert b.children("root", "items") == [n1, n2]
        assert b.value_of(n2) == "second"

    def test_remove_subtree(self):
        seqr, (a, b) = make_trees()
        parent = a.insert("root", "items", value="p")
        child = a.insert(parent, "kids", value="c")
        seqr.process_all_messages()
        b.remove(parent)
        seqr.process_all_messages()
        assert not a.has_node(parent) and not a.has_node(child)
        assert len(a) == len(b) == 1   # just root

    def test_move_between_parents(self):
        seqr, (a, b) = make_trees()
        p1 = a.insert("root", "items", value="p1")
        p2 = a.insert("root", "items", value="p2", after=p1)
        x = a.insert(p1, "kids", value="x")
        seqr.process_all_messages()
        b.move(x, p2, "kids")
        seqr.process_all_messages()
        assert a.children(p1, "kids") == []
        assert a.children(p2, "kids") == [x]
        assert digests((a, b)) == {a.digest()}

    def test_set_value_lww(self):
        seqr, (a, b) = make_trees()
        n = a.insert("root", "items", value=0)
        seqr.process_all_messages()
        a.set_value(n, "from-a")
        b.set_value(n, "from-b")
        seqr.process_all_messages()
        # b's op sequenced second → wins on both replicas
        assert a.value_of(n) == b.value_of(n) == "from-b"


# -------------------------------------------------------------- merge rules

class TestMergeRules:
    def test_concurrent_inserts_same_anchor_later_seq_closer(self):
        seqr, (a, b) = make_trees()
        anchor = a.insert("root", "items", value="anchor")
        seqr.process_all_messages()
        na = a.insert("root", "items", value="A", after=anchor)
        nb = b.insert("root", "items", value="B", after=anchor)
        seqr.process_all_messages()   # a's sequenced first
        # later-sequenced (b) lands closer to the anchor
        assert a.children("root", "items") == [anchor, nb, na]
        assert digests((a, b)) == {a.digest()}

    def test_edit_under_concurrently_removed_subtree_dropped(self):
        seqr, (a, b) = make_trees()
        p = a.insert("root", "items", value="p")
        seqr.process_all_messages()
        a.remove(p)
        nb = b.insert(p, "kids", value="orphan")   # concurrent with removal
        seqr.process_all_messages()
        assert not a.has_node(nb) and not b.has_node(nb)
        assert digests((a, b)) == {a.digest()}

    def test_concurrent_moves_last_sequenced_wins(self):
        seqr, (a, b) = make_trees()
        p1 = a.insert("root", "items", value="p1")
        p2 = a.insert("root", "items", value="p2", after=p1)
        x = a.insert("root", "items", value="x", after=p2)
        seqr.process_all_messages()
        a.move(x, p1, "kids")
        b.move(x, p2, "kids")
        seqr.process_all_messages()
        assert a.children(p2, "kids") == [x]     # b sequenced last → wins
        assert a.children(p1, "kids") == []
        assert digests((a, b)) == {a.digest()}

    def test_cycle_creating_move_dropped(self):
        seqr, (a, b) = make_trees()
        p = a.insert("root", "items", value="p")
        c = a.insert(p, "kids", value="c")
        seqr.process_all_messages()
        # concurrently: a moves c under root, b moves p under c (cycle if
        # both applied naively)
        a.move(c, "root", "items")
        b.move(p, c, "kids")
        seqr.process_all_messages()
        assert digests((a, b)) == {a.digest()}
        # p under c applied after c moved to root: no cycle, both survive
        assert a.children(c, "kids") == [p]

    def test_direct_self_cycle_dropped(self):
        seqr, (a, b) = make_trees()
        p = a.insert("root", "items", value="p")
        c = a.insert(p, "kids", value="c")
        seqr.process_all_messages()
        b.move(p, c, "kids")       # p under its own child, sequenced alone
        seqr.process_all_messages()
        assert a.children("root", "items") == [p]   # dropped
        assert digests((a, b)) == {a.digest()}

    def test_missing_anchor_degrades_to_field_start(self):
        seqr, (a, b) = make_trees()
        s1 = a.insert("root", "items", value="s1")
        s2 = a.insert("root", "items", value="s2", after=s1)
        seqr.process_all_messages()
        a.remove(s1)
        nb = b.insert("root", "items", value="n", after=s1)  # anchor dying
        seqr.process_all_messages()
        assert a.children("root", "items") == [nb, s2]
        assert digests((a, b)) == {a.digest()}


# ------------------------------------------------------------------- schema

class TestSchema:
    def test_schema_validates_types_and_fields(self):
        seqr, (a, b) = make_trees()
        schema = TreeSchema({"list": ["items"], "item": []})
        a.set_schema(schema)
        lst = a.insert("root", "items", node_type=None)  # untyped parent ok
        with pytest.raises(ValueError):
            a.insert("root", "items", node_type="nosuch")
        n = a.insert(lst, "x", node_type="item")  # untyped parent: any field
        seqr.process_all_messages()
        assert b.has_node(n)

    def test_schema_rejects_bad_field_on_typed_parent(self):
        seqr, (a, _) = make_trees()
        a.set_schema(TreeSchema({"list": ["items"]}))
        lst = a.insert("root", "x", node_type="list")
        with pytest.raises(ValueError):
            a.insert(lst, "wrong", value=1)
        a.insert(lst, "items", value=1)   # allowed


# -------------------------------------------------------- summaries + fuzz

class TestSummariesAndFuzz:
    def test_summary_roundtrip(self):
        seqr, (a, b) = make_trees()
        p = a.insert("root", "items", value="p")
        a.insert(p, "kids", value="k")
        seqr.process_all_messages()
        fresh = SharedTree("t", 99)
        fresh.load_core(a.summarize())
        assert fresh.digest() == a.digest()

    @pytest.mark.parametrize("seed", range(12))
    def test_convergence_fuzz(self, seed):
        rng = random.Random(seed)
        seqr, trees = make_trees(3)
        for t in trees:
            t._fuzz_nodes = ["root"]

        def random_edit(t):
            kind = rng.choice(["insert", "insert", "insert", "remove",
                               "move", "setValue"])
            live = [n for n in t._fuzz_nodes if t.has_node(n)]
            if not live:
                live = ["root"]
            if kind == "insert":
                parent = rng.choice(live)
                sibs = t.children(parent, "f")
                after = rng.choice([None] + sibs) if sibs else None
                nid = t.insert(parent, "f", value=rng.randint(0, 99),
                               after=after)
                t._fuzz_nodes.append(nid)
            elif kind == "remove":
                target = rng.choice(live)
                if target != "root":
                    t.remove(target)
            elif kind == "move":
                target, dest = rng.choice(live), rng.choice(live)
                if target != "root":
                    t.move(target, dest, "f")
            else:
                t.set_value(rng.choice(live), rng.randint(0, 99))

        for _ in range(30):
            for t in trees:
                if rng.random() < 0.7:
                    random_edit(t)
            # partial sequencing so ops cross in flight
            seqr.process_some(rng.randint(0, 4))
        seqr.process_all_messages()
        assert len(digests(trees)) == 1, f"diverged at seed {seed}"
