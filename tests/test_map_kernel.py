"""Kernel-vs-oracle parity for the batched SharedMap device kernel.

The oracle is plain sequenced-order dict replay (what every converged replica
of models.SharedMap holds after draining); the kernel applies the same ops as
dense (doc × op) batches in one jit'd call.
"""

import random

import numpy as np
import pytest

from fluidframework_tpu.ops.map_kernel import TensorMapStore
from fluidframework_tpu.ops.schema import OpKind


def oracle_replay(n_docs, records):
    docs = [dict() for _ in range(n_docs)]
    for doc, kind, key, value, seq in records:
        if kind == OpKind.MAP_SET:
            docs[doc][key] = value
        elif kind == OpKind.MAP_DELETE:
            docs[doc].pop(key, None)
        elif kind == OpKind.MAP_CLEAR:
            docs[doc].clear()
    return docs


def random_records(rng, n_docs, n_ops, start_seq=1):
    keys = [f"k{i}" for i in range(12)]
    out = []
    seq = start_seq
    for _ in range(n_ops):
        doc = rng.randrange(n_docs)
        roll = rng.random()
        if roll < 0.72:
            out.append((doc, OpKind.MAP_SET, rng.choice(keys),
                        rng.choice([1, 2.5, "v", [1, 2], {"a": 1}, None]), seq))
        elif roll < 0.96:
            out.append((doc, OpKind.MAP_DELETE, rng.choice(keys), None, seq))
        else:
            out.append((doc, OpKind.MAP_CLEAR, None, None, seq))
        seq += 1
    return out, seq


@pytest.mark.parametrize("seed", range(8))
def test_map_kernel_matches_oracle_single_batch(seed):
    rng = random.Random(seed)
    n_docs = 16
    store = TensorMapStore(n_docs, n_keys=16)
    records, _ = random_records(rng, n_docs, 300)
    store.apply_batch(records)
    expect = oracle_replay(n_docs, records)
    for d in range(n_docs):
        assert store.read_doc(d) == expect[d], f"doc {d} mismatch"


@pytest.mark.parametrize("seed", range(8, 12))
def test_map_kernel_matches_oracle_multi_batch(seed):
    rng = random.Random(seed)
    n_docs = 8
    store = TensorMapStore(n_docs, n_keys=16)
    all_records = []
    seq = 1
    for _ in range(6):  # state threads across batches
        records, seq = random_records(rng, n_docs, rng.randint(10, 80), seq)
        store.apply_batch(records)
        all_records += records
    expect = oracle_replay(n_docs, all_records)
    for d in range(n_docs):
        assert store.read_doc(d) == expect[d]


def test_map_kernel_digest_detects_divergence():
    store_a = TensorMapStore(4, n_keys=8)
    store_b = TensorMapStore(4, n_keys=8)
    recs = [(0, OpKind.MAP_SET, "x", 1, 1), (2, OpKind.MAP_SET, "y", 2, 2)]
    store_a.apply_batch(recs)
    store_b.apply_batch(recs)
    assert np.array_equal(store_a.digests(), store_b.digests())
    store_b.apply_batch([(2, OpKind.MAP_SET, "y", 3, 3)])
    assert not np.array_equal(store_a.digests(), store_b.digests())


def test_map_kernel_parity_with_shared_map_model():
    """The device store and the interactive SharedMap replicas converge to the
    same per-doc contents when fed the same sequenced stream."""
    from fluidframework_tpu.models import SharedMap
    from fluidframework_tpu.testing.mocks import MockSequencer, create_connected_dds

    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedMap, "m")
    b = create_connected_dds(seqr, SharedMap, "m")
    store = TensorMapStore(1, n_keys=8)

    a.set("title", "hello")
    b.set("title", "world")
    a.delete("missing")
    b.set("n", 42)
    a.clear()
    a.set("post", [1])
    msgs = []
    while True:
        m = seqr.process_one()
        if m is None:
            break
        msgs.append(m)
    records = []
    for m in msgs:
        op = m.contents
        kind = {"set": OpKind.MAP_SET, "delete": OpKind.MAP_DELETE,
                "clear": OpKind.MAP_CLEAR}[op["op"]]
        records.append((0, kind, op.get("key"), op.get("value"), m.seq))
    store.apply_batch(records)
    assert store.read_doc(0) == dict(a.items()) == dict(b.items())
