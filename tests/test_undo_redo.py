"""Undo-redo package (reference: @fluidframework/undo-redo) + DDS events.

The reference tests this with mock-runtime multi-client setups: local edits
push revertibles via DDS events; undo issues ordinary ops so replicas
converge. Key reference behaviors pinned here: operation grouping, redo
cleared by fresh edits, revert-of-remove restoring text+props at the slid
position, tracked tombstones surviving zamboni, and annotate reverts
restoring previous values across segment splits.
"""

import random

from fluidframework_tpu.core.protocol import MessageType
from fluidframework_tpu.framework.undo_redo import (
    SharedMapUndoRedoHandler,
    SharedSegmentSequenceUndoRedoHandler,
    UndoRedoStackManager,
)
from fluidframework_tpu.models import SharedMap, SharedString
from fluidframework_tpu.testing.mocks import MockSequencer, create_connected_dds


def _pair(seqr, cls):
    return (create_connected_dds(seqr, cls, "x"),
            create_connected_dds(seqr, cls, "x"))


def _mk_undo(dds, handler_cls):
    stack = UndoRedoStackManager()
    handler = handler_cls(stack)
    handler.attach(dds)
    return stack


# ------------------------------------------------------------------ events


def test_map_value_changed_events():
    from fluidframework_tpu.models.shared_map import NO_VALUE
    seqr = MockSequencer()
    a, b = _pair(seqr, SharedMap)
    got = []
    b.on("valueChanged", lambda m, k, prev, local: got.append((k, prev, local)))
    a.set("k", 1)
    seqr.process_all_messages()
    assert got == [("k", NO_VALUE, False)]
    a.set("k", 2)
    seqr.process_all_messages()
    assert got[-1] == ("k", 1, False)
    # local emission on the editing replica
    local_got = []
    a.on("valueChanged", lambda m, k, prev, local: local_got.append((k, prev, local)))
    a.set("k", 3)
    assert local_got == [("k", 2, True)]
    seqr.process_all_messages()
    # concurrent remote op shadowed by a's in-flight local op: no event on a
    b.set("k", 99)   # sequenced FIRST
    a.set("k", 100)  # a's local op in flight when b's arrives
    n_before = len(local_got)
    seqr.process_all_messages()
    remote_events = [e for e in local_got[n_before:] if not e[2]]
    assert remote_events == []  # b's set was shadowed on a
    assert a.get("k") == b.get("k") == 100


def test_string_sequence_delta_events():
    seqr = MockSequencer()
    a, b = _pair(seqr, SharedString)
    got = []
    b.on("sequenceDelta", lambda s, d, local: got.append((d["operation"], local)))
    a.insert_text(0, "hi")
    seqr.process_all_messages()
    assert got == [("insert", False)]
    a.remove_text(0, 1)
    seqr.process_all_messages()
    assert got[-1] == ("remove", False)


# -------------------------------------------------------------------- map


def test_map_undo_redo_roundtrip():
    seqr = MockSequencer()
    a, b = _pair(seqr, SharedMap)
    stack = _mk_undo(a, SharedMapUndoRedoHandler)
    a.set("k", "v1")
    stack.close_current_operation()
    a.set("k", "v2")
    stack.close_current_operation()
    seqr.process_all_messages()
    assert stack.undo_operation()
    seqr.process_all_messages()
    assert a.get("k") == b.get("k") == "v1"
    assert stack.undo_operation()
    seqr.process_all_messages()
    assert not a.has("k") and not b.has("k")
    assert stack.redo_operation()
    seqr.process_all_messages()
    assert a.get("k") == b.get("k") == "v1"
    assert stack.redo_operation()
    seqr.process_all_messages()
    assert a.get("k") == b.get("k") == "v2"
    assert not stack.redo_operation()


def test_map_undo_grouped_operation_and_clear():
    seqr = MockSequencer()
    a, b = _pair(seqr, SharedMap)
    stack = _mk_undo(a, SharedMapUndoRedoHandler)
    a.set("x", 1)
    a.set("y", 2)
    stack.close_current_operation()  # one gesture = one operation
    a.clear()
    stack.close_current_operation()
    seqr.process_all_messages()
    assert len(a) == 0
    assert stack.undo_operation()  # undo the clear restores both keys
    seqr.process_all_messages()
    assert b.items() == [("x", 1), ("y", 2)]
    assert stack.undo_operation()  # undo the grouped sets removes both
    seqr.process_all_messages()
    assert len(a) == len(b) == 0


def test_map_fresh_edit_clears_redo():
    seqr = MockSequencer()
    a, _ = _pair(seqr, SharedMap)
    stack = _mk_undo(a, SharedMapUndoRedoHandler)
    a.set("k", 1)
    stack.close_current_operation()
    stack.undo_operation()
    assert stack.redo_stack_size == 1
    a.set("k", 5)  # fresh edit in normal mode
    assert stack.redo_stack_size == 0
    assert not stack.redo_operation()


# ------------------------------------------------------------------ string


def test_string_undo_insert_remove_annotate():
    seqr = MockSequencer()
    a, b = _pair(seqr, SharedString)
    stack = _mk_undo(a, SharedSegmentSequenceUndoRedoHandler)

    a.insert_text(0, "hello world")
    stack.close_current_operation()
    a.annotate_range(0, 5, {"bold": True})
    stack.close_current_operation()
    a.remove_text(5, 11)
    stack.close_current_operation()
    seqr.process_all_messages()
    assert b.get_text() == "hello"

    assert stack.undo_operation()  # undo remove: " world" restored
    seqr.process_all_messages()
    assert a.get_text() == b.get_text() == "hello world"

    assert stack.undo_operation()  # undo annotate: bold gone
    seqr.process_all_messages()
    assert a.get_properties(0) == {} and b.get_properties(0) == {}

    assert stack.undo_operation()  # undo insert: empty doc
    seqr.process_all_messages()
    assert a.get_text() == b.get_text() == ""

    assert stack.redo_operation()  # redo insert
    seqr.process_all_messages()
    assert a.get_text() == b.get_text() == "hello world"
    assert stack.redo_operation()  # redo annotate
    seqr.process_all_messages()
    assert b.get_properties(0) == {"bold": True}
    assert stack.redo_operation()  # redo remove
    seqr.process_all_messages()
    assert a.get_text() == b.get_text() == "hello"


def test_string_undo_remove_restores_props_and_markers():
    seqr = MockSequencer()
    a, b = _pair(seqr, SharedString)
    stack = _mk_undo(a, SharedSegmentSequenceUndoRedoHandler)
    a.insert_text(0, "ab", {"k": 1})
    a.insert_marker(2, {"m": True})
    a.insert_text(3, "cd", {"k": 2})
    seqr.process_all_messages()
    stack.close_current_operation()  # don't undo the setup

    a.remove_text(1, 4)  # "b", marker, "c"
    stack.close_current_operation()
    seqr.process_all_messages()
    assert a.get_text() == "ad"

    assert stack.undo_operation()
    seqr.process_all_messages()
    assert a.get_text() == b.get_text() == "abcd"
    assert b.get_properties(1) == {"k": 1}
    assert b.get_properties(3) == {"k": 2}
    # the marker is back between b and c
    seg, _ = b.tree.get_containing_segment(2)
    assert seg.props == {"m": True}


def test_string_undo_positions_shift_with_remote_edits():
    """Undo after remote edits moved the content: revert targets the
    tracked segments' CURRENT positions."""
    seqr = MockSequencer()
    a, b = _pair(seqr, SharedString)
    stack = _mk_undo(a, SharedSegmentSequenceUndoRedoHandler)
    a.insert_text(0, "world")
    seqr.process_all_messages()
    stack.close_current_operation()

    a.insert_text(5, "!")  # the op we will undo
    stack.close_current_operation()
    b.insert_text(0, "hello ")  # concurrent remote edit shifts positions
    seqr.process_all_messages()
    assert a.get_text() == "hello world!"

    assert stack.undo_operation()
    seqr.process_all_messages()
    assert a.get_text() == b.get_text() == "hello world"


def test_string_undo_insert_split_by_remote_insert():
    """A remote insert lands INSIDE my tracked insert: undo removes both
    halves of mine but keeps the remote text."""
    seqr = MockSequencer()
    a, b = _pair(seqr, SharedString)
    stack = _mk_undo(a, SharedSegmentSequenceUndoRedoHandler)
    a.insert_text(0, "aaaa")
    stack.close_current_operation()
    seqr.process_all_messages()
    b.insert_text(2, "BB")
    seqr.process_all_messages()
    assert a.get_text() == "aaBBaa"
    assert stack.undo_operation()
    seqr.process_all_messages()
    assert a.get_text() == b.get_text() == "BB"


def test_string_undo_remove_survives_zamboni():
    """The tracked tombstone must survive the collaboration window closing
    (zamboni spares tracked segments), so undo still restores the text."""
    seqr = MockSequencer()
    a, b = _pair(seqr, SharedString)
    stack = _mk_undo(a, SharedSegmentSequenceUndoRedoHandler)
    a.insert_text(0, "keep DROP keep")
    seqr.process_all_messages()
    stack.close_current_operation()
    a.remove_text(5, 10)
    stack.close_current_operation()
    seqr.process_all_messages()
    # advance MSN well past the remove on every replica → zamboni runs
    for _ in range(3):
        for r in (a, b):
            seqr.submit(r, {}, type=MessageType.NOOP)
        seqr.process_all_messages()
    assert stack.undo_operation()
    seqr.process_all_messages()
    assert a.get_text() == b.get_text() == "keep DROP keep"


def test_string_annotate_undo_across_split():
    """Annotate, then a remote insert splits the annotated segment; undo
    must restore previous values on BOTH split halves."""
    seqr = MockSequencer()
    a, b = _pair(seqr, SharedString)
    stack = _mk_undo(a, SharedSegmentSequenceUndoRedoHandler)
    a.insert_text(0, "abcdef", {"color": "red"})
    seqr.process_all_messages()
    stack.close_current_operation()
    a.annotate_range(0, 6, {"color": "blue"})
    stack.close_current_operation()
    seqr.process_all_messages()
    b.insert_text(3, "XY")  # splits the annotated segment
    seqr.process_all_messages()
    assert stack.undo_operation()
    seqr.process_all_messages()
    for replica in (a, b):
        assert replica.get_properties(0)["color"] == "red"
        assert replica.get_properties(7)["color"] == "red"


def test_undo_discard_unblocks_zamboni():
    """Clearing the redo stack discards revertibles, unlinking tracking
    groups so tombstones become collectable again."""
    seqr = MockSequencer()
    a, b = _pair(seqr, SharedString)
    stack = _mk_undo(a, SharedSegmentSequenceUndoRedoHandler)
    a.insert_text(0, "abcdef")
    seqr.process_all_messages()
    stack.close_current_operation()
    a.remove_text(0, 3)
    stack.close_current_operation()
    seqr.process_all_messages()
    stack.undo_operation()  # remove's revertible consumed; redo holds insert-revert
    seqr.process_all_messages()
    a.insert_text(0, "Z")  # fresh edit clears redo → discards its tracking
    seqr.process_all_messages()
    # the undo stack still tracks LIVE segments (that's its job), but no
    # tombstone may stay tracked — zamboni must be able to free them
    assert all(not s.tracking for s in a.tree.segments
               if s.removed_seq is not None)
    for _ in range(3):  # MSN catch-up: zamboni reclaims the tombstones
        for r in (a, b):
            seqr.submit(r, {}, type=MessageType.NOOP)
        seqr.process_all_messages()
    assert all(s.removed_seq is None for s in a.tree.segments)


def test_undo_fuzz_converges():
    """Random edits + undos on one replica, concurrent edits on the other:
    all replicas converge after every drain (undo ops are ordinary ops)."""
    rng = random.Random(11)
    seqr = MockSequencer()
    a, b = _pair(seqr, SharedString)
    stack = _mk_undo(a, SharedSegmentSequenceUndoRedoHandler)
    for round_no in range(60):
        r = rng.random()
        n_a, n_b = a.get_length(), b.get_length()
        if r < 0.35 or n_a == 0:
            a.insert_text(rng.randint(0, n_a), rng.choice("xyzw") * rng.randint(1, 3))
            stack.close_current_operation()
        elif r < 0.55:
            s = rng.randrange(n_a)
            a.remove_text(s, rng.randint(s + 1, min(n_a, s + 4)))
            stack.close_current_operation()
        elif r < 0.7 and n_b > 0:
            s = rng.randrange(n_b)
            b.insert_text(s, "R")
        elif r < 0.85:
            stack.undo_operation()
        else:
            stack.redo_operation()
        if rng.random() < 0.4:
            seqr.process_some(rng.randint(0, seqr.outstanding))
        else:
            seqr.process_all_messages()
    seqr.process_all_messages()
    assert a.get_text() == b.get_text()
    assert a.tree.structure_digest() == b.tree.structure_digest()
