"""Overflow recovery end-to-end (VERDICT r1 #2): a doc whose device row
overflows mid-stream — acked ops silently dropped by the kernel — must be
drained from the durable log through a fresh rebuild and come back correct,
automatically, with zero acked ops lost."""

import random

import numpy as np
import pytest

from fluidframework_tpu.server.serving import StringServingEngine
from tests.test_merge_tree_kernel import collab_stream


def _feed(engine, doc, msgs):
    """Push oracle-sequenced messages through the engine's raw submit path
    (the engine re-sequences; oracle msgs provide the op contents)."""
    cseq = {}
    for m in msgs:
        cseq[m.client_id] = cseq.get(m.client_id, 0) + 1
        got, nack = engine.submit(doc, m.client_id, cseq[m.client_id],
                                  engine.deli.doc_seq(doc), m.contents)
        assert nack is None, (m, nack)


def _connect_clients(engine, doc, msgs):
    for cid in sorted({m.client_id for m in msgs}):
        engine.connect(doc, cid)


def _control_text(msgs, doc="d", capacity=2048, **kw):
    """What the engine SHOULD read: the same feed through an engine whose
    capacity never overflows."""
    control = StringServingEngine(n_docs=2, capacity=capacity,
                                  batch_window=8, compact_every=10 ** 9,
                                  **kw)
    _connect_clients(control, doc, msgs)
    _feed(control, doc, msgs)
    return control.read_text(doc)


def test_flat_overflow_reupload_recovers_text():
    """Tiny capacity forces overflow mid-stream; after recovery (window
    floor = everything acked, so the rebuild compacts well below capacity)
    the doc is re-uploaded and reads what a never-overflowed engine reads."""
    _, _, msgs = collab_stream(3, n_rounds=20)
    want = _control_text(msgs)
    engine = StringServingEngine(n_docs=2, capacity=64, batch_window=8,
                                 compact_every=10 ** 9)  # manual compaction
    engine.auto_recover = False
    _connect_clients(engine, "d", msgs)
    _feed(engine, "d", msgs)
    engine.flush()
    assert engine.overflowed_docs() == ["d"]
    report = engine.recover_overflowed()
    assert report == {"d": "reuploaded"}
    assert engine.overflowed_docs() == []
    assert engine.read_text("d") == want
    # visible length includes markers; compare against a no-overflow control
    control = StringServingEngine(n_docs=2, capacity=2048, batch_window=8,
                                  compact_every=10 ** 9)
    _connect_clients(control, "d", msgs)
    _feed(control, "d", msgs)
    assert engine.store.visible_length(engine.doc_row("d")) == \
        control.store.visible_length(control.doc_row("d"))


def test_flat_overflow_graduates_when_too_big():
    """A doc whose LIVE text exceeds the flat tier's capacity graduates to
    its own store and keeps serving (reads + later ops)."""
    engine = StringServingEngine(n_docs=2, capacity=32, batch_window=4,
                                 compact_every=10 ** 9)
    engine.auto_recover = False
    engine.connect("d", 1)
    rng = random.Random(0)
    shadow = ""
    # 80 inserts * 3 chars, never removed: live slots >> 32
    for i in range(80):
        pos = rng.randint(0, len(shadow))
        word = f"w{i}"
        msg, nack = engine.submit(
            "d", 1, i + 1, engine.deli.doc_seq("d"),
            {"mt": "insert", "kind": 0, "pos": pos, "text": word})
        assert nack is None
        shadow = shadow[:pos] + word + shadow[pos:]
    engine.flush()
    assert engine.overflowed_docs() == ["d"]
    report = engine.recover_overflowed()
    assert report == {"d": "graduated"}
    assert engine.read_text("d") == shadow
    # later ops keep flowing (graduated tier is a full serving store)
    msg, nack = engine.submit(
        "d", 1, 81, engine.deli.doc_seq("d"),
        {"mt": "insert", "kind": 0, "pos": 0, "text": "HEAD:"})
    assert nack is None
    assert engine.read_text("d") == "HEAD:" + shadow
    # the vacated flat row is RELEASED and reused by the next doc
    engine.connect("e", 9)
    engine.submit("e", 9, 1, 0,
                  {"mt": "insert", "kind": 0, "pos": 0, "text": "ok"})
    assert engine.doc_row("e") == 0  # d's old row, recycled
    assert engine.read_text("e") == "ok"
    assert engine.read_text("d") == "HEAD:" + shadow  # d unaffected


def test_auto_recovery_on_compaction_cadence():
    """With auto_recover on (default), the compaction cadence detects the
    overflow and heals it with no operator involvement."""
    _, _, msgs = collab_stream(5, n_rounds=20)
    want = _control_text(msgs)
    engine = StringServingEngine(n_docs=2, capacity=64, batch_window=8,
                                 compact_every=2)
    _connect_clients(engine, "d", msgs)
    _feed(engine, "d", msgs)
    engine.flush()
    engine.compact()  # cadence point (flush count independent)
    assert engine.overflowed_docs() == []
    assert engine.read_text("d") == want


def test_recovery_survives_summary_reload():
    """Summarize AFTER recovery (graduated doc included) and reload: the
    graduated store round-trips and the tail replays into it."""
    engine = StringServingEngine(n_docs=2, capacity=32, batch_window=4,
                                 compact_every=10 ** 9)
    engine.auto_recover = False
    engine.connect("d", 1)
    shadow = ""
    for i in range(60):
        word = f"x{i}"
        engine.submit("d", 1, i + 1, engine.deli.doc_seq("d"),
                      {"mt": "insert", "kind": 0, "pos": 0, "text": word})
        shadow = word + shadow
    engine.flush()
    engine.recover_overflowed()
    summary = engine.summarize()
    # tail after the summary
    msg, nack = engine.submit(
        "d", 1, 61, engine.deli.doc_seq("d"),
        {"mt": "insert", "kind": 0, "pos": 0, "text": "TAIL:"})
    assert nack is None
    restored = StringServingEngine.load(summary, engine.log)
    assert restored.read_text("d") == "TAIL:" + shadow
    assert "d" in restored._graduated


def _storm_mega(engine, doc, n_churn, n_keep):
    """Churn inserts+removes (tombstone build-up) then durable inserts;
    returns the expected text."""
    cs = 0
    for i in range(n_churn):
        cs += 1
        engine.submit(doc, 1, cs, engine.deli.doc_seq(doc),
                      {"mt": "insert", "kind": 0, "pos": 0, "text": "ab"})
        cs += 1
        engine.submit(doc, 1, cs, engine.deli.doc_seq(doc),
                      {"mt": "remove", "start": 0, "end": 2})
    shadow = ""
    for i in range(n_keep):
        cs += 1
        word = f"k{i}"
        engine.submit(doc, 1, cs, engine.deli.doc_seq(doc),
                      {"mt": "insert", "kind": 0, "pos": 0, "text": word})
        shadow = word + shadow
    engine.flush()
    return shadow


def test_mega_overflow_reuploads():
    """Tombstone churn overflows the mega shards (compaction disabled);
    the drain compacts at the window floor and re-uploads across shards."""
    engine = StringServingEngine(n_docs=1, capacity=64, batch_window=8,
                                 compact_every=10 ** 9, mega_docs=1,
                                 mega_capacity_per_shard=16)
    engine.auto_recover = False
    engine.mark_mega("m")
    engine.connect("m", 1)
    want = _storm_mega(engine, "m", n_churn=150, n_keep=10)
    assert engine.overflowed_docs() == ["m"]
    report = engine.recover_overflowed()
    assert report == {"m": "reuploaded"}
    assert engine.overflowed_docs() == []
    assert engine.read_text("m") == want


def test_mega_overflow_graduates_when_live_exceeds_shards():
    """Live text larger than shards×capacity graduates the mega doc."""
    engine = StringServingEngine(n_docs=1, capacity=64, batch_window=8,
                                 compact_every=10 ** 9, mega_docs=1,
                                 mega_capacity_per_shard=16)
    engine.auto_recover = False
    engine.mark_mega("m")
    engine.connect("m", 1)
    want = _storm_mega(engine, "m", n_churn=0, n_keep=200)
    assert engine.overflowed_docs() == ["m"]
    report = engine.recover_overflowed()
    assert report == {"m": "graduated"}
    assert engine.overflowed_docs() == []
    assert engine.read_text("m") == want
    # later ops land on the graduated store
    msg, nack = engine.submit(
        "m", 1, 201, engine.deli.doc_seq("m"),
        {"mt": "insert", "kind": 0, "pos": 0, "text": "NEW:"})
    assert nack is None
    assert engine.read_text("m") == "NEW:" + want


def test_recovery_preserves_annotations():
    """Props survive the rebuild + handle/plane remapping."""
    _, _, msgs = collab_stream(9, n_rounds=16, with_annotates=True)
    engine = StringServingEngine(n_docs=1, capacity=64, batch_window=8,
                                 compact_every=10 ** 9)
    engine.auto_recover = False
    _connect_clients(engine, "d", msgs)
    _feed(engine, "d", msgs)
    engine.flush()
    assert engine.overflowed_docs() == ["d"]  # corpus must overflow cap 64
    engine.recover_overflowed()
    # full parity against a never-overflowed control engine
    control = StringServingEngine(n_docs=1, capacity=2048, batch_window=8,
                                  compact_every=10 ** 9)
    _connect_clients(control, "d", msgs)
    _feed(control, "d", msgs)
    text = control.read_text("d")
    assert engine.read_text("d") == text
    for pos in range(0, len(text), max(1, len(text) // 16)):
        assert engine.get_properties("d", pos) == \
            control.get_properties("d", pos), pos


def test_graduated_store_reoverflow_regrows():
    """The terminal tier is watched too: a graduated doc that outgrows its
    rebuild-time capacity is rebuilt again at doubled capacity
    (code-review r2 finding: data loss reintroduced on the terminal tier)."""
    engine = StringServingEngine(n_docs=2, capacity=32, batch_window=4,
                                 compact_every=10 ** 9)
    engine.auto_recover = False
    engine.connect("d", 1)
    shadow = ""
    cs = 0
    for i in range(60):
        cs += 1
        word = f"w{i}"
        engine.submit("d", 1, cs, engine.deli.doc_seq("d"),
                      {"mt": "insert", "kind": 0, "pos": 0, "text": word})
        shadow = word + shadow
    engine.flush()
    assert engine.recover_overflowed() == {"d": "graduated"}
    cap0 = engine._graduated["d"].capacity
    # keep growing until the graduated store overflows as well
    while not engine._graduated["d"].overflowed().any():
        cs += 1
        word = f"g{cs}"
        engine.submit("d", 1, cs, engine.deli.doc_seq("d"),
                      {"mt": "insert", "kind": 0, "pos": 0, "text": word})
        shadow = word + shadow
        engine.flush()
    report = engine.recover_overflowed()
    assert report == {"d": "regrown"}
    assert engine._graduated["d"].capacity > cap0
    assert engine.read_text("d") == shadow


def test_mass_overflow_recovers_in_batch():
    """A correlated mass overflow (many docs hitting capacity together —
    the r4 profiling cliff) must recover via the BATCHED rebuild: every
    doc rebuilt in one multi-doc store per doubling, mixed outcomes
    (re-upload for compactable docs, graduation for genuinely big ones),
    zero acked ops lost."""
    import time as _time
    from fluidframework_tpu.server.serving import StringServingEngine
    R = 32
    eng = StringServingEngine(n_docs=R, capacity=128,
                              batch_window=10 ** 9)
    docs = [f"mass-{i}" for i in range(R)]
    for d in docs:
        eng.connect(d, 1)
    eng.auto_recover = False
    # half the docs: grow past capacity and STAY big (graduate);
    # other half: grow, then tombstone most + advance the floor (reupload)
    for i, d in enumerate(docs):
        for k in range(150):
            _, nack = eng.submit(d, 1, k + 1, 0,
                                 {"mt": "insert", "kind": 0, "pos": 0,
                                  "text": "M"})
            assert nack is None
        if i % 2:
            for k in range(130):
                _, nack = eng.submit(d, 1, 151 + k, 150,
                                     {"mt": "remove", "start": 0,
                                      "end": 1})
                assert nack is None
    eng.flush()
    for i, d in enumerate(docs):
        if i % 2:
            eng.heartbeat(d, 1, eng.deli.doc_seq(d))
    assert eng.store.overflowed().sum() == R  # everyone overflowed
    t0 = _time.monotonic()
    report = eng.recover_overflowed()
    wall = _time.monotonic() - t0
    assert len(report) == R
    for i, d in enumerate(docs):
        want = "reuploaded" if i % 2 else "graduated"
        assert report[d] == want, (d, report[d])
        text = eng.read_text(d)
        assert len(text) == (20 if i % 2 else 150), d
    # the batched path's device reads are O(doublings), not O(docs):
    # generous bound that the per-doc path (32 × 2 syncs + applies)
    # would blow through on a remote device
    assert wall < 120, wall
