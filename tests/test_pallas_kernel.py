"""Pallas VMEM-resident merge kernel vs the XLA scan path (interpret mode on
the CPU mesh; the real-TPU run is covered by bench.py and the driver)."""

import numpy as np
import pytest
import jax.numpy as jnp

from fluidframework_tpu.ops.merge_tree_kernel import (
    StringState, apply_string_batch,
)
from fluidframework_tpu.ops.pallas_string_kernel import (
    apply_string_batch_pallas,
)
from fluidframework_tpu.testing.synthetic import typing_storm

ORDER = ("kind", "a0", "a1", "a2", "seq", "client", "ref_seq")
CHECK = ("seq", "client", "removed_seq", "removers", "length", "handle_op",
         "handle_off", "count", "overflow")


def _assert_equal(a: StringState, b: StringState):
    for k in CHECK:
        assert np.array_equal(np.asarray(getattr(a, k)),
                              np.asarray(getattr(b, k))), k


@pytest.mark.parametrize("seed", range(3))
def test_pallas_matches_xla_single_batch(seed):
    planes, _ = typing_storm(16, 32, seed=seed)
    ops = tuple(jnp.asarray(planes[k]) for k in ORDER)
    ref = apply_string_batch(StringState.create(16, 256), *ops)
    out = apply_string_batch_pallas(StringState.create(16, 256), *ops,
                                    tile=8, interpret=True)
    _assert_equal(ref, out)


def test_pallas_matches_xla_multiclient_stream():
    """Real multi-client concurrency (lagging ref_seq) through the Pallas
    op loop."""
    from tests.test_megadoc import _planes_from_msgs
    from tests.test_merge_tree_kernel import collab_stream
    _, _, msgs = collab_stream(4, n_rounds=12)
    ops = _planes_from_msgs(msgs)
    ref = apply_string_batch(StringState.create(1, 512), *ops)
    out = apply_string_batch_pallas(StringState.create(1, 512), *ops,
                                    tile=1, interpret=True)
    _assert_equal(ref, out)


def test_pallas_threads_state_across_batches():
    state_p = StringState.create(8, 128)
    state_x = StringState.create(8, 128)
    seq = 1
    for r in range(3):
        planes, seq = typing_storm(8, 16, seed=r, start_seq=seq)
        ops = tuple(jnp.asarray(planes[k]) for k in ORDER)
        state_p = apply_string_batch_pallas(state_p, *ops, tile=8,
                                            interpret=True)
        state_x = apply_string_batch(state_x, *ops)
        _assert_equal(state_x, state_p)


def test_pallas_overflow_flag_not_corruption():
    planes, _ = typing_storm(8, 64, seed=5)
    ops = tuple(jnp.asarray(planes[k]) for k in ORDER)
    ref = apply_string_batch(StringState.create(8, 16), *ops)
    out = apply_string_batch_pallas(StringState.create(8, 16), *ops,
                                    tile=8, interpret=True)
    _assert_equal(ref, out)
    assert np.asarray(out.overflow).any()
