"""Pallas VMEM-resident merge kernel vs the XLA scan path (interpret mode on
the CPU mesh; the real-TPU run is covered by bench.py and the driver)."""

import numpy as np
import pytest
import jax.numpy as jnp

from fluidframework_tpu.ops.merge_tree_kernel import (
    StringState, apply_string_batch,
)
from fluidframework_tpu.ops.pallas_string_kernel import (
    apply_string_batch_pallas,
)
from fluidframework_tpu.testing.synthetic import typing_storm

ORDER = ("kind", "a0", "a1", "a2", "seq", "client", "ref_seq")
CHECK = ("seq", "client", "removed_seq", "removers", "length", "handle_op",
         "handle_off", "count", "overflow")


def _assert_equal(a: StringState, b: StringState):
    for k in CHECK:
        assert np.array_equal(np.asarray(getattr(a, k)),
                              np.asarray(getattr(b, k))), k


@pytest.mark.parametrize("seed", range(3))
def test_pallas_matches_xla_single_batch(seed):
    planes, _ = typing_storm(16, 32, seed=seed)
    ops = tuple(jnp.asarray(planes[k]) for k in ORDER)
    ref = apply_string_batch(StringState.create(16, 256), *ops)
    out = apply_string_batch_pallas(StringState.create(16, 256), *ops,
                                    tile=8, interpret=True)
    _assert_equal(ref, out)


def test_pallas_matches_xla_multiclient_stream():
    """Real multi-client concurrency (lagging ref_seq) through the Pallas
    op loop."""
    from tests.test_megadoc import _planes_from_msgs
    from tests.test_merge_tree_kernel import collab_stream
    _, _, msgs = collab_stream(4, n_rounds=12)
    ops = _planes_from_msgs(msgs)
    ref = apply_string_batch(StringState.create(1, 512), *ops)
    out = apply_string_batch_pallas(StringState.create(1, 512), *ops,
                                    tile=1, interpret=True)
    _assert_equal(ref, out)


def test_pallas_threads_state_across_batches():
    state_p = StringState.create(8, 128)
    state_x = StringState.create(8, 128)
    seq = 1
    for r in range(3):
        planes, seq = typing_storm(8, 16, seed=r, start_seq=seq)
        ops = tuple(jnp.asarray(planes[k]) for k in ORDER)
        state_p = apply_string_batch_pallas(state_p, *ops, tile=8,
                                            interpret=True)
        state_x = apply_string_batch(state_x, *ops)
        _assert_equal(state_x, state_p)


def test_pallas_overflow_flag_not_corruption():
    planes, _ = typing_storm(8, 64, seed=5)
    ops = tuple(jnp.asarray(planes[k]) for k in ORDER)
    ref = apply_string_batch(StringState.create(8, 16), *ops)
    out = apply_string_batch_pallas(StringState.create(8, 16), *ops,
                                    tile=8, interpret=True)
    _assert_equal(ref, out)
    assert np.asarray(out.overflow).any()


def test_pallas_fused_compaction_matches_xla_apply_then_compact():
    """min_seq fused into the kernel epilogue (bit-shift stream compaction
    in VMEM) must match XLA apply + sort-based compact exactly on the
    active region, including stability (kept-slot order)."""
    from fluidframework_tpu.ops.merge_tree_kernel import (
        compact_string_state, string_state_digest,
    )
    for seed in range(3):
        sp = StringState.create(8, 128)
        sx = StringState.create(8, 128)
        seq = 1
        for r in range(3):
            planes, seq = typing_storm(8, 16, seed=seed * 10 + r,
                                       start_seq=seq)
            ops = tuple(jnp.asarray(planes[k]) for k in ORDER)
            ms = np.full((8,), max(seq - 17, 0), np.int32)  # partial window
            sp = apply_string_batch_pallas(sp, *ops, min_seq=ms, tile=8,
                                           interpret=True)
            sx = compact_string_state(apply_string_batch(sx, *ops),
                                      jnp.asarray(ms))
            cnt = np.asarray(sp.count)
            assert np.array_equal(cnt, np.asarray(sx.count)), (seed, r)
            for k in ("seq", "client", "removed_seq", "removers", "length",
                      "handle_op", "handle_off"):
                a = np.asarray(getattr(sp, k))
                b = np.asarray(getattr(sx, k))
                for d in range(8):
                    assert np.array_equal(a[d, :cnt[d]], b[d, :cnt[d]]), \
                        (k, seed, r, d)
            assert np.array_equal(np.asarray(string_state_digest(sp)),
                                  np.asarray(string_state_digest(sx)))


def test_store_product_path_runs_pallas():
    """The PRODUCT path (TensorStringStore._dispatch_apply, VERDICT r1 #1):
    the same multi-client message stream through the Pallas-interpret store
    and the XLA store must converge to identical text and digests."""
    from fluidframework_tpu.ops.string_store import (
        TensorStringStore, pallas_tile_for,
    )
    from tests.test_merge_tree_kernel import collab_stream

    assert pallas_tile_for(8, 256) == 8
    assert pallas_tile_for(10240, 384) == 128
    assert pallas_tile_for(7, 256) is None      # doc count not tileable
    assert pallas_tile_for(8, 200) is None      # capacity not lane-aligned

    text, length, msgs = collab_stream(7, n_rounds=10)
    a = TensorStringStore(n_docs=8, capacity=256)
    a.pallas = "interpret"
    b = TensorStringStore(n_docs=8, capacity=256)
    b.pallas = "off"
    for store in (a, b):
        store.apply_messages((3, m) for m in msgs)
    assert a.read_text(3) == text == b.read_text(3)
    assert a.visible_length(3) == length
    assert np.array_equal(a.digests(), b.digests())


def test_store_pallas_falls_back_on_annotate():
    """A store that sees an annotate must leave the fused no-props kernel
    and still converge (the one-way _has_props transition)."""
    from fluidframework_tpu.ops.string_store import TensorStringStore
    from tests.test_merge_tree_kernel import collab_stream

    text, _, msgs = collab_stream(11, n_rounds=10, with_annotates=True)
    store = TensorStringStore(n_docs=8, capacity=512)
    store.pallas = "interpret"
    store.apply_messages((0, m) for m in msgs)
    assert store.read_text(0) == text


def test_replicated_step_pallas_matches_xla():
    """Multi-chip step on the fused kernel (VERDICT r1 #1): per-shard Pallas
    apply under shard_map agrees with the single-device XLA scan."""
    from fluidframework_tpu.ops.merge_tree_kernel import string_state_digest
    from fluidframework_tpu.parallel import (
        make_mesh, make_replicated_step, shard_state, shard_ops,
    )

    mesh = make_mesh(8)
    _, doc_shards = mesh.devices.shape
    n_docs, n_ops, cap = 8 * doc_shards, 8, 128
    planes, _ = typing_storm(n_docs, n_ops, seed=5)
    ops = tuple(jnp.asarray(planes[k]) for k in ORDER)

    single = apply_string_batch(StringState.create(n_docs, cap), *ops)
    step = make_replicated_step(mesh, with_props=False, use_pallas=True,
                                pallas_tile=8, pallas_interpret=True)
    state = shard_state(StringState.create(n_docs, cap), mesh)
    new_state, digest, agree = step(state, *shard_ops(mesh, *ops))
    assert int(agree) == 1
    assert np.array_equal(np.asarray(digest),
                          np.asarray(string_state_digest(single)))


def _annotate_ops(seed, n_docs=8, n_ops=24):
    """Raw op planes with interleaved annotates (packed key<<20|value)."""
    import numpy as np
    from fluidframework_tpu.ops.merge_tree_kernel import PROP_HANDLE_BITS
    from fluidframework_tpu.ops.schema import OpKind
    rng = np.random.default_rng(seed)
    planes, _ = typing_storm(n_docs, n_ops, seed=seed)
    kind, a0, a1, a2 = (planes[k] for k in ("kind", "a0", "a1", "a2"))
    # turn ~1/3 of removes into annotates over the same range
    ann = (kind == OpKind.STR_REMOVE) & (rng.random(kind.shape) < 0.5)
    kind = np.where(ann, OpKind.STR_ANNOTATE, kind)
    key = rng.integers(0, 4, kind.shape).astype(np.int32)
    val = rng.integers(0, 7, kind.shape).astype(np.int32)  # 0 = delete key
    a2 = np.where(ann, (key << PROP_HANDLE_BITS) | val, a2)
    planes.update(kind=kind, a2=a2)
    return tuple(jnp.asarray(planes[k]) for k in ORDER)


def _assert_equal_with_props(a: StringState, b: StringState):
    _assert_equal(a, b)
    assert np.array_equal(np.asarray(a.prop_val), np.asarray(b.prop_val))


@pytest.mark.parametrize("seed", range(3))
def test_pallas_props_matches_xla(seed):
    """The props specialization: annotate-bearing batches through the VMEM
    kernel agree with the XLA scan, property planes included."""
    ops = _annotate_ops(seed)
    ref = apply_string_batch(StringState.create(8, 256), *ops,
                             with_props=True)
    out = apply_string_batch_pallas(StringState.create(8, 256), *ops,
                                    tile=8, interpret=True, with_props=True)
    _assert_equal_with_props(ref, out)


def test_pallas_props_fused_compact_matches_xla():
    """Active-region parity (beyond count the sort path parks dropped
    slots, the shift path zeroes — both semantically ignored)."""
    from fluidframework_tpu.ops.merge_tree_kernel import (
        compact_string_state, string_state_digest,
    )
    ops = _annotate_ops(7)
    ms = jnp.full((8,), 40, jnp.int32)
    ref = compact_string_state(
        apply_string_batch(StringState.create(8, 256), *ops,
                           with_props=True), ms, True)
    out = apply_string_batch_pallas(StringState.create(8, 256), *ops,
                                    tile=8, interpret=True, with_props=True,
                                    min_seq=ms)
    cnt = np.asarray(out.count)
    assert np.array_equal(cnt, np.asarray(ref.count))
    for k in CHECK[:-2] + ("prop_val",):
        a, b = np.asarray(getattr(out, k)), np.asarray(getattr(ref, k))
        for d in range(8):
            assert np.array_equal(a[d, :cnt[d]], b[d, :cnt[d]]), (k, d)
    assert np.array_equal(np.asarray(string_state_digest(out)),
                          np.asarray(string_state_digest(ref)))


def test_store_annotate_stream_stays_on_pallas():
    """An annotate-bearing store now KEEPS the fused path (props kernel)
    and still converges with the oracle (the r1 one-way fall-off, fixed)."""
    from fluidframework_tpu.ops.string_store import TensorStringStore
    from tests.test_merge_tree_kernel import collab_stream

    text, _, msgs = collab_stream(13, n_rounds=12, with_annotates=True)
    store = TensorStringStore(n_docs=8, capacity=512)
    store.pallas = "interpret"
    store.apply_messages((2, m) for m in msgs)
    assert store._has_props
    use_pallas, _, _ = store._pallas_choice()
    assert use_pallas  # props no longer kicks the store off the kernel
    assert store.read_text(2) == text


@pytest.mark.parametrize("seed", range(3))
def test_conflict_storm_pallas_matches_xla(seed):
    """The conflict-heavy corpus (divergent ref_seq, overlapping removes,
    annotates) through BOTH kernels, multi-batch with fused compaction."""
    from fluidframework_tpu.ops.merge_tree_kernel import (
        compact_string_state, string_state_digest,
    )
    from fluidframework_tpu.testing.synthetic import conflict_storm

    sp = StringState.create(8, 512)
    sx = StringState.create(8, 512)
    seq = 1
    for r in range(3):
        planes, seq = conflict_storm(8, 48, seed=seed * 10 + r,
                                     start_seq=seq)
        ops = tuple(jnp.asarray(planes[k]) for k in ORDER)
        ms = np.full((8,), max(seq - 8 * 50, 0), np.int32)
        sp = apply_string_batch_pallas(sp, *ops, tile=8, interpret=True,
                                       with_props=True, min_seq=ms)
        sx = compact_string_state(
            apply_string_batch(sx, *ops, with_props=True),
            jnp.asarray(ms), True)
        cnt = np.asarray(sp.count)
        assert np.array_equal(cnt, np.asarray(sx.count)), (seed, r)
        for k in CHECK[:-2] + ("prop_val",):
            a, b = np.asarray(getattr(sp, k)), np.asarray(getattr(sx, k))
            for d in range(8):
                assert np.array_equal(a[d, :cnt[d]], b[d, :cnt[d]]), \
                    (k, seed, r, d)
        assert np.array_equal(np.asarray(string_state_digest(sp)),
                              np.asarray(string_state_digest(sx)))
    assert not np.asarray(sp.overflow).any()
