"""Test configuration: force an 8-device virtual CPU mesh for all tests.

Multi-chip hardware is not available in this environment; sharding and
collective paths are validated on a virtual CPU mesh
(``--xla_force_host_platform_device_count=8``).

Note: the environment's sitecustomize imports jax at interpreter start, which
snapshots JAX_PLATFORMS=axon (the TPU tunnel) into jax.config — env vars set
afterwards are ignored. ``jax.config.update`` + XLA_FLAGS before first backend
use is the reliable override.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# The full suite compiles many hundreds of distinct XLA programs; past a
# threshold the in-process CPU compiler segfaults (observed twice at
# different tests, always inside backend_compile_and_load). Bound the
# live-executable arena by clearing jit caches between test modules.
#
# Do NOT re-enable the persistent on-disk compilation cache here: on this
# jaxlib (0.4.37, CPU), executables loaded WARM from the disk cache
# flakily compute garbage (reproduced: a fresh cache dir passes, every
# later process fails ~50% with corrupted store planes — wrong replay
# text, payload handles past the interner table). Cold compiles are
# correct; only deserialized executables misbehave, so clearing caches
# between modules + a disk cache turned every module boundary into a
# roll of that dice. Recompiles are the price of correct kernels.

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _bound_compile_arena():
    yield
    jax.clear_caches()


# Hard-exit machinery: full-suite runs have died in XLA's C++ teardown
# (atexit destructors) AFTER every test passed, eating the terminal
# summary and the exit status — CI could not prove the green run. The
# latest safe point to bail is pytest_unconfigure: by then the terminal
# reporter's sessionfinish wrapper has completed (failure recap,
# warnings, --durations, the stats line are all printed); os._exit then
# skips only the crashing interpreter teardown, preserving the status.
_exit_status = [None]


def pytest_sessionfinish(session, exitstatus):
    _exit_status[0] = int(exitstatus)


@pytest.hookimpl(trylast=True)
def pytest_unconfigure(config):
    import sys
    # os._exit skips ALL buffered-stream flushing: flush every stream the
    # terminal reporter may have written through (capture swaps sys.stdout,
    # so the summary text can sit in the ORIGINAL stream's buffer)
    try:
        config.get_terminal_writer().flush()
    except Exception:
        pass
    for f in (sys.stdout, sys.stderr, sys.__stdout__, sys.__stderr__):
        try:
            f.flush()
        except Exception:
            pass
    # sessionfinish never ran (startup failure before the session): let
    # pytest's own error exit code through rather than forging a 0
    if _exit_status[0] is not None \
            and not os.environ.get("FLUID_NO_HARDEXIT"):
        os._exit(_exit_status[0])
