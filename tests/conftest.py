"""Test configuration: force an 8-device virtual CPU mesh for sharding tests.

Multi-chip hardware is not available in this environment; per the build
instructions, sharding/collective paths are validated on a virtual CPU mesh
(``--xla_force_host_platform_device_count=8``). Must run before jax import.
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()
