"""Test configuration: force an 8-device virtual CPU mesh for all tests.

Multi-chip hardware is not available in this environment; sharding and
collective paths are validated on a virtual CPU mesh
(``--xla_force_host_platform_device_count=8``).

Note: the environment's sitecustomize imports jax at interpreter start, which
snapshots JAX_PLATFORMS=axon (the TPU tunnel) into jax.config — env vars set
afterwards are ignored. ``jax.config.update`` + XLA_FLAGS before first backend
use is the reliable override.
"""

import os

_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

# The full suite compiles many hundreds of distinct XLA programs; past a
# threshold the in-process CPU compiler segfaults (observed twice at
# different tests, always inside backend_compile_and_load). Bound the
# live-executable arena by clearing jit caches between test modules, and
# make the recompiles cheap with the persistent on-disk cache.
jax.config.update("jax_compilation_cache_dir",
                  "/tmp/fluidframework_tpu_jax_cache")
jax.config.update("jax_persistent_cache_min_compile_time_secs", 0.0)

import pytest  # noqa: E402


@pytest.fixture(autouse=True, scope="module")
def _bound_compile_arena():
    yield
    jax.clear_caches()
