"""Every byte-packing wire profile of the columnar apply, pinned by name.

``TensorStringStore.apply_planes`` picks a wire profile data-dependently
(``compact8`` / u16-lag / ``ref_wide`` for the head; u16 vs i32 positions;
broadcast vs rich payloads). A wrong branch silently corrupts merges, so
each branch is forced here at its boundary values and asserted against the
per-op message path (``apply_messages``) — byte-for-byte text and property
parity (VERDICT r3 weak #3 / next #4).

The ``cidx < 64`` guard of compact8 cannot be driven false: the kernel's
remover bitmask caps clients per doc at MAX_CLIENTS = 32
(``ops/merge_tree_kernel.py``), so client indexes end at 31. The test at
the cap proves the 6-bit field holds the whole reachable range.
"""

import numpy as np
import pytest

from fluidframework_tpu.core.protocol import MessageType, \
    SequencedDocumentMessage
from fluidframework_tpu.ops.merge_tree_kernel import MAX_CLIENTS
from fluidframework_tpu.ops.schema import OpKind
from fluidframework_tpu.ops.string_store import TensorStringStore

INS = int(OpKind.STR_INSERT)
REM = int(OpKind.STR_REMOVE)
ANN = int(OpKind.STR_ANNOTATE)
NOOP = int(OpKind.NOOP)


def _messages_from_planes(kind, a0, a1, seq_base, client, ref,
                          text="", texts=None, tidx=None, props=None):
    """The same batch as per-op sequenced messages (the reference path)."""
    R, O = kind.shape
    valid = kind != NOOP
    seq = seq_base[:, None] + np.cumsum(valid, axis=1, dtype=np.int64)
    out = []
    for r in range(R):
        for o in range(O):
            k = int(kind[r, o])
            if k == NOOP:
                continue
            if k == INS:
                t = text if texts is None else texts[int(tidx[r, o])]
                contents = {"mt": "insert", "kind": 0,
                            "pos": int(a0[r, o]), "text": t}
            elif k == ANN:
                contents = {"mt": "annotate", "start": int(a0[r, o]),
                            "end": int(a1[r, o]),
                            "props": props[int(tidx[r, o])]}
            else:
                contents = {"mt": "remove", "start": int(a0[r, o]),
                            "end": int(a1[r, o])}
            out.append((r, SequencedDocumentMessage(
                doc_id=f"d{r}", client_id=int(client[r, o]), client_seq=0,
                ref_seq=int(ref[r, o]), seq=int(seq[r, o]), min_seq=0,
                type=MessageType.OP, contents=contents)))
    return out


def _run_both(kind, a0, a1, seq_base, client, ref, expect_profile,
              text="", texts=None, tidx=None, props=None, n_docs=None,
              seed=None):
    """Columnar store vs message store on identical op streams; returns the
    columnar store (for follow-up batches). ``seed`` pre-seeds both docs
    with one broadcast insert so boundary batches have text to edit."""
    R, O = kind.shape
    n_docs = n_docs or R
    a = TensorStringStore(n_docs, capacity=1024)
    b = TensorStringStore(n_docs, capacity=1024)
    rows = np.arange(R, dtype=np.int32)
    if seed is not None:
        skind = np.full((R, 1), INS, np.int32)
        z = np.zeros((R, 1), np.int32)
        a.apply_planes(rows, skind, z, z, np.zeros(R, np.int32),
                       np.ones((R, 1), np.int32), z, text=seed)
        b.apply_messages(_messages_from_planes(
            skind, z, z, np.zeros(R, np.int64),
            np.ones((R, 1), np.int32), z, text=seed))
    a.apply_planes(rows, kind, np.asarray(a0, np.int32),
                   np.asarray(a1, np.int32), np.asarray(seq_base, np.int32),
                   client, np.asarray(ref, np.int32), text=text,
                   texts=texts, tidx=tidx, props=props)
    assert a.last_profile == expect_profile, a.last_profile
    b.apply_messages(_messages_from_planes(
        kind, np.asarray(a0, np.int64), np.asarray(a1, np.int64),
        np.asarray(seq_base, np.int64), client, np.asarray(ref, np.int64),
        text=text, texts=texts, tidx=tidx, props=props))
    for r in range(R):
        assert a.read_text(r) == b.read_text(r), (r, expect_profile)
        n = len(a.read_text(r))
        if props is not None and n:
            for pos in range(0, n, max(1, n // 7)):
                assert a.get_properties(r, pos) == b.get_properties(r, pos)
    return a


def _insert_batch(R, O, lag, text_len):
    kind = np.full((R, O), INS, np.int32)
    a0 = np.zeros((R, O), np.int32)  # prepend: position stays narrow
    a1 = np.zeros((R, O), np.int32)
    base = np.full((R,), max(lag + 5, 1), np.int32)
    seq = base[:, None] + np.cumsum(np.ones((R, O), np.int32), axis=1)
    ref = seq - lag
    client = np.ones((R, O), np.int32)
    return kind, a0, a1, base, client, ref, "x" * text_len


def test_compact8_basic():
    k, a0, a1, base, cl, ref, text = _insert_batch(4, 8, lag=1, text_len=4)
    _run_both(k, a0, a1, base, cl, ref,
              ("compact8", "pos16", "broadcast"), text=text)


def test_lag_boundary_255_takes_compact8():
    k, a0, a1, base, cl, ref, text = _insert_batch(2, 8, lag=255, text_len=4)
    _run_both(k, a0, a1, base, cl, ref,
              ("compact8", "pos16", "broadcast"), text=text)


def test_lag_boundary_256_flips_to_lag16():
    k, a0, a1, base, cl, ref, text = _insert_batch(2, 8, lag=256, text_len=4)
    _run_both(k, a0, a1, base, cl, ref,
              ("lag16", "pos16", "broadcast"), text=text)


def test_insert_span_boundary_255_vs_256():
    k, a0, a1, base, cl, ref, text = _insert_batch(2, 4, lag=1, text_len=255)
    _run_both(k, a0, a1, base, cl, ref,
              ("compact8", "pos16", "broadcast"), text=text)
    k, a0, a1, base, cl, ref, text = _insert_batch(2, 4, lag=1, text_len=256)
    _run_both(k, a0, a1, base, cl, ref,
              ("lag16", "pos16", "broadcast"), text=text)


def test_remove_span_boundary_255_vs_256():
    R, O = 2, 1
    cl = np.ones((R, O), np.int32)
    base = np.full((R,), 1, np.int32)
    ref = np.full((R, O), 1, np.int32)
    for span, prof in ((255, "compact8"), (256, "lag16")):
        kind = np.full((R, O), REM, np.int32)
        a0 = np.zeros((R, O), np.int32)
        a1 = np.full((R, O), span, np.int32)
        _run_both(kind, a0, a1, base, cl, ref,
                  (prof, "pos16", "broadcast"), seed="y" * 600)


def test_wide_positions_take_pos32():
    """An edit beyond position 32767 must ship i32 positions."""
    R, O = 2, 1
    kind = np.full((R, O), INS, np.int32)
    a0 = np.full((R, O), 39_000, np.int32)
    a1 = np.zeros((R, O), np.int32)
    base = np.ones((R,), np.int32)
    cl = np.ones((R, O), np.int32)
    ref = np.ones((R, O), np.int32)
    _run_both(kind, a0, a1, base, cl, ref,
              ("lag16", "pos32", "broadcast"), text="Z" * 4,
              seed="s" * 40_000)


def test_negative_position_forces_sign_preserving_path():
    """A (malformed) negative position must NOT alias through the unsigned
    u16 packing (~65535): the minima gate routes it to i32, where both
    paths see the identical value (ADVICE r3: string_store gate)."""
    R, O = 2, 2
    kind = np.full((R, O), REM, np.int32)
    a0 = np.array([[-5, 0], [-5, 0]], np.int32)
    a1 = np.array([[-1, 2], [-1, 2]], np.int32)
    base = np.ones((R,), np.int32)
    cl = np.ones((R, O), np.int32)
    ref = np.ones((R, O), np.int32)
    _run_both(kind, a0, a1, base, cl, ref,
              ("lag16", "pos32", "broadcast"), seed="neg" * 4)


def test_ref_wide_when_lag_exceeds_u16():
    """seq far past ref (lag > 65535) must ship full i32 refs."""
    R, O = 2, 4
    kind = np.full((R, O), INS, np.int32)
    a0 = np.zeros((R, O), np.int32)
    a1 = np.zeros((R, O), np.int32)
    base = np.full((R,), 70_000, np.int32)
    cl = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)  # lag ~70k
    _run_both(kind, a0, a1, base, cl, ref,
              ("ref_wide", "pos16", "broadcast"), text="abcd")


def test_rich_payloads_ride_compact8_head():
    """Distinct payloads + single-key annotates with byte-size spans/lags
    keep the 5 B/op head; the a2 plane widens to (N,) i32."""
    R, O = 2, 6
    texts = ["ab", "cdef", "g", "hijkl", "mn", "opq"]
    props = [{"bold": True}, {"color": "red"}]
    kind = np.array([[INS, INS, INS, ANN, INS, ANN]] * R, np.int32)
    a0 = np.array([[0, 0, 1, 0, 2, 1]] * R, np.int32)
    a1 = np.array([[0, 0, 0, 2, 0, 3]] * R, np.int32)
    tidx = np.array([[0, 1, 2, 0, 3, 1]] * R, np.int32)
    base = np.ones((R,), np.int32)
    cl = np.ones((R, O), np.int32)
    seq = base[:, None] + np.cumsum(np.ones((R, O), np.int32), axis=1)
    ref = seq - 1
    _run_both(kind, a0, a1, base, cl, ref, ("compact8", "pos16", "rich"),
              texts=texts, tidx=tidx, props=props)


def test_rich_wide_payload_stays_compact8():
    """A 300-char payload no longer widens the head: with the table wire
    the insert's span field ships 0 (the device reads the length from the
    table), so byte-size spans/lags keep the 5 B/op head."""
    R, O = 2, 2
    texts = ["q" * 300, "r" * 2]
    kind = np.full((R, O), INS, np.int32)
    a0 = np.zeros((R, O), np.int32)
    a1 = np.zeros((R, O), np.int32)
    tidx = np.array([[0, 1]] * R, np.int32)
    base = np.ones((R,), np.int32)
    cl = np.ones((R, O), np.int32)
    ref = np.ones((R, O), np.int32)
    a = _run_both(kind, a0, a1, base, cl, ref,
                  ("compact8", "pos16", "rich"), texts=texts, tidx=tidx)
    assert a.last_rich_wire == "tab8"


def test_rich_wide_remove_span_takes_lag16():
    """A remove spanning > 255 chars on a rich batch still widens the
    head (the span field genuinely carries it)."""
    R, O = 2, 2
    texts = ["q" * 300, "r" * 2]
    kind = np.array([[INS, REM]] * R, np.int32)
    a0 = np.zeros((R, O), np.int32)
    a1 = np.array([[0, 280]] * R, np.int32)
    tidx = np.array([[0, 0]] * R, np.int32)
    base = np.ones((R,), np.int32)
    cl = np.ones((R, O), np.int32)
    ref = np.ones((R, O), np.int32)
    a = _run_both(kind, a0, a1, base, cl, ref, ("lag16", "pos16", "rich"),
                  texts=texts, tidx=tidx)
    assert a.last_rich_wire == "tab8"


def test_noop_slots_remap_through_compact8():
    """NOOP (kind 12) rides compact8's 2-bit field as code 3 and must come
    back out as NOOP — and consume no sequence number on either path."""
    R, O = 2, 6
    kind = np.array([[INS, NOOP, INS, NOOP, NOOP, INS]] * R, np.int32)
    a0 = np.zeros((R, O), np.int32)
    a1 = np.zeros((R, O), np.int32)
    base = np.ones((R,), np.int32)
    cl = np.ones((R, O), np.int32)
    valid = kind != NOOP
    seq = base[:, None] + np.cumsum(valid, axis=1, dtype=np.int32)
    ref = np.maximum(seq - 1, 1)
    a = _run_both(kind, a0, a1, base, cl, ref,
                  ("compact8", "pos16", "broadcast"), text="ab")
    assert a.read_text(0) == "ab" * 3  # exactly the three real inserts


def test_client_index_cap_fits_compact8_field():
    """All MAX_CLIENTS client indexes (0..31) pack into the 6-bit cidx
    field; the 64 boundary is unreachable by construction."""
    R, O = 1, MAX_CLIENTS
    kind = np.full((R, O), INS, np.int32)
    a0 = np.zeros((R, O), np.int32)
    a1 = np.zeros((R, O), np.int32)
    base = np.ones((R,), np.int32)
    client = np.arange(100, 100 + O, dtype=np.int32).reshape(R, O)
    seq = base[:, None] + np.cumsum(np.ones((R, O), np.int32), axis=1)
    ref = seq - 1
    _run_both(kind, a0, a1, base, client, ref,
              ("compact8", "pos16", "broadcast"), text="k")


def test_profile_sweep_cross_parity():
    """One corpus pushed through EVERY head×pos×payload combination (by
    varying only the profile-steering fields) must converge to the same
    digesting state as the message path each time."""
    rng = np.random.default_rng(42)
    R, O = 4, 12
    for head_lag, expect_head in ((1, "compact8"), (300, "lag16"),
                                  (70_000, "ref_wide")):
        kind = rng.choice([INS, REM], size=(R, O), p=[0.8, 0.2]) \
            .astype(np.int32)
        kind[:, 0] = INS
        a0 = np.zeros((R, O), np.int32)
        a1 = np.zeros((R, O), np.int32)
        vis = np.zeros(R, np.int64)
        for r in range(R):
            for o in range(O):
                if kind[r, o] == INS:
                    a0[r, o] = rng.integers(0, vis[r] + 1)
                    vis[r] += 3
                elif vis[r] >= 2:
                    a0[r, o] = rng.integers(0, vis[r] - 1)
                    a1[r, o] = a0[r, o] + 2
                    vis[r] -= 2
                else:
                    kind[r, o] = NOOP
        valid = kind != NOOP
        base = np.full((R,), max(head_lag + 2, 1), np.int32)
        seq = base[:, None] + np.cumsum(valid, axis=1, dtype=np.int32)
        ref = np.maximum(seq - head_lag, 0)
        cl = np.ones((R, O), np.int32)
        _run_both(kind, a0, a1, base, cl, ref,
                  (expect_head, "pos16", "broadcast"), text="xyz")
