"""Integration tests for the in-process ordering service (LocalService):
the production lambda topology — ingress log → Deli → deltas log →
broadcaster/scriptorium/scribe — with real multi-replica collaboration,
nacks, checkpoint/restart, and summary flow."""

import pytest

from fluidframework_tpu.core.protocol import MessageType
from fluidframework_tpu.models.merge_tree_client import SequenceClient
from fluidframework_tpu.server import LocalService, NackReason
from fluidframework_tpu.server.oplog import partition_of


class StringReplica:
    """Minimal client binding: SequenceClient wired to a DeltaConnection
    (the DeltaManager role, before the full loader exists)."""

    def __init__(self, service, doc_id):
        self.conn = service.connect(doc_id)
        self.client = SequenceClient(self.conn.client_id)
        self.conn.on_op(self._on_op)

    def _on_op(self, msg):
        if msg.type == MessageType.OP:
            self.client.apply_msg(msg)
        else:
            self.client.last_processed_seq = msg.seq
            if msg.min_seq > self.client.tree.min_seq:
                self.client.tree.zamboni(msg.min_seq)

    def insert(self, pos, text):
        op = self.client.insert_text_local(pos, text)
        self.conn.submit(op, ref_seq=self.client.last_processed_seq)

    def remove(self, start, end):
        op = self.client.remove_range_local(start, end)
        self.conn.submit(op, ref_seq=self.client.last_processed_seq)

    @property
    def text(self):
        return self.client.get_text()


def test_two_clients_collaborate_through_service():
    svc = LocalService()
    a = StringReplica(svc, "doc1")
    b = StringReplica(svc, "doc1")
    a.insert(0, "hello")
    b.insert(0, "world ")   # concurrent with a's op already sequenced
    a.insert(5, "!")
    assert a.text == b.text
    assert "hello" in a.text and "world" in a.text


def test_documents_are_isolated():
    svc = LocalService()
    a = StringReplica(svc, "docA")
    b = StringReplica(svc, "docB")
    a.insert(0, "aaa")
    b.insert(0, "bbb")
    assert a.text == "aaa" and b.text == "bbb"


def test_unknown_client_nacked():
    svc = LocalService()
    conn = svc.connect("doc")
    conn2 = svc.connect("doc")
    conn2.disconnect()
    # hand-inject an op from the departed client
    svc._ingest("doc", conn2.client_id, 1, 0, MessageType.OP, {"x": 1}, None)
    assert svc.nacks and svc.nacks[-1].reason == NackReason.UNKNOWN_CLIENT


def test_duplicate_and_gap_nacks():
    svc = LocalService()
    conn = svc.connect("doc")
    svc._ingest("doc", conn.client_id, 1, 0, MessageType.OP, {"n": 1}, None)
    svc._ingest("doc", conn.client_id, 1, 0, MessageType.OP, {"n": 1}, None)
    # a duplicate of an already-DURABLE op is idempotently dup-acked
    # with the original seq (ISSUE 9 durable dedup), not nacked
    assert not svc.nacks
    assert conn.dup_acks and conn.dup_acks[-1].client_seq == 1
    assert conn.dup_acks[-1].seq > 0
    svc._ingest("doc", conn.client_id, 5, 0, MessageType.OP, {"n": 5}, None)
    assert svc.nacks[-1].reason == NackReason.CLIENT_SEQ_GAP
    # the doc saw exactly one OP: the duplicate never re-applied
    assert len([m for m in svc.get_deltas("doc", 0)
                if m.type == MessageType.OP]) == 1


def test_catchup_via_scriptorium():
    svc = LocalService()
    a = StringReplica(svc, "doc")
    a.insert(0, "abc")
    a.insert(3, "def")
    late = StringReplica(svc, "doc")
    # replay the tail through the same apply path as live ops (SURVEY §3.1)
    for msg in svc.get_deltas("doc"):
        if msg.type == MessageType.OP and msg.seq > late.client.last_processed_seq:
            late.client.apply_msg(msg)
    assert late.text == a.text == "abcdef"


def test_summary_upload_and_ack():
    svc = LocalService()
    a = StringReplica(svc, "doc")
    a.insert(0, "summarize me")
    summary = a.client.tree.summarize()
    seq = a.client.last_processed_seq
    sha = svc.upload_summary("doc", summary, seq)
    acks = []
    a.conn.on_op(lambda m: acks.append(m) if m.type in
                 (MessageType.SUMMARY_ACK, MessageType.SUMMARY_NACK) else None)
    a.conn.submit({"handle": sha}, type=MessageType.SUMMARIZE, ref_seq=seq)
    assert acks and acks[0].type == MessageType.SUMMARY_ACK
    loaded, got_seq, got_sha = svc.latest_summary("doc")
    assert got_sha == sha and got_seq == seq
    from fluidframework_tpu.models.merge_tree import MergeTree
    assert MergeTree.load(loaded, 99).get_text() == "summarize me"
    # bad handle -> nack
    a.conn.submit({"handle": "deadbeef"}, type=MessageType.SUMMARIZE, ref_seq=seq)
    assert acks[-1].type == MessageType.SUMMARY_NACK


def test_sequencer_checkpoint_restart_resumes_seq():
    svc = LocalService()
    a = StringReplica(svc, "doc")
    a.insert(0, "x")
    ckpt = svc.checkpoint()
    seq_before = svc.deli.doc_seq("doc")
    svc.restart_sequencer(ckpt)
    assert svc.deli.doc_seq("doc") == seq_before
    a.insert(1, "y")  # sequencing continues seamlessly after restart
    assert a.text == "xy"


def test_msn_advances_and_zamboni_runs_via_service():
    svc = LocalService()
    a = StringReplica(svc, "doc")
    b = StringReplica(svc, "doc")
    a.insert(0, "abcdef")
    a.remove(1, 3)
    # both clients heartbeat their refSeq so MSN catches up
    a.conn.submit({}, type=MessageType.NOOP, ref_seq=a.client.last_processed_seq)
    b.conn.submit({}, type=MessageType.NOOP, ref_seq=b.client.last_processed_seq)
    a.conn.submit({}, type=MessageType.NOOP, ref_seq=a.client.last_processed_seq)
    assert a.text == b.text == "adef"
    assert all(s.removed_seq is None for s in a.client.tree.segments)


def test_partitioning_is_stable():
    assert partition_of("doc-42", 8) == partition_of("doc-42", 8)
    spread = {partition_of(f"doc-{i}", 8) for i in range(100)}
    assert len(spread) > 4  # docs actually spread across partitions
