"""Incremental summaries for the map/matrix/tree engines (VERDICT r4
missing #2: the dirty-row machinery was one engine wide): idle-store
deltas are O(changed) bytes, delta chains restore exactly, and
engine-specific invalidations (tree overflow recovery, matrix cell-pool
skip) hold."""

import pickle

import numpy as np
import pytest

from fluidframework_tpu.server import native_deli
from fluidframework_tpu.server.serving import (
    MapServingEngine, MatrixServingEngine, TreeServingEngine,
)

pytestmark = pytest.mark.skipif(not native_deli.available(),
                                reason="native sequencer unavailable")


def _delta_bytes(summary: dict) -> int:
    slim = {k: v for k, v in summary.items() if k != "base"}
    return len(pickle.dumps(slim))


# ----------------------------------------------------------------- map

def _mk_map(n_docs=512):
    eng = MapServingEngine(n_docs=n_docs, n_keys=16,
                           batch_window=10 ** 9, sequencer="native")
    docs = [f"m-{i}" for i in range(n_docs)]
    for d in docs:
        eng.connect(d, 1)
    return eng, docs


def _map_set(eng, docs, cseq, subset=None, val="v"):
    for d in (docs if subset is None else subset):
        _, nack = eng.submit(d, 1, cseq, 0,
                             {"op": "set", "key": "k", "value": val})
        assert nack is None
    eng.flush()


def test_map_idle_delta_small_and_chain_restores():
    eng, docs = _mk_map()
    _map_set(eng, docs, 1)
    full = eng.summarize()
    full_bytes = _delta_bytes(full)
    _map_set(eng, docs, 2, subset=docs[:4], val="w")
    delta = eng.summarize(incremental=True)
    assert delta["kind"] == "delta"
    assert len(delta["store_delta"]["rows"]) == 4
    # whole delta beats the full summary (the residual floor is the
    # sequencer checkpoint + doc map); the STORE payload is O(changed)
    assert _delta_bytes(delta) < full_bytes / 5
    assert len(pickle.dumps(delta["store_delta"])) < \
        len(pickle.dumps(full["store"])) / 50
    # more edits AFTER the summary land via tail replay
    _map_set(eng, docs, 2, subset=docs[4:8], val="x")
    revived = MapServingEngine.load(delta, eng.log)
    for d in docs[:4]:
        assert revived.get(d, "k") == "w", d
    for d in docs[4:8]:
        assert revived.get(d, "k") == "x", d
    for d in docs[8:16]:
        assert revived.get(d, "k") == "v", d
    # value-interner delta covered the new values
    assert revived.store._interner.export() == \
        eng.store._interner.export()


def test_map_second_level_delta_chain():
    eng, docs = _mk_map(64)
    _map_set(eng, docs, 1)
    eng.summarize()
    _map_set(eng, docs, 2, subset=docs[:3], val="a")
    eng.summarize(incremental=True)
    _map_set(eng, docs, 2, subset=docs[3:6], val="b")
    d2 = eng.summarize(incremental=True)
    assert d2["kind"] == "delta" and d2["base"]["kind"] == "delta"
    revived = MapServingEngine.load(d2, eng.log)
    want = {d: eng.read_doc(d) for d in docs}
    assert {d: revived.read_doc(d) for d in docs} == want


# ----------------------------------------------------------------- tree

def _mk_tree(n_docs=256):
    eng = TreeServingEngine(n_docs=n_docs, capacity=64,
                            batch_window=10 ** 9, sequencer="native")
    docs = [f"t-{i}" for i in range(n_docs)]
    for d in docs:
        eng.connect(d, 1)
    return eng, docs


def _tree_insert(eng, docs, cseq, tag, subset=None):
    ds = docs if subset is None else subset
    res = eng.ingest_batch(
        ds, [1] * len(ds), [cseq] * len(ds), [0] * len(ds),
        [{"op": "insert", "parent": "root", "field": "kids",
          "after": None, "nodes": [{"id": f"{d}-{tag}"}]} for d in ds])
    assert res["nacked"] == 0


def test_tree_idle_delta_small_and_chain_restores():
    eng, docs = _mk_tree()
    _tree_insert(eng, docs, 1, "a")
    full = eng.summarize()
    full_bytes = _delta_bytes(full)
    _tree_insert(eng, docs, 2, "b", subset=docs[:3])
    delta = eng.summarize(incremental=True)
    assert delta["kind"] == "delta"
    assert len(delta["store_delta"]["rows"]) == 3
    assert _delta_bytes(delta) < full_bytes / 10
    _tree_insert(eng, docs, 2, "c", subset=docs[3:6])  # tail
    revived = TreeServingEngine.load(delta, eng.log)
    for d in docs[:6]:
        assert revived.to_dict(d) == eng.to_dict(d), d
    assert revived.has_node(docs[0], f"{docs[0]}-b")
    assert revived.has_node(docs[4], f"{docs[4]}-c")


def test_tree_recovery_reupload_dirties_row():
    """Overflow recovery rewrites a row outside the op stream; the next
    delta must carry it (the string engine's invariant, now shared)."""
    eng, docs = _mk_tree(8)
    _tree_insert(eng, docs, 1, "x")
    eng.summarize()
    d0 = docs[0]
    # overflow d0 (capacity 64), then recover (re-upload at same row)
    for i in range(70):
        _, nack = eng.submit(d0, 1, 2 + i, 0,
                             {"op": "insert", "parent": "root",
                              "field": "kids",
                              "after": None,
                              "nodes": [{"id": f"{d0}-ov{i}"}]})
        assert nack is None
    eng.flush()
    assert eng.overflowed_docs() == [d0]
    report = eng.recover_overflowed()
    assert d0 in report
    delta = eng.summarize(incremental=True)
    revived = TreeServingEngine.load(delta, eng.log)
    assert revived.to_dict(d0) == eng.to_dict(d0)
    assert revived.node_count(d0) == eng.node_count(d0)


def test_tree_numeric_id_watermark_survives_delta_chain():
    eng, docs = _mk_tree(8)
    base = eng.allocate_node_ids(100)
    res = eng.ingest_batch(
        [docs[0]], [1], [1], [0],
        [{"op": "insert", "parent": "root", "field": "kids",
          "after": None, "nodes": [{"id": f"#{base}"}]}])
    assert res["nacked"] == 0
    eng.summarize()
    res = eng.ingest_batch(
        [docs[1]], [1], [1], [0],
        [{"op": "insert", "parent": "root", "field": "kids",
          "after": None, "nodes": [{"id": f"#{base + 1}"}]}])
    delta = eng.summarize(incremental=True)
    revived = TreeServingEngine.load(delta, eng.log)
    assert revived.store._ids._next_anon == eng.store._ids._next_anon
    assert revived.has_node(docs[1], f"#{base + 1}")


# --------------------------------------------------------------- matrix

def _mk_matrix(n_docs=64):
    eng = MatrixServingEngine(n_docs=n_docs, cell_capacity=4096,
                              batch_window=10 ** 9, sequencer="native")
    docs = [f"x-{i}" for i in range(n_docs)]
    for d in docs:
        eng.connect(d, 1)
    return eng, docs


def _mx_seed(eng, docs, subset=None, base_cseq=1):
    ds = docs if subset is None else subset
    for d in ds:
        for i, op in enumerate((
                {"mx": "insRow", "pos": 0, "count": 2, "opKey": [1, 0]},
                {"mx": "insCol", "pos": 0, "count": 2, "opKey": [2, 0]},
                {"mx": "setCell", "row": 0, "col": 0, "value": f"{d}"})):
            _, nack = eng.submit(d, 1, base_cseq + i, 0, op)
            assert nack is None
    eng.flush()


def test_matrix_idle_delta_small_and_restores():
    eng, docs = _mk_matrix()
    _mx_seed(eng, docs)
    full = eng.summarize()
    full_bytes = _delta_bytes(full)
    # idle: NO dirty docs → the cell pool rides by reference
    idle = eng.summarize(incremental=True)
    assert idle["kind"] == "delta" and idle["cells_delta"] is None
    assert len(idle["axis_delta"]["rows"]) == 0
    assert _delta_bytes(idle) < full_bytes / 10
    revived = MapAlike = MatrixServingEngine.load(idle, eng.log)
    for d in docs[:4]:
        assert revived.to_lists(d) == eng.to_lists(d), d
    # touch 2 docs → their axis rows + the live-trimmed pool ship
    for d in docs[:2]:
        _, nack = eng.submit(d, 1, 4, 0, {"mx": "setCell", "row": 1,
                                          "col": 1, "value": "new"})
        assert nack is None
    eng.flush()
    delta = eng.summarize(incremental=True)
    assert delta["kind"] == "delta"
    assert delta["cells_delta"] is not None
    assert len(delta["axis_delta"]["rows"]) == 4   # 2 docs × 2 axes
    revived = MatrixServingEngine.load(delta, eng.log)
    for d in docs[:4]:
        assert revived.to_lists(d) == eng.to_lists(d), d
    assert revived.get_cell(docs[0], 1, 1) == "new"


def test_matrix_fww_metadata_rides_delta():
    eng, docs = _mk_matrix(8)
    _mx_seed(eng, docs)
    eng.summarize()
    d = docs[0]
    _, nack = eng.submit(d, 1, 4, 0, {"mx": "policy"})
    assert nack is None
    _, nack = eng.submit(d, 1, 5, 0, {"mx": "setCell", "row": 0,
                                      "col": 1, "value": "first"})
    assert nack is None
    eng.flush()
    delta = eng.summarize(incremental=True)
    revived = MatrixServingEngine.load(delta, eng.log)
    row = revived.doc_row(d)
    assert revived._fww.get(row) is True
    assert revived.get_cell(d, 0, 1) == eng.get_cell(d, 0, 1)
