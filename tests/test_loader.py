"""Loader layer (L2) + drivers (L1): DeltaQueue, Quorum/ProtocolHandler,
DeltaManager state machine, Container load/catch-up/connect, replay and file
drivers. Reference behaviors per SURVEY.md §2.10/2.12, §3.1–3.3."""

import pytest

from fluidframework_tpu.core.protocol import (
    MessageType, SequencedDocumentMessage,
)
from fluidframework_tpu.drivers import (
    FileDocumentService, LocalDocumentServiceFactory, ReadonlyConnectionError,
    ReplayDocumentService, write_document,
)
from fluidframework_tpu.loader import (
    ConnectionState, Container, DeltaQueue, Loader, ProtocolHandler, Quorum,
)
from fluidframework_tpu.server.tinylicious import LocalService


def msg(seq, type=MessageType.OP, client_id=1, contents=None, min_seq=0,
        doc_id="d"):
    return SequencedDocumentMessage(
        doc_id=doc_id, client_id=client_id, client_seq=seq, ref_seq=0,
        seq=seq, min_seq=min_seq, type=type, contents=contents)


# --------------------------------------------------------------- DeltaQueue

class TestDeltaQueue:
    def test_in_order_delivery(self):
        got = []
        q = DeltaQueue(got.append, lambda m: m.seq)
        for s in (1, 2, 3):
            q.push(msg(s))
        assert [m.seq for m in got] == [1, 2, 3]

    def test_buffers_gap_until_filled(self):
        got = []
        q = DeltaQueue(got.append, lambda m: m.seq)
        q.push(msg(2))
        q.push(msg(3))
        assert got == [] and q.has_gap() == 1
        q.push(msg(1))
        assert [m.seq for m in got] == [1, 2, 3] and q.has_gap() is None

    def test_drops_duplicates(self):
        got = []
        q = DeltaQueue(got.append, lambda m: m.seq)
        q.push(msg(1))
        q.push(msg(1))
        q.push(msg(2))
        q.push(msg(2))
        assert [m.seq for m in got] == [1, 2]
        assert q.dropped_duplicates == 2

    def test_pause_resume(self):
        got = []
        q = DeltaQueue(got.append, lambda m: m.seq)
        q.pause()
        q.push(msg(1))
        q.push(msg(2))
        assert got == [] and q.pending == 2
        q.resume()
        assert [m.seq for m in got] == [1, 2]

    def test_initial_seq_skips_already_summarized(self):
        got = []
        q = DeltaQueue(got.append, lambda m: m.seq, initial_seq=10)
        q.push(msg(9))
        q.push(msg(10))
        q.push(msg(11))
        assert [m.seq for m in got] == [11]

    def test_reentrant_push_from_handler(self):
        got = []
        q = None

        def handler(m):
            got.append(m.seq)
            if m.seq == 1:
                q.push(msg(2))
        q = DeltaQueue(handler, lambda m: m.seq)
        q.push(msg(1))
        assert got == [1, 2]


# ----------------------------------------------------- Quorum / ProtocolHandler

class TestProtocol:
    def test_join_leave_membership(self):
        p = ProtocolHandler()
        p.process(msg(1, MessageType.CLIENT_JOIN, contents={"clientId": 7}))
        assert 7 in p.quorum.members
        p.process(msg(2, MessageType.CLIENT_LEAVE, contents={"clientId": 7}))
        assert 7 not in p.quorum.members

    def test_seq_gap_asserts(self):
        p = ProtocolHandler()
        p.process(msg(1))
        with pytest.raises(AssertionError):
            p.process(msg(3))

    def test_proposal_accepted_when_msn_passes(self):
        p = ProtocolHandler()
        p.process(msg(1, MessageType.PROPOSAL,
                      contents={"key": "code", "value": "v2"}))
        assert not p.quorum.has("code")
        # MSN passes the proposal's seq → accepted
        p.process(msg(2, min_seq=1))
        assert p.quorum.get("code") == "v2"
        assert p.quorum.pending == []

    def test_snapshot_load_roundtrip(self):
        p = ProtocolHandler()
        p.process(msg(1, MessageType.CLIENT_JOIN, contents={"clientId": 3}))
        p.process(msg(2, MessageType.PROPOSAL,
                      contents={"key": "k", "value": 1}))
        p.process(msg(3, min_seq=2))
        p2 = ProtocolHandler.load(p.snapshot())
        assert p2.seq == 3 and p2.min_seq == 2
        assert 3 in p2.quorum.members and p2.quorum.get("k") == 1


# --------------------------------------------- a minimal runtime for the tests

class RecordingRuntime:
    """Runtime stub: records processed ops, echoes connection state."""

    def __init__(self, container, summary):
        self.container = container
        self.ops = []
        self.loaded_from = summary
        self.connected = False
        self.client_id = None

    def process(self, msg, local):
        self.ops.append((msg.seq, msg.contents, local))

    def set_connection_state(self, connected, client_id):
        self.connected = connected
        self.client_id = client_id


def make_runtime(container, summary):
    return RecordingRuntime(container, summary)


# ------------------------------------------------------ Container end-to-end

class TestContainerLocalService:
    def test_two_containers_converge(self):
        loader = Loader(LocalDocumentServiceFactory(), make_runtime)
        a = loader.resolve("doc")
        b = loader.resolve("doc")
        assert a.connected and b.connected
        a.submit({"x": 1})
        b.submit({"y": 2})
        ops_a = [c for _, c, _ in a.runtime.ops]
        ops_b = [c for _, c, _ in b.runtime.ops]
        assert ops_a == ops_b == [{"x": 1}, {"y": 2}]
        # the echo of your own op is local=True, the other's is local=False
        assert a.runtime.ops[0][2] is True and a.runtime.ops[1][2] is False
        assert b.runtime.ops[0][2] is False and b.runtime.ops[1][2] is True

    def test_quorum_tracks_joins(self):
        loader = Loader(LocalDocumentServiceFactory(), make_runtime)
        a = loader.resolve("doc")
        b = loader.resolve("doc")
        # a saw both joins; b joined later but caught up on a's join
        assert set(a.quorum.members) == {a.client_id, b.client_id}
        assert set(b.quorum.members) == {a.client_id, b.client_id}
        b.close()
        assert set(a.quorum.members) == {a.client_id}

    def test_late_joiner_catches_up(self):
        factory = LocalDocumentServiceFactory()
        loader = Loader(factory, make_runtime)
        a = loader.resolve("doc")
        for i in range(5):
            a.submit({"i": i})
        b = loader.resolve("doc")
        assert [c for _, c, _ in b.runtime.ops] == [{"i": i} for i in range(5)]
        assert b.delta_manager.last_sequence_number == \
            a.delta_manager.last_sequence_number

    def test_disconnect_reconnect_new_client_id(self):
        loader = Loader(LocalDocumentServiceFactory(), make_runtime)
        a = loader.resolve("doc")
        first = a.client_id
        a.disconnect("test")
        assert not a.connected and a.runtime.connected is False
        a.connect()
        assert a.connected and a.client_id != first
        assert a.runtime.connected and a.runtime.client_id == a.client_id

    def test_ops_while_disconnected_arrive_on_reconnect(self):
        loader = Loader(LocalDocumentServiceFactory(), make_runtime)
        a = loader.resolve("doc")
        b = loader.resolve("doc")
        a.disconnect("offline")
        b.submit({"while": "away"})
        assert {"while": "away"} not in [c for _, c, _ in a.runtime.ops]
        a.connect()
        assert {"while": "away"} in [c for _, c, _ in a.runtime.ops]

    def test_proposal_via_containers(self):
        loader = Loader(LocalDocumentServiceFactory(), make_runtime)
        a = loader.resolve("doc")
        b = loader.resolve("doc")
        a.propose("code", "pkg-v3")
        # acceptance needs the MSN to pass the proposal seq: both clients
        # must reference a later seq — noops advance their refSeq
        a.delta_manager.submit_noop()
        b.delta_manager.submit_noop()
        a.submit({"tick": 1})
        a.delta_manager.submit_noop()
        b.delta_manager.submit_noop()
        a.submit({"tick": 2})
        assert a.quorum.get("code") == "pkg-v3"
        assert b.quorum.get("code") == "pkg-v3"

    def test_offline_load_sees_stored_ops(self):
        factory = LocalDocumentServiceFactory()
        loader = Loader(factory, make_runtime)
        a = loader.resolve("doc")
        a.submit({"n": 1})
        c = loader.resolve("doc", connect=False)
        assert not c.connected
        assert {"n": 1} in [x for _, x, _ in c.runtime.ops]


# ------------------------------------------------------------ replay driver

class TestReplayDriver:
    def _ops(self, n=5):
        return [msg(s, contents={"s": s}) for s in range(1, n + 1)]

    def test_replay_catchup_only(self):
        svc = ReplayDocumentService("doc", self._ops())
        c = Container.load(svc, make_runtime)
        assert [s for s, _, _ in c.runtime.ops] == [1, 2, 3, 4, 5]

    def test_to_seq_caps_history(self):
        svc = ReplayDocumentService("doc", self._ops(), to_seq=3)
        c = Container.load(svc, make_runtime)
        assert [s for s, _, _ in c.runtime.ops] == [1, 2, 3]

    def test_submit_raises(self):
        svc = ReplayDocumentService("doc", self._ops())
        c = Container.load(svc, make_runtime)
        with pytest.raises(ReadonlyConnectionError):
            c.submit({"no": 1})


# -------------------------------------------------------------- file driver

class TestFileDriver:
    def test_roundtrip(self, tmp_path):
        ops = [msg(s, contents={"s": s}) for s in range(1, 4)]
        d = str(tmp_path / "doc")
        write_document(d, ops, summaries=[({"protocol": None, "blob": 1}, 0)])
        svc = FileDocumentService(d)
        c = Container.load(svc, make_runtime)
        assert [s for s, _, _ in c.runtime.ops] == [1, 2, 3]

    def test_loads_latest_summary_at_or_below_to_seq(self, tmp_path):
        d = str(tmp_path / "doc")
        ops = [msg(s, contents={"s": s}) for s in range(1, 6)]
        write_document(d, ops, summaries=[
            ({"runtime": {"at": 0}, "protocol": None}, 0),
        ])
        svc = FileDocumentService(d, to_seq=4)
        c = Container.load(svc, make_runtime)
        assert [s for s, _, _ in c.runtime.ops] == [1, 2, 3, 4]


# -------------------------------------------------- live local-service nacks

class TestNackReconnect:
    def test_nack_triggers_reconnect(self):
        service = LocalService()
        factory = LocalDocumentServiceFactory(service)
        loader = Loader(factory, make_runtime)
        a = loader.resolve("doc")
        first_client = a.client_id
        nacks = []
        a.delta_manager.on("nack", nacks.append)
        # forge a client-seq gap by bumping the raw connection's counter
        a.delta_manager.connection._conn._client_seq += 5
        a.submit({"gap": True})
        assert nacks, "nack should surface"
        assert a.connected and a.client_id != first_client
