"""Merge-tree oracle semantics: the anchor behaviors every kernel must match.

These pin the Fluid merge rules (reference: @fluidframework/merge-tree, mount
empty — SURVEY.md §2.1): perspective-based position resolution, concurrent
insert tie-break, remove-vs-insert interleavings, overlapping removes, annotate
LWW, zamboni, local references, and summary roundtrip.
"""

import pytest

from fluidframework_tpu.core.protocol import MessageType
from fluidframework_tpu.models.merge_tree import SegmentKind, SlidePolicy
from fluidframework_tpu.models.merge_tree_client import SequenceClient
from fluidframework_tpu.testing.mocks import MockSequencer
from fluidframework_tpu.testing.fuzz import assert_converged


def make_collab(n):
    seqr = MockSequencer()
    clients = [SequenceClient(seqr.allocate_client_id()) for _ in range(n)]
    for c in clients:
        seqr.connect(c)
    return seqr, clients


def submit(seqr, client, op):
    seqr.submit(client, op)


def test_local_insert_at_same_position_stacks_leftward():
    _, (a,) = make_collab(1)
    a.insert_text_local(0, "a")
    a.insert_text_local(0, "b")
    assert a.get_text() == "ba"


def test_sequential_typing():
    seqr, (a, b) = make_collab(2)
    for i, ch in enumerate("hello"):
        submit(seqr, a, a.insert_text_local(i, ch))
    seqr.process_all_messages()
    assert a.get_text() == b.get_text() == "hello"


def test_concurrent_insert_same_position_later_seq_wins_left():
    seqr, (a, b) = make_collab(2)
    submit(seqr, a, a.insert_text_local(0, "a"))   # will be seq 1
    submit(seqr, b, b.insert_text_local(0, "x"))   # will be seq 2, refSeq 0
    seqr.process_all_messages()
    assert a.get_text() == b.get_text() == "xa"


def test_concurrent_typing_runs_stay_contiguous():
    seqr, (a, b) = make_collab(2)
    for i, ch in enumerate("abc"):
        submit(seqr, a, a.insert_text_local(i, ch))
    for i, ch in enumerate("xyz"):
        submit(seqr, b, b.insert_text_local(i, ch))
    seqr.process_all_messages()
    # B's ops sequenced after A's at the same origin position -> B lands left,
    # and each client's run is contiguous (never interleaved).
    assert a.get_text() == b.get_text() == "xyzabc"


def test_insert_into_concurrently_removed_range_survives():
    seqr, (a, b) = make_collab(2)
    submit(seqr, a, a.insert_text_local(0, "abcd"))
    seqr.process_all_messages()
    # concurrent: B removes [1,3) while A inserts "XX" at 2
    submit(seqr, b, b.remove_range_local(1, 3))
    submit(seqr, a, a.insert_text_local(2, "XX"))
    seqr.process_all_messages()
    assert a.get_text() == b.get_text() == "aXXd"


def test_remove_does_not_cover_concurrent_insert():
    seqr, (a, b) = make_collab(2)
    submit(seqr, a, a.insert_text_local(0, "abcd"))
    seqr.process_all_messages()
    # A inserts inside [1,3) first in sequence order; B's remove was issued
    # without seeing it -> the inserted text survives.
    submit(seqr, a, a.insert_text_local(2, "ZZ"))
    submit(seqr, b, b.remove_range_local(1, 3))
    seqr.process_all_messages()
    assert a.get_text() == b.get_text() == "aZZd"


def test_overlapping_concurrent_removes():
    seqr, (a, b, c) = make_collab(3)
    submit(seqr, a, a.insert_text_local(0, "abcdef"))
    seqr.process_all_messages()
    submit(seqr, a, a.remove_range_local(0, 4))
    submit(seqr, b, b.remove_range_local(2, 6))
    seqr.process_all_messages()
    assert a.get_text() == b.get_text() == c.get_text() == ""
    # earliest acked removal seq is kept; both removers recorded
    tomb = [s for s in c.tree.segments if s.removed_seq is not None]
    overlap = [s for s in tomb if len(s.removers) == 2]
    assert overlap and all(s.removed_seq == 2 for s in overlap)


def test_annotate_last_sequenced_writer_wins():
    seqr, (a, b, c) = make_collab(3)
    submit(seqr, a, a.insert_text_local(0, "mm"))
    seqr.process_all_messages()
    submit(seqr, a, a.annotate_range_local(0, 2, {"bold": 1}))
    submit(seqr, b, b.annotate_range_local(0, 2, {"bold": 2}))
    seqr.process_all_messages()
    for cl in (a, b, c):
        seg, _ = cl.tree.get_containing_segment(0)
        assert seg.props == {"bold": 2}


def test_pending_local_annotate_beats_earlier_remote_after_ack():
    seqr, (a, b, c) = make_collab(3)
    submit(seqr, a, a.insert_text_local(0, "mm"))
    seqr.process_all_messages()
    submit(seqr, b, b.annotate_range_local(0, 2, {"k": "B"}))  # seq 2
    submit(seqr, a, a.annotate_range_local(0, 2, {"k": "A"}))  # seq 3
    seqr.process_all_messages()
    for cl in (a, b, c):
        seg, _ = cl.tree.get_containing_segment(0)
        assert seg.props == {"k": "A"}
    assert_converged([a, b, c])


def test_marker_insert_and_convergence():
    seqr, (a, b) = make_collab(2)
    submit(seqr, a, a.insert_text_local(0, "ab"))
    seqr.process_all_messages()
    submit(seqr, a, a.insert_marker_local(1, {"tag": "pg"}))
    seqr.process_all_messages()
    assert a.get_text() == b.get_text() == "ab"  # markers are out-of-band
    assert a.get_length() == b.get_length() == 3
    assert_converged([a, b])


def test_zamboni_frees_tombstones_and_coalesces():
    seqr, (a, b) = make_collab(2)
    submit(seqr, a, a.insert_text_local(0, "abcdef"))
    seqr.process_all_messages()
    submit(seqr, a, a.remove_range_local(1, 3))
    seqr.process_all_messages()
    # advance everyone's refSeq so MSN catches up, then heartbeat
    seqr.submit(a, {}, type=MessageType.NOOP)
    seqr.submit(b, {}, type=MessageType.NOOP)
    seqr.process_all_messages()
    for cl in (a, b):
        assert cl.get_text() == "adef"
        assert all(s.removed_seq is None for s in cl.tree.segments)
    assert_converged([a, b])


def test_local_reference_tracks_position_and_slides():
    seqr, (a, b) = make_collab(2)
    submit(seqr, a, a.insert_text_local(0, "abcdef"))
    seqr.process_all_messages()
    ref = a.tree.create_local_reference(3, SlidePolicy.SLIDE)  # at 'd'
    submit(seqr, b, b.insert_text_local(0, "XX"))
    seqr.process_all_messages()
    assert a.tree.get_position(ref.segment, ref.offset) == 5  # shifted by 2
    # remove the segment under the ref, zamboni, ref slides forward
    submit(seqr, b, b.remove_range_local(4, 6))  # removes 'cd' (post-shift)
    seqr.process_all_messages()
    seqr.submit(a, {}, type=MessageType.NOOP)
    seqr.submit(b, {}, type=MessageType.NOOP)
    seqr.process_all_messages()
    assert a.get_text() == "XXabef"
    pos = a.tree.get_position(ref.segment, ref.offset)
    assert pos == 4  # slid to 'e'


def test_summary_roundtrip():
    seqr, (a, b) = make_collab(2)
    submit(seqr, a, a.insert_text_local(0, "hello world"))
    submit(seqr, b, b.insert_text_local(0, "hi "))
    seqr.process_all_messages()
    submit(seqr, a, a.remove_range_local(0, 3))
    seqr.process_all_messages()
    from fluidframework_tpu.models.merge_tree import MergeTree
    summary = a.tree.summarize()
    loaded = MergeTree.load(summary, local_client=99)
    assert loaded.get_text() == a.get_text()
    assert loaded.structure_digest() == a.tree.structure_digest()


def test_insert_position_beyond_length_raises():
    _, (a,) = make_collab(1)
    a.insert_text_local(0, "ab")
    with pytest.raises(IndexError):
        a.insert_text_local(5, "x")
