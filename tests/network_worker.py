"""Worker process for the network-ingress e2e test: one collaborator
editing a SharedString through the FULL client stack (framework →
runtime → loader → network driver → localhost Alfred) — every byte
crosses a process boundary.

Usage: python tests/network_worker.py PORT DOC_ID WORKER_ID N_OPS [--reconnect]

Protocol: inserts its ops as ``<wid>:<j>;`` tokens, waits until it has
seen BOTH workers' full op sets converge, prints one JSON line with the
final text, and exits 0.
"""

import json
import os
import random
import re
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import jax  # noqa: E402  (keep the CPU: no TPU contention from workers)

jax.config.update("jax_platforms", "cpu")

from fluidframework_tpu.framework.fluid_static import NetworkClient  # noqa


SCHEMA = {"initialObjects": {"text": "sharedString"}}


def tokens_of(text: str):
    return re.findall(r"[0-9]+:[0-9]+;", text)


def main() -> int:
    port = int(sys.argv[1])
    doc_id = sys.argv[2]
    wid = int(sys.argv[3])
    n_ops = int(sys.argv[4])
    do_reconnect = "--reconnect" in sys.argv
    rng = random.Random(wid)

    client = NetworkClient(port=port, enable_summarizer=False)
    fc = client.get_container(doc_id, SCHEMA)
    # catch-up is synchronous at resolve: the creator's channel-create ops
    # are already applied, so the channel exists now
    text = fc.initial_objects["text"]

    for j in range(n_ops):
        # insert at a token boundary so tokens never interleave mid-token
        bounds = [0] + [m.end() for m in
                        re.finditer(r";", text.get_text())]
        pos = rng.choice(bounds)
        text.insert_text(pos, f"{wid}:{j};")
        fc.flush()
        # see own op acked before the next (keeps the trace readable)
        want = f"{wid}:{j};"
        fc.pump_until(lambda: want in text.get_text(), timeout=20)
        if do_reconnect and j == n_ops // 2:
            fc.disconnect("e2e drill")
            fc.connect()

    # wait for the OTHER worker's full op set
    other = 1 - wid

    def both_done():
        toks = set(tokens_of(text.get_text()))
        return all(f"{other}:{j};" in toks for j in range(n_ops)) and \
            all(f"{wid}:{j};" in toks for j in range(n_ops))

    fc.pump_until(both_done, timeout=45)
    # settle: no more inbound for a moment → converged order
    while fc.pump(timeout=0.3):
        pass
    print(json.dumps({"worker": wid, "text": text.get_text()}), flush=True)
    fc.dispose()
    return 0


if __name__ == "__main__":
    sys.exit(main())
