"""Regenerate the golden summary fixtures (run ONLY on an intentional
format change: ``python tests/goldens/generate.py``).

Each fixture stores a DDS summary produced by a deterministic edit script
plus the reads a loader must reproduce. ``test_golden_snapshots.py`` loads
the CHECKED-IN files — never regenerates — so an accidental format change
breaks the test instead of silently rewriting history (reference:
test-snapshots golden suite, SURVEY.md §4)."""

import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..", ".."))

from fluidframework_tpu.models import (  # noqa: E402
    SharedMap, SharedMatrix, SharedString,
)
from fluidframework_tpu.models.shared_tree import SharedTree  # noqa: E402
from fluidframework_tpu.testing.mocks import (  # noqa: E402
    MockSequencer, create_connected_dds,
)

OUT = os.path.dirname(os.path.abspath(__file__))


def save(name, summary, expect, base_seq):
    with open(os.path.join(OUT, name), "w") as f:
        json.dump({"summary": summary, "expect": expect,
                   "base_seq": base_seq}, f, indent=1, sort_keys=True)
    print("wrote", name)


def gen_string():
    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedString)
    b = create_connected_dds(seqr, SharedString)
    a.insert_text(0, "hello world")
    b.insert_text(0, "## ")
    seqr.process_all_messages()
    a.annotate_range(3, 8, {"bold": True, "color": "red"})
    b.remove_text(9, 11)
    a.insert_marker(a.get_length())
    seqr.process_all_messages()
    b.annotate_range(4, 6, {"color": None})  # delete color on a span
    seqr.process_all_messages()
    assert a.get_text() == b.get_text()
    expect = {
        "text": a.get_text(),
        "length": a.get_length(),
        "props": [[p, a.get_properties(p)] for p in range(a.get_length())],
    }
    save("shared_string_v1.json", a.summarize(), expect, seqr.seq)


def gen_map():
    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedMap)
    b = create_connected_dds(seqr, SharedMap)
    a.set("title", "golden")
    b.set("count", 3)
    a.set("nested", {"x": [1, 2, 3]})
    seqr.process_all_messages()
    b.delete("count")
    seqr.process_all_messages()
    save("shared_map_v1.json", a.summarize(),
         {"entries": {k: a.get(k) for k in ("title", "nested")},
          "absent": ["count"]}, seqr.seq)


def gen_matrix():
    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedMatrix)
    b = create_connected_dds(seqr, SharedMatrix)
    a.insert_rows(0, 3)
    a.insert_cols(0, 3)
    seqr.process_all_messages()
    for r in range(3):
        for c in range(3):
            a.set_cell(r, c, r * 10 + c)
    b.remove_rows(1, 1)
    seqr.process_all_messages()
    cells = [[a.get_cell(r, c) for c in range(a.col_count)]
             for r in range(a.row_count)]
    save("shared_matrix_v1.json", a.summarize(),
         {"rows": a.row_count, "cols": a.col_count, "cells": cells},
         seqr.seq)


def gen_tree():
    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedTree)
    b = create_connected_dds(seqr, SharedTree)
    n1 = a.insert("root", "children", value={"title": "golden"})
    seqr.process_all_messages()
    n2 = b.insert(n1, "children", value={"text": "first"})
    a.insert(n1, "children", value={"text": "zeroth"})
    seqr.process_all_messages()
    a.set_value(n2, {"text": "edited"})
    seqr.process_all_messages()
    assert a.to_dict() == b.to_dict()
    save("shared_tree_v1.json", a.summarize(), {"tree": a.to_dict()},
         seqr.seq)


if __name__ == "__main__":
    gen_string()
    gen_map()
    gen_matrix()
    gen_tree()
