"""MegaDocStringStore: the host facade for segment-axis-sharded documents,
driven with real multi-client oracle streams on the 8-device CPU mesh."""

import numpy as np
import pytest

from fluidframework_tpu.ops.megadoc_store import MegaDocStringStore
from fluidframework_tpu.ops.string_store import TensorStringStore
from tests.test_merge_tree_kernel import collab_stream


@pytest.mark.parametrize("seed", range(2))
def test_megadoc_store_matches_oracle_and_flat_store(seed):
    text, length, msgs, clients = collab_stream(
        seed, with_annotates=True, return_clients=True)
    mega = MegaDocStringStore(n_docs=1, capacity_per_shard=64)
    flat = TensorStringStore(n_docs=1, capacity=512)
    mega.apply_messages((0, m) for m in msgs)
    flat.apply_messages((0, m) for m in msgs)
    assert not mega.overflowed().any()
    assert mega.read_text(0) == flat.read_text(0) == text
    assert mega.visible_length(0) == length
    oracle = clients[0]
    for pos in range(length):
        seg, _ = oracle.tree.get_containing_segment(pos)
        want = {k: v for k, v in seg.props.items() if v is not None}
        assert mega.get_properties(0, pos) == want, pos


def test_megadoc_store_preemptive_rebalance_survives_long_stream():
    """Tiny shards + incremental batches: the store must spread load before
    any shard can overflow."""
    text, _, msgs = collab_stream(8, n_rounds=14)
    mega = MegaDocStringStore(n_docs=1, capacity_per_shard=24,
                              rebalance_headroom=0.4)
    for i in range(0, len(msgs), 8):
        mega.apply_messages((0, m) for m in msgs[i:i + 8])
    assert not mega.overflowed().any()
    assert mega.read_text(0) == text
    counts = mega.slot_usage()
    assert (counts <= 24).all()


def test_megadoc_store_compaction_frees_slots_preserves_text():
    text, _, msgs = collab_stream(5, n_rounds=15)
    mega = MegaDocStringStore(n_docs=1, capacity_per_shard=128)
    mega.apply_messages((0, m) for m in msgs)
    used = mega.slot_usage().sum()
    mega.compact(max(m.seq for m in msgs))
    assert mega.slot_usage().sum() <= used
    assert mega.read_text(0) == text


def test_megadoc_store_many_docs():
    streams = [collab_stream(seed, n_rounds=4) for seed in range(3)]
    mega = MegaDocStringStore(n_docs=3, capacity_per_shard=64)
    interleaved = []
    idx = [0] * 3
    import random
    rng = random.Random(0)
    while any(idx[d] < len(streams[d][2]) for d in range(3)):
        d = rng.randrange(3)
        if idx[d] < len(streams[d][2]):
            interleaved.append((d, streams[d][2][idx[d]]))
            idx[d] += 1
    mega.apply_messages(interleaved)
    for d in range(3):
        assert mega.read_text(d) == streams[d][0], d
