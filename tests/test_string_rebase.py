"""SharedString reconnect rebasing (SURVEY.md §3.3 — correctness-critical):
pending merge-tree ops regenerated against state merged while offline."""

import random

import pytest

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.runtime import ContainerRuntime
from fluidframework_tpu.server.tinylicious import LocalService


def make_pair():
    svc = LocalService()
    loader = Loader(LocalDocumentServiceFactory(svc),
                    ContainerRuntime.factory())
    a = loader.resolve("doc")
    b = loader.resolve("doc")
    sa = a.runtime.create_data_store("default") \
        .create_channel("text", "sharedString")
    sb = b.runtime.get_data_store("default").get_channel("text")
    return a, b, sa, sb


def converged(sa, sb):
    assert sa.get_text() == sb.get_text(), \
        f"diverged: {sa.get_text()!r} vs {sb.get_text()!r}"
    assert sa.tree.structure_digest() == sb.tree.structure_digest()
    return sa.get_text()


class TestInsertRebase:
    def test_offline_insert_repositioned_after_remote_prefix(self):
        a, b, sa, sb = make_pair()
        sa.insert_text(0, "world")
        a.disconnect("net")
        sa.insert_text(5, "!")            # offline, at end
        sb.insert_text(0, "hello ")       # sequenced while a offline
        a.connect()
        assert converged(sa, sb) == "hello world!"

    def test_offline_insert_into_remotely_removed_context(self):
        a, b, sa, sb = make_pair()
        sa.insert_text(0, "abcdef")
        a.disconnect("net")
        sa.insert_text(3, "XY")           # between c and d
        sb.remove_text(1, 5)              # remove bcde (around the insert pt)
        a.connect()
        # a's text lands at the collapsed position; nothing lost
        assert converged(sa, sb) == "aXYf"

    def test_multiple_offline_inserts_keep_relative_order(self):
        a, b, sa, sb = make_pair()
        sa.insert_text(0, "13")
        a.disconnect("net")
        sa.insert_text(1, "2")            # 123
        sa.insert_text(3, "4")            # 1234
        sb.insert_text(0, "0")            # 013 for b
        a.connect()
        assert converged(sa, sb) == "01234"


class TestRemoveRebase:
    def test_offline_remove_skips_text_typed_inside_range(self):
        a, b, sa, sb = make_pair()
        sa.insert_text(0, "delete this please")
        a.disconnect("net")
        sa.remove_text(0, 11)             # "delete this" pending remove
        sb.insert_text(7, "NEW ")         # typed inside the doomed range
        a.connect()
        # the regenerated removes must not eat b's concurrent text
        assert converged(sa, sb) == "NEW  please"

    def test_offline_remove_overlapping_remote_remove(self):
        a, b, sa, sb = make_pair()
        sa.insert_text(0, "abcdefgh")
        a.disconnect("net")
        sa.remove_text(2, 6)              # cdef
        sb.remove_text(4, 8)              # efgh (overlaps)
        a.connect()
        assert converged(sa, sb) == "ab"

    def test_offline_remove_fully_superseded_by_remote_remove(self):
        a, b, sa, sb = make_pair()
        sa.insert_text(0, "abcdef")
        a.disconnect("net")
        sa.remove_text(2, 4)              # cd
        sb.remove_text(0, 6)              # everything
        a.connect()                        # a's remove regenerates to nothing
        assert converged(sa, sb) == ""


class TestAnnotateRebase:
    def test_offline_annotate_follows_its_text(self):
        a, b, sa, sb = make_pair()
        sa.insert_text(0, "plain bold")
        a.disconnect("net")
        sa.annotate_range(6, 10, {"weight": "bold"})
        sb.insert_text(0, ">>> ")
        a.connect()
        assert converged(sa, sb) == ">>> plain bold"
        # the annotation moved with the text on BOTH replicas
        for s in (sa, sb):
            assert s.get_properties(10) == {"weight": "bold"}
            assert s.get_properties(5) == {}


class TestIntervalRebase:
    def test_offline_interval_add_reanchors(self):
        a, b, sa, sb = make_pair()
        sa.insert_text(0, "mark this span")
        a.disconnect("net")
        iva = sa.get_interval_collection("c")
        iva.add(5, 9, {"note": "x"})      # "this"
        sb.insert_text(0, "## ")
        a.connect()
        converged(sa, sb)
        ivb = sb.get_interval_collection("c")
        (iv,) = ivb.find_overlapping(0, sb.get_length())
        s, e = ivb.endpoints(iv.interval_id)
        assert sb.get_text()[s:e + 1].startswith("this")


class TestMixedFuzz:
    @pytest.mark.parametrize("seed", range(8))
    def test_offline_edit_storm_converges(self, seed):
        rng = random.Random(seed)
        a, b, sa, sb = make_pair()
        sa.insert_text(0, "the quick brown fox jumps over the lazy dog")

        def edit(s):
            n = s.get_length()
            kind = rng.choice(["ins", "ins", "del", "ann"])
            if kind == "ins" or n < 4:
                s.insert_text(rng.randint(0, n), rng.choice(
                    ["X", "yy", "zzz", " "]))
            elif kind == "del":
                i = rng.randint(0, n - 2)
                j = rng.randint(i + 1, min(n, i + 5))
                s.remove_text(i, j)
            else:
                i = rng.randint(0, n - 2)
                j = rng.randint(i + 1, min(n, i + 4))
                s.annotate_range(i, j, {"k": rng.randint(0, 9)})

        a.disconnect("net")
        for _ in range(6):
            edit(sa)                      # offline edits pile up pending
        for _ in range(6):
            edit(sb)                      # sequenced meanwhile
        a.connect()
        converged(sa, sb)

    def test_double_disconnect_cycle(self):
        a, b, sa, sb = make_pair()
        sa.insert_text(0, "abc")
        a.disconnect("1")
        sa.insert_text(3, "def")
        a.connect()
        a.disconnect("2")
        sa.remove_text(0, 2)
        sb.insert_text(3, "-mid-")
        a.connect()
        assert converged(sa, sb) == "c-mid-def"
