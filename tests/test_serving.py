"""End-to-end serving engine: Deli sequencing + durable log + batched device
merge, with summary + log-tail recovery (the north-star slice as a service)."""

import random

import pytest

from fluidframework_tpu.models.merge_tree_client import SequenceClient
from fluidframework_tpu.server.deli import NackReason
from fluidframework_tpu.server.oplog import PartitionedLog
from fluidframework_tpu.server.serving import StringServingEngine

PROPS = ({"bold": True}, {"color": "red"}, {"color": None}, None)


def _run_storm(engine, docs, clients, rng, n_ops, inflight):
    """Clients edit concurrently: sequenced msgs are delivered lazily (so
    ref_seq genuinely lags), via per-doc in-order delivery queues."""
    for _ in range(n_ops):
        doc = rng.choice(docs)
        c = rng.choice(clients[doc])
        n = c.get_length()
        roll = rng.random()
        if n == 0 or roll < 0.55:
            props = rng.choice(PROPS) if roll < 0.2 else None
            op = c.insert_text_local(rng.randint(0, n),
                                     "t%d" % rng.randint(0, 99), props)
        elif roll < 0.7:
            start = rng.randint(0, n - 1)
            op = c.annotate_range_local(
                start, rng.randint(start + 1, min(n, start + 6)),
                {"bold": rng.choice((True, None))})
        else:
            start = rng.randint(0, n - 1)
            op = c.remove_range_local(start,
                                      rng.randint(start + 1, min(n, start + 5)))
        msg, nack = engine.submit(doc, c.client_id, op["clientSeq"],
                                  c.last_processed_seq, op)
        assert nack is None
        inflight[doc].append(msg)
        # deliver a random prefix of each doc's backlog (in seq order)
        for d in docs:
            k = rng.randint(0, len(inflight[d]))
            for m in inflight[d][:k]:
                for cc in clients[d]:
                    cc.apply_msg(m)
            del inflight[d][:k]


def _drain(docs, clients, inflight):
    for d in docs:
        for m in inflight[d]:
            for cc in clients[d]:
                cc.apply_msg(m)
        inflight[d].clear()


def _mk(engine, docs, n_clients, id_start=1):
    clients = {}
    cid = id_start
    for d in docs:
        clients[d] = []
        for _ in range(n_clients):
            engine.connect(d, cid)
            clients[d].append(SequenceClient(cid))
            cid += 1
    return clients


@pytest.mark.parametrize("seed", range(4))
def test_engine_end_to_end_converges_with_clients(seed):
    rng = random.Random(seed)
    docs = ["doc-a", "doc-b"]
    engine = StringServingEngine(n_docs=2, capacity=512, batch_window=8)
    clients = _mk(engine, docs, 3)
    inflight = {d: [] for d in docs}
    _run_storm(engine, docs, clients, rng, 60, inflight)
    _drain(docs, clients, inflight)
    for d in docs:
        texts = {c.get_text() for c in clients[d]}
        assert len(texts) == 1
        assert engine.read_text(d) == texts.pop(), d
        oracle = clients[d][0]
        for pos in range(oracle.get_length()):
            seg, _ = oracle.tree.get_containing_segment(pos)
            want = {k: v for k, v in seg.props.items() if v is not None}
            assert engine.get_properties(d, pos) == want, (d, pos)


def test_engine_nack_paths():
    engine = StringServingEngine(n_docs=1, capacity=64)
    engine.connect("d", 1)
    c = SequenceClient(1)
    op = c.insert_text_local(0, "hi")
    # unknown client
    _, nack = engine.submit("d", 99, 1, 0, op)
    assert nack.reason == NackReason.UNKNOWN_CLIENT
    # clientSeq gap (lost op 1)
    _, nack = engine.submit("d", 1, 2, 0, op)
    assert nack.reason == NackReason.CLIENT_SEQ_GAP
    # good, then duplicate
    msg, nack = engine.submit("d", 1, 1, 0, op)
    assert nack is None and msg.seq > 0
    _, nack = engine.submit("d", 1, 1, 0, op)
    assert nack.reason == NackReason.DUPLICATE


def test_engine_summary_and_log_tail_recovery(tmp_path):
    rng = random.Random(7)
    docs = ["alpha", "beta", "gamma"]
    log = PartitionedLog(4)
    engine = StringServingEngine(n_docs=3, capacity=512, batch_window=8,
                                 log=log)
    clients = _mk(engine, docs, 2)
    inflight = {d: [] for d in docs}
    _run_storm(engine, docs, clients, rng, 40, inflight)

    summary = engine.summarize()
    # more ops AFTER the summary: this is the durable-log tail
    _run_storm(engine, docs, clients, rng, 25, inflight)
    _drain(docs, clients, inflight)
    want = {d: engine.read_text(d) for d in docs}

    # crash: rebuild purely from summary + log
    engine2 = StringServingEngine.load(summary, log)
    for d in docs:
        assert engine2.read_text(d) == want[d], d

    # sequencing must CONTINUE past the tail (no seq reuse): new ops land
    for d in docs:
        c = clients[d][0]
        op = c.insert_text_local(0, "Z")
        msg, nack = engine2.submit(d, c.client_id, op["clientSeq"],
                                   c.last_processed_seq, op)
        assert nack is None
        for cc in clients[d]:
            cc.apply_msg(msg)
        assert engine2.read_text(d) == clients[d][0].get_text() == \
            clients[d][1].get_text()


def test_engine_batch_window_autoflush():
    engine = StringServingEngine(n_docs=1, capacity=128, batch_window=4)
    engine.connect("d", 1)
    c = SequenceClient(1)
    for i in range(10):
        op = c.insert_text_local(c.get_length(), "ab")
        msg, _ = engine.submit("d", 1, op["clientSeq"],
                               c.last_processed_seq, op)
        c.apply_msg(msg)
    assert len(engine._queue) < 4  # windows flushed automatically
    assert engine.read_text("d") == c.get_text()


def test_engine_heartbeat_advances_msn_for_zamboni():
    engine = StringServingEngine(n_docs=1, capacity=128, batch_window=64)
    engine.connect("d", 1)
    c = SequenceClient(1)
    for i in range(6):
        op = c.insert_text_local(c.get_length(), "abc")
        msg, _ = engine.submit("d", 1, op["clientSeq"],
                               c.last_processed_seq, op)
        c.apply_msg(msg)
    op = c.remove_range_local(0, 9)
    msg, _ = engine.submit("d", 1, op["clientSeq"], c.last_processed_seq, op)
    c.apply_msg(msg)
    engine.flush()
    used_with_tombstones = engine.store.slot_usage()[0]
    engine.heartbeat("d", 1, c.last_processed_seq)  # window floor advances
    engine.compact()
    assert engine.store.slot_usage()[0] < used_with_tombstones
    assert engine.read_text("d") == c.get_text()


def test_engine_recovery_join_only_doc_in_tail():
    """A doc whose CLIENT_JOIN landed after the summary (no ops yet) must be
    fully usable after recovery: first submit applies, read works."""
    log = PartitionedLog(4)
    engine = StringServingEngine(n_docs=2, capacity=64, log=log)
    engine.connect("old", 1)
    c_old = SequenceClient(1)
    op = c_old.insert_text_local(0, "x")
    msg, _ = engine.submit("old", 1, op["clientSeq"], 0, op)
    c_old.apply_msg(msg)
    summary = engine.summarize()
    engine.connect("newdoc", 5)  # join-only: in the log tail

    engine2 = StringServingEngine.load(summary, log)
    c = SequenceClient(5)
    op = c.insert_text_local(0, "hello")
    msg, nack = engine2.submit("newdoc", 5, op["clientSeq"], 0, op)
    assert nack is None
    c.apply_msg(msg)
    assert engine2.read_text("newdoc") == "hello"
    assert engine2.read_text("old") == "x"


def test_engine_mega_tier_routes_and_converges():
    """Documents marked mega are served by the segment-axis-sharded store
    with the same API and convergence as the flat tier."""
    rng = random.Random(3)
    engine = StringServingEngine(n_docs=1, capacity=256, batch_window=8,
                                 mega_docs=1, mega_capacity_per_shard=64)
    engine.mark_mega("huge")
    docs = ["huge", "small"]
    clients = _mk(engine, docs, 2)
    inflight = {d: [] for d in docs}
    _run_storm(engine, docs, clients, rng, 50, inflight)
    _drain(docs, clients, inflight)
    for d in docs:
        texts = {c.get_text() for c in clients[d]}
        assert len(texts) == 1
        assert engine.read_text(d) == texts.pop(), d
        oracle = clients[d][0]
        for pos in range(oracle.get_length()):
            seg, _ = oracle.tree.get_containing_segment(pos)
            want = {k: v for k, v in seg.props.items() if v is not None}
            assert engine.get_properties(d, pos) == want, (d, pos)


def test_engine_mega_tier_summary_recovery():
    rng = random.Random(9)
    log = PartitionedLog(4)
    engine = StringServingEngine(n_docs=1, capacity=256, batch_window=8,
                                 mega_docs=1, mega_capacity_per_shard=64,
                                 log=log)
    engine.mark_mega("huge")
    docs = ["huge", "small"]
    clients = _mk(engine, docs, 2)
    inflight = {d: [] for d in docs}
    _run_storm(engine, docs, clients, rng, 30, inflight)
    summary = engine.summarize()
    _run_storm(engine, docs, clients, rng, 20, inflight)
    _drain(docs, clients, inflight)
    want = {d: engine.read_text(d) for d in docs}

    engine2 = StringServingEngine.load(summary, log)
    for d in docs:
        assert engine2.read_text(d) == want[d], d
    # post-recovery edits keep working on the mega tier
    c = clients["huge"][0]
    op = c.insert_text_local(0, "Z")
    msg, nack = engine2.submit("huge", c.client_id, op["clientSeq"],
                               c.last_processed_seq, op)
    assert nack is None
    for cc in clients["huge"]:
        cc.apply_msg(msg)
    assert engine2.read_text("huge") == clients["huge"][0].get_text()


def test_engine_mega_mark_survives_crash_before_summary():
    """A mark_mega issued after the last summary must be replayed from the
    durable log, or tail ops route to the flat tier and overflow it."""
    log = PartitionedLog(4)
    engine = StringServingEngine(n_docs=1, capacity=16, batch_window=4,
                                 mega_docs=1, mega_capacity_per_shard=64,
                                 log=log)
    engine.connect("old", 1)
    c_old = SequenceClient(1)
    op = c_old.insert_text_local(0, "x")
    msg, _ = engine.submit("old", 1, op["clientSeq"], 0, op)
    c_old.apply_msg(msg)
    summary = engine.summarize()

    # mark + heavy ops AFTER the summary: tail must replay onto the mega tier
    engine.mark_mega("huge")
    engine.connect("huge", 5)
    c = SequenceClient(5)
    for i in range(30):  # 30 inserts would overflow the 16-slot flat tier
        op = c.insert_text_local(c.get_length(), f"t{i} ")
        msg, nack = engine.submit("huge", 5, op["clientSeq"],
                                  c.last_processed_seq, op)
        assert nack is None
        c.apply_msg(msg)

    engine2 = StringServingEngine.load(summary, log)
    assert engine2.read_text("huge") == c.get_text()
    assert engine2.read_text("old") == "x"
    assert "huge" in engine2._mega_rows
    assert not engine2.overflowed_docs()
    # membership keeps surviving a SECOND recovery from the same log
    engine3 = StringServingEngine.load(engine2.summarize(), log)
    assert engine3.read_text("huge") == c.get_text()


def test_engine_mark_mega_after_connect_allowed():
    """A JOIN must not pin the doc to the flat tier (rows are lazy)."""
    engine = StringServingEngine(n_docs=1, capacity=64, mega_docs=1,
                                 mega_capacity_per_shard=32)
    engine.connect("d", 1)
    engine.mark_mega("d")  # must not raise
    c = SequenceClient(1)
    op = c.insert_text_local(0, "hello")
    msg, nack = engine.submit("d", 1, op["clientSeq"], 0, op)
    assert nack is None
    assert engine.read_text("d") == "hello"
    assert "d" in engine._mega_rows and "d" not in engine._doc_rows


# ------------------------------------------------------- map serving engine

class TestMapServingEngine:
    def _mk(self, **kw):
        from fluidframework_tpu.server.serving import MapServingEngine
        return MapServingEngine(**kw)

    def test_storm_matches_oracle(self):
        """Random set/delete/clear storm across docs and clients: the served
        state must equal a SharedMap oracle replica fed the same stream."""
        from fluidframework_tpu.models import SharedMap
        rng = random.Random(5)
        engine = self._mk(n_docs=4, n_keys=32, batch_window=16)
        docs = [f"d{i}" for i in range(4)]
        oracles = {}
        clientseqs = {}
        for d in docs:
            engine.connect(d, 1)
            oracles[d] = SharedMap(d, 99)   # pure observer replica
            clientseqs[d] = 0
        for i in range(300):
            d = rng.choice(docs)
            roll = rng.random()
            if roll < 0.7:
                op = {"op": "set", "key": f"k{rng.randrange(8)}",
                      "value": rng.choice([1, "s", None, [1, 2],
                                           {"a": rng.randrange(3)}])}
            elif roll < 0.92:
                op = {"op": "delete", "key": f"k{rng.randrange(8)}"}
            else:
                op = {"op": "clear"}
            clientseqs[d] += 1
            msg, nack = engine.submit(d, 1, clientseqs[d], 0, op)
            assert nack is None
            oracles[d].process_core(msg, local=False)
        for d in docs:
            assert engine.read_doc(d) == dict(oracles[d].kernel.data), d

    def test_summary_and_tail_recovery(self):
        from fluidframework_tpu.server.serving import MapServingEngine
        log = PartitionedLog(4)
        engine = self._mk(n_docs=2, log=log)
        engine.connect("a", 1)
        engine.submit("a", 1, 1, 0, {"op": "set", "key": "x", "value": 1})
        summary = engine.summarize()
        engine.submit("a", 1, 2, 0, {"op": "set", "key": "y", "value": 2})
        engine.connect("b", 7)  # join-only doc in the tail
        engine2 = MapServingEngine.load(summary, log)
        assert engine2.read_doc("a") == {"x": 1, "y": 2}
        # sequencing continues correctly past the tail
        msg, nack = engine2.submit("b", 7, 1, 0,
                                   {"op": "set", "key": "k", "value": "v"})
        assert nack is None
        assert engine2.read_doc("b") == {"k": "v"}

    def test_capacity_and_dedupe(self):
        engine = self._mk(n_docs=1)
        engine.connect("a", 1)
        engine.submit("a", 1, 1, 0, {"op": "set", "key": "x", "value": 1})
        # duplicate clientSeq → nack, state unchanged
        msg, nack = engine.submit("a", 1, 1, 0,
                                  {"op": "set", "key": "x", "value": 99})
        assert msg is None and nack is not None
        assert engine.read_doc("a") == {"x": 1}
        engine.connect("b", 1)
        with pytest.raises(KeyError):
            engine.read_doc("b")  # second doc exceeds n_docs=1


# ---------------------------------------------------- matrix serving engine

class TestMatrixServingEngine:
    def _engine(self, **kw):
        from fluidframework_tpu.server.serving import MatrixServingEngine
        kw.setdefault("n_docs", 2)
        kw.setdefault("cell_capacity", 4096)
        return MatrixServingEngine(**kw)

    def _oracle(self, doc):
        from fluidframework_tpu.models import SharedMatrix
        return SharedMatrix(doc, 999)  # pure observer replica

    def _storm(self, engine, oracle, doc, rng, n_ops, fww_at=None):
        cs = 0
        last = {"seq": 0}
        def submit(op):
            nonlocal cs
            cs += 1
            op = dict(op, clientSeq=cs)
            if op["mx"] in ("insRow", "insCol"):
                op.setdefault("opKey", (7, cs))
            msg, nack = engine.submit(doc, 7, cs, last["seq"], op)
            assert nack is None, nack
            last["seq"] = msg.seq
            oracle.process_core(msg, local=False)
        submit({"mx": "insRow", "pos": 0, "count": 4})
        submit({"mx": "insCol", "pos": 0, "count": 4})
        for i in range(n_ops):
            if fww_at is not None and i == fww_at:
                submit({"mx": "policy"})
            nr, nc = oracle.row_count, oracle.col_count
            roll = rng.random()
            if roll < 0.6 and nr and nc:
                submit({"mx": "setCell", "row": rng.randrange(nr),
                        "col": rng.randrange(nc), "value": f"v{i}"})
            elif roll < 0.75:
                submit({"mx": "insRow" if roll < 0.68 else "insCol",
                        "pos": rng.randint(0, nr if roll < 0.68 else nc),
                        "count": rng.randint(1, 2)})
            elif nr > 1 and roll < 0.88:
                s = rng.randrange(nr - 1)
                submit({"mx": "rmRow", "start": s, "count": 1})
            elif nc > 1:
                s = rng.randrange(nc - 1)
                submit({"mx": "rmCol", "start": s, "count": 1})
        return last["seq"]

    def test_storm_matches_oracle(self):
        rng = random.Random(2)
        engine = self._engine()
        engine.connect("m", 7)
        oracle = self._oracle("m")
        self._storm(engine, oracle, "m", rng, 120)
        assert engine.to_lists("m") == oracle.to_lists()
        assert engine.dims("m") == (oracle.row_count, oracle.col_count)

    def test_fww_flip_matches_oracle(self):
        rng = random.Random(8)
        engine = self._engine()
        engine.connect("m", 7)
        oracle = self._oracle("m")
        self._storm(engine, oracle, "m", rng, 100, fww_at=40)
        assert engine.to_lists("m") == oracle.to_lists()

    def test_fww_concurrent_writer_loses(self):
        """A write whose ref_seq predates the current value (different
        writer) must lose under FWW — and a later write that HAS seen it
        must still replace (the kernel's first-ever-wins flag alone would
        get this wrong)."""
        engine = self._engine()
        engine.connect("m", 1)
        engine.connect("m", 2)
        def submit(client, cs, ref, op):
            msg, nack = engine.submit("m", client, cs, ref, op)
            assert nack is None
            return msg
        submit(1, 1, 0, {"mx": "insRow", "pos": 0, "count": 1,
                         "opKey": (1, 1)})
        submit(1, 2, 0, {"mx": "insCol", "pos": 0, "count": 1,
                         "opKey": (1, 2)})
        submit(1, 3, 0, {"mx": "policy"})
        m1 = submit(1, 4, 0, {"mx": "setCell", "row": 0, "col": 0,
                              "value": "first"})
        # client 2 wrote concurrently (ref_seq below m1.seq): loses
        submit(2, 1, m1.seq - 1, {"mx": "setCell", "row": 0, "col": 0,
                                  "value": "concurrent"})
        assert engine.get_cell("m", 0, 0) == "first"
        # client 2 writes again AFTER seeing it: replaces
        submit(2, 2, m1.seq + 1, {"mx": "setCell", "row": 0, "col": 0,
                                  "value": "seen"})
        assert engine.get_cell("m", 0, 0) == "seen"

    def test_summary_and_tail_recovery(self):
        from fluidframework_tpu.server.serving import MatrixServingEngine
        rng = random.Random(4)
        log = PartitionedLog(4)
        engine = self._engine(log=log)
        engine.connect("m", 7)
        oracle = self._oracle("m")
        seen = self._storm(engine, oracle, "m", rng, 60)
        summary = engine.summarize()
        # tail ops after the summary (fresh client: the storm owns client 7)
        engine.connect("m", 8)
        msg, _ = engine.submit("m", 8, 1, seen,
                               {"mx": "setCell", "row": 0, "col": 0,
                                "value": "tail"})
        oracle.process_core(msg, local=False)
        engine2 = MatrixServingEngine.load(summary, log)
        assert engine2.to_lists("m") == oracle.to_lists()
        # engine live after recovery
        msg, nack = engine2.submit("m", 8, 2, msg.seq,
                                   {"mx": "setCell", "row": 0, "col": 0,
                                    "value": "post"})
        assert nack is None
        assert engine2.get_cell("m", 0, 0) == "post"


# ------------------------------------------- serving service (full stack)

class TestServingLocalService:
    """Interactive clients on the FULL container stack (loader + runtime +
    outbox grouping/compression) against a service whose sequenced stream
    also feeds the device replica — server-side reads with no client."""

    def _mk(self, **kw):
        from fluidframework_tpu.framework import LocalClient
        from fluidframework_tpu.server.serving_service import (
            ServingLocalService)
        svc = ServingLocalService(n_docs=8, capacity=512, **kw)
        return svc, LocalClient(service=svc)

    def test_container_edits_served_on_device(self):
        svc, client = self._mk()
        schema = {"initialObjects": {"text": "sharedString"}}
        c1, doc_id = client.create_container(schema)
        c2 = client.get_container(doc_id, schema)
        t1 = c1.initial_objects["text"]
        t2 = c2.initial_objects["text"]
        t1.insert_text(0, "hello world", {"bold": True})
        t2.insert_text(0, "[b] ")
        t1.annotate_range(0, 2, {"color": "red"})
        t1.remove_text(0, 1)
        # the container stack delivers synchronously through LocalService;
        # all replicas and the SERVER's device replica must agree
        assert t1.get_text() == t2.get_text()
        assert svc.read_text(doc_id, "text") == t1.get_text()
        for pos in range(t1.get_length()):
            assert svc.get_properties(doc_id, "text", pos) == \
                t1.get_properties(pos), pos

    def test_multiple_docs_and_channels(self):
        svc, client = self._mk()
        schema = {"initialObjects": {"a": "sharedString",
                                     "b": "sharedString"}}
        c1, d1 = client.create_container(schema)
        c2, d2 = client.create_container(schema)
        c1.initial_objects["a"].insert_text(0, "doc1-a")
        c1.initial_objects["b"].insert_text(0, "doc1-b")
        c2.initial_objects["a"].insert_text(0, "doc2-a")
        assert svc.read_text(d1, "a") == "doc1-a"
        assert svc.read_text(d1, "b") == "doc1-b"
        assert svc.read_text(d2, "a") == "doc2-a"
        assert set(svc.served_channels(d1)) == {("default", "a"),
                                                ("default", "b")}

    def test_storm_with_compaction_matches_clients(self):
        import random as _r
        rng = _r.Random(13)
        svc, client = self._mk(batch_window=8, compact_every=2)
        schema = {"initialObjects": {"text": "sharedString"}}
        c1, doc_id = client.create_container(schema)
        c2 = client.get_container(doc_id, schema)
        texts = [c1.initial_objects["text"], c2.initial_objects["text"]]
        for i in range(120):
            t = rng.choice(texts)
            n = t.get_length()
            roll = rng.random()
            if n == 0 or roll < 0.6:
                t.insert_text(rng.randint(0, n), f"w{i} ")
            elif roll < 0.8:
                s = rng.randrange(n)
                t.remove_text(s, rng.randint(s + 1, min(n, s + 5)))
            else:
                s = rng.randrange(n)
                t.annotate_range(s, rng.randint(s + 1, min(n, s + 4)),
                                 {"k": rng.randint(0, 3)})
        assert texts[0].get_text() == texts[1].get_text()
        assert svc.read_text(doc_id, "text") == texts[0].get_text()

    def test_non_string_channels_ignored(self):
        svc, client = self._mk()
        schema = {"initialObjects": {"m": "map", "text": "sharedString"}}
        c1, doc_id = client.create_container(schema)
        c1.initial_objects["m"].set("k", 1)
        c1.initial_objects["text"].insert_text(0, "served")
        assert svc.read_text(doc_id, "text") == "served"
        assert svc.served_channels(doc_id) == [("default", "text")]


def test_string_engine_rejects_malformed_before_logging():
    """A malformed op must be nacked BEFORE sequencing/logging — a logged
    op the flush path cannot apply would poison the engine and its
    recovery replay (found by a live drive; VERDICT r1 era gap)."""
    engine = StringServingEngine(n_docs=1, capacity=64)
    engine.connect("d", 1)
    bad = [
        {"mt": "bogus"},
        "not a dict",
        {"mt": "insert", "kind": 0, "pos": -1, "text": "x"},
        {"mt": "insert", "kind": 0, "pos": 0},            # no text
        {"mt": "insert", "kind": 2, "pos": 0, "text": "x"},
        {"mt": "insert", "kind": 0, "pos": 0, "text": "x",
         "props": {"k": object()}},                        # unserializable
        {"mt": "remove", "start": 3, "end": 3},
        {"mt": "remove", "start": 0},
        {"mt": "annotate", "start": 0, "end": 1, "props": {}},
        {"mt": "annotate", "start": 0, "end": 1},
    ]
    log_before = sum(engine.log.size(p)
                     for p in range(engine.log.n_partitions))
    for contents in bad:
        msg, nack = engine.submit("d", 1, 1, 0, contents)
        assert msg is None and nack is not None, contents
        assert nack.reason == NackReason.MALFORMED, contents
    # nothing was sequenced or logged; a good op still lands with seq
    # continuity intact
    assert sum(engine.log.size(p)
               for p in range(engine.log.n_partitions)) == log_before
    msg, nack = engine.submit(
        "d", 1, 1, 0, {"mt": "insert", "kind": 0, "pos": 0, "text": "ok"})
    assert nack is None
    assert engine.read_text("d") == "ok"


def test_string_engine_prop_plane_capacity_nacked():
    """Annotates minting more distinct property keys than the store has
    planes must be CAPACITY-nacked at admission, not die at flush."""
    engine = StringServingEngine(n_docs=1, capacity=64, n_props=2)
    engine.connect("d", 1)
    msg, _ = engine.submit(
        "d", 1, 1, 0, {"mt": "insert", "kind": 0, "pos": 0, "text": "abcd"})
    ref = msg.seq
    for i, key in enumerate(("k1", "k2")):
        msg, nack = engine.submit(
            "d", 1, 2 + i, ref, {"mt": "annotate", "start": 0, "end": 2,
                                 "props": {key: "v"}})
        assert nack is None
    msg, nack = engine.submit(
        "d", 1, 4, ref, {"mt": "annotate", "start": 0, "end": 2,
                         "props": {"k3": "v"}})
    assert msg is None and nack.reason == NackReason.CAPACITY
    assert engine.read_text("d") == "abcd"  # flush unpoisoned


def test_deli_nack_refunds_prop_reservation():
    """An annotate admitted (prop plane minted) but then DELI-nacked
    (clientSeq gap) must refund the mint — otherwise a stream of nacked
    ops exhausts the plane table for everyone (code-review r2 finding)."""
    engine = StringServingEngine(n_docs=1, capacity=64, n_props=2)
    engine.connect("d", 1)
    msg, _ = engine.submit(
        "d", 1, 1, 0, {"mt": "insert", "kind": 0, "pos": 0, "text": "abcd"})
    ref = msg.seq
    for i in range(5):  # clientSeq gap → deli nack, after admission
        msg, nack = engine.submit(
            "d", 1, 99 + i, ref, {"mt": "annotate", "start": 0, "end": 2,
                                  "props": {f"leak{i}": "v"}})
        assert msg is None and nack.reason == NackReason.CLIENT_SEQ_GAP
    # both planes are still free for legitimate annotates
    for i, key in enumerate(("k1", "k2")):
        msg, nack = engine.submit(
            "d", 1, 2 + i, ref, {"mt": "annotate", "start": 0, "end": 2,
                                 "props": {key: "v"}})
        assert nack is None, key
    assert engine.get_properties("d", 0) == {"k1": "v", "k2": "v"}


def test_valid_op_rejects_boolean_kind():
    """`True in (0, 1)` is True in Python — a JSON-boolean kind must still
    be MALFORMED (code-review r2 finding)."""
    engine = StringServingEngine(n_docs=1, capacity=64)
    engine.connect("d", 1)
    msg, nack = engine.submit(
        "d", 1, 1, 0, {"mt": "insert", "kind": True, "pos": 0})
    assert msg is None and nack.reason == NackReason.MALFORMED


def test_matrix_33rd_client_is_capacity_nacked():
    """Per-axis client capacity (MAX_CLIENTS=32): the 33rd distinct
    client's op must be CAPACITY-nacked BEFORE sequencing — an acked op
    the flush path cannot apply would diverge server reads from every
    client replica (review r4 finding)."""
    from fluidframework_tpu.ops.merge_tree_kernel import MAX_CLIENTS
    from fluidframework_tpu.server.deli import NackReason
    from fluidframework_tpu.server.serving import MatrixServingEngine
    eng = MatrixServingEngine(n_docs=1, cell_capacity=4096,
                              batch_window=10 ** 9, axis_capacity=64)
    eng.connect("m", 1)
    msg, nack = eng.submit("m", 1, 1, 0, {"mx": "insRow", "pos": 0,
                                          "count": 4, "opKey": (1, 1)})
    assert nack is None
    seq = msg.seq
    for c in range(2, MAX_CLIENTS + 1):  # clients 2..32 fit
        eng.connect("m", c)
        msg, nack = eng.submit("m", c, 1, seq,
                               {"mx": "setCell", "row": 0, "col": 0,
                                "value": c})
        # col axis is empty: the op may drop at flush, but it must ACK
        assert nack is None
        seq = msg.seq
    eng.connect("m", 999)
    doc_seq_before = eng.deli.doc_seq("m")
    _, nack = eng.submit("m", 999, 1, seq,
                         {"mx": "setCell", "row": 0, "col": 0,
                          "value": "x"})
    assert nack is not None and nack.reason == NackReason.CAPACITY
    assert eng.deli.doc_seq("m") == doc_seq_before  # nothing sequenced
    eng.flush()  # engine still healthy


def test_matrix_axis_admission_rebased_after_load():
    """load() must re-base the axis-slot admission bound from the
    restored planes — a zeroed bound would admit ops past capacity
    (review r4 finding)."""
    from fluidframework_tpu.server.serving import MatrixServingEngine
    log = PartitionedLog(4)
    eng = MatrixServingEngine(n_docs=1, cell_capacity=4096,
                              batch_window=10 ** 9, axis_capacity=16,
                              log=log)
    eng.connect("m", 1)
    cs = 0
    for k in range(6):  # 6 admitted axis ops ≈ 12/16 of the bound
        cs += 1
        _, nack = eng.submit("m", 1, cs, 0,
                             {"mx": "insRow", "pos": 0, "count": 1,
                              "opKey": (1, cs)})
        assert nack is None
    revived = MatrixServingEngine.load(eng.summarize(), log,
                                       axis_capacity=16)
    assert revived._axis_used[0] >= 6  # bound reflects restored planes
    # headroom accounting continues: (16-6)//2 = 5 more fit...
    for k in range(5):
        cs += 1
        _, nack = revived.submit("m", 1, cs, 0,
                                 {"mx": "insRow", "pos": 0, "count": 1,
                                  "opKey": (1, cs)})
        assert nack is None
    # ...then the conservative bound trips before the axis can overflow
    cs += 1
    _, nack = revived.submit("m", 1, cs, 0,
                             {"mx": "insRow", "pos": 0, "count": 1,
                              "opKey": (1, cs)})
    assert nack is not None
