"""Kernel-vs-oracle parity for the batched merge-tree device kernel.

The oracle is the fuzz-hardened ``models.MergeTree`` replica network: random
multi-client edit storms (with ops crossing in flight, so ref_seq perspectives
genuinely lag) are sequenced by the mock service; every sequenced message is
also fed to the device store, which must reproduce the converged text exactly.
"""

import random

import numpy as np
import pytest

from fluidframework_tpu.core.protocol import MessageType
from fluidframework_tpu.models.merge_tree_client import SequenceClient
from fluidframework_tpu.ops.string_store import TensorStringStore
from fluidframework_tpu.testing.fuzz import _rand_text
from fluidframework_tpu.testing.mocks import MockSequencer


_PROP_KEYS = ("bold", "italic", "color")
_PROP_VALUES = (True, 1, "red", "blue", None)  # None deletes the key


def collab_stream(seed, n_clients=3, n_rounds=20, ops_per_round=4,
                  with_markers=True, with_annotates=False,
                  return_clients=False):
    """Run an oracle collab session; return (converged text, sequenced msgs)."""
    rng = random.Random(seed)
    seqr = MockSequencer()
    clients = [SequenceClient(seqr.allocate_client_id())
               for _ in range(n_clients)]
    for c in clients:
        seqr.connect(c)
    msgs = []
    orig_process = seqr.process_one

    def capture():
        m = orig_process()
        if m is not None and m.type == MessageType.OP:
            msgs.append(m)
        return m
    seqr.process_one = capture

    for _ in range(n_rounds):
        for _ in range(ops_per_round):
            c = rng.choice(clients)
            n = c.get_length()
            roll = rng.random()
            if n == 0 or roll < 0.5:
                props = {k: rng.choice(_PROP_VALUES[:-1])
                         for k in rng.sample(_PROP_KEYS, rng.randint(0, 2))} \
                    if with_annotates and rng.random() < 0.3 else None
                op = c.insert_text_local(rng.randint(0, n), _rand_text(rng),
                                         props)
            elif roll < 0.57 and with_markers:
                props = {"markerId": rng.randint(1, 9)} \
                    if with_annotates and rng.random() < 0.5 else None
                op = c.insert_marker_local(rng.randint(0, n), props)
            elif roll < 0.75 and with_annotates:
                start = rng.randint(0, n - 1)
                props = {k: rng.choice(_PROP_VALUES)
                         for k in rng.sample(_PROP_KEYS,
                                             rng.randint(1, len(_PROP_KEYS)))}
                op = c.annotate_range_local(
                    start, rng.randint(start + 1, min(n, start + 8)), props)
            else:
                start = rng.randint(0, n - 1)
                op = c.remove_range_local(
                    start, rng.randint(start + 1, min(n, start + 6)))
            seqr.submit(c, op)
        seqr.process_some(rng.randint(0, seqr.outstanding))
    seqr.process_all_messages()
    texts = {c.get_text() for c in clients}
    assert len(texts) == 1
    out = (texts.pop(), clients[0].get_length(), msgs)
    return out + (clients,) if return_clients else out


@pytest.mark.parametrize("seed", range(12))
def test_kernel_matches_oracle_fuzz(seed):
    text, length, msgs = collab_stream(seed)
    store = TensorStringStore(n_docs=2, capacity=512)
    store.apply_messages((1, m) for m in msgs)  # doc 1; doc 0 stays empty
    assert not store.overflowed().any()
    assert store.read_text(1) == text
    assert store.visible_length(1) == length
    assert store.read_text(0) == ""


@pytest.mark.parametrize("seed", [50, 51])
def test_kernel_matches_oracle_batched_incremental(seed):
    """State must thread correctly across many small apply calls."""
    text, length, msgs = collab_stream(seed, n_rounds=15)
    store = TensorStringStore(n_docs=1, capacity=512)
    rng = random.Random(seed)
    i = 0
    while i < len(msgs):
        step = rng.randint(1, 7)
        store.apply_messages((0, m) for m in msgs[i:i + step])
        i += step
    assert store.read_text(0) == text


def test_kernel_many_docs_parallel():
    """Independent documents merge independently in one batch."""
    streams = [collab_stream(seed, n_rounds=8) for seed in range(6)]
    store = TensorStringStore(n_docs=6, capacity=512)
    interleaved = []
    idx = [0] * 6
    rng = random.Random(0)
    while any(idx[d] < len(streams[d][2]) for d in range(6)):
        d = rng.randrange(6)
        if idx[d] < len(streams[d][2]):
            interleaved.append((d, streams[d][2][idx[d]]))
            idx[d] += 1
    store.apply_messages(interleaved)
    for d in range(6):
        assert store.read_text(d) == streams[d][0], f"doc {d}"


def test_kernel_compaction_preserves_text_and_frees_slots():
    text, _, msgs = collab_stream(3, n_rounds=25)
    store = TensorStringStore(n_docs=1, capacity=1024)
    store.apply_messages((0, m) for m in msgs)
    used_before = store.slot_usage()[0]
    max_seq = max(m.seq for m in msgs)
    store.compact(max_seq)  # whole window closed
    assert store.read_text(0) == text
    assert store.slot_usage()[0] <= used_before
    d_before = store.digests().copy()
    store.compact(max_seq)  # idempotent
    assert np.array_equal(store.digests(), d_before)


def test_kernel_overflow_flag_not_corruption():
    _, _, msgs = collab_stream(7, n_rounds=20)
    store = TensorStringStore(n_docs=1, capacity=8)  # absurdly small
    store.apply_messages((0, m) for m in msgs)
    assert store.overflowed()[0] == 1  # flagged, not crashed
    assert store.slot_usage()[0] <= 8


def test_kernel_digest_split_invariance():
    """Same content via different split histories digests identically."""
    from fluidframework_tpu.models.merge_tree_client import SequenceClient
    # store A: one insert of "abcdef"; store B: "abcdef" then remove+the same
    # content reinserted... simpler: two stores fed identical streams match
    text, _, msgs = collab_stream(9)
    s1 = TensorStringStore(1, 512)
    s2 = TensorStringStore(1, 512)
    s1.apply_messages((0, m) for m in msgs)
    for m in msgs:  # second store applies one-by-one (different batch shapes)
        s2.apply_messages([(0, m)])
    assert s1.read_text(0) == s2.read_text(0) == text
    assert np.array_equal(s1.digests(), s2.digests())


@pytest.mark.parametrize("seed", range(8))
def test_kernel_annotate_matches_oracle(seed):
    """Per-key LWW annotate on device: every visible position's property set
    must match the converged oracle replica (incl. None-deletes, concurrent
    annotates crossing removes/inserts, and split inheritance)."""
    text, length, msgs, clients = collab_stream(
        seed, with_annotates=True, return_clients=True)
    store = TensorStringStore(n_docs=1, capacity=512)
    store.apply_messages((0, m) for m in msgs)
    assert store.read_text(0) == text
    oracle = clients[0]
    for pos in range(length):
        seg, _ = oracle.tree.get_containing_segment(pos)
        want = {k: v for k, v in seg.props.items() if v is not None}
        assert store.get_properties(0, pos) == want, f"pos {pos}"


def test_kernel_annotate_survives_compaction():
    text, length, msgs, clients = collab_stream(
        11, with_annotates=True, return_clients=True, n_rounds=25)
    store = TensorStringStore(n_docs=1, capacity=1024)
    store.apply_messages((0, m) for m in msgs)
    store.compact(max(m.seq for m in msgs))
    assert store.read_text(0) == text
    oracle = clients[0]
    for pos in range(length):
        seg, _ = oracle.tree.get_containing_segment(pos)
        want = {k: v for k, v in seg.props.items() if v is not None}
        assert store.get_properties(0, pos) == want, f"pos {pos}"


@pytest.mark.parametrize("seed", range(6))
def test_store_intervals_match_oracle(seed):
    """Serving-side intervals (handle anchors, lazy slide, re-anchor at
    zamboni) must track the oracle IntervalCollection's endpoints through
    edit storms that remove anchor text."""
    from fluidframework_tpu.models.merge_tree import LOCAL_VIEW
    from fluidframework_tpu.models.interval_collection import (
        IntervalCollection,
    )
    rng = random.Random(seed)
    # phase 1: build a document
    text, length, msgs, clients = collab_stream(
        seed, n_rounds=10, return_clients=True)
    store = TensorStringStore(n_docs=1, capacity=1024)
    store.apply_messages((0, m) for m in msgs)
    oracle = clients[0]
    coll = IntervalCollection("c", oracle.tree)

    # anchors at random converged positions
    ivs = []
    for i in range(6):
        if length < 2:
            break
        s = rng.randrange(length - 1)
        e = rng.randint(s + 1, length - 1)
        coll.apply_add(f"iv{i}", s, e, {}, LOCAL_VIEW, oracle.client_id)
        ivs.append((f"iv{i}", store.add_interval(0, s, e)))

    def check(stage):
        for oid, sid in ivs:
            want = coll.endpoints(coll.get(oid))
            got = store.interval_endpoints(0, sid)
            assert got == want, (stage, oid, got, want)

    check("initial")

    # phase 2: more edits (removes cross the anchors), same stream to both
    from fluidframework_tpu.testing.mocks import MockSequencer
    seqr = MockSequencer()
    seqr.seq = max(m.seq for m in msgs)
    for c in clients:
        seqr.connect(c)
    more = []
    orig = seqr.process_one

    def capture():
        m = orig()
        if m is not None and m.type == MessageType.OP:
            more.append(m)
        return m
    seqr.process_one = capture
    for _ in range(40):
        c = rng.choice(clients)
        n = c.get_length()
        if n == 0 or rng.random() < 0.5:
            seqr.submit(c, c.insert_text_local(rng.randint(0, n),
                                               _rand_text(rng)))
        else:
            s = rng.randrange(n)
            seqr.submit(c, c.remove_range_local(
                s, rng.randint(s + 1, min(n, s + 8))))
        seqr.process_some(rng.randint(0, seqr.outstanding))
    seqr.process_all_messages()
    store.apply_messages((0, m) for m in more)
    check("after storm")

    # phase 3: close the window — zamboni both sides, anchors must slide
    # identically off the dropped tombstones
    max_seq = max(m.seq for m in more) if more else seqr.seq
    oracle.tree.zamboni(max_seq)
    store.compact(max_seq)
    check("after zamboni")
    assert store.read_text(0) == oracle.get_text()


def test_store_interval_snapshot_roundtrip():
    """Interval anchors, ids, and the window floor must survive
    snapshot/restore (the Summarizer resume path)."""
    text, length, msgs, _ = collab_stream(4, return_clients=True)
    store = TensorStringStore(1, 512)
    store.apply_messages((0, m) for m in msgs)
    iid = store.add_interval(0, 2, min(9, length - 1), {"note": "keep"})
    before = store.interval_endpoints(0, iid)
    restored = TensorStringStore.restore(store.snapshot())
    assert restored.interval_endpoints(0, iid) == before
    assert restored.intervals(0)[iid][2] == {"note": "keep"}
    assert (restored._iv_min_seq == store._iv_min_seq).all()
    # a fresh interval id allocated after restore must not collide
    iid2 = restored.add_interval(0, 0, 1)
    assert iid2 != iid
