"""Incremental summaries (SURVEY.md §2.16: handle reuse): a second
summary of a mostly-idle store must upload O(changed) bytes, and the
delta chain must restore bit-identically through load()."""

import pickle

import numpy as np
import pytest

from fluidframework_tpu.server import native_deli
from fluidframework_tpu.server.serving import StringServingEngine

pytestmark = pytest.mark.skipif(not native_deli.available(),
                                reason="native sequencer unavailable")


def _delta_bytes(summary: dict) -> int:
    """Serialized size of a summary EXCLUDING its by-reference base —
    what an incremental upload actually ships."""
    slim = {k: v for k, v in summary.items() if k != "base"}
    return len(pickle.dumps(slim))


def _mk(n_docs=1024, O=16):
    eng = StringServingEngine(n_docs=n_docs, capacity=128,
                              batch_window=10 ** 9, sequencer="native")
    docs = [f"doc-{i}" for i in range(n_docs)]
    for d in docs:
        eng.connect(d, 1)
        eng.doc_row(d)
    rows = np.array([eng.doc_row(d) for d in docs], np.int32)
    return eng, docs, rows, O, np.ones(n_docs, np.int64)


def _ingest(eng, rows, O, next_cseq, subset=None):
    """Insert-only batch for all rows (or a subset); ``next_cseq`` is the
    per-doc clientSeq cursor array, advanced in place."""
    idx = np.arange(len(rows)) if subset is None else \
        np.arange(len(rows))[subset]
    r = rows[idx]
    R = len(r)
    kind = np.zeros((R, O), np.int32)
    z = np.zeros((R, O), np.int32)
    cseq = (next_cseq[idx][:, None] +
            np.arange(O, dtype=np.int64)[None, :]).astype(np.int32)
    res = eng.ingest_planes(r, np.ones((R, O), np.int32), cseq, z,
                            kind, z, z, "abcd")
    assert res["nacked"] == 0
    next_cseq[idx] += O


def test_second_summary_of_idle_store_is_small():
    eng, docs, rows, O, nc = _mk()
    _ingest(eng, rows, O, nc)
    full = eng.summarize()
    full_bytes = _delta_bytes(full)
    # touch 5 of 1024 docs, then summarize incrementally
    _ingest(eng, rows, O, nc, subset=slice(0, 5))
    delta = eng.summarize(incremental=True)
    assert delta["kind"] == "delta"
    assert len(delta["store_delta"]["rows"]) == 5
    d_bytes = _delta_bytes(delta)
    # O(changed): the 5-row delta must be far below the 1024-row full
    assert d_bytes < full_bytes / 10, (d_bytes, full_bytes)

    # an untouched store's next delta carries ZERO rows: the store
    # payload vanishes entirely; what remains is the O(n_docs) protocol
    # metadata (sequencer checkpoint + doc-row map), which every summary
    # must carry fresh
    idle = eng.summarize(incremental=True)
    assert len(idle["store_delta"]["rows"]) == 0
    assert len(pickle.dumps(idle["store_delta"])) < 5000
    assert _delta_bytes(idle) < full_bytes / 10


def test_delta_chain_restores_exactly():
    eng, docs, rows, O, nc = _mk(n_docs=64)
    _ingest(eng, rows, O, nc)
    eng.summarize()
    _ingest(eng, rows, O, nc, subset=slice(0, 7))
    s1 = eng.summarize(incremental=True)
    _ingest(eng, rows, O, nc, subset=slice(5, 12))
    s2 = eng.summarize(incremental=True)  # chain: s2 -> s1 -> full
    # ops AFTER the last summary ride the log tail as usual
    _ingest(eng, rows, O, nc, subset=slice(60, 64))
    want = {d: eng.read_text(d) for d in docs}

    revived = StringServingEngine.load(s2, eng.log)
    # read_text is the semantic parity check; digests are identity-
    # sensitive (tail replay re-interns payloads at different handles)
    assert {d: revived.read_text(d) for d in docs} == want
    # sequencing resumes past the tail
    msg, nack = revived.submit(
        docs[0], 1, int(nc[0]), 0,
        {"mt": "insert", "kind": 0, "pos": 0, "text": "Z"})
    assert nack is None


def test_incremental_covers_rich_payload_tables():
    """Interner deltas: payload/props tables grow append-only; a delta
    must carry only the NEW entries and restore them."""
    from fluidframework_tpu.ops.schema import OpKind
    eng, docs, rows, O, nc = _mk(n_docs=32, O=8)
    texts0 = [f"t{k}" for k in range(O)]
    props0 = [{"b": 1}]
    R = len(rows)
    kind = np.zeros((R, O), np.int32)
    tidx = np.broadcast_to(np.arange(O, dtype=np.int32), (R, O)).copy()
    z = np.zeros((R, O), np.int32)
    cseq = np.broadcast_to(np.arange(1, O + 1, dtype=np.int32), (R, O))
    eng.ingest_planes(rows, np.ones((R, O), np.int32), cseq, z, kind,
                      z, z, texts=texts0, tidx=tidx, props=props0)
    full = eng.summarize()
    n_payloads = len(eng.store._payloads)

    texts1 = [f"u{k}" for k in range(O)]
    kind2 = kind.copy()
    kind2[:, -1] = int(OpKind.STR_ANNOTATE)
    a1 = z.copy()
    a1[:, -1] = 2
    props1 = [{"c": "red"}]
    tidx2 = tidx.copy()
    tidx2[:, -1] = 0
    cseq2 = cseq + O
    eng.ingest_planes(rows[:4], np.ones((4, O), np.int32), cseq2[:4],
                      z[:4], kind2[:4], z[:4], a1[:4],
                      texts=texts1, tidx=tidx2[:4], props=props1)
    delta = eng.summarize(incremental=True)
    assert len(delta["store_delta"]["payloads_delta"]) == \
        len(eng.store._payloads) - n_payloads
    want = {d: eng.read_text(d) for d in docs}
    revived = StringServingEngine.load(delta, eng.log)
    assert {d: revived.read_text(d) for d in docs} == want
    assert revived.get_properties(docs[0], 0) == \
        eng.get_properties(docs[0], 0)


def test_graduation_dirties_the_freed_row():
    """A doc that graduates off the flat tier frees its row; the next
    incremental summary must ship that row's (cleared or re-adopted)
    planes — stale clean-row reuse would resurrect the old doc."""
    eng, docs, rows, O, nc = _mk(n_docs=16)
    _ingest(eng, rows, O, nc)
    eng.summarize()
    # overflow doc 0 (capacity 128): per-op inserts of distinct chars
    eng.auto_recover = False
    for i in range(140):
        _, nack = eng.submit(docs[0], 1, O + 1 + i,
                             0, {"mt": "insert", "kind": 0, "pos": 0,
                                 "text": "Q"})
        assert nack is None
    eng.flush()
    report = eng.recover_overflowed()
    assert report.get(docs[0]) == "graduated", report
    delta = eng.summarize(incremental=True)
    freed_row = 0  # doc-0 held row 0
    assert freed_row in set(int(r) for r in delta["store_delta"]["rows"])
    want = {d: eng.read_text(d) for d in docs}
    revived = StringServingEngine.load(delta, eng.log)
    assert {d: revived.read_text(d) for d in docs} == want


def test_reupload_dirties_row_without_seq_delta():
    """Overflow re-upload (adopt_doc) rewrites a row's planes WITHOUT the
    doc sequencing anything new; the next incremental summary must ship
    that row anyway (review r4 finding)."""
    eng, docs, rows, O, nc = _mk(n_docs=16)
    _ingest(eng, rows, O, nc)
    eng.auto_recover = False
    # overflow doc 0 with tombstoned churn so the rebuild FITS (reupload)
    for i in range(140):
        _, nack = eng.submit(docs[0], 1, int(nc[0]) + i, 0,
                             {"mt": "insert", "kind": 0, "pos": 0,
                              "text": "Q"})
        assert nack is None
    nc[0] += 140
    for i in range(130):
        _, nack = eng.submit(docs[0], 1, int(nc[0]) + i, 140 + O,
                             {"mt": "remove", "start": 0, "end": 1})
        assert nack is None
    nc[0] += 130
    eng.flush()
    eng.heartbeat(docs[0], 1, eng.deli.doc_seq(docs[0]))
    eng.summarize()  # full summary AFTER the ops, BEFORE the re-upload
    report = eng.recover_overflowed()
    assert report.get(docs[0]) == "reuploaded", report
    delta = eng.summarize(incremental=True)
    assert 0 in set(int(r) for r in delta["store_delta"]["rows"])
    want = {d: eng.read_text(d) for d in docs}
    revived = StringServingEngine.load(delta, eng.log)
    assert {d: revived.read_text(d) for d in docs} == want


def test_chain_depth_cap_falls_back_to_full():
    eng, docs, rows, O, nc = _mk(n_docs=8, O=4)
    _ingest(eng, rows, O, nc)
    eng.max_incremental_chain = 2
    eng.summarize()
    for i in range(2):
        _ingest(eng, rows, O, nc, subset=slice(0, 1))
        assert eng.summarize(incremental=True)["kind"] == "delta"
    _ingest(eng, rows, O, nc, subset=slice(0, 1))
    assert eng.summarize(incremental=True)["kind"] == "full"  # cap hit
    assert eng.summarize(incremental=True)["kind"] == "delta"  # reset
