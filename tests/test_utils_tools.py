"""Telemetry, config provider, replay/fetch tools.
Reference behaviors per SURVEY.md §2.15, §5.1, §5.6, §2.18."""

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.runtime import ContainerRuntime
from fluidframework_tpu.server.tinylicious import LocalService
from fluidframework_tpu.tools import fetch_document, replay_document
from fluidframework_tpu.utils import (
    BufferSink, ConfigProvider, Histogram, MetricsCollector,
    SampledTelemetry, TelemetryLogger,
)


# ---------------------------------------------------------------- telemetry

class TestTelemetry:
    def test_child_logger_namespaces_and_props(self):
        sink = BufferSink()
        root = TelemetryLogger(sink, "fluid", {"docId": "d1"})
        child = root.child("runtime", {"dsId": "default"})
        child.send_event("opApply", seq=7)
        (e,) = sink.events
        assert e["eventName"] == "fluid:runtime:opApply"
        assert e["docId"] == "d1" and e["dsId"] == "default" and e["seq"] == 7

    def test_performance_event_emits_start_end_with_duration(self):
        sink = BufferSink()
        log = TelemetryLogger(sink)
        with log.performance_event("summarize", attempt=1):
            pass
        names = [e["eventName"] for e in sink.events]
        assert names == ["summarize_start", "summarize_end"]
        assert sink.events[1]["duration_ms"] >= 0

    def test_performance_event_cancel_on_error(self):
        sink = BufferSink()
        log = TelemetryLogger(sink)
        try:
            with log.performance_event("load"):
                raise ValueError("boom")
        except ValueError:
            pass
        assert [e["eventName"] for e in sink.events] == \
            ["load_start", "load_cancel"]
        assert "boom" in sink.events[1]["error"]

    def test_sampled_telemetry_aggregates(self):
        sink = BufferSink()
        s = SampledTelemetry(TelemetryLogger(sink), "opApply", rate=10)
        for i in range(25):
            s.record(2.0)
        assert len(sink.events) == 2          # two full windows of 10
        s.flush()
        assert sink.events[-1]["samples"] == 5 and \
            sink.events[-1]["mean"] == 2.0

    def test_error_logger_tags(self):
        sink = BufferSink()
        TelemetryLogger(sink).send_error("containerClose",
                                         RuntimeError("nope"))
        (e,) = sink.events
        assert e["category"] == "error" and e["errorType"] == "RuntimeError"

    def test_histogram_percentiles(self):
        h = Histogram(buckets_ms=[1, 2, 4, 8, 16])
        for v in [0.5] * 98 + [12.0, 12.0]:
            h.record(v)
        assert h.percentile(50) == 1
        assert h.percentile(99) == 16

    def test_metrics_collector_snapshot(self):
        m = MetricsCollector()
        m.inc("ops_merged", 128)
        m.inc("ops_merged", 64)
        m.observe("apply_latency", 1.5)
        snap = m.snapshot()
        assert snap["ops_merged"] == 192
        assert snap["apply_latency_count"] == 1
        assert snap["apply_latency_p99_ms"] >= 1.5


# ------------------------------------------------------------------- config

class TestConfigProvider:
    def test_precedence_override_env_file(self, tmp_path):
        path = tmp_path / "cfg.json"
        path.write_text('{"gc.enabled": false, "batch.max": 7}')
        cfg = ConfigProvider(
            overrides={"batch.max": 9},
            json_path=str(path),
            env={"FLUID_TPU_gc__enabled": "true"})
        assert cfg.get_bool("gc.enabled") is True      # env beats file
        assert cfg.get_int("batch.max") == 9           # override beats env
        assert cfg.get_int("missing", 3) == 3

    def test_typed_getters_coerce_strings(self):
        cfg = ConfigProvider(env={"FLUID_TPU_a": "off", "FLUID_TPU_b": "2.5"})
        assert cfg.get_bool("a", True) is False
        assert cfg.get_float("b") == 2.5
        assert cfg.get_str("a") == "off"

    def test_runtime_set_wins(self):
        cfg = ConfigProvider(env={})
        cfg.set("feature.x", True)
        assert cfg.get_bool("feature.x") is True


# ------------------------------------------------------------ fetch + replay

class TestReplayTool:
    def _make_recorded_doc(self, tmp_path):
        svc = LocalService()
        loader = Loader(LocalDocumentServiceFactory(svc),
                        ContainerRuntime.factory())
        a = loader.resolve("doc")
        m = a.runtime.create_data_store("default").create_channel("r", "map")
        for i in range(20):
            m.set(f"k{i}", i)
        s = a.runtime.get_data_store("default") \
            .create_channel("text", "sharedString")
        s.insert_text(0, "recorded history")
        service = LocalDocumentServiceFactory(svc) \
            .create_document_service("doc")
        out = str(tmp_path / "doc")
        n = fetch_document(service, out)
        assert n > 20
        return out

    def test_fetch_then_replay_full_history(self, tmp_path):
        recorded = self._make_recorded_doc(tmp_path)
        container, stats = replay_document(recorded)
        ds = container.runtime.get_data_store("default")
        assert ds.get_channel("r").get("k19") == 19
        assert ds.get_channel("text").get_text() == "recorded history"
        assert stats.ops_replayed == stats.last_seq  # no summary: full replay
        assert stats.ops_per_sec > 0

    def test_replay_prefix_with_to_seq(self, tmp_path):
        recorded = self._make_recorded_doc(tmp_path)
        full, _ = replay_document(recorded)
        full_text = full.runtime.get_data_store("default") \
            .get_channel("text").get_text()
        partial, stats = replay_document(recorded, to_seq=10)
        assert stats.last_seq == 10
        pds = partial.runtime.get_data_store("default")
        assert pds.get_channel("r").get("k19") is None
        assert full_text == "recorded history"

    def test_cli_main(self, tmp_path, capsys):
        from fluidframework_tpu.tools.replay import main
        recorded = self._make_recorded_doc(tmp_path)
        assert main([recorded]) == 0
        out = capsys.readouterr().out
        assert "ops_per_sec=" in out and "doc=doc" in out


# ------------------------------------------------------------------ devtools

class TestDevtools:
    def test_inspect_container(self):
        from fluidframework_tpu.framework import LocalClient
        from fluidframework_tpu.tools.devtools import inspect_container
        client = LocalClient()
        fc, doc_id = client.create_container(
            {"initialObjects": {"text": "sharedString", "m": "map"}})
        fc.initial_objects["text"].insert_text(0, "hello")
        fc.initial_objects["m"].set("k", 1)
        view = inspect_container(fc.container)
        assert view["state"] in ("LOADED", "CONNECTED")
        assert view["connected"] is True
        assert view["lastSeq"] >= 1
        channels = view["dataStores"]["default"]["channels"]
        assert channels["text"]["type"] == "sharedString"
        assert channels["text"]["length"] == 5
        assert channels["m"]["keys"] == 1
        assert view["pendingOps"] == 0  # local service delivers synchronously

    def test_inspect_engine_metrics(self):
        from fluidframework_tpu.models.merge_tree_client import SequenceClient
        from fluidframework_tpu.server.serving import StringServingEngine
        from fluidframework_tpu.tools.devtools import inspect_engine
        engine = StringServingEngine(n_docs=2, capacity=128, batch_window=4)
        engine.connect("d", 1)
        c = SequenceClient(1)
        for i in range(9):
            op = c.insert_text_local(c.get_length(), "ab")
            msg, _ = engine.submit("d", 1, op["clientSeq"],
                                   c.last_processed_seq, op)
            c.apply_msg(msg)
        # a nack for the metrics counter
        engine.submit("d", 99, 1, 0, {"mt": "remove", "start": 0, "end": 1})
        engine.flush()
        view = inspect_engine(engine)
        assert view["documents"] == ["d"]
        m = view["metrics"]
        assert m["ops_ingested"] == 9
        assert m["nacks"] == 1 and m["nacks_unknown_client"] == 1
        assert m["ops_flushed"] == 9 and m["flushes"] >= 2
        assert m["flush_ms_count"] >= 2 and m["flush_ms_p99_ms"] > 0
        assert view["slotUsage"]["max"] >= 1
        assert view["overflowedDocs"] == []


# ----------------------------------------------------- bench report tool

class TestBenchReport:
    """``tools/bench_report.py`` must run clean on the checked-in driver
    record (BENCH_r05.json: the wrapper shape whose ``tail`` is a stdout
    STRING with the bench JSON as its last line)."""

    def _mod(self):
        import importlib.util
        from pathlib import Path
        path = Path(__file__).parent.parent / "tools" / "bench_report.py"
        spec = importlib.util.spec_from_file_location("bench_report", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        return mod

    def test_regenerates_config4_from_r05(self, tmp_path):
        import json
        import shutil
        from pathlib import Path
        mod = self._mod()
        root = Path(__file__).parent.parent
        # work on a copy: the tool must never touch the real BENCHES.md
        # from a test run
        shutil.copy(root / "BENCHES.md", tmp_path / "BENCHES.md")
        shutil.copy(root / "BENCH_r05.json", tmp_path / "BENCH_r05.json")
        block = mod.regenerate(tmp_path, tmp_path / "BENCH_r05.json",
                               write=True)
        rec = json.loads(block)
        assert rec["metric"] == "sharedstring_ops_per_sec_merged"
        assert rec["value"] == 7283596.5
        assert rec["serving_interval_ops_per_sec"] == 1516.7
        assert rec["rich_pack_p50_ms"] == 100.0
        updated = (tmp_path / "BENCHES.md").read_text()
        assert block in updated
        # only the Config #4 fence changed; the other sections survive
        assert "## Config #5" in updated and "## Config #2" in updated
        assert "config2_sharedmap_ops_per_sec" in updated

    def test_latest_record_discovery_and_cli(self, tmp_path):
        import shutil
        import subprocess
        import sys
        from pathlib import Path
        mod = self._mod()
        root = Path(__file__).parent.parent
        for name in ("BENCH_r01.json", "BENCH_r05.json"):
            shutil.copy(root / name, tmp_path / name)
        assert mod.find_latest_record(tmp_path).name == "BENCH_r05.json"
        shutil.copy(root / "BENCHES.md", tmp_path / "BENCHES.md")
        out = subprocess.run(
            [sys.executable, str(root / "tools" / "bench_report.py"),
             "--root", str(tmp_path)],
            capture_output=True, text=True, timeout=60)
        assert out.returncode == 0, out.stderr
        assert '"sharedstring_ops_per_sec_merged"' in out.stdout
