"""Telemetry, config provider, replay/fetch tools.
Reference behaviors per SURVEY.md §2.15, §5.1, §5.6, §2.18."""

from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.runtime import ContainerRuntime
from fluidframework_tpu.server.tinylicious import LocalService
from fluidframework_tpu.tools import fetch_document, replay_document
from fluidframework_tpu.utils import (
    BufferSink, ConfigProvider, Histogram, MetricsCollector,
    SampledTelemetry, TelemetryLogger,
)


# ---------------------------------------------------------------- telemetry

class TestTelemetry:
    def test_child_logger_namespaces_and_props(self):
        sink = BufferSink()
        root = TelemetryLogger(sink, "fluid", {"docId": "d1"})
        child = root.child("runtime", {"dsId": "default"})
        child.send_event("opApply", seq=7)
        (e,) = sink.events
        assert e["eventName"] == "fluid:runtime:opApply"
        assert e["docId"] == "d1" and e["dsId"] == "default" and e["seq"] == 7

    def test_performance_event_emits_start_end_with_duration(self):
        sink = BufferSink()
        log = TelemetryLogger(sink)
        with log.performance_event("summarize", attempt=1):
            pass
        names = [e["eventName"] for e in sink.events]
        assert names == ["summarize_start", "summarize_end"]
        assert sink.events[1]["duration_ms"] >= 0

    def test_performance_event_cancel_on_error(self):
        sink = BufferSink()
        log = TelemetryLogger(sink)
        try:
            with log.performance_event("load"):
                raise ValueError("boom")
        except ValueError:
            pass
        assert [e["eventName"] for e in sink.events] == \
            ["load_start", "load_cancel"]
        assert "boom" in sink.events[1]["error"]

    def test_sampled_telemetry_aggregates(self):
        sink = BufferSink()
        s = SampledTelemetry(TelemetryLogger(sink), "opApply", rate=10)
        for i in range(25):
            s.record(2.0)
        assert len(sink.events) == 2          # two full windows of 10
        s.flush()
        assert sink.events[-1]["samples"] == 5 and \
            sink.events[-1]["mean"] == 2.0

    def test_error_logger_tags(self):
        sink = BufferSink()
        TelemetryLogger(sink).send_error("containerClose",
                                         RuntimeError("nope"))
        (e,) = sink.events
        assert e["category"] == "error" and e["errorType"] == "RuntimeError"

    def test_histogram_percentiles(self):
        h = Histogram(buckets_ms=[1, 2, 4, 8, 16])
        for v in [0.5] * 98 + [12.0, 12.0]:
            h.record(v)
        assert h.percentile(50) == 1
        assert h.percentile(99) == 16

    def test_metrics_collector_snapshot(self):
        m = MetricsCollector()
        m.inc("ops_merged", 128)
        m.inc("ops_merged", 64)
        m.observe("apply_latency", 1.5)
        snap = m.snapshot()
        assert snap["ops_merged"] == 192
        assert snap["apply_latency_count"] == 1
        assert snap["apply_latency_p99_ms"] >= 1.5


# ------------------------------------------------------------------- config

class TestConfigProvider:
    def test_precedence_override_env_file(self, tmp_path):
        path = tmp_path / "cfg.json"
        path.write_text('{"gc.enabled": false, "batch.max": 7}')
        cfg = ConfigProvider(
            overrides={"batch.max": 9},
            json_path=str(path),
            env={"FLUID_TPU_gc__enabled": "true"})
        assert cfg.get_bool("gc.enabled") is True      # env beats file
        assert cfg.get_int("batch.max") == 9           # override beats env
        assert cfg.get_int("missing", 3) == 3

    def test_typed_getters_coerce_strings(self):
        cfg = ConfigProvider(env={"FLUID_TPU_a": "off", "FLUID_TPU_b": "2.5"})
        assert cfg.get_bool("a", True) is False
        assert cfg.get_float("b") == 2.5
        assert cfg.get_str("a") == "off"

    def test_runtime_set_wins(self):
        cfg = ConfigProvider(env={})
        cfg.set("feature.x", True)
        assert cfg.get_bool("feature.x") is True


# ------------------------------------------------------------ fetch + replay

class TestReplayTool:
    def _make_recorded_doc(self, tmp_path):
        svc = LocalService()
        loader = Loader(LocalDocumentServiceFactory(svc),
                        ContainerRuntime.factory())
        a = loader.resolve("doc")
        m = a.runtime.create_data_store("default").create_channel("r", "map")
        for i in range(20):
            m.set(f"k{i}", i)
        s = a.runtime.get_data_store("default") \
            .create_channel("text", "sharedString")
        s.insert_text(0, "recorded history")
        service = LocalDocumentServiceFactory(svc) \
            .create_document_service("doc")
        out = str(tmp_path / "doc")
        n = fetch_document(service, out)
        assert n > 20
        return out

    def test_fetch_then_replay_full_history(self, tmp_path):
        recorded = self._make_recorded_doc(tmp_path)
        container, stats = replay_document(recorded)
        ds = container.runtime.get_data_store("default")
        assert ds.get_channel("r").get("k19") == 19
        assert ds.get_channel("text").get_text() == "recorded history"
        assert stats.ops_replayed == stats.last_seq  # no summary: full replay
        assert stats.ops_per_sec > 0

    def test_replay_prefix_with_to_seq(self, tmp_path):
        recorded = self._make_recorded_doc(tmp_path)
        full, _ = replay_document(recorded)
        full_text = full.runtime.get_data_store("default") \
            .get_channel("text").get_text()
        partial, stats = replay_document(recorded, to_seq=10)
        assert stats.last_seq == 10
        pds = partial.runtime.get_data_store("default")
        assert pds.get_channel("r").get("k19") is None
        assert full_text == "recorded history"

    def test_cli_main(self, tmp_path, capsys):
        from fluidframework_tpu.tools.replay import main
        recorded = self._make_recorded_doc(tmp_path)
        assert main([recorded]) == 0
        out = capsys.readouterr().out
        assert "ops_per_sec=" in out and "doc=doc" in out


# ------------------------------------------------------------------ devtools

class TestDevtools:
    def test_inspect_container(self):
        from fluidframework_tpu.framework import LocalClient
        from fluidframework_tpu.tools.devtools import inspect_container
        client = LocalClient()
        fc, doc_id = client.create_container(
            {"initialObjects": {"text": "sharedString", "m": "map"}})
        fc.initial_objects["text"].insert_text(0, "hello")
        fc.initial_objects["m"].set("k", 1)
        view = inspect_container(fc.container)
        assert view["state"] in ("LOADED", "CONNECTED")
        assert view["connected"] is True
        assert view["lastSeq"] >= 1
        channels = view["dataStores"]["default"]["channels"]
        assert channels["text"]["type"] == "sharedString"
        assert channels["text"]["length"] == 5
        assert channels["m"]["keys"] == 1
        assert view["pendingOps"] == 0  # local service delivers synchronously

    def test_inspect_engine_metrics(self):
        from fluidframework_tpu.models.merge_tree_client import SequenceClient
        from fluidframework_tpu.server.serving import StringServingEngine
        from fluidframework_tpu.tools.devtools import inspect_engine
        engine = StringServingEngine(n_docs=2, capacity=128, batch_window=4)
        engine.connect("d", 1)
        c = SequenceClient(1)
        for i in range(9):
            op = c.insert_text_local(c.get_length(), "ab")
            msg, _ = engine.submit("d", 1, op["clientSeq"],
                                   c.last_processed_seq, op)
            c.apply_msg(msg)
        # a nack for the metrics counter
        engine.submit("d", 99, 1, 0, {"mt": "remove", "start": 0, "end": 1})
        engine.flush()
        view = inspect_engine(engine)
        assert view["documents"] == ["d"]
        m = view["metrics"]
        assert m["ops_ingested"] == 9
        assert m["nacks"] == 1 and m["nacks_unknown_client"] == 1
        assert m["ops_flushed"] == 9 and m["flushes"] >= 2
        assert m["flush_ms_count"] >= 2 and m["flush_ms_p99_ms"] > 0
        assert view["slotUsage"]["max"] >= 1
        assert view["overflowedDocs"] == []
