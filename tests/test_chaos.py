"""Fault-injection drills: seeded crashes, torn writes, wire byzantium,
and visible degradation — the harness half lives in ``testing.chaos``;
this file pins the coverage the PR promises:

- crash-restart at every registered in-engine site × every DDS family,
  asserting the full recovery contract (no acked op lost, deterministic
  replay, monotone seqs, cross-replica convergence);
- torn spill tails and torn checkpoints (mid-``write(2)`` kills) are
  truncated / rolled back, never parsed as data;
- byzantine wire input (duplicated / reordered / corrupted frames) is
  nacked or evicted — the sequenced stream stays clean;
- degradation (replica overflow, injected apply stalls) sheds load
  VISIBLY through metrics + telemetry, never silently.

Tier-1 runs the deterministic grid; wide random sweeps ride behind
``-m slow``."""

import socket

import pytest

from fluidframework_tpu.core.protocol import MessageType
from fluidframework_tpu.server import wire
from fluidframework_tpu.server.deli import NackReason
from fluidframework_tpu.server.ingress import AlfredServer
from fluidframework_tpu.testing import chaos
from fluidframework_tpu.utils.faultpoints import (
    SITE_SUMMARIZER_POST_UPLOAD, CrashInjected, armed,
)
from fluidframework_tpu.utils.telemetry import BufferSink, TelemetryLogger

pytestmark = pytest.mark.chaos


# ------------------------------------------------- crash-restart drills

GRID = [(f, s) for f in chaos.FAMILIES for s in chaos.CRASH_SITES]


@pytest.mark.parametrize("family,site", GRID,
                         ids=[f"{f}-{s}" for f, s in GRID])
def test_crash_drill(family, site):
    """Every family survives a kill at every in-engine site; the drill
    itself asserts the recovery invariants — here we pin that the fault
    actually fired mid-traffic (acked ops exist on both sides of it)."""
    seed = 100 + GRID.index((family, site))
    report = chaos.run_crash_drill(seed, family=family, site=site)
    assert report["family"] == family and report["site"] == site
    assert report["logged"] >= 8  # phase A is always durable
    assert report["crashed_at"] is not None


@pytest.mark.parametrize("seed", [11, 12])
def test_spill_torn_tail_drill(seed, tmp_path):
    report = chaos.run_spill_drill(seed, str(tmp_path / f"s{seed}"))
    assert report["recovered"] >= report["acked"] >= 1


@pytest.mark.parametrize("seed", [21, 22])
def test_checkpoint_atomicity_drill(seed, tmp_path):
    chaos.run_checkpoint_drill(seed, str(tmp_path / "deli.ckpt.json"))


@pytest.mark.slow
def test_crash_drill_random_sweep():
    """Seeded but unpinned: random (family, site, schedule) combinations
    well past the deterministic grid."""
    for seed in range(1000, 1040):
        chaos.run_crash_drill(seed)


# -------------------------------------------- torn bytes, not torn luck

def test_spill_byte_corruption(tmp_path):
    """Recovery distinguishes a torn TAIL (crash artifact: drop +
    truncate) from corruption MID-file (disk rot: refuse loudly)."""
    from fluidframework_tpu.server.oplog import PartitionedLog
    log = PartitionedLog(1, str(tmp_path), "t")
    engine = chaos.make_engine("string", log=log)
    engine.connect("d", 1)
    for i in range(6):
        msg, nack = engine.submit("d", 1, i + 1, 0,
                                  {"mt": "insert", "kind": 0, "pos": 0,
                                   "text": f"w{i}"})
        assert nack is None
    log.close()
    path = tmp_path / "t-p0.jsonl"
    clean = path.read_bytes()
    n_records = clean.count(b"\n")  # 6 ops + the JOIN from connect
    assert n_records >= 7

    # garbage appended past the last record = torn tail: dropped, truncated
    path.write_bytes(clean + b'{"type": 0, "doc_id": "d", "cl')
    recovered = PartitionedLog.recover(1, str(tmp_path), "t")
    assert recovered.size(0) == n_records
    assert path.read_bytes() == clean  # file truncated back to clean
    recovered.close()

    # the same garbage mid-file is NOT a crash signature: hard error
    lines = clean.splitlines(keepends=True)
    path.write_bytes(lines[0] + b'{"rot":' + b"".join(lines[2:]))
    with pytest.raises(ValueError, match="mid-file"):
        PartitionedLog.recover(1, str(tmp_path), "t")


# --------------------------------------------- summarizer crash window

def _make_doc():
    from fluidframework_tpu.drivers import LocalDocumentServiceFactory
    from fluidframework_tpu.loader import Loader
    from fluidframework_tpu.runtime import ContainerRuntime, SummaryManager
    from fluidframework_tpu.server.tinylicious import LocalService
    svc = LocalService()
    loader = Loader(LocalDocumentServiceFactory(svc),
                    ContainerRuntime.factory())
    a, b = loader.resolve("doc"), loader.resolve("doc")
    return a, SummaryManager(a), SummaryManager(b)


def test_summarizer_killed_between_upload_and_proposal():
    """The upload lands, the SUMMARIZE proposal never sequences: the blob
    is an orphan, nothing is in flight, and a restarted summarize runs
    from the last ACKED summary as if the orphan never happened."""
    a, ma, _ = _make_doc()
    m = a.runtime.create_data_store("default").create_channel("r", "map")
    m.set("k", 1)
    plan = chaos.FaultPlan(crash={SITE_SUMMARIZER_POST_UPLOAD: 1})
    with armed(plan):
        with pytest.raises(CrashInjected):
            ma.summarize_now()
    assert plan.fired == [SITE_SUMMARIZER_POST_UPLOAD]
    assert not ma._in_flight          # a dead manager holds no lease
    assert ma.summaries_acked == 0    # no ack ever references the orphan
    ma.summarize_now()                # the retry proposes + acks cleanly
    assert ma.summaries_acked == 1 and not ma._in_flight


# -------------------------------------------------- byzantine wire input

@pytest.fixture()
def server():
    srv = AlfredServer(port=0).start_in_thread()
    yield srv
    srv.stop()


def _connect(port: int, doc: str) -> socket.socket:
    s = socket.create_connection(("127.0.0.1", port))
    s.settimeout(10)
    s.sendall(wire.encode_frame({"t": "connect", "doc": doc}))
    hello = wire.recv_frame(s)
    assert hello["t"] == "connected"
    return s


def _op(client_seq: int, n: int) -> bytes:
    return wire.encode_frame({"t": "op", "client_seq": client_seq,
                              "contents": {"n": n},
                              "type": int(MessageType.OP), "ref_seq": 0})


def test_duplicated_frame_nacked_not_resequenced(server):
    """At-least-once ingress replays a frame: Deli dedupes on clientSeq —
    one sequenced op, an idempotent dup-ack carrying the ORIGINAL seq
    (ISSUE 9 durable dedup ledger), stream continues."""
    with _connect(server.port, "dup") as s:
        s.sendall(_op(1, 1))
        first = wire.recv_frame(s)
        assert first["t"] == "op" and first["msg"]["client_seq"] == 1
        s.sendall(_op(1, 1))  # the replay
        nack = wire.recv_frame(s)
        assert nack["t"] == "dup_ack"
        assert nack["client_seq"] == 1
        assert nack["seq"] == first["msg"]["seq"]
        s.sendall(_op(2, 2))
        nxt = wire.recv_frame(s)
        assert nxt["t"] == "op"
        assert nxt["msg"]["seq"] == first["msg"]["seq"] + 1
    ops = [m for m in server.service.get_deltas("dup", 0)
           if m.type == MessageType.OP]
    assert [m.contents["n"] for m in ops] == [1, 2]  # no double apply


def test_reordered_frames_gap_nacked_then_converge(server):
    """clientSeq 2 arrives before 1 (network reorder): the gap is nacked
    — never sequenced out of order — and the in-order resend converges."""
    with _connect(server.port, "gap") as s:
        s.sendall(_op(2, 2))
        nack = wire.recv_frame(s)
        assert nack["t"] == "nack"
        assert nack["reason"] == int(NackReason.CLIENT_SEQ_GAP)
        s.sendall(_op(1, 1))
        s.sendall(_op(2, 2))
        got = [wire.recv_frame(s), wire.recv_frame(s)]
        assert [g["t"] for g in got] == ["op", "op"]
        assert [g["msg"]["client_seq"] for g in got] == [1, 2]
    ops = [m for m in server.service.get_deltas("gap", 0)
           if m.type == MessageType.OP]
    assert [m.contents["n"] for m in ops] == [1, 2]


def test_corrupted_op_frame_evicts_connection_only(server):
    """A CRC-corrupt op frame after a healthy one: this connection gets a
    diagnostic + close; the already-sequenced op and the service survive."""
    with _connect(server.port, "crc") as s:
        s.sendall(_op(1, 1))
        assert wire.recv_frame(s)["t"] == "op"
        frame = bytearray(_op(2, 2))
        frame[-1] ^= 0xFF
        s.sendall(bytes(frame))
        err = wire.recv_frame(s)
        assert err["t"] == "error" and "CRC" in err["message"]
        assert s.recv(1024) == b""  # dropped
    ops = [m for m in server.service.get_deltas("crc", 0)
           if m.type == MessageType.OP]
    assert [m.contents["n"] for m in ops] == [1]
    # the service still accepts fresh connections afterwards; a fresh
    # client catches up via deltas (its refSeq must clear the doc's MSN,
    # which advanced when the evicted client left)
    with _connect(server.port, "crc") as s2:
        s2.sendall(wire.encode_frame({"t": "deltas", "doc": "crc"}))
        tail = max(m["seq"] for m in wire.recv_frame(s2)["msgs"])
        s2.sendall(wire.encode_frame(
            {"t": "op", "client_seq": 1, "contents": {"n": 10},
             "type": int(MessageType.OP), "ref_seq": tail}))
        assert wire.recv_frame(s2)["t"] == "op"


# -------------------------------------------------- visible degradation

def test_replica_full_sheds_visibly():
    """One store row, two string channels: the second is shed from the
    device replica — counted, warned, listed — while ordering/broadcast
    (and thus the clients) stay fully correct."""
    from fluidframework_tpu.framework import LocalClient
    from fluidframework_tpu.server.serving_service import ServingLocalService
    svc = ServingLocalService(n_docs=1, capacity=256)
    sink = BufferSink()
    svc.telemetry = TelemetryLogger(sink, "servingService")
    client = LocalClient(service=svc)
    schema = {"initialObjects": {"a": "sharedString", "b": "sharedString"}}
    c, doc_id = client.create_container(schema)
    c.initial_objects["a"].insert_text(0, "served")
    c.initial_objects["b"].insert_text(0, "shed")
    c.initial_objects["b"].insert_text(4, "!")

    assert svc.read_text(doc_id, "a") == "served"  # admitted row serves
    assert svc.metrics.counters["replica_channels_dropped"] == 1
    assert svc.metrics.counters["replica_ops_dropped"] >= 2
    assert svc.dropped_channels() == [(doc_id, "default", "b")]
    warns = sink.named("replicaChannelDropped")
    assert warns and warns[0]["channel"] == "b" \
        and warns[0]["capacity"] == 1
    with pytest.raises(KeyError):
        svc.read_text(doc_id, "b")  # degraded read is an error, not junk
    # the ordering service itself never shed anything
    assert c.initial_objects["b"].get_text() == "shed!"


@pytest.mark.parametrize("family", ["string", "map"])
def test_injected_apply_stall_is_observable(family):
    report = chaos.run_stall_drill(31, family=family)
    assert report["stalls"] >= 1 and report["events"] >= 1


# ------------------------------------- pipelined ingest crash drill

def test_pipelined_crash_between_sequencing_and_append():
    """ISSUE 6 drill: with several waves in flight in the staged ingest
    pipeline, crash the seq worker AFTER native sequencing but BEFORE
    the wave's durable append (``SITE_INGEST_MID_BATCH``). The recovery
    contract must hold across the overlap: every ACKED wave is durably
    logged; the crashed wave's seqs exist nowhere durable; the engine
    stays poisoned (refuses summaries) until rebuilt; and two rebuilds
    from the same summary + log converge byte-for-byte."""
    import numpy as np

    from fluidframework_tpu.server import native_deli
    if not native_deli.available():
        pytest.skip("native sequencer unavailable")
    from fluidframework_tpu.ops.merge_tree_kernel import string_state_digest
    from fluidframework_tpu.server.ingest_pipeline import (
        PipelinedIngestExecutor,
    )
    from fluidframework_tpu.server.serving import StringServingEngine
    from fluidframework_tpu.testing.synthetic import typing_storm
    from fluidframework_tpu.utils.faultpoints import SITE_INGEST_MID_BATCH

    R, O = 4, 4
    eng = StringServingEngine(n_docs=R, capacity=256,
                              batch_window=10 ** 9, sequencer="native")
    docs = [f"d{i}" for i in range(R)]
    for d in docs:
        eng.connect(d, 1)
    summary0 = eng.summarize()  # recovery replays the whole storm tail
    rows = np.array([eng.doc_row(d) for d in docs], np.int32)
    client = np.ones((R, O), np.int32)

    CRASH_WAVE = 2                      # 0-based; third sequencing hit
    plan = chaos.FaultPlan(crash={SITE_INGEST_MID_BATCH: CRASH_WAVE + 1})
    ex = PipelinedIngestExecutor(eng, depth=2)
    tickets = []
    seq = 1
    with armed(plan):
        for b in range(5):
            planes, seq = typing_storm(R, O, seed=b, start_seq=seq)
            cs = np.broadcast_to(
                np.arange(b * O + 1, (b + 1) * O + 1, dtype=np.int32),
                (R, O))
            try:
                tickets.append(ex.submit(rows, client, cs, cs,
                                         planes["kind"], planes["a0"],
                                         planes["a1"], text="ab"))
            except RuntimeError:
                break  # fail-stop: the executor already refused new work
        with pytest.raises(RuntimeError) as ei:
            ex.drain()
    assert plan.fired == [SITE_INGEST_MID_BATCH]
    assert isinstance(ei.value.__cause__, CrashInjected)

    # acks are exactly the pre-crash waves; everything after fails
    acked_waves, acked_keys = [], set()
    for b, t in enumerate(tickets):
        if b < CRASH_WAVE:
            res = t.result(timeout=5)
            assert res["nacked"] == 0
            acked_waves.append(b)
            for d in docs:
                for c in range(O):
                    acked_keys.add((d, b * O + c + 1))
        else:
            err = t.error()
            assert err is not None, f"wave {b} must not ack past a crash"
            if b == CRASH_WAVE:
                assert isinstance(err, CrashInjected)
    assert acked_waves == list(range(CRASH_WAVE))

    # no acked op lost / no phantom seqs: the durable log holds exactly
    # the acked waves' ops — none of the crashed wave's sequenced cseqs
    logged = {(m.doc_id, m.client_seq) for m in chaos.logged_ops(eng)}
    assert acked_keys <= logged
    crashed_keys = {(d, CRASH_WAVE * O + c + 1)
                    for d in docs for c in range(O)}
    assert not (crashed_keys & logged)

    # the victim is poisoned by design: device/sequencer state is ahead
    # of the log, so summaries (and new ingest) must be refused
    with pytest.raises(RuntimeError, match="poisoned"):
        eng.summarize()
    with pytest.raises(RuntimeError):
        ex.submit(rows, client, client, client,
                  np.zeros((R, O), np.int32), np.zeros((R, O), np.int32),
                  np.zeros((R, O), np.int32), text="x")
    ex.close()

    # deterministic replay: two independent rebuilds from the same
    # summary + log converge, carry every acked op, and the crashed
    # wave's seqs are gone (doc seq == the acked tail)
    twins = [StringServingEngine.load(summary0, eng.log,
                                      sequencer="native")
             for _ in range(2)]
    d0 = np.asarray(string_state_digest(twins[0].store.state))
    d1 = np.asarray(string_state_digest(twins[1].store.state))
    assert (d0 == d1).all()
    for d in docs:
        assert twins[0].read_text(d) == twins[1].read_text(d)
    # post-recovery ingest resumes exactly after the acked tail
    t0 = twins[0]
    base = t0.deli.doc_seq(docs[0])
    msg, nack = t0.submit(docs[0], 1, CRASH_WAVE * O + 1, base,
                          {"mt": "insert", "kind": 0, "pos": 0,
                           "text": "z", "clientSeq": CRASH_WAVE * O + 1})
    assert nack is None and msg.seq == base + 1


def test_tree_pipelined_crash_between_sequencing_and_append():
    """ISSUE 7 drill: the tree record pipeline under the same crash
    window — waves of pre-encoded tree batches in flight through the
    staged executor, seq worker killed after native sequencing but
    before the wave's durable TreeRecordOps append. Acked ⊆ logged,
    the crashed wave's seqs exist nowhere durable, the victim stays
    poisoned, and two rebuilds from summary + log converge."""
    import numpy as np

    from fluidframework_tpu.server import native_deli
    if not native_deli.available():
        pytest.skip("native sequencer unavailable")
    from fluidframework_tpu.ops.tree_kernel import tree_state_digest
    from fluidframework_tpu.server.ingest_pipeline import (
        PipelinedIngestExecutor,
    )
    from fluidframework_tpu.server.serving import TreeServingEngine
    from fluidframework_tpu.server.tree_wire import encode_tree_batch
    from fluidframework_tpu.utils.faultpoints import SITE_INGEST_MID_BATCH

    R = 4
    eng = TreeServingEngine(n_docs=R, capacity=64,
                            batch_window=10 ** 9, sequencer="native")
    docs = [f"t{i}" for i in range(R)]
    for d in docs:
        eng.connect(d, 1)
    summary0 = eng.summarize()

    def wave_batch(w):
        ops = []
        for d in docs:
            if w == 0:
                ops.append({"op": "insert", "parent": "root",
                            "field": "kids", "after": None,
                            "nodes": [{"id": f"{d}-n0", "value": 0}]})
            else:
                ops.append({"op": "insert", "parent": "root",
                            "field": "kids", "after": f"{d}-n{w - 1}",
                            "nodes": [{"id": f"{d}-n{w}", "value": w}]})
        return encode_tree_batch(ops)

    CRASH_WAVE = 2                      # 0-based; third sequencing hit
    plan = chaos.FaultPlan(crash={SITE_INGEST_MID_BATCH: CRASH_WAVE + 1})
    ex = PipelinedIngestExecutor(eng, depth=2)
    tickets = []
    with armed(plan):
        for b in range(5):
            try:
                tickets.append(ex.submit(docs, [1] * R, [b + 1] * R,
                                         [0] * R, wave_batch(b)))
            except RuntimeError:
                break  # fail-stop: the executor already refused new work
        with pytest.raises(RuntimeError) as ei:
            ex.drain()
    assert plan.fired == [SITE_INGEST_MID_BATCH]
    assert isinstance(ei.value.__cause__, CrashInjected)

    # acks are exactly the pre-crash waves; everything after fails
    acked_keys = set()
    for b, t in enumerate(tickets):
        if b < CRASH_WAVE:
            res = t.result(timeout=5)
            assert res["nacked"] == 0
            acked_keys.update((d, b + 1) for d in docs)
        else:
            err = t.error()
            assert err is not None, f"wave {b} must not ack past a crash"
            if b == CRASH_WAVE:
                assert isinstance(err, CrashInjected)

    # no acked op lost / no phantom seqs in the durable record stream
    logged = {(m.doc_id, m.client_seq) for m in chaos.logged_ops(eng)}
    assert acked_keys <= logged
    crashed_keys = {(d, CRASH_WAVE + 1) for d in docs}
    assert not (crashed_keys & logged)

    # the victim is poisoned: device/sequencer state is ahead of the log
    with pytest.raises(RuntimeError, match="poisoned"):
        eng.summarize()
    with pytest.raises(RuntimeError):
        ex.submit(docs, [1] * R, [9] * R, [0] * R, wave_batch(0))
    ex.close()

    # deterministic replay: two independent rebuilds converge, carry
    # every acked wave's nodes, and none of the crashed wave's
    twins = [TreeServingEngine.load(summary0, eng.log,
                                    sequencer="native")
             for _ in range(2)]
    d0 = np.asarray(tree_state_digest(twins[0].store.state))
    d1 = np.asarray(tree_state_digest(twins[1].store.state))
    assert (d0 == d1).all()
    for d in docs:
        assert twins[0].to_dict(d) == twins[1].to_dict(d)
        for b in range(CRASH_WAVE):
            assert twins[0].has_node(d, f"{d}-n{b}"), (d, b)
        assert not twins[0].has_node(d, f"{d}-n{CRASH_WAVE}")
    # post-recovery ingest resumes exactly after the acked tail
    t0 = twins[0]
    base = t0.deli.doc_seq(docs[0])
    msg, nack = t0.submit(docs[0], 1, CRASH_WAVE + 1, base,
                          {"op": "insert", "parent": "root",
                           "field": "kids", "after": None,
                           "nodes": [{"id": "fresh", "value": 7}]})
    assert nack is None and msg.seq == base + 1
