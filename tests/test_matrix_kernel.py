"""Matrix cell kernel vs a plain-dict LWW/FWW oracle.

The kernel's contract (merge a sequenced set-cell stream into the persistent
cell set under LWW or first-writer-wins policy) is exactly expressible as a
dict fold, so the oracle is trivial — the interesting part is that the
sort-based table merge (concat → sort → winner mark → re-sort → truncate)
reproduces it under every batch split. Reference semantics: SURVEY.md §2.4
(``SharedMatrix`` LWW cells, ``switchSetCellPolicy``).
"""

import random

import numpy as np
import pytest

from fluidframework_tpu.ops.matrix_kernel import (
    EMPTY_KEY, MatrixCellState, TensorMatrixStore, apply_cells_batch_jit,
    matrix_cells_digest,
)

import jax.numpy as jnp


def oracle_merge(records, fww=False):
    cells = {}
    for r, c, v, s in records:  # seq ascending
        if fww and (r, c) in cells:
            continue
        cells[(r, c)] = v
    return cells


def storm(seed, n_ops, n_rows=16, n_cols=16):
    rng = random.Random(seed)
    return [(rng.randrange(n_rows), rng.randrange(n_cols),
             f"v{rng.randrange(40)}", s + 1) for s in range(n_ops)]


@pytest.mark.parametrize("seed", range(8))
def test_lww_matches_oracle_random_batching(seed):
    recs = storm(seed, 300)
    store = TensorMatrixStore(capacity=512, batch_size=64)
    rng = random.Random(seed + 1)
    i = 0
    while i < len(recs):
        step = rng.randint(1, 90)
        store.apply_batch(recs[i:i + step])
        i += step
    assert not store.overflowed()
    assert store.read_cells() == oracle_merge(recs)


@pytest.mark.parametrize("seed", [0, 3])
def test_fww_matches_oracle(seed):
    recs = storm(seed, 200, n_rows=6, n_cols=6)
    store = TensorMatrixStore(capacity=256, batch_size=32)
    store.switch_set_cell_policy()
    store.apply_batch(recs)
    assert store.read_cells() == oracle_merge(recs, fww=True)


def test_fww_respects_existing_table_entries():
    store = TensorMatrixStore(capacity=64, batch_size=8)
    store.apply_batch([(0, 0, "first", 1)])     # LWW phase
    store.switch_set_cell_policy()
    store.apply_batch([(0, 0, "late", 5), (1, 1, "new", 6)])
    assert store.read_cells() == {(0, 0): "first", (1, 1): "new"}


def test_digest_invariant_to_batch_split():
    recs = storm(5, 256)
    digs = []
    for bs in (16, 64, 256):
        store = TensorMatrixStore(capacity=512, batch_size=bs)
        store.apply_batch(recs)
        digs.append(int(matrix_cells_digest(store.state)))
    assert len(set(digs)) == 1


def test_overflow_sticky_flag():
    state = MatrixCellState.create(4)
    keys = jnp.asarray(np.arange(8, dtype=np.int32))
    seqs = jnp.asarray(np.arange(1, 9, dtype=np.int32))
    vals = jnp.asarray(np.arange(8, dtype=np.int32))
    state = apply_cells_batch_jit(state, keys, seqs, vals, False)
    assert int(state.overflow) == 1
    assert int(state.count) == 4  # clamped


def test_empty_pads_are_inert():
    store = TensorMatrixStore(capacity=32, batch_size=16)
    store.apply_batch([(2, 3, "x", 1)])  # 15 pad rows ride along
    store.apply_batch([])                # no-op
    assert store.read_cells() == {(2, 3): "x"}
    assert int(store.state.count) == 1
    assert not store.overflowed()
