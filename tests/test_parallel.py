"""Multi-chip replicated/sharded step on the virtual 8-device CPU mesh:
parity with the single-device kernel, replica agreement, compaction under
shardings, and the graft entry points."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from fluidframework_tpu.ops.merge_tree_kernel import (
    StringState, apply_string_batch, string_state_digest,
)
from fluidframework_tpu.parallel import (
    make_mesh, make_replicated_step, shard_state, shard_ops,
)
from fluidframework_tpu.testing.synthetic import typing_storm

ORDER = ("kind", "a0", "a1", "a2", "seq", "client", "ref_seq")


def planes_for(n_docs, n_ops, seed=0):
    planes, _ = typing_storm(n_docs, n_ops, seed=seed)
    return tuple(jnp.asarray(planes[k]) for k in ORDER)


def test_replicated_step_matches_single_device():
    mesh = make_mesh(8)  # (2 replicas, 4 doc shards)
    _, doc_shards = mesh.devices.shape
    n_docs, n_ops, cap = 4 * doc_shards, 8, 64
    ops = planes_for(n_docs, n_ops)

    single = apply_string_batch(StringState.create(n_docs, cap), *ops)
    ref_digest = np.asarray(string_state_digest(single))

    step = make_replicated_step(mesh)
    state = shard_state(StringState.create(n_docs, cap), mesh)
    new_state, digest, agree = step(state, *shard_ops(mesh, *ops))
    assert int(agree) == 1
    assert np.array_equal(np.asarray(digest), ref_digest)
    for plane in ("seq", "length", "handle_op", "handle_off", "removed_seq"):
        assert np.array_equal(np.asarray(getattr(new_state, plane)),
                              np.asarray(getattr(single, plane))), plane


def test_replicated_step_multiple_rounds():
    mesh = make_mesh(8)
    _, doc_shards = mesh.devices.shape
    n_docs, n_ops, cap = 2 * doc_shards, 8, 128
    step = make_replicated_step(mesh)
    state = shard_state(StringState.create(n_docs, cap), mesh)
    ref = StringState.create(n_docs, cap)
    seq = 1
    for r in range(3):
        planes, seq = typing_storm(n_docs, n_ops, seed=r, start_seq=seq)
        ops = tuple(jnp.asarray(planes[k]) for k in ORDER)
        state, digest, agree = step(state, *shard_ops(mesh, *ops))
        ref = apply_string_batch(ref, *ops)
        assert int(agree) == 1
        assert np.array_equal(np.asarray(digest),
                              np.asarray(string_state_digest(ref)))


@pytest.mark.slow  # two full subprocess engine drills, ~9 min — the
def test_graft_entry_and_dryrun():  # driver runs dryrun_multichip itself
    import importlib.util
    spec = importlib.util.spec_from_file_location(
        "graft_entry", "/root/repo/__graft_entry__.py")
    g = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(g)
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    jax.block_until_ready(out)
    # the dryrun runs in its own PROCESS, exactly as the driver invokes
    # it (the engine drill is heavyweight; in-process it shares this
    # long-lived suite interpreter's jit caches and native-lib state)
    import os
    import subprocess
    import sys
    env = dict(os.environ,
               XLA_FLAGS=(os.environ.get("XLA_FLAGS", "")
                          + " --xla_force_host_platform_device_count=8")
               .strip())
    for n in (8, 4):
        proc = subprocess.run(
            [sys.executable, "-c",
             "import __graft_entry__ as g; "
             f"g._ensure_virtual_devices({n}); g.dryrun_multichip({n})"],
            cwd=os.path.dirname(os.path.dirname(
                os.path.abspath(__file__))),
            env=env, capture_output=True, text=True,
            timeout=900)
        assert proc.returncode == 0, proc.stderr[-3000:]
        assert "dryrun_multichip OK" in proc.stdout
