"""Observability suite (ISSUE 2): end-to-end op tracing, the unified
metrics registry, and the crash flight recorder — plus the telemetry
satellites (performance-event cancel, child-logger props, sampled flush
on close, histogram overflow) and a lint-style check that every
``send_warning`` degradation site also counts.
"""

import ast
import json
import os
import pathlib

import pytest

from fluidframework_tpu.testing.chaos import FaultPlan
from fluidframework_tpu.tools import trace_viewer
from fluidframework_tpu.utils import flight_recorder, tracing
from fluidframework_tpu.utils.faultpoints import (
    SITE_SUBMIT_POST_SEQUENCE, CrashInjected, armed, fault_point,
)
from fluidframework_tpu.utils.telemetry import (
    BufferSink, Histogram, MetricsRegistry, REGISTRY, SampledTelemetry,
    TelemetryLogger,
)

pytestmark = pytest.mark.telemetry

PKG_ROOT = pathlib.Path(__file__).resolve().parent.parent \
    / "fluidframework_tpu"


# --------------------------------------------------------------- telemetry

def test_performance_event_cancel_path():
    sink = BufferSink()
    log = TelemetryLogger(sink, "t")
    with pytest.raises(ValueError):
        with log.performance_event("load", doc="d"):
            raise ValueError("boom")
    cancel, = sink.named("load_cancel")
    assert cancel["category"] == "error"
    assert "boom" in cancel["error"]
    assert cancel["duration_ms"] >= 0
    assert cancel["doc"] == "d"
    assert not sink.named("load_end")


def test_child_logger_prop_merging():
    sink = BufferSink()
    root = TelemetryLogger(sink, "svc", {"docId": "d1", "tier": "a"})
    child = root.child("deli", {"tier": "b", "partition": 3})
    child.send_event("seq", n=1)
    ev, = sink.events
    assert ev["eventName"] == "svc:deli:seq"
    assert ev["docId"] == "d1"        # inherited
    assert ev["tier"] == "b"          # child overrides parent
    assert ev["partition"] == 3
    # the parent's own props are untouched by the child
    assert root.props == {"docId": "d1", "tier": "a"}


def test_sampled_telemetry_min_max_and_close_flush():
    sink = BufferSink()
    st = SampledTelemetry(TelemetryLogger(sink), "lat", rate=3)
    for v in (5.0, 1.0, 9.0):
        st.record(v)
    ev, = sink.events                 # auto-flush at rate
    assert (ev["min"], ev["max"], ev["samples"]) == (1.0, 9.0, 3)
    assert ev["mean"] == pytest.approx(5.0)
    # a partial window is NOT lost on shutdown
    st.record(42.0)
    st.close()
    tail = sink.events[-1]
    assert (tail["samples"], tail["min"], tail["max"]) == (1, 42.0, 42.0)
    st.close()                        # idempotent: nothing to flush
    assert len(sink.events) == 2


def test_sampled_telemetry_context_manager_flushes():
    sink = BufferSink()
    with SampledTelemetry(TelemetryLogger(sink), "lat", rate=100) as st:
        st.record(7.0)
    assert sink.events[-1]["samples"] == 1


def test_histogram_overflow_in_snapshot():
    reg = MetricsRegistry()
    reg.observe("lat_ms", 1.0)
    reg.observe("lat_ms", 1e9)        # past the last bucket bound
    snap = reg.snapshot()
    assert snap["lat_ms_count"] == 2
    assert snap["lat_ms_overflow"] == 1
    assert snap["lat_ms_p99_ms"] == float("inf")
    h = Histogram()
    assert h.overflow == 0


# ---------------------------------------------------------------- registry

def test_registry_counters_gauges_prometheus():
    reg = MetricsRegistry()
    reg.inc("ops")
    reg.inc("ops", 2)
    reg.set_gauge("queue_depth", 7)
    reg.observe("apply_ms", 0.5)
    snap = reg.snapshot()
    assert snap["ops"] == 3
    assert snap["queue_depth"] == 7
    text = reg.render_prometheus()
    assert "# TYPE ops counter" in text
    assert "# TYPE queue_depth gauge" in text
    assert "# TYPE apply_ms histogram" in text
    assert 'apply_ms_bucket{le="+Inf"} 1' in text


def test_registry_attach_collision_and_full_snapshot():
    root = MetricsRegistry()
    a, b = MetricsRegistry(), MetricsRegistry()
    a.inc("flushes", 4)
    b.inc("flushes", 9)
    name_a = root.attach("engine", a)
    name_b = root.attach("engine", b)
    assert name_a == "engine" and name_b == "engine2"
    # re-attaching the same registry keeps its name (no suffix churn)
    assert root.attach("engine", a) == "engine"
    full = root.full_snapshot()
    assert full["engine.flushes"] == 4
    assert full["engine2.flushes"] == 9
    labeled = root.render_prometheus()
    assert 'flushes{component="engine"} 4' in labeled
    # dead components are pruned, their name becomes reusable
    del b
    assert "engine2" not in root.components()


def test_global_registry_sees_engine_components():
    from fluidframework_tpu.testing.chaos import make_engine
    engine = make_engine("string")
    engine.connect("d", 1)
    engine.submit("d", 1, 1, 0, {"mt": "insert", "kind": 0, "pos": 0,
                                 "text": "hi"})
    engine.flush()
    comps = REGISTRY.components()
    name = next((n for n, r in comps.items() if r is engine.metrics), None)
    assert name is not None and name.startswith("StringServingEngine")
    assert REGISTRY.full_snapshot()[f"{name}.flushes"] >= 1


# ----------------------------------------------------------------- tracing

def test_span_nesting_and_wire_roundtrip():
    tracer = tracing.Tracer()
    with tracer.span("outer", ops=2) as outer:
        wire = outer.ctx.to_wire()
        with tracer.span("inner") as inner:
            assert inner.ctx.trace_id == outer.ctx.trace_id
    # a wire dict re-attaches across a (simulated) socket hop
    ctx = tracing.TraceContext.from_wire(wire)
    assert (ctx.trace_id, ctx.span_id) == (outer.ctx.trace_id,
                                           outer.ctx.span_id)
    assert tracing.TraceContext.from_wire(None) is None
    assert tracing.TraceContext.from_wire({"x": 1}) is None
    evs = tracer.events(outer.ctx.trace_id)
    by_name = {e["name"]: e for e in evs}
    assert by_name["inner"]["parent_id"] == outer.ctx.span_id
    assert by_name["outer"]["parent_id"] is None
    assert by_name["outer"]["args"] == {"ops": 2}


def test_span_error_recorded_and_stack_unwound():
    tracer = tracing.Tracer()
    with pytest.raises(RuntimeError):
        with tracer.span("doomed"):
            raise RuntimeError("dead")
    e, = tracer.events()
    assert "dead" in e["error"]
    assert tracer.current() is None   # the stack unwound despite the raise


def test_record_complete_and_maybe_root_sampling():
    tracer = tracing.Tracer()
    ctx = tracer.record_complete("hot.batch", 12.5, ops=64)
    e, = tracer.events(ctx.trace_id)
    assert e["dur"] == pytest.approx(12.5e3)  # µs
    assert e["args"]["ops"] == 64
    opened = 0
    for _ in range(8):
        with tracer.maybe_root_span("srv", every=4):
            pass
    opened = len([e for e in tracer.events() if e["name"] == "srv"])
    assert opened == 2                # 1-in-4 sampling over 8 calls
    tracer.enabled = False
    assert tracer.record_complete("off", 1.0) is None


def test_trace_id_propagation_full_round_trip():
    """A client op batch yields the acceptance span tree: outbox.flush →
    wire.submit → deli.sequence → serving.apply → ack, one trace id,
    correct parent chain."""
    from fluidframework_tpu.framework import LocalClient
    tracing.TRACER.clear()
    client = LocalClient()
    c1, doc_id = client.create_container(
        {"initialObjects": {"text": "sharedString"}})
    c1.initial_objects["text"].insert_text(0, "hello")
    flushes = [e for e in tracing.TRACER.events()
               if e["name"] == "outbox.flush"]
    assert flushes, "no outbox.flush span recorded"
    tid = flushes[-1]["trace_id"]
    evs = tracing.TRACER.events(tid)
    by_name = {e["name"]: e for e in evs}
    for name in ("outbox.flush", "wire.submit", "deli.sequence",
                 "serving.apply", "ack"):
        assert name in by_name, (name, sorted(by_name))
        assert by_name[name]["trace_id"] == tid
    chain = ("outbox.flush", "wire.submit", "deli.sequence",
             "serving.apply", "ack")
    for parent, child in zip(chain, chain[1:]):
        assert by_name[child]["parent_id"] == by_name[parent]["span_id"], \
            (parent, child)
    # the sequenced message carried the context out of band
    assert by_name["deli.sequence"]["args"]["doc"] == doc_id


def test_trace_viewer_renders_chrome_export(tmp_path):
    tracer = tracing.Tracer()
    with tracer.span("root", ops=1):
        with tracer.span("child"):
            pass
    tid = tracer.trace_ids()[0]
    path = str(tmp_path / "trace.json")
    doc = tracer.export_chrome(path, tid)
    assert json.load(open(path)) == doc
    assert all(ev["ph"] == "X" for ev in doc["traceEvents"])
    # viewer loads + renders both forms: dump file and live tracer
    out = trace_viewer.render(trace_viewer.load_events(path))
    lines = out.splitlines()
    assert lines[0].startswith("root") and "ops=1" in lines[0]
    assert lines[1].startswith("  child")
    assert trace_viewer.trace_ids(doc["traceEvents"]) == [tid]
    assert "root" in trace_viewer.render_tracer(tracer)


def test_span_tree_orphan_becomes_root():
    evs = [{"name": "a", "trace_id": "t", "span_id": 1,
            "parent_id": 999, "ts": 0.0, "dur": 1.0}]
    roots = tracing.span_tree(evs)
    assert [r["name"] for r in roots] == ["a"]


# --------------------------------------------------------- flight recorder

def test_flight_recorder_ring_and_dump(tmp_path):
    rec = flight_recorder.FlightRecorder(capacity=4,
                                         dump_dir=str(tmp_path))
    for i in range(6):
        rec.note("tick", i=i)
    events = rec.snapshot()
    assert len(events) == 4           # bounded: oldest two evicted
    assert events[0]["i"] == 2
    path = rec.dump("test", extra={"fh": open(os.devnull)})
    back = flight_recorder.load_dump(path)
    assert back[0]["flight_recorder"] == "test"
    assert back[0]["n_events"] == 4
    assert "TextIOWrapper" in back[0]["fh"]   # non-JSON coerced via repr
    assert [e["i"] for e in back[1:]] == [2, 3, 4, 5]


def test_flight_recorder_dump_rotation(tmp_path):
    rec = flight_recorder.FlightRecorder(dump_dir=str(tmp_path),
                                         max_dumps=2)
    paths = [rec.dump(f"r{i}") for i in range(3)]
    assert paths[0] == paths[2]       # seq rotates mod max_dumps
    assert len(rec.dumps) == 2        # bounded bookkeeping


def test_telemetry_feeds_flight_recorder_without_sink():
    flight_recorder.RECORDER.clear()
    TelemetryLogger(None, "eng").send_warning("overloaded", depth=9)
    ev = flight_recorder.RECORDER.snapshot()[-1]
    assert ev["eventName"] == "eng:overloaded"
    assert ev["depth"] == 9 and "ts" in ev


def test_faultpoint_crash_dumps_flight_recorder(tmp_path, monkeypatch):
    """The acceptance path: a chaos-drill crash leaves a JSONL dump whose
    events include the faultpoint firing."""
    monkeypatch.setenv("FLUID_FLIGHT_DIR", str(tmp_path))
    flight_recorder.RECORDER.clear()
    plan = FaultPlan(crash={SITE_SUBMIT_POST_SEQUENCE: 1})
    with armed(plan):
        with pytest.raises(CrashInjected):
            fault_point(SITE_SUBMIT_POST_SEQUENCE, doc="d0")
    path = flight_recorder.RECORDER.dumps[-1]
    assert path.startswith(str(tmp_path))
    events = flight_recorder.load_dump(path)
    assert events[0]["flight_recorder"] == \
        f"faultpoint:{SITE_SUBMIT_POST_SEQUENCE}"
    fired = [e for e in events if e.get("eventName") == "faultpoint_fired"]
    assert fired and fired[-1]["site"] == SITE_SUBMIT_POST_SEQUENCE
    assert fired[-1]["doc"] == "d0"
    assert "CrashInjected" in fired[-1]["error"]


def test_drill_assertion_failure_dumps(tmp_path, monkeypatch):
    from fluidframework_tpu.testing import chaos
    monkeypatch.setenv("FLUID_FLIGHT_DIR", str(tmp_path))

    @chaos._recorded_drill
    def failing_drill():
        assert False, "invariant violated"

    with pytest.raises(AssertionError):
        failing_drill()
    events = flight_recorder.load_dump(flight_recorder.RECORDER.dumps[-1])
    assert events[0]["flight_recorder"] == "drill:failing_drill"
    assert any(e.get("eventName") == "drill_assertion_failed"
               for e in events)


# ----------------------------------------------------------- lint: warn+count

def _warning_sites_without_counter():
    """AST sweep: every ``send_warning`` call's enclosing function must
    also increment a metrics counter (``.inc(``) — warnings are for
    humans, counters are for rates; a warn-only degradation path is
    invisible to dashboards."""
    offenders = []
    for path in sorted(PKG_ROOT.rglob("*.py")):
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            calls = [c.func.attr for c in ast.walk(node)
                     if isinstance(c, ast.Call)
                     and isinstance(c.func, ast.Attribute)]
            if "send_warning" in calls and "inc" not in calls:
                offenders.append(f"{path.relative_to(PKG_ROOT)}:"
                                 f"{node.lineno} {node.name}")
    return offenders


def test_every_send_warning_site_also_counts():
    offenders = _warning_sites_without_counter()
    # telemetry.py itself defines send_warning; definitions have no calls
    assert not offenders, (
        "send_warning without a metrics counter in the same function "
        f"(warn-only degradation): {offenders}")


def _metric_names_in_tree():
    """AST sweep of every ``.inc(`` / ``.set_gauge(`` / ``.observe(``
    call whose first argument names a metric: string literals verbatim,
    f-strings as their literal prefix + ``*`` (the per-reason counter
    families), and both arms of a literal conditional. ``observe`` calls
    with a non-string first arg are ``Histogram.observe(value)`` — not a
    name site. Returns {name: "file:line"}."""
    roots = [PKG_ROOT,
             PKG_ROOT.parent / "bench.py",
             PKG_ROOT.parent / "tools"]
    files = []
    for r in roots:
        files += sorted(r.rglob("*.py")) if r.is_dir() else [r]
    kinds = {"inc", "set_gauge", "observe"}
    names = {}

    def literal_names(node):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            return [node.value]
        if isinstance(node, ast.JoinedStr) and node.values and \
                isinstance(node.values[0], ast.Constant):
            return [str(node.values[0].value) + "*"]
        if isinstance(node, ast.IfExp):
            return literal_names(node.body) + literal_names(node.orelse)
        return []

    for path in files:
        tree = ast.parse(path.read_text())
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and isinstance(node.func, ast.Attribute)
                    and node.func.attr in kinds and node.args):
                continue
            for name in literal_names(node.args[0]):
                names.setdefault(name, f"{path.name}:{node.lineno}")
    return names


def test_metric_names_all_in_observability_doc():
    """Dark-metric lint (ISSUE 4 satellite): every metric name used in
    the tree must appear, backtick-quoted, in docs/OBSERVABILITY.md's
    registry — a counter nobody documented is a counter nobody reads."""
    doc = (PKG_ROOT.parent / "docs" / "OBSERVABILITY.md").read_text()
    names = _metric_names_in_tree()
    assert names, "AST sweep found no metric call sites — lint is broken"
    assert len(names) > 20, f"sweep saw too few sites: {sorted(names)}"
    missing = [f"{n} ({where})" for n, where in sorted(names.items())
               if f"`{n}`" not in doc]
    assert not missing, (
        "metric names missing from docs/OBSERVABILITY.md's registry "
        f"table: {missing}")
