"""Read plane (ISSUE 20): encode-once observer fanout + generation-diff
catch-up.

Four surfaces under test:

* catch-up parity fuzz — ``build_generation_diff`` between two summary
  generations, applied over the FROM base plus the TO tail, must
  converge byte-identically with a full summary load across all four
  engine families (the acceptance gate).
* hub semantics — encode-once byte sharing, whole-window byte-budget
  shedding (park + gap notice + resume), retained-ring resubscribe
  replay and the ``catchup_needed`` signal when the ring is too short.
* the wire loop — every family's sequenced windows delivered through
  the real socket door and decoded by the real client
  (``ResilientObserver``): string batches as columnar ``B``/``R``
  frames, tree batches as binary ``T`` frames, map/matrix as JSON.
* reconnect-mid-storm exactly-once — observers killed repeatedly while
  a writer storms; every observer must end with every op applied, zero
  window/op gaps, zero dups.
"""

import json
import random
import threading
import time

import pytest

from fluidframework_tpu.drivers.resilient import ResilientObserver
from fluidframework_tpu.server.observer import ObserverDoor, ObserverHub
from fluidframework_tpu.server.read_plane import (
    ReadPlane, ReadReplica, StalenessTracker, build_generation_diff,
    apply_generation_diff, encode_window, summary_doc_seqs,
)
from fluidframework_tpu.testing.chaos import (
    OpGen, digest, engine_class, make_engine,
)

pytestmark = pytest.mark.readplane

FAMILIES = ("string", "map", "matrix", "tree")
DOCS = [f"d{i}" for i in range(4)]


def _run_engine(family, seed, n1=40, n2=60, tail=20):
    """One engine lineage with two summary generations and a durable
    tail past the second: returns (engine, s_from, s_to, opgen)."""
    rng = random.Random(seed)
    eng = make_engine(family, n_docs=len(DOCS))
    gen = OpGen(rng, family, DOCS)
    cseq = {d: 0 for d in DOCS}

    def push(n):
        for i in range(n):
            d = DOCS[i % len(DOCS)]
            cseq[d] += 1
            _msg, nack = eng.submit(d, 1, cseq[d], 0, gen.op(d))
            assert not nack, nack
        eng.flush()

    for d in DOCS:
        eng.connect(d, 1)
    push(n1)
    s_from = eng.summarize()
    push(n2)
    s_to = eng.summarize()
    push(tail)            # the short tail both loaders must replay
    return eng, s_from, s_to


# ------------------------------------------------------ catch-up parity

@pytest.mark.parametrize("family", FAMILIES)
@pytest.mark.parametrize("seed", [3, 11])
def test_catchup_parity_fuzz(family, seed):
    """diff(G-1 → G) + tail replay must converge byte-identically with
    a full load of G + tail replay — the device-computed catch-up is a
    perfect substitute for full-tail rehydration."""
    eng, s_from, s_to = _run_engine(family, seed)
    diff = build_generation_diff(family, s_from, s_to)
    e_diff = apply_generation_diff(family, diff, s_from, eng.log)
    e_full = engine_class(family).load(s_to, eng.log)
    d_diff = json.dumps(digest(e_diff, family, DOCS), sort_keys=True)
    d_full = json.dumps(digest(e_full, family, DOCS), sort_keys=True)
    assert d_diff == d_full
    # and both match the live engine (the tail really replayed)
    d_live = json.dumps(digest(eng, family, DOCS), sort_keys=True)
    assert d_diff == d_live


def test_generation_diff_needs_full_generations():
    eng, s_from, s_to = _run_engine("map", 5, tail=0)
    delta = dict(s_to)
    delta["kind"] = "delta"
    with pytest.raises(ValueError, match="FULL generations"):
        build_generation_diff("map", s_from, delta)
    with pytest.raises(ValueError, match="FULL generations"):
        build_generation_diff("map", delta, s_to)


def test_summary_doc_seqs_reads_checkpoint():
    eng, s_from, s_to = _run_engine("string", 7, tail=0)
    seqs_from = summary_doc_seqs(s_from)
    seqs_to = summary_doc_seqs(s_to)
    assert set(seqs_to) == set(DOCS)
    assert all(seqs_to[d] > seqs_from[d] for d in DOCS)


# ------------------------------------------------------- hub semantics

def test_hub_encode_once_shares_bytes():
    """The fanout contract: every subscriber's sink receives the SAME
    bytes object — one encode, N sends, zero per-subscriber copies."""
    hub = ObserverHub(tracker=StalenessTracker())
    got = [[], []]
    hub.subscribe(got[0].append)
    hub.subscribe(got[1].append)
    payload = b"window-bytes"
    wid = hub.next_wid()
    assert hub.publish(wid, payload, 3) == 2
    assert got[0][0] is payload and got[1][0] is payload


def test_hub_shed_park_resume():
    """A subscriber whose byte budget cannot take a WHOLE window is
    shed that window (gap notice, parked) and resumes via ring replay —
    never a torn frame, never a stalled publisher."""
    hub = ObserverHub(tracker=StalenessTracker())
    got = []
    ack = hub.subscribe(got.append, byte_rate=1.0, byte_burst=64.0)
    big = bytes(200)
    wid = hub.next_wid()
    assert hub.publish(wid, big, 1) == 0          # over budget: shed
    rows = hub.readers()
    assert rows[0]["parked"] and rows[0]["sheds"] == 1
    # the gap notice arrived INSTEAD of the window
    assert len(got) == 1 and len(got[0]) != len(big)
    # parked: later windows skip it entirely
    assert hub.publish(hub.next_wid(), b"x", 1) == 0
    assert len(got) == 1
    # resume replays the ring from the cursor, unparked
    assert hub.resume(ack["sid"], wid)
    assert big in got and got[-1] == b"x"
    assert not hub.readers()[0]["parked"]


def test_hub_ring_replay_and_catchup_signal():
    hub = ObserverHub(ring=4, tracker=StalenessTracker())
    payloads = [f"w{i}".encode() for i in range(8)]
    for p in payloads:
        hub.publish(hub.next_wid(), p, 1)
    # ring holds wids 5..8: a joiner at wid 6 replays 6..8
    got = []
    ack = hub.subscribe(got.append, from_wid=6)
    assert not ack["catchup_needed"]
    assert got == payloads[5:]
    # a joiner at wid 2 predates the ring: catch-up ladder territory
    got2 = []
    ack2 = hub.subscribe(got2.append, from_wid=2)
    assert ack2["catchup_needed"] and ack2["ring_from"] == 5
    assert got2 == []


def test_hub_dead_sink_unsubscribes():
    hub = ObserverHub(tracker=StalenessTracker())

    def dead(_b):
        raise OSError("gone")

    hub.subscribe(dead)
    assert hub.publish(hub.next_wid(), b"x", 1) == 0
    assert hub.stats()["subscribers"] == 0


# ----------------------------------------------------- wire delivery

def _start_plane(family, **eng_kw):
    eng = make_engine(family, **eng_kw)
    hub = ObserverHub(ring=1024, tracker=StalenessTracker())
    plane = ReadPlane(eng, hub)
    eng.attach_read_plane(plane)
    door = ObserverDoor(hub).start_in_thread()
    return eng, hub, plane, door


@pytest.mark.parametrize("family", FAMILIES)
def test_delivery_all_families(family):
    """Every family's sequenced windows reach a socket observer exactly
    once, decoded by the real client: string rides the columnar B/R
    frames, tree the binary T frames, map/matrix the JSON fallback."""
    eng, hub, plane, door = _start_plane(family)
    obs = ResilientObserver("127.0.0.1", door.port, name=family,
                            rng=random.Random(1))
    try:
        rng = random.Random(9)
        gen = OpGen(rng, family, DOCS)
        cseq = {d: 0 for d in DOCS}
        for d in DOCS:
            eng.connect(d, 1)
        n = 24
        for i in range(n):
            d = DOCS[i % len(DOCS)]
            cseq[d] += 1
            _msg, nack = eng.submit(d, 1, cseq[d], 0, gen.op(d))
            assert not nack, nack
        eng.flush()
        assert obs.wait_ops(n, 30), (obs.ops_applied, obs.gave_up)
        assert obs.ops_applied == n
        assert obs.gaps == 0 and obs.op_gaps == 0
        assert obs.dups == 0 and obs.window_dups == 0
        # the client's per-doc cursors match the sequencer's
        for d in DOCS:
            assert obs.doc_seqs[d] == eng.deli.doc_seq(d)
    finally:
        obs.close()
        door.stop()


def test_reconnect_mid_storm_exactly_once():
    """Observers killed repeatedly while a writer storms: each redial
    resubscribes from ``last_wid + 1`` and the hub's ring replays the
    missed windows — every observer ends with every op, no gap, no dup
    (the ISSUE 20 acceptance gate)."""
    eng, hub, plane, door = _start_plane("string")
    obs = [ResilientObserver("127.0.0.1", door.port, name=f"o{i}",
                             rng=random.Random(100 + i),
                             base_delay=0.01)
           for i in range(3)]
    try:
        for d in DOCS:
            eng.connect(d, 1)
        time.sleep(0.1)
        total = 160
        cseq = {d: 0 for d in DOCS}
        stop = threading.Event()

        def storm():
            for i in range(total):
                d = DOCS[i % len(DOCS)]
                cseq[d] += 1
                eng.submit(d, 1, cseq[d], 0,
                           {"mt": "insert", "kind": 0, "pos": 0,
                            "text": f"s{i}"})
                if i % 40 == 0:
                    eng.flush()
                    time.sleep(0.01)
            eng.flush()
            stop.set()

        t = threading.Thread(target=storm)
        t.start()
        # kill every observer's socket a few times mid-storm
        for _round in range(3):
            time.sleep(0.05)
            for o in obs:
                o.kill_socket()
        t.join(30)
        assert stop.is_set()
        for o in obs:
            assert o.wait_ops(total, 30), \
                (o.name, o.ops_applied, o.reconnects, o.gave_up)
            assert o.ops_applied == total
            assert o.gaps == 0 and o.op_gaps == 0, (o.gaps, o.op_gaps)
            assert o.dups == 0 and o.window_dups == 0
            assert o.reconnects >= 1     # the storm actually bit
        assert sum(o.reconnects for o in obs) >= 3
    finally:
        for o in obs:
            o.close()
        door.stop()


def test_encode_window_empty_records():
    payload, n_ops = encode_window([], 1)
    assert n_ops == 0 and payload


# --------------------------------------------------- replica staleness

def test_read_replica_bounded_staleness():
    """A follower-fed replica drains the leader's durable tail and
    samples staleness per poll; reads from the replica then match the
    leader exactly (bounded-stale, currently caught up)."""
    leader = make_engine("string")
    for d in DOCS:
        leader.connect(d, 1)
    cseq = {d: 0 for d in DOCS}

    def push(n0, n1):
        for i in range(n0, n1):
            d = DOCS[i % len(DOCS)]
            cseq[d] += 1
            leader.submit(d, 1, cseq[d], 0,
                          {"mt": "insert", "kind": 0, "pos": 0,
                           "text": f"r{i}"})
        leader.flush()

    push(0, 12)
    s0 = leader.summarize()       # replica anchors a generation behind
    tracker = StalenessTracker()
    rep = ReadReplica(leader, family="string", summary=s0,
                      tracker=tracker)
    push(12, 24)                  # the tail the replica must drain
    n = rep.poll()
    assert n > 0
    assert rep.poll() == 0           # caught up: idle poll is free
    assert tracker.p99() >= 0.0
    d_leader = digest(leader, "string", DOCS)
    d_replica = digest(rep.engine, "string", DOCS)
    assert d_leader == d_replica


def test_default_slos_include_read_staleness():
    from fluidframework_tpu.utils.slo import default_slos
    names = {s.name for s in default_slos()}
    assert "read_staleness" in names


def test_opsd_readers_route():
    """`/debug/readers` aggregates every attached hub's census."""
    from fluidframework_tpu.server.opsd import OpsServer
    hub = ObserverHub(tracker=StalenessTracker())
    hub.subscribe(lambda b: None, name="panel")
    hub.publish(hub.next_wid(), b"w", 2)
    ops = OpsServer(port=0, tick_interval_s=0)
    ops.add_readers(hub)
    _ctype, body = ops._r_readers({})
    out = json.loads(body)
    assert out["subscribers"] == 1 and out["count"] == 1
    assert out["ops_published"] == 2
    assert out["readers"][0]["name"] == "panel"
