"""Pipelined-ingest correctness: the staged executor must be
observationally identical to the serial ``ingest_planes`` walk — same
seqs, same nacks, same merged state (digests) — on every wire profile,
while actually overlapping stages (depth > 1 exercised, CPU tier-1).

docs/INGEST_PIPELINE.md has the stage diagram and the ack-after-durable
rule these tests pin."""

import numpy as np
import pytest

from fluidframework_tpu.ops.merge_tree_kernel import string_state_digest
from fluidframework_tpu.server import native_deli
from fluidframework_tpu.server.ingest_pipeline import (
    PipelinedIngestExecutor,
)
from fluidframework_tpu.server.serving import StringServingEngine
from fluidframework_tpu.testing.synthetic import rich_storm, typing_storm

pytestmark = pytest.mark.skipif(not native_deli.available(),
                                reason="native sequencer unavailable")

R, O = 8, 4   # docs × ops per wave (constant shapes share the jit cache)


def _mk_engine():
    eng = StringServingEngine(n_docs=R, capacity=256,
                              batch_window=10 ** 9, sequencer="native")
    for i in range(R):
        eng.connect(f"d{i}", 1)
    return eng


def _rows(eng):
    return np.array([eng.doc_row(f"d{i}") for i in range(R)], np.int32)


def _cseq(wave):
    return np.broadcast_to(
        np.arange(wave * O + 1, (wave + 1) * O + 1, dtype=np.int32),
        (R, O))


def _typing_waves(n_waves, seed0=0):
    """Broadcast-payload waves: one shared text, plane-coded ops."""
    waves = []
    seq = 1
    for b in range(n_waves):
        planes, seq = typing_storm(R, O, seed=seed0 + b, start_seq=seq)
        cs = _cseq(b)
        waves.append(dict(client=np.ones((R, O), np.int32),
                          client_seq=cs, ref_seq=cs,
                          kind=planes["kind"], a0=planes["a0"],
                          a1=planes["a1"], text="abcd"))
    return waves


def _rich_waves(n_waves, seed0=0):
    """Distinct payload handles + single-key annotates: the tab8/tab16
    rich wire profiles, the interner prepack runs off-thread for."""
    waves = []
    for b in range(n_waves):
        planes, texts, rprops, _ = rich_storm(R, O, seed=seed0 + b)
        cs = _cseq(b)
        waves.append(dict(client=np.ones((R, O), np.int32),
                          client_seq=cs, ref_seq=cs,
                          kind=planes["kind"], a0=planes["a0"],
                          a1=planes["a1"], texts=texts,
                          tidx=planes["tidx"], props=rprops))
    return waves


def _run_serial(waves, eng=None):
    eng = eng or _mk_engine()
    rows = _rows(eng)
    outs = [eng.ingest_planes(rows, **w) for w in waves]
    return eng, outs


def _run_pipelined(waves, depth=3, eng=None):
    eng = eng or _mk_engine()
    rows = _rows(eng)
    with PipelinedIngestExecutor(eng, depth=depth) as ex:
        tickets = [ex.submit(rows, **w) for w in waves]
        ex.drain()
        outs = [t.result() for t in tickets]
        stats = ex.stats()
    return eng, outs, stats


def _assert_parity(serial, pipelined):
    eng_s, outs_s = serial
    eng_p, outs_p, _stats = pipelined
    for b, (a, c) in enumerate(zip(outs_s, outs_p)):
        assert np.array_equal(np.asarray(a["seq"]),
                              np.asarray(c["seq"])), f"seqs diverge @{b}"
        assert a["nacked"] == c["nacked"], f"nacks diverge @{b}"
    d_s = np.asarray(string_state_digest(eng_s.store.state))
    d_p = np.asarray(string_state_digest(eng_p.store.state))
    assert (d_s == d_p).all(), "merged-state digests diverge"
    for i in (0, R - 1):
        assert eng_s.read_text(f"d{i}") == eng_p.read_text(f"d{i}"), i
    # the pipeline fully logged: poison sentinel cleared at quiescence
    assert eng_p._ingest_inflight() == 0
    eng_p._check_poisoned()


@pytest.mark.parametrize("seed", [0, 7, 23])
def test_broadcast_parity(seed):
    waves = _typing_waves(5, seed0=seed)
    _assert_parity(_run_serial(waves), _run_pipelined(waves))


@pytest.mark.parametrize("seed", [0, 11])
def test_rich_parity(seed):
    """The prepacked tables (interner hoisted to the pack worker, pow2
    capacity reused across waves) must produce byte-identical merges."""
    waves = _rich_waves(6, seed0=seed)
    _assert_parity(_run_serial(waves), _run_pipelined(waves))


def test_mixed_profile_parity():
    """Profile switches mid-stream (broadcast → rich → broadcast) reuse
    and release pooled tables across waves without cross-talk."""
    t = _typing_waves(2)
    r = _rich_waves(2, seed0=3)
    # cseqs must stay per-client contiguous across the mixed stream
    waves = [t[0], None, t[1], None]
    for k, w in ((1, r[0]), (3, r[1])):
        w = dict(w)
        w["client_seq"] = _cseq(k)
        w["ref_seq"] = _cseq(k)
        waves[k] = w
    _assert_parity(_run_serial(waves), _run_pipelined(waves))


def test_interval_wave_parity():
    """Interval-holding rows cannot prepack (anchor handles mint
    post-nack): the pack worker barriers on dispatch, keeping handle
    allocation in submission order — endpoints must match the serial
    path exactly."""
    def _with_intervals():
        eng = _mk_engine()
        base = "the quick brown fox jumps over the dazed dog"
        for i in range(R):
            _, nack = eng.submit(f"d{i}", 1, 1, 0,
                                 {"mt": "insert", "kind": 0, "pos": 0,
                                  "text": base, "clientSeq": 1})
            assert nack is None
        eng.flush()
        req = {eng.doc_row(f"d{i}"): [(3, 9, None), (12, 20, None)]
               for i in range(R)}
        ids = eng.store.add_intervals_bulk(req)
        return eng, ids

    import random
    rng = random.Random(5)
    waves = []
    lengths = [44] * R
    for w in range(3):
        kind = np.zeros((R, O), np.int32)
        a0 = np.zeros((R, O), np.int32)
        a1 = np.zeros((R, O), np.int32)
        for di in range(R):
            ln = lengths[di]
            for c in range(O):
                if rng.random() < 0.5:
                    a0[di, c], a1[di, c] = rng.randrange(ln + 1), 2
                    ln += 2
                else:
                    s = rng.randrange(ln - 3)
                    kind[di, c] = 1
                    a0[di, c], a1[di, c] = s, s + 2
                    ln -= 2
            lengths[di] = ln
        cs = np.broadcast_to(
            np.arange(2 + w * O, 2 + (w + 1) * O, dtype=np.int32),
            (R, O))
        waves.append(dict(client=np.ones((R, O), np.int32),
                          client_seq=cs,
                          ref_seq=np.full((R, O), 2 + w * O, np.int32),
                          kind=kind, a0=a0, a1=a1, text="XY"))

    eng_s, iv_s = _with_intervals()
    eng_p, iv_p = _with_intervals()
    serial = _run_serial(waves, eng=eng_s)
    pipelined = _run_pipelined(waves, eng=eng_p)
    _assert_parity(serial, pipelined)
    for i in range(R):
        row = eng_s.doc_row(f"d{i}")
        for sid_s, sid_p in zip(iv_s[row], iv_p[row]):
            assert eng_s.store.interval_endpoints(row, sid_s) == \
                eng_p.store.interval_endpoints(row, sid_p), (i, sid_s)


def test_depth_exercised_and_metrics_published():
    """The CPU tier-1 smoke the ISSUE asks for: a small pipelined ingest
    where depth > 1 is ACTUALLY in flight, with the occupancy gauges
    registered in docs/OBSERVABILITY.md published on close."""
    waves = _typing_waves(6)
    eng, outs, stats = _run_pipelined(waves, depth=2)
    assert all(o["nacked"] == 0 for o in outs)
    assert stats["waves"] == len(waves)
    assert stats["max_inflight"] > 1, stats   # depth genuinely exercised
    assert stats["depth"] == 2
    assert set(stats["stage_occupancy"]) == {"pack", "seq_dispatch",
                                             "log"}
    snap = eng.metrics.snapshot()
    for gauge in ("ingest_pack_occupancy", "ingest_seq_dispatch_occupancy",
                  "ingest_log_occupancy", "ingest_stage_overlap",
                  "ingest_inflight_depth"):
        assert gauge in snap, gauge
    assert snap["ingest_inflight_depth"] == stats["max_inflight"]
    assert snap.get("ingest_waves", 0) >= len(waves)


# ------------------------------------------------- tree record waves

def _mk_tree_engine(n_docs=8):
    from fluidframework_tpu.server.serving import TreeServingEngine
    eng = TreeServingEngine(n_docs=n_docs, capacity=64,
                            batch_window=10 ** 9, sequencer="native")
    for i in range(n_docs):
        eng.connect(f"t{i}", 1)
    return eng, [f"t{i}" for i in range(n_docs)]


def _tree_waves(docs, n_waves):
    """General waves: chained inserts + a guarded transaction per doc,
    pre-encoded client-side (encode_tree_batch)."""
    from fluidframework_tpu.server.tree_wire import encode_tree_batch
    waves = []
    for w in range(n_waves):
        ops = []
        for d in docs:
            if w == 0:
                ops.append({"op": "insert", "parent": "root",
                            "field": "kids", "after": None,
                            "nodes": [{"id": f"{d}-n0", "value": 0}]})
            else:
                prev = f"{d}-n{w - 1}"
                ops.append({"op": "transaction",
                            "constraints": [{"nodeExists": prev}],
                            "edits": [
                                {"op": "insert", "parent": "root",
                                 "field": "kids", "after": prev,
                                 "nodes": [{"id": f"{d}-n{w}",
                                            "value": w}]},
                                {"op": "setValue", "id": prev,
                                 "value": w * 10}]})
        waves.append(encode_tree_batch(ops))
    return waves


def _tree_parity(docs, waves, serial_outs, eng_s, pipe):
    from fluidframework_tpu.ops.tree_kernel import tree_state_digest
    eng_p, outs_p, stats = pipe
    for b, (a, c) in enumerate(zip(serial_outs, outs_p)):
        assert np.array_equal(np.asarray(a["seq"]),
                              np.asarray(c["seq"])), f"seqs diverge @{b}"
        assert a["nacked"] == c["nacked"] == 0, b
    d_s = np.asarray(tree_state_digest(eng_s.store.state))
    d_p = np.asarray(tree_state_digest(eng_p.store.state))
    assert (d_s == d_p).all(), "merged tree digests diverge"
    for d in (docs[0], docs[-1]):
        assert eng_s.to_dict(d) == eng_p.to_dict(d), d
    assert stats["waves"] == len(waves)
    assert eng_p._ingest_inflight() == 0
    eng_p._check_poisoned()


def test_tree_records_pipelined_parity():
    """Pipelined tree record ingest (prepacked wire on the pack worker)
    must be observationally identical to the serial ingest_records walk
    — same seqs, same merged trees (digests), same to_dict."""
    eng_s, docs = _mk_tree_engine()
    eng_p, _ = _mk_tree_engine()
    waves = _tree_waves(docs, 5)
    n = len(docs)
    serial_outs = [eng_s.ingest_records(docs, [1] * n, [w + 1] * n,
                                        [0] * n, b)
                   for w, b in enumerate(waves)]
    with PipelinedIngestExecutor(eng_p, depth=3) as ex:
        tickets = [ex.submit(docs, [1] * n, [w + 1] * n, [0] * n, b)
                   for w, b in enumerate(waves)]
        ex.drain()
        outs_p = [t.result() for t in tickets]
        stats = ex.stats()
    _tree_parity(docs, waves, serial_outs, eng_s, (eng_p, outs_p, stats))


def test_tree_flat_pipelined_parity():
    """The unified flat path under the executor: pre-encoded leaf
    records (rows= fast path) match the serial walk, and a width-coded
    u32 wave (padded id/value tables past 64k) merges identically."""
    from fluidframework_tpu.server.tree_wire import encode_leaf_records
    eng_s, docs = _mk_tree_engine()
    eng_p, _ = _mk_tree_engine()
    n = len(docs)
    waves = []
    for w in range(4):
        waves.append(encode_leaf_records(
            ["root"] * n, ["kids"] * n, [f"{d}-f{w}" for d in docs],
            [w] * n, ["leaf"] * n,
            [None if w == 0 else f"{d}-f{w - 1}" for d in docs]))
    # the last wave crosses the u16 table budget: unused padding entries
    # force the u32 id/value lanes without changing the ops
    waves[-1] = dict(waves[-1])
    waves[-1]["ids"] = list(waves[-1]["ids"]) + \
        [f"pad{i}" for i in range(0x10000)]
    waves[-1]["values"] = list(waves[-1]["values"]) + \
        list(range(0x10000))
    assert eng_s._wire_eligible(waves[-1])
    rows_s = np.array([eng_s.doc_row(d) for d in docs], np.int32)
    rows_p = np.array([eng_p.doc_row(d) for d in docs], np.int32)
    serial_outs = [eng_s.ingest_records(None, [1] * n, [w + 1] * n,
                                        [0] * n, b, rows=rows_s)
                   for w, b in enumerate(waves)]
    with PipelinedIngestExecutor(eng_p, depth=3) as ex:
        tickets = [ex.submit(None, [1] * n, [w + 1] * n, [0] * n, b,
                             rows=rows_p)
                   for w, b in enumerate(waves)]
        ex.drain()
        outs_p = [t.result() for t in tickets]
        stats = ex.stats()
    _tree_parity(docs, waves, serial_outs, eng_s, (eng_p, outs_p, stats))


def test_tree_pipelined_nacked_wave_discards_prepack():
    """A clientSeq gap mid-stream nacks one op of one wave: the prepack
    (packed ahead of sequencing) must be discarded and the wave repacked
    with the keep mask — nacked records apply nowhere, later waves land."""
    eng, docs = _mk_tree_engine()
    n = len(docs)
    waves = _tree_waves(docs, 4)
    with PipelinedIngestExecutor(eng, depth=3) as ex:
        tickets = []
        for w, b in enumerate(waves):
            cs = [w + 1] * n
            if w == 3:
                cs = list(cs)
                cs[3] = 99          # gap: doc t3's last-wave op nacks
            tickets.append(ex.submit(docs, [1] * n, cs, [0] * n, b))
        ex.drain()
        outs = [t.result() for t in tickets]
    assert [o["nacked"] for o in outs] == [0, 0, 0, 1]
    assert outs[3]["seq"][3] < 0
    bad = docs[3]
    assert eng.has_node(bad, f"{bad}-n2")
    assert not eng.has_node(bad, f"{bad}-n3")   # nacked transaction
    good = docs[0]
    for w in range(4):
        assert eng.has_node(good, f"{good}-n{w}"), w
    # recovery replays the same trees (nacked records never logged)
    from fluidframework_tpu.server.serving import TreeServingEngine
    want = {d: eng.to_dict(d) for d in docs}
    revived = TreeServingEngine.load(eng.summarize(), eng.log)
    assert {d: revived.to_dict(d) for d in docs} == want


def test_submit_after_close_and_result_order():
    eng = _mk_engine()
    waves = _typing_waves(2)
    rows = _rows(eng)
    ex = PipelinedIngestExecutor(eng, depth=2)
    t0 = ex.submit(rows, **waves[0])
    t1 = ex.submit(rows, **waves[1])
    ex.drain()
    s0 = np.asarray(t0.result()["seq"]).reshape(-1)
    s1 = np.asarray(t1.result()["seq"]).reshape(-1)
    # FIFO: wave 0 sequenced strictly before wave 1 on every doc
    assert (s1 > s0).all()
    ex.close()
    with pytest.raises(RuntimeError):
        ex.submit(rows, **waves[0])
