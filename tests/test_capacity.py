"""Capacity plane (ISSUE 19): census accuracy, device exactness,
idle-age correctness, live exposure.

Acceptance criteria pinned here:

1. **Host accuracy** — hydrating a batch of fresh docs moves the
   ledger's host total by within 15% of the ``tracemalloc`` delta for
   the same window (the sizing constants are measurements, not vibes).
2. **Device exactness** — an engine's device charge equals the
   ``.nbytes`` sum of its store's live jax arrays, and those arrays are
   the ones ``jax.live_arrays()`` reports.
3. **Census speed** — a full census (device walk included) at
   bench-like scale completes in < 50 ms.
4. **Idle-age correctness** — after a seeded Zipf storm the top-K
   coldest rows carry the EXACT stamp of their last touch and are
   provably untouched since (oracle comparison), both at the tracker
   and through the columnar door's drain pass.
5. **Exposure** — the capacity gauges ride a live partitioned
   ``/metrics`` exposition, survive the ``tools/healthz.py`` parser
   round-trip with partition-labeled rows intact, and every flight
   dump embeds the census + a metrics snapshot.
"""

import gc
import importlib.util
import json
import os
import random
import time
import tracemalloc
import urllib.request

import numpy as np
import pytest

from fluidframework_tpu.server import native_deli
from fluidframework_tpu.server.serving import StringServingEngine
from fluidframework_tpu.utils import capacity, flight_recorder
from fluidframework_tpu.utils import slo as slo_mod
from fluidframework_tpu.utils import telemetry, timeseries, tracing

pytestmark = [pytest.mark.telemetry]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tool(name):
    """Load a tools/*.py script as a module (tools/ is not a package)."""
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _host_total(engine) -> int:
    return sum(engine._capacity_report()["host"].values())


def _insert(text, cseq=1):
    return {"mt": "insert", "pos": 0, "kind": 0, "text": text,
            "props": None, "clientSeq": cseq}


# ------------------------------------------------------------ idle tracker

class TestIdleAgeTracker:
    def test_zipf_storm_coldest_rows_provably_untouched(self):
        """Seeded Zipf storm against a fake clock: the top-K coldest
        rows report the EXACT stamp of their last touch, matching an
        oracle that recorded every scatter — so "untouched since tick
        T" is a provable statement, not an estimate."""
        clock = {"t": 0.0}
        tr = capacity.IdleAgeTracker(clock=lambda: clock["t"])
        rng = random.Random(19)
        n_rows = 256
        oracle = {}
        tr.touch(np.arange(n_rows))            # everyone resident at t=0
        oracle.update({r: 0.0 for r in range(n_rows)})
        weights = [1.0 / (i + 1) for i in range(n_rows)]   # Zipf s=1
        for w in range(1, 160):
            clock["t"] = float(w)
            sel = sorted(set(rng.choices(range(n_rows),
                                         weights=weights, k=32)))
            tr.touch(np.asarray(sel, dtype=np.int64))
            for r in sel:
                oracle[r] = float(w)
        clock["t"] = 500.0
        cold = tr.coldest(10)
        assert len(cold) == 10
        for row in cold:
            # exact stamp: the row was last touched at last_touch and
            # the oracle agrees nothing touched it after
            assert row["last_touch"] == oracle[row["row"]]
            assert row["idle_s"] == 500.0 - row["last_touch"]
        # the reported stamps are exactly the 10 oldest in the oracle
        # (as a multiset — ties may resolve to any of the tied rows)
        want = sorted(oracle.values())[:10]
        assert sorted(r["last_touch"] for r in cold) == want
        snap = tr.snapshot()
        assert snap["resident_rows"] == n_rows
        assert snap["touch_windows"] == 160
        assert snap["idle_max_s"] == 500.0 - min(oracle.values())

    def test_grows_on_demand_and_untouched_rows_not_resident(self):
        tr = capacity.IdleAgeTracker(capacity=4,
                                     clock=lambda: 7.0)
        tr.touch(np.array([900]))              # far past the capacity
        assert tr.last_touch(900) == 7.0
        assert tr.last_touch(1) is None
        assert list(tr.resident_rows()) == [900]
        assert tr.snapshot()["resident_rows"] == 1

    def test_idle_age_histogram_is_a_snapshot(self):
        ages = np.array([0.5, 2.0, 2.0, 40.0])
        h = capacity.idle_age_histogram(ages)
        assert h.n == 4
        assert h.sum_ms == pytest.approx(44.5)
        assert sum(h.counts) == 4


# ---------------------------------------------------------------- accuracy

class TestCensusAccuracy:
    def test_host_total_within_15pct_of_tracemalloc_delta(self):
        """Hydrate 256 fresh docs with distinct ~8-12 KB texts; the
        ledger's host delta must land within 15% of what tracemalloc
        saw for the same window (text payloads dominate, so the
        calibrated container constants only need to be sane)."""
        batch = 64
        n = 256
        rng = random.Random(19)
        engine = StringServingEngine(n_docs=n + batch, capacity=8,
                                     batch_window=batch)
        docs = [f"cap-{i:04d}" for i in range(n)]
        for d in docs:
            engine.connect(d, 1)
        # warm the jit caches with one full same-shaped batch so the
        # measured window holds doc memory, not compile-cache growth
        for i in range(batch):
            w = f"warm-{i}"
            engine.connect(w, 1)
            engine.submit(w, 1, 1, 0, _insert("w" * 4096))
        engine.flush()
        tracing.TRACER.clear()
        gc.collect()
        host0 = _host_total(engine)
        tracemalloc.start()
        gc.collect()
        base = tracemalloc.get_traced_memory()[0]
        try:
            for i, d in enumerate(docs):
                text = f"{i:04x}" * rng.randint(2048, 3072)  # 8-12 KB
                msg, nack = engine.submit(d, 1, 1, 0, _insert(text))
                assert nack is None
            engine.flush()
            tracing.TRACER.clear()     # span ring is not doc memory
            gc.collect()
            actual = tracemalloc.get_traced_memory()[0] - base
        finally:
            tracemalloc.stop()
        ledger = _host_total(engine) - host0
        assert actual > n * 4096       # texts really were measured
        rel = abs(ledger - actual) / actual
        assert rel < 0.15, (
            f"ledger delta {ledger} vs tracemalloc {actual} "
            f"({rel:.1%} off)")

    def test_device_charge_matches_live_arrays_exactly(self):
        jax = pytest.importorskip("jax")
        engine = StringServingEngine(n_docs=8, capacity=32)
        engine.connect("dv", 1)
        engine.submit("dv", 1, 1, 0, _insert("hello"))
        engine.flush()
        charged = sum(engine._capacity_report()["device"].values())
        leaves = [a for a in jax.tree_util.tree_leaves(engine.store.state)
                  if isinstance(a, jax.Array)]
        assert charged == sum(int(a.nbytes) for a in leaves)
        live = {id(a) for a in jax.live_arrays()}
        assert all(id(a) in live for a in leaves)
        walk = capacity.device_census()
        if walk["available"]:
            assert walk["total_bytes"] >= charged

    def test_full_census_under_50ms_at_bench_scale(self):
        engine = StringServingEngine(n_docs=2048, capacity=64)
        for i in range(1024):
            engine.doc_row(f"scale-{i}")
        capacity.LEDGER.census(top_k=8, device=True)      # warm the walk
        best = min(capacity.LEDGER.census(top_k=8,
                                          device=True)["census_ms"]
                   for _ in range(3))
        assert best < 50.0, f"census took {best:.1f} ms"
        del engine


# ------------------------------------------------------------------ ledger

class _FixedOwner:
    def __init__(self, host_bytes, docs=3):
        self._host = host_bytes
        self._docs = docs

    def report(self):
        return capacity.report(host={"stuff": self._host},
                               docs=self._docs,
                               heaviest=[("big-doc", self._host)])


class TestCapacityLedger:
    def test_budget_headroom_and_gauges(self):
        led = capacity.CapacityLedger()
        owner = _FixedOwner(60)
        led.register("fixed", owner.report)
        led.set_budget(100)
        c = led.census(device=False)
        assert c["host"]["total_bytes"] == 60
        assert c["headroom"] == pytest.approx(0.4)
        assert c["top"]["heaviest"][0]["doc"] == "big-doc"
        reg = telemetry.MetricsRegistry()
        led.publish_gauges(registry=reg, device_ttl_s=60.0)
        snap = reg.snapshot()
        assert snap["doc_resident_bytes"] == 60.0
        assert snap["doc_memory_budget_bytes"] == 100.0
        assert snap["memory_budget_headroom"] == pytest.approx(0.4)
        assert snap["resident_docs_total"] == 3.0
        led.set_budget(None)
        assert led.census(device=False)["headroom"] == 1.0

    def test_dead_owner_silently_leaves_the_census(self):
        led = capacity.CapacityLedger()
        owner = _FixedOwner(10)
        key = led.register("mortal", owner.report)
        assert led.census(device=False)["host"]["by_owner"] == {key: 10}
        del owner
        gc.collect()
        assert led.census(device=False)["host"]["by_owner"] == {}

    def test_broken_provider_lands_in_errors_not_a_crash(self):
        led = capacity.CapacityLedger()
        def bad():
            raise RuntimeError("boom")
        led.register("bad", bad)
        c = led.census(device=False)
        assert "boom" in c["errors"]["bad"]
        assert c["host"]["total_bytes"] == 0

    def test_memory_budget_headroom_is_a_default_slo(self):
        specs = {s.name for s in slo_mod.default_slos()}
        assert "memory_budget_headroom" in specs

    def test_flight_dump_embeds_census_and_metrics(self, tmp_path):
        rec = flight_recorder.FlightRecorder()
        rec.note("capacity_test", x=1)
        path = rec.dump("capacity-plane-test",
                        path=str(tmp_path / "dump.jsonl"), force=True)
        header = flight_recorder.load_dump(path)[0]
        census = header["capacity_census"]
        assert isinstance(census, dict), census   # not a repr(error)
        assert "host" in census and "idle" in census
        assert census["host"]["total_bytes"] >= 0
        assert isinstance(header["metrics_snapshot"], dict)


# ------------------------------------------------------- door + exposition

def _wave(client, rows, cseqs, marker="m_"):
    from fluidframework_tpu.server.columnar_ingress import _OP_DTYPE
    ops = np.zeros(len(rows), _OP_DTYPE)
    for i, r in enumerate(rows):
        ops[i] = (r, 0, 0, 0, 0, cseqs[i], 0)
    client.send_ops([marker], ops)


def _drain(client, expect, deadline_s=20.0):
    n = 0
    deadline = time.time() + deadline_s
    while n < expect:
        assert time.time() < deadline, f"ack drain stuck at {n}/{expect}"
        fr = client.recv_json()
        assert fr.get("t") == "acks", fr
        n += len(fr["acks"])


class TestDoorIdleTracking:
    @pytest.mark.skipif(not native_deli.available(),
                        reason="native sequencer unavailable")
    def test_columnar_zipf_storm_cold_docs_surface_in_census(self):
        """Cold docs written once early then abandoned while hot docs
        keep storming: the door's drain-pass idle tracker ranks the
        cold rows coldest with stamps from before the storm, and the
        global census resolves them back to doc ids."""
        from fluidframework_tpu.server.columnar_ingress import (
            ColumnarAlfred, ColumnarClient)
        engine = StringServingEngine(n_docs=32, capacity=64,
                                     batch_window=10 ** 9,
                                     sequencer="native")
        door = ColumnarAlfred(engine, window_min_rows=1,
                              window_ms=2.0).start_in_thread()
        try:
            rng = random.Random(7)
            cold_docs = [f"cold-{i}" for i in range(4)]
            hot_docs = [f"hot-{i}" for i in range(8)]
            cl = ColumnarClient("127.0.0.1", door.port)
            rows = cl.join(cold_docs + hot_docs)
            cseq = {d: 0 for d in cold_docs + hot_docs}

            def send(docs):
                for d in docs:
                    cseq[d] += 1
                _wave(cl, [rows[d] for d in docs],
                      [cseq[d] for d in docs])
                _drain(cl, len(docs))

            send(cold_docs + hot_docs)          # everyone touched once
            t_mark = time.monotonic()
            weights = [1.0 / (i + 1) for i in range(len(hot_docs))]
            for _ in range(6):                  # the storm never looks back
                send(sorted(set(rng.choices(hot_docs,
                                            weights=weights, k=6))))
            cold = door.idle_ages.coldest(len(cold_docs))
            assert {r["row"] for r in cold} \
                == {rows[d] for d in cold_docs}
            for r in cold:
                assert r["last_touch"] <= t_mark, \
                    "a cold doc was touched during the storm"
            # the global census resolves the rows back to doc ids
            c = capacity.LEDGER.census(top_k=32, device=False)
            resolved = {e.get("doc") for e in c["top"]["coldest"]
                        if e["owner"].startswith("ColumnarAlfred")}
            assert set(cold_docs) <= resolved
            assert any(k.startswith("ColumnarAlfred")
                       for k in c["idle"])
            cl.close()
        finally:
            door.stop()


class TestPartitionedScrape:
    def test_partitioned_metrics_roundtrip_through_healthz(self, capsys):
        """A live ``PartitionedStringServing`` behind the columnar door:
        the capacity gauges ride ``/metrics``, partition-labeled rows
        survive the Prometheus exposition AND the ``tools/healthz.py``
        parser round-trip, ``/debug/memory`` serves partition-labeled
        owners, and the healthz CLI renders the capacity panel."""
        from fluidframework_tpu.server.columnar_ingress import (
            ColumnarAlfred, ColumnarClient)
        from fluidframework_tpu.server.partitioned import (
            PartitionedStringServing)
        healthz = _tool("healthz")
        svc = PartitionedStringServing(n_partitions=2,
                                       docs_per_partition=8)
        door = ColumnarAlfred(svc, window_min_rows=1, window_ms=2.0,
                              pipeline_depth=2).start_in_thread()
        ops = door.start_ops()
        try:
            # one doc per partition, found by hashing candidate names
            need, docs, i = {0, 1}, [], 0
            while need:
                d = f"cap-{i}"
                i += 1
                p = svc.partition_of_doc(d)
                if p in need:
                    need.discard(p)
                    docs.append(d)
            cl = ColumnarClient("127.0.0.1", door.port)
            rows = cl.join(docs)
            _wave(cl, [rows[d] for d in docs], [1] * len(docs))
            _drain(cl, len(docs))
            ops.tick_once()

            with urllib.request.urlopen(ops.url + "/metrics",
                                        timeout=10) as resp:
                text = resp.read().decode("utf-8")
            # partition labels survive the exposition...
            assert 'partition="0"' in text and 'partition="1"' in text
            metrics, kinds = healthz.parse_prometheus(text)
            # ...and the parser round-trip reconstructs labeled keys
            assert any("partition=0" in k for k in metrics)
            assert any("partition=1" in k for k in metrics)
            assert metrics["doc_resident_bytes"] > 0
            assert kinds["doc_resident_bytes"] == "gauge"
            assert metrics["resident_docs_total"] >= len(docs)
            assert metrics["memory_budget_headroom"] == 1.0

            # the parsed sample feeds the same store healthz --url uses
            store = timeseries.TimeSeriesStore(
                registry=telemetry.MetricsRegistry())
            store.ingest_sample(time.time(), metrics, kinds=kinds)
            panel = healthz.render_capacity(store=store)
            assert panel.startswith("capacity")
            assert "host" in panel and "docs" in panel

            # /debug/memory carries partition-labeled owners
            with urllib.request.urlopen(ops.url + "/debug/memory",
                                        timeout=10) as resp:
                census = json.loads(resp.read())
            owners = census["host"]["by_owner"]
            assert any("[part0]" in o for o in owners), owners
            assert any("[part1]" in o for o in owners), owners
            live_panel = healthz.render_capacity(census=census)
            assert "[part0]" in live_panel or "part0" in live_panel \
                or live_panel.startswith("capacity")

            # per-partition memory rollup off the labeled registry
            roll = svc.memory_rollup()
            assert [r["partition"] for r in roll["partitions"]] == [0, 1]
            assert roll["host_bytes"] \
                == sum(r["host_bytes"] for r in roll["partitions"])

            # the operator CLI end to end: sparklines + capacity panel
            rc = healthz.main(["--url", ops.url, "--interval", "0.05",
                               "--polls", "2", "--no-slo"])
            out = capsys.readouterr().out
            assert rc == 0
            assert "capacity" in out
            cl.close()
        finally:
            door.stop()
