"""Mega-doc (segment-axis-sharded) kernel: parity with the single-device
merge-tree kernel on the virtual 8-device CPU mesh.

The mega-doc path is this framework's sequence/context parallelism
(SURVEY.md §5.7): one very long document's segment slots are sharded across
the mesh, position resolution is a distributed prefix sum over ICI, and the
content digest must equal ``string_state_digest`` of the same op stream
applied unsharded.
"""

import numpy as np
import jax.numpy as jnp

from fluidframework_tpu.ops.megadoc_kernel import (
    apply_megadoc_batch, compact_megadoc, create_megadoc_state,
    make_megadoc_mesh, megadoc_digest, rebalance_megadoc, visible_runs,
)
from fluidframework_tpu.ops.merge_tree_kernel import (
    StringState, apply_string_batch, string_state_digest,
)
from fluidframework_tpu.testing.synthetic import typing_storm

ORDER = ("kind", "a0", "a1", "a2", "seq", "client", "ref_seq")


def _ops(n_docs, n_ops, seed=0, start_seq=1):
    planes, next_seq = typing_storm(n_docs, n_ops, seed=seed,
                                    start_seq=start_seq)
    return tuple(jnp.asarray(planes[k]) for k in ORDER), next_seq


def test_megadoc_matches_single_device():
    mesh = make_megadoc_mesh(8)
    n_docs, n_ops, cap_per_shard = 3, 24, 64
    ops, _ = _ops(n_docs, n_ops)

    single = apply_string_batch(
        StringState.create(n_docs, 8 * cap_per_shard), *ops)
    ref_digest = np.asarray(string_state_digest(single))

    state = create_megadoc_state(mesh, n_docs, cap_per_shard)
    state = apply_megadoc_batch(mesh, state, *ops)
    assert not np.asarray(state.overflow).any()
    assert np.array_equal(np.asarray(megadoc_digest(mesh, state)), ref_digest)
    # order-sensitive oracle: the additive digest is blind to reordered runs
    assert visible_runs(state) == visible_runs(single)


def test_megadoc_multiple_rounds_threads_state():
    mesh = make_megadoc_mesh(8)
    n_docs, n_ops, cap_per_shard = 2, 12, 64
    state = create_megadoc_state(mesh, n_docs, cap_per_shard)
    ref = StringState.create(n_docs, 8 * cap_per_shard)
    seq = 1
    for r in range(3):
        ops, seq = _ops(n_docs, n_ops, seed=r, start_seq=seq)
        state = apply_megadoc_batch(mesh, state, *ops)
        ref = apply_string_batch(ref, *ops)
        assert np.array_equal(np.asarray(megadoc_digest(mesh, state)),
                              np.asarray(string_state_digest(ref))), r
        assert visible_runs(state) == visible_runs(ref), r


def test_megadoc_compaction_preserves_digest_and_frees_slots():
    mesh = make_megadoc_mesh(8)
    n_docs, n_ops, cap_per_shard = 2, 32, 64
    ops, next_seq = _ops(n_docs, n_ops)
    state = apply_megadoc_batch(
        mesh, create_megadoc_state(mesh, n_docs, cap_per_shard), *ops)
    before = np.asarray(megadoc_digest(mesh, state))
    used_before = np.asarray(state.count).sum()
    min_seq = np.full((n_docs,), next_seq - 1, np.int32)  # window closed
    state = compact_megadoc(mesh, state, min_seq)
    assert np.array_equal(np.asarray(megadoc_digest(mesh, state)), before)
    assert np.asarray(state.count).sum() <= used_before
    # digest must stay correct after post-compaction ops (stale slots beyond
    # count left by the compaction sort must not leak into the digest)
    ops2, _ = _ops(n_docs, 8, seed=9, start_seq=next_seq)
    state = apply_megadoc_batch(mesh, state, *ops2)
    ref = apply_string_batch(StringState.create(n_docs, 8 * cap_per_shard),
                             *_ops(n_docs, n_ops)[0])
    from fluidframework_tpu.ops.merge_tree_kernel import compact_string_state
    ref = compact_string_state(ref, jnp.asarray(min_seq))
    ref = apply_string_batch(ref, *ops2)
    assert np.array_equal(np.asarray(megadoc_digest(mesh, state)),
                          np.asarray(string_state_digest(ref)))
    assert visible_runs(state) == visible_runs(ref)


def test_megadoc_rebalance_spreads_load_and_preserves_parity():
    """Small shards survive a long stream via rebalance between rounds."""
    mesh = make_megadoc_mesh(8)
    n_docs, cap_per_shard = 2, 16
    state = create_megadoc_state(mesh, n_docs, cap_per_shard)
    ref = StringState.create(n_docs, 8 * cap_per_shard)
    seq = 1
    for r in range(5):
        ops, seq = _ops(n_docs, 6, seed=r, start_seq=seq)
        state = apply_megadoc_batch(mesh, state, *ops)
        ref = apply_string_batch(ref, *ops)
        assert not np.asarray(state.overflow).any(), r
        state = rebalance_megadoc(mesh, state)
        counts = np.asarray(state.count)
        spread = counts.max(axis=1) - counts.min(axis=1)
        assert (spread <= 1).all()  # dealt evenly within each doc
        assert np.array_equal(np.asarray(megadoc_digest(mesh, state)),
                              np.asarray(string_state_digest(ref))), r
        assert visible_runs(state) == visible_runs(ref), r


def test_megadoc_overflow_flag_not_corruption():
    mesh = make_megadoc_mesh(8)
    n_docs, cap_per_shard = 1, 4  # absurdly small shards
    ops, _ = _ops(n_docs, 64)
    state = apply_megadoc_batch(
        mesh, create_megadoc_state(mesh, n_docs, cap_per_shard), *ops)
    counts = np.asarray(state.count)
    assert np.asarray(state.overflow).any()  # flagged, not crashed
    assert (counts <= cap_per_shard).all()


def _planes_from_msgs(msgs, n_ops_pad=None):
    """Convert oracle-sequenced merge-tree messages to (1, O) op planes with
    host-side client/payload/property interning (mirrors TensorStringStore)."""
    from fluidframework_tpu.ops.merge_tree_kernel import PROP_HANDLE_BITS
    from fluidframework_tpu.ops.schema import OpKind
    recs, clients, payloads = [], {}, [None]
    prop_planes, prop_vals = {}, {}
    for m in msgs:
        op = m.contents
        cl = clients.setdefault(m.client_id, len(clients))
        if op["mt"] == "insert":
            if op["kind"] == 1:
                payloads.append(("marker", ""))
                recs.append((int(OpKind.STR_INSERT), op["pos"], 1,
                             len(payloads) - 1, m.seq, cl, m.ref_seq))
            elif op["text"]:
                payloads.append(("text", op["text"]))
                recs.append((int(OpKind.STR_INSERT), op["pos"],
                             len(op["text"]), len(payloads) - 1, m.seq, cl,
                             m.ref_seq))
        elif op["mt"] == "remove":
            recs.append((int(OpKind.STR_REMOVE), op["start"], op["end"], 0,
                         m.seq, cl, m.ref_seq))
        elif op["mt"] == "annotate":
            for key in sorted(op["props"]):
                plane = prop_planes.setdefault(key, len(prop_planes))
                v = op["props"][key]
                h = 0 if v is None else prop_vals.setdefault(repr(v),
                                                             len(prop_vals) + 1)
                recs.append((int(OpKind.STR_ANNOTATE), op["start"],
                             op["end"], (plane << PROP_HANDLE_BITS) | h,
                             m.seq, cl, m.ref_seq))
    o = n_ops_pad or len(recs)
    planes = np.zeros((7, 1, o), np.int32)
    planes[0, :, :] = int(OpKind.NOOP)
    for j, r in enumerate(recs):
        planes[:, 0, j] = r
    return tuple(jnp.asarray(planes[i]) for i in range(7))


def test_megadoc_multiclient_fuzz_matches_single_device():
    """Real multi-client streams (lagging ref_seq → invisible concurrent
    segments) must resolve insert ownership identically to the unsharded
    kernel — the case single-client storms cannot exercise."""
    from tests.test_merge_tree_kernel import collab_stream
    mesh = make_megadoc_mesh(8)
    for seed in range(6):
        _, _, msgs = collab_stream(seed, n_rounds=10, with_annotates=True)
        ops = _planes_from_msgs(msgs)
        single = apply_string_batch(StringState.create(1, 1024), *ops)
        state = create_megadoc_state(mesh, 1, 128)
        state = apply_megadoc_batch(mesh, state, *ops)
        assert not np.asarray(state.overflow).any(), seed
        assert visible_runs(state) == visible_runs(single), seed


def test_megadoc_boundary_insert_orders_before_invisible_concurrent():
    """Regression: a later-sequenced insert at a shard boundary must land
    LEFT of an earlier concurrent insert held by the earlier shard, even
    when that shard's perspective-visible length is zero."""
    from fluidframework_tpu.ops.schema import OpKind
    mesh = make_megadoc_mesh(8)
    I, R = int(OpKind.STR_INSERT), int(OpKind.STR_REMOVE)
    # seq1: client 0 inserts Y(len 2, handle 10) at 0
    # seq2: client 1 removes [0,2) (ref 1)          -> Y tombstoned
    # seq3: client 2 inserts E(len 3, handle 11) at 0 (ref 1: still sees Y)
    # seq4: client 3 inserts L(len 4, handle 12) at 0 (ref 2: sees removal,
    #        NOT E) -> must land before E (leftmost rule)
    recs = [(I, 0, 2, 10, 1, 0, 0), (R, 0, 2, 0, 2, 1, 1),
            (I, 0, 3, 11, 3, 2, 1), (I, 0, 4, 12, 4, 3, 2)]
    planes = np.zeros((7, 1, 4), np.int32)
    for j, r in enumerate(recs):
        planes[:, 0, j] = r
    ops = tuple(jnp.asarray(planes[i]) for i in range(7))
    single = apply_string_batch(StringState.create(1, 64), *ops)

    state = create_megadoc_state(mesh, 1, 8)
    # seed Y onto shard 0 the way a rebalance would place it
    state = apply_megadoc_batch(mesh, state, *(p[:, :1] for p in ops))
    state = rebalance_megadoc(mesh, state)
    assert np.asarray(state.count)[0, 0] == 1  # Y lives on shard 0
    state = apply_megadoc_batch(mesh, state, *(p[:, 1:] for p in ops))
    runs = visible_runs(state)
    assert runs == visible_runs(single)
    assert [r[0] for r in runs[0]] == [12, 11]  # L before E


def test_megadoc_rebalance_refuses_overflowed_state():
    """Overflow means ops were dropped; rebalance must not erase the flag."""
    import pytest
    mesh = make_megadoc_mesh(8)
    ops, _ = _ops(1, 64)
    state = apply_megadoc_batch(
        mesh, create_megadoc_state(mesh, 1, 4), *ops)
    assert np.asarray(state.overflow).any()
    with pytest.raises(ValueError, match="overflow"):
        rebalance_megadoc(mesh, state)
