"""Batched tree kernel vs the SharedTree oracle: convergence fuzz at
merge-tree-suite scale (VERDICT r1 #5) — multi-client concurrent
insert/remove/move/setValue/transaction sessions sequenced by the mock
service, applied to the device store, compared structurally."""

import random

import numpy as np
import pytest

from fluidframework_tpu.models.shared_tree import SharedTree
from fluidframework_tpu.ops.tree_store import TensorTreeStore
from fluidframework_tpu.testing.mocks import MockSequencer


def _strip_ids(d):
    """Oracle to_dict keeps 'id'; compare full shape including ids."""
    return d


def tree_session(seed, n_clients=3, n_rounds=15, ops_per_round=4,
                 with_txns=True):
    """Run an oracle collab session; returns (converged dict, msgs)."""
    rng = random.Random(seed)
    seqr = MockSequencer()
    clients = [SharedTree(f"t", seqr.allocate_client_id())
               for _ in range(n_clients)]
    for c in clients:
        seqr.connect(c)
    msgs = []
    seqr.on_sequenced(msgs.append)

    def random_node(c):
        ids = list(c.kernel.view.nodes)
        return rng.choice(ids)

    for r in range(n_rounds):
        for _ in range(ops_per_round):
            c = rng.choice(clients)
            roll = rng.random()
            try:
                if roll < 0.45 or len(c.kernel.view.nodes) < 4:
                    parent = random_node(c)
                    sibs = c.children(parent, "kids")
                    after = rng.choice([None] + sibs) if sibs else None
                    c.insert(parent, "kids", node_type=None,
                             value=rng.randint(0, 99), after=after)
                elif roll < 0.6:
                    nid = random_node(c)
                    if nid != "root":
                        c.remove(nid)
                elif roll < 0.75:
                    nid, dest = random_node(c), random_node(c)
                    if nid != "root":
                        c.move(nid, dest, "kids")
                elif roll < 0.9 or not with_txns:
                    c.set_value(random_node(c), rng.randint(100, 199))
                else:
                    anchor = random_node(c)

                    def txn(t, anchor=anchor):
                        a = t.insert(anchor, "kids", value=1000)
                        t.insert(a, "kids", value=1001)
                        t.set_value(a, 1002)

                    c.run_transaction(
                        txn, constraints=[{"nodeExists": anchor}])
            except KeyError:
                pass  # the chosen node vanished from this client's view
        seqr.process_some(rng.randint(0, seqr.outstanding))
    seqr.process_all_messages()
    dicts = [c.to_dict() for c in clients]
    for d in dicts[1:]:
        assert d == dicts[0], "oracle replicas diverged (bug in the spec!)"
    return dicts[0], msgs


@pytest.mark.parametrize("seed", range(12))
def test_tree_kernel_matches_oracle_fuzz(seed):
    want, msgs = tree_session(seed)
    store = TensorTreeStore(n_docs=2, capacity=512)
    store.apply_messages((1, m) for m in msgs)   # doc 1; doc 0 stays empty
    assert not store.overflowed().any()
    assert store.to_dict(1) == want
    assert store.to_dict(0) == {"id": "root", "type": None, "value": None}


@pytest.mark.parametrize("seed", [30, 31])
def test_tree_kernel_incremental_batches(seed):
    """State threads correctly across many small apply calls."""
    want, msgs = tree_session(seed, n_rounds=10)
    store = TensorTreeStore(n_docs=1, capacity=512)
    rng = random.Random(seed)
    i = 0
    while i < len(msgs):
        step = rng.randint(1, 5)
        store.apply_messages((0, m) for m in msgs[i:i + step])
        i += step
    assert store.to_dict(0) == want


def test_tree_many_docs_parallel():
    sessions = [tree_session(s, n_rounds=8) for s in range(4)]
    store = TensorTreeStore(n_docs=4, capacity=512)
    interleaved = []
    idx = [0] * 4
    rng = random.Random(0)
    while any(idx[d] < len(sessions[d][1]) for d in range(4)):
        d = rng.randrange(4)
        if idx[d] < len(sessions[d][1]):
            interleaved.append((d, sessions[d][1][idx[d]]))
            idx[d] += 1
    store.apply_messages(interleaved)
    for d in range(4):
        assert store.to_dict(d) == sessions[d][0], f"doc {d}"


def test_tree_undo_subtree_reinsert():
    """The nested-insert path: removing a subtree and re-inserting its spec
    (what undo does) must restore it exactly — including the oracle's
    skip-if-survived rule."""
    seqr = MockSequencer()
    a = SharedTree("t", seqr.allocate_client_id())
    b = SharedTree("t", seqr.allocate_client_id())
    for c in (a, b):
        seqr.connect(c)
    msgs = []
    seqr.on_sequenced(msgs.append)

    x = a.insert("root", "kids", value=1, node_id="x")
    y = a.insert(x, "kids", value=2, node_id="y")
    z = a.insert(y, "kids", value=3, node_id="z")
    seqr.process_all_messages()
    spec = a.kernel.view.subtree_spec(x)
    # concurrent: b moves z out while a removes x's subtree; a then
    # "undoes" by re-inserting the captured spec — z survived elsewhere,
    # so its nested spec must be SKIPPED (subtree and all)
    b.move(z, "root", "kids")
    a.remove(x)
    a._submit_edit({"op": "insert", "parent": "root", "field": "kids",
                    "after": None, "nodes": [spec]})
    seqr.process_all_messages()
    assert a.to_dict() == b.to_dict()

    store = TensorTreeStore(n_docs=1, capacity=128)
    store.apply_messages((0, m) for m in msgs)
    assert store.to_dict(0) == a.to_dict()


def test_tree_capacity_overflow_sticky():
    seqr = MockSequencer()
    a = SharedTree("t", seqr.allocate_client_id())
    seqr.connect(a)
    msgs = []
    seqr.on_sequenced(msgs.append)
    for i in range(30):
        a.insert("root", "kids", value=i)
    seqr.process_all_messages()
    store = TensorTreeStore(n_docs=1, capacity=16)
    store.apply_messages((0, m) for m in msgs)
    assert store.overflowed()[0]
