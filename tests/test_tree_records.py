"""The tree columnar record wire format (server/tree_wire.py):
encode→decode round-trips, ingest_records vs per-op submit parity,
durable TreeRecordOps codec, raw-plane recovery, and bounds rejection."""

import numpy as np
import pytest

from fluidframework_tpu.models.shared_tree import SharedTree
from fluidframework_tpu.server.serving import (
    TreeRecordOps, TreeServingEngine,
)
from fluidframework_tpu.server.tree_wire import (
    TreeBatchEncoder, decode_op, encode_tree_batch,
)

from tests.test_tree_kernel import tree_session


def _normalize(op):
    """Encoder-canonical form of an op dict: every spec carries explicit
    type/value keys; a constraint-free one-edit transaction is its edit."""
    kind = op["op"]
    if kind == "insert":
        def norm_spec(s):
            out = {"id": s["id"], "type": s.get("type"),
                   "value": s.get("value")}
            kids = {f: [norm_spec(c) for c in cs]
                    for f, cs in (s.get("children") or {}).items() if cs}
            if kids:
                out["children"] = kids
            return out
        return {"op": "insert", "parent": op["parent"],
                "field": op["field"], "after": op.get("after"),
                "nodes": [norm_spec(s) for s in op["nodes"]]}
    if kind == "transaction":
        cons = [c for c in op.get("constraints", ())]
        edits = [_normalize(e) for e in op["edits"]]
        if not cons and len(edits) == 1 and edits[0]["op"] == "insert":
            return edits[0]
        out = {"op": "transaction", "edits": edits}
        if cons:
            out["constraints"] = cons
        return out
    if kind == "move":
        return {"op": "move", "id": op["id"], "parent": op["parent"],
                "field": op["field"], "after": op.get("after")}
    return dict(op)


@pytest.mark.parametrize("seed", range(4))
def test_encode_decode_round_trip_fuzz(seed):
    """decode(encode(op)) ≡ op (canonical form) over the fuzz corpus."""
    _, msgs = tree_session(seed)
    ops = [m.contents for m in msgs]
    enc = TreeBatchEncoder()
    for op in ops:
        enc.add(op)
    b = enc.batch()
    rec_op = np.asarray(b["rec_op"])
    for i, op in enumerate(ops):
        sel = np.flatnonzero(rec_op == i)
        recs = [tuple(int(v) for v in b["recs"][j]) for j in sel]
        got = decode_op(recs, b["ids"], b["fields"], b["types"],
                        b["values"])
        assert _normalize(got) == _normalize(op), f"op {i}"


def test_decode_preserves_multinode_and_nested():
    op = {"op": "insert", "parent": "root", "field": "kids",
          "after": "anchor",
          "nodes": [
              {"id": "a", "type": "t", "value": 1,
               "children": {"f1": [{"id": "a1", "type": None,
                                    "value": None},
                                   {"id": "a2", "type": "u",
                                    "value": [1, 2]}],
                            "f2": [{"id": "a3", "type": None,
                                    "value": "x"}]}},
              {"id": "b", "type": None, "value": None}]}
    b = encode_tree_batch([op, {"op": "insert", "parent": "root",
                                "field": "kids", "after": "anchor",
                                "nodes": [{"id": "c"}]}])
    rec_op = np.asarray(b["rec_op"])
    sel = np.flatnonzero(rec_op == 0)
    recs = [tuple(int(v) for v in b["recs"][j]) for j in sel]
    got = decode_op(recs, b["ids"], b["fields"], b["types"], b["values"])
    assert _normalize(got) == _normalize(op)


def _mk(n_docs=6):
    eng = TreeServingEngine(n_docs=n_docs, capacity=256,
                            batch_window=10 ** 9, sequencer="native")
    docs = [f"d{i}" for i in range(n_docs)]
    for d in docs:
        eng.connect(d, 1)
    return eng, docs


def _fuzz_waves(docs, seeds):
    """Per-doc fuzz sessions re-cut into cross-doc ingest waves."""
    per_doc = {d: [m.contents for m in tree_session(s, n_rounds=6)[1]]
               for d, s in zip(docs, seeds)}
    waves = []
    w = 0
    while any(per_doc.values()):
        ids, ops = [], []
        for d in docs:
            if per_doc[d]:
                ids.append(d)
                ops.append(per_doc[d].pop(0))
        waves.append((ids, ops))
        w += 1
    return waves


def test_ingest_records_matches_per_op_submit():
    """The columnar record path and the per-op submit path produce the
    same trees for the same op streams (fuzz corpus incl. transactions,
    nested inserts, moves, removes)."""
    eng_a, docs = _mk()
    eng_b, _ = _mk()
    waves = _fuzz_waves(docs, range(10, 16))
    for w, (ids, ops) in enumerate(waves):
        cseq = [w + 1] * len(ids)
        res = eng_a.ingest_batch(ids, [1] * len(ids), cseq,
                                 [0] * len(ids), ops)
        assert res["nacked"] == 0
        for d, op in zip(ids, ops):
            _, nack = eng_b.submit(d, 1, w + 1, 0, op)
            assert nack is None
    for d in docs:
        assert eng_a.to_dict(d) == eng_b.to_dict(d), d


def test_ingest_records_oracle_parity_and_log_replay():
    eng, docs = _mk()
    waves = _fuzz_waves(docs, range(20, 26))
    for w, (ids, ops) in enumerate(waves):
        eng.ingest_batch(ids, [1] * len(ids), [w + 1] * len(ids),
                         [0] * len(ids), ops)
    for d in docs[:3]:
        oracle = SharedTree(d, 999)
        for m in eng._doc_log_messages(d):
            oracle.process_core(m, local=False)
        assert eng.to_dict(d) == oracle.to_dict(), d


def test_tree_records_summary_tail_recovery():
    """Raw-plane tail replay: summary mid-stream, more record batches,
    then load() rebuilds the same trees (and sequencing continues)."""
    eng, docs = _mk()
    waves = _fuzz_waves(docs, range(30, 36))
    cut = len(waves) // 2
    for w, (ids, ops) in enumerate(waves[:cut]):
        eng.ingest_batch(ids, [1] * len(ids), [w + 1] * len(ids),
                         [0] * len(ids), ops)
    summary = eng.summarize()
    for w, (ids, ops) in enumerate(waves[cut:]):
        eng.ingest_batch(ids, [1] * len(ids), [cut + w + 1] * len(ids),
                         [0] * len(ids), ops)
    want = {d: eng.to_dict(d) for d in docs}
    revived = TreeServingEngine.load(summary, eng.log)
    assert {d: revived.to_dict(d) for d in docs} == want
    # sequencing resumes past the tail: a fresh op lands, same on both
    n_sent = sum(1 for ids, _ in waves if docs[0] in ids)
    op = {"op": "insert", "parent": "root", "field": "kids",
          "after": None, "nodes": [{"id": "fresh", "type": None,
                                    "value": 7}]}
    for e in (eng, revived):
        r = e.ingest_batch([docs[0]], [1], [n_sent + 1], [0], [op])
        assert r["nacked"] == 0
    assert revived.to_dict(docs[0]) == eng.to_dict(docs[0])


def test_tree_records_nacks_drop_records_everywhere():
    eng, docs = _mk()
    d0, d1 = docs[0], docs[1]
    # clientSeq gap on the middle op: its records must not apply nor log
    res = eng.ingest_batch(
        [d0, d0, d1], [1] * 3, [1, 99, 1], [0] * 3,
        [{"op": "insert", "parent": "root", "field": "kids",
          "after": None, "nodes": [{"id": "x0"}]},
         {"op": "insert", "parent": "root", "field": "kids",
          "after": None, "nodes": [{"id": "x1"}]},
         {"op": "insert", "parent": "root", "field": "kids",
          "after": None, "nodes": [{"id": "y0"}]}])
    assert res["nacked"] == 1 and res["seq"][1] < 0
    assert eng.has_node(d0, "x0") and not eng.has_node(d0, "x1")
    assert eng.has_node(d1, "y0")
    # the durable record kept only the acked ops
    msgs = eng._doc_log_messages(d0)
    assert [m.contents["nodes"][0]["id"] for m in msgs] == ["x0"]
    # and recovery agrees
    revived = TreeServingEngine.load(eng.summarize(), eng.log)
    assert revived.to_dict(d0) == eng.to_dict(d0)


def test_malformed_record_batches_rejected_before_sequencing():
    eng, docs = _mk()
    d = docs[0]
    seq_before = eng.deli.doc_seq(d)
    base = {"rec_op": np.zeros(1, np.int64),
            "recs": np.zeros((1, 8), np.int32),
            "ids": ["n"], "fields": ["f"], "types": [], "values": []}

    def bad(**kw):
        b = dict(base)
        b.update(kw)
        return b

    recs_badkind = np.zeros((1, 8), np.int32)
    recs_badkind[0, 0] = 99
    with pytest.raises(ValueError, match="kind out of range"):
        eng.ingest_records([d], [1], [1], [0], bad(recs=recs_badkind))
    recs_badnode = np.zeros((1, 8), np.int32)
    recs_badnode[0, 0] = 9   # INSERT_SOLO
    recs_badnode[0, 1] = 5   # out of ids table
    with pytest.raises(ValueError, match="node handle"):
        eng.ingest_records([d], [1], [1], [0], bad(recs=recs_badnode))
    with pytest.raises(ValueError, match="rec_op"):
        eng.ingest_records([d], [1], [1], [0],
                           bad(rec_op=np.asarray([3], np.int64)))
    with pytest.raises(ValueError, match="non-empty str"):
        eng.ingest_records([d], [1], [1], [0], bad(ids=[""]))
    with pytest.raises(ValueError, match="unserializable"):
        eng.ingest_records([d], [1], [1], [0], bad(values=[set()]))
    assert eng.deli.doc_seq(d) == seq_before
    eng.summarize()   # not poisoned


def test_tree_records_native_log_round_trip(tmp_path):
    from fluidframework_tpu.server import native_oplog
    if not native_oplog.available():
        pytest.skip("native oplog unavailable")
    rec = TreeRecordOps(
        doc_ids=["a", "b"], doc=np.array([0, 1, 0], np.int64),
        client=np.array([1, 2, 1], np.int64),
        client_seq=np.array([1, 1, 2], np.int64),
        ref_seq=np.array([0, 0, 1], np.int64),
        seq=np.array([2, 2, 3], np.int64),
        min_seq=np.array([0, 0, 0], np.int64),
        rec_op=np.array([0, 1, 1, 2], np.int64),
        recs=np.array([[9, 1, 2, 0, 1, 0, 0, 0],
                       [3, 0, 0, 0, 0, 0, 0, 0],
                       [8, 1, 0, 0, 0, 1, 0, 0],
                       [10, 1, 0, 0, 0, 0, 0, 0]], np.int32),
        ids=["n1", "root"], fields=["kids"], types=[],
        values=[{"deep": [1, None]}], timestamp=123.5)
    log = native_oplog.NativePartitionedLog(str(tmp_path), 2)
    log.append(1, rec)
    got = next(iter(log.read(1)))
    log.close()
    assert isinstance(got, TreeRecordOps)
    assert got.doc_ids == rec.doc_ids and got.ids == rec.ids
    assert got.fields == rec.fields and got.values == rec.values
    assert got.timestamp == rec.timestamp
    for f in ("doc", "client", "client_seq", "ref_seq", "seq", "min_seq",
              "rec_op"):
        assert np.array_equal(getattr(got, f), getattr(rec, f)), f
    assert np.array_equal(got.recs, rec.recs)


def _batch_equal(a, b):
    """Byte-level batch identity: same record planes AND same tables
    (handle order included) — the vectorized encoder is a drop-in."""
    return (np.array_equal(np.asarray(a["rec_op"]),
                           np.asarray(b["rec_op"]))
            and np.array_equal(np.asarray(a["recs"]),
                               np.asarray(b["recs"]))
            and list(a["ids"]) == list(b["ids"])
            and list(a["fields"]) == list(b["fields"])
            and list(a["types"]) == list(b["types"])
            and list(a["values"]) == list(b["values"]))


#: deterministic corpus touching every record kind the encoder emits:
#: guarded multi-node insert, nested children, solo insert/remove/
#: set/move, and a constrained transaction (TXN_BEGIN_EXISTS + guards)
ALL_KINDS_OPS = [
    {"op": "insert", "parent": "root", "field": "kids", "after": None,
     "nodes": [{"id": "a", "type": "t", "value": 1},
               {"id": "b", "type": None, "value": None}]},
    {"op": "insert", "parent": "a", "field": "sub", "after": None,
     "nodes": [{"id": "c", "type": "u", "value": [1, {"k": None}],
                "children": {"f1": [{"id": "c1", "value": "x"}],
                             "f2": [{"id": "c2", "type": "v"}]}}]},
    {"op": "insert", "parent": "root", "field": "kids", "after": "a",
     "nodes": [{"id": "solo", "value": 7}]},
    {"op": "setValue", "id": "a", "value": {"deep": [None, 2.5]}},
    {"op": "move", "id": "b", "parent": "a", "field": "sub",
     "after": "c"},
    {"op": "remove", "id": "solo"},
    {"op": "transaction",
     "constraints": [{"nodeExists": "a"}, {"nodeExists": "c"}],
     "edits": [{"op": "insert", "parent": "a", "field": "sub",
                "after": "c", "nodes": [{"id": "d", "value": 9}]},
               {"op": "setValue", "id": "c", "value": 10},
               {"op": "move", "id": "d", "parent": "c", "field": "f1",
                "after": None},
               {"op": "remove", "id": "b"}]},
]


def test_vectorized_encoder_matches_reference_all_kinds():
    """The vectorized TreeBatchEncoder (one interner pass per table,
    numpy-packed records) is byte-identical to the per-op reference
    encoder on a corpus covering every record kind."""
    from fluidframework_tpu.server.tree_wire import (
        ReferenceTreeBatchEncoder,
    )
    vec, ref = TreeBatchEncoder(), ReferenceTreeBatchEncoder()
    for op in ALL_KINDS_OPS:
        assert vec.add(op) == ref.add(op)
    assert _batch_equal(vec.batch(), ref.batch())


@pytest.mark.parametrize("seed", range(4))
def test_vectorized_encoder_matches_reference_fuzz(seed):
    """Seeded parity over the oracle fuzz corpus (numeric ``#N`` ids ride
    the int fast path; tables must still come out handle-identical)."""
    from fluidframework_tpu.server.tree_wire import (
        ReferenceTreeBatchEncoder,
    )
    _, msgs = tree_session(seed)
    vec, ref = TreeBatchEncoder(), ReferenceTreeBatchEncoder()
    for m in msgs:
        vec.add(m.contents)
        ref.add(m.contents)
    assert _batch_equal(vec.batch(), ref.batch())


def test_leaf_builder_matches_general_encoder():
    """encode_leaf_records (the unified flat path) emits the same
    INSERT_SOLO ops as the general encoder fed the equivalent one-node
    inserts — flat is the same wire, not a parallel format. (Table
    stream order differs — the flat builder resolves ids column-wise —
    so the comparison is decoded-op identity, not byte identity.)"""
    from fluidframework_tpu.server.tree_wire import (decode_records,
                                                     encode_leaf_records)
    n = 9
    parents = ["root" if i % 3 else f"n{i - 1}" for i in range(n)]
    parents[0] = "root"
    fields = [f"f{i % 2}" for i in range(n)]
    nids = [f"n{i}" for i in range(n)]
    values = [None if i % 4 == 3 else {"i": i} for i in range(n)]
    types = [None if i % 2 else "leaf" for i in range(n)]
    afters = [None if i % 3 != 1 else f"n{i - 1}" for i in range(n)]
    flat = encode_leaf_records(parents, fields, nids, values, types,
                               afters)
    general = encode_tree_batch(
        [{"op": "insert", "parent": p, "field": f, "after": a,
          "nodes": [{"id": i, "type": t, "value": v}]}
         for p, f, i, v, t, a in zip(parents, fields, nids, values,
                                     types, afters)])
    def decoded(b):
        return [_normalize(op) for op in decode_records(
            b["rec_op"], b["recs"], b["ids"], b["fields"], b["types"],
            b["values"])]

    assert decoded(flat) == decoded(general)
    assert (np.asarray(flat["recs"])[:, 0] == 9).all()  # INSERT_SOLO


def test_ingest_leaves_is_records_path():
    """Flat-via-records parity: ingest_leaves ≡ encode_leaf_records +
    ingest_records — same seqs, same trees, same durable log (the thin
    builder really did retire the duplicate pipeline)."""
    from fluidframework_tpu.server.tree_wire import encode_leaf_records
    eng_a, docs = _mk()
    eng_b, _ = _mk()
    for wave in range(3):
        parents = ["root"] * len(docs) if wave == 0 \
            else [f"{d}-L0" for d in docs]
        nids = [f"{d}-L{wave}" for d in docs]
        values = [{"w": wave}] * len(docs)
        types = ["leaf"] * len(docs)
        afters = [None if wave < 2 else f"{d}-L1" for d in docs]
        cs = [wave + 1] * len(docs)
        zeros = [0] * len(docs)
        res_a = eng_a.ingest_leaves(docs, [1] * len(docs), cs, zeros,
                                    parents, ["kids"] * len(docs), nids,
                                    values, types, afters)
        batch = encode_leaf_records(parents, ["kids"] * len(docs), nids,
                                    values, types, afters)
        res_b = eng_b.ingest_records(docs, [1] * len(docs), cs, zeros,
                                     batch)
        assert np.array_equal(np.asarray(res_a["seq"]),
                              np.asarray(res_b["seq"]))
        assert res_a["nacked"] == res_b["nacked"] == 0
    for d in docs:
        assert eng_a.to_dict(d) == eng_b.to_dict(d), d
    la = [(m.doc_id, m.seq, m.contents) for m in
          (m for d in docs for m in eng_a._doc_log_messages(d))]
    lb = [(m.doc_id, m.seq, m.contents) for m in
          (m for d in docs for m in eng_b._doc_log_messages(d))]
    assert la == lb


def test_wire_width_coding_u32_parity():
    """The id/value index lanes widen u16 → u32 past 64k table entries;
    a batch whose tables cross the boundary (padded with unused ids and
    values) must still be wire-eligible and merge identically to the
    unpadded ingest."""
    eng_a, docs = _mk()
    eng_b, _ = _mk()
    ops = [{"op": "insert", "parent": "root", "field": "kids",
            "after": None, "nodes": [{"id": f"{d}-n", "type": "t",
                                      "value": 5}]} for d in docs]
    batch = encode_tree_batch(ops)
    padded = dict(batch)
    padded["ids"] = list(batch["ids"]) + \
        [f"pad{i}" for i in range(0x10000)]
    padded["values"] = list(batch["values"]) + list(range(0x10000))
    assert eng_a._wire_eligible(padded)
    ones, cs, zeros = [1] * len(docs), [1] * len(docs), [0] * len(docs)
    res_a = eng_a.ingest_records(docs, ones, cs, zeros, padded)
    res_b = eng_b.ingest_records(docs, ones, cs, zeros, batch)
    assert res_a["nacked"] == res_b["nacked"] == 0
    assert np.array_equal(np.asarray(res_a["seq"]),
                          np.asarray(res_b["seq"]))
    for d in docs:
        assert eng_a.to_dict(d) == eng_b.to_dict(d), d


def test_pack_wire_records_width_parameters():
    """pack_wire_records' u16 and u32 packings carry identical indices —
    the width is a wire-size knob, not a semantic one — and prepack_wire
    picks the width from the table sizes (pool buckets keyed by
    itemsize, so u16 and u32 waves never alias a buffer)."""
    from fluidframework_tpu.ops.tree_store import pack_wire_records
    ops = [{"op": "insert", "parent": "root", "field": "kids",
            "after": None, "nodes": [{"id": f"m{i}", "value": i}]}
           for i in range(6)]
    b = encode_tree_batch(ops)
    recs = np.asarray(b["recs"])
    rec_op = np.asarray(b["rec_op"])
    rows_r = np.arange(len(rec_op), dtype=np.int64)
    p16 = pack_wire_records(recs, rec_op, rows_r)
    p32 = pack_wire_records(recs, rec_op, rows_r,
                            id_t=np.uint32, val_t=np.uint32)
    k16, ids16, vals16, row16, pos16 = p16[:5]
    k32, ids32, vals32, row32, pos32 = p32[:5]
    assert ids16.dtype == np.uint16 and vals16.dtype == np.uint16
    assert ids32.dtype == np.uint32 and vals32.dtype == np.uint32
    assert np.array_equal(ids16.astype(np.uint32), ids32)
    assert np.array_equal(vals16.astype(np.uint32), vals32)
    assert np.array_equal(k16, k32) and np.array_equal(row16, row32)
    assert np.array_equal(pos16, pos32)


def test_nested_transaction_rejected():
    eng, docs = _mk()
    nested = {"op": "transaction", "edits": [
        {"op": "transaction", "edits": [
            {"op": "setValue", "id": "root", "value": 1}]}]}
    _, nack = eng.submit(docs[0], 1, 1, 0, nested)
    assert nack is not None
    with pytest.raises(ValueError, match="malformed"):
        eng.ingest_batch([docs[0]], [1], [1], [0], [nested])
