"""Native durable op log (C++ liboplog) + binary op codec.

Pins the crash-recovery contract the reference gets from Kafka: records
before a torn tail survive a reopen, the tear disappears, and the serving
engines recover from summary + durable-tail replay across a process
"crash" (close + reopen of the same directory).
"""

import json
import os
import random

import pytest

from fluidframework_tpu.core.protocol import MessageType, \
    SequencedDocumentMessage
from fluidframework_tpu.server.native_oplog import (
    NativePartitionedLog,
    available,
    decode_message,
    encode_message,
)

pytestmark = pytest.mark.skipif(not available(),
                                reason="native oplog not built")


def _msg(seq, contents, doc="d", mtype=MessageType.OP, **kw):
    return SequencedDocumentMessage(
        doc_id=doc, client_id=1, client_seq=seq, ref_seq=seq - 1, seq=seq,
        min_seq=0, type=mtype, contents=contents, **kw)


def test_codec_roundtrip_property():
    rng = random.Random(3)
    for i in range(50):
        msg = SequencedDocumentMessage(
            doc_id="doc-%d-αβ" % i, client_id=rng.randint(-1, 2**31),
            client_seq=rng.randint(0, 2**40), ref_seq=rng.randint(0, 9),
            seq=rng.randint(0, 2**50), min_seq=rng.randint(0, 5),
            type=rng.choice(list(MessageType)),
            contents=rng.choice([None, {"mt": "insert", "text": "αβ\x00γ"},
                                 [1, [2, {"k": None}]], "s"]),
            metadata=rng.choice([None, {"x": 1}]),
            address=rng.choice([None, "ds/ch"]))
        assert decode_message(encode_message(msg)) == msg


def test_append_read_survives_reopen(tmp_path):
    d = str(tmp_path)
    log = NativePartitionedLog(d, 4)
    msgs = [_msg(i, {"op": "set", "key": f"k{i}", "value": i})
            for i in range(1, 21)]
    for i, m in enumerate(msgs):
        log.append(i % 4, m)
    log.sync()
    log.close()
    log2 = NativePartitionedLog(d, 4)
    back = [m for p in range(4) for m in log2.read(p)]
    assert sorted(m.seq for m in back) == [m.seq for m in msgs]
    assert all(isinstance(m, SequencedDocumentMessage) for m in back)
    # offsets continue, not restart
    off = log2.append(0, _msg(99, None))
    assert off == log2.size(0) - 1


def test_torn_tail_truncated_on_reopen(tmp_path):
    d = str(tmp_path)
    log = NativePartitionedLog(d, 1)
    for i in range(1, 6):
        log.append(0, _msg(i, {"v": i}))
    log.sync()
    log.close()
    path = os.path.join(d, "p0.log")
    full = os.path.getsize(path)
    # tear the last record: chop a few bytes off the file tail
    with open(path, "r+b") as f:
        f.truncate(full - 3)
    log2 = NativePartitionedLog(d, 1)
    seqs = [m.seq for m in log2.read(0)]
    assert seqs == [1, 2, 3, 4]  # record 5 torn away, prefix intact
    # appends continue cleanly from the record edge
    log2.append(0, _msg(6, {"v": 6}))
    assert [m.seq for m in log2.read(0)] == [1, 2, 3, 4, 6]


def test_corrupt_middle_record_cuts_log_at_corruption(tmp_path):
    d = str(tmp_path)
    log = NativePartitionedLog(d, 1)
    for i in range(1, 4):
        log.append(0, _msg(i, {"v": "x" * 40}))
    log.close()
    path = os.path.join(d, "p0.log")
    rec1_len = 8 + 1 + len(encode_message(_msg(1, {"v": "x" * 40})))
    with open(path, "r+b") as f:
        f.seek(rec1_len + 20)          # inside record 2's payload
        f.write(b"\xff\xff")
    log2 = NativePartitionedLog(d, 1)
    assert [m.seq for m in log2.read(0)] == [1]  # CRC cut at the corruption


def test_json_records_roundtrip(tmp_path):
    log = NativePartitionedLog(str(tmp_path), 2)
    log.append(1, {"plain": "json", "n": [1, 2]})
    log.close()
    log2 = NativePartitionedLog(str(tmp_path), 2)
    assert list(log2.read(1)) == [{"plain": "json", "n": [1, 2]}]


def test_serving_engine_recovers_from_native_log(tmp_path):
    """Process-crash drill: map engine on the durable log, summary taken,
    more ops, 'crash' (close), reopen + load → tail replayed from disk."""
    from fluidframework_tpu.server.serving import MapServingEngine
    d = str(tmp_path)
    log = NativePartitionedLog(d, 4)
    engine = MapServingEngine(n_docs=2, log=log)
    engine.connect("a", 1)
    engine.submit("a", 1, 1, 0, {"op": "set", "key": "x", "value": 1})
    summary = engine.summarize()
    engine.submit("a", 1, 2, 0, {"op": "set", "key": "y", "value": 2})
    engine.connect("b", 7)
    log.sync()
    log.close()  # the crash

    log2 = NativePartitionedLog(d, 4)
    engine2 = MapServingEngine.load(summary, log2)
    assert engine2.read_doc("a") == {"x": 1, "y": 2}
    msg, nack = engine2.submit("b", 7, 1, 0,
                               {"op": "set", "key": "k", "value": "v"})
    assert nack is None and engine2.read_doc("b") == {"k": "v"}


def test_string_engine_on_native_log(tmp_path):
    from fluidframework_tpu.models.merge_tree_client import SequenceClient
    from fluidframework_tpu.server.serving import StringServingEngine
    d = str(tmp_path)
    log = NativePartitionedLog(d, 4)
    engine = StringServingEngine(n_docs=1, capacity=128, log=log)
    engine.connect("doc", 1)
    c = SequenceClient(1)
    for i in range(10):
        op = c.insert_text_local(c.get_length(), f"w{i} ")
        msg, nack = engine.submit("doc", 1, op["clientSeq"],
                                  c.last_processed_seq, op)
        assert nack is None
        c.apply_msg(msg)
    summary = engine.summarize()
    op = c.remove_range_local(0, 3)
    msg, _ = engine.submit("doc", 1, op["clientSeq"],
                           c.last_processed_seq, op)
    c.apply_msg(msg)
    log.close()

    engine2 = StringServingEngine.load(summary, NativePartitionedLog(d, 4))
    assert engine2.read_text("doc") == c.get_text()


# ------------------------------------------------- columnar × durable log
# (VERDICT r2 weak #2 / next #3: the columnar fast path and the durable
# C++ log must COMPOSE — binary ColumnarOps codec, no lossy str() fallback)


def test_columnar_codec_roundtrip():
    import numpy as np
    from fluidframework_tpu.server.native_oplog import (decode_columnar,
                                                        encode_columnar)
    from fluidframework_tpu.server.serving import ColumnarOps
    rng = np.random.default_rng(5)
    n = 37
    rec = ColumnarOps(
        doc_ids=["doc-α", "doc-b"],
        doc=rng.integers(0, 2, n).astype(np.int32),
        client=rng.integers(1, 9, n).astype(np.int32),
        client_seq=rng.integers(1, 1 << 20, n).astype(np.int64),
        ref_seq=rng.integers(0, 1 << 20, n).astype(np.int64),
        seq=np.arange(1, n + 1, dtype=np.int64),
        min_seq=np.zeros(n, np.int64),
        kind=rng.integers(0, 2, n).astype(np.int32),
        a0=rng.integers(0, 100, n).astype(np.int32),
        a1=rng.integers(0, 100, n).astype(np.int32),
        text="abcd αβ", timestamp=123.25)
    back = decode_columnar(encode_columnar(rec))
    assert back.doc_ids == rec.doc_ids
    assert back.text == rec.text and back.timestamp == rec.timestamp
    for f in ("doc", "client", "client_seq", "ref_seq", "seq", "min_seq",
              "kind", "a0", "a1"):
        assert (getattr(back, f) == getattr(rec, f)).all(), f
    # and the expansions (what recovery replays) agree exactly
    assert back.expand() == rec.expand()


def test_columnar_record_survives_reopen(tmp_path):
    import numpy as np
    from fluidframework_tpu.server.serving import ColumnarOps
    log = NativePartitionedLog(str(tmp_path), 2)
    rec = ColumnarOps(
        doc_ids=["d"], doc=np.zeros(600, np.int32),
        client=np.ones(600, np.int32),
        client_seq=np.arange(1, 601, dtype=np.int64),
        ref_seq=np.zeros(600, np.int64),
        seq=np.arange(1, 601, dtype=np.int64),
        min_seq=np.zeros(600, np.int64),
        kind=np.ones(600, np.int32), a0=np.zeros(600, np.int32),
        a1=np.full(600, 4, np.int32), text="abcd", timestamp=1.0)
    log.append(0, rec)
    log.sync()
    log.close()
    back = list(NativePartitionedLog(str(tmp_path), 2).read(0))[0]
    assert isinstance(back, ColumnarOps)
    # 600 entries: the old str() repr would have elided these arrays
    assert (back.client_seq == rec.client_seq).all()
    assert len(back.expand()) == 600


def test_unloggable_record_raises_not_corrupts(tmp_path):
    log = NativePartitionedLog(str(tmp_path), 1)
    with pytest.raises(TypeError, match="losslessly"):
        log.append(0, object())
    assert log.size(0) == 0  # nothing half-written


def test_columnar_ingest_crash_recovery_on_native_log(tmp_path):
    """The composed path end-to-end: columnar ingest → binary ColumnarOps
    records on the durable C++ log → process 'crash' → reopen → summary +
    tail replay → text parity with a per-op reference engine."""
    import numpy as np
    from fluidframework_tpu.ops.schema import OpKind
    from fluidframework_tpu.server import native_deli
    from fluidframework_tpu.server.serving import StringServingEngine
    from fluidframework_tpu.testing.synthetic import typing_storm
    if not native_deli.available():
        pytest.skip("native sequencer unavailable")
    R, O = 4, 16
    d = str(tmp_path)
    log = NativePartitionedLog(d, 4)
    eng = StringServingEngine(n_docs=R, capacity=256,
                              batch_window=10 ** 9, sequencer="native",
                              log=log)
    ref = StringServingEngine(n_docs=R, capacity=256, batch_window=10 ** 9)
    docs = [f"doc-{i}" for i in range(R)]
    for e in (eng, ref):
        for dd in docs:
            e.connect(dd, 1)
    rows = np.array([eng.doc_row(dd) for dd in docs], np.int32)
    client = np.ones((R, O), np.int32)
    refp = np.zeros((R, O), np.int32)
    summary = eng.summarize()  # columnar batches land in the TAIL
    seq = 1
    for bi in range(3):
        planes, seq = typing_storm(R, O, seed=bi, start_seq=seq)
        cseq = np.broadcast_to(
            np.arange(bi * O + 1, (bi + 1) * O + 1, dtype=np.int32),
            (R, O))
        res = eng.ingest_planes(rows, client, cseq, refp,
                                planes["kind"], planes["a0"], planes["a1"],
                                "abcd")
        assert res["nacked"] == 0
        for di in range(R):  # same ops through the per-op reference
            for o in range(O):
                if planes["kind"][di, o] == OpKind.STR_INSERT:
                    contents = {"mt": "insert", "kind": 0,
                                "pos": int(planes["a0"][di, o]),
                                "text": "abcd"}
                else:
                    contents = {"mt": "remove",
                                "start": int(planes["a0"][di, o]),
                                "end": int(planes["a1"][di, o])}
                _, nack = ref.submit(docs[di], 1, int(cseq[di, o]), 0,
                                     contents)
                assert nack is None
    want = {dd: ref.read_text(dd) for dd in docs}
    assert {dd: eng.read_text(dd) for dd in docs} == want
    log.sync()
    log.close()  # the crash

    revived = StringServingEngine.load(summary, NativePartitionedLog(d, 4))
    assert {dd: revived.read_text(dd) for dd in docs} == want
