"""Native durable op log (C++ liboplog) + binary op codec.

Pins the crash-recovery contract the reference gets from Kafka: records
before a torn tail survive a reopen, the tear disappears, and the serving
engines recover from summary + durable-tail replay across a process
"crash" (close + reopen of the same directory).
"""

import json
import os
import random

import pytest

from fluidframework_tpu.core.protocol import MessageType, \
    SequencedDocumentMessage
from fluidframework_tpu.server.native_oplog import (
    NativePartitionedLog,
    available,
    decode_message,
    encode_message,
)

pytestmark = pytest.mark.skipif(not available(),
                                reason="native oplog not built")


def _msg(seq, contents, doc="d", mtype=MessageType.OP, **kw):
    return SequencedDocumentMessage(
        doc_id=doc, client_id=1, client_seq=seq, ref_seq=seq - 1, seq=seq,
        min_seq=0, type=mtype, contents=contents, **kw)


def test_codec_roundtrip_property():
    rng = random.Random(3)
    for i in range(50):
        msg = SequencedDocumentMessage(
            doc_id="doc-%d-αβ" % i, client_id=rng.randint(-1, 2**31),
            client_seq=rng.randint(0, 2**40), ref_seq=rng.randint(0, 9),
            seq=rng.randint(0, 2**50), min_seq=rng.randint(0, 5),
            type=rng.choice(list(MessageType)),
            contents=rng.choice([None, {"mt": "insert", "text": "αβ\x00γ"},
                                 [1, [2, {"k": None}]], "s"]),
            metadata=rng.choice([None, {"x": 1}]),
            address=rng.choice([None, "ds/ch"]))
        assert decode_message(encode_message(msg)) == msg


def test_append_read_survives_reopen(tmp_path):
    d = str(tmp_path)
    log = NativePartitionedLog(d, 4)
    msgs = [_msg(i, {"op": "set", "key": f"k{i}", "value": i})
            for i in range(1, 21)]
    for i, m in enumerate(msgs):
        log.append(i % 4, m)
    log.sync()
    log.close()
    log2 = NativePartitionedLog(d, 4)
    back = [m for p in range(4) for m in log2.read(p)]
    assert sorted(m.seq for m in back) == [m.seq for m in msgs]
    assert all(isinstance(m, SequencedDocumentMessage) for m in back)
    # offsets continue, not restart
    off = log2.append(0, _msg(99, None))
    assert off == log2.size(0) - 1


def test_torn_tail_truncated_on_reopen(tmp_path):
    d = str(tmp_path)
    log = NativePartitionedLog(d, 1)
    for i in range(1, 6):
        log.append(0, _msg(i, {"v": i}))
    log.sync()
    log.close()
    path = os.path.join(d, "p0.log")
    full = os.path.getsize(path)
    # tear the last record: chop a few bytes off the file tail
    with open(path, "r+b") as f:
        f.truncate(full - 3)
    log2 = NativePartitionedLog(d, 1)
    seqs = [m.seq for m in log2.read(0)]
    assert seqs == [1, 2, 3, 4]  # record 5 torn away, prefix intact
    # appends continue cleanly from the record edge
    log2.append(0, _msg(6, {"v": 6}))
    assert [m.seq for m in log2.read(0)] == [1, 2, 3, 4, 6]


def test_corrupt_middle_record_cuts_log_at_corruption(tmp_path):
    d = str(tmp_path)
    log = NativePartitionedLog(d, 1)
    for i in range(1, 4):
        log.append(0, _msg(i, {"v": "x" * 40}))
    log.close()
    path = os.path.join(d, "p0.log")
    rec1_len = 8 + 1 + len(encode_message(_msg(1, {"v": "x" * 40})))
    with open(path, "r+b") as f:
        f.seek(rec1_len + 20)          # inside record 2's payload
        f.write(b"\xff\xff")
    log2 = NativePartitionedLog(d, 1)
    assert [m.seq for m in log2.read(0)] == [1]  # CRC cut at the corruption


def test_json_records_roundtrip(tmp_path):
    log = NativePartitionedLog(str(tmp_path), 2)
    log.append(1, {"plain": "json", "n": [1, 2]})
    log.close()
    log2 = NativePartitionedLog(str(tmp_path), 2)
    assert list(log2.read(1)) == [{"plain": "json", "n": [1, 2]}]


def test_serving_engine_recovers_from_native_log(tmp_path):
    """Process-crash drill: map engine on the durable log, summary taken,
    more ops, 'crash' (close), reopen + load → tail replayed from disk."""
    from fluidframework_tpu.server.serving import MapServingEngine
    d = str(tmp_path)
    log = NativePartitionedLog(d, 4)
    engine = MapServingEngine(n_docs=2, log=log)
    engine.connect("a", 1)
    engine.submit("a", 1, 1, 0, {"op": "set", "key": "x", "value": 1})
    summary = engine.summarize()
    engine.submit("a", 1, 2, 0, {"op": "set", "key": "y", "value": 2})
    engine.connect("b", 7)
    log.sync()
    log.close()  # the crash

    log2 = NativePartitionedLog(d, 4)
    engine2 = MapServingEngine.load(summary, log2)
    assert engine2.read_doc("a") == {"x": 1, "y": 2}
    msg, nack = engine2.submit("b", 7, 1, 0,
                               {"op": "set", "key": "k", "value": "v"})
    assert nack is None and engine2.read_doc("b") == {"k": "v"}


def test_string_engine_on_native_log(tmp_path):
    from fluidframework_tpu.models.merge_tree_client import SequenceClient
    from fluidframework_tpu.server.serving import StringServingEngine
    d = str(tmp_path)
    log = NativePartitionedLog(d, 4)
    engine = StringServingEngine(n_docs=1, capacity=128, log=log)
    engine.connect("doc", 1)
    c = SequenceClient(1)
    for i in range(10):
        op = c.insert_text_local(c.get_length(), f"w{i} ")
        msg, nack = engine.submit("doc", 1, op["clientSeq"],
                                  c.last_processed_seq, op)
        assert nack is None
        c.apply_msg(msg)
    summary = engine.summarize()
    op = c.remove_range_local(0, 3)
    msg, _ = engine.submit("doc", 1, op["clientSeq"],
                           c.last_processed_seq, op)
    c.apply_msg(msg)
    log.close()

    engine2 = StringServingEngine.load(summary, NativePartitionedLog(d, 4))
    assert engine2.read_text("doc") == c.get_text()
