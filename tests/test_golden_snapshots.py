"""Golden snapshot compatibility: summaries checked in by older code must
keep loading (reference: the test-snapshots golden suite, SURVEY.md §4).

These tests read the CHECKED-IN fixtures under tests/goldens/ — they never
regenerate. If a summary format change breaks them, either add a
backwards-compatible load path or consciously regenerate via
``python tests/goldens/generate.py`` and say so in the commit message.
"""

import json
import os

from fluidframework_tpu.models import SharedMap, SharedMatrix, SharedString
from fluidframework_tpu.models.shared_tree import SharedTree
from fluidframework_tpu.testing.mocks import (
    MockSequencer, create_connected_dds,
)

GOLDENS = os.path.join(os.path.dirname(__file__), "goldens")


def _load(name, cls):
    with open(os.path.join(GOLDENS, name)) as f:
        fixture = json.load(f)
    dds = create_connected_dds(MockSequencer(), cls)
    dds.load_from_summary(fixture["summary"], fixture["base_seq"])
    return dds, fixture["expect"]


def test_golden_shared_string_loads():
    s, expect = _load("shared_string_v1.json", SharedString)
    assert s.get_text() == expect["text"]
    assert s.get_length() == expect["length"]
    for pos, props in expect["props"]:
        assert s.get_properties(pos) == props, pos


def test_golden_shared_map_loads():
    m, expect = _load("shared_map_v1.json", SharedMap)
    for k, v in expect["entries"].items():
        assert m.get(k) == v, k
    for k in expect["absent"]:
        assert m.get(k) is None, k


def test_golden_shared_matrix_loads():
    m, expect = _load("shared_matrix_v1.json", SharedMatrix)
    assert m.row_count == expect["rows"]
    assert m.col_count == expect["cols"]
    for r in range(expect["rows"]):
        for c in range(expect["cols"]):
            assert m.get_cell(r, c) == expect["cells"][r][c], (r, c)


def test_golden_shared_tree_loads():
    t, expect = _load("shared_tree_v1.json", SharedTree)
    assert t.to_dict() == expect["tree"]


def test_golden_loaded_string_accepts_new_edits():
    """A loaded document must keep collaborating, not just read back."""
    with open(os.path.join(GOLDENS, "shared_string_v1.json")) as f:
        fixture = json.load(f)
    seqr = MockSequencer()
    seqr.seq = fixture["base_seq"]  # resume the stream past the summary
    a = create_connected_dds(seqr, SharedString)
    b = create_connected_dds(seqr, SharedString)
    a.load_from_summary(fixture["summary"], fixture["base_seq"])
    b.load_from_summary(fixture["summary"], fixture["base_seq"])
    a.insert_text(0, ">> ")
    b.insert_text(b.get_length(), " <<")
    seqr.process_all_messages()
    assert a.get_text() == b.get_text() == \
        ">> " + fixture["expect"]["text"] + " <<"
