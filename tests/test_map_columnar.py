"""Columnar map ingest (MapServingEngine.ingest_planes): parity with the
per-op submit path, nack handling, and durable-log recovery of the
family="map" whole-batch records."""

import numpy as np
import pytest

from fluidframework_tpu.ops.schema import OpKind
from fluidframework_tpu.server import native_deli
from fluidframework_tpu.server.serving import MapServingEngine

pytestmark = pytest.mark.skipif(not native_deli.available(),
                                reason="native sequencer unavailable")

SET, DEL, CLR = (int(OpKind.MAP_SET), int(OpKind.MAP_DELETE),
                 int(OpKind.MAP_CLEAR))


def _engines(R=16, O=12):
    a = MapServingEngine(n_docs=R, batch_window=10 ** 9, sequencer="native")
    b = MapServingEngine(n_docs=R, batch_window=10 ** 9)
    docs = [f"m-{i}" for i in range(R)]
    for e in (a, b):
        for d in docs:
            e.connect(d, 1)
            e.doc_row(d)
    rows = np.array([a.doc_row(d) for d in docs], np.int32)
    return a, b, docs, rows


def _batch(R, O, bi):
    rng = np.random.default_rng(500 + bi)
    keys = [f"k{j}" for j in range(6)]
    values = [f"v{bi}-{j}" for j in range(5)] + [{"n": bi}, [1, bi], None]
    kind = rng.choice([SET, SET, SET, DEL, CLR],
                      p=[0.5, 0.2, 0.15, 0.1, 0.05], size=(R, O)) \
        .astype(np.int32)
    kidx = rng.integers(0, len(keys), size=(R, O)).astype(np.int32)
    vidx = rng.integers(0, len(values), size=(R, O)).astype(np.int32)
    return kind, kidx, keys, vidx, values


def _submit_mirror(b, docs, kind, kidx, keys, vidx, values, cseq):
    for d in range(kind.shape[0]):
        for o in range(kind.shape[1]):
            k = kind[d, o]
            if k == CLR:
                c = {"op": "clear"}
            elif k == DEL:
                c = {"op": "delete", "key": keys[kidx[d, o]]}
            else:
                c = {"op": "set", "key": keys[kidx[d, o]],
                     "value": values[vidx[d, o]]}
            _, nack = b.submit(docs[d], 1, int(cseq[d, o]), 0, c)
            assert nack is None


def test_map_columnar_matches_per_op_engine():
    R, O = 16, 12
    a, b, docs, rows = _engines(R, O)
    client = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)
    for bi in range(3):
        kind, kidx, keys, vidx, values = _batch(R, O, bi)
        cseq = np.broadcast_to(
            np.arange(bi * O + 1, (bi + 1) * O + 1, dtype=np.int32), (R, O))
        res = a.ingest_planes(rows, client, cseq, ref, kind, kidx, keys,
                              values, vidx)
        assert res["nacked"] == 0
        _submit_mirror(b, docs, kind, kidx, keys, vidx, values, cseq)
    for d in docs:
        assert a.read_doc(d) == b.read_doc(d), d


def test_map_columnar_nacks_skipped():
    R, O = 4, 8
    a, _, docs, rows = _engines(R, O)
    kind, kidx, keys, vidx, values = _batch(R, O, 0)
    cseq = np.broadcast_to(np.arange(1, O + 1, dtype=np.int32),
                           (R, O)).copy()
    cseq[1, 3] = 99  # gap: ops 3.. of doc 1 nack
    res = a.ingest_planes(rows, np.ones((R, O), np.int32), cseq,
                          np.zeros((R, O), np.int32), kind, kidx, keys,
                          values, vidx)
    assert res["nacked"] == O - 3
    assert (res["seq"][1, 3:] < 0).all()
    # the logged record skips them
    from fluidframework_tpu.server.serving import ColumnarOps
    logged = sum(len(rec.seq) for p in range(a.log.n_partitions)
                 for rec in a.log.read(p) if isinstance(rec, ColumnarOps))
    assert logged == R * O - (O - 3)


def test_map_columnar_recovery_through_log_replay():
    R, O = 8, 10
    a, b, docs, rows = _engines(R, O)
    client = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)
    kind, kidx, keys, vidx, values = _batch(R, O, 0)
    cseq = np.broadcast_to(np.arange(1, O + 1, dtype=np.int32), (R, O))
    a.ingest_planes(rows, client, cseq, ref, kind, kidx, keys, values, vidx)
    summary = a.summarize()
    kind, kidx, keys, vidx, values = _batch(R, O, 1)
    cseq = cseq + O
    a.ingest_planes(rows, client, cseq, ref, kind, kidx, keys, values, vidx)
    want = {d: a.read_doc(d) for d in docs}
    revived = MapServingEngine.load(summary, a.log)
    assert {d: revived.read_doc(d) for d in docs} == want
    # sequencing resumes
    _, nack = revived.submit(docs[0], 1, 2 * O + 1, 0,
                             {"op": "set", "key": "fresh", "value": 1})
    assert nack is None
    assert revived.get(docs[0], "fresh") == 1


def test_map_columnar_native_log_crash_recovery(tmp_path):
    from fluidframework_tpu.server.native_oplog import (
        NativePartitionedLog, available as oplog_available)
    if not oplog_available():
        pytest.skip("native oplog not built")
    R, O = 6, 8
    log = NativePartitionedLog(str(tmp_path), 4)
    a = MapServingEngine(n_docs=R, batch_window=10 ** 9,
                         sequencer="native", log=log, n_partitions=4)
    docs = [f"m-{i}" for i in range(R)]
    for d in docs:
        a.connect(d, 1)
        a.doc_row(d)
    rows = np.array([a.doc_row(d) for d in docs], np.int32)
    summary = a.summarize()
    kind, kidx, keys, vidx, values = _batch(R, O, 2)
    cseq = np.broadcast_to(np.arange(1, O + 1, dtype=np.int32), (R, O))
    a.ingest_planes(rows, np.ones((R, O), np.int32), cseq,
                    np.zeros((R, O), np.int32), kind, kidx, keys,
                    values, vidx)
    want = {d: a.read_doc(d) for d in docs}
    log.sync()
    log.close()  # the crash
    revived = MapServingEngine.load(
        summary, NativePartitionedLog(str(tmp_path), 4))
    assert {d: revived.read_doc(d) for d in docs} == want


def test_map_columnar_validation():
    R, O = 2, 4
    a, _, docs, rows = _engines(R, O)
    client = np.ones((R, O), np.int32)
    cseq = np.broadcast_to(np.arange(1, O + 1, dtype=np.int32), (R, O))
    z = np.zeros((R, O), np.int32)
    keys = ["k"]
    seq_before = {d: a.deli.doc_seq(d) for d in docs}
    bad = z.copy()
    bad[0, 0] = 5
    with pytest.raises(ValueError, match="keys table"):
        a.ingest_planes(rows, client, cseq, z,
                        np.full((R, O), SET, np.int32), bad, keys,
                        ["v"], z)
    with pytest.raises(ValueError, match="values table"):
        a.ingest_planes(rows, client, cseq, z,
                        np.full((R, O), SET, np.int32), z, keys,
                        ["v"], bad)
    with pytest.raises(ValueError, match="set/delete/clear"):
        a.ingest_planes(rows, client, cseq, z, z, z, keys, ["v"], z)
    for d in docs:  # nothing sequenced by rejected batches
        assert a.deli.doc_seq(d) == seq_before[d]
