"""Seeded convergence fuzzing of the merge-tree oracle (SURVEY.md §4 pattern).

Random multi-client edit storms with ops crossing in flight; every replica must
converge on text, properties and structure. Seeds are the reproduction handle.
"""

import pytest

from fluidframework_tpu.testing.fuzz import run_sequence_fuzz


@pytest.mark.parametrize("seed", range(20))
def test_sequence_convergence_fuzz(seed):
    run_sequence_fuzz(seed, n_clients=3, n_rounds=25, ops_per_round=4)


@pytest.mark.parametrize("seed", [100, 101, 102])
def test_sequence_convergence_fuzz_many_clients(seed):
    run_sequence_fuzz(seed, n_clients=5, n_rounds=15, ops_per_round=6)
