"""Byte-split fuzz + oracle parity for the batch ingress decode
(ISSUE 15): the accumulate-then-drain door must produce the SAME
(ops, acks, nacks, errors) as the retired per-frame decoder no matter
where the byte stream is cut — mid-header, mid-payload, mid-crc, across
drain passes — and the native (libingress.so) and numpy tiers must agree
bit-for-bit, including on poisoned input."""

import socket
import struct
import zlib

import numpy as np
import pytest

from fluidframework_tpu.server import native_deli, native_ingress
from fluidframework_tpu.server.columnar_ingress import (
    ColumnarAlfred, ColumnarClient, _OP_DTYPE, SCAN_BAD_CRC,
    SCAN_TOO_LARGE, encode_frame, encode_json, encode_op_batch,
    read_frame, reference_decode_op_frame, split_frames,
)
from fluidframework_tpu.server.serving import StringServingEngine

TIERS = [False] + ([True] if native_ingress.available() else [])


def _ops(rows, kinds, a0s, a1s, tidxs, cseqs, refs):
    ops = np.zeros(len(rows), _OP_DTYPE)
    ops["row"], ops["kind"] = rows, kinds
    ops["a0"], ops["a1"], ops["tidx"] = a0s, a1s, tidxs
    ops["cseq"], ops["ref"] = cseqs, refs
    return ops


def _stream():
    """A representative frame stream: control, plain batch, rich batch,
    a zero-op frame, and a second control frame."""
    frames = [
        encode_json({"t": "join", "docs": ["d0", "d1"]}),
        encode_op_batch(["hello ", "world"],
                        _ops([0, 1, 0], [0, 0, 1], [0, 0, 2], [0, 0, 4],
                             [0, 1, 0], [1, 1, 2], [0, 0, 0])),
        encode_op_batch(["x"],
                        _ops([1, 0], [2, 0], [0, 6], [3, 6], [0, 0],
                             [2, 3], [0, 0]),
                        props=[{"bold": True}]),
        encode_op_batch([], _ops([], [], [], [], [], [], [])),
        encode_json({"t": "bye"}),
    ]
    return frames, b"".join(frames)


# ------------------------------------------------------- splitter fuzz

@pytest.mark.parametrize("native", TIERS,
                         ids=["numpy", "native"][:len(TIERS)])
def test_split_frames_every_cut_offset(native):
    """Feed the stream cut at EVERY byte offset (two drain calls) — the
    union of both calls' frames must equal the whole-buffer split, and
    the torn tail must never produce a frame or consume bytes."""
    frames, blob = _stream()
    whole, consumed, status = split_frames(blob, native=native)
    assert status == 0 and consumed == len(blob)
    assert len(whole) == len(frames)
    for cut in range(len(blob) + 1):
        a, ca, sa = split_frames(blob[:cut], native=native)
        assert sa == 0
        # frames reported by the first call must sit on true frame
        # boundaries and be re-derivable from the whole split
        assert a == whole[:len(a)]
        rest = blob[ca:cut] + blob[cut:]
        b, cb, sb = split_frames(rest, native=native)
        assert sb == 0 and ca + cb == len(blob)
        shifted = [(t, off + ca, ln) for t, off, ln in b]
        assert a + shifted == whole


@pytest.mark.parametrize("native", TIERS,
                         ids=["numpy", "native"][:len(TIERS)])
def test_split_frames_poisoned(native):
    frames, blob = _stream()
    # corrupt one payload byte of frame 2: scan must deliver frames 0-1,
    # stop AT the bad frame, and exclude it from `consumed`
    bad = bytearray(blob)
    f2_off = len(frames[0]) + len(frames[1])
    bad[f2_off + 5] ^= 0xFF
    got, consumed, status = split_frames(bytes(bad), native=native)
    assert status == SCAN_BAD_CRC
    assert len(got) == 2 and consumed == f2_off
    # oversized length field: stop with SCAN_TOO_LARGE, same prefix rule
    big = blob[:f2_off] + struct.pack("<BI", ord("B"), 1 << 30)
    got, consumed, status = split_frames(big, native=native)
    assert status == SCAN_TOO_LARGE
    assert len(got) == 2 and consumed == f2_off


@pytest.mark.skipif(len(TIERS) < 2, reason="native ingress unavailable")
def test_split_frames_tiers_agree():
    _, blob = _stream()
    cases = [blob, blob[:17], blob[:5], b"", b"\x00" * 8]
    bad = bytearray(blob)
    bad[9] ^= 1
    cases.append(bytes(bad))
    for buf in cases:
        assert split_frames(buf, native=False) == \
            split_frames(buf, native=True)


# --------------------------------------------------- per-frame oracle

def test_reference_decoder_round_trip():
    texts = ["alpha", "β-utf8 ✓", ""]
    props = [{"color": "red"}, {"nested": {"a": [1, 2]}}]
    ops = _ops([3, 7], [0, 2], [1, 2], [0, 9], [1, 1], [10, 11], [5, 6])
    frame = encode_op_batch(texts, ops, props=props)
    payload = frame[5:-4]
    t, p, got = reference_decode_op_frame(payload, rich=True)
    assert t == texts and p == props
    assert got.tobytes() == ops.tobytes()


@pytest.mark.parametrize("mutate,msg", [
    (lambda pl: pl[:len(pl) - 7], "record section"),
    (lambda pl: pl[:2], None),          # truncated table → struct/IndexError
    (lambda pl: b"\x05" + pl[1:], None),  # table overruns payload
])
def test_reference_decoder_rejects(mutate, msg):
    ops = _ops([0], [0], [0], [0], [0], [1], [0])
    frame = encode_op_batch(["t"], ops)
    payload = mutate(frame[5:-4])
    with pytest.raises((ValueError, IndexError, struct.error)) as ei:
        reference_decode_op_frame(payload, rich=False)
    if msg:
        assert msg in str(ei.value)


@pytest.mark.parametrize("rich", [False, True])
def test_reference_decoder_validation_messages(rich):
    # tidx beyond the table
    ops = _ops([0], [0], [0], [0], [7], [1], [0])
    frame = encode_op_batch(["only"], ops,
                            props=[{"k": 1}] if rich else None)
    with pytest.raises(ValueError, match="text-table range"):
        reference_decode_op_frame(frame[5:-4], rich=rich)
    # kind beyond what the frame type carries
    ops = _ops([0], [2 if not rich else 3], [0], [0], [0], [1], [0])
    frame = encode_op_batch(["t"], ops,
                            props=[{"k": 1}] if rich else None)
    with pytest.raises(ValueError, match="op kind out of range"):
        reference_decode_op_frame(frame[5:-4], rich=rich)


# ------------------------------------------------- end-to-end dribble

pytestmark_native = pytest.mark.skipif(
    not native_deli.available(), reason="native sequencer unavailable")


def _mk(decode="auto", window_ms=1.0):
    eng = StringServingEngine(n_docs=8, capacity=256,
                              batch_window=10 ** 9, sequencer="native")
    srv = ColumnarAlfred(eng, window_min_rows=4, window_ms=window_ms,
                         decode=decode).start_in_thread()
    return eng, srv


def _drive(srv, blob, n_acks, cuts, client_id=None, bases=None):
    """Send ``blob`` (a post-join op stream) sliced at ``cuts`` with a
    tiny pause (so drain ticks land mid-stream), then collect ``n_acks``
    acks. Returns the cut-invariant ack pattern: the sorted set of
    ``(row, cseq - bases[row], acked?)`` — exact seqs vary with window
    packing, but WHICH ops ack vs nack cannot — after asserting per-row
    seq order follows cseq order (per-doc FIFO)."""
    import time
    from collections import defaultdict
    cl = ColumnarClient("127.0.0.1", srv.port)
    cl.join(["d0", "d1"], client_id=client_id)
    pos = 0
    for cut in [*cuts, len(blob)]:
        if cut > pos:
            cl.sock.sendall(blob[pos:cut])
            pos = cut
            time.sleep(0.004)
    got = []
    while len(got) < n_acks:
        resp = cl.recv_json()
        assert resp["t"] == "acks", resp
        for (cseq, seq), row in zip(resp["acks"], resp["rows"]):
            got.append((row, cseq, seq))
    cl.close()
    per_row = defaultdict(list)
    for r, c, s in got:
        if s > 0:
            per_row[r].append((c, s))
    for r, pairs in per_row.items():
        pairs.sort()
        seqs = [s for _, s in pairs]
        assert seqs == sorted(seqs), f"row {r} acked out of FIFO: {pairs}"
    bases = bases or {}
    return sorted((r, c - bases.get(r, 0), s > 0) for r, c, s in got)


@pytestmark_native
@pytest.mark.parametrize("decode", ["numpy"] +
                         (["native"] if native_ingress.available()
                          else []))
def test_dribbled_stream_acks_match_clean_run(decode):
    """Cut the SAME op stream at every byte offset (one cut per run,
    dribbled across drain passes): the ack/nack pattern, per-row FIFO
    order, and ingested-op count must match the cleanly-sent run. Ops
    are net-zero (insert then remove) so hundreds of runs don't run the
    docs out of capacity."""
    eng, srv = _mk(decode=decode)
    try:
        # every run resumes the SAME client identity (its seat persists;
        # a fresh client per cut would exhaust doc capacity) with cseqs
        # continuing CONTIGUOUSLY per row (the dedup cursor nacks gaps).
        # cseqs are fixed-width, so every run's blob has identical
        # length and cut offsets line up across runs.
        cid = 777

        def mkblob(run):
            b0, b1_ = 2 * run, 3 * run   # row 0 sends 2 ops/run, row 1: 3
            fb = encode_op_batch(
                ["aa", "bb"],
                _ops([0, 1], [0, 0], [0, 0], [0, 0], [0, 1],
                     [b0 + 1, b1_ + 1], [0, 0]))
            fr = encode_op_batch(
                [], _ops([1], [2], [0], [2], [0], [b1_ + 2], [0]),
                props=[{"mark": "x"}])
            f2 = encode_op_batch(
                [], _ops([0, 1], [1, 1], [0, 0], [2, 2], [0, 0],
                         [b0 + 2, b1_ + 3], [0, 0]))
            return fb + fr + f2, {0: b0, 1: b1_}

        n_acks = 5
        blob, bases = mkblob(0)
        before = srv.ops_ingested
        want = _drive(srv, blob, n_acks=n_acks, cuts=[],
                      client_id=cid, bases=bases)
        want_ops = srv.ops_ingested - before
        assert want_ops == n_acks
        for cut in range(1, len(blob)):
            blob, bases = mkblob(cut)
            before = srv.ops_ingested
            got = _drive(srv, blob, n_acks=n_acks, cuts=[cut],
                         client_id=cid, bases=bases)
            assert got == want, f"cut={cut}"
            assert srv.ops_ingested - before == want_ops, f"cut={cut}"
    finally:
        srv.stop()


@pytestmark_native
def test_mid_stream_corruption_keeps_prefix():
    """Good frames ahead of a CRC-poisoned one in the same drain still
    SEQUENCE (their ack goes to the now-dead socket, exactly as the
    per-frame door dropped it — resubmit+dedup recovers it); the client
    gets the diagnostic, the connection dies, the server keeps
    serving."""
    import time
    eng, srv = _mk()
    try:
        good = encode_op_batch(["ok"],
                               _ops([0], [0], [0], [0], [0], [1], [0]))
        bad = bytearray(encode_op_batch(
            ["zz"], _ops([1], [0], [0], [0], [0], [2], [0])))
        bad[7] ^= 0x55
        cl = ColumnarClient("127.0.0.1", srv.port)
        cl.join(["d0", "d1"])
        cl.sock.sendall(good + bytes(bad))
        resp = cl.recv_json()
        assert resp["t"] == "error" and "crc" in resp["message"].lower()
        assert cl.sock.recv(1) == b""
        # the good prefix was still decoded and sequenced
        deadline = time.monotonic() + 2.0
        while srv.ops_ingested < 1 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert srv.ops_ingested == 1
        # server survives: a fresh client still gets service
        cl2 = ColumnarClient("127.0.0.1", srv.port)
        cl2.join(["d0"])
        cl2.send_ops(["y"], _ops([0], [0], [0], [0], [0], [1], [0]))
        assert cl2.recv_json()["t"] == "acks"
        cl2.close()
    finally:
        srv.stop()


@pytestmark_native
def test_oversized_frame_faults_connection():
    eng, srv = _mk()
    try:
        cl = ColumnarClient("127.0.0.1", srv.port)
        cl.join(["d0"])
        cl.sock.sendall(struct.pack("<BI", ord("B"), 1 << 30))
        resp = cl.recv_json()
        assert resp["t"] == "error" and "too large" in resp["message"]
        assert cl.sock.recv(1) == b""
    finally:
        srv.stop()


@pytestmark_native
def test_numpy_tier_end_to_end():
    """The always-available fallback must serve the full socket path on
    its own (no native library consulted)."""
    eng, srv = _mk(decode="numpy")
    try:
        assert srv.drain_stats()["tier"] == "numpy"
        cl = ColumnarClient("127.0.0.1", srv.port)
        cl.join(["d0"])
        cl.send_ops(["hi"], _ops([0], [0], [0], [0], [0], [1], [0]))
        assert cl.recv_json()["acks"][0][1] > 0
        st = srv.drain_stats()
        assert st["passes"] >= 1 and st["drained_bytes"] > 0
        cl.close()
    finally:
        srv.stop()
