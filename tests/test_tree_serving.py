"""TreeServingEngine end-to-end: Deli sequencing + durable log + batched
device tree merge, vs live SharedTree oracle clients — plus summary +
log-tail recovery and the overflow escape hatch (VERDICT r2 #1)."""

import random

import pytest

from fluidframework_tpu.models.shared_tree import SharedTree
from fluidframework_tpu.server.deli import NackReason
from fluidframework_tpu.server.oplog import PartitionedLog
from fluidframework_tpu.server.serving import TreeServingEngine


class _Client:
    """One SharedTree oracle replica wired to a serving engine: edits are
    captured locally and submitted through the engine's ingress."""

    def __init__(self, engine, doc_id, client_id):
        self.engine = engine
        self.doc_id = doc_id
        self.tree = SharedTree(doc_id, client_id)
        self.client_seq = 0
        self._out = []
        self.tree.connect(self._out.append)
        engine.connect(doc_id, client_id)

    def drain_submit(self):
        """Submit every locally-captured edit; returns sequenced msgs."""
        msgs = []
        while self._out:
            contents = self._out.pop(0)
            self.client_seq += 1
            msg, nack = self.engine.submit(
                self.doc_id, self.tree.client_id, self.client_seq,
                self.tree.last_processed_seq, contents)
            assert nack is None, nack
            msgs.append(msg)
        return msgs


def _random_edit(rng, c):
    """One random oracle edit on client ``c`` (same op mix as the kernel
    fuzz in test_tree_kernel.py)."""
    t = c.tree

    def random_node():
        return rng.choice(list(t.kernel.view.nodes))

    roll = rng.random()
    try:
        if roll < 0.45 or len(t.kernel.view.nodes) < 4:
            parent = random_node()
            sibs = t.children(parent, "kids")
            after = rng.choice([None] + sibs) if sibs else None
            t.insert(parent, "kids", value=rng.randint(0, 99), after=after)
        elif roll < 0.6:
            nid = random_node()
            if nid != "root":
                t.remove(nid)
        elif roll < 0.75:
            nid, dest = random_node(), random_node()
            if nid != "root":
                t.move(nid, dest, "kids")
        elif roll < 0.9:
            t.set_value(random_node(), rng.randint(100, 199))
        else:
            anchor = random_node()

            def txn(tr, anchor=anchor):
                a = tr.insert(anchor, "kids", value=1000)
                tr.insert(a, "kids", value=1001)
                tr.set_value(a, 1002)

            t.run_transaction(txn, constraints=[{"nodeExists": anchor}])
    except KeyError:
        pass


def _storm(engine, docs, clients, rng, n_ops, inflight):
    """Concurrent edits with lazy delivery (ref_seq genuinely lags)."""
    for _ in range(n_ops):
        doc = rng.choice(docs)
        c = rng.choice(clients[doc])
        _random_edit(rng, c)
        inflight[doc].extend(c.drain_submit())
        for d in docs:
            k = rng.randint(0, len(inflight[d]))
            for m in inflight[d][:k]:
                for cc in clients[d]:
                    cc.tree.apply_msg(m)
            del inflight[d][:k]


def _drain(docs, clients, inflight):
    for d in docs:
        for m in inflight[d]:
            for cc in clients[d]:
                cc.tree.apply_msg(m)
        inflight[d].clear()


def _mk(engine, docs, n_clients, id_start=1):
    clients, cid = {}, id_start
    for d in docs:
        clients[d] = [_Client(engine, d, cid + i) for i in range(n_clients)]
        cid += n_clients
    return clients


@pytest.mark.parametrize("seed", range(4))
def test_tree_engine_converges_with_clients(seed):
    rng = random.Random(seed)
    docs = ["doc-a", "doc-b"]
    engine = TreeServingEngine(n_docs=2, capacity=512, batch_window=8)
    clients = _mk(engine, docs, 3)
    inflight = {d: [] for d in docs}
    _storm(engine, docs, clients, rng, 50, inflight)
    _drain(docs, clients, inflight)
    for d in docs:
        dicts = [c.tree.to_dict() for c in clients[d]]
        for x in dicts[1:]:
            assert x == dicts[0]
        assert engine.to_dict(d) == dicts[0], d


def test_tree_engine_nack_paths():
    engine = TreeServingEngine(n_docs=1, capacity=64)
    engine.connect("d", 1)
    # malformed shapes are rejected before sequencing/logging
    for bad in (None, 7, {"op": "frobnicate"},
                {"op": "insert", "parent": "root"},          # no field/nodes
                {"op": "insert", "parent": "root", "field": "kids",
                 "nodes": [{"id": ""}]},                      # empty id
                {"op": "setValue", "id": "x", "value": object()},
                {"op": "transaction", "edits": []},
                {"op": "transaction", "edits": [{"op": "remove", "id": "x"}],
                 "constraints": [{"nodeExists": 3}]}):
        msg, nack = engine.submit("d", 1, 1, 0, bad)
        assert msg is None and nack.reason == NackReason.MALFORMED, bad
    assert engine.log.size(0) == 0 or all(
        m.type != 0 for m in engine.log.read(0))  # nothing op-logged
    # a valid op still flows
    msg, nack = engine.submit(
        "d", 1, 1, 0, {"op": "insert", "parent": "root", "field": "kids",
                       "after": None, "nodes": [{"id": "n1", "value": 5}]})
    assert nack is None and msg.seq >= 1
    assert engine.node_value("d", "n1") == 5


def test_tree_engine_summary_and_tail_recovery():
    rng = random.Random(7)
    docs = ["t-0", "t-1"]
    log = PartitionedLog(4)
    engine = TreeServingEngine(n_docs=2, capacity=512, batch_window=8,
                               n_partitions=4, log=log)
    clients = _mk(engine, docs, 2)
    inflight = {d: [] for d in docs}
    _storm(engine, docs, clients, rng, 30, inflight)
    summary = engine.summarize()
    # ops AFTER the summary live only in the log tail
    _storm(engine, docs, clients, rng, 15, inflight)
    _drain(docs, clients, inflight)
    want = {d: engine.to_dict(d) for d in docs}

    revived = TreeServingEngine.load(summary, log)
    for d in docs:
        assert revived.to_dict(d) == want[d], d
    # the revived sequencer continues past the tail: new ops still flow
    c = clients[docs[0]][0]
    c.tree.insert("root", "kids", value=777, node_id="post-revive")
    msgs = []
    while c._out:
        contents = c._out.pop(0)
        c.client_seq += 1
        msg, nack = revived.submit(docs[0], c.tree.client_id, c.client_seq,
                                   c.tree.last_processed_seq, contents)
        assert nack is None
        msgs.append(msg)
    for m in msgs:
        for cc in clients[docs[0]]:
            cc.tree.apply_msg(m)
    assert revived.node_value(docs[0], "post-revive") == 777
    assert revived.to_dict(docs[0]) == clients[docs[0]][0].tree.to_dict()


def test_tree_engine_overflow_reupload_and_graduate():
    rng = random.Random(3)
    log = PartitionedLog(2)
    engine = TreeServingEngine(n_docs=2, capacity=16, batch_window=4,
                               n_partitions=2, log=log)
    clients = _mk(engine, ["big", "small"], 1)
    big, small = clients["big"][0], clients["small"][0]
    small.tree.insert("root", "kids", value=1, node_id="s1")
    for m in small.drain_submit():
        small.tree.apply_msg(m)
    # overflow the 16-slot row with 40 inserts
    for i in range(40):
        big.tree.insert("root", "kids", value=i, node_id=f"b{i}")
    for m in big.drain_submit():
        big.tree.apply_msg(m)
    engine.flush()
    assert "big" in engine.overflowed_docs()
    report = engine.recover_overflowed(grow_limit=1 << 12)
    assert report["big"] == "graduated"  # 41 nodes > 16-slot tier
    assert engine.to_dict("big") == big.tree.to_dict()
    assert engine.to_dict("small") == small.tree.to_dict()
    # the graduated doc keeps serving new ops through its own store
    big.tree.insert("root", "kids", value=99, node_id="late")
    for m in big.drain_submit():
        big.tree.apply_msg(m)
    assert engine.node_value("big", "late") == 99
    assert engine.to_dict("big") == big.tree.to_dict()
    # summary + recovery carries the graduated tier
    summary = engine.summarize()
    revived = TreeServingEngine.load(summary, log)
    assert revived.to_dict("big") == big.tree.to_dict()

    # a doc that shrinks back under capacity re-uploads instead
    rng2 = random.Random(4)
    log2 = PartitionedLog(2)
    e2 = TreeServingEngine(n_docs=1, capacity=16, batch_window=4,
                           n_partitions=2, log=log2)
    c2 = _mk(e2, ["d"], 1)["d"][0]
    for i in range(30):
        c2.tree.insert("root", "kids", value=i, node_id=f"x{i}")
    for i in range(25):          # remove most: final tree fits in 16 slots
        c2.tree.remove(f"x{i}")
    for m in c2.drain_submit():
        c2.tree.apply_msg(m)
    e2.flush()
    assert "d" in e2.overflowed_docs()
    rep2 = e2.recover_overflowed(grow_limit=1 << 12)
    assert rep2["d"] == "reuploaded"
    assert e2.to_dict("d") == c2.tree.to_dict()
    # and the row serves new ops after re-upload
    c2.tree.insert("root", "kids", value=5, node_id="fresh")
    for m in c2.drain_submit():
        c2.tree.apply_msg(m)
    assert e2.to_dict("d") == c2.tree.to_dict()


def test_tree_engine_graduated_tier_regrows():
    log = PartitionedLog(2)
    engine = TreeServingEngine(n_docs=1, capacity=8, batch_window=4,
                               n_partitions=2, log=log)
    c = _mk(engine, ["d"], 1)["d"][0]
    for i in range(20):
        c.tree.insert("root", "kids", value=i, node_id=f"a{i}")
    for m in c.drain_submit():
        c.tree.apply_msg(m)
    engine.flush()
    assert engine.recover_overflowed(grow_limit=1 << 12)["d"] == "graduated"
    grad_cap = engine._graduated["d"].capacity
    # keep growing past the graduated store's capacity
    for i in range(2 * grad_cap):
        c.tree.insert("root", "kids", value=i, node_id=f"z{i}")
    for m in c.drain_submit():
        c.tree.apply_msg(m)
    engine.flush()
    assert engine.recover_overflowed(grow_limit=1 << 14)["d"] == "regrown"
    assert engine.to_dict("d") == c.tree.to_dict()


def test_tree_engine_setvalue_without_value_key_nacked():
    """Review regression: a setValue op missing the "value" key must be
    nacked BEFORE logging — acked-and-logged, it would crash every flush
    and every recovery replay (KeyError in the expand path)."""
    engine = TreeServingEngine(n_docs=1, capacity=64)
    engine.connect("d", 1)
    msg, nack = engine.submit("d", 1, 1, 0, {"op": "setValue", "id": "n1"})
    assert msg is None and nack.reason == NackReason.MALFORMED
    engine.flush()  # must not raise
    # and recovery of the log must not raise either
    revived = TreeServingEngine.load(engine.summarize(), engine.log)
    assert revived.to_dict("d") == {"id": "root", "type": None,
                                    "value": None}


def test_tree_engine_graduated_doc_does_not_repin_row():
    """Review regression: ops to a graduated doc must not re-allocate a
    flat-tier row (permanent capacity leak, persisted via summarize)."""
    log = PartitionedLog(2)
    engine = TreeServingEngine(n_docs=1, capacity=8, batch_window=4,
                               n_partitions=2, log=log)
    c = _mk(engine, ["A"], 1)["A"][0]
    for i in range(20):
        c.tree.insert("root", "kids", value=i, node_id=f"a{i}")
    for m in c.drain_submit():
        c.tree.apply_msg(m)
    engine.flush()
    assert engine.recover_overflowed(grow_limit=1 << 12)["A"] == "graduated"
    # post-graduation op must not consume the freed row...
    c.tree.insert("root", "kids", value=99, node_id="post")
    for m in c.drain_submit():
        c.tree.apply_msg(m)
    assert "A" not in engine._doc_rows
    # ...so a NEW doc can still claim it
    c2 = _Client(engine, "B", 50)
    c2.tree.insert("root", "kids", value=1, node_id="b1")
    for m in c2.drain_submit():
        c2.tree.apply_msg(m)
    assert engine.node_value("B", "b1") == 1
    assert engine.to_dict("A") == c.tree.to_dict()
