"""Native C++ sequencer: build, parity vs the Python Deli, checkpoint
round-trip, and batch stamping."""

import random

import numpy as np
import pytest

from fluidframework_tpu.core.protocol import MessageType
from fluidframework_tpu.server.deli import DeliSequencer, NackReason
from fluidframework_tpu.server import native_deli

pytestmark = pytest.mark.skipif(
    not native_deli.available(), reason="no native toolchain")


def test_native_matches_python_on_random_stream():
    rng = random.Random(0)
    py = DeliSequencer()
    nat = native_deli.NativeDeli()
    docs = ["a", "b"]
    clients = {}
    next_id = [100]
    for d in docs:
        clients[d] = []
    for step in range(400):
        d = rng.choice(docs)
        roll = rng.random()
        if roll < 0.05 or not clients[d]:
            cid = next_id[0]
            next_id[0] += 1
            clients[d].append({"id": cid, "cs": 0, "ref": py._doc(d).seq})
            jm = py.client_join(d, cid)
            nseq = nat.client_join(d, cid)
            assert jm.seq == nseq
            continue
        c = rng.choice(clients[d])
        if roll < 0.08 and len(clients[d]) > 1:
            clients[d].remove(c)
            lm = py.client_leave(d, c["id"])
            nseq = nat.client_leave(d, c["id"])
            assert lm.seq == nseq
            continue
        is_noop = roll < 0.15
        if not is_noop:
            c["cs"] += 1
        c["ref"] = py._doc(d).seq  # up-to-date client
        msg, nack = py.sequence(
            d, c["id"], c["cs"], c["ref"],
            MessageType.NOOP if is_noop else MessageType.OP, {})
        nseq, nmin, nnack = nat.sequence(d, c["id"], c["cs"], c["ref"],
                                         is_noop)
        assert nack is None and nnack is None, (step, nack, nnack)
        assert (msg.seq, msg.min_seq) == (nseq, nmin), step


def test_native_nack_codes():
    nat = native_deli.NativeDeli()
    assert nat.sequence("d", 1, 1, 0)[2] == NackReason.UNKNOWN_CLIENT
    nat.client_join("d", 1)
    assert nat.sequence("d", 1, 1, 0)[2] is None
    assert nat.sequence("d", 1, 1, 0)[2] == NackReason.DUPLICATE
    assert nat.sequence("d", 1, 5, 0)[2] == NackReason.CLIENT_SEQ_GAP


def test_native_checkpoint_roundtrip():
    nat = native_deli.NativeDeli()
    nat.client_join("doc", 7)
    for i in range(1, 6):
        nat.sequence("doc", 7, i, i)
    blob = nat.checkpoint()
    restored = native_deli.NativeDeli.restore(blob)
    assert restored.doc_seq("doc") == nat.doc_seq("doc")
    assert restored.doc_min_seq("doc") == nat.doc_min_seq("doc")
    # sequencing continues with dedupe state intact
    assert restored.sequence("doc", 7, 5, 5)[2] == NackReason.DUPLICATE
    assert restored.sequence("doc", 7, 6, 5)[2] is None


def test_native_batch_stamping():
    nat = native_deli.NativeDeli()
    nat.client_join("doc", 1)
    nat.client_join("doc", 2)
    n = 1000
    clients = np.where(np.arange(n) % 2 == 0, 1, 2).astype(np.int32)
    client_seqs = (np.arange(n) // 2 + 1).astype(np.int32)
    ref_seqs = np.full(n, 2, np.int32)
    seqs, mins = nat.sequence_batch("doc", clients, client_seqs, ref_seqs)
    assert (seqs > 0).all()
    assert list(seqs) == list(range(3, n + 3))  # dense total order
    assert (np.diff(mins) >= 0).all()           # MSN monotone


def test_checkpoint_hostile_doc_ids():
    """Doc ids containing the checkpoint delimiters must roundtrip (they are
    percent-encoded in the blob) and malformed blobs must not crash."""
    nat = native_deli.NativeDeli()
    hostile = "doc\twith\ndelims%and%more"
    nat.client_join(hostile, 1)
    nat.client_join("plain", 2)
    nat.sequence(hostile, 1, 1, 1)
    blob = nat.checkpoint()
    restored = native_deli.NativeDeli.restore(blob)
    assert restored.doc_seq(hostile) == nat.doc_seq(hostile)
    assert restored.doc_seq("plain") == nat.doc_seq("plain")
    # sequencing continues on the hostile doc with dedupe intact
    assert restored.sequence(hostile, 1, 1, 1)[2] == NackReason.DUPLICATE
    # garbage blobs parse without raising (and without crashing the process)
    native_deli.NativeDeli.restore(b"not\ta\tvalid\nblob\x00\xff\t\t\t\n")
