"""Durability integrity plane (ISSUE 10): checksum-chained oplog,
epoch-fenced appends, the multi-generation recovery ladder, and the
offline scrubber.

The contract under test:

- every spilled record carries a CRC32 chained over its predecessor, so
  disk rot (bit flips, splices, truncate-then-regrowth) is DETECTED on
  replay — never silently applied;
- a torn tail (crash artifact) is still recovered by truncation, exactly
  as before — the chain distinguishes rot from tears;
- append authority is epoch-fenced: after a takeover (recover() or a
  follower promotion) the deposed writer's appends raise
  ``FencedWriterError`` instead of splitting the brain;
- summaries are kept K generations deep behind hashed manifests; a
  corrupt newest generation falls back rung by rung and converges to the
  SAME digest via longer tail replay;
- ``tools/log_scrub.py --repair`` restores a corrupt spill to its last
  verified prefix, after which recovery succeeds.
"""

import json
import os
import random

import pytest

from fluidframework_tpu.server.oplog import (
    FencedWriterError, OplogCorruptionError, PartitionedLog, chain_step,
    scan_chained_spill,
)
from fluidframework_tpu.runtime.summarizer import (
    SummaryGenerationStore, SummaryIntegrityError,
)
from fluidframework_tpu.testing import chaos
from fluidframework_tpu.utils.faultpoints import (
    corrupt_bitflip, corrupt_splice, corrupt_truncate,
)
from fluidframework_tpu.utils.telemetry import REGISTRY


def _fill_string_engine(log, n_ops=8, doc="d"):
    """A spilled string engine with ``n_ops`` sequenced inserts."""
    engine = chaos.make_engine("string", log=log)
    engine.connect(doc, 1)
    for i in range(n_ops):
        msg, nack = engine.submit(doc, 1, i + 1, 0,
                                  {"mt": "insert", "kind": 0, "pos": 0,
                                   "text": f"w{i}"})
        assert nack is None
    engine.flush()
    return engine


# ------------------------------------------------------- checksum chain

def test_chain_verifies_on_clean_replay(tmp_path):
    """A clean spill replays fully; the recovered log's chain head equals
    the writer's (the reader re-derived the same chain, byte for byte)."""
    log = PartitionedLog(2, str(tmp_path), "t")
    engine = _fill_string_engine(log, n_ops=10)
    heads = [log.chain_head(p) for p in range(2)]
    sizes = [log.size(p) for p in range(2)]
    log.close()
    recovered = PartitionedLog.recover(2, str(tmp_path), "t")
    assert [recovered.size(p) for p in range(2)] == sizes
    assert [recovered.chain_head(p) for p in range(2)] == heads
    assert any(h not in (None, 0) for h in heads)  # chain actually ran
    recovered.close()


def test_chain_step_is_a_chain():
    """The chain word depends on every predecessor, not just the record
    itself — swapping two payloads changes downstream words."""
    a, b = b'{"x": 1}', b'{"x": 2}'
    c1 = chain_step(b, chain_step(a, 0))
    c2 = chain_step(a, chain_step(b, 0))
    assert c1 != c2


def test_single_bit_flip_detected(tmp_path):
    """One flipped bit anywhere mid-file refuses recovery loudly."""
    log = PartitionedLog(1, str(tmp_path), "t")
    _fill_string_engine(log, n_ops=8)
    log.close()
    path = tmp_path / "t-p0.jsonl"
    clean = path.read_bytes()
    # flip a bit inside the SECOND record's payload: unambiguously
    # mid-file, far from the torn-tail window
    lines = clean.splitlines(keepends=True)
    assert len(lines) >= 5
    off = len(lines[0]) + len(lines[1]) // 2
    rotted = bytearray(clean)
    rotted[off] ^= 0x10
    path.write_bytes(bytes(rotted))
    before = REGISTRY.snapshot().get("oplog_chain_verify_failures_total", 0)
    with pytest.raises(OplogCorruptionError, match="mid-file"):
        PartitionedLog.recover(1, str(tmp_path), "t")
    after = REGISTRY.snapshot().get("oplog_chain_verify_failures_total", 0)
    assert after > before


def test_record_splice_detected(tmp_path):
    """Removing one interior record leaves every line individually
    well-formed — only the CHAIN can see the gap. It must."""
    log = PartitionedLog(1, str(tmp_path), "t")
    _fill_string_engine(log, n_ops=8)
    log.close()
    path = tmp_path / "t-p0.jsonl"
    rng = random.Random(5)
    ev = corrupt_splice(str(path), rng)
    assert "skipped" not in ev
    scan = scan_chained_spill(str(path))
    assert scan["problems"], "splice invisible to the chain scan"
    with pytest.raises(OplogCorruptionError, match="mid-file"):
        PartitionedLog.recover(1, str(tmp_path), "t")


def test_torn_tail_still_recovers(tmp_path):
    """The chain must NOT turn crash artifacts into hard errors: an
    unterminated trailing fragment is truncated away, as ever."""
    log = PartitionedLog(1, str(tmp_path), "t")
    _fill_string_engine(log, n_ops=6)
    n = log.size(0)
    log.close()
    path = tmp_path / "t-p0.jsonl"
    clean = path.read_bytes()
    path.write_bytes(clean + clean.splitlines(keepends=True)[-1][:9])
    recovered = PartitionedLog.recover(1, str(tmp_path), "t")
    assert recovered.size(0) == n
    assert path.read_bytes() == clean
    recovered.close()


def test_boundary_truncation_caught_by_summary_anchor(tmp_path):
    """Truncation at an exact record boundary is locally invisible (it
    looks like a shorter, healthy log). The summary's chain anchor
    (offset + chain word per partition) catches it at load time."""
    from fluidframework_tpu.server.serving import StringServingEngine
    log = PartitionedLog(1, str(tmp_path), "t")
    engine = _fill_string_engine(log, n_ops=8)
    summary = engine.summarize()
    assert summary.get("chain_heads") is not None
    log.close()
    path = tmp_path / "t-p0.jsonl"
    lines = path.read_bytes().splitlines(keepends=True)
    # drop the last two records EXACTLY at their boundaries
    path.write_bytes(b"".join(lines[:-2]))
    recovered = PartitionedLog.recover(1, str(tmp_path), "t")  # looks fine
    with pytest.raises(OplogCorruptionError,
                       match="truncated behind the summary"):
        StringServingEngine.load(summary, recovered)
    recovered.close()


def test_mid_record_truncation_then_regrowth_detected(tmp_path):
    """Truncate mid-record, then let new appends regrow the file: the
    fused boundary breaks the chain and recovery refuses — regrowth must
    not launder a truncation into a 'clean' log."""
    log = PartitionedLog(1, str(tmp_path), "t")
    _fill_string_engine(log, n_ops=8)
    log.close()
    path = tmp_path / "t-p0.jsonl"
    clean = path.read_bytes()
    lines = clean.splitlines(keepends=True)
    cut = sum(len(ln) for ln in lines[:-2]) + len(lines[-2]) // 2
    regrown = clean[:cut] + lines[-1]
    path.write_bytes(regrown)
    with pytest.raises(OplogCorruptionError, match="mid-file"):
        PartitionedLog.recover(1, str(tmp_path), "t")


# ---------------------------------------------------------- epoch fence

def test_follower_promotion_fences_old_leader(tmp_path):
    """Split-brain drill: after a follower promotes, exactly ONE writer
    lands records — the deposed leader's appends raise, and digest
    parity holds on the survivor."""
    from fluidframework_tpu.parallel.replicated import OplogFollower
    log = PartitionedLog(2, str(tmp_path), "deltas")
    leader = _fill_string_engine(log, n_ops=6)
    follower = OplogFollower(leader, family="string")
    # more leader traffic the follower must pick up at promotion
    for i in range(6, 9):
        msg, nack = leader.submit("d", 1, i + 1, 0,
                                  {"mt": "insert", "kind": 0, "pos": 0,
                                   "text": f"w{i}"})
        assert nack is None
    leader.flush()
    before = REGISTRY.snapshot().get("fenced_appends_rejected_total", 0)
    promoted = follower.promote()
    sizes = [log.size(p) for p in range(2)]
    # the not-actually-dead leader tries to keep writing: fenced out,
    # nothing lands
    with pytest.raises(FencedWriterError):
        leader.submit("d", 1, 10, 0,
                      {"mt": "insert", "kind": 0, "pos": 0, "text": "zz"})
    assert [log.size(p) for p in range(2)] == sizes
    after = REGISTRY.snapshot().get("fenced_appends_rejected_total", 0)
    assert after > before
    # the promoted engine holds the full history and still has the pen
    assert promoted.read_text("d") == "".join(
        f"w{i}" for i in reversed(range(9)))
    msg, nack = promoted.submit("d", 1, 10, 0,
                                {"mt": "insert", "kind": 0, "pos": 0,
                                 "text": "ok"})
    assert nack is None
    assert sum(log.size(p) for p in range(2)) > sum(sizes)


def test_cross_process_fence_via_fence_file(tmp_path):
    """A takeover by a SECOND LocalService instance (recover() on the
    same spill) fences the first through the persisted fence file — the
    in-memory epoch word alone cannot protect across processes."""
    from fluidframework_tpu.server.tinylicious import LocalService
    svc1 = LocalService(n_partitions=2, spill_dir=str(tmp_path))
    conn = svc1.connect("doc")
    for i in range(5):
        conn.submit({"op": "set", "key": f"k{i}", "value": i})
    svc2 = LocalService.recover(str(tmp_path), n_partitions=2)
    assert svc2.writer_epoch > svc1.writer_epoch
    with pytest.raises(FencedWriterError):
        conn.submit({"op": "set", "key": "zombie", "value": -1})
    # the new authority writes freely
    conn2 = svc2.connect("doc")
    conn2.submit({"op": "set", "key": "k5", "value": 5})
    svc1.close()
    svc2.close()


def test_unfenced_appends_still_pass(tmp_path):
    """Legacy callers that never took a fence (epoch=None) keep working
    even after bumps — fencing is opt-in per append."""
    log = PartitionedLog(1, str(tmp_path), "t")
    log.append(0, {"a": 1})
    log.bump_fence()
    log.append(0, {"a": 2})          # unfenced: passes
    w = log.open_for_append(log.fence_epoch)
    w.append(0, {"a": 3})            # current-epoch writer: passes
    stale = log.open_for_append(log.fence_epoch)
    log.bump_fence()
    with pytest.raises(FencedWriterError):
        stale.append(0, {"a": 4})
    assert log.size(0) == 3
    log.close()


# ------------------------------------------------------ recovery ladder

def test_generation_store_keeps_k_and_prunes(tmp_path):
    store = SummaryGenerationStore(str(tmp_path), keep=3)
    for g in range(5):
        store.save({"gen": g}, seq=g * 10)
    assert store.generations() == [2, 3, 4]
    summary, seq, depth = store.load_latest()
    assert (summary["gen"], seq, depth) == (4, 40, 0)


def test_ladder_falls_back_generation_by_generation(tmp_path):
    store = SummaryGenerationStore(str(tmp_path), keep=3)
    for g in range(3):
        store.save({"gen": g}, seq=g * 10)
    rng = random.Random(9)
    corrupt_bitflip(
        os.path.join(str(tmp_path), store._BLOB.format(2)), rng)
    summary, seq, depth = store.load_latest()
    assert (summary["gen"], seq, depth) == (1, 10, 1)
    assert REGISTRY.snapshot().get("recovery_ladder_depth") == 1
    # next rung rotted too: one deeper
    corrupt_truncate(
        os.path.join(str(tmp_path), store._BLOB.format(1)), rng)
    summary, seq, depth = store.load_latest()
    assert (summary["gen"], seq, depth) == (0, 0, 2)
    # all rungs rotted: loud failure listing every reason
    corrupt_bitflip(
        os.path.join(str(tmp_path), store._MANIFEST.format(0)), rng)
    with pytest.raises(SummaryIntegrityError):
        store.load_latest()


def test_ladder_converges_to_identical_digest(tmp_path):
    """Engine-level drill: corrupt the newest summary generation; the
    ladder loads the older one, replays a LONGER durable tail, and ends
    at the exact digest of an uncorrupted control."""
    from fluidframework_tpu.server.serving import StringServingEngine
    spill = tmp_path / "spill"
    spill.mkdir()
    log = PartitionedLog(2, str(spill), "deltas")
    store = SummaryGenerationStore(str(tmp_path / "gens"), keep=3)
    engine = chaos.make_engine("string", log=log)
    engine.connect("d", 1)
    seq = 0
    for i in range(4):
        msg, nack = engine.submit("d", 1, i + 1, 0,
                                  {"mt": "insert", "kind": 0, "pos": 0,
                                   "text": f"a{i}"})
        assert nack is None
        seq = msg.seq
    engine.flush()
    store.save(engine.summarize(), seq)
    for i in range(4, 8):
        msg, nack = engine.submit("d", 1, i + 1, 0,
                                  {"mt": "insert", "kind": 0, "pos": 0,
                                   "text": f"a{i}"})
        assert nack is None
        seq = msg.seq
    engine.flush()
    store.save(engine.summarize(), seq)
    control = engine.read_text("d")
    log.close()

    corrupt_bitflip(os.path.join(str(tmp_path / "gens"),
                                 store._BLOB.format(1)),
                    random.Random(3))
    summary, _seq, depth = store.load_latest()
    assert depth == 1
    recovered_log = PartitionedLog.recover(2, str(spill), "deltas")
    recovered = StringServingEngine.load(summary, recovered_log)
    recovered.flush()
    assert recovered.read_text("d") == control
    recovered_log.close()


# -------------------------------------------------------------- scrubber

def _tool(name):
    import importlib.util
    path = os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools", f"{name}.py")
    spec = importlib.util.spec_from_file_location(name, path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_scrubber_reports_break_with_offset(tmp_path):
    log = PartitionedLog(1, str(tmp_path), "t")
    _fill_string_engine(log, n_ops=8)
    log.close()
    path = tmp_path / "t-p0.jsonl"
    lines = path.read_bytes().splitlines(keepends=True)
    corrupt_splice(str(path), random.Random(2))
    log_scrub = _tool("log_scrub")
    reports = log_scrub.scrub_tree(str(tmp_path))
    (rep,) = [r for r in reports if r["path"].endswith(".jsonl")]
    assert rep["problems"]
    p = rep["problems"][0]
    # the reported byte offset is a real line boundary in the rotted file
    data = path.read_bytes()
    assert 0 < p["offset"] < len(data)
    assert data[:p["offset"]].endswith(b"\n")
    assert not rep["repaired"]
    assert path.read_bytes() == data  # --check never mutates


def test_scrubber_repair_roundtrip(tmp_path):
    """corrupt → scrub --repair → recover() succeeds on the verified
    prefix; the repair is idempotent."""
    log = PartitionedLog(1, str(tmp_path), "t")
    _fill_string_engine(log, n_ops=8)
    log.close()
    path = tmp_path / "t-p0.jsonl"
    corrupt_bitflip(str(path), random.Random(4))
    scan = scan_chained_spill(str(path))
    assert scan["problems"] or scan["torn"]
    log_scrub = _tool("log_scrub")
    before = REGISTRY.snapshot().get("scrub_repairs_total", 0)
    reports = log_scrub.scrub_tree(str(tmp_path), repair=True)
    assert any(r["repaired"] for r in reports)
    assert REGISTRY.snapshot().get("scrub_repairs_total", 0) > before
    # repaired file verifies clean and recovers without error
    scan = scan_chained_spill(str(path))
    assert not scan["problems"] and not scan["torn"]
    recovered = PartitionedLog.recover(1, str(tmp_path), "t")
    recovered.close()
    # idempotent: a second scrub finds nothing to repair
    reports = log_scrub.scrub_tree(str(tmp_path), repair=True)
    assert not any(r["repaired"] for r in reports)


def test_scrubber_quarantines_rotted_generation(tmp_path):
    store = SummaryGenerationStore(str(tmp_path), keep=3)
    for g in range(3):
        store.save({"gen": g}, seq=g)
    corrupt_bitflip(os.path.join(str(tmp_path), store._BLOB.format(2)),
                    random.Random(6))
    log_scrub = _tool("log_scrub")
    reports = log_scrub.scrub_tree(str(tmp_path), repair=True)
    (rep,) = [r for r in reports if r["format"] == "generations"]
    assert rep["problems"] and rep["repaired"]
    # the rotted rung is gone; the ladder now starts at a verified one
    summary, seq, depth = store.load_latest()
    assert summary["gen"] == 1 and depth == 0


def test_scrub_cli_check_exits_nonzero_on_break(tmp_path, capsys):
    log = PartitionedLog(1, str(tmp_path), "t")
    _fill_string_engine(log, n_ops=8)
    log.close()
    log_scrub = _tool("log_scrub")
    assert log_scrub.main(["--check", str(tmp_path)]) == 0
    capsys.readouterr()  # drain the human-readable report
    corrupt_splice(str(tmp_path / "t-p0.jsonl"), random.Random(8))
    assert log_scrub.main(["--check", "--json", str(tmp_path)]) == 1
    out = json.loads(capsys.readouterr().out)
    assert out["summary"]["chain_breaks"] >= 1


# ----------------------------------------------------------- native log

def _native_log():
    from fluidframework_tpu.server import native_oplog
    if not native_oplog.available():
        pytest.skip("native oplog not built")
    return native_oplog


def _native_msgs(n):
    from fluidframework_tpu.core.protocol import (
        MessageType, SequencedDocumentMessage,
    )
    return [SequencedDocumentMessage(
        doc_id="d", client_id=1, client_seq=i, ref_seq=i - 1, seq=i,
        min_seq=0, type=MessageType.OP, contents={"i": i})
        for i in range(1, n + 1)]


def _split_frames(data):
    import struct
    frames, off = [], 0
    while off + 8 <= len(data):
        ln, _crc = struct.unpack_from("<II", data, off)
        frames.append(data[off:off + 8 + ln])
        off += 8 + ln
    return frames


def test_native_chain_detects_frame_splice(tmp_path):
    """Removing one whole frame keeps every remaining frame's own CRC
    valid — only the cross-frame chain can see it, on reopen AND in the
    scrubber."""
    native_oplog = _native_log()
    d = str(tmp_path)
    log = native_oplog.NativePartitionedLog(d, 1)
    for m in _native_msgs(6):
        log.append(0, m)
    log.sync()
    log.close()
    path = os.path.join(d, "p0.log")
    with open(path, "rb") as f:
        frames = _split_frames(f.read())
    assert len(frames) >= 6
    with open(path, "wb") as f:
        f.write(b"".join(frames[:2] + frames[3:]))  # splice frame 2 out
    log_scrub = _tool("log_scrub")
    rep = log_scrub.scrub_native_segment(path)
    assert rep["problems"] and rep["problems"][0]["reason"] == \
        "chain mismatch"
    with pytest.raises(OplogCorruptionError, match="chain break"):
        native_oplog.NativePartitionedLog(d, 1)


def test_native_fence_rejects_stale_writer(tmp_path):
    native_oplog = _native_log()
    d = str(tmp_path)
    log = native_oplog.NativePartitionedLog(d, 1)
    msgs = _native_msgs(4)
    w = log.open_for_append(log.fence_epoch)
    w.append(0, msgs[0])
    log.bump_fence()
    with pytest.raises(FencedWriterError):
        w.append(0, msgs[1])
    log.append(0, msgs[2], epoch=log.fence_epoch)
    log.append(0, msgs[3])   # unfenced legacy append still passes
    log.sync()
    assert log.size(0) == 3
    log.close()
    # the fence survives reopen (persisted fence file)
    log2 = native_oplog.NativePartitionedLog(d, 1)
    assert log2.fence_epoch == 1
    assert log2.size(0) == 3
    log2.close()


# ------------------------------------------------------- corruption soak

def test_corrupt_soak_detects_every_injection(tmp_path):
    """The chaos soak's --corrupt profile: seeded rot between restarts,
    every injection detected before apply, audit still exactly-once."""
    soak = _tool("chaos_soak")
    report = soak.run_soak(seed=7, steps=150, n_clients=3, restarts=3,
                           spill_dir=str(tmp_path), corrupt=True)
    assert report["violations"] == 0
    assert report["corruptions_injected"] >= 1
    assert (report["corruptions_detected"]
            == report["corruptions_injected"])
