"""Wire-tier hardening (VERDICT r3 weak #8): bounded outbound queues with
slow-client eviction, and a flaky-socket fault injector exercising
reconnect-with-pending-rebase over a real TCP link."""

import socket
import threading
import time

import pytest

from fluidframework_tpu.framework.fluid_static import NetworkClient
from fluidframework_tpu.server import wire
from fluidframework_tpu.server.ingress import AlfredServer

SCHEMA = {"initialObjects": {"text": "sharedString"}}


# ------------------------------------------------- slow-client eviction


def test_slow_client_is_evicted_not_buffered():
    """A client that never drains its broadcast stream must be EVICTED
    when its bounded outbound queue fills; healthy clients keep going."""
    srv = AlfredServer(port=0, max_outbound=8).start_in_thread()
    try:
        # slow client: subscribes, never reads
        slow = socket.create_connection(("127.0.0.1", srv.port))
        wire.send_frame(slow, {"t": "connect", "doc": "dd"})
        _ = wire.recv_frame(slow)  # connected ack
        # healthy client floods the doc
        good = socket.create_connection(("127.0.0.1", srv.port))
        wire.send_frame(good, {"t": "connect", "doc": "dd"})
        _ = wire.recv_frame(good)
        got = 0
        blob = "x" * 65536    # large frames: kernel buffers fill, the
        for i in range(128):  # stalled reader's queue hits its bound
            wire.send_frame(good, {"t": "op", "client_seq": i + 1,
                                   "contents": {"i": i, "b": blob},
                                   "ref_seq": 0})
            # a healthy client DRAINS its stream as it goes
            while True:
                frame = wire.recv_frame(good)
                if frame.get("t") == "op":
                    got += 1
                    break
        assert got == 128         # the healthy client saw everything
        deadline = time.monotonic() + 10
        while srv.evictions < 1 and time.monotonic() < deadline:
            time.sleep(0.05)
        assert srv.evictions == 1  # the slow one was disconnected
        good.close()
        slow.close()
    finally:
        srv.stop()


# ------------------------------------------------ flaky-socket injection


class _FlakyProxy:
    """TCP proxy that hard-closes the live connection when armed — the
    network failing mid-session, not a graceful disconnect."""

    def __init__(self, upstream_port: int):
        self.upstream_port = upstream_port
        self._srv = socket.socket()
        self._srv.bind(("127.0.0.1", 0))
        self._srv.listen(8)
        self.port = self._srv.getsockname()[1]
        self._cut = threading.Event()
        self._live = []
        threading.Thread(target=self._accept_loop, daemon=True).start()

    def cut(self):
        """Kill every live proxied connection NOW."""
        self._cut.set()
        for s in list(self._live):
            try:
                s.shutdown(socket.SHUT_RDWR)
            except OSError:
                pass
            try:
                s.close()
            except OSError:
                pass
        self._live.clear()
        self._cut.clear()

    def _accept_loop(self):
        while True:
            try:
                client, _ = self._srv.accept()
            except OSError:
                return
            up = socket.create_connection(("127.0.0.1",
                                           self.upstream_port))
            self._live += [client, up]
            threading.Thread(target=self._pump, args=(client, up),
                             daemon=True).start()
            threading.Thread(target=self._pump, args=(up, client),
                             daemon=True).start()

    def _pump(self, src, dst):
        try:
            while True:
                data = src.recv(65536)
                if not data:
                    break
                dst.sendall(data)
        except OSError:
            pass
        for s in (src, dst):
            try:
                s.close()
            except OSError:
                pass

    def close(self):
        self._srv.close()


def test_flaky_socket_reconnect_with_pending_rebase():
    """The link dies AFTER a local edit is submitted but before its ack
    arrives; on reconnect the pending op must rebase/resubmit and the
    document converge — no loss, no duplication (VERDICT r3 weak #8)."""
    srv = AlfredServer(port=0).start_in_thread()
    proxy = _FlakyProxy(srv.port)
    try:
        # creator goes DIRECT (stable); the flaky client rides the proxy
        direct = NetworkClient(port=srv.port, enable_summarizer=False)
        fc0, doc_id = direct.create_container(SCHEMA, doc_id="flaky-doc")
        text0 = fc0.initial_objects["text"]
        text0.insert_text(0, "base;")
        fc0.flush()
        fc0.pump_until(lambda: text0.get_text() == "base;")

        flaky = NetworkClient(port=proxy.port, enable_summarizer=False)
        fc1 = flaky.get_container(doc_id, SCHEMA)
        text1 = fc1.initial_objects["text"]
        fc1.pump_until(lambda: text1.get_text() == "base;")

        # a local edit goes out... and the network dies before the ack
        text1.insert_text(0, "PENDING;")
        fc1.flush()
        proxy.cut()
        time.sleep(0.3)

        # reconnect over a fresh (healthy) proxied connection: the pending
        # op must be resubmitted/rebased by the connection machinery
        fc1.disconnect("link died")
        fc1.connect()
        fc1.pump_until(lambda: "PENDING;" in text1.get_text(), timeout=20)
        fc0.pump_until(lambda: "PENDING;" in text0.get_text(), timeout=20)
        assert text0.get_text() == text1.get_text()
        assert text0.get_text().count("PENDING;") == 1  # no duplication

        # the revived session still serves new edits both ways
        text1.insert_text(0, "after;")
        fc1.flush()
        fc0.pump_until(lambda: text0.get_text().startswith("after;"),
                       timeout=20)
        fc0.dispose()
        fc1.dispose()
    finally:
        proxy.close()
        srv.stop()
