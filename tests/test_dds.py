"""DDS-level semantics tests: map, directory, matrix, string+intervals, and
the small DDSes — multi-replica via the mock sequencer (SURVEY.md §4 pattern).
"""

import pytest

from fluidframework_tpu.models import (
    SharedMap, SharedDirectory, SharedMatrix, SharedString, SharedCounter,
    SharedCell, RegisterCollection, ConsensusQueue, TaskManager,
    default_registry,
)
from fluidframework_tpu.testing.mocks import MockSequencer, create_connected_dds


def pair(cls):
    seqr = MockSequencer()
    a = create_connected_dds(seqr, cls)
    b = create_connected_dds(seqr, cls)
    return seqr, a, b


# ------------------------------------------------------------------ SharedMap

def test_map_set_get_converges():
    seqr, a, b = pair(SharedMap)
    a.set("x", 1)
    assert a.get("x") == 1          # optimistic local
    assert b.get("x") is None
    seqr.process_all_messages()
    assert b.get("x") == 1


def test_map_concurrent_set_last_sequenced_wins():
    seqr, a, b = pair(SharedMap)
    a.set("k", "from-a")
    b.set("k", "from-b")            # submitted second -> sequenced later
    seqr.process_all_messages()
    assert a.get("k") == b.get("k") == "from-b"


def test_map_pending_local_shadows_remote():
    seqr, a, b = pair(SharedMap)
    b.set("k", "remote")
    a.set("k", "local")             # a's op sequenced after b's
    seqr.process_some(1)            # only b's op arrives at a
    assert a.get("k") == "local"    # a never flickers to "remote"
    seqr.process_all_messages()
    assert a.get("k") == b.get("k") == "local"


def test_map_clear_vs_concurrent_set():
    seqr, a, b = pair(SharedMap)
    a.set("x", 1)
    seqr.process_all_messages()
    a.clear()
    b.set("y", 2)                   # sequenced after the clear -> survives
    seqr.process_all_messages()
    assert dict(a.items()) == dict(b.items()) == {"y": 2}


def test_map_delete_and_summary():
    seqr, a, b = pair(SharedMap)
    a.set("x", 1)
    a.set("y", [1, 2])
    a.delete("x")
    seqr.process_all_messages()
    summary = b.summarize()
    c = SharedMap("dds", 99)
    c.load_core(summary)
    assert c.get("y") == [1, 2] and not c.has("x")


# ------------------------------------------------------------ SharedDirectory

def test_directory_subdirs_and_keys():
    seqr, a, b = pair(SharedDirectory)
    a.create_sub_directory("/users/alice")
    a.set("role", "admin", path="/users/alice")
    a.set("top", 1)
    seqr.process_all_messages()
    assert b.get("role", path="/users/alice") == "admin"
    assert b.get("top") == 1
    assert "/users/alice/" in b.subdirectories()


# --------------------------------------------------------------- SharedMatrix

def test_matrix_basic_cells():
    seqr, a, b = pair(SharedMatrix)
    a.insert_rows(0, 2)
    a.insert_cols(0, 3)
    seqr.process_all_messages()
    a.set_cell(0, 1, "x")
    b.set_cell(1, 2, "y")
    seqr.process_all_messages()
    assert a.to_lists() == b.to_lists() == [[None, "x", None],
                                            [None, None, "y"]]


def test_matrix_concurrent_row_insert_converges():
    seqr, a, b = pair(SharedMatrix)
    a.insert_rows(0, 1)
    a.insert_cols(0, 1)
    seqr.process_all_messages()
    a.set_cell(0, 0, "base")
    seqr.process_all_messages()
    a.insert_rows(0, 1)            # both insert at row 0 concurrently
    b.insert_rows(0, 1)
    seqr.process_all_messages()
    assert a.row_count == b.row_count == 3
    assert a.to_lists() == b.to_lists()
    # the original cell still reads "base" at its (moved) position
    assert "base" in [c for row in a.to_lists() for c in row]


def test_matrix_cell_on_concurrently_moved_row():
    seqr, a, b = pair(SharedMatrix)
    a.insert_rows(0, 3)
    a.insert_cols(0, 1)
    seqr.process_all_messages()
    # b writes to row 2 while a inserts a row above it: the write must land
    # on the same logical row after the insert shifts positions
    b.set_cell(2, 0, "target")
    a.insert_rows(0, 1)
    seqr.process_all_messages()
    assert a.to_lists() == b.to_lists()
    assert a.get_cell(3, 0) == "target"


def test_matrix_remove_rows_and_lww():
    seqr, a, b = pair(SharedMatrix)
    a.insert_rows(0, 2)
    a.insert_cols(0, 2)
    seqr.process_all_messages()
    a.set_cell(0, 0, 1)
    b.set_cell(0, 0, 2)            # sequenced later -> wins
    seqr.process_all_messages()
    assert a.get_cell(0, 0) == b.get_cell(0, 0) == 2
    a.remove_rows(0, 1)
    seqr.process_all_messages()
    assert a.row_count == b.row_count == 1
    assert a.to_lists() == b.to_lists()


def test_interval_partial_changes_merge_per_field():
    # regression: an in-flight start-only local change must NOT swallow an
    # earlier-sequenced remote end-only change (per-field shadowing)
    seqr, a, b = pair(SharedString)
    a.insert_text(0, "abcdefgh")
    seqr.process_all_messages()
    iid = a.get_interval_collection("c").add(1, 3)
    seqr.process_all_messages()
    b.get_interval_collection("c").change(iid, end=6)    # sequenced first
    a.get_interval_collection("c").change(iid, start=2)  # in flight at a
    seqr.process_all_messages()
    ca, cb = a.get_interval_collection("c"), b.get_interval_collection("c")
    assert ca.endpoints(iid) == cb.endpoints(iid) == (2, 6)


def test_matrix_fww_switch_not_optimistic():
    # regression: the policy flip must take effect at sequencing time, not at
    # submit — otherwise the originator judges pre-switch ops under FWW
    seqr, a, b = pair(SharedMatrix)
    a.insert_rows(0, 1)
    a.insert_cols(0, 1)
    seqr.process_all_messages()
    a.set_cell(0, 0, "W1")
    seqr.process_all_messages()
    b.set_cell(0, 0, "W2")           # sequenced before the switch: LWW, wins
    a.switch_set_cell_policy()
    seqr.process_all_messages()
    assert a.get_cell(0, 0) == b.get_cell(0, 0) == "W2"
    assert a.fww and b.fww


def test_matrix_fww_policy():
    seqr, a, b = pair(SharedMatrix)
    a.insert_rows(0, 1)
    a.insert_cols(0, 1)
    a.switch_set_cell_policy()
    seqr.process_all_messages()
    a.set_cell(0, 0, "first")      # sequenced first -> wins under FWW
    b.set_cell(0, 0, "second")
    seqr.process_all_messages()
    assert a.get_cell(0, 0) == b.get_cell(0, 0) == "first"


# ------------------------------------------------- SharedString + intervals

def test_shared_string_channel_and_intervals():
    seqr, a, b = pair(SharedString)
    a.insert_text(0, "hello world")
    seqr.process_all_messages()
    ivs_a = a.get_interval_collection("comments")
    iid = ivs_a.add(6, 10, {"author": "a"})     # over "world"
    seqr.process_all_messages()
    ivs_b = b.get_interval_collection("comments")
    assert ivs_b.endpoints(iid) == (6, 10)
    # remote edit before the interval shifts it on every replica
    b.insert_text(0, ">> ")
    seqr.process_all_messages()
    assert ivs_a.endpoints(iid) == ivs_b.endpoints(iid) == (9, 13)
    assert ivs_a.digest() == ivs_b.digest()
    # overlapping query
    assert [iv.interval_id for iv in ivs_a.find_overlapping(10, 11)] == [iid]


def test_interval_change_and_delete_converge():
    seqr, a, b = pair(SharedString)
    a.insert_text(0, "abcdefgh")
    seqr.process_all_messages()
    iv1 = a.get_interval_collection("c").add(1, 3)
    iv2 = a.get_interval_collection("c").add(4, 6)
    seqr.process_all_messages()
    a.get_interval_collection("c").change(iv1, start=0, end=2)
    b.get_interval_collection("c").delete(iv2)
    seqr.process_all_messages()
    ca, cb = a.get_interval_collection("c"), b.get_interval_collection("c")
    assert ca.digest() == cb.digest()
    assert ca.endpoints(iv1) == (0, 2) and ca.get(iv2) is None


# ---------------------------------------------------------------- small DDSes

def test_counter_commutative_increments():
    seqr, a, b = pair(SharedCounter)
    a.increment(5)
    b.increment(-2)
    assert a.value == 5 and b.value == -2   # optimistic
    seqr.process_all_messages()
    assert a.value == b.value == 3


def test_cell_lww_with_shadow():
    seqr, a, b = pair(SharedCell)
    b.set("old")
    a.set("new")                    # sequenced later
    seqr.process_all_messages()
    assert a.get() == b.get() == "new"
    a.delete()
    seqr.process_all_messages()
    assert a.empty() and b.empty()


def test_register_collection_concurrent_versions():
    seqr, a, b = pair(RegisterCollection)
    a.write("k", "va")
    b.write("k", "vb")              # concurrent: neither saw the other
    seqr.process_all_messages()
    # both versions survive; atomic read = earliest sequenced
    assert a.read("k") == b.read("k") == "va"
    assert a.read_versions("k") == b.read_versions("k") == ["va", "vb"]
    a.write("k", "final")           # supersedes both (a has seen them)
    seqr.process_all_messages()
    assert b.read_versions("k") == ["final"]


def test_consensus_queue_single_winner():
    seqr, a, b = pair(ConsensusQueue)
    a.add("job1")
    seqr.process_all_messages()
    ra = a.acquire()
    rb = b.acquire()                # sequenced second: queue already empty
    seqr.process_all_messages()
    assert a.result(ra) == "job1" and b.result(rb) is None
    # release puts it back for the other client
    a.release(ra)
    seqr.process_all_messages()
    rb2 = b.acquire()
    seqr.process_all_messages()
    assert b.result(rb2) == "job1"
    b.complete(rb2)
    seqr.process_all_messages()
    assert not a.acquired and not b.acquired


def test_task_manager_lock_queue():
    seqr, a, b = pair(TaskManager)
    a.volunteer("summarizer")
    b.volunteer("summarizer")
    seqr.process_all_messages()
    assert a.assigned_to("summarizer") == b.assigned_to("summarizer") == a.client_id
    assert a.have_task("summarizer") and not b.have_task("summarizer")
    a.abandon("summarizer")
    seqr.process_all_messages()
    assert b.have_task("summarizer")


# ------------------------------------------------------------------- registry

def test_channel_registry_creates_all_types():
    reg = default_registry()
    assert set(reg.types()) >= {"map", "directory", "sharedString", "matrix",
                                "counter", "cell", "registerCollection",
                                "consensusQueue", "taskManager"}
    obj = reg.get("map").create("m1", 7)
    assert isinstance(obj, SharedMap) and obj.id == "m1"
