"""Interval-holding docs on the columnar serving fast path.

Pins the serving fast-path contract for rich-text interval documents:

- **Endpoint parity under seeded fuzz**: random annotate/insert/remove
  waves go through ``ingest_planes`` (device-side batched apply with
  slide-at-crossing) and every interval's endpoints must match the
  pure-Python ``IntervalCollection`` oracle replayed message-by-message
  (``apply_msg``, so the oracle zambonis at min-seq crossings exactly
  like the reference client).
- **Crash-restart mid-window** (chaos faultpoints): a kill between
  sequencing and the batched apply must neither lose an anchor nor
  mis-slide it — the recovered engine's endpoints still match the oracle
  replay of the durable log, and keep matching for traffic sequenced
  AFTER the restart.
- **Routing regressions**: interval docs ride the columnar apply (the
  old per-op fallback kept no segment accounting), every insert on an
  interval doc mints its OWN payload handle (dedup'd table handles make
  (handle, offset) anchor keys ambiguous), and interval-free batches
  keep the dedup'd-table fast wire.
"""

import random

import numpy as np
import pytest

from fluidframework_tpu.models.interval_collection import IntervalCollection
from fluidframework_tpu.models.merge_tree import LOCAL_VIEW
from fluidframework_tpu.models.shared_string import SharedString
from fluidframework_tpu.ops.schema import OpKind
from fluidframework_tpu.server.serving import StringServingEngine
from fluidframework_tpu.testing import chaos
from fluidframework_tpu.utils.faultpoints import (
    SITE_DELI_MID_WINDOW, SITE_FLUSH_MID_BATCH, CrashInjected, armed,
)

BASE_TEXT = "the quick brown fox jumps over the dazed dog"
IV_TEXTS = ["XY"]
IV_PROPS = [{"bold": True}, {"bold": False}]


def _iv_engine(n_docs, seed, n_spans=3):
    """Engine with BASE_TEXT in every doc and ``n_spans`` anchored
    intervals per doc (bulk add). Returns (engine, docs, spans) where
    spans[di] is [(start, end, interval_id), ...]."""
    rng = random.Random(seed)
    eng = StringServingEngine(n_docs=n_docs, capacity=128,
                              batch_window=10 ** 9, compact_every=10 ** 9,
                              sequencer="native")
    docs = [f"iv-{i}" for i in range(n_docs)]
    for d in docs:
        eng.connect(d, 1)
        _, nack = eng.submit(d, 1, 1, 0, {"mt": "insert", "kind": 0,
                                          "pos": 0, "text": BASE_TEXT,
                                          "clientSeq": 1})
        assert nack is None
    eng.flush()
    req = {}
    for d in docs:
        spans = []
        for _k in range(n_spans):
            s = rng.randrange(len(BASE_TEXT) - 8)
            spans.append((s, s + 2 + rng.randrange(5), None))
        req[eng.doc_row(d)] = spans
    ids = eng.store.add_intervals_bulk(req)
    spans = [[(s, e, sid) for (s, e, _), sid in
              zip(req[eng.doc_row(d)], ids[eng.doc_row(d)])]
             for d in docs]
    return eng, docs, spans


def _wave(rng, n_docs, ow, w, lengths):
    """One mixed annotate/insert/remove wave of planes; mutates
    ``lengths`` to track per-doc text length. The ref plane is pinned at
    the wave's first seq so the min-seq floor crosses the PREVIOUS
    wave's tombstones mid-window (slide-at-crossing on device)."""
    kind = np.zeros((n_docs, ow), np.int32)
    a0 = np.zeros((n_docs, ow), np.int32)
    a1 = np.zeros((n_docs, ow), np.int32)
    tix = np.zeros((n_docs, ow), np.int32)
    for di in range(n_docs):
        ln = lengths[di]
        for c in range(ow):
            roll = rng.random()
            if roll < 0.5 and ln >= 6:
                s = rng.randrange(ln - 4)
                kind[di, c] = OpKind.STR_ANNOTATE
                a0[di, c], a1[di, c] = s, s + 2
                tix[di, c] = rng.randrange(2)
            elif roll < 0.8 or ln < 16:
                kind[di, c] = OpKind.STR_INSERT
                a0[di, c], a1[di, c] = rng.randrange(ln + 1), 2
                ln += 2
            else:
                s = rng.randrange(ln - 3)
                kind[di, c] = OpKind.STR_REMOVE
                a0[di, c], a1[di, c] = s, s + 2
                ln -= 2
        lengths[di] = ln
    cseq = np.broadcast_to(
        np.arange(2 + w * ow, 2 + (w + 1) * ow, dtype=np.int32),
        (n_docs, ow))
    ref = np.full((n_docs, ow), 2 + w * ow, np.int32)
    return kind, a0, a1, tix, cseq, ref


def _oracle_endpoints(engine, doc, spans):
    """Replay ``doc``'s durable log through the pure-Python oracle
    (``apply_msg`` — zamboni at crossings), anchoring ``spans`` at the
    same point in history they were added on the engine (right after the
    base insert). Returns (text, [endpoints...])."""
    oracle = SharedString(doc, 999)
    msgs = engine._doc_log_messages(doc)
    for m in (m for m in msgs if m.client_seq == 1):
        oracle.apply_msg(m)
    coll = IntervalCollection("c", oracle.tree)
    for k, (s, e, _sid) in enumerate(spans):
        coll.apply_add(f"o{k}", s, e, {}, LOCAL_VIEW, 999)
    for m in (m for m in msgs if m.client_seq > 1):
        oracle.apply_msg(m)
    return (oracle.get_text(),
            [coll.endpoints(coll.get(f"o{k}")) for k in range(len(spans))])


@pytest.mark.parametrize("seed", [5, 23])
def test_columnar_interval_parity_fuzz(seed):
    """Seeded fuzz: mixed waves through the columnar ingest; every doc's
    text AND every interval's endpoints match the oracle replay."""
    n_docs, ow, waves = 8, 8, 5
    rng = random.Random(seed)
    eng, docs, spans = _iv_engine(n_docs, seed)
    rows = np.array([eng.doc_row(d) for d in docs], np.int32)
    client = np.ones((n_docs, ow), np.int32)
    lengths = [len(BASE_TEXT)] * n_docs
    seg_waves = []
    for w in range(waves):
        kind, a0, a1, tix, cseq, ref = _wave(rng, n_docs, ow, w, lengths)
        res = eng.ingest_planes(rows, client, cseq, ref, kind, a0, a1,
                                texts=IV_TEXTS, tidx=tix, props=IV_PROPS)
        assert res["nacked"] == 0
        seg_waves.append(eng.store.last_apply_stats["segments"])
    # the min-seq floor really crossed tombstones mid-window: waves past
    # the first split into >= 2 apply segments around the slide boundary
    assert all(s >= 2 for s in seg_waves[1:]), seg_waves
    for di, d in enumerate(docs):
        want_text, want_eps = _oracle_endpoints(eng, d, spans[di])
        assert eng.read_text(d) == want_text, d
        for k, (s, e, sid) in enumerate(spans[di]):
            got = eng.store.interval_endpoints(eng.doc_row(d), sid)
            assert got == want_eps[k], (d, k, got, want_eps[k])


def test_interval_docs_take_columnar_path():
    """Regression pin: interval docs stay ON the batched columnar apply
    (segment accounting exists only there), and every insert mints its
    own payload handle — the wire is the resolved a2 plane, with one
    payload entry per insert op."""
    n_docs, ow = 8, 8
    rng = random.Random(7)
    eng, docs, _spans = _iv_engine(n_docs, 7)
    rows = np.array([eng.doc_row(d) for d in docs], np.int32)
    client = np.ones((n_docs, ow), np.int32)
    lengths = [len(BASE_TEXT)] * n_docs
    n_payloads = len(eng.store._payloads)
    kind, a0, a1, tix, cseq, ref = _wave(rng, n_docs, ow, 0, lengths)
    res = eng.ingest_planes(rows, client, cseq, ref, kind, a0, a1,
                            texts=IV_TEXTS, tidx=tix, props=IV_PROPS)
    assert res["nacked"] == 0
    # columnar apply ran (the retired per-op fallback kept no stats)
    assert eng.store.last_apply_stats["segments"] >= 1
    # per-op handle mint: resolved plane wire + one payload per insert
    assert eng.store.last_rich_wire == "plane"
    n_inserts = int((kind == OpKind.STR_INSERT).sum())
    assert n_inserts > 0
    assert len(eng.store._payloads) - n_payloads == n_inserts


def test_interval_free_batches_keep_table_wire():
    """The per-op handle mint is interval-gated: the SAME batch on an
    engine with no intervals still ships the dedup'd-table fast wire."""
    n_docs, ow = 8, 8
    rng = random.Random(7)
    eng = StringServingEngine(n_docs=n_docs, capacity=128,
                              batch_window=10 ** 9, compact_every=10 ** 9,
                              sequencer="native")
    docs = [f"nf-{i}" for i in range(n_docs)]
    for d in docs:
        eng.connect(d, 1)
        eng.submit(d, 1, 1, 0, {"mt": "insert", "kind": 0, "pos": 0,
                                "text": BASE_TEXT, "clientSeq": 1})
    eng.flush()
    rows = np.array([eng.doc_row(d) for d in docs], np.int32)
    client = np.ones((n_docs, ow), np.int32)
    lengths = [len(BASE_TEXT)] * n_docs
    kind, a0, a1, tix, cseq, ref = _wave(rng, n_docs, ow, 0, lengths)
    res = eng.ingest_planes(rows, client, cseq, ref, kind, a0, a1,
                            texts=IV_TEXTS, tidx=tix, props=IV_PROPS)
    assert res["nacked"] == 0
    assert eng.store.last_rich_wire in ("tab8", "tab16")


@pytest.mark.chaos
@pytest.mark.parametrize("site", [SITE_DELI_MID_WINDOW,
                                  SITE_FLUSH_MID_BATCH])
def test_crash_restart_mid_window_keeps_anchors(site):
    """Kill the engine mid-window while interval docs take traffic;
    recovery (summary + log-tail replay) must neither lose an anchor nor
    mis-slide it, and anchors must KEEP sliding correctly for traffic
    sequenced after the restart."""
    rng = random.Random(911 + len(site))
    docs = ["d0", "d1", "d2"]
    clients = {d: i + 1 for i, d in enumerate(docs)}
    victim = chaos.make_engine("string")
    for d in docs:
        victim.connect(d, clients[d])
    cseq = {d: 0 for d in docs}
    last_seq = {d: 0 for d in docs}

    def push(engine, d, contents):
        cseq[d] += 1
        if contents.get("mt") == "insert":
            # the oracle mints insert handles from op["clientSeq"]
            contents["clientSeq"] = cseq[d]
        msg, nack = engine.submit(d, clients[d], cseq[d], last_seq[d],
                                  contents)
        assert nack is None, nack
        last_seq[d] = msg.seq
        return msg

    for d in docs:
        push(victim, d, {"mt": "insert", "kind": 0, "pos": 0,
                         "text": BASE_TEXT})
    victim.flush()
    spans = {}
    for d in docs:
        row = victim.doc_row(d)
        ss = []
        for _k in range(2):
            s = rng.randrange(len(BASE_TEXT) - 8)
            e = s + 2 + rng.randrange(5)
            ss.append((s, e, victim.store.add_interval(row, s, e)))
        spans[d] = ss
    summary = victim.summarize()  # recovery anchor holds the intervals

    gen = chaos.OpGen(rng, "string", docs)
    gen._len = {d: len(BASE_TEXT) for d in docs}
    plan = chaos.FaultPlan(crash={site: rng.randint(2, 5)})
    with armed(plan):
        try:
            for i in range(24):
                d = docs[i % len(docs)]
                contents = gen.op(d)
                cs_before = cseq[d]
                push(victim, d, contents)
        except CrashInjected:
            cseq[d] = cs_before + 1  # the crashed op consumed its seq
    assert plan.fired == [site], plan.hits

    recovered = StringServingEngine.load(summary, victim.log)
    for d in docs:
        want_text, want_eps = _oracle_endpoints(recovered, d, spans[d])
        assert recovered.read_text(d) == want_text, d
        row = recovered.doc_row(d)
        for k, (s, e, sid) in enumerate(spans[d]):
            got = recovered.store.interval_endpoints(row, sid)
            assert got == want_eps[k], (site, d, k, got, want_eps[k])

    # life goes on: post-restart traffic still slides anchors in step
    # with the oracle (resync the generator — a crashed op may have been
    # sequenced-but-lost, so its length delta never landed)
    for d in docs:
        cseq[d] = max((m.client_seq
                       for m in recovered._doc_log_messages(d)), default=0)
        last_seq[d] = recovered.deli.doc_seq(d)
        gen._len[d] = len(recovered.read_text(d))
    for i in range(12):
        d = docs[i % len(docs)]
        push(recovered, d, gen.op(d))
    recovered.flush()
    for d in docs:
        want_text, want_eps = _oracle_endpoints(recovered, d, spans[d])
        assert recovered.read_text(d) == want_text, d
        row = recovered.doc_row(d)
        for k, (s, e, sid) in enumerate(spans[d]):
            got = recovered.store.interval_endpoints(row, sid)
            assert got == want_eps[k], (site, d, k, got, want_eps[k])
