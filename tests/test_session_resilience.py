"""Session resilience plane (ISSUE 9): reconnect/resubmit with durable
dedup, replica failover with oplog catch-up, torn-frame recovery, and
the seeded chaos soak.

The contract under test, end to end: **an acked op is durable and
applied exactly once — across socket kills, torn frames, injected
sequencer crashes, and whole-service crash-restarts — and an un-acked op
may be dropped but never corrupts.**
"""

import importlib.util
import os
import random
import threading
import time

import pytest

from fluidframework_tpu.core.protocol import MessageType
from fluidframework_tpu.drivers.resilient import (
    ResilientColumnarClient, ResilientConnection,
)
from fluidframework_tpu.server import native_deli
from fluidframework_tpu.server.ingress import AlfredServer
from fluidframework_tpu.server.tinylicious import LocalService
from fluidframework_tpu.utils.backoff import Backoff, retry
from fluidframework_tpu.utils.faultpoints import (
    SITE_DELI_MID_WINDOW, CrashInjected, ProbabilisticPlan, armed,
)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tool(name):
    """Load a tools/*.py script as a module (tools/ is not a package)."""
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------- backoff util


class TestBackoff:
    def test_decorrelated_jitter_bounded_and_seeded(self):
        a = Backoff(base=0.01, cap=0.5, rng=random.Random(3))
        b = Backoff(base=0.01, cap=0.5, rng=random.Random(3))
        da = [a.next_delay() for _ in range(20)]
        db = [b.next_delay() for _ in range(20)]
        assert da == db                      # same seed, same schedule
        assert all(0.01 <= d <= 0.5 for d in da)
        a.reset()
        assert a.next_delay() <= 0.03        # reset forgets the growth

    def test_retry_retries_then_succeeds(self):
        calls = []
        slept = []

        def flaky():
            calls.append(1)
            if len(calls) < 3:
                raise OSError("transient")
            return "ok"

        bo = Backoff(base=0.01, cap=0.1, rng=random.Random(0))
        assert retry(flaky, attempts=5, backoff=bo,
                     sleep=slept.append) == "ok"
        assert len(calls) == 3 and len(slept) == 2

    def test_retry_exhausts(self):
        def dead():
            raise OSError("forever")

        with pytest.raises(OSError):
            retry(dead, attempts=3,
                  backoff=Backoff(base=0.001, rng=random.Random(0)),
                  sleep=lambda _s: None)


# ------------------------------------------------- probabilistic faultpoints


class TestProbabilisticFaultpoints:
    def _drive(self, seed, hits=200, p=0.05):
        plan = ProbabilisticPlan(rng=random.Random(seed))
        plan.arm("t.site", p)
        trace = []
        for i in range(hits):
            try:
                plan.hit("t.site")
                trace.append(0)
            except CrashInjected:
                trace.append(1)
        return plan, trace

    def test_seeded_fire_schedule_replays(self):
        p1, t1 = self._drive(11)
        p2, t2 = self._drive(11)
        assert t1 == t2 and sum(t1) == p1.fires["t.site"] > 0

    def test_stall_arm_counts_without_killing(self):
        plan = ProbabilisticPlan(rng=random.Random(5))
        plan.arm_stall("t.stall", p=1.0, seconds=0.0)
        for _ in range(7):
            plan.hit("t.stall")              # never raises
        assert plan.stalls["t.stall"] == 7 and not plan.fires

    def test_armed_context_uninstalls_on_crash(self):
        from fluidframework_tpu.utils import faultpoints as fp
        plan = ProbabilisticPlan(rng=random.Random(1)).arm("t.die", 1.0)
        with pytest.raises(CrashInjected):
            with armed(plan):
                fp.fault_point("t.die")
        assert fp.active_plan() is None


# -------------------------------------------------------- JSON front door


def _drain_all(conns, timeout=20.0):
    for c in conns:
        assert c.wait_idle(timeout=timeout), (
            c.doc_id, c.pending_count, c.reconnects)
        assert not c.nacks, c.nacks


def _durable_ops(svc, doc):
    return [m for m in svc.get_deltas(doc, 0)
            if m.type == MessageType.OP]


class TestJsonReconnect:
    def test_socket_kill_resubmits_exactly_once(self):
        svc = LocalService(n_partitions=2)
        server = AlfredServer(svc).start_in_thread()
        try:
            conn = ResilientConnection("127.0.0.1", server.port, "d0",
                                       rng=random.Random(0))
            uids = []
            for i in range(10):
                uids.append(conn.submit({"mt": "insert", "kind": 0,
                                         "pos": 0, "text": f"x{i}",
                                         "u": i}))
                if i == 4:
                    conn.kill_socket()
            _drain_all([conn])
            assert conn.reconnects >= 1
            durable = _durable_ops(svc, "d0")
            markers = [m.contents["u"] for m in durable]
            assert markers == list(range(10))        # order, exactly once
            assert {conn.op_acks[u] for u in uids} == \
                {m.seq for m in durable}
            conn.close()
        finally:
            server.stop()
            svc.close()

    def test_crash_restart_rides_through(self, tmp_path):
        """The whole service dies mid-session and recovers from its
        spill on the same port; the client resyncs against the new
        epoch and every op lands exactly once."""
        spill = str(tmp_path)
        svc = LocalService(n_partitions=2, spill_dir=spill)
        server = AlfredServer(svc).start_in_thread()
        port = server.port
        conn = ResilientConnection("127.0.0.1", port, "d0",
                                   rng=random.Random(1), attempts=12)
        try:
            for i in range(5):
                conn.submit({"mt": "insert", "kind": 0, "pos": 0,
                             "text": "a", "u": i})
            assert conn.wait_idle(timeout=10)
            epoch0 = conn.epoch
            server.stop()
            svc.close()
            # in-flight ops against a dead server: tracked, not lost
            for i in range(5, 8):
                conn.submit({"mt": "insert", "kind": 0, "pos": 0,
                             "text": "b", "u": i})
            svc = LocalService.recover(spill, n_partitions=2)
            server = AlfredServer(svc, port=port).start_in_thread()
            _drain_all([conn])
            assert conn.epoch > epoch0
            markers = [m.contents["u"] for m in _durable_ops(svc, "d0")]
            assert markers == list(range(8))
            conn.close()
        finally:
            server.stop()
            svc.close()

    def test_recover_dup_acks_resubmit_with_original_seq(self, tmp_path):
        """Durable dedup across restart, at the service layer: a resubmit
        of an already-durable clientSeq is acked idempotently with the
        ORIGINAL seq and never re-applied."""
        spill = str(tmp_path)
        svc = LocalService(n_partitions=2, spill_dir=spill)
        conn = svc.connect("docA")
        cid = conn.client_id
        for i in range(1, 4):
            conn.submit_raw(i, {"u": i}, MessageType.OP, 0)
        orig = {m.client_seq: m.seq for m in _durable_ops(svc, "docA")}
        svc.close()

        svc2 = LocalService.recover(spill, n_partitions=2)
        try:
            assert svc2.last_client_seq("docA", cid) == 3
            conn2 = svc2.reconnect("docA", cid)
            for i in range(1, 4):
                conn2.submit_raw(i, {"u": i}, MessageType.OP, 0)
            assert [(d.client_seq, d.seq) for d in conn2.dup_acks] == \
                sorted(orig.items())
            assert len(_durable_ops(svc2, "docA")) == 3   # no re-apply
            # the seat still sequences fresh ops
            conn2.submit_raw(4, {"u": 4}, MessageType.OP, 0)
            assert len(_durable_ops(svc2, "docA")) == 4
        finally:
            svc2.close()


# ------------------------------------------------------ columnar front door

needs_native = pytest.mark.skipif(not native_deli.available(),
                                  reason="native sequencer unavailable")


def _mk_columnar(n_docs=8, window_min_rows=1, window_ms=2.0):
    from fluidframework_tpu.server.columnar_ingress import ColumnarAlfred
    from fluidframework_tpu.server.serving import StringServingEngine
    eng = StringServingEngine(n_docs=n_docs, capacity=256,
                              batch_window=10 ** 9, sequencer="native")
    srv = ColumnarAlfred(eng, window_min_rows=window_min_rows,
                         window_ms=window_ms).start_in_thread()
    return eng, srv


@needs_native
class TestColumnarReconnect:
    def test_kill_rejoin_keeps_identity_and_dedups(self):
        eng, srv = _mk_columnar()
        try:
            cl = ResilientColumnarClient("127.0.0.1", srv.port, ["d0"],
                                         rng=random.Random(2))
            cid = cl.client_id
            for i in range(6):
                cl.submit("d0", kind=0, a0=0, payload=f"w{i}.")
                if i == 2:
                    cl.kill_socket()
            assert cl.wait_idle(timeout=10), cl.pending_count
            assert cl.client_id == cid and cl.reconnects >= 1
            assert sorted(cl.acks["d0"]) == list(range(1, 7))
            text = eng.read_text("d0")
            for i in range(6):
                assert text.count(f"w{i}.") == 1, (i, text)
            cl.close()
        finally:
            srv.stop()

    def test_rejoin_reports_dedup_cursor(self):
        """`joined` carries last-accepted clientSeq per doc so a resumed
        client can renumber/skip without probing."""
        eng, srv = _mk_columnar()
        try:
            cl = ResilientColumnarClient("127.0.0.1", srv.port,
                                         ["a", "b"],
                                         rng=random.Random(3))
            cl.submit("a", kind=0, a0=0, payload="x.")
            cl.submit("a", kind=0, a0=0, payload="y.")
            cl.submit("b", kind=0, a0=0, payload="z.")
            assert cl.wait_idle(timeout=10)
            cl.kill_socket()
            deadline = time.monotonic() + 10
            while cl.reconnects < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert cl.lcs.get("a") == 2 and cl.lcs.get("b") == 1, cl.lcs
            cl.close()
        finally:
            srv.stop()


@needs_native
class TestTornFrames:
    """A connection dying mid-frame must never sequence a partial
    window, and a resilient client recovers the op on reconnect."""

    def _torn(self, cut):
        import socket as socklib

        from fluidframework_tpu.server import columnar_ingress as colwire
        import numpy as np
        eng, srv = _mk_columnar()
        try:
            cl = ResilientColumnarClient("127.0.0.1", srv.port, ["d0"],
                                         rng=random.Random(4))
            cl.submit("d0", kind=0, a0=0, payload="pre.")
            assert cl.wait_idle(timeout=10)
            # a second, torn submission: register it pending as submit()
            # would, but write only a prefix of its frame before the
            # socket dies (the frame layout: 5B header | payload | crc)
            ops = np.zeros(1, dtype=colwire._OP_DTYPE)
            ops["row"] = cl.rows["d0"]
            ops["cseq"] = 2
            frame = colwire.encode_op_batch(["torn."], ops)
            with cl._lock:
                cl._cseq["d0"] = 2
                cl._pending["d0"][2] = (0, 0, 0, "torn.", 0)
                sock = cl._sock
            sock.sendall(frame[:cut])
            cl.kill_socket()
            # reconnect resubmits the torn op; exactly one copy lands
            assert cl.wait_idle(timeout=10), cl.pending_count
            text = eng.read_text("d0")
            assert text.count("torn.") == 1, text
            assert text.count("pre.") == 1, text
            # the server survived the tear: a fresh op still flows
            cl.submit("d0", kind=0, a0=0, payload="post.")
            assert cl.wait_idle(timeout=10)
            assert eng.read_text("d0").count("post.") == 1
            cl.close()
        finally:
            srv.stop()

    def test_killed_mid_length_prefix(self):
        self._torn(cut=3)       # inside the 5-byte type+length header

    def test_killed_mid_payload(self):
        self._torn(cut=12)      # header complete, payload truncated


# ------------------------------------------------------------ failover


@needs_native
class TestFailover:
    def test_follower_promotion_digest_parity(self):
        from fluidframework_tpu.parallel.replicated import OplogFollower
        from fluidframework_tpu.server.oplog import PartitionedLog
        from fluidframework_tpu.testing.chaos import (
            OpGen, digest, make_engine,
        )
        rng = random.Random(6)
        docs = [f"doc{i}" for i in range(3)]
        leader = make_engine("string", log=PartitionedLog(2))
        for d in docs:
            leader.connect(d, 1)
        follower = OplogFollower(leader, family="string")
        gen = OpGen(rng, "string", docs)
        cseq = {d: 0 for d in docs}
        for i in range(60):
            d = rng.choice(docs)
            cseq[d] += 1
            leader.submit(d, 1, cseq[d], 0, gen.op(d))
            if i == 30:
                follower.catch_up()     # trailing mid-stream is fine
        leader.flush()
        expected = digest(leader, "string", docs)
        # the leader "dies"; the durable log is all that remains
        promoted = follower.promote()
        assert follower.promoted
        assert digest(promoted, "string", docs) == expected
        # the new leader sequences fresh traffic on the same seats
        d = docs[0]
        cseq[d] += 1
        msg, nack = promoted.submit(d, 1, cseq[d], 0,
                                    {"mt": "insert", "kind": 0,
                                     "pos": 0, "text": "after."})
        assert nack is None and msg.seq > 0
        assert promoted.read_text(d).count("after.") == 1
        # dedup continuity: resubmitting a pre-failover cseq dup-acks
        msg2, nack2 = promoted.submit(d, 1, 1, 0, {"mt": "insert",
                                                   "kind": 0, "pos": 0,
                                                   "text": "dup."})
        assert nack2 is not None and nack2.seq > 0
        assert "dup." not in promoted.read_text(d)


# ------------------------------------------------------------- chaos soak


@pytest.mark.soak
class TestChaosSoak:
    def test_quick_seeded_soak_holds_invariants(self):
        chaos_soak = _tool("chaos_soak")
        report = chaos_soak.run_soak(seed=7, steps=150, n_clients=3,
                                     restarts=3, kill_p=0.02,
                                     crash_p=0.01)
        assert report["violations"] == 0
        assert report["ops_acked"] == report["ops_submitted"] == 150
        assert report["restarts"] == 3       # the acceptance's >=3 bar
        assert report["reconnects"] >= 3     # every restart forces some
        assert report["final_epoch"] >= 3

    def test_soak_audit_catches_seeded_corruption(self, tmp_path):
        """The auditor itself is load-bearing: feed it a stream with a
        doctored ack map and it must raise, not pass vacuously."""
        chaos_soak = _tool("chaos_soak")
        svc = LocalService(n_partitions=1, spill_dir=str(tmp_path))
        server = AlfredServer(svc).start_in_thread()
        try:
            conn = ResilientConnection("127.0.0.1", server.port, "d0",
                                       rng=random.Random(8))
            uids = [conn.submit({"u": f"d0:{i}"}) for i in range(3)]
            assert conn.wait_idle(timeout=10)
            good = {"d0": [f"d0:{i}" for i in range(3)]}
            uid_marker = {"d0": {u: f"d0:{i}"
                                 for i, u in enumerate(uids)}}
            chaos_soak._audit(svc, [conn], good, uid_marker)   # clean
            conn.op_acks[uids[1]] += 7       # corrupt one acked seq
            with pytest.raises(chaos_soak.SoakViolation,
                               match="ack_seq_mismatch"):
                chaos_soak._audit(svc, [conn], good, uid_marker)
            conn.close()
        finally:
            server.stop()
            svc.close()
