"""Framework layer (L5): DataObject lifecycle, fluid-static simple API,
service client, signals + presence. Reference behaviors per SURVEY.md §1 L5."""

from fluidframework_tpu.core.protocol import SignalMessage
from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.framework import (
    ContainerRuntimeFactoryWithDefaultDataObject, DataObject,
    DataObjectFactory, FluidContainer, LocalClient, PresenceManager,
)
from fluidframework_tpu.loader import Container, Loader
from fluidframework_tpu.server.tinylicious import LocalService


# ------------------------------------------------------------------ signals

class TestSignals:
    def test_signal_broadcast_to_all_connected(self):
        svc = LocalService()
        client = LocalClient(service=svc)
        c1, doc_id = client.create_container({"initialObjects": {}})
        c2 = client.get_container(doc_id, {"initialObjects": {}})
        got1, got2 = [], []
        c1.on("signal", lambda s: got1.append((s.client_id, s.contents)))
        c2.on("signal", lambda s: got2.append((s.client_id, s.contents)))
        c1.submit_signal({"cursor": 5})
        # both (including the sender) see it; it was never sequenced
        assert got1 == got2 == [(c1.container.client_id, {"cursor": 5})]
        assert all(m.contents != {"cursor": 5}
                   for m in svc.get_deltas(doc_id))

    def test_signals_not_stored_for_late_joiners(self):
        client = LocalClient()
        c1, doc_id = client.create_container({"initialObjects": {}})
        c1.submit_signal("ephemeral")
        late = client.get_container(doc_id, {"initialObjects": {}})
        got = []
        late.on("signal", lambda s: got.append(s))
        assert got == []   # no history replay for signals


# --------------------------------------------------------------- DataObject

class TodoApp(DataObject):
    created = 0
    loaded = 0

    def initializing_first_time(self):
        TodoApp.created += 1
        self.root.set("title", "untitled")
        self.create_channel("items", "map")

    def initializing_from_existing(self):
        TodoApp.loaded += 1

    @property
    def items(self):
        return self.get_channel("items")


class TestDataObject:
    def setup_method(self):
        TodoApp.created = 0
        TodoApp.loaded = 0

    def test_lifecycle_first_time_vs_existing(self):
        svc = LocalService()
        factory = ContainerRuntimeFactoryWithDefaultDataObject(
            DataObjectFactory("todo", TodoApp))
        loader = Loader(LocalDocumentServiceFactory(svc), factory)
        a = loader.resolve("doc")
        app_a = factory.get_default(a.runtime)
        assert TodoApp.created == 1
        app_a.items.set("buy milk", False)
        app_a.root.set("title", "groceries")

        b = loader.resolve("doc")
        app_b = factory.get_default(b.runtime)
        assert TodoApp.created == 1 and TodoApp.loaded == 1
        assert app_b.root.get("title") == "groceries"
        assert app_b.items.get("buy milk") is False
        app_b.items.set("buy milk", True)
        assert app_a.items.get("buy milk") is True


# ------------------------------------------------------------- fluid-static

class TestFluidStatic:
    SCHEMA = {"initialObjects": {"meta": "map", "text": "sharedString"}}

    def test_create_and_get_container(self):
        client = LocalClient()
        c1, doc_id = client.create_container(self.SCHEMA)
        c1.initial_objects["meta"].set("lang", "en")
        c1.initial_objects["text"].insert_text(0, "hello")
        c2 = client.get_container(doc_id, self.SCHEMA)
        assert c2.initial_objects["meta"].get("lang") == "en"
        assert c2.initial_objects["text"].get_text() == "hello"
        c2.initial_objects["text"].insert_text(5, " world")
        assert c1.initial_objects["text"].get_text() == "hello world"

    def test_dynamic_objects_via_handles(self):
        client = LocalClient()
        c1, doc_id = client.create_container(self.SCHEMA)
        counter = c1.create("counter")
        counter.increment(3)
        c1.initial_objects["meta"].set("counterRef",
                                       FluidContainer.handle_of(counter))
        c2 = client.get_container(doc_id, self.SCHEMA)
        handle = c2.initial_objects["meta"].get("counterRef")
        resolved = c2.resolve_handle(handle)
        assert resolved.value == 3
        resolved.increment(2)
        assert counter.value == 5

    def test_background_summarizer_trims_catchup(self):
        from fluidframework_tpu.runtime import SummaryConfig
        client = LocalClient(
            summary_config=SummaryConfig(max_ops=5, max_time_s=1e9))
        c1, doc_id = client.create_container(self.SCHEMA)
        m = c1.initial_objects["meta"]
        for i in range(25):
            m.set(f"k{i}", i)
        summary, seq, _ = client.service.latest_summary(doc_id)
        assert summary is not None and seq > 0
        late = client.get_container(doc_id, self.SCHEMA)
        assert late.container.base_seq > 0        # loaded from summary
        assert late.initial_objects["meta"].get("k24") == 24


# ----------------------------------------------------------------- presence

class TestPresence:
    def test_presence_roundtrip_and_leave(self):
        client = LocalClient()
        c1, doc_id = client.create_container({"initialObjects": {}})
        c2 = client.get_container(doc_id, {"initialObjects": {}})
        p1, p2 = PresenceManager(c1.container), PresenceManager(c2.container)
        p1.set_presence({"cursor": 10})
        p2.set_presence({"cursor": 99})
        assert p2.get_presences() == {c1.container.client_id: {"cursor": 10}}
        assert p1.get_presences() == {c2.container.client_id: {"cursor": 99}}
        changes = []
        p1.on_presence_changed(lambda cid, d: changes.append((cid, d)))
        cid2 = c2.container.client_id
        c2.dispose()
        assert (cid2, None) in changes
        assert p1.get_presences() == {}

    def test_late_joiner_gets_refresh(self):
        client = LocalClient()
        c1, doc_id = client.create_container({"initialObjects": {}})
        p1 = PresenceManager(c1.container)
        p1.set_presence({"user": "ada"})
        c2 = client.get_container(doc_id, {"initialObjects": {}})
        p2 = PresenceManager(c2.container)
        # p2 was constructed after connect; trigger the handshake manually
        # (the reference wires presence before connecting)
        p2._on_connected(c2.container.client_id)
        assert p2.get_presences() == {c1.container.client_id:
                                      {"user": "ada"}}


# ------------------------------------------------------------ examples (§2.19)

class TestSharedTextExample:
    def test_example_runs_and_converges(self):
        import importlib.util
        import os
        path = os.path.join(os.path.dirname(__file__), "..", "examples",
                            "shared_text.py")
        spec = importlib.util.spec_from_file_location("shared_text", path)
        mod = importlib.util.module_from_spec(spec)
        spec.loader.exec_module(mod)
        assert mod.main() == 0
