"""Convergence fuzz for SharedMap, SharedMatrix, and the SharedString channel
(text + interval collections), per the reference's DDS-fuzz strategy
(SURVEY.md §4)."""

import pytest

from fluidframework_tpu.testing.fuzz import (
    run_map_fuzz, run_matrix_fuzz, run_string_channel_fuzz,
)


@pytest.mark.parametrize("seed", range(10))
def test_map_fuzz(seed):
    run_map_fuzz(seed)


@pytest.mark.parametrize("seed", range(10))
def test_matrix_fuzz(seed):
    run_matrix_fuzz(seed)


@pytest.mark.parametrize("seed", range(10))
def test_string_channel_fuzz(seed):
    run_string_channel_fuzz(seed)
