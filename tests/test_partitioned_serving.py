"""Partitioned serving (ISSUE 18): doc-sharded sequencer mesh tests.

Covers the four load-bearing claims of ``server/partitioned.py``:

1. routing is deterministic plane math — hash + bounded overrides, one
   vectorized divmod from global row to (partition, local row);
2. the skew guard moves only NON-resident heavy hitters, and flags
   (without moving) when everything heavy is already pinned by a row;
3. the partition-aware columnar door keeps full wire semantics across
   N engines — acks, text parity, per-partition stats — and survives a
   kill → promote failover with the deposed leader epoch-fenced;
4. cross-replica digest parity (``ReplicaDigestTap``) holds per window
   on the virtual ``(replica, docs)`` mesh, fed by REAL sequenced
   windows from the door's drain pass.

The full chaos drill (outage waves, cross-partition session audits)
lives in ``tools/chaos_soak.py --partitions N``; these tests pin the
component contracts tier-1-fast.
"""

import json
import time
import urllib.request

import numpy as np
import pytest

from fluidframework_tpu.server.columnar_ingress import (
    _OP_DTYPE, ColumnarAlfred, ColumnarClient)
from fluidframework_tpu.server.oplog import FencedWriterError, partition_of
from fluidframework_tpu.server.partitioned import (
    DocPartitionRouter, PartitionedStringServing, ReplicaDigestTap)

pytestmark = [pytest.mark.partition]


# ------------------------------------------------------------------ helpers

def _names_on_partition(n_partitions, target, count, prefix="pt"):
    """Doc names whose FNV hash lands on ``target`` (no overrides)."""
    out, i = [], 0
    while len(out) < count:
        d = f"{prefix}-{i}"
        i += 1
        if partition_of(d, n_partitions) == target:
            out.append(d)
    return out


def _docs_covering_all_partitions(svc, prefix):
    """One doc per partition, discovered by hashing candidate names."""
    need = set(range(svc.n_partitions))
    docs, i = [], 0
    while need:
        d = f"{prefix}-{i}"
        i += 1
        p = svc.partition_of_doc(d)
        if p in need:
            need.discard(p)
            docs.append(d)
    return docs


class _FakeSketch:
    """Stands in for ``opsd.SpaceSaving``: fixed top-k rows."""

    def __init__(self, docs):
        self._rows = [((d, "t0"), 100 - i, 0) for i, d in enumerate(docs)]

    def top(self, k):
        return self._rows[:k]


def _drain_acks(client, rows_to_doc, expect, deadline_s=20.0):
    """Collect ``expect`` acks; returns {doc: {cseq: seq}}."""
    got = {}
    n = 0
    deadline = time.time() + deadline_s
    while n < expect:
        assert time.time() < deadline, \
            f"ack drain timed out at {n}/{expect}"
        fr = client.recv_json()
        assert fr.get("t") == "acks", fr
        for (cs, seq), r in zip(fr["acks"], fr["rows"]):
            d = rows_to_doc[r]
            assert seq > 0, f"nack {seq} for {d} cseq {cs}"
            per = got.setdefault(d, {})
            assert cs not in per, f"double ack {d} cseq {cs}"
            per[int(cs)] = int(seq)
            n += 1
    return got


def _send_wave(client, rows, marker, cseqs):
    """One insert-at-0 op per row; oracle text = markers reversed."""
    ops = np.zeros(len(rows), _OP_DTYPE)
    for i, r in enumerate(rows):
        ops[i] = (r, 0, 0, 0, 0, cseqs[i], 0)
    client.send_ops([marker], ops)


def _get_json(url, timeout=10.0):
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return json.loads(resp.read())


# ------------------------------------------------------------------- router

class TestDocPartitionRouter:
    def test_hash_route_is_stable_and_in_range(self):
        r = DocPartitionRouter(4)
        for i in range(64):
            d = f"doc-{i}"
            p = r.route(d)
            assert 0 <= p < 4
            assert r.route(d) == p == partition_of(d, 4)

    def test_skew_guard_moves_only_nonresident_heavies(self):
        n = 4
        r = DocPartitionRouter(n)
        # 8 heavy docs all hashing to partition 0 — maximal skew
        heavy = _names_on_partition(n, 0, 8, prefix="skew")
        rep = r.check_skew(_FakeSketch(heavy), resident=lambda d: False,
                           k=8, factor=1.0)
        assert 0 in rep["flagged"]
        assert rep["moved"], "nothing rebalanced despite total skew"
        assert r.rebalanced_docs == len(rep["moved"]) == len(r.overrides)
        for d, src, dst in rep["moved"]:
            assert src == 0 and dst != 0
            assert r.route(d) == dst  # override took effect
        # loads after the guard respect the fair share bound
        assert max(rep["loads"]) <= rep["fair_share"]

    def test_skew_guard_flags_but_never_moves_resident_docs(self):
        n = 4
        r = DocPartitionRouter(n)
        heavy = _names_on_partition(n, 1, 8, prefix="pin")
        rep = r.check_skew(_FakeSketch(heavy), resident=lambda d: True,
                           k=8, factor=1.0)
        assert 1 in rep["flagged"]
        assert rep["moved"] == [] and r.overrides == {}
        assert r.skew_flags >= 1

    def test_override_table_is_bounded(self):
        r = DocPartitionRouter(4, max_overrides=3)
        heavy = _names_on_partition(4, 0, 10, prefix="cap")
        r.check_skew(_FakeSketch(heavy), resident=lambda d: False,
                     k=10, factor=0.5)
        assert len(r.overrides) <= 3


# ---------------------------------------------------------------- row space

class TestGlobalRowSpace:
    def test_doc_row_maps_partition_times_dpp_plus_local(self):
        svc = PartitionedStringServing(n_partitions=4,
                                       docs_per_partition=8)
        docs = [f"rs-{i}" for i in range(16)]
        for d in docs:
            g = svc.doc_row(d)
            p = svc.partition_of_doc(d)
            assert g // svc.docs_per_partition == p
            assert svc.engines[p].doc_row(d) == g % svc.docs_per_partition
            assert svc._row_doc_id[g] == d
        parts, local = svc.split_rows(
            np.array([svc.doc_row(d) for d in docs]))
        np.testing.assert_array_equal(
            parts, [svc.partition_of_doc(d) for d in docs])
        assert (local < svc.docs_per_partition).all()

    def test_membership_and_acks_route_to_owning_partition(self):
        svc = PartitionedStringServing(n_partitions=2,
                                       docs_per_partition=4)
        d0, d1 = _docs_covering_all_partitions(svc, "mb")
        for d in (d0, d1):
            svc.doc_row(d)
            svc.connect(d, client_id=7)
            assert svc.is_member(d, 7)
            assert svc.last_client_seq(d, 7) == 0
        # ack fan-in lands on the right per-partition dedup ledger
        rows = np.array([svc.doc_row(d0), svc.doc_row(d1)])
        svc.note_acked_planes(rows, np.array([7, 7]), np.array([3, 5]),
                              np.array([11, 12]))
        assert svc.last_client_seq(d0, 7) == 3
        assert svc.last_client_seq(d1, 7) == 5

    def test_partition_stats_shape(self):
        svc = PartitionedStringServing(n_partitions=3,
                                       docs_per_partition=4)
        svc.doc_row("st-a")
        rows = svc.partition_stats()
        assert [r["partition"] for r in rows] == [0, 1, 2]
        assert sum(r["resident_docs"] for r in rows) == 1
        for r in rows:
            assert not r["dead"] and not r["follower_armed"]


# ------------------------------------------------------- door + digest tap

class TestPartitionedDoor:
    def test_storm_acks_text_parity_and_digest(self):
        """Small cross-partition storm through the columnar door: every
        ack arrives exactly once, per-doc text matches submission
        order, per-partition stats populate — and (devices permitting)
        every sequenced window clears the replica digest tap."""
        jax = pytest.importorskip("jax")
        svc = PartitionedStringServing(n_partitions=4,
                                       docs_per_partition=16,
                                       capacity=256)
        door = ColumnarAlfred(svc, window_min_rows=8, window_ms=2.0,
                              pipeline_depth=2)
        tap = None
        if jax.device_count() >= 2:
            from fluidframework_tpu.parallel.mesh import make_mesh
            tap = ReplicaDigestTap(make_mesh(jax.device_count()),
                                   n_docs=32, capacity=64)
            door.digest_tap = tap
        door.start_in_thread()
        try:
            docs = _docs_covering_all_partitions(svc, "storm") \
                + _docs_covering_all_partitions(svc, "storm2")
            cl = ColumnarClient("127.0.0.1", door.port)
            rows = cl.join(docs)
            row_doc = {rows[d]: d for d in docs}
            waves = 4
            for w in range(waves):
                _send_wave(cl, [rows[d] for d in docs], f"w{w}_",
                           [w + 1] * len(docs))
            acked = _drain_acks(cl, row_doc, waves * len(docs))
            expect = "".join(f"w{w}_" for w in reversed(range(waves)))
            for d in docs:
                assert sorted(acked[d]) == list(range(1, waves + 1))
                seqs = [acked[d][cs] for cs in sorted(acked[d])]
                assert all(b > a for a, b in zip(seqs, seqs[1:]))
                assert svc.read_text(d) == expect
            stats = door.partition_stats()
            assert len(stats) == svc.n_partitions
            assert sum(r["resident_docs"] for r in stats) == len(docs)
            for r in stats:
                assert r["resident_docs"] >= 2  # docs cover every part
                assert r["backlog_ops"] == 0
                assert r["waves_inflight"] == 0
            if tap is not None:
                assert tap.windows > 0
                assert tap.agree_all, "cross-replica digest diverged"
            cl.close()
        finally:
            door.stop()

    def test_failover_fences_deposed_leader_and_resumes(self, tmp_path):
        """kill → promote on one partition: the deposed leader's next
        durable append raises ``FencedWriterError``, the promoted
        follower serves the doc's full history, and ingest through the
        door keeps working on the SAME rows post-promotion."""
        svc = PartitionedStringServing(n_partitions=2,
                                       docs_per_partition=8,
                                       capacity=256,
                                       spill_dir=str(tmp_path))
        door = ColumnarAlfred(svc, window_min_rows=4, window_ms=2.0,
                              pipeline_depth=2).start_in_thread()
        try:
            docs = _docs_covering_all_partitions(svc, "fo")
            cl = ColumnarClient("127.0.0.1", door.port)
            rows = cl.join(docs)
            row_doc = {rows[d]: d for d in docs}
            _send_wave(cl, [rows[d] for d in docs], "w0_", [1, 1])
            _send_wave(cl, [rows[d] for d in docs], "w1_", [2, 2])
            _drain_acks(cl, row_doc, 2 * len(docs))

            victim = svc.partition_of_doc(docs[0])
            svc.attach_follower(victim)
            assert svc.partition_stats()[victim]["follower_armed"]
            deposed = svc.engines[victim]
            svc.kill_partition(victim)
            assert svc.partition_stats()[victim]["dead"]
            old = svc.promote(victim)
            assert old is deposed
            door.rebind_executor(victim)
            with pytest.raises(FencedWriterError):
                deposed.log.open_for_append(deposed.writer_epoch)

            # promoted engine replayed the durable tail 1:1
            assert svc.read_text(docs[0]) == "w1_w0_"
            st = svc.partition_stats()[victim]
            assert not st["dead"] and not st["follower_armed"]
            assert st["writer_epoch"] > deposed.writer_epoch

            # same rows keep working through the door post-promotion
            _send_wave(cl, [rows[d] for d in docs], "w2_", [3, 3])
            _drain_acks(cl, row_doc, len(docs))
            for d in docs:
                assert svc.read_text(d) == "w2_w1_w0_"
            cl.close()
        finally:
            door.stop()


class TestReplicaDigestTap:
    def test_pad_and_fold_kinds_map_to_noop(self):
        """Unit contract: odd-size windows pad to a replica multiple,
        fold kinds (> STR_REMOVE) are masked to NOOP so the
        with_props=False shadow never sees a prop op."""
        jax = pytest.importorskip("jax")
        if jax.device_count() < 2:
            pytest.skip("virtual mesh needs >= 2 devices")
        from fluidframework_tpu.ops.schema import OpKind
        from fluidframework_tpu.parallel.mesh import make_mesh
        tap = ReplicaDigestTap(make_mesh(jax.device_count()),
                               n_docs=16, capacity=32)
        noop = int(OpKind.NOOP)
        fold = int(OpKind.STR_REMOVE) + 1  # masked to NOOP inside
        for w, size in enumerate((3, 5, 7)):  # never a replica multiple
            rows = np.arange(size, dtype=np.int32)
            kinds = np.full(size, noop, np.int32)
            kinds[-1] = fold
            zeros = np.zeros(size, np.int32)
            seqs = np.arange(size, dtype=np.int32) + 1 + w * size
            assert tap.on_window(rows, kinds, zeros, zeros, seqs,
                                 zeros, zeros)
        assert tap.windows == 3 and tap.agree_all
        assert tap.n_replicas >= 2


# --------------------------------------------------------------- ops plane

class TestOpsPlaneRoutes:
    def test_debug_partitions_and_partition_scoped_latency(self):
        """``/debug/partitions`` serves the door's per-partition rows;
        ``/debug/latency?partition=p`` scopes the stage breakdown to
        one partition's labeled collector (ISSUE 18 satellite)."""
        svc = PartitionedStringServing(n_partitions=2,
                                       docs_per_partition=8)
        door = ColumnarAlfred(svc, window_min_rows=4, window_ms=2.0,
                              pipeline_depth=2).start_in_thread()
        ops = door.start_ops()
        try:
            docs = _docs_covering_all_partitions(svc, "ops")
            cl = ColumnarClient("127.0.0.1", door.port)
            rows = cl.join(docs)
            row_doc = {rows[d]: d for d in docs}
            _send_wave(cl, [rows[d] for d in docs], "x_", [1, 1])
            _drain_acks(cl, row_doc, len(docs))

            body = _get_json(ops.url + "/debug/partitions")
            assert body["count"] == 2
            for r in body["partitions"]:
                for key in ("partition", "resident_docs", "backlog_ops",
                            "waves_inflight", "writer_epoch", "dead"):
                    assert key in r, key
            assert sum(r["resident_docs"]
                       for r in body["partitions"]) == len(docs)

            for p in range(2):
                bd = _get_json(ops.url + f"/debug/latency?partition={p}")
                assert bd["partition"] == p
                assert "stages" in bd
            # both partitions sequenced a window, so both labeled
            # collectors carry stage samples
            seen = [_get_json(ops.url + f"/debug/latency?partition={p}")
                    for p in range(2)]
            assert any(bd["stages"] for bd in seen)
            cl.close()
        finally:
            door.stop()
