"""Health plane (ISSUE 4): time-series retention, SLO burn-rate engine,
mesh-aware rollups, and the perf-regression sentinel.

Covers the acceptance criteria end to end: reset-aware counter rates,
fast/slow multi-window burn math, exemplar capture + breach trace
resolution, per-shard/per-replica labels round-tripping through
``render_prometheus()``, a forced replica digest divergence on the
virtual mesh driving ``replica_digest_divergence_total`` and an
SLO-breach flight dump tagged with the breaching trace id, and the
sentinel judging the committed BENCH trajectory green while failing an
injected synthetic regression.
"""

import importlib.util
import json
import os
import re
import shutil
import types

import numpy as np
import pytest

from fluidframework_tpu.utils import (
    flight_recorder, slo, telemetry, timeseries, tracing,
)
from fluidframework_tpu.utils.telemetry import (
    BufferSink, Histogram, MetricsCollector, MetricsRegistry,
    TelemetryLogger,
)

pytestmark = pytest.mark.telemetry

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tool(name):
    """Load a tools/*.py script as a module (tools/ is not a package)."""
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _ctx(tid, sid="s0"):
    return types.SimpleNamespace(trace_id=tid, span_id=sid)


# ---------------------------------------------------------- TimeSeriesStore


class TestTimeSeriesStore:
    def test_tick_samples_and_ring_bound(self):
        reg = MetricsRegistry()
        store = timeseries.TimeSeriesStore(registry=reg, capacity=64)
        for i in range(100):
            reg.inc("ops_ingested", 5)
            reg.set_gauge("queue_depth", float(i))
            store.tick(now=float(i))
        assert store.n_ticks == 100
        assert len(store.values("ops_ingested")) == 64  # ring-bounded
        assert store.latest("ops_ingested") == 500.0
        assert store.latest("queue_depth") == 99.0
        assert store.kinds["ops_ingested"] == "counter"
        assert store.kinds["queue_depth"] == "gauge"

    def test_bools_sample_as_01_and_nan_skipped(self):
        reg = MetricsRegistry()
        store = timeseries.TimeSeriesStore(registry=reg)
        reg.set_gauge("digest_parity", True)
        reg.set_gauge("broken", float("nan"))
        store.tick(now=0.0)
        assert store.latest("digest_parity") == 1.0
        assert store.latest("broken") is None

    def test_rate_reset_aware(self):
        store = timeseries.TimeSeriesStore(registry=MetricsRegistry())
        # counter restarts between t=1 and t=2 (engine rebuild): the
        # post-reset sample contributes its own value, never a negative
        for t, v in [(0, 10.0), (1, 20.0), (2, 5.0), (3, 15.0)]:
            store.ingest_sample(float(t), {"ops_ingested": v})
        assert store.rate("ops_ingested") == pytest.approx(25.0 / 3.0)
        # trailing 1s window: just the (5 -> 15) delta
        assert store.rate("ops_ingested", window_s=1.0) == \
            pytest.approx(10.0)

    def test_rate_needs_counter_kind_and_history(self):
        store = timeseries.TimeSeriesStore(registry=MetricsRegistry())
        store.ingest_sample(0.0, {"queue_depth": 3.0, "ops_ingested": 1.0})
        assert store.rate("queue_depth") is None      # gauge
        assert store.rate("ops_ingested") is None     # one sample
        assert store.rate("missing") is None

    def test_window_summary_percentiles(self):
        store = timeseries.TimeSeriesStore(registry=MetricsRegistry())
        for t in range(100):
            store.ingest_sample(float(t), {"lag": float(t + 1)})
        s = store.window_summary("lag")
        assert (s["n"], s["min"], s["max"], s["last"]) == (100, 1, 100, 100)
        assert s["p50"] == 51.0
        assert s["p99"] == 99.0
        # clipped window sees only the tail
        s10 = store.window_summary("lag", window_s=9.0)
        assert s10["n"] == 10 and s10["min"] == 91.0

    def test_jsonl_round_trip_and_torn_tail(self, tmp_path):
        path = str(tmp_path / "health.jsonl")
        reg = MetricsRegistry()
        store = timeseries.TimeSeriesStore(registry=reg, jsonl_path=path)
        for i in range(3):
            reg.inc("ops_ingested", 10)
            reg.set_gauge("digest_parity", True)
            store.tick(now=float(i))
        loaded = timeseries.TimeSeriesStore.from_jsonl(path)
        assert loaded.values("ops_ingested") == \
            store.values("ops_ingested")
        assert loaded.kinds["ops_ingested"] == "counter"  # inferred
        assert loaded.latest("digest_parity") == 1.0
        # torn tail (crash mid-append) must not break the re-load
        with open(path, "a") as f:
            f.write('{"t": 99, "metr')
        torn = timeseries.TimeSeriesStore.from_jsonl(path)
        assert len(torn.values("ops_ingested")) == 3

    def test_export_jsonl_matches_incremental(self, tmp_path):
        reg = MetricsRegistry()
        store = timeseries.TimeSeriesStore(registry=reg)
        for i in range(4):
            reg.inc("flushes")
            store.tick(now=float(i))
        out = str(tmp_path / "export.jsonl")
        assert store.export_jsonl(out) == 4
        assert timeseries.TimeSeriesStore.from_jsonl(out).values(
            "flushes") == store.values("flushes")

    def test_sparklines_counters_plot_deltas(self):
        reg = MetricsRegistry()
        store = timeseries.TimeSeriesStore(registry=reg)
        for i, by in enumerate([0, 10, 20, 30]):
            reg.inc("ops_ingested", by)
            reg.set_gauge("idle_gauge", 0.0)
            store.tick(now=float(i))
        text = store.render_sparklines()
        assert "ops_ingested" in text
        assert "rate=" in text                 # counters carry the rate
        assert "idle_gauge" not in text        # all-zero series hidden
        assert "idle_gauge" in store.render_sparklines(active_only=False)
        empty = timeseries.TimeSeriesStore(registry=MetricsRegistry())
        assert "no active series" in empty.render_sparklines()


# ----------------------------------------------------------- SLO burn math


class TestSLOSpec:
    def test_parse_forms(self):
        s = slo.SLOSpec.parse("ack_p99_ms < 200")
        assert (s.metric, s.op, s.threshold, s.kind) == \
            ("ack_p99_ms", "<", 200.0, "value")
        s = slo.SLOSpec.parse("digest_parity == true")
        assert s.threshold == 1.0
        s = slo.SLOSpec.parse("rate(flight_dump_total) == 0")
        assert (s.metric, s.kind) == ("flight_dump_total", "rate")
        # bare *_rate sugar targets the counter behind it
        s = slo.SLOSpec.parse("flight_dump_rate == 0")
        assert (s.metric, s.kind) == ("flight_dump_total", "rate")
        with pytest.raises(ValueError):
            slo.SLOSpec.parse("no operator here")

    def test_multi_window_requires_both_burning(self):
        store = timeseries.TimeSeriesStore(registry=MetricsRegistry())
        spec = slo.SLOSpec.parse("ack_p99_ms < 200", name="ack",
                                 fast_window_s=10.0, slow_window_s=1000.0,
                                 fast_burn=0.5, slow_burn=0.1)
        for t in range(90):                       # healthy history
            store.ingest_sample(float(t), {"ack_p99_ms": 100.0})
        (r,) = spec.evaluate(store, now=89.0)
        assert r["ok"] and r["judged"]
        # a fresh cliff: the fast window burns (6 bad of 11) but the slow
        # window holds (6 of 96 < 10%) — fast-only is noise, no breach
        for t in range(90, 96):
            store.ingest_sample(float(t), {"ack_p99_ms": 500.0})
        (r,) = spec.evaluate(store, now=95.0)
        assert r["ok"]
        assert r["fast_burn"] >= 0.5
        assert r["slow_burn"] < 0.1
        # the cliff persists: slow window reaches 10 bad of 100 — breach
        for t in range(96, 100):
            store.ingest_sample(float(t), {"ack_p99_ms": 500.0})
        (r,) = spec.evaluate(store, now=99.0)
        assert not r["ok"]
        assert r["worst"] == 500.0

    def test_rate_kind_judges_derived_rate(self):
        store = timeseries.TimeSeriesStore(registry=MetricsRegistry())
        spec = slo.SLOSpec.parse("rate(flight_dump_total) == 0",
                                 name="quiet")
        for t, v in enumerate([0.0, 0.0, 0.0]):
            store.ingest_sample(float(t), {"flight_dump_total": v})
        (r,) = spec.evaluate(store)
        assert r["ok"]
        store.ingest_sample(3.0, {"flight_dump_total": 2.0})
        (r,) = spec.evaluate(store)
        assert not r["ok"] and r["worst"] > 0

    def test_insufficient_data_never_pages(self):
        store = timeseries.TimeSeriesStore(registry=MetricsRegistry())
        store.ingest_sample(0.0, {"ack_p99_ms": 9999.0})
        spec = slo.SLOSpec.parse("ack_p99_ms < 200")   # min_samples=2
        (r,) = spec.evaluate(store)
        assert r["ok"] and not r["judged"]


class TestSLOEngine:
    def _engine(self, tmp_path, specs):
        reg = MetricsRegistry()
        store = timeseries.TimeSeriesStore(registry=reg)
        sink = BufferSink()
        eng = slo.SLOEngine(
            store, specs=specs, registry=reg,
            logger=TelemetryLogger(sink, "slo"),
            recorder=flight_recorder.FlightRecorder(
                dump_dir=str(tmp_path)))
        return reg, store, sink, eng

    def test_breach_edge_trigger_and_rearm(self, tmp_path):
        spec = slo.SLOSpec.parse("digest_parity == true", name="parity",
                                 min_samples=1)
        reg, store, sink, eng = self._engine(tmp_path, [spec])
        reg.set_gauge("digest_parity", 1.0)
        store.tick(now=0.0)
        assert eng.check(now=0.0) == []
        reg.set_gauge("digest_parity", 0.0)
        store.tick(now=1.0)
        new = eng.check(now=1.0)
        assert len(new) == 1
        assert reg.counters["slo_breach_total"] == 1.0
        assert os.path.exists(new[0]["dump"])
        header = json.loads(open(new[0]["dump"]).readline())
        assert header["slo"] == "parity"
        # still breaching: edge-triggered, no duplicate side effects
        store.tick(now=2.0)
        assert eng.check(now=2.0) == []
        assert reg.counters["slo_breach_total"] == 1.0
        # recovery re-arms (window far enough ahead to shed bad samples)
        reg.set_gauge("digest_parity", 1.0)
        store.tick(now=1000.0)
        assert eng.check(now=1000.0) == []
        reg.set_gauge("digest_parity", 0.0)
        store.tick(now=1001.0)
        assert len(eng.check(now=1001.0)) == 1
        assert reg.counters["slo_breach_total"] == 2.0
        assert len(sink.named("slo_breach")) == 2

    def test_breach_carries_worst_exemplar_trace(self, tmp_path):
        spec = slo.SLOSpec.parse("ack_ms_p99_ms < 200", name="ack",
                                 min_samples=1)
        reg, store, sink, eng = self._engine(tmp_path, [spec])
        reg.observe("ack_ms", 50.0, exemplar=_ctx("tid-fine", "s-f"))
        reg.observe("ack_ms", 950.0, exemplar=_ctx("tid-worst", "s-w"))
        store.tick(now=0.0)
        store.tick(now=1.0)
        (breach,) = eng.check(now=1.0)
        assert breach["trace_id"] == "tid-worst"
        assert breach["span_id"] == "s-w"
        assert breach["exemplar_value_ms"] == 950.0
        assert "tid-worst" in open(breach["dump"]).readline()

    def test_breach_falls_back_to_current_span(self, tmp_path):
        spec = slo.SLOSpec.parse("digest_parity == true", name="parity",
                                 min_samples=1)
        reg, store, sink, eng = self._engine(tmp_path, [spec])
        reg.set_gauge("digest_parity", 0.0)
        store.tick(now=0.0)
        with tracing.span("health-probe") as sp:
            (breach,) = eng.check(now=0.0)
            assert breach["trace_id"] == sp.ctx.trace_id

    def test_scorecard_surfaces_unmatched_specs(self):
        reg = MetricsRegistry()
        store = timeseries.TimeSeriesStore(registry=reg)
        eng = slo.SLOEngine(store, specs=slo.default_slos(), registry=reg)
        rows = eng.scorecard()
        # nothing sampled yet: every spec reports, none pages
        assert len(rows) >= len(slo.default_slos())
        assert all(r["ok"] for r in rows)
        text = slo.render_scorecard(rows)
        assert "no-data" in text and "ack_latency" in text


# ------------------------------------------------------- exemplar capture


class TestExemplars:
    def test_worst_exemplar_and_bound(self):
        h = Histogram()
        for i in range(40):
            h.observe(float(i), exemplar=_ctx(f"tid-{i}"))
        h.observe(7.0, exemplar=_ctx("tid-late-small"))
        assert len(h.exemplars) <= Histogram.EXEMPLAR_KEEP
        assert h.worst_exemplar == (39.0, "tid-39", "s0")

    def test_exemplar_true_captures_current_span(self):
        h = Histogram()
        with tracing.span("obs") as sp:
            h.observe(5.0, exemplar=True)
        assert h.worst_exemplar[1] == sp.ctx.trace_id
        # no active span: exemplar=True records the value, no exemplar
        h2 = Histogram()
        h2.observe(5.0, exemplar=True)
        assert h2.n == 1 and h2.exemplars == []


# --------------------------------------------------- mesh-labeled rollups


class TestMeshRollups:
    def test_shard_labels_skew_and_prometheus(self):
        parent = MetricsRegistry()
        colls = []
        for s in range(4):
            c = MetricsCollector()
            parent.attach("Engine", c, labels={"shard": s})
            c.inc("ops_applied", 10.0 * (s + 1))
            colls.append(c)
        snap = parent.full_snapshot()
        assert snap["Engine{shard=2}.ops_applied"] == 30.0
        assert snap["Engine.ops_applied_shard_min"] == 10.0
        assert snap["Engine.ops_applied_shard_max"] == 40.0
        assert snap["Engine.ops_applied_shard_skew"] == 30.0
        kinds = parent.full_snapshot_kinds()
        assert kinds["Engine{shard=2}.ops_applied"] == "counter"
        assert kinds["Engine.ops_applied_shard_skew"] == "gauge"
        prom = parent.render_prometheus()
        assert 'ops_applied{component="Engine",shard="3"} 40.0' in prom

    def test_serving_engine_shard_accounting(self):
        from fluidframework_tpu.parallel.sharded import make_doc_mesh
        from fluidframework_tpu.server.serving import StringServingEngine
        from fluidframework_tpu.utils.telemetry import REGISTRY
        mesh = make_doc_mesh(8)
        eng = StringServingEngine(n_docs=16, capacity=64, mesh=mesh)
        eng._ensure_shard_collectors()
        assert len(eng.shard_metrics) == 8    # one per doc shard
        assert eng._rows_per_shard == 2
        # credit two ops on every row, then pile extra load on shard 0
        eng._note_shard_ops(np.arange(16), counts=np.full(16, 2.0))
        eng._note_shard_ops(np.array([0, 1]), counts=np.array([10., 10.]))
        assert eng.shard_metrics[0].counters["ops_applied"] == 24.0
        assert eng.shard_metrics[3].counters["ops_applied"] == 4.0
        snap = REGISTRY.full_snapshot()
        skews = {k: v for k, v in snap.items()
                 if k.startswith("StringServingEngine")
                 and k.endswith(".ops_applied_shard_skew")}
        assert 20.0 in skews.values()
        # per-shard series round-trip through the Prometheus exposition
        prom = REGISTRY.render_prometheus()
        assert re.search(
            r'ops_applied\{component="StringServingEngine\d*",'
            r'shard="3"\} 4\.0', prom)

    def test_partition_collectors_count_appends(self):
        from fluidframework_tpu.core.protocol import (
            MessageType, SequencedDocumentMessage,
        )
        from fluidframework_tpu.server.oplog import partition_of
        from fluidframework_tpu.server.serving import StringServingEngine
        eng = StringServingEngine(n_docs=4, capacity=32, n_partitions=4)
        assert len(eng.partition_metrics) == 4
        msg = SequencedDocumentMessage("doc-0", 1, 1, 0, 1, 0,
                                       MessageType.NOOP)
        eng._log_append("doc-0", msg)
        p = partition_of("doc-0", 4)
        assert eng.partition_metrics[p].counters["appends"] == 1.0
        assert sum(c.counters.get("appends", 0.0)
                   for c in eng.partition_metrics) == 1.0
        prom = eng.partition_metrics[p].render_prometheus()
        assert "appends 1.0" in prom.replace("\n", " ")


# --------------------------------- replicated mesh: forced divergence path


class TestReplicaDivergence:
    def test_injected_divergence_breaks_agreement_and_pages(self, tmp_path):
        import jax.numpy as jnp
        from fluidframework_tpu.ops.merge_tree_kernel import StringState
        from fluidframework_tpu.parallel import (
            make_mesh, make_replicated_step, shard_ops, shard_state,
        )
        from fluidframework_tpu.parallel.replicated import ReplicaSetMetrics
        from fluidframework_tpu.testing.synthetic import typing_storm

        mesh = make_mesh(8)                  # 2 replicas x 4 doc shards
        _, doc_shards = mesh.devices.shape
        n_docs, n_ops, cap = 2 * doc_shards, 8, 64
        planes, _ = typing_storm(n_docs, n_ops, seed=3)
        ops = tuple(jnp.asarray(planes[k]) for k in
                    ("kind", "a0", "a1", "a2", "seq", "client", "ref_seq"))
        step = make_replicated_step(mesh, inject_divergence=True)
        state = shard_state(StringState.create(n_docs, cap), mesh)
        _, _, agree = step(state, *shard_ops(mesh, *ops))
        assert int(agree) == 0               # the chaos hook forced it

        reg = MetricsRegistry()
        sink = BufferSink()
        rsm = ReplicaSetMetrics(mesh, registry=reg,
                                logger=TelemetryLogger(sink, "replicaSet"))
        assert rsm.n_replicas == 2
        assert rsm.on_step(agree, n_ops=n_docs * n_ops) is False
        assert reg.counters["replica_digest_divergence_total"] == 1.0
        assert reg.gauges["digest_parity"] == 0.0
        assert len(sink.named("replica_digest_divergence")) == 1
        prom = reg.render_prometheus()
        assert 'component="ReplicaSet",replica="0"' in prom
        assert 'component="ReplicaSet",replica="1"' in prom

        # the health plane on top: parity SLO breaches, and the flight
        # dump is tagged with the breaching trace id
        store = timeseries.TimeSeriesStore(registry=reg)
        store.tick(now=0.0)
        eng = slo.SLOEngine(
            store,
            specs=[slo.SLOSpec.parse("digest_parity == true",
                                     name="digest_parity",
                                     min_samples=1)],
            registry=reg, logger=TelemetryLogger(BufferSink(), "slo"),
            recorder=flight_recorder.FlightRecorder(
                dump_dir=str(tmp_path)))
        with tracing.span("divergence-probe") as sp:
            (breach,) = eng.check(now=0.0)
        assert breach["slo"] == "digest_parity"
        assert breach["trace_id"] == sp.ctx.trace_id
        assert reg.counters["slo_breach_total"] == 1.0
        header = json.loads(open(breach["dump"]).readline())
        assert header["flight_recorder"] == "slo:digest_parity"
        assert header["trace_id"] == sp.ctx.trace_id


# --------------------------------------------- flight-dump rate limiting


class TestFlightDumpRateLimit:
    def test_same_reason_suppressed_within_window(self, tmp_path):
        from fluidframework_tpu.utils.telemetry import REGISTRY
        rec = flight_recorder.FlightRecorder(dump_dir=str(tmp_path),
                                             dedup_window_s=30.0)
        rec.note("precursor", detail=1)
        before = REGISTRY.counters.get("flight_dump_suppressed_total", 0.0)
        p1 = rec.dump("crash")
        p2 = rec.dump("crash")               # within the window
        assert p2 == p1                      # prior evidence returned
        assert rec.suppressed["crash"] == 1
        assert REGISTRY.counters["flight_dump_suppressed_total"] == \
            before + 1
        assert len(list(tmp_path.glob("flight-*.jsonl"))) == 1
        # a different reason and a forced dump both still write
        p3 = rec.dump("other")
        p4 = rec.dump("crash", force=True)
        assert len({p1, p3, p4}) == 3
        assert len(list(tmp_path.glob("flight-*.jsonl"))) == 3
        # the suppression itself is on the record
        events = flight_recorder.load_dump(p4)
        assert any(e.get("eventName") == "flight_dump_suppressed"
                   for e in events)


# ------------------------------------------------------------- sentinel


class TestPerfSentinel:
    def test_classify_directions(self):
        ps = _tool("perf_sentinel")
        assert ps.classify("serving_ops_per_sec") == "up"
        assert ps.classify("value") == "up"
        assert ps.classify("ack_p99_ms") == "down"
        assert ps.classify("digest_parity") == "hold"
        assert ps.classify("apply_window_worst_ms") == "info"
        assert ps.classify("dispatch_rtt_ms") == "info"
        assert ps.classify("docs") == "info"

    def test_judge_band_math(self):
        ps = _tool("perf_sentinel")
        priors = [{"value": v, "ack_p99_ms": 10.0, "digest_parity": True,
                   "_round": f"r{i}"}
                  for i, v in enumerate([100.0, 102.0, 98.0])]
        # band on "value": max(10% of 100, 3 sigma of [100,102,98]) = 10
        v = {x["metric"]: x for x in ps.judge(
            priors + [{"value": 60.0, "ack_p99_ms": 30.0,
                       "digest_parity": False, "fresh_ms": 1.0,
                       "_round": "r9"}])}
        assert v["value"]["verdict"] == ps.REGRESS       # -40 > band
        assert v["ack_p99_ms"]["verdict"] == ps.REGRESS  # latency tripled
        assert v["digest_parity"]["verdict"] == ps.REGRESS
        assert v["fresh_ms"]["verdict"] == ps.NEW        # no history
        v = {x["metric"]: x for x in ps.judge(
            priors + [{"value": 150.0, "ack_p99_ms": 10.5,
                       "digest_parity": True, "_round": "r9"}])}
        assert v["value"]["verdict"] == ps.IMPROVE
        assert v["ack_p99_ms"]["verdict"] == ps.FLAT
        assert v["digest_parity"]["verdict"] == ps.FLAT
        assert ps.has_regression([{"verdict": ps.REGRESS}])
        assert not ps.has_regression([{"verdict": ps.FLAT}])

    def test_committed_trajectory_is_green(self, capsys):
        # the tier-1 gate: the committed BENCH_r*.json history must judge
        # clean (known r05 stall outlier included — it is info-classed)
        ps = _tool("perf_sentinel")
        assert ps.main(["--check"]) == 0
        out = capsys.readouterr().out
        assert "perf_sentinel: OK" in out

    def test_synthetic_regression_fails(self, tmp_path, capsys):
        ps = _tool("perf_sentinel")
        from pathlib import Path
        for p in Path(REPO).glob("BENCH_r*.json"):
            shutil.copy(p, tmp_path / p.name)
        rounds = ps.load_trajectory(Path(REPO))
        doctored = {k: v for k, v in rounds[-1].items()
                    if not k.startswith("_")}
        doctored["value"] = doctored["value"] * 0.4   # a real cliff
        (tmp_path / "BENCH_r90.json").write_text(json.dumps(doctored))
        assert ps.main(["--root", str(tmp_path)]) == 1
        out = capsys.readouterr().out
        assert "regress" in out
        verdicts = ps.judge(ps.load_trajectory(tmp_path))
        bad = [v for v in verdicts if v["verdict"] == ps.REGRESS]
        assert any(v["metric"] == "value" for v in bad)

    def test_torn_record_skipped_not_fatal(self, tmp_path, capsys):
        ps = _tool("perf_sentinel")
        from pathlib import Path
        for p in Path(REPO).glob("BENCH_r*.json"):
            shutil.copy(p, tmp_path / p.name)
        (tmp_path / "BENCH_r00.json").write_text('{"rc": 1, "tail": ""}')
        rounds = ps.load_trajectory(tmp_path)
        assert [r["_round"] for r in rounds][0] == "BENCH_r01"
        assert ps.main(["--root", str(tmp_path), "--check"]) == 0
        capsys.readouterr()

    def test_write_md_creates_trajectory_section(self, tmp_path, capsys):
        ps = _tool("perf_sentinel")
        from pathlib import Path
        for p in Path(REPO).glob("BENCH_r*.json"):
            shutil.copy(p, tmp_path / p.name)
        (tmp_path / "BENCHES.md").write_text("# Recorded outputs\n")
        assert ps.main(["--root", str(tmp_path), "--check",
                        "--write-md"]) == 0
        capsys.readouterr()
        md = (tmp_path / "BENCHES.md").read_text()
        assert ps.TRAJECTORY_HEADING in md
        block = md.split("```json\n", 1)[1].split("```", 1)[0]
        lines = [json.loads(x) for x in block.strip().splitlines()]
        assert lines[0]["round"] == "BENCH_r01"
        assert "sentinel" in lines[-1]


# -------------------------------------------------------------- healthz


class TestHealthz:
    def test_demo_dashboard_green(self, capsys):
        hz = _tool("healthz")
        assert hz.main(["--demo"]) == 0
        out = capsys.readouterr().out
        assert "ops_ingested" in out
        assert "ack_latency" in out          # default SLO scorecard

    def test_breaching_extra_slo_fails(self, capsys):
        hz = _tool("healthz")
        rc = hz.main(["--demo", "--slo", "ops_ingested < 0"])
        capsys.readouterr()
        assert rc == 1

    def test_jsonl_input_round_trip(self, tmp_path, capsys):
        path = str(tmp_path / "h.jsonl")
        reg = MetricsRegistry()
        store = timeseries.TimeSeriesStore(registry=reg, jsonl_path=path)
        for i in range(8):
            reg.inc("ops_ingested", 50)
            reg.set_gauge("digest_parity", 1.0)
            store.tick(now=float(i))
        hz = _tool("healthz")
        assert hz.main([path]) == 0
        out = capsys.readouterr().out
        assert "ops_ingested" in out and "digest_parity" in out
