"""Container runtime layer (L3): outbox pipeline, remote message processor,
id compressor, datastore routing, pending state / reconnect / stash.
Reference behaviors per SURVEY.md §2.8/§2.9/§2.11, §3.2–3.3, §5.3."""

import dataclasses

import pytest

from fluidframework_tpu.core.protocol import (
    MessageType, SequencedDocumentMessage,
)
from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Container, Loader
from fluidframework_tpu.runtime import (
    ContainerRuntime, ContainerRuntimeOptions, IdCompressor, IdCreationRange,
    Outbox, RemoteMessageProcessor, stable_id,
)
from fluidframework_tpu.server.tinylicious import LocalService


def wire_msg(seq, contents, client_id=1, type=MessageType.OP, metadata=None):
    return SequencedDocumentMessage(
        doc_id="d", client_id=client_id, client_seq=seq, ref_seq=0,
        seq=seq, min_seq=0, type=type, contents=contents, metadata=metadata)


# ------------------------------------------------------------- IdCompressor

class TestIdCompressor:
    def test_local_ids_are_negative_and_monotone(self):
        c = IdCompressor()
        assert [c.generate_id() for _ in range(3)] == [-1, -2, -3]

    def test_creation_range_covers_unannounced_tail(self):
        c = IdCompressor()
        c.generate_id(), c.generate_id()
        rng = c.take_next_creation_range()
        assert (rng.first_gen_count, rng.count) == (1, 2)
        assert c.take_next_creation_range() is None
        c.generate_id()
        rng2 = c.take_next_creation_range()
        assert (rng2.first_gen_count, rng2.count) == (3, 1)

    def test_two_sessions_converge_on_final_ids(self):
        a, b = IdCompressor(cluster_capacity=4), IdCompressor(cluster_capacity=4)
        a.generate_id(); a.generate_id()
        b.generate_id()
        ra = a.take_next_creation_range()
        rb = b.take_next_creation_range()
        for comp in (a, b):              # same total order on both replicas
            comp.finalize_range(ra)
            comp.finalize_range(rb)
        # a's ids finalized first → finals 0,1; b's id starts a new cluster
        assert a.normalize_to_op_space(-1) == 0
        assert a.normalize_to_op_space(-2) == 1
        assert b.normalize_to_op_space(-1) == 4  # after a's capacity-4 cluster
        # cross-session resolution
        assert b.normalize_to_session_space(-2, originator=a.session_id) == 1
        assert a.decompress(-1) == b.decompress(0) == stable_id(a.session_id, 1)

    def test_cluster_slack_keeps_session_contiguous(self):
        a, b = IdCompressor(cluster_capacity=8), IdCompressor(cluster_capacity=8)
        a.generate_id()
        r1 = a.take_next_creation_range()
        a.finalize_range(r1); b.finalize_range(r1)
        a.generate_id(); a.generate_id()
        r2 = a.take_next_creation_range()
        a.finalize_range(r2); b.finalize_range(r2)
        # second range fills the same cluster: finals stay contiguous
        assert [a.normalize_to_op_space(i) for i in (-1, -2, -3)] == [0, 1, 2]

    def test_out_of_order_range_rejected(self):
        a = IdCompressor()
        a.generate_id()
        with pytest.raises(ValueError):
            a.finalize_range(IdCreationRange(a.session_id,
                                             first_gen_count=5, count=1))

    def test_summary_roundtrip(self):
        a = IdCompressor(cluster_capacity=4)
        a.generate_id()
        rng = a.take_next_creation_range()
        a.finalize_range(rng)
        fresh = IdCompressor.load(a.summarize())
        assert fresh.normalize_to_session_space(0) == 0
        assert fresh.decompress(0) == stable_id(a.session_id, 1)
        # new clusters on the loaded replica allocate past the loaded ones
        fresh.generate_id()
        r2 = fresh.take_next_creation_range()
        fresh.finalize_range(r2)
        assert fresh.normalize_to_op_space(-1) == 4


# ------------------------------------------- outbox → remote processor loop

def roundtrip(outbox_kwargs, ops):
    """Push ops through an Outbox, replay the wire ops through a
    RemoteMessageProcessor, return the expanded runtime messages."""
    wire = []
    ob = Outbox(lambda c, m: wire.append(c), **outbox_kwargs)
    for op in ops:
        ob.submit(op)
    ob.flush()
    rmp = RemoteMessageProcessor()
    out = []
    for i, contents in enumerate(wire):
        out.extend(rmp.process(wire_msg(i + 1, contents)))
    return wire, out


class TestOutboxPipeline:
    def test_grouped_batch_is_one_wire_op(self):
        ops = [{"op": "set", "key": f"k{i}", "value": i} for i in range(5)]
        wire, out = roundtrip(dict(grouped_batching=True), ops)
        assert len(wire) == 1
        assert [m.contents for m in out] == ops
        assert all(m.seq == 1 for m in out)  # shared envelope seq

    def test_ungrouped_batch_carries_boundary_metadata(self):
        ops = [{"i": 0}, {"i": 1}, {"i": 2}]
        wire = []
        ob = Outbox(lambda c, m: wire.append((c, m)), grouped_batching=False)
        for op in ops:
            ob.submit(op)
        ob.flush()
        assert len(wire) == 3
        assert wire[0][1] == {"batch": True}
        assert wire[-1][1] == {"batch": False}

    def test_compression_roundtrip(self):
        big = {"op": "set", "key": "k", "value": "x" * 9000}
        wire, out = roundtrip(
            dict(compression_threshold=256, max_op_size=1 << 20), [big])
        assert len(wire) == 1 and wire[0]["type"] == "compressed"
        assert [m.contents for m in out] == [big]

    def test_chunking_roundtrip(self):
        import base64
        import hashlib
        # incompressible payload so the compressed form still overflows
        # max_op_size and must chunk
        chunks = [hashlib.sha256(str(i).encode()).digest()
                  for i in range(300)]
        big = {"op": "set", "key": "k",
               "value": base64.b64encode(b"".join(chunks)).decode()}
        wire, out = roundtrip(
            dict(compression_threshold=256, max_op_size=512), [big])
        assert len(wire) > 1
        assert all(c["type"] == "chunkedOp" for c in wire)
        assert [m.contents for m in out] == [big]

    def test_grouped_compressed_batch(self):
        ops = [{"k": i, "pad": "y" * 600} for i in range(10)]
        wire, out = roundtrip(
            dict(grouped_batching=True, compression_threshold=1024,
                 max_op_size=1 << 20), ops)
        assert len(wire) == 1 and wire[0]["type"] == "compressed"
        assert [m.contents for m in out] == ops

    def test_empty_flush_sends_nothing(self):
        wire = []
        ob = Outbox(lambda c, m: wire.append(c))
        assert ob.flush() == 0 and wire == []


# ---------------------------------------------------- end-to-end containers

def make_pair(options=None, service=None):
    svc = service or LocalService()
    loader = Loader(LocalDocumentServiceFactory(svc),
                    ContainerRuntime.factory(options=options))
    a = loader.resolve("doc")
    b = loader.resolve("doc")
    return svc, loader, a, b


class TestRuntimeEndToEnd:
    def test_map_converges_across_containers(self):
        _, _, a, b = make_pair()
        ds_a = a.runtime.create_data_store("default")
        m_a = ds_a.create_channel("root", "map")
        m_a.set("title", "hello")
        m_a.set("n", 42)
        m_b = b.runtime.get_data_store("default").get_channel("root")
        assert m_b.get("title") == "hello" and m_b.get("n") == 42
        m_b.set("n", 43)
        assert m_a.get("n") == 43

    def test_turn_mode_groups_batch_into_one_sequenced_op(self):
        opts = ContainerRuntimeOptions(flush_mode="turn",
                                       grouped_batching=True)
        svc, _, a, b = make_pair(opts)
        ds = a.runtime.create_data_store("default")
        m = ds.create_channel("root", "map")
        a.runtime.flush()
        seq_before = a.delta_manager.last_sequence_number
        for i in range(10):
            m.set(f"k{i}", i)
        assert a.delta_manager.last_sequence_number == seq_before
        a.runtime.flush()
        # one grouped envelope = one sequence number for all 10 ops
        assert a.delta_manager.last_sequence_number == seq_before + 1
        m_b = b.runtime.get_data_store("default").get_channel("root")
        assert all(m_b.get(f"k{i}") == i for i in range(10))

    def test_compressed_chunked_ops_converge(self):
        opts = ContainerRuntimeOptions(compression_threshold=128,
                                       max_op_size=256)
        _, _, a, b = make_pair(opts)
        ds = a.runtime.create_data_store("default")
        m = ds.create_channel("root", "map")
        m.set("blob", "q" * 5000)
        m_b = b.runtime.get_data_store("default").get_channel("root")
        assert m_b.get("blob") == "q" * 5000

    def test_multiple_datastores_and_channels_route_independently(self):
        _, _, a, b = make_pair()
        d1 = a.runtime.create_data_store("d1")
        d2 = a.runtime.create_data_store("d2")
        d1.create_channel("m", "map").set("x", 1)
        d2.create_channel("m", "map").set("x", 2)
        d2.create_channel("c", "counter").increment(5)
        assert b.runtime.get_data_store("d1").get_channel("m").get("x") == 1
        assert b.runtime.get_data_store("d2").get_channel("m").get("x") == 2
        assert b.runtime.get_data_store("d2").get_channel("c").value == 5

    def test_late_joiner_realizes_from_attach_ops(self):
        svc = LocalService()
        loader = Loader(LocalDocumentServiceFactory(svc),
                        ContainerRuntime.factory())
        a = loader.resolve("doc")
        m = a.runtime.create_data_store("default").create_channel("r", "map")
        m.set("k", "v")
        late = loader.resolve("doc")
        assert late.runtime.get_data_store("default") \
                   .get_channel("r").get("k") == "v"

    def test_id_compressor_rides_op_stream(self):
        _, _, a, b = make_pair()
        a.runtime.create_data_store("default").create_channel("r", "map")
        local = a.runtime.generate_document_unique_id()
        assert local == -1
        # any flush ships the pending creation range
        a.runtime.get_data_store("default").get_channel("r").set("x", 1)
        final = a.runtime.id_compressor.normalize_to_op_space(local)
        assert final >= 0
        # replica b finalized the same range at the same sequence point
        assert b.runtime.id_compressor.normalize_to_session_space(
            final) == final
        assert b.runtime.id_compressor.decompress(final) == \
            a.runtime.id_compressor.decompress(local)

    def test_shared_string_via_runtime(self):
        _, _, a, b = make_pair()
        ds = a.runtime.create_data_store("default")
        s = ds.create_channel("text", "sharedString")
        s.insert_text(0, "hello world")
        s_b = b.runtime.get_data_store("default").get_channel("text")
        s_b.insert_text(5, ",")
        assert s.get_text() == s_b.get_text() == "hello, world"


# ------------------------------------------------- reconnect + stash resume

class TestPendingAndReconnect:
    def test_ops_while_disconnected_resubmit_on_reconnect(self):
        _, _, a, b = make_pair()
        m = a.runtime.create_data_store("default").create_channel("r", "map")
        m.set("before", 1)
        a.disconnect("test")
        m.set("offline", 2)          # recorded pending, not sent
        m_b = b.runtime.get_data_store("default").get_channel("r")
        assert m_b.get("offline") is None
        a.connect()                  # resubmits through the channels
        assert m_b.get("offline") == 2
        assert not a.runtime.pending.has_pending

    def test_remote_edits_during_offline_merge_lww(self):
        _, _, a, b = make_pair()
        m_a = a.runtime.create_data_store("default").create_channel("r", "map")
        m_b = b.runtime.get_data_store("default").get_channel("r")
        a.disconnect("net")
        m_a.set("k", "from-a")       # pending offline
        m_b.set("k", "from-b")       # sequenced now
        a.connect()                  # a's op sequenced after b's → a wins
        assert m_a.get("k") == "from-a" and m_b.get("k") == "from-a"

    def test_stash_and_rehydrate_resumes_pending_ops(self):
        svc = LocalService()
        loader = Loader(LocalDocumentServiceFactory(svc),
                        ContainerRuntime.factory())
        a = loader.resolve("doc")
        m = a.runtime.create_data_store("default").create_channel("r", "map")
        m.set("committed", 1)
        # summary covering the committed state (rehydrate loads from it)
        summary = {"protocol": a.protocol.snapshot(),
                   "runtime": a.runtime.summarize()}
        svc.upload_summary("doc", summary, a.protocol.seq)
        a.disconnect("going offline")
        m.set("stashed", 2)
        blob = a.runtime.get_pending_local_state()
        a.close()

        resumed = Loader(
            LocalDocumentServiceFactory(svc),
            ContainerRuntime.factory(pending_blob=blob)).resolve("doc")
        m2 = resumed.runtime.get_data_store("default").get_channel("r")
        assert m2.get("committed") == 1 and m2.get("stashed") == 2
        b = loader.resolve("doc")
        assert b.runtime.get_data_store("default").get_channel("r") \
                .get("stashed") == 2

    def test_summary_roundtrip_through_runtime(self):
        svc = LocalService()
        loader = Loader(LocalDocumentServiceFactory(svc),
                        ContainerRuntime.factory())
        a = loader.resolve("doc")
        ds = a.runtime.create_data_store("default")
        ds.create_channel("m", "map").set("k", "v")
        ds.create_channel("s", "sharedString").insert_text(0, "abc")
        summary_seq = a.protocol.seq
        summary = {"protocol": a.protocol.snapshot(),
                   "runtime": a.runtime.summarize()}
        svc.upload_summary("doc", summary, summary_seq)
        fresh = loader.resolve("doc")
        assert fresh.base_seq == summary_seq  # loaded summary, not replay
        fds = fresh.runtime.get_data_store("default")
        assert fds.get_channel("m").get("k") == "v"
        assert fds.get_channel("s").get_text() == "abc"
        # post-summary collaboration still flows
        fds.get_channel("m").set("k2", 2)
        assert ds.get_channel("m").get("k2") == 2


# ----------------------------------------- review-finding regression tests

class TestReviewRegressions:
    def test_small_threshold_large_op_still_respects_max_size(self):
        # op under compression_threshold but over max_op_size must not ship
        # as one oversized wire op
        wire, out = roundtrip(
            dict(compression_threshold=1 << 20, max_op_size=64),
            [{"op": "set", "key": "k", "value": "v" * 500}])
        assert all(
            len(__import__("json").dumps(c, separators=(",", ":"))) <= 3 * 64
            for c in wire)  # chunk pieces bounded (payload + small envelope)
        assert out[0].contents["value"] == "v" * 500

    def test_batch_metadata_travels_over_the_wire(self):
        opts = ContainerRuntimeOptions(flush_mode="turn",
                                       grouped_batching=False)
        _, _, a, b = make_pair(opts)
        seen = []
        b.runtime.on("runtimeOp",
                     lambda msg, local: seen.append(msg.metadata))
        m = a.runtime.create_data_store("default").create_channel("r", "map")
        a.runtime.flush()
        seen.clear()
        m.set("x", 1)
        m.set("y", 2)
        m.set("z", 3)
        a.runtime.flush()
        # first wire op of the batch marked batch=True, last batch=False
        metas = [meta for meta in seen if meta is not None]
        assert {"batch": True} in metas and {"batch": False} in metas

    def test_stash_with_post_summary_datastore_defers_until_catchup(self):
        svc = LocalService()
        loader = Loader(LocalDocumentServiceFactory(svc),
                        ContainerRuntime.factory())
        a = loader.resolve("doc")
        # summary BEFORE the datastore exists
        svc.upload_summary("doc", {"protocol": a.protocol.snapshot(),
                                   "runtime": a.runtime.summarize()},
                           a.protocol.seq)
        m = a.runtime.create_data_store("late").create_channel("r", "map")
        m.set("committed", 1)
        a.disconnect("offline")
        m.set("stashed", 2)
        blob = a.runtime.get_pending_local_state()
        a.close()
        # rehydrate: summary has no 'late' datastore; the attach op is in
        # the op tail, so the stashed record must defer, then apply
        resumed = Loader(
            LocalDocumentServiceFactory(svc),
            ContainerRuntime.factory(pending_blob=blob)).resolve("doc")
        m2 = resumed.runtime.get_data_store("late").get_channel("r")
        assert m2.get("committed") == 1 and m2.get("stashed") == 2

    def test_reconnect_id_ranges_stay_in_generation_order(self):
        _, _, a, b = make_pair()
        a.runtime.create_data_store("default").create_channel("r", "map")
        a.disconnect("net")
        # range R1 generated+pending while offline
        i1 = a.runtime.generate_document_unique_id()
        ds = a.runtime.get_data_store("default")
        ds.get_channel("r").set("k", 1)
        a.connect()
        # on reconnect a second id: its range must finalize after R1's
        i2 = a.runtime.generate_document_unique_id()
        ds.get_channel("r").set("k2", 2)
        f1 = a.runtime.id_compressor.normalize_to_op_space(i1)
        f2 = a.runtime.id_compressor.normalize_to_op_space(i2)
        assert 0 <= f1 < f2
        assert b.runtime.id_compressor.decompress(f1) == \
            a.runtime.id_compressor.decompress(i1)


class TestStaleReconnectEcho:
    def test_stale_old_connection_echo_applies_as_remote(self):
        """A reconnect can race an in-flight op that the service still
        sequences under the OLD client id AFTER the catch-up read: its echo
        then arrives post-resubmission. Every peer applies that echo, so we
        must too — as a REMOTE op — while pending state waits for the
        resubmission's echo (code-review r2 finding: the old behavior
        crashed on the empty/mismatched pending deque)."""
        wire_log = []
        rt = ContainerRuntime(lambda contents: wire_log.append(contents),
                              options=ContainerRuntimeOptions(
                                  enable_id_compressor=False,
                                  grouped_batching=False),
                              client_id=1)
        peer = ContainerRuntime(lambda contents: None,
                                options=ContainerRuntimeOptions(
                                    enable_id_compressor=False,
                                    grouped_batching=False),
                                client_id=9)
        m = rt.create_data_store("default").create_channel("r", "map")
        rt.flush()
        attach_ops = list(wire_log)
        wire_log.clear()
        m.set("k", "v1")
        rt.flush()
        assert len(wire_log) == 1
        original = wire_log.pop()

        # reconnect: pending records resubmit under the NEW client id
        rt.set_connection_state(False, None)
        rt.set_connection_state(True, 2)
        rt.flush()
        resubmits = list(wire_log)
        assert resubmits  # the attach ops + set were all still pending

        def seq_msgs(payloads, client_id, start_seq):
            return [SequencedDocumentMessage(
                doc_id="d", client_id=client_id, client_seq=i + 1,
                ref_seq=0, seq=start_seq + i, min_seq=0,
                type=MessageType.OP, contents=c)
                for i, c in enumerate(payloads)]

        # the STALE echoes (old id) arrive first — after resubmission
        stale = seq_msgs(attach_ops + [original], 1, 1)
        # then the resubmission's echoes (new id)
        fresh = seq_msgs(resubmits, 2, 1 + len(stale))
        for msg in stale + fresh:
            rt.process(msg, local=(msg.client_id in (1, 2)))
            peer.process(msg, local=False)
        assert not rt.pending.has_pending
        got = rt.get_data_store("default").get_channel("r")
        got_peer = peer.get_data_store("default").get_channel("r")
        assert got.get("k") == "v1" == got_peer.get("k")
