"""Stress/load tier (reference: test-service-load, SURVEY.md §4): many
containers per document against the in-proc service, randomized op storms
with disconnect/reconnect (pending-op rebase) and summarization under load,
then deep convergence checks across every replica."""

import random

import pytest

from fluidframework_tpu.framework import LocalClient
from fluidframework_tpu.runtime import SummaryConfig

SCHEMA = {"initialObjects": {"meta": "map", "text": "sharedString",
                             "grid": "matrix"}}


def _storm(rng, containers, n_ops):
    """Randomized edits on random replicas; some replicas are offline and
    accumulate pending ops that rebase at reconnect."""
    for _ in range(n_ops):
        c = rng.choice(containers)
        roll = rng.random()
        text = c.initial_objects["text"]
        if roll < 0.35:
            n = text.get_length()
            text.insert_text(rng.randint(0, n), f"w{rng.randint(0, 99)} ")
        elif roll < 0.45 and text.get_length() > 0:
            start = rng.randrange(text.get_length())
            end = min(text.get_length(), start + rng.randint(1, 4))
            text.remove_text(start, end)
        elif roll < 0.55 and text.get_length() > 0:
            start = rng.randrange(text.get_length())
            end = min(text.get_length(), start + rng.randint(1, 6))
            text.annotate_range(start, end,
                                {"mark": rng.choice(("a", "b", None))})
        elif roll < 0.8:
            c.initial_objects["meta"].set(f"k{rng.randint(0, 30)}",
                                          rng.randint(0, 1000))
        else:
            g = c.initial_objects["grid"]
            if g.row_count == 0 or (g.row_count < 6 and roll < 0.85):
                g.insert_rows(rng.randint(0, g.row_count), 1)
                if g.col_count < 4:
                    g.insert_cols(rng.randint(0, g.col_count), 1)
            elif g.col_count > 0:
                g.set_cell(rng.randrange(g.row_count),
                           rng.randrange(g.col_count), rng.randint(0, 99))


def _assert_converged(containers):
    texts = {c.initial_objects["text"].get_text() for c in containers}
    assert len(texts) == 1, texts
    first = containers[0]
    length = first.initial_objects["text"].get_length()
    for c in containers[1:]:
        for pos in range(length):
            assert c.initial_objects["text"].get_properties(pos) == \
                first.initial_objects["text"].get_properties(pos), pos
        for k in range(31):
            assert c.initial_objects["meta"].get(f"k{k}") == \
                first.initial_objects["meta"].get(f"k{k}"), k
        g0, g1 = first.initial_objects["grid"], c.initial_objects["grid"]
        assert (g1.row_count, g1.col_count) == (g0.row_count, g0.col_count)
        for r in range(g0.row_count):
            for col in range(g0.col_count):
                assert g1.get_cell(r, col) == g0.get_cell(r, col), (r, col)


@pytest.mark.parametrize("seed", range(3))
def test_service_load_with_reconnects_and_summaries(seed):
    rng = random.Random(seed)
    client = LocalClient(
        summary_config=SummaryConfig(max_ops=40, max_time_s=1e9))
    c1, doc_id = client.create_container(SCHEMA)
    containers = [c1] + [client.get_container(doc_id, SCHEMA)
                         for _ in range(3)]

    for phase in range(6):
        _storm(rng, containers, 30)
        # random connection churn: offline replicas keep editing (pending
        # ops) and rebase on reconnect
        for c in containers[1:]:
            if rng.random() < 0.4 and c.connected:
                c.disconnect("storm-churn")
            elif not c.connected:
                c.connect()
    for c in containers:
        if not c.connected:
            c.connect()
    _assert_converged(containers)

    # a summary must exist (summarizer ran under load) and late joiners
    # load from it and still converge
    summary, seq, _ = client.service.latest_summary(doc_id)
    assert summary is not None and seq > 0
    late = client.get_container(doc_id, SCHEMA)
    assert late.container.base_seq > 0
    _assert_converged(containers + [late])


def test_many_documents_isolated_under_load():
    rng = random.Random(7)
    client = LocalClient()
    docs = []
    for _ in range(5):
        c, doc_id = client.create_container(SCHEMA)
        docs.append((doc_id, [c, client.get_container(doc_id, SCHEMA)]))
    for _ in range(4):
        for _doc_id, containers in docs:
            _storm(rng, containers, 12)
    for _doc_id, containers in docs:
        _assert_converged(containers)
    # documents never bleed into each other
    texts = [cs[0].initial_objects["text"].get_text() for _d, cs in docs]
    assert len(set(texts)) == len(texts)  # distinct random streams


def test_matrix_offline_insert_rebases_position():
    """Directed regression: an offline row insert must re-resolve its
    position against rows sequenced while offline (a verbatim resubmit
    places it at a stale index and replicas diverge)."""
    client = LocalClient(enable_summarizer=False)
    schema = {"initialObjects": {"grid": "matrix"}}
    c1, doc_id = client.create_container(schema)
    c2 = client.get_container(doc_id, schema)
    g1, g2 = c1.initial_objects["grid"], c2.initial_objects["grid"]
    g1.insert_rows(0, 3)
    g1.insert_cols(0, 1)
    for r in range(3):
        g1.set_cell(r, 0, f"r{r}")
    c2.disconnect("offline")
    g2.insert_rows(2, 1)       # between r1 and r2 in c2's view
    g2.set_cell(2, 0, "X")
    g1.insert_rows(0, 1)       # sequenced while c2 offline, shifts positions
    g1.set_cell(0, 0, "front")
    c2.connect()
    assert g1.digest() == g2.digest(), (g1.to_lists(), g2.to_lists())
    assert g1.to_lists() == [["front"], ["r0"], ["r1"], ["X"], ["r2"]]


def test_matrix_offline_setcell_on_concurrently_removed_row_drops():
    """A pending setCell whose row was removed while offline must drop
    cleanly (the cell no longer exists anywhere)."""
    client = LocalClient(enable_summarizer=False)
    schema = {"initialObjects": {"grid": "matrix"}}
    c1, doc_id = client.create_container(schema)
    c2 = client.get_container(doc_id, schema)
    g1, g2 = c1.initial_objects["grid"], c2.initial_objects["grid"]
    g1.insert_rows(0, 2)
    g1.insert_cols(0, 1)
    c2.disconnect("offline")
    g2.set_cell(1, 0, "doomed")
    g1.remove_rows(1, 1)        # the row dies while c2 is offline
    c2.connect()
    assert g1.digest() == g2.digest()
    assert g1.row_count == 1


def test_matrix_offline_remove_rebases_range():
    client = LocalClient(enable_summarizer=False)
    schema = {"initialObjects": {"grid": "matrix"}}
    c1, doc_id = client.create_container(schema)
    c2 = client.get_container(doc_id, schema)
    g1, g2 = c1.initial_objects["grid"], c2.initial_objects["grid"]
    g1.insert_rows(0, 4)
    g1.insert_cols(0, 1)
    for r in range(4):
        g1.set_cell(r, 0, f"r{r}")
    c2.disconnect("offline")
    g2.remove_rows(1, 2)        # removes r1, r2 in c2's view
    g1.insert_rows(0, 1)        # shifts everything right
    g1.set_cell(0, 0, "front")
    c2.connect()
    assert g1.digest() == g2.digest(), (g1.to_lists(), g2.to_lists())
    assert g1.to_lists() == [["front"], ["r0"], ["r3"]]


@pytest.mark.parametrize("seed", range(4))
def test_matrix_reconnect_fuzz(seed):
    """Randomized matrix-only churn with removes: axis rebase + cell-key
    stability under offline/online interleavings."""
    rng = random.Random(seed)
    client = LocalClient(enable_summarizer=False)
    schema = {"initialObjects": {"grid": "matrix"}}
    c1, doc_id = client.create_container(schema)
    containers = [c1] + [client.get_container(doc_id, schema)
                         for _ in range(2)]
    for phase in range(8):
        for _ in range(15):
            c = rng.choice(containers)
            g = c.initial_objects["grid"]
            roll = rng.random()
            if g.row_count == 0 or g.col_count == 0 or \
                    (g.row_count < 7 and roll < 0.4):
                g.insert_rows(rng.randint(0, g.row_count), 1)
                if g.col_count < 3:
                    g.insert_cols(rng.randint(0, g.col_count), 1)
            elif roll < 0.55 and g.row_count > 1:
                g.remove_rows(rng.randrange(g.row_count), 1)
            else:
                g.set_cell(rng.randrange(g.row_count),
                           rng.randrange(g.col_count), rng.randint(0, 99))
        for c in containers[1:]:
            if rng.random() < 0.5 and c.connected:
                c.disconnect("churn")
            elif not c.connected:
                c.connect()
    for c in containers:
        if not c.connected:
            c.connect()
    d0 = containers[0].initial_objects["grid"].digest()
    for c in containers[1:]:
        assert c.initial_objects["grid"].digest() == d0


def test_matrix_offline_split_remove_rebases_both_runs():
    """Regression: a pending multi-row remove split by a concurrently
    sequenced INTERIOR insert must rebase its later run with the earlier
    run's shrinkage accounted for (start - emitted)."""
    client = LocalClient(enable_summarizer=False)
    schema = {"initialObjects": {"grid": "matrix"}}
    c1, doc_id = client.create_container(schema)
    c2 = client.get_container(doc_id, schema)
    g1, g2 = c1.initial_objects["grid"], c2.initial_objects["grid"]
    g1.insert_rows(0, 4)
    g1.insert_cols(0, 1)
    for r in range(4):
        g1.set_cell(r, 0, f"r{r}")
    c2.disconnect("offline")
    g2.remove_rows(0, 3)        # removes r0..r2 in c2's view
    g1.insert_rows(1, 1)        # sequenced INSIDE the removed range
    g1.set_cell(1, 0, "mid")
    c2.connect()
    assert g1.digest() == g2.digest(), (g1.to_lists(), g2.to_lists())
    assert g1.to_lists() == [["mid"], ["r3"]]
