"""Summarizer subsystem (election, heuristics, ack protocol) + GC
mark/sweep. Reference behaviors per SURVEY.md §2.8, §3.4."""

import pytest

from fluidframework_tpu.core.protocol import MessageType
from fluidframework_tpu.drivers import LocalDocumentServiceFactory
from fluidframework_tpu.loader import Loader
from fluidframework_tpu.runtime import (
    ContainerRuntime, ContainerRuntimeOptions, GarbageCollector,
    SummaryConfig, SummaryManager, collect_handles, fluid_handle, is_handle,
)
from fluidframework_tpu.server.tinylicious import LocalService


def make_doc(n_containers=2, options=None, svc=None, doc="doc",
             summary_config=None, clock=None):
    svc = svc or LocalService()
    loader = Loader(LocalDocumentServiceFactory(svc),
                    ContainerRuntime.factory(options=options))
    containers = [loader.resolve(doc) for _ in range(n_containers)]
    managers = [SummaryManager(c, config=summary_config, clock=clock)
                for c in containers]
    return svc, loader, containers, managers


# ----------------------------------------------------------------- election

class TestElection:
    def test_oldest_client_is_elected(self):
        _, _, (a, b), (ma, mb) = make_doc()
        assert ma.is_elected and not mb.is_elected
        assert ma.elected_client == a.client_id

    def test_election_moves_when_elected_leaves(self):
        _, _, (a, b), (ma, mb) = make_doc()
        a.disconnect("gone")
        # the leave op has been sequenced; b is now oldest
        assert mb.is_elected

    def test_only_elected_summarizes(self):
        cfg = SummaryConfig(max_ops=1)
        svc, _, (a, b), (ma, mb) = make_doc(summary_config=cfg)
        m = a.runtime.create_data_store("default").create_channel("r", "map")
        m.set("k", 1)
        assert ma.summaries_acked >= 1
        assert mb.summaries_acked == 0 and mb.summaries_nacked == 0


# --------------------------------------------------------------- heuristics

class TestHeuristics:
    def test_summarizes_after_max_ops(self):
        cfg = SummaryConfig(max_ops=5, max_time_s=1e9)
        svc, _, (a, b), (ma, _) = make_doc(summary_config=cfg)
        m = a.runtime.create_data_store("default").create_channel("r", "map")
        before = ma.summaries_acked
        for i in range(10):
            m.set(f"k{i}", i)
        assert ma.summaries_acked > before
        # the stored summary is loadable and current-ish
        summary, seq, _ = svc.latest_summary("doc")
        assert summary is not None and seq > 0

    def test_no_summary_below_min_ops(self):
        cfg = SummaryConfig(max_ops=100, min_ops=50, max_time_s=0.0)
        _, _, (a, b), (ma, _) = make_doc(summary_config=cfg)
        m = a.runtime.create_data_store("default").create_channel("r", "map")
        m.set("k", 1)
        # time heuristic fires only at/after min_ops
        assert ma.summaries_acked == 0

    def test_time_heuristic_with_injected_clock(self):
        now = [0.0]
        cfg = SummaryConfig(max_ops=10_000, min_ops=1, max_time_s=30.0)
        _, _, (a, b), (ma, _) = make_doc(summary_config=cfg,
                                         clock=lambda: now[0])
        m = a.runtime.create_data_store("default").create_channel("r", "map")
        m.set("k", 1)
        assert ma.summaries_acked == 0
        now[0] = 31.0
        m.set("k2", 2)
        assert ma.summaries_acked == 1

    def test_fresh_client_loads_latest_summary_and_tail(self):
        cfg = SummaryConfig(max_ops=3, max_time_s=1e9)
        svc, loader, (a, b), (ma, _) = make_doc(summary_config=cfg)
        m = a.runtime.create_data_store("default").create_channel("r", "map")
        for i in range(7):
            m.set(f"k{i}", i)
        fresh = loader.resolve("doc")
        assert fresh.base_seq > 0   # loaded from a summary, not op 0
        fm = fresh.runtime.get_data_store("default").get_channel("r")
        assert all(fm.get(f"k{i}") == i for i in range(7))


# ------------------------------------------------------------- ack protocol

class TestAckProtocol:
    def test_ack_recorded_and_in_flight_cleared(self):
        _, _, (a, b), (ma, _) = make_doc()
        m = a.runtime.create_data_store("default").create_channel("r", "map")
        m.set("k", 1)
        seq = ma.summarize_now()
        assert not ma._in_flight and ma.pending_proposal is None
        assert ma.summaries_acked == 1 and ma.last_ack_seq > seq

    def test_nack_on_bogus_handle_counts_attempt(self):
        _, _, (a, b), (ma, _) = make_doc()
        a.runtime.create_data_store("default").create_channel("r", "map")
        ma._in_flight = True
        a.submit({"handle": "sha-does-not-exist", "summarySeq": 1},
                 MessageType.SUMMARIZE)
        assert ma.summaries_nacked == 1 and ma.failed_attempts == 1
        assert not ma._in_flight

    def test_gives_up_after_max_attempts(self):
        cfg = SummaryConfig(max_ops=1, max_attempts=2)
        _, _, (a, b), (ma, _) = make_doc(summary_config=cfg)
        a.runtime.create_data_store("default").create_channel("r", "map")
        ma.failed_attempts = 2
        assert not ma.should_summarize()


# ------------------------------------------------------------------- the GC

class TestGarbageCollector:
    def test_handle_helpers(self):
        h = fluid_handle("ds1", "chan")
        assert is_handle(h) and h["url"] == "/ds1/chan"
        assert collect_handles({"a": [1, {"b": h}]}) == {"ds1"}

    def test_mark_keeps_reachable_chain(self):
        gc = GarbageCollector()
        summaries = {
            "root": {"channels": {"m": {"data": {"ref": fluid_handle("mid")}}}},
            "mid": {"channels": {"m": {"data": {"ref": fluid_handle("leaf")}}}},
            "leaf": {"channels": {}},
            "orphan": {"channels": {}},
        }
        out = gc.run(summaries, roots={"root"})
        assert set(out) == {"root", "mid", "leaf", "orphan"}  # grace period
        assert gc.unreferenced_for == {"orphan": 1}

    def test_sweep_after_grace(self):
        gc = GarbageCollector(sweep_grace_summaries=2)
        summaries = {"root": {}, "orphan": {}}
        for _ in range(2):
            out = gc.run(dict(summaries), roots={"root"})
            assert "orphan" in out
        out = gc.run(dict(summaries), roots={"root"})
        assert "orphan" not in out and gc.swept == ["orphan"]

    def test_revival_resets_grace(self):
        gc = GarbageCollector(sweep_grace_summaries=1)
        no_ref = {"root": {}, "x": {}}
        with_ref = {"root": {"h": fluid_handle("x")}, "x": {}}
        gc.run(dict(no_ref), roots={"root"})
        assert gc.unreferenced_for == {"x": 1}
        gc.run(dict(with_ref), roots={"root"})          # revived
        assert gc.unreferenced_for == {}
        out = gc.run(dict(no_ref), roots={"root"})      # grace restarts
        assert "x" in out

    def test_gc_through_runtime_summaries(self):
        cfg = SummaryConfig(max_ops=10_000)  # manual summaries only
        opts = ContainerRuntimeOptions(gc_sweep_grace_summaries=1)
        svc, loader, (a, b), (ma, _) = make_doc(options=opts,
                                                summary_config=cfg)
        root = a.runtime.create_data_store("default")
        rm = root.create_channel("r", "map")
        side = a.runtime.create_data_store("side", root=False)
        side.create_channel("s", "map").set("x", 1)
        rm.set("side", fluid_handle("side"))
        ma.summarize_now()
        assert "side" in a.runtime.summarize(run_gc=False)["datastores"]
        # drop the only reference → unreferenced → swept after grace
        rm.delete("side")
        ma.summarize_now()      # stamps unreferenced
        ma.summarize_now()      # sweeps
        assert "side" not in a.runtime.summarize(run_gc=False)["datastores"]
        # a fresh client never sees the swept datastore
        fresh = loader.resolve("doc")
        assert not fresh.runtime.has_data_store("side")
        assert fresh.runtime.get_data_store("default") \
                    .get_channel("r").get("side") is None


class TestChannelHandleReuse:
    """Channel-handle reuse (SURVEY.md §2.16; VERDICT r4 missing #2):
    after an acked summary, unchanged channels upload a __handle__ node;
    the storage service materializes it against the prior summary."""

    def test_one_dirty_channel_of_n_uploads(self):
        svc, loader, (a, _b), (ma, _mb) = make_doc()
        ds = a.runtime.create_data_store("default")
        chans = [ds.create_channel(f"c{i}", "map") for i in range(8)]
        for i, ch in enumerate(chans):
            ch.set("k", i)
        ma.summarize_now()
        assert ma.summaries_acked == 1
        chans[3].set("k", 99)  # ONE dirty channel of 8
        tree = a.runtime.summarize(incremental=True)
        entries = tree["datastores"]["default"]["channels"]
        handles = [cid for cid, ch in entries.items()
                   if "__handle__" in ch]
        assert len(handles) == 7 and "c3" not in handles
        # the storage-resolved upload restores every channel's content
        ma.summarize_now()
        assert ma.summaries_acked == 2
        stored, _seq, _sha = svc.historian.latest_summary("doc")
        ch_stored = stored["runtime"]["datastores"]["default"]["channels"]
        assert all("__handle__" not in ch for ch in ch_stored.values())
        fresh = loader.resolve("doc")
        fds = fresh.runtime.get_data_store("default")
        for i in range(8):
            want = 99 if i == 3 else i
            assert fds.get_channel(f"c{i}").get("k") == want, i

    def test_handle_upload_is_smaller(self):
        import json
        svc, _loader, (a, _b), (ma, _mb) = make_doc()
        ds = a.runtime.create_data_store("default")
        for i in range(16):
            ds.create_channel(f"c{i}", "map").set("payload", "x" * 1000)
        ma.summarize_now()
        full_bytes = len(json.dumps(a.runtime.summarize(run_gc=False)))
        inc_bytes = len(json.dumps(
            a.runtime.summarize(run_gc=False, incremental=True)))
        assert inc_bytes < full_bytes / 5
