"""Columnar setCell ingest (MatrixServingEngine.ingest_cells): parity
with the per-op submit path under LWW and FWW, plus log recovery."""

import numpy as np
import pytest

from fluidframework_tpu.server import native_deli
from fluidframework_tpu.server.serving import MatrixServingEngine

pytestmark = pytest.mark.skipif(not native_deli.available(),
                                reason="native sequencer unavailable")


def _mk(D=4, grid=6, fww=False, sequencer="native"):
    eng = MatrixServingEngine(n_docs=D, cell_capacity=4096,
                              batch_window=10 ** 9, sequencer=sequencer,
                              axis_capacity=64)
    docs = [f"mx-{i}" for i in range(D)]
    cs = {}
    for d in docs:
        eng.connect(d, 7)
        cs[d] = 0
        for mx in ("insRow", "insCol"):
            cs[d] += 1
            _, nack = eng.submit(d, 7, cs[d], 0,
                                 {"mx": mx, "pos": 0, "count": grid,
                                  "opKey": (7, cs[d])})
            assert nack is None
        if fww:
            cs[d] += 1
            _, nack = eng.submit(d, 7, cs[d], 0, {"mx": "policy"})
            assert nack is None
    eng.flush()
    return eng, docs, cs


def _storm(rng, docs, cs, grid, n_per_doc):
    ids, cseqs, rp, cp, vals = [], [], [], [], []
    for d in docs:
        for _ in range(n_per_doc):
            cs[d] += 1
            ids.append(d)
            cseqs.append(cs[d])
            rp.append(int(rng.integers(0, grid)))
            cp.append(int(rng.integers(0, grid)))
            vals.append(f"{d}:{cs[d]}")
    return ids, cseqs, rp, cp, vals


@pytest.mark.parametrize("fww", [False, True])
def test_cell_ingest_matches_per_op_engine(fww):
    rng = np.random.default_rng(11)
    grid = 6
    a, docs, cs_a = _mk(fww=fww)
    b, _, cs_b = _mk(fww=fww, sequencer="python")
    for wave in range(3):
        ids, cseqs, rp, cp, vals = _storm(rng, docs, cs_a, grid, 8)
        res = a.ingest_cells(ids, [7] * len(ids), cseqs,
                             [0] * len(ids), rp, cp, vals)
        assert res["nacked"] == 0
        for i, d in enumerate(ids):
            cs_b[d] += 1
            _, nack = b.submit(d, 7, cs_b[d], 0,
                               {"mx": "setCell", "row": rp[i],
                                "col": cp[i], "value": vals[i]})
            assert nack is None
    for d in docs:
        assert a.to_lists(d) == b.to_lists(d), d


def test_cell_ingest_recovery_through_log_replay():
    rng = np.random.default_rng(12)
    grid = 5
    a, docs, cs = _mk(grid=grid)
    summary = a.summarize()
    ids, cseqs, rp, cp, vals = _storm(rng, docs, cs, grid, 10)
    assert a.ingest_cells(ids, [7] * len(ids), cseqs, [0] * len(ids),
                          rp, cp, vals)["nacked"] == 0
    want = {d: a.to_lists(d) for d in docs}
    revived = MatrixServingEngine.load(summary, a.log)
    assert {d: revived.to_lists(d) for d in docs} == want


def test_cell_ingest_nack_and_out_of_range():
    grid = 4
    a, docs, cs = _mk(D=2, grid=grid)
    d = docs[0]
    ids = [d, d, d]
    cseqs = [cs[d] + 1, 99, cs[d] + 2]  # middle op: clientSeq gap → nack
    res = a.ingest_cells(ids, [7] * 3, cseqs, [0] * 3,
                         [0, 1, grid + 5], [0, 1, 0],
                         ["ok", "gap", "oor"])
    assert res["nacked"] == 1 and res["seq"][1] < 0
    assert a.get_cell(d, 0, 0) == "ok"
    # out-of-range position resolved to nothing: dropped, engine alive
    assert a.dims(d) == (grid, grid)
