"""The loopback ingress tier end-to-end (VERDICT r1 missing #1): a real
socket server (Alfred analog), a network driver, and client PROCESSES
collaborating through localhost — reconnect included."""

import json
import os
import subprocess
import sys
import threading
import time

import pytest

from fluidframework_tpu.core.protocol import MessageType
from fluidframework_tpu.drivers.network_driver import (
    NetworkDocumentServiceFactory,
)
from fluidframework_tpu.framework.fluid_static import NetworkClient
from fluidframework_tpu.server.ingress import AlfredServer
from fluidframework_tpu.server import wire

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture()
def server():
    srv = AlfredServer(port=0).start_in_thread()
    yield srv
    srv.stop()


# --------------------------------------------------- driver-level, in-proc

def test_stream_submit_broadcast_roundtrip(server):
    factory = NetworkDocumentServiceFactory(port=server.port)
    svc = factory.create_document_service("d")
    a = svc.connect_to_delta_stream()
    b = svc.connect_to_delta_stream()
    got_a, got_b = [], []
    a.on_op(got_a.append)
    b.on_op(got_b.append)
    a.submit({"x": 1}, ref_seq=0)
    deadline = time.monotonic() + 10
    while (len(got_a) < 1 or len(got_b) < 1) and \
            time.monotonic() < deadline:
        time.sleep(0.01)
    assert got_a and got_b
    assert got_a[0].contents == {"x": 1} == got_b[0].contents
    assert got_a[0].seq == got_b[0].seq
    assert got_a[0].client_id == a.client_id
    a.disconnect()
    b.disconnect()


def test_storage_requests(server):
    factory = NetworkDocumentServiceFactory(port=server.port)
    svc = factory.create_document_service("d2")
    conn = svc.connect_to_delta_stream()
    conn.submit({"n": 1})
    conn.submit({"n": 2})
    deadline = time.monotonic() + 10
    while len(svc.delta_storage.get_deltas()) < 3 and \
            time.monotonic() < deadline:  # join + 2 ops
        time.sleep(0.01)
    msgs = svc.delta_storage.get_deltas()
    assert [m.contents for m in msgs if m.type == MessageType.OP] == \
        [{"n": 1}, {"n": 2}]
    # summary round-trip
    assert svc.summary_storage.get_latest_summary() is None
    svc.summary_storage.upload_summary({"tree": {"a": 1}}, seq=2)
    got = svc.summary_storage.get_latest_summary()
    assert got is not None and got[0] == {"tree": {"a": 1}}
    conn.disconnect()


def test_nack_pushed_over_wire(server):
    factory = NetworkDocumentServiceFactory(port=server.port)
    svc = factory.create_document_service("d3")
    conn = svc.connect_to_delta_stream()
    nacks = []
    conn.on_nack(nacks.append)
    conn._client_seq = 50  # forge a clientSeq gap
    conn.submit({"bad": True})
    deadline = time.monotonic() + 10
    while not nacks and time.monotonic() < deadline:
        time.sleep(0.01)
    assert nacks and nacks[0].client_id == conn.client_id
    conn.disconnect()


def test_signals_bypass_sequencing(server):
    factory = NetworkDocumentServiceFactory(port=server.port)
    svc = factory.create_document_service("d4")
    a = svc.connect_to_delta_stream()
    b = svc.connect_to_delta_stream()
    sigs = []
    b.on_signal(sigs.append)
    a.submit_signal({"cursor": 7})
    deadline = time.monotonic() + 10
    while not sigs and time.monotonic() < deadline:
        time.sleep(0.01)
    assert sigs[0].contents == {"cursor": 7}
    stored_ops = [m for m in svc.delta_storage.get_deltas()
                  if m.type == MessageType.OP]
    assert not stored_ops  # signals are never sequenced or stored
    a.disconnect()
    b.disconnect()


def test_corrupt_frame_rejected(server):
    import socket as socketlib
    with socketlib.create_connection(("127.0.0.1", server.port)) as s:
        frame = bytearray(wire.encode_frame({"t": "connect", "doc": "x"}))
        frame[-1] ^= 0xFF  # corrupt the payload → CRC mismatch
        s.sendall(bytes(frame))
        # server answers with a diagnostic error frame, then drops the
        # connection — and must not crash
        s.settimeout(5)
        err = wire.recv_frame(s)
        assert err["t"] == "error" and "CRC" in err["message"]
        assert s.recv(1024) == b""
    # and still serve new connections
    factory = NetworkDocumentServiceFactory(port=server.port)
    conn = factory.create_document_service("x").connect_to_delta_stream()
    assert conn.client_id > 0
    conn.disconnect()


# ------------------------------------------------- full stack, two processes

SCHEMA = {"initialObjects": {"text": "sharedString"}}


def test_two_client_processes_collaborate(server):
    """Two OS processes co-edit one SharedString through the localhost
    service (one of them disconnects/reconnects mid-session); their final
    texts must converge token-for-token."""
    creator = NetworkClient(port=server.port, enable_summarizer=False)
    _fc, doc_id = creator.create_container(SCHEMA, doc_id="e2e-doc")
    _fc.dispose()

    n_ops = 6
    env = dict(os.environ, JAX_PLATFORMS="cpu")
    procs = [
        subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tests",
                                          "network_worker.py"),
             str(server.port), doc_id, str(i), str(n_ops)]
            + (["--reconnect"] if i == 1 else []),
            stdout=subprocess.PIPE, stderr=subprocess.PIPE, text=True,
            env=env)
        for i in range(2)
    ]
    outs = []
    for p in procs:
        try:
            out, err = p.communicate(timeout=120)
        except subprocess.TimeoutExpired:
            p.kill()
            out, err = p.communicate()
            pytest.fail(f"worker timed out; stderr:\n{err[-2000:]}")
        assert p.returncode == 0, err[-2000:]
        outs.append(json.loads(out.strip().splitlines()[-1]))

    texts = {o["worker"]: o["text"] for o in outs}
    assert texts[0] == texts[1]
    for w in (0, 1):
        for j in range(n_ops):
            assert f"{w}:{j};" in texts[0]


def test_late_reader_sees_converged_text(server):
    """A third client loading AFTER the session reads the same text via
    summary-less catch-up (storage tail replay through the wire)."""
    creator = NetworkClient(port=server.port, enable_summarizer=False)
    fc, doc_id = creator.create_container(SCHEMA, doc_id="late-doc")
    text = fc.initial_objects["text"]
    text.insert_text(0, "hello ")
    text.insert_text(6, "world")
    fc.flush()
    # local edits apply optimistically, so the text predicate alone can be
    # true while an op is still in flight; disposing then loses it (a
    # dirty close drops unacked ops by contract). Wait for the acks too.
    fc.pump_until(lambda: text.get_text() == "hello world"
                  and not fc.container.runtime.pending.has_pending,
                  timeout=15)
    fc.dispose()

    reader = NetworkClient(port=server.port, enable_summarizer=False)
    fc2 = reader.get_container(doc_id, SCHEMA)
    # catch-up is delivered over the wire: pump until the tail replay
    # lands rather than asserting an instantaneous load
    fc2.pump_until(lambda: fc2.initial_objects["text"].get_text()
                   == "hello world", timeout=15)
    assert fc2.initial_objects["text"].get_text() == "hello world"
    fc2.dispose()
