"""Regressions pinned from code-review findings."""

import numpy as np

from fluidframework_tpu.core.protocol import MessageType
from fluidframework_tpu.models import SharedMap, SharedMatrix
from fluidframework_tpu.server.deli import DeliSequencer
from fluidframework_tpu.testing.mocks import MockSequencer, create_connected_dds


def test_deli_clamps_future_ref_seq():
    """An inflated ref_seq must not drive MSN past seq and brick the doc."""
    d = DeliSequencer()
    d.client_join("doc", 1)
    msg, nack = d.sequence("doc", 1, 1, 999_999, MessageType.OP, {})
    assert nack is None and msg.min_seq <= msg.seq
    assert d.sequence("doc", 1, 2, msg.seq, MessageType.OP, {})[1] is None


def test_map_summary_keeps_acked_value_under_pending_shadow():
    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedMap, "m")
    b = create_connected_dds(seqr, SharedMap, "m")
    a.set("x", 1)
    seqr.process_all_messages()
    a.set("x", 2)  # in flight: summary must still carry acked x=1
    summary = a.summarize()
    assert summary["data"] == {"x": 1}
    seqr.process_all_messages()
    assert a.summarize()["data"] == {"x": 2}


def test_matrix_summary_excludes_pending_and_keeps_fww_provenance():
    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedMatrix, "m")
    b = create_connected_dds(seqr, SharedMatrix, "m")
    a.insert_rows(0, 1)
    a.insert_cols(0, 1)
    a.switch_set_cell_policy()
    seqr.process_all_messages()
    a.set_cell(0, 0, "acked")
    seqr.process_all_messages()
    a.set_cell(0, 0, "pending")  # in flight
    summary = a.summarize()
    assert summary["grid"][0][0][0] == "acked"
    assert summary["fww"] is True
    # a loaded replica keeps FWW provenance: a write whose ref predates the
    # acked value must still be rejected
    c = SharedMatrix("m2", 99)
    c.load_core(summary)
    assert c.cell_seq != {} and c.get_cell(0, 0) == "acked"


def test_zamboni_slide_with_coalesce_in_same_pass():
    """Refs on a dead segment must not slide onto a segment the same zamboni
    pass coalesces away (confirmed review repro)."""
    from fluidframework_tpu.models import SharedString
    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedString, "s")
    b = create_connected_dds(seqr, SharedString, "s")
    a.insert_text(0, "abcd")     # one insert -> coalescible halves
    seqr.process_all_messages()
    a.insert_text(2, "X")        # splits abcd -> ab|X|cd
    seqr.process_all_messages()
    a.insert_text(5, "ZZ")
    seqr.process_all_messages()
    iid = a.get_interval_collection("c").add(5, 6)   # anchored on ZZ
    seqr.process_all_messages()
    a.remove_text(2, 3)          # remove X -> ab|cd adjacency restored
    a.remove_text(4, 6)          # remove ZZ (the anchor)
    seqr.process_all_messages()
    for r in (a, b):
        seqr.submit(r, {}, type=MessageType.NOOP)
    seqr.process_all_messages()  # MSN catches up -> zamboni w/ coalesce
    # endpoints must still resolve on every replica (no dangling anchors)
    d1 = a.get_interval_collection("c").digest()
    d2 = b.get_interval_collection("c").digest()
    assert d1 == d2


def test_heartbeat_does_not_pin_doc_to_flat_tier():
    """A heartbeat-only doc must not allocate a flat-tier row: that would
    break a later mark_mega and consume capacity for docs that never carry
    an op (confirmed review repro)."""
    from fluidframework_tpu.server.serving import StringServingEngine
    engine = StringServingEngine(n_docs=1, capacity=64, mega_docs=1,
                                 mega_capacity_per_shard=32)
    engine.connect("bigdoc", 1)
    engine.heartbeat("bigdoc", 1, 0)
    engine.mark_mega("bigdoc")  # must not raise
    assert "bigdoc" not in engine._doc_rows
    # heartbeat-only docs also must not exhaust flat capacity (n_docs=1)
    engine.connect("idle", 2)
    engine.heartbeat("idle", 2, 0)
    assert "idle" not in engine._doc_rows
    engine.connect("real", 3)
    from fluidframework_tpu.models.merge_tree_client import SequenceClient
    c = SequenceClient(3)
    op = c.insert_text_local(0, "hi")
    msg, nack = engine.submit("real", 3, op["clientSeq"], 0, op)
    assert nack is None
    assert engine.read_text("real") == "hi"


def test_interval_docs_stay_batched_until_tombstone_crossing():
    """min_seq advances on an interval-holding doc must NOT split the
    batched dispatch unless the advance actually dooms a tombstone
    (review finding: per-message dispatches in active collaborations)."""
    from fluidframework_tpu.core.protocol import SequencedDocumentMessage
    from fluidframework_tpu.ops.string_store import TensorStringStore

    def mk(seq, min_seq, contents):
        return SequencedDocumentMessage(
            doc_id="d", client_id=1, client_seq=seq, ref_seq=seq - 1,
            seq=seq, min_seq=min_seq, type=MessageType.OP,
            contents=contents)

    store = TensorStringStore(1, capacity=256)
    store.apply_messages(
        [(0, mk(1, 0, {"mt": "insert", "kind": 0, "pos": 0,
                       "text": "hello world"}))])
    store.add_interval(0, 2, 7)

    batches = []
    orig = store._apply_batch
    store._apply_batch = lambda g: (batches.append(len(g)), orig(g))[1]

    # insert-only storm, MSN advancing on every message: one dispatch
    stream = [(0, mk(s, s - 1, {"mt": "insert", "kind": 0, "pos": 0,
                                "text": "x"}))
              for s in range(2, 18)]
    store.apply_messages(stream)
    assert batches == [len(stream)]

    # a remove followed by the MSN crossing it: exactly one split
    batches.clear()
    stream2 = [(0, mk(18, 16, {"mt": "remove", "start": 0, "end": 2}))]
    stream2 += [(0, mk(s, 17, {"mt": "insert", "kind": 0, "pos": 0,
                               "text": "y"})) for s in (19, 20)]
    stream2 += [(0, mk(s, 19, {"mt": "insert", "kind": 0, "pos": 0,
                               "text": "z"})) for s in (21, 22)]
    store.apply_messages(stream2)
    assert len(batches) == 2  # split once, at the min_seq=19>=18 crossing


def test_map_remote_delete_of_absent_key_emits_nothing():
    """Concurrent deletes of the same key: the second remote delete is a
    no-op and must NOT emit a phantom valueChanged (confirmed review
    repro: a third replica saw two events for one logical deletion)."""
    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedMap, "m")
    b = create_connected_dds(seqr, SharedMap, "m")
    c = create_connected_dds(seqr, SharedMap, "m")
    a.set("k", 1)
    seqr.process_all_messages()
    events = []
    c.on("valueChanged", lambda m, k, prev, local: events.append((k, prev)))
    a.delete("k")
    b.delete("k")  # concurrent: sequenced after a's delete
    seqr.process_all_messages()
    assert events == [("k", 1)]


def test_map_undo_restores_stored_none():
    """None is a legal stored value (unlike JS undefined): undo of a set
    over a None-valued key must restore None, not delete the key."""
    from fluidframework_tpu.framework.undo_redo import (
        SharedMapUndoRedoHandler, UndoRedoStackManager)
    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedMap, "m")
    stack = UndoRedoStackManager()
    SharedMapUndoRedoHandler(stack).attach(a)
    a.set("k", None)
    stack.close_current_operation()
    a.set("k", 5)
    stack.close_current_operation()
    seqr.process_all_messages()
    assert stack.undo_operation()
    seqr.process_all_messages()
    assert a.has("k") and a.get("k") is None
    assert stack.undo_operation()
    seqr.process_all_messages()
    assert not a.has("k")


def test_map_engine_nacks_malformed_op_before_logging():
    """A malformed op must be nacked BEFORE sequencing/logging: an
    acked-and-logged op the flush path cannot apply bricks the engine and
    its recovery replay (confirmed review repro)."""
    from fluidframework_tpu.server.deli import NackReason
    from fluidframework_tpu.server.oplog import PartitionedLog
    from fluidframework_tpu.server.serving import MapServingEngine
    log = PartitionedLog(2)
    engine = MapServingEngine(n_docs=1, log=log)
    engine.connect("a", 1)
    for bad in ({"op": "bogus"}, {"op": "set", "key": 7}, "junk", None):
        msg, nack = engine.submit("a", 1, 1, 0, bad)
        assert msg is None and nack.reason == NackReason.MALFORMED
    # the engine keeps working, nothing poisoned the log
    msg, nack = engine.submit("a", 1, 1, 0,
                              {"op": "set", "key": "x", "value": 1})
    assert nack is None
    assert engine.read_doc("a") == {"x": 1}
    engine2 = MapServingEngine.load(engine.summarize(), log)
    assert engine2.read_doc("a") == {"x": 1}


def test_tree_inverse_guards_root_ops_with_undo_attached():
    """remove(root)/move(root) are benign no-ops; attaching an undo handler
    must not turn them into crashes (confirmed review repro: inverse_of
    raised KeyError(None) computing the root's prev sibling)."""
    from fluidframework_tpu.framework.undo_redo import (
        SharedTreeUndoRedoHandler, UndoRedoStackManager)
    from fluidframework_tpu.models import SharedTree
    seqr = MockSequencer()
    t = create_connected_dds(seqr, SharedTree, "t")
    stack = UndoRedoStackManager()
    SharedTreeUndoRedoHandler(stack).attach(t)
    t.remove("root")
    t.move("root", "root", "f")
    seqr.process_all_messages()
    assert t.has_node("root")


def test_engine_capacity_nacked_before_logging():
    """Capacity overflows (doc rows, key slots) and unserializable values
    must be nacked BEFORE the op reaches the durable log — a logged op the
    flush path cannot apply bricks the engine and all recovery (confirmed
    review repros)."""
    from fluidframework_tpu.server.deli import NackReason
    from fluidframework_tpu.server.oplog import PartitionedLog
    from fluidframework_tpu.server.serving import MapServingEngine
    log = PartitionedLog(2)
    engine = MapServingEngine(n_docs=1, n_keys=2, log=log)
    engine.connect("a", 1)
    engine.submit("a", 1, 1, 0, {"op": "set", "key": "k0", "value": 0})
    # doc capacity: a second doc's op is nacked, not logged
    engine.connect("b", 1)
    msg, nack = engine.submit("b", 1, 1, 0,
                              {"op": "set", "key": "k", "value": 1})
    assert msg is None and nack.reason == NackReason.CAPACITY
    # key capacity: third distinct key nacked, not logged
    engine.submit("a", 1, 2, 0, {"op": "set", "key": "k1", "value": 1})
    msg, nack = engine.submit("a", 1, 3, 0,
                              {"op": "set", "key": "k2", "value": 2})
    assert msg is None and nack.reason == NackReason.CAPACITY
    # unserializable value nacked as malformed
    msg, nack = engine.submit("a", 1, 3, 0,
                              {"op": "set", "key": "k0", "value": object()})
    assert msg is None and nack.reason == NackReason.MALFORMED
    # engine healthy; recovery replays the log without poison
    assert engine.read_doc("a") == {"k0": 0, "k1": 1}
    engine2 = MapServingEngine.load(engine.summarize(), log)
    assert engine2.read_doc("a") == {"k0": 0, "k1": 1}


def test_native_log_concurrent_appends_keep_framing():
    """Two threads appending to one partition must not tear frames (the
    reopen CRC scan would silently truncate acked records)."""
    import tempfile
    import threading
    from fluidframework_tpu.server.native_oplog import (
        NativePartitionedLog, available)
    if not available():
        import pytest
        pytest.skip("native oplog not built")
    d = tempfile.mkdtemp()
    log = NativePartitionedLog(d, 1)
    N = 200
    def writer(tag):
        for i in range(N):
            log.append(0, {"t": tag, "i": i, "pad": "x" * (i % 50)})
    threads = [threading.Thread(target=writer, args=(t,)) for t in "ab"]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    log.sync()
    log.close()
    back = list(NativePartitionedLog(d, 1).read(0))
    assert len(back) == 2 * N  # nothing torn, nothing truncated
    for tag in "ab":
        assert [r["i"] for r in back if r["t"] == tag] == list(range(N))


def test_merge_tree_summary_preserves_handles():
    """Segment handles are payload/key identity — the matrix permutation
    axes resolve row/col KEYS through them, so a summary that drops them
    breaks serving-engine recovery (caught by the matrix e2e drive: a
    recovered engine resolved every axis position to a zeroed key)."""
    from fluidframework_tpu.core.constants import NO_CLIENT
    from fluidframework_tpu.models.merge_tree import (
        MergeTree, SegmentKind)
    t = MergeTree(1)
    t.insert(0, SegmentKind.TEXT, "abc", 1, 1, 0, handle=(42, 0))
    t.insert(3, SegmentKind.TEXT, "def", 2, 1, 1, handle=(99, 5))
    clone = MergeTree.load(t.summarize(), local_client=NO_CLIENT)
    assert [s.handle for s in clone.segments] == [(42, 0), (99, 5)]


def test_matrix_engine_nacks_malformed_structure_before_logging():
    """Every field the matrix flush path touches must be validated before
    the op is logged (confirmed review repro: opKey=5 raised TypeError in
    flush forever, and recovery replayed the poison)."""
    from fluidframework_tpu.server.deli import NackReason
    from fluidframework_tpu.server.oplog import PartitionedLog
    from fluidframework_tpu.server.serving import MatrixServingEngine
    log = PartitionedLog(2)
    engine = MatrixServingEngine(n_docs=1, cell_capacity=256, log=log)
    engine.connect("m", 7)
    bad_ops = [
        {"mx": "insRow", "pos": 0, "count": 1, "opKey": 5},
        {"mx": "insRow", "pos": "x", "count": 1, "opKey": (7, 1)},
        {"mx": "insRow", "pos": 0, "count": 10**9, "opKey": (7, 1)},
        {"mx": "rmRow", "start": 0, "count": 0},
        {"mx": "setCell", "row": None, "col": 0, "value": 1},
        {"mx": "setCell", "row": 0, "col": 0, "value": object()},
    ]
    for bad in bad_ops:
        msg, nack = engine.submit("m", 7, 1, 0, bad)
        assert msg is None and nack.reason == NackReason.MALFORMED, bad
    # engine healthy afterwards, recovery clean
    msg, nack = engine.submit("m", 7, 1, 0, {"mx": "insRow", "pos": 0,
                                             "count": 2, "opKey": (7, 1)})
    assert nack is None
    engine.submit("m", 7, 2, msg.seq, {"mx": "insCol", "pos": 0, "count": 1,
                                       "opKey": (7, 2)})
    engine.submit("m", 7, 3, msg.seq, {"mx": "setCell", "row": 1, "col": 0,
                                       "value": "ok"})
    assert engine.get_cell("m", 1, 0) == "ok"
    engine2 = MatrixServingEngine.load(engine.summarize(), log)
    assert engine2.get_cell("m", 1, 0) == "ok"


def test_matrix_engine_nacks_cell_capacity_before_logging():
    """An acked setCell must never be silently dropped by device-table
    truncation (confirmed review repro: 16 acked writes, 8 read back None).
    Admission reserves cell capacity and nacks CAPACITY past the bound."""
    from fluidframework_tpu.server.deli import NackReason
    from fluidframework_tpu.server.oplog import PartitionedLog
    from fluidframework_tpu.server.serving import MatrixServingEngine
    log = PartitionedLog(2)
    engine = MatrixServingEngine(n_docs=1, cell_capacity=8, log=log,
                                 batch_window=64)
    engine.connect("m", 7)
    seen = 0
    def submit(cs, op):
        nonlocal seen
        msg, nack = engine.submit("m", 7, cs, seen, op)
        if msg is not None:
            seen = msg.seq
        return msg, nack
    submit(1, {"mx": "insRow", "pos": 0, "count": 16, "opKey": (7, 1)})
    submit(2, {"mx": "insCol", "pos": 0, "count": 1, "opKey": (7, 2)})
    acked, nacked = [], 0
    for i in range(16):
        msg, nack = submit(3 + i, {"mx": "setCell", "row": i, "col": 0,
                                   "value": f"v{i}"})
        if nack is None:
            acked.append(i)
        else:
            assert nack.reason == NackReason.CAPACITY
            nacked += 1
    assert nacked > 0
    # EVERY acked write is readable — no silent loss
    for i in acked:
        assert engine.get_cell("m", i, 0) == f"v{i}", i
    assert not engine.overflowed()
    # and recovery preserves them all
    engine2 = MatrixServingEngine.load(engine.summarize(), log)
    for i in acked:
        assert engine2.get_cell("m", i, 0) == f"v{i}", i


def test_replay_tail_orders_join_before_columnar_ops():
    """A client that joins after the base summary, whose columnar ops land
    in an earlier-scanned partition than its JOIN (whole-batch records
    round-robin; JOINs stay in the doc's partition), must survive recovery
    with its sequencer state intact. Pre-fix, partition-scan replay fed the
    ops before the JOIN: they were skipped (unknown client) and the late
    JOIN reset ClientState to last_client_seq=0 — the next legitimate op
    was CLIENT_SEQ_GAP-nacked forever and resent old clientSeqs were
    re-accepted (dedupe broken)."""
    import pytest

    from fluidframework_tpu.server import native_deli
    if not native_deli.available():
        pytest.skip("native sequencer unavailable")
    from fluidframework_tpu.ops.schema import OpKind
    from fluidframework_tpu.server.deli import NackReason
    from fluidframework_tpu.server.oplog import partition_of
    from fluidframework_tpu.server.serving import StringServingEngine

    eng = StringServingEngine(n_docs=4, capacity=256, batch_window=10 ** 9,
                              sequencer="native", n_partitions=8)
    # a doc whose own partition is scanned AFTER partition 0, where the
    # first whole-batch columnar record lands
    doc = next(f"doc-{i}" for i in range(64)
               if partition_of(f"doc-{i}", 8) > 0)
    eng.connect(doc, 1)
    summary = eng.summarize()
    eng.connect(doc, 2)  # joins AFTER the base summary
    O = 4
    rows = np.array([eng.doc_row(doc)], np.int32)
    kind = np.full((1, O), int(OpKind.STR_INSERT), np.int32)
    zeros = np.zeros((1, O), np.int32)
    cseq = np.arange(1, O + 1, dtype=np.int32).reshape(1, O)
    client = np.full((1, O), 2, np.int32)
    res = eng.ingest_planes(rows, client, cseq, zeros, kind, zeros, zeros,
                            "ab")
    assert res["nacked"] == 0
    want = eng.read_text(doc)

    restored = StringServingEngine.load(summary, eng.log)
    assert restored.read_text(doc) == want
    # the client's next op is accepted: ClientState survived the replay
    msg, nack = restored.submit(
        doc, 2, O + 1, 0, {"mt": "insert", "kind": 0, "pos": 0, "text": "Z"})
    assert nack is None and msg is not None
    # and a resent old clientSeq is still deduped, not re-applied
    _, nack = restored.submit(
        doc, 2, 1, 0, {"mt": "insert", "kind": 0, "pos": 0, "text": "Z"})
    assert nack is not None and nack.reason == NackReason.DUPLICATE
