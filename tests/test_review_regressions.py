"""Regressions pinned from code-review findings."""

import numpy as np

from fluidframework_tpu.core.protocol import MessageType
from fluidframework_tpu.models import SharedMap, SharedMatrix
from fluidframework_tpu.server.deli import DeliSequencer
from fluidframework_tpu.testing.mocks import MockSequencer, create_connected_dds


def test_deli_clamps_future_ref_seq():
    """An inflated ref_seq must not drive MSN past seq and brick the doc."""
    d = DeliSequencer()
    d.client_join("doc", 1)
    msg, nack = d.sequence("doc", 1, 1, 999_999, MessageType.OP, {})
    assert nack is None and msg.min_seq <= msg.seq
    assert d.sequence("doc", 1, 2, msg.seq, MessageType.OP, {})[1] is None


def test_map_summary_keeps_acked_value_under_pending_shadow():
    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedMap, "m")
    b = create_connected_dds(seqr, SharedMap, "m")
    a.set("x", 1)
    seqr.process_all_messages()
    a.set("x", 2)  # in flight: summary must still carry acked x=1
    summary = a.summarize()
    assert summary["data"] == {"x": 1}
    seqr.process_all_messages()
    assert a.summarize()["data"] == {"x": 2}


def test_matrix_summary_excludes_pending_and_keeps_fww_provenance():
    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedMatrix, "m")
    b = create_connected_dds(seqr, SharedMatrix, "m")
    a.insert_rows(0, 1)
    a.insert_cols(0, 1)
    a.switch_set_cell_policy()
    seqr.process_all_messages()
    a.set_cell(0, 0, "acked")
    seqr.process_all_messages()
    a.set_cell(0, 0, "pending")  # in flight
    summary = a.summarize()
    assert summary["grid"][0][0][0] == "acked"
    assert summary["fww"] is True
    # a loaded replica keeps FWW provenance: a write whose ref predates the
    # acked value must still be rejected
    c = SharedMatrix("m2", 99)
    c.load_core(summary)
    assert c.cell_seq != {} and c.get_cell(0, 0) == "acked"


def test_zamboni_slide_with_coalesce_in_same_pass():
    """Refs on a dead segment must not slide onto a segment the same zamboni
    pass coalesces away (confirmed review repro)."""
    from fluidframework_tpu.models import SharedString
    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedString, "s")
    b = create_connected_dds(seqr, SharedString, "s")
    a.insert_text(0, "abcd")     # one insert -> coalescible halves
    seqr.process_all_messages()
    a.insert_text(2, "X")        # splits abcd -> ab|X|cd
    seqr.process_all_messages()
    a.insert_text(5, "ZZ")
    seqr.process_all_messages()
    iid = a.get_interval_collection("c").add(5, 6)   # anchored on ZZ
    seqr.process_all_messages()
    a.remove_text(2, 3)          # remove X -> ab|cd adjacency restored
    a.remove_text(4, 6)          # remove ZZ (the anchor)
    seqr.process_all_messages()
    for r in (a, b):
        seqr.submit(r, {}, type=MessageType.NOOP)
    seqr.process_all_messages()  # MSN catches up -> zamboni w/ coalesce
    # endpoints must still resolve on every replica (no dangling anchors)
    d1 = a.get_interval_collection("c").digest()
    d2 = b.get_interval_collection("c").digest()
    assert d1 == d2
