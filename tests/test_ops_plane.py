"""Live operations plane (ISSUE 17): scrape endpoint under load,
per-stage latency attribution, heavy-hitter sketch accuracy.

Covers the acceptance criteria end to end: every route of the in-process
ops endpoint answers — with bounded latency and no deadlock — while a
real columnar ingress storm is running; the Prometheus exposition
survives a STRICT scraper-grammar parse including label-value escaping
(backslash, double quote, newline) and round-trips through the live
``tools/healthz.py`` parser; the telescoping stage histograms sum to the
observed end-to-end ack latency within the 10% tolerance (exactly, by
construction); and the Space-Saving sketch honors its overestimate/
guaranteed-tracking bounds against exact counts on Zipf traffic.
"""

import importlib.util
import json
import os
import random
import re
import threading
import time
import urllib.request

import numpy as np
import pytest

from fluidframework_tpu.server import native_deli, opsd
from fluidframework_tpu.server.opsd import (
    STAGES, OpsServer, SpaceSaving, latency_breakdown,
    observe_window_timeline,
)
from fluidframework_tpu.utils import telemetry
from fluidframework_tpu.utils.telemetry import (
    MetricsCollector, MetricsRegistry, PROM_CONTENT_TYPE,
)

pytestmark = [pytest.mark.opsplane, pytest.mark.telemetry]

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _tool(name):
    """Load a tools/*.py script as a module (tools/ is not a package)."""
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def _get(url, timeout=10.0):
    """(status, content_type, body_bytes) — the scraper's eye view."""
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.status, resp.headers.get("Content-Type"), resp.read()


# ----------------------------------------------------- strict exposition

#: the text-format grammar a strict scraper enforces: metric names,
#: label names, and label values where ONLY \\ \" \n escapes may carry
#: backslash / quote / newline
_NAME = r"[a-zA-Z_:][a-zA-Z0-9_:]*"
_LABEL = r'[a-zA-Z_][a-zA-Z0-9_]*="(?:[^"\\\n]|\\\\|\\"|\\n)*"'
_SAMPLE = re.compile(
    rf"^({_NAME})(?:\{{{_LABEL}(?:,{_LABEL})*\}})? (\S+)$")
_COMMENT = re.compile(rf"^# (?:TYPE {_NAME} (?:counter|gauge|histogram)"
                      rf"|HELP {_NAME} .*)$")


class TestPrometheusExposition:
    def _nasty_registry(self):
        reg = MetricsRegistry()
        reg.inc("ops_ingested", 41)
        reg.set_gauge("queue_depth", 7.0)
        reg.observe("ack_ms", 3.0)
        reg.observe("ack_ms", 9.0)
        coll = MetricsCollector()
        # every character class the escaper must handle, in one value
        coll.inc("ingress_ops", 5)
        reg.attach("alfred", coll,
                   labels={"door": 'col"umn\\ar\nx', "shard": "3"})
        # attachments are weakrefs: pin the collector to the registry's
        # lifetime or it vanishes from the exposition mid-test
        reg._test_pin = coll
        return reg

    def test_every_line_matches_strict_scraper_grammar(self):
        text = self._nasty_registry().render_prometheus()
        assert text.endswith("\n")
        for line in text.splitlines():
            if line.startswith("#"):
                assert _COMMENT.match(line), line
                continue
            m = _SAMPLE.match(line)
            assert m, f"unparseable sample line: {line!r}"
            float(m.group(2))   # the value must be a number

    def test_label_escaping_is_exactly_the_three_escapes(self):
        text = self._nasty_registry().render_prometheus()
        [line] = [ln for ln in text.splitlines()
                  if ln.startswith("ingress_ops")]
        assert r'door="col\"umn\\ar\nx"' in line
        assert "\n" not in line  # the raw newline never leaks

    def test_histogram_emits_sum_count_and_monotone_buckets(self):
        reg = self._nasty_registry()
        lines = reg.render_prometheus().splitlines()
        assert "ack_ms_sum 12.0" in lines
        assert "ack_ms_count 2" in lines
        cums = [int(ln.rsplit(" ", 1)[1]) for ln in lines
                if ln.startswith("ack_ms_bucket")]
        assert cums == sorted(cums) and cums[-1] == 2

    def test_healthz_parser_round_trips_escaped_labels(self):
        healthz = _tool("healthz")
        text = self._nasty_registry().render_prometheus()
        metrics, kinds = healthz.parse_prometheus(text)
        assert metrics["ops_ingested"] == 41.0
        assert kinds["ops_ingested"] == "counter"
        assert metrics["queue_depth"] == 7.0
        assert kinds["queue_depth"] == "gauge"
        # the component key carries the UNESCAPED label value back
        key = 'alfred{door=col"umn\\ar\nx,shard=3}.ingress_ops'
        assert metrics[key] == 5.0
        # histogram accumulators survive as counters, buckets dropped
        assert metrics["ack_ms_sum"] == 12.0
        assert kinds["ack_ms_sum"] == "counter"
        assert not any(k.endswith("_bucket") for k in metrics)


# --------------------------------------------------- stage attribution

class TestStageAttribution:
    def _observe(self, reg, stage_ms):
        """Observe one synthetic window whose 8 stage durations (ms)
        are exactly ``stage_ms``."""
        t = 100.0
        crossings = [t]
        for ms in stage_ms:
            t += ms * 1e-3
            crossings.append(t)
        tl = {"t_rx": crossings[0], "t_drain0": crossings[1],
              "admit_ms": stage_ms[2], "t_ready": crossings[3]}
        marks = {"pack1": crossings[4], "seq1": crossings[5],
                 "disp1": crossings[6], "log1": crossings[7]}
        observe_window_timeline(tl, marks, crossings[8], registry=reg)

    def test_stages_sum_to_e2e_exactly(self):
        reg = MetricsRegistry()
        rng = random.Random(17)
        for _ in range(50):
            self._observe(reg, [rng.uniform(0.1, 5.0) for _ in STAGES])
        bd = latency_breakdown(reg)
        assert bd["windows"] == 50
        assert set(bd["stages"]) == set(STAGES)
        # the acceptance tolerance is 10%; the construction is exact
        assert bd["e2e_mean_ms"] > 0
        assert abs(bd["stage_sum_ms"] - bd["e2e_mean_ms"]) \
            <= 0.10 * bd["e2e_mean_ms"]
        assert abs(bd["coverage"] - 1.0) < 1e-6
        assert abs(sum(r["share"] for r in bd["stages"].values())
                   - 1.0) < 1e-6

    def test_known_durations_land_in_their_stages(self):
        reg = MetricsRegistry()
        ms = [1.0, 2.0, 3.0, 4.0, 5.0, 6.0, 7.0, 8.0]
        self._observe(reg, ms)
        for name, want in zip(STAGES, ms):
            h = reg.histograms[f"stage_{name}_ms"]
            assert h.n == 1
            assert abs(h.mean - want) < 1e-6, name
        assert abs(reg.histograms["stage_e2e_ack_ms"].mean
                   - sum(ms)) < 1e-6

    def test_skewed_marks_clamp_never_negative(self):
        reg = MetricsRegistry()
        tl = {"t_rx": 10.0, "t_drain0": 9.0,       # rx after drain?!
              "admit_ms": 5000.0, "t_ready": 10.001}
        marks = {"pack1": 10.0005, "seq1": 10.2,
                 "disp1": 10.1, "log1": 10.3}      # disp before seq
        observe_window_timeline(tl, marks, 10.25, registry=reg)
        for name in STAGES:
            h = reg.histograms[f"stage_{name}_ms"]
            assert h.n == 1 and h.sum_ms >= 0.0, name
        bd = latency_breakdown(reg)
        assert abs(bd["coverage"] - 1.0) < 1e-6

    def test_missing_marks_degrade_to_zero_width_stages(self):
        reg = MetricsRegistry()
        tl = {"t_rx": 1.0, "t_drain0": 1.001, "t_ready": 1.002}
        observe_window_timeline(tl, {}, 1.010, registry=reg)
        bd = latency_breakdown(reg)
        assert abs(bd["e2e_mean_ms"] - 10.0) < 1e-6
        assert abs(bd["coverage"] - 1.0) < 1e-6
        # everything after t_ready collapses into the ack stage
        assert abs(reg.histograms["stage_ack_ms"].mean - 8.0) < 1e-6


# ------------------------------------------------------- space-saving

class TestSpaceSaving:
    def test_zipf_accuracy_vs_exact_counts(self):
        rng = random.Random(7)
        n_keys, capacity, draws = 400, 64, 30_000
        weights = [1.0 / (k + 1) ** 1.2 for k in range(n_keys)]
        sk = SpaceSaving(capacity=capacity)
        exact = {}
        for _ in range(draws):
            key = rng.choices(range(n_keys), weights=weights)[0]
            exact[key] = exact.get(key, 0) + 1
            sk.offer(key)
        assert sk.total == draws and len(sk) == capacity
        rows = {key: (est, err) for key, est, err in sk.top(capacity)}
        for key, (est, err) in rows.items():
            true = exact.get(key, 0)
            # the Space-Saving contract: est overestimates by <= err
            assert true <= est <= true + err, (key, true, est, err)
        # every key above the total/capacity threshold IS tracked
        threshold = draws / capacity
        for key, true in exact.items():
            if true > threshold:
                assert key in rows, (key, true, threshold)
        # the sketch's top-10 contains the true top-5 heavy hitters
        true_top5 = sorted(exact, key=exact.get, reverse=True)[:5]
        sketch_top10 = [key for key, _, _ in sk.top(10)]
        assert set(true_top5) <= set(sketch_top10)

    def test_bounded_memory_and_concurrent_offers(self):
        sk = SpaceSaving(capacity=16)
        def pound(seed):
            r = random.Random(seed)
            for _ in range(5000):
                sk.offer(("doc-%d" % r.randrange(200), "t"))
        threads = [threading.Thread(target=pound, args=(s,))
                   for s in range(4)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sk.total == 4 * 5000
        assert len(sk) <= 16
        sk.clear()
        assert len(sk) == 0 and sk.total == 0


# ------------------------------------------------------- the endpoint

class TestOpsServerRoutes:
    def test_all_routes_serve_and_ticker_ticks(self):
        reg = MetricsRegistry()
        reg.inc("ops_ingested", 3)
        sk = SpaceSaving(capacity=8)
        sk.offer(("d0", "acme"), 5)
        with OpsServer(registry=reg, tick_interval_s=0.05) as ops:
            ops.add_hotdocs(sk)
            status, ctype, body = _get(ops.url + "/metrics")
            assert status == 200 and ctype == PROM_CONTENT_TYPE
            assert b"ops_ingested 3" in body
            status, ctype, body = _get(ops.url + "/healthz")
            assert status == 200 and "application/json" in ctype
            health = json.loads(body)
            assert {"ok", "rows", "ticks", "uptime_s"} <= set(health)
            hot = json.loads(_get(ops.url + "/debug/hotdocs?k=5")[2])
            assert hot["top"][0] == {"doc": "d0", "tenant": "acme",
                                     "count": 5, "err": 0}
            for route in ("/debug/flights", "/debug/trace",
                          "/debug/latency"):
                status, _, body = _get(ops.url + route)
                assert status == 200
                json.loads(body)
            deadline = time.time() + 5.0
            while ops.ticks < 2 and time.time() < deadline:
                time.sleep(0.02)
            assert ops.ticks >= 2           # the ticker thread is live
            assert ops.store.names()        # ... and sampling
            assert reg.gauges["hotdoc_top_count"] == 5.0

    def test_unknown_route_404s_with_route_list(self):
        with OpsServer(registry=MetricsRegistry(),
                       tick_interval_s=0) as ops:
            try:
                urllib.request.urlopen(ops.url + "/nope", timeout=5)
                raise AssertionError("expected 404")
            except urllib.error.HTTPError as e:
                assert e.code == 404
                assert "/metrics" in json.loads(e.read())["routes"]


# ------------------------------------------- the storm (acceptance)

needs_native = pytest.mark.skipif(not native_deli.available(),
                                  reason="native sequencer unavailable")


@needs_native
class TestScrapeUnderIngestStorm:
    def test_live_scrape_during_columnar_storm(self):
        from fluidframework_tpu.server.columnar_ingress import (
            ColumnarAlfred, ColumnarClient, _OP_DTYPE,
        )
        from fluidframework_tpu.server.serving import StringServingEngine
        eng = StringServingEngine(n_docs=32, capacity=256,
                                  batch_window=10 ** 9,
                                  sequencer="native")
        srv = ColumnarAlfred(eng, window_min_rows=4,
                             window_ms=2.0).start_in_thread()
        ops = srv.start_ops(tick_interval_s=0.1)
        routes = ("/metrics", "/healthz", "/debug/hotdocs",
                  "/debug/latency", "/debug/flights", "/debug/trace")
        stop = threading.Event()
        lat, errors = [], []

        def scraper():
            i = 0
            while not stop.is_set():
                route = routes[i % len(routes)]
                i += 1
                t0 = time.perf_counter()
                try:
                    status, _, _ = _get(ops.url + route)
                    assert status == 200
                except Exception as e:          # noqa: BLE001
                    errors.append((route, repr(e)))
                lat.append(time.perf_counter() - t0)
                time.sleep(0.01)

        threads = [threading.Thread(target=scraper, daemon=True)
                   for _ in range(2)]
        try:
            for t in threads:
                t.start()
            n_clients, docs_per, waves = 3, 4, 10
            clients = []
            for c in range(n_clients):
                cl = ColumnarClient("127.0.0.1", srv.port)
                docs = [f"c{c}-d{j}" for j in range(docs_per)]
                cl.join(docs)
                clients.append((cl, docs))
            for w in range(waves):
                for cl, docs in clients:
                    rows = [cl.rows[d] for d in docs]
                    o = np.zeros(docs_per, _OP_DTYPE)
                    o["row"] = rows
                    o["cseq"] = w + 1
                    cl.send_ops([f"t{w}."], o)
            for cl, docs in clients:
                acked = 0
                while acked < docs_per * waves:
                    resp = cl.recv_json()
                    assert resp["t"] == "acks", resp
                    acked += len(resp["acks"])
                cl.close()
            stop.set()
            for t in threads:
                t.join(timeout=10)
            # the endpoint never deadlocked and stayed bounded while
            # the ingest loop was storming
            assert not errors, errors[:3]
            assert len(lat) >= 10
            assert max(lat) < 5.0
            # acceptance: the per-stage breakdown sums to the observed
            # e2e ack latency within 10% on the storm workload
            bd = json.loads(_get(ops.url + "/debug/latency")[2])
            assert bd["windows"] > 0
            assert bd["e2e_mean_ms"] > 0
            assert abs(bd["stage_sum_ms"] - bd["e2e_mean_ms"]) \
                <= 0.10 * bd["e2e_mean_ms"]
            assert set(bd["stages"]) == set(STAGES)
            # the drain-pass sketch saw exactly the ingested ops (all
            # (doc, tenant) keys fit: no evictions, err == 0)
            hot = json.loads(_get(ops.url + "/debug/hotdocs?k=64")[2])
            assert hot["total_ops"] == srv.ops_ingested
            assert sum(r["count"] for r in hot["top"]) \
                == srv.ops_ingested
            assert all(r["err"] == 0 for r in hot["top"])
        finally:
            stop.set()
            srv.stop()

    def test_healthz_cli_live_mode_against_storm_server(self, capsys):
        from fluidframework_tpu.server.columnar_ingress import (
            ColumnarAlfred, ColumnarClient, _OP_DTYPE,
        )
        from fluidframework_tpu.server.serving import StringServingEngine
        healthz = _tool("healthz")
        eng = StringServingEngine(n_docs=8, capacity=128,
                                  batch_window=10 ** 9,
                                  sequencer="native")
        srv = ColumnarAlfred(eng, window_min_rows=1,
                             window_ms=2.0).start_in_thread()
        ops = srv.start_ops(tick_interval_s=0.05)
        try:
            cl = ColumnarClient("127.0.0.1", srv.port)
            cl.join(["d0"])
            o = np.zeros(1, _OP_DTYPE)
            o["row"] = cl.rows["d0"]
            o["cseq"] = 1
            cl.send_ops(["x"], o)
            assert cl.recv_json()["t"] == "acks"
            cl.close()
            rc = healthz.main(["--url", ops.url,
                               "--interval", "0.05", "--polls", "3"])
            out = capsys.readouterr().out
            assert "SLO" in out            # the scorecard rendered
            assert "ops_" in out           # live sparklines rendered
            assert rc in (0, 1)            # a judged verdict, not a crash
        finally:
            srv.stop()
