"""Overload protection plane (ISSUE 16): token-bucket admission, AIMD
control policy, THROTTLED handling in both resilient drivers, and the
multi-tenant simulator's acceptance gates.

The contract under test: **shed work is never silently dropped and
never burns a clientSeq** — a throttled op is parked client-side and
resubmitted with the SAME number after the hinted backoff, so the
durable stream stays gapless and exactly-once even while the admission
plane refuses most of the offered load.
"""

import importlib.util
import os
import random
import socket
import sys
import time

import pytest

from fluidframework_tpu.drivers.resilient import (
    ResilientColumnarClient, ResilientConnection,
)
from fluidframework_tpu.core.protocol import MessageType
from fluidframework_tpu.server import native_deli, wire
from fluidframework_tpu.server.admission import (
    Admission, AdmissionController, ControlPolicy, TokenBucket,
)
from fluidframework_tpu.server.ingress import AlfredServer
from fluidframework_tpu.server.tinylicious import LocalService
from fluidframework_tpu.utils.backoff import Backoff

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

pytestmark = pytest.mark.overload


def _tool(name):
    spec = importlib.util.spec_from_file_location(
        name, os.path.join(REPO, "tools", f"{name}.py"))
    mod = importlib.util.module_from_spec(spec)
    # visible in sys.modules BEFORE exec: the tool's dataclasses
    # resolve string annotations through sys.modules[cls.__module__]
    sys.modules[name] = mod
    spec.loader.exec_module(mod)
    return mod


# ------------------------------------------------------------ token bucket


class TestTokenBucket:
    def test_prefix_grant_consumes_exactly_what_it_grants(self):
        tb = TokenBucket(10.0, burst=5.0)
        assert tb.grant(3, now=0.0) == 3          # burst covers it
        assert tb.grant(4, now=0.0) == 2          # prefix of the rest
        assert tb.grant(1, now=0.0) == 0          # empty
        assert tb.grant(5, now=1.0) == 5          # 10/s refill for 1s

    def test_refill_caps_at_burst(self):
        tb = TokenBucket(100.0, burst=4.0)
        tb.grant(4, now=0.0)
        assert tb.grant(100, now=10.0) == 4       # never past burst

    def test_scale_multiplies_rate_and_burst(self):
        tb = TokenBucket(10.0, burst=10.0)
        tb.grant(10, now=0.0)
        # half scale: 5/s refill against a 5-token ceiling
        assert tb.grant(100, now=1.0, scale=0.5) == 5

    def test_retry_after_math_floor_and_cap(self):
        tb = TokenBucket(10.0, burst=2.0)
        assert tb.retry_after_ms(1, now=0.0) == 5.0        # have tokens
        tb.grant(2, now=0.0)
        assert tb.retry_after_ms(1, now=0.0) == \
            pytest.approx(100.0)                           # 1 / 10/s
        assert tb.retry_after_ms(1000, now=0.0) == 2000.0  # ceiling

    def test_rejects_nonpositive_rate(self):
        with pytest.raises(ValueError):
            TokenBucket(0.0)


# ----------------------------------------------------- admission controller


class TestAdmissionController:
    def _adm(self, **kw):
        return AdmissionController(rng=random.Random(7), **kw)

    def test_prefix_grant_and_retry_hint(self):
        adm = self._adm(tenants={"t": 10.0})
        adm.bind("c1", "t")
        res = adm.admit("c1", "d", 14, now=0.0)
        assert isinstance(res, Admission)
        assert res.admitted == 10 and res.reason == "budget"
        assert res.retry_after_ms >= 5.0
        assert adm.snapshot()["tenants"]["t"] == \
            {"admitted": 10, "shed": 4}

    def test_unknown_tenant_without_default_is_unbudgeted(self):
        adm = self._adm()
        assert adm.admit("nobody", "d", 1000, now=0.0).admitted == 1000

    def test_default_rate_auto_buckets_new_tenants(self):
        adm = self._adm(default_rate=5.0)
        adm.bind("c1", "fresh")
        assert adm.admit("c1", "d", 9, now=0.0).admitted == 5

    def test_doc_bucket_refunds_tenant_tokens(self):
        adm = self._adm(tenants={"t": 100.0})
        adm.bind("c1", "t")
        adm.set_doc_rate("hot", 100.0, burst=2.0)
        res = adm.admit("c1", "hot", 5, now=0.0)
        assert res.admitted == 2 and res.reason == "doc_budget"
        # the 3 doc-shed ops must not stay charged to the tenant
        assert adm._tenant_bucket["t"].tokens == pytest.approx(98.0)

    def test_inflight_gate_sheds_whole_batch(self):
        adm = self._adm(max_inflight_ops=5)
        res = adm.admit("c1", "d", 3, backlog=6, now=0.0)
        assert res.admitted == 0 and res.reason == "inflight"

    def test_deadline_shed_needs_evidence(self):
        adm = self._adm(deadline_ms=50.0)
        # estimator unfed: absence of evidence never sheds
        assert adm.admit("c1", "d", 1, backlog=10 ** 6,
                         now=0.0).admitted == 1
        adm.note_served(10, now=0.0)
        adm.note_served(10, now=1.0)              # EWMA ~10 ops/s
        res = adm.admit("c1", "d", 1, backlog=100, now=1.0)
        assert res.admitted == 0 and res.reason == "deadline"
        # per-op deadline overrides the default budget
        assert adm.admit("c1", "d", 1, backlog=100, now=1.0,
                         deadline_ms=60_000.0).admitted == 1

    def test_pressure_gate_is_seeded_and_scaled(self):
        adm = self._adm(tenants={"t": 1000.0})
        adm.bind("c1", "t")
        adm.set_pressure(shed_probability=1.0)
        res = adm.admit("c1", "d", 4, now=0.0)
        assert res.admitted == 0 and res.reason == "pressure"
        # quarter scale: refill rate AND ceiling shrink to 250/s / 250
        adm2 = self._adm(tenants={"t": 1000.0})
        adm2.bind("c1", "t")
        adm2.admit("c1", "d", 1000, now=0.0)      # drain initial burst
        adm2.set_pressure(scale=0.25)
        assert adm2.admit("c1", "d", 1000, now=1.0).admitted == 250

    def test_retry_after_ms_is_pure(self):
        adm = self._adm(tenants={"t": 10.0})
        adm.bind("c1", "t")
        before = adm._tenant_bucket["t"].tokens
        hint = adm.retry_after_ms("c1", "d", n=100, now=0.0)
        assert hint > 5.0
        assert adm._tenant_bucket["t"].tokens == before
        assert adm.snapshot()["shed_total"] == 0


# --------------------------------------------------------- control policy


class _FakeEngine:
    """SLOEngine stand-in: one judged objective, burn switchable."""

    def __init__(self):
        self.burning = True

    def scorecard(self, now=None):
        return [{"slo": "ack_p99", "judged": True,
                 "ok": not self.burning}]


class TestControlPolicy:
    def test_aimd_brakes_multiplicatively_recovers_additively(self):
        adm = AdmissionController()
        eng = _FakeEngine()
        pol = ControlPolicy(adm, eng)
        pol.tick()
        assert adm.scale == pytest.approx(0.5)
        assert adm.shed_probability == pytest.approx(0.2)
        pol.tick()
        assert adm.scale == pytest.approx(0.25)
        assert adm.shed_probability == pytest.approx(0.4)
        eng.burning = False
        pol.tick()
        assert adm.scale == pytest.approx(0.35)
        assert adm.shed_probability == pytest.approx(0.2)
        assert pol.ticks == 3 and pol.breach_ticks == 2
        assert pol.min_scale_seen == pytest.approx(0.25)
        assert pol.max_shed_seen == pytest.approx(0.4)

    def test_floors_and_ceilings_hold(self):
        adm = AdmissionController()
        eng = _FakeEngine()
        pol = ControlPolicy(adm, eng, min_scale=0.1, max_shed=0.5)
        for _ in range(20):
            pol.tick()
        assert adm.scale == pytest.approx(0.1)
        assert adm.shed_probability == pytest.approx(0.5)
        eng.burning = False
        for _ in range(20):
            pol.tick()
        assert adm.scale == pytest.approx(1.0)
        assert adm.shed_probability == pytest.approx(0.0)


# ------------------------------------------------------- backoff guarantees


class TestBackoffJitter:
    def test_delay_bounds_decorrelated(self):
        bo = Backoff(base=0.01, cap=0.8, rng=random.Random(9))
        prev = bo.base
        for _ in range(200):
            d = bo.next_delay()
            assert 0.01 <= d <= 0.8
            assert d <= max(prev * 3, 0.01) + 1e-12
            prev = max(0.01, d)

    def test_seeded_schedule_replays_and_reset(self):
        a = Backoff(base=0.02, cap=1.0, rng=random.Random(4))
        b = Backoff(base=0.02, cap=1.0, rng=random.Random(4))
        assert [a.next_delay() for _ in range(16)] == \
            [b.next_delay() for _ in range(16)]
        a.reset()
        assert a.next_delay() <= 0.06          # episode forgot growth


# -------------------------------------------------------- wire timeouts


class TestWireTimeouts:
    def test_recv_frame_timeout_raises_wire_error(self):
        a, b = socket.socketpair()
        try:
            t0 = time.monotonic()
            with pytest.raises(wire.WireError):
                wire.recv_frame(a, timeout=0.15)
            assert time.monotonic() - t0 < 2.0   # bounded, no busy-wait
            assert a.gettimeout() is None        # restored
        finally:
            a.close()
            b.close()

    def test_recv_frame_timeout_mid_frame(self):
        a, b = socket.socketpair()
        try:
            frame = wire.encode_frame({"t": "op"})
            b.sendall(frame[: len(frame) // 2])  # torn: header, no tail
            with pytest.raises(wire.WireError):
                wire.recv_frame(a, timeout=0.15)
        finally:
            a.close()
            b.close()


# ---------------------------------------------- JSON door THROTTLED e2e


class TestJsonDoorThrottle:
    def test_shed_burst_drains_exactly_once_without_cseq_burn(self):
        svc = LocalService(n_partitions=2)
        adm = AdmissionController(tenants={"t": 60.0},
                                  rng=random.Random(0))
        adm.register_tenant("t", 60.0, burst=8.0)
        server = AlfredServer(svc, admission=adm).start_in_thread()
        try:
            conn = ResilientConnection("127.0.0.1", server.port, "d0",
                                       rng=random.Random(1), tenant="t")
            n = 40
            uids = [conn.submit({"mt": "insert", "kind": 0, "pos": 0,
                                 "text": f"x{i}.", "u": i})
                    for i in range(n)]
            assert conn.wait_idle(timeout=30), conn.pending_count
            assert not conn.nacks, conn.nacks     # shed ≠ nacked
            assert conn.throttled > 0             # burst over budget
            assert conn.throttle_resubmits > 0
            assert conn.throttled_uids            # latency bookkeeping
            assert set(conn.op_acks) == set(uids)
            durable = [m for m in svc.get_deltas("d0", 0)
                       if m.type == MessageType.OP]
            # exactly once, in order, cseqs gapless from 1: a shed op
            # was resubmitted with the SAME number, never renumbered
            assert [m.contents["u"] for m in durable] == list(range(n))
            assert [m.client_seq for m in durable] == \
                list(range(1, n + 1))
            assert adm.snapshot()["tenants"]["t"]["shed"] > 0
            conn.close()
        finally:
            server.stop()
            svc.close()

    def test_throttled_frame_carries_retry_hint(self):
        svc = LocalService(n_partitions=1)
        adm = AdmissionController(tenants={"t": 20.0},
                                  rng=random.Random(0))
        adm.register_tenant("t", 20.0, burst=2.0)
        server = AlfredServer(svc, admission=adm).start_in_thread()
        try:
            sock = socket.create_connection(("127.0.0.1", server.port))
            wire.send_frame(sock, {"t": "connect", "doc": "d0",
                                   "tenant": "t"})
            hello = wire.recv_frame(sock, timeout=5.0)
            assert hello["t"] == "connected"
            for cs in (1, 2, 3, 4):
                wire.send_frame(sock, {"t": "op", "client_seq": cs,
                                       "ref_seq": hello.get("seq", 0),
                                       "type": int(MessageType.OP),
                                       "contents": {"u": cs}})
            got = []
            while len([f for f in got if f["t"] == "throttled"]) < 1:
                got.append(wire.recv_frame(sock, timeout=5.0))
            th = [f for f in got if f["t"] == "throttled"][0]
            assert th["retry_after_ms"] >= 5.0
            assert th["client_seq"] >= 3          # suffix shed only
            sock.close()
        finally:
            server.stop()
            svc.close()


# ------------------------------------------ columnar door THROTTLED e2e

needs_native = pytest.mark.skipif(not native_deli.available(),
                                  reason="native sequencer unavailable")


@needs_native
class TestColumnarDoorThrottle:
    def test_shed_burst_drains_exactly_once(self):
        from fluidframework_tpu.server.columnar_ingress import (
            ColumnarAlfred)
        from fluidframework_tpu.server.serving import StringServingEngine
        eng = StringServingEngine(n_docs=4, capacity=256,
                                  batch_window=10 ** 9,
                                  sequencer="native")
        adm = AdmissionController(tenants={"t": 80.0},
                                  rng=random.Random(0))
        adm.register_tenant("t", 80.0, burst=8.0)
        srv = ColumnarAlfred(eng, window_min_rows=1, window_ms=2.0,
                             admission=adm).start_in_thread()
        try:
            cl = ResilientColumnarClient("127.0.0.1", srv.port, ["d0"],
                                         rng=random.Random(3),
                                         tenant="t")
            n = 30
            for i in range(n):
                cl.submit("d0", kind=0, a0=0, payload=f"w{i}.")
            assert cl.wait_idle(timeout=30), cl.pending_count
            assert not cl.nacks, cl.nacks
            assert cl.throttled > 0
            assert cl.throttled_cseqs["d0"]
            assert sorted(cl.acks["d0"]) == list(range(1, n + 1))
            text = eng.read_text("d0")
            for i in range(n):
                assert text.count(f"w{i}.") == 1, (i, text)
            cl.close()
        finally:
            srv.stop()


# ------------------------------------------------- replica shed counter


class TestReplicaShedCounter:
    def test_replica_full_counts_sheds_and_default_slo_exists(self):
        from fluidframework_tpu.framework import LocalClient
        from fluidframework_tpu.server.serving_service import (
            ServingLocalService)
        from fluidframework_tpu.utils.slo import default_slos
        svc = ServingLocalService(n_docs=1, capacity=256)
        try:
            client = LocalClient(service=svc)
            schema = {"initialObjects": {"a": "sharedString",
                                         "b": "sharedString"}}
            c1, _doc = client.create_container(schema)
            c1.initial_objects["a"].insert_text(0, "fits")
            c1.initial_objects["b"].insert_text(0, "sheds")
            assert svc.metrics.counters["replica_sheds_total"] >= 1
            assert svc.metrics.counters["replica_channels_dropped"] == 1
            assert svc.dropped_channels()
        finally:
            svc.close()
        assert any(s.name == "replica_shed_rate"
                   for s in default_slos())


# -------------------------------------------------- tenant sim soak gate


class TestTenantSimGate:
    def test_quick_profile_holds_correctness_gates(self):
        ts = _tool("tenant_sim")
        # lenient latency/goodput floors: tier-1 boxes vary, and the
        # CORRECTNESS gates (zero silent drops, exactly-once, abusive
        # overage visibly shed) are the ones that must never flex
        report = ts.run_sim(seed=3, duration_s=1.2, slo_ms=1000.0,
                            goodput_min=0.3, quick=True)
        assert report["silent_drops"] == 0
        assert report["ops_acked"] == report["ops_offered"]
        assert report["abusive_throttled"] > 0
        assert report["abusive_shed"] > 0
        assert report["throttled_frames"] > 0
        assert report["gate_failures"] == [], report["gate_failures"]
        assert report["policy"]["ticks"] > 0
