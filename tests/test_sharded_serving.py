"""The doc-sharded serving engine (parallel/sharded.py): the product's
multi-chip path on the virtual 8-device CPU mesh — parity with the
unsharded engine, recovery onto the mesh, and the collective-free proof.
"""

import numpy as np
import pytest

from fluidframework_tpu.parallel.sharded import (
    assert_collective_free, make_doc_mesh,
)
from fluidframework_tpu.server import native_deli
from fluidframework_tpu.server.serving import StringServingEngine

pytestmark = pytest.mark.skipif(not native_deli.available(),
                                reason="native sequencer unavailable")

TEXT = "abcd"


def _pair(R=64, cap=256):
    mesh = make_doc_mesh(8)
    eng = StringServingEngine(n_docs=R, capacity=cap, batch_window=10 ** 9,
                              sequencer="native", mesh=mesh, compact_every=2)
    ora = StringServingEngine(n_docs=R, capacity=cap, batch_window=10 ** 9,
                              sequencer="native", compact_every=2)
    docs = [f"doc-{i}" for i in range(R)]
    for e in (eng, ora):
        for d in docs:
            e.connect(d, 1)
            e.doc_row(d)
    rows = np.array([eng.doc_row(d) for d in docs], np.int32)
    return mesh, eng, ora, docs, rows


def test_sharded_engine_matches_unsharded():
    R, O = 64, 16
    mesh, eng, ora, docs, rows = _pair(R)
    client = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)
    kind = np.zeros((R, O), np.int32)
    z = np.zeros((R, O), np.int32)
    from fluidframework_tpu.testing.synthetic import typing_storm
    for b in range(3):
        planes, _ = typing_storm(R, O, seed=b)
        cseq = np.broadcast_to(
            np.arange(b * O + 1, (b + 1) * O + 1, dtype=np.int32), (R, O))
        for e in (eng, ora):
            assert e.ingest_planes(rows, client, cseq, ref, planes["kind"],
                                   planes["a0"], planes["a1"],
                                   TEXT)["nacked"] == 0
    assert np.array_equal(eng.store.digests(), ora.store.digests())
    for d in docs[::13]:
        assert eng.read_text(d) == ora.read_text(d)
    assert "docs" in str(eng.store.state.seq.sharding.spec)


def test_sharded_rich_and_recovery_onto_mesh():
    R, O = 64, 8
    mesh, eng, ora, docs, rows = _pair(R)
    client = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)
    texts = [f"t{k}" for k in range(O)]
    props = [{"b": 1}, {"c": "x"}]
    kind = np.zeros((R, O), np.int32)
    kind[:, O // 2:] = 2  # annotate
    a0 = np.zeros((R, O), np.int32)
    a1 = np.zeros((R, O), np.int32)
    a1[:, O // 2:] = 2
    tidx = np.zeros((R, O), np.int32)
    tidx[:, :O // 2] = np.arange(O // 2, dtype=np.int32)
    tidx[:, O // 2:] = np.arange(O // 2, dtype=np.int32) % 2
    cseq = np.broadcast_to(np.arange(1, O + 1, dtype=np.int32), (R, O))
    for e in (eng, ora):
        assert e.ingest_planes(rows, client, cseq, ref, kind, a0, a1,
                               texts=texts, tidx=tidx,
                               props=props)["nacked"] == 0
    assert np.array_equal(eng.store.digests(), ora.store.digests())
    assert eng.get_properties(docs[0], 0) == ora.get_properties(docs[0], 0)

    summary = eng.summarize()
    revived = StringServingEngine.load(summary, eng.log, mesh=mesh)
    assert np.array_equal(revived.store.digests(), eng.store.digests())
    assert "docs" in str(revived.store.state.seq.sharding.spec)
    # restored engine keeps serving, sharded
    msg, nack = revived.submit(
        docs[0], 1, O + 1, 0,
        {"mt": "insert", "kind": 0, "pos": 0, "text": "Z"})
    assert nack is None
    assert revived.read_text(docs[0]) == "Z" + eng.read_text(docs[0])


def test_sharded_apply_hlo_is_collective_free():
    mesh = make_doc_mesh(8)
    assert assert_collective_free(mesh, 64, 128, 16) == "collective-free"


def test_mesh_requires_divisible_docs():
    mesh = make_doc_mesh(8)
    from fluidframework_tpu.ops.string_store import TensorStringStore
    with pytest.raises(ValueError, match="divisible"):
        TensorStringStore(30, 128, mesh=mesh)


def test_sharded_incremental_summary_roundtrip():
    """Incremental summaries of a SHARDED store: the dirty-row gather and
    the delta-restore scatter must work over the mesh, and load(mesh=...)
    must resolve the chain back onto it."""
    R, O = 64, 8
    mesh, eng, ora, docs, rows = _pair(R)
    client = np.ones((R, O), np.int32)
    z = np.zeros((R, O), np.int32)
    kind = np.zeros((R, O), np.int32)
    cseq = np.broadcast_to(np.arange(1, O + 1, dtype=np.int32), (R, O))
    assert eng.ingest_planes(rows, client, cseq, z, kind, z, z,
                             TEXT)["nacked"] == 0
    eng.summarize()
    # touch 3 docs, delta-summarize, touch 2 more, delta again (chain)
    sub = rows[:3]
    cseq2 = np.broadcast_to(np.arange(O + 1, 2 * O + 1, dtype=np.int32),
                            (3, O))
    assert eng.ingest_planes(sub, client[:3], cseq2, z[:3], kind[:3],
                             z[:3], z[:3], TEXT)["nacked"] == 0
    s1 = eng.summarize(incremental=True)
    assert len(s1["store_delta"]["rows"]) == 3
    sub2 = rows[10:12]
    cseq3 = np.broadcast_to(np.arange(O + 1, 2 * O + 1, dtype=np.int32),
                            (2, O))
    assert eng.ingest_planes(sub2, client[:2], cseq3, z[:2], kind[:2],
                             z[:2], z[:2], TEXT)["nacked"] == 0
    s2 = eng.summarize(incremental=True)
    want = {d: eng.read_text(d) for d in docs}
    revived = StringServingEngine.load(s2, eng.log, mesh=mesh)
    assert {d: revived.read_text(d) for d in docs} == want
    assert "docs" in str(revived.store.state.seq.sharding.spec)


def test_sharded_map_engine_matches_unsharded():
    """MapServingEngine(mesh=...): columnar merge as a collective-free
    shard_map; parity with the unsharded engine + recovery onto mesh."""
    from fluidframework_tpu.ops.schema import OpKind
    from fluidframework_tpu.server.serving import MapServingEngine
    mesh = make_doc_mesh(8)
    R, O = 64, 12
    a = MapServingEngine(n_docs=R, batch_window=10 ** 9,
                         sequencer="native", mesh=mesh)
    b = MapServingEngine(n_docs=R, batch_window=10 ** 9,
                         sequencer="native")
    docs = [f"sm-{i}" for i in range(R)]
    for e in (a, b):
        for d in docs:
            e.connect(d, 1)
            e.doc_row(d)
    rows = np.array([a.doc_row(d) for d in docs], np.int32)
    rng = np.random.default_rng(3)
    keys = [f"k{j}" for j in range(6)]
    values = [f"v{j}" for j in range(5)]
    client = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)
    for bi in range(3):
        kind = rng.choice([int(OpKind.MAP_SET), int(OpKind.MAP_DELETE),
                           int(OpKind.MAP_CLEAR)],
                          p=[0.8, 0.15, 0.05], size=(R, O)).astype(np.int32)
        kidx = rng.integers(0, len(keys), size=(R, O)).astype(np.int32)
        vidx = rng.integers(0, len(values), size=(R, O)).astype(np.int32)
        cseq = np.broadcast_to(
            np.arange(bi * O + 1, (bi + 1) * O + 1, dtype=np.int32), (R, O))
        for e in (a, b):
            assert e.ingest_planes(rows, client, cseq, ref, kind, kidx,
                                   keys, values, vidx)["nacked"] == 0
    assert np.array_equal(a.store.digests(), b.store.digests())
    for d in docs[::11]:
        assert a.read_doc(d) == b.read_doc(d), d
    assert "docs" in str(a.store.state.present.sharding.spec)

    summary = a.summarize()
    revived = MapServingEngine.load(summary, a.log, mesh=mesh)
    assert {d: revived.read_doc(d) for d in docs} == \
        {d: a.read_doc(d) for d in docs}
    assert "docs" in str(revived.store.state.present.sharding.spec)
