"""The doc-sharded serving engine (parallel/sharded.py): the product's
multi-chip path on the virtual 8-device CPU mesh — parity with the
unsharded engine, recovery onto the mesh, and the collective-free proof.
"""

import numpy as np
import pytest

from fluidframework_tpu.parallel.sharded import (
    assert_collective_free, make_doc_mesh,
)
from fluidframework_tpu.server import native_deli
from fluidframework_tpu.server.serving import StringServingEngine

pytestmark = pytest.mark.skipif(not native_deli.available(),
                                reason="native sequencer unavailable")

TEXT = "abcd"


def _pair(R=64, cap=256):
    mesh = make_doc_mesh(8)
    eng = StringServingEngine(n_docs=R, capacity=cap, batch_window=10 ** 9,
                              sequencer="native", mesh=mesh, compact_every=2)
    ora = StringServingEngine(n_docs=R, capacity=cap, batch_window=10 ** 9,
                              sequencer="native", compact_every=2)
    docs = [f"doc-{i}" for i in range(R)]
    for e in (eng, ora):
        for d in docs:
            e.connect(d, 1)
            e.doc_row(d)
    rows = np.array([eng.doc_row(d) for d in docs], np.int32)
    return mesh, eng, ora, docs, rows


def test_sharded_engine_matches_unsharded():
    R, O = 64, 16
    mesh, eng, ora, docs, rows = _pair(R)
    client = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)
    kind = np.zeros((R, O), np.int32)
    z = np.zeros((R, O), np.int32)
    from fluidframework_tpu.testing.synthetic import typing_storm
    for b in range(3):
        planes, _ = typing_storm(R, O, seed=b)
        cseq = np.broadcast_to(
            np.arange(b * O + 1, (b + 1) * O + 1, dtype=np.int32), (R, O))
        for e in (eng, ora):
            assert e.ingest_planes(rows, client, cseq, ref, planes["kind"],
                                   planes["a0"], planes["a1"],
                                   TEXT)["nacked"] == 0
    assert np.array_equal(eng.store.digests(), ora.store.digests())
    for d in docs[::13]:
        assert eng.read_text(d) == ora.read_text(d)
    assert "docs" in str(eng.store.state.seq.sharding.spec)


def test_sharded_rich_and_recovery_onto_mesh():
    R, O = 64, 8
    mesh, eng, ora, docs, rows = _pair(R)
    client = np.ones((R, O), np.int32)
    ref = np.zeros((R, O), np.int32)
    texts = [f"t{k}" for k in range(O)]
    props = [{"b": 1}, {"c": "x"}]
    kind = np.zeros((R, O), np.int32)
    kind[:, O // 2:] = 2  # annotate
    a0 = np.zeros((R, O), np.int32)
    a1 = np.zeros((R, O), np.int32)
    a1[:, O // 2:] = 2
    tidx = np.zeros((R, O), np.int32)
    tidx[:, :O // 2] = np.arange(O // 2, dtype=np.int32)
    tidx[:, O // 2:] = np.arange(O // 2, dtype=np.int32) % 2
    cseq = np.broadcast_to(np.arange(1, O + 1, dtype=np.int32), (R, O))
    for e in (eng, ora):
        assert e.ingest_planes(rows, client, cseq, ref, kind, a0, a1,
                               texts=texts, tidx=tidx,
                               props=props)["nacked"] == 0
    assert np.array_equal(eng.store.digests(), ora.store.digests())
    assert eng.get_properties(docs[0], 0) == ora.get_properties(docs[0], 0)

    summary = eng.summarize()
    revived = StringServingEngine.load(summary, eng.log, mesh=mesh)
    assert np.array_equal(revived.store.digests(), eng.store.digests())
    assert "docs" in str(revived.store.state.seq.sharding.spec)
    # restored engine keeps serving, sharded
    msg, nack = revived.submit(
        docs[0], 1, O + 1, 0,
        {"mt": "insert", "kind": 0, "pos": 0, "text": "Z"})
    assert nack is None
    assert revived.read_text(docs[0]) == "Z" + eng.read_text(docs[0])


def test_sharded_apply_hlo_is_collective_free():
    mesh = make_doc_mesh(8)
    assert assert_collective_free(mesh, 64, 128, 16) == "collective-free"


def test_mesh_requires_divisible_docs():
    mesh = make_doc_mesh(8)
    from fluidframework_tpu.ops.string_store import TensorStringStore
    with pytest.raises(ValueError, match="divisible"):
        TensorStringStore(30, 128, mesh=mesh)
