"""Attribution subsystem (reference: @fluid-experimental/attributor).

Op-stream attribution keyed by sequence number: who typed each character,
with the service-stamped timestamp — on interactive replicas (merge-tree
segment seq → attributor) and on the serving engine (device seq plane →
attributor), surviving splits, summaries, and recovery.
"""

import pytest

from fluidframework_tpu.models import SharedString
from fluidframework_tpu.models.merge_tree_client import SequenceClient
from fluidframework_tpu.runtime.attributor import (
    LOCAL_ATTRIBUTION,
    Attributor,
    string_attribution_at,
)
from fluidframework_tpu.server.oplog import PartitionedLog
from fluidframework_tpu.server.serving import StringServingEngine
from fluidframework_tpu.testing.mocks import MockSequencer, create_connected_dds


def test_client_side_attribution_per_character():
    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedString, "s")
    b = create_connected_dds(seqr, SharedString, "s")
    att = Attributor()
    b.attach_attributor(att)
    a.insert_text(0, "aaa")
    b.insert_text(0, "bb")
    seqr.process_all_messages()
    # a remote insert SPLITS a's run on b? (b's text lands at 0) — either
    # way every char attributes to its writer
    text = b.get_text()
    for pos, ch in enumerate(text):
        info = string_attribution_at(b, att, pos)
        want = a.client_id if ch == "a" else b.client_id
        assert info.client_id == want, (pos, ch)
        assert info.timestamp is not None


def test_pending_local_edit_attributes_local():
    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedString, "s")
    att = Attributor()
    a.attach_attributor(att)
    a.insert_text(0, "x")  # not yet sequenced
    assert string_attribution_at(a, att, 0) == LOCAL_ATTRIBUTION
    seqr.process_all_messages()
    assert string_attribution_at(a, att, 0).client_id == a.client_id


def test_attribution_survives_split_and_zamboni():
    from fluidframework_tpu.core.protocol import MessageType
    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedString, "s")
    b = create_connected_dds(seqr, SharedString, "s")
    att = Attributor()
    a.attach_attributor(att)
    a.insert_text(0, "hello world")
    seqr.process_all_messages()
    b.insert_text(5, "|B|")  # splits a's segment
    seqr.process_all_messages()
    for _ in range(3):
        for r in (a, b):
            seqr.submit(r, {}, type=MessageType.NOOP)
        seqr.process_all_messages()  # zamboni
    text = a.get_text()
    for pos, ch in enumerate(text):
        want = b.client_id if ch in "|B" else a.client_id
        assert string_attribution_at(a, att, pos).client_id == want, (pos, ch)


def test_attributor_summary_roundtrip():
    seqr = MockSequencer()
    a = create_connected_dds(seqr, SharedString, "s")
    att = Attributor()
    a.attach_attributor(att)
    a.insert_text(0, "abc")
    a.annotate_range(0, 2, {"b": 1})
    seqr.process_all_messages()
    clone = Attributor.load(att.summarize())
    assert len(clone) == len(att) == 2
    for seq in (1, 2):
        assert clone.get(seq) == att.get(seq)


def test_serving_engine_attribution_and_recovery():
    log = PartitionedLog(4)
    engine = StringServingEngine(n_docs=1, capacity=128, log=log)
    engine.enable_attribution()
    engine.connect("d", 1)
    engine.connect("d", 2)
    c1, c2 = SequenceClient(1), SequenceClient(2)
    clients = [c1, c2]

    def submit(c, op):
        msg, nack = engine.submit("d", c.client_id, op["clientSeq"],
                                  c.last_processed_seq, op)
        assert nack is None
        for cc in clients:
            cc.apply_msg(msg)
    submit(c1, c1.insert_text_local(0, "one "))
    submit(c2, c2.insert_text_local(4, "two "))
    summary = engine.summarize()
    submit(c1, c1.insert_text_local(8, "tail"))  # after the summary

    for eng in (engine, StringServingEngine.load(summary, log)):
        text = eng.read_text("d")
        assert text == c1.get_text()
        assert eng.attribution_at("d", 0).client_id == 1
        assert eng.attribution_at("d", 4).client_id == 2
        assert eng.attribution_at("d", 8).client_id == 1
        assert eng.attribution_at("d", 0).timestamp is not None
        with pytest.raises(IndexError):
            eng.attribution_at("d", 99)


def test_native_codec_preserves_timestamp():
    from fluidframework_tpu.server.native_oplog import (
        available, decode_message, encode_message)
    if not available():
        pytest.skip("native oplog not built")
    from fluidframework_tpu.core.protocol import (
        MessageType, SequencedDocumentMessage)
    for ts in (None, 0.0, 1234.5):
        m = SequencedDocumentMessage(
            doc_id="d", client_id=1, client_seq=1, ref_seq=0, seq=1,
            min_seq=0, type=MessageType.OP, contents={"x": 1}, timestamp=ts)
        assert decode_message(encode_message(m)) == m


def test_engine_attribution_keyed_per_document():
    """Deli seqs are per-doc: ops from two docs sharing seq numbers must
    not collide in the engine attributor (review finding)."""
    engine = StringServingEngine(n_docs=2, capacity=64)
    engine.enable_attribution()
    engine.connect("a", 1)
    engine.connect("b", 2)
    ca, cb = SequenceClient(1), SequenceClient(2)
    op = ca.insert_text_local(0, "A")
    msg, _ = engine.submit("a", 1, op["clientSeq"], 0, op)
    ca.apply_msg(msg)
    op = cb.insert_text_local(0, "B")
    msg, _ = engine.submit("b", 2, op["clientSeq"], 0, op)  # same seq as a's
    cb.apply_msg(msg)
    assert engine.attribution_at("a", 0).client_id == 1
    assert engine.attribution_at("b", 0).client_id == 2


def test_native_codec_reads_pre_timestamp_records(tmp_path):
    """Logs written before the timestamp field (tag M, 48-byte header)
    must still decode after the upgrade (review finding: silent corruption
    of durable logs on format change)."""
    from fluidframework_tpu.server import native_oplog as no
    if not no.available():
        pytest.skip("native oplog not built")
    import json as _json
    from fluidframework_tpu.core.protocol import (MessageType,
                                                  SequencedDocumentMessage)
    m = SequencedDocumentMessage(
        doc_id="doc", client_id=3, client_seq=4, ref_seq=2, seq=5,
        min_seq=1, type=MessageType.OP, contents={"mt": "remove"},
        address="ds")
    # hand-craft an OLD record: V1 header, no timestamp, tag b"M"
    doc = m.doc_id.encode()
    blob = _json.dumps({"c": m.contents, "a": m.address,
                        "m": m.metadata}).encode()
    old = no._HEADER_V1.pack(m.client_id, m.client_seq, m.ref_seq, m.seq,
                             m.min_seq, int(m.type), len(doc)) + doc + blob
    log = no.NativePartitionedLog(str(tmp_path), 1)
    log._lib.oplog_append(log._h, 0, b"M" + old, len(old) + 1)
    back = list(log.read(0))[0]
    assert back.doc_id == "doc" and back.seq == 5
    assert back.contents == {"mt": "remove"} and back.address == "ds"
    assert back.timestamp is None


def test_deli_restore_keeps_injected_clock():
    from fluidframework_tpu.core.protocol import MessageType
    from fluidframework_tpu.server.deli import DeliSequencer
    d = DeliSequencer(clock=lambda: 42.0)
    d.client_join("x", 1)
    d2 = DeliSequencer.restore(d.checkpoint(), clock=d.clock)
    msg, _ = d2.sequence("x", 1, 1, 0, MessageType.OP, {})
    assert msg.timestamp == 42.0


def test_mega_tier_attribution():
    """attribution_at must work for mega-tier documents too (review
    finding: MegaDocStringStore lacked seq_at)."""
    engine = StringServingEngine(n_docs=1, capacity=64, mega_docs=1,
                                 mega_capacity_per_shard=32)
    engine.enable_attribution()
    engine.connect("huge", 5)
    engine.mark_mega("huge")
    c = SequenceClient(5)
    op = c.insert_text_local(0, "mega")
    msg, nack = engine.submit("huge", 5, op["clientSeq"], 0, op)
    assert nack is None
    c.apply_msg(msg)
    op = c.insert_text_local(4, "-doc")
    msg, nack = engine.submit("huge", 5, op["clientSeq"],
                              c.last_processed_seq, op)
    assert nack is None
    assert engine.read_text("huge") == "mega-doc"
    for pos in range(8):
        info = engine.attribution_at("huge", pos)
        assert info.client_id == 5 and info.timestamp is not None
    with pytest.raises(IndexError):
        engine.attribution_at("huge", 99)
