"""Doc-sharded matrix and tree serving engines (VERDICT r4 missing #3):
sharded-vs-unsharded parity on the virtual 8-device CPU mesh, recovery
onto the mesh, and collective-free proofs for the new sharded applies."""

import numpy as np
import pytest

from fluidframework_tpu.parallel.sharded import make_doc_mesh
from fluidframework_tpu.server import native_deli
from fluidframework_tpu.server.serving import (
    MatrixServingEngine, TreeServingEngine,
)

from tests.test_tree_kernel import tree_session

pytestmark = pytest.mark.skipif(not native_deli.available(),
                                reason="native sequencer unavailable")


def _tree_pair(n_docs=16):
    mesh = make_doc_mesh(8)
    eng = TreeServingEngine(n_docs=n_docs, capacity=256,
                            batch_window=10 ** 9, sequencer="native",
                            mesh=mesh)
    ora = TreeServingEngine(n_docs=n_docs, capacity=256,
                            batch_window=10 ** 9, sequencer="native")
    docs = [f"t-{i}" for i in range(n_docs)]
    for e in (eng, ora):
        for d in docs:
            e.connect(d, 1)
    return mesh, eng, ora, docs


def _tree_drive(eng, ora, docs, seeds):
    per_doc = {d: [m.contents for m in tree_session(s, n_rounds=5)[1]]
               for d, s in zip(docs, seeds)}
    w = 0
    while any(per_doc.values()):
        ids, ops = [], []
        for d in docs:
            if per_doc[d]:
                ids.append(d)
                ops.append(per_doc[d].pop(0))
        for e in (eng, ora):
            res = e.ingest_batch(ids, [1] * len(ids), [w + 1] * len(ids),
                                 [0] * len(ids), ops)
            assert res["nacked"] == 0
        w += 1


def test_sharded_tree_engine_matches_unsharded():
    mesh, eng, ora, docs = _tree_pair()
    _tree_drive(eng, ora, docs, range(40, 56))
    assert np.array_equal(eng.store.digests(), ora.store.digests())
    for d in docs[::5]:
        assert eng.to_dict(d) == ora.to_dict(d), d
    assert "docs" in str(eng.store.state.node_id.sharding.spec)


def test_sharded_tree_recovery_onto_mesh():
    mesh, eng, ora, docs = _tree_pair()
    _tree_drive(eng, ora, docs, range(60, 76))
    summary = eng.summarize()
    # post-summary tail
    res = eng.ingest_batch(
        [docs[0]], [1], [eng.deli.doc_seq(docs[0])], [0],
        [{"op": "insert", "parent": "root", "field": "kids",
          "after": None, "nodes": [{"id": "tail-node"}]}])
    revived = TreeServingEngine.load(summary, eng.log, mesh=mesh)
    for d in docs[::5]:
        assert revived.to_dict(d) == eng.to_dict(d), d
    assert "docs" in str(revived.store.state.node_id.sharding.spec)


def test_sharded_tree_collective_free():
    import jax.numpy as jnp
    from fluidframework_tpu.ops.tree_kernel import TreeState
    from fluidframework_tpu.parallel.sharded import (
        shard_tree_store_state, sharded_tree_apply)
    mesh = make_doc_mesh(8)
    state = shard_tree_store_state(TreeState.create(16, 64), mesh)
    planes = jnp.zeros((9, 16, 4), jnp.int32)
    fn = sharded_tree_apply(mesh)
    hlo = fn.lower(state, planes).compile().as_text()
    bad = [op for op in ("all-reduce", "all-gather", "all-to-all",
                         "collective-permute", "reduce-scatter")
           if op in hlo]
    assert not bad, f"sharded tree apply HLO has collectives: {bad}"


def _mx_pair(n_docs=16):
    mesh = make_doc_mesh(8)
    eng = MatrixServingEngine(n_docs=n_docs, cell_capacity=4096,
                              batch_window=10 ** 9, sequencer="native",
                              mesh=mesh)
    ora = MatrixServingEngine(n_docs=n_docs, cell_capacity=4096,
                              batch_window=10 ** 9, sequencer="native")
    docs = [f"x-{i}" for i in range(n_docs)]
    for e in (eng, ora):
        for d in docs:
            e.connect(d, 1)
    return mesh, eng, ora, docs


def _mx_drive(eng, ora, docs, with_fww=False):
    import random
    rng = random.Random(7)
    cseq = {d: 0 for d in docs}
    for rnd in range(4):
        for d in docs:
            ops = [{"mx": "insRow", "pos": 0, "count": 2,
                    "opKey": [rnd + 1, 0]},
                   {"mx": "insCol", "pos": 0, "count": 2,
                    "opKey": [100 + rnd, 0]},
                   {"mx": "setCell", "row": rng.randrange(2),
                    "col": rng.randrange(2),
                    "value": f"{d}-{rnd}"}]
            if with_fww and rnd == 2:
                ops.append({"mx": "policy"})
            if rnd == 3:
                ops.append({"mx": "rmRow", "start": 0, "count": 1})
            for op in ops:
                cseq[d] += 1
                for e in (eng, ora):
                    _, nack = e.submit(d, 1, cseq[d], 0, op)
                    assert nack is None, (d, op, nack)
        for e in (eng, ora):
            e.flush()


def test_sharded_matrix_engine_matches_unsharded():
    mesh, eng, ora, docs = _mx_pair()
    _mx_drive(eng, ora, docs, with_fww=True)
    for d in docs:
        assert eng.dims(d) == ora.dims(d), d
        assert eng.to_lists(d) == ora.to_lists(d), d
    assert "docs" in str(eng.store.state.key.sharding.spec)
    assert "docs" in str(eng.axis_store.state.seq.sharding.spec)


def test_sharded_matrix_cell_ingest_and_recovery():
    mesh, eng, ora, docs = _mx_pair()
    _mx_drive(eng, ora, docs)
    n = len(docs)
    res_a = eng.ingest_cells(docs, [1] * n, [14] * n, [0] * n,
                             [0] * n, [1] * n, [f"v{i}" for i in
                                                range(n)])
    res_b = ora.ingest_cells(docs, [1] * n, [14] * n, [0] * n,
                             [0] * n, [1] * n, [f"v{i}" for i in
                                                range(n)])
    assert res_a["nacked"] == res_b["nacked"] == 0
    for d in docs[::3]:
        assert eng.to_lists(d) == ora.to_lists(d), d
    summary = eng.summarize()
    revived = MatrixServingEngine.load(summary, eng.log, mesh=mesh)
    for d in docs[::3]:
        assert revived.to_lists(d) == eng.to_lists(d), d


def test_sharded_matrix_incremental_summary():
    mesh, eng, ora, docs = _mx_pair()
    _mx_drive(eng, ora, docs)
    eng.summarize()
    d0 = docs[0]
    _, nack = eng.submit(d0, 1, 14, 0, {"mx": "setCell", "row": 0,
                                        "col": 0, "value": "late"})
    assert nack is None
    eng.flush()
    delta = eng.summarize(incremental=True)
    assert delta["kind"] == "delta"
    revived = MatrixServingEngine.load(delta, eng.log, mesh=mesh)
    assert revived.get_cell(d0, 0, 0) == "late"
    for d in docs[::3]:
        assert revived.to_lists(d) == eng.to_lists(d), d
