#!/usr/bin/env python
"""Perf-regression sentinel over the BENCH_r*.json trajectory.

The driver records every round's ``python bench.py`` run as
``BENCH_r{NN}.json``; nothing so far READS the whole trajectory — a
regression between rounds is only caught if a human happens to diff two
records. This tool is the mechanical judge (ISSUE 4 tentpole piece 4):

- load every committed round (oldest → newest, via ``bench_report``'s
  shape-tolerant ``load_record``),
- for each scalar metric in the NEWEST round, compare against the median
  of the prior rounds, with a variance band wide enough for the known
  tunnel noise: ``band = max(rel_band·|median|, k_sigma·stdev(priors))``
  (defaults 10% / 3σ — the committed r01–r05 swings, including the −12%
  conflict-throughput dip, sit inside it; a real cliff does not),
- emit one verdict per metric: ``regress`` / ``improve`` / ``flat``
  (plus ``new`` for metrics without enough history and ``info`` for
  metrics that must never fail the build — worst-case single samples,
  environmental RTT, config constants),
- exit nonzero iff any metric regressed beyond its band.

Direction is inferred from the name (``*ops_per_sec*`` up is good,
``*_ms``/``*_retries`` down is good); parity booleans are must-hold.
On top of the relative bands, DECLARED_FLOORS carries absolute
per-metric bars (e.g. ``serving_rich_ops_per_sec >= 2e6``) that arm
once achieved and then fail ``--check`` on any later dip below.
``--write-md`` refreshes the ``## Trajectory`` section in BENCHES.md;
``--check`` is the quiet tier-1 mode (table only on failure). bench.py
imports :func:`judge` to embed a live verdict in its own record.

Usage::

    python tools/perf_sentinel.py              # verdict table, exit 0/1
    python tools/perf_sentinel.py --check      # tier-1 gate
    python tools/perf_sentinel.py --write-md   # refresh BENCHES.md
"""

from __future__ import annotations

import argparse
import json
import math
import os
import sys
from pathlib import Path
from typing import Any, Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
import bench_report  # noqa: E402  (tools/ is not a package)

#: verdicts that can fail the build
REGRESS = "regress"
IMPROVE = "improve"
FLAT = "flat"
NEW = "new"       # not enough prior rounds to judge
INFO = "info"     # tracked but never failing
STALE = "stale-record"   # floor declared after the newest committed record

#: metrics where a LOWER newest value is the bad direction
HIGHER_BETTER_HINTS = ("ops_per_sec", "per_sec")
HIGHER_BETTER_EXACT = {"value", "vs_baseline"}
#: metrics where a HIGHER newest value is the bad direction
LOWER_BETTER_SUFFIXES = ("_ms", "_retries", "_round_trips", "_stalled")
#: booleans that must stay truthy once they have held for >=1 prior round
MUST_HOLD = {"digest_parity", "conflict_parity"}
#: never-failing metrics: worst-case single samples are outliers by
#: construction (the committed r05 carries a known 983 ms stall), RTT is
#: the tunnel's property not the code's, and config constants are inputs
INFO_PATTERNS = ("worst",)
INFO_EXACT = {"dispatch_rtt_ms", "docs", "total_ops", "contended"}

#: declared per-metric floors (ISSUE 6 satellite): absolute bars the
#: roadmap has committed to, judged in --check tier-1 mode alongside the
#: trajectory bands. A floor only ARMS once some prior round achieved it
#: ("once achieved"): a still-climbing metric is never failed
#: retroactively, but any later round dipping back below an armed floor
#: fails the build even if the dip sits inside the variance band.
DECLARED_FLOORS: Dict[str, float] = {
    "serving_rich_ops_per_sec": 2e6,
    "columnar_ingress_ops_per_sec": 45e3,
    # ISSUE 7 floors: tree general waves on the width-coded wire through
    # the pipelined executor; matrix storms on the prefix gather-merge
    # kernel. Armed by the first (TPU) round that achieves them — CPU
    # rounds report them unarmed/info rather than failing.
    "tree_serving_ops_per_sec": 5e5,
    "matrix_serving_ops_per_sec": 1e5,
    # ISSUE 18 floor: the partitioned columnar storm (best rate at >= 4
    # sequencer partitions) must reach 2x the committed single-partition
    # columnar number (BENCHES.md: 8683.4 ops/s on the 1-core dev host).
    # Arms on the first round with the host cores to overlap the
    # partition sequencers; stale-record until BENCH_r06 lands.
    "partition_columnar_ops_per_sec": 17.4e3,
    # ISSUE 20 floor: delivered ops/s at 1024 observer subscribers —
    # the encode-once fanout makes delivery a sink call per subscriber,
    # so even the 1-core dev host should clear millions/s. Arms on the
    # first committed clearing round; stale-record until BENCH_r06.
    "read_delivery_ops_per_sec": 5e6,
}

#: round number each floor was declared in (ISSUE 17 satellite): a
#: floor whose declaration postdates the newest COMMITTED ``BENCH_r*``
#: record has never been verified by a committed run — the sentinel
#: says so explicitly (``stale-record``, info-class: visibility, not a
#: build failure) instead of silently judging it "unarmed". Keep this
#: in sync when adding to DECLARED_FLOORS: the round of the PR that
#: declares the floor.
FLOOR_DECLARED_ROUND: Dict[str, int] = {
    "serving_rich_ops_per_sec": 6,
    "columnar_ingress_ops_per_sec": 6,
    "tree_serving_ops_per_sec": 7,
    "matrix_serving_ops_per_sec": 7,
    "partition_columnar_ops_per_sec": 6,
    "read_delivery_ops_per_sec": 6,
}

#: Known-variance note (headline drift, r04 → r05): the merged-kernel
#: headline moved 7.98M → 7.28M ops/s (−8.8%) with no change on the
#: kernel path. That sits INSIDE the 10% rel_band by design: the
#: per-suite ``headline_trials`` of a single record spread up to ~±15%
#: (see ``headline_variance_band.spread_pct``) under test-tunnel
#: latency noise, so a cross-round drift smaller than one record's own
#: in-run spread is noise, not regression. Compare
#: ``headline_variance_band.median`` across rounds — not the
#: best-of-suite ``value`` — before reading a drift as real.


def classify(name: str) -> Optional[str]:
    """'up' (higher better), 'down' (lower better), 'info', 'hold'
    (boolean must-hold), or None for unjudgeable names."""
    if name in MUST_HOLD:
        return "hold"
    if name in INFO_EXACT or any(p in name for p in INFO_PATTERNS):
        return "info"
    if name in HIGHER_BETTER_EXACT or \
            any(h in name for h in HIGHER_BETTER_HINTS):
        return "up"
    if name.endswith(LOWER_BETTER_SUFFIXES):
        return "down"
    return "info"


def load_trajectory(root: Path) -> List[dict]:
    """Every committed round's parsed bench record, oldest → newest.
    Rounds that fail to parse are skipped with a stderr note (one torn
    record must not blind the sentinel to the rest)."""
    rounds: List[dict] = []
    for path in sorted(root.glob("BENCH_r*.json")):
        try:
            rec = bench_report.load_record(path)
        except (ValueError, json.JSONDecodeError) as e:
            print(f"perf_sentinel: skipping {path.name}: {e}",
                  file=sys.stderr)
            continue
        rec["_round"] = path.stem
        rounds.append(rec)
    return rounds


def _median(vals: List[float]) -> float:
    s = sorted(vals)
    n = len(s)
    return s[n // 2] if n % 2 else (s[n // 2 - 1] + s[n // 2]) / 2.0


def _stdev(vals: List[float]) -> float:
    if len(vals) < 2:
        return 0.0
    mean = sum(vals) / len(vals)
    return math.sqrt(sum((v - mean) ** 2 for v in vals)
                     / (len(vals) - 1))


def judge(rounds: List[dict], rel_band: float = 0.10,
          k_sigma: float = 3.0, min_priors: int = 2) -> List[dict]:
    """Verdict per scalar metric of the newest round vs its history.

    A metric regresses when its newest value falls outside
    ``max(rel_band·|median|, k_sigma·stdev)`` of the prior rounds in the
    bad direction for its class; the same excursion in the good
    direction is ``improve``. Metrics seen in fewer than ``min_priors``
    prior rounds are ``new`` — a metric's first appearance can never
    fail the build."""
    if not rounds:
        return []
    newest, priors = rounds[-1], rounds[:-1]
    verdicts: List[dict] = []
    for name in sorted(newest):
        if name.startswith("_"):
            continue
        val = newest[name]
        direction = classify(name)
        if isinstance(val, bool):
            if direction != "hold":
                continue
            held = [r[name] for r in priors if isinstance(r.get(name), bool)]
            ok = val or not any(held)
            verdicts.append({
                "metric": name, "verdict": FLAT if ok else REGRESS,
                "value": val, "expected": "true (must hold)",
                "delta_pct": None,
                "note": "held" if ok else "parity lost vs prior rounds",
            })
            continue
        if not isinstance(val, (int, float)):
            continue
        hist = [float(r[name]) for r in priors
                if isinstance(r.get(name), (int, float))
                and not isinstance(r.get(name), bool)]
        if len(hist) < min_priors:
            verdicts.append({"metric": name, "verdict": NEW,
                             "value": val, "expected": None,
                             "delta_pct": None,
                             "note": f"{len(hist)} prior round(s)"})
            continue
        med = _median(hist)
        band = max(rel_band * abs(med), k_sigma * _stdev(hist))
        delta = float(val) - med
        delta_pct = (delta / med * 100.0) if med else None
        if abs(delta) <= band:
            verdict = FLAT
        elif direction == "info":
            verdict = INFO
        elif direction == "up":
            verdict = IMPROVE if delta > 0 else REGRESS
        elif direction == "down":
            verdict = IMPROVE if delta < 0 else REGRESS
        else:
            verdict = INFO
        verdicts.append({
            "metric": name, "verdict": verdict, "value": val,
            "expected": f"{med:g} ±{band:g}",
            "delta_pct": None if delta_pct is None
            else round(delta_pct, 2),
            "note": f"n={len(hist)}",
        })
    return verdicts


def judge_floors(rounds: List[dict]) -> List[dict]:
    """Declared-floor verdicts for the newest round (see
    DECLARED_FLOORS). Unarmed floors (never achieved in a prior round)
    report ``info``; armed floors report ``flat`` while they hold and
    ``regress`` the moment a round lands below them."""
    if not rounds:
        return []
    newest, priors = rounds[-1], rounds[:-1]
    out: List[dict] = []
    for name, floor in sorted(DECLARED_FLOORS.items()):
        val = newest.get(name)
        if not isinstance(val, (int, float)) or isinstance(val, bool):
            continue
        armed = any(
            isinstance(r.get(name), (int, float))
            and not isinstance(r.get(name), bool)
            and float(r[name]) >= floor for r in priors)
        if val >= floor:
            verdict = FLAT
            note = "floor holds" if armed else "floor achieved (now armed)"
        elif armed:
            verdict, note = REGRESS, "below an ACHIEVED declared floor"
        else:
            verdict, note = INFO, "floor not yet achieved (unarmed)"
        out.append({"metric": name, "verdict": verdict, "value": val,
                    "expected": f">={floor:g} (declared floor)",
                    "delta_pct": round((float(val) - floor) / floor * 100,
                                       2),
                    "note": note})
    return out


def _round_number(stem: str) -> Optional[int]:
    """``"BENCH_r04"`` → 4; None for stems that don't parse."""
    digits = "".join(c for c in stem.rsplit("r", 1)[-1] if c.isdigit())
    return int(digits) if digits else None


def judge_staleness(rounds: List[dict]) -> List[dict]:
    """``stale-record`` verdicts (ISSUE 17 satellite): one per declared
    floor whose declaration round has NO newer committed ``BENCH_r*``
    record. Info-class — the point is an explicit "this bar has never
    been verified by a committed run", not a build failure (the
    floor-arming logic already refuses to fail unachieved floors)."""
    if not rounds:
        return []
    newest = rounds[-1]
    newest_n = _round_number(newest.get("_round", ""))
    if newest_n is None:
        return []
    out: List[dict] = []
    for name, declared in sorted(FLOOR_DECLARED_ROUND.items()):
        if name not in DECLARED_FLOORS or newest_n > declared:
            continue
        out.append({
            "metric": name, "verdict": STALE,
            "value": newest.get(name),
            "expected": f">={DECLARED_FLOORS[name]:g} (declared floor)",
            "delta_pct": None,
            "note": f"floor declared in round {declared}; newest "
                    f"committed record is {newest['_round']} — no "
                    f"committed run verifies it yet",
        })
    return out


def judge_resilience(rounds: List[dict]) -> List[dict]:
    """Hard gate on the newest round's reconnect-storm phase (ISSUE 9):
    ``invariant_violations`` is a correctness count, not a perf number —
    any nonzero value (or a storm that errored out, recorded as −1)
    regresses regardless of bands or history. Rounds predating the
    phase produce no verdict."""
    if not rounds:
        return []
    storm = rounds[-1].get("reconnect_storm")
    if not isinstance(storm, dict):
        return []
    v = storm.get("invariant_violations")
    if not isinstance(v, (int, float)) or isinstance(v, bool):
        return []
    ok = v == 0
    return [{"metric": "reconnect_storm.invariant_violations",
             "verdict": FLAT if ok else REGRESS, "value": v,
             "expected": "0 (resilience invariant)", "delta_pct": None,
             "note": "acked ops exactly-once under the storm" if ok
             else ("storm errored" if v < 0
                   else "resilience invariant broken — see "
                        "docs/RESILIENCE.md")}]


def judge_overload(rounds: List[dict]) -> List[dict]:
    """Hard gate on the newest round's overload-storm phase (ISSUE 16):
    like the resilience gate, ``invariant_violations`` and
    ``silent_drops`` are correctness counts — any nonzero value (or a
    storm that errored out, recorded as −1) regresses regardless of
    bands or history. Rounds predating the phase produce no verdict."""
    if not rounds:
        return []
    storm = rounds[-1].get("overload_storm")
    if not isinstance(storm, dict):
        return []
    out: List[dict] = []
    for key, note_ok, note_bad in (
            ("invariant_violations",
             "exactly-once held under admission shedding",
             "overload invariant broken — see docs/OVERLOAD.md"),
            ("silent_drops",
             "every shed op explicitly throttled, none dropped",
             "shed work silently dropped — see docs/OVERLOAD.md")):
        v = storm.get(key)
        if not isinstance(v, (int, float)) or isinstance(v, bool):
            continue
        ok = v == 0
        out.append({"metric": f"overload_storm.{key}",
                    "verdict": FLAT if ok else REGRESS, "value": v,
                    "expected": "0 (overload invariant)",
                    "delta_pct": None,
                    "note": note_ok if ok
                    else ("storm errored" if v < 0 else note_bad)})
    return out


def judge_partition(rounds: List[dict]) -> List[dict]:
    """Gate on the newest round's ``partition_scaling`` phase (ISSUE
    18). Two verdict classes:

    - digest parity is a MUST-HOLD: the phase folds every sequenced
      window into the replicated shadow state on the virtual device
      mesh — any cross-replica disagreement (or an errored phase)
      regresses regardless of bands or history;
    - the speedup ratio vs the 1-partition baseline is info-class: it
      measures the host's core budget as much as the code (a 1-core
      host serializes the CPU-bound ``seq_dispatch`` stages, ratio
      ~1.0), so the absolute throughput bar rides the
      ``partition_columnar_ops_per_sec`` declared floor instead —
      armed once achieved, ``stale-record`` until a committed round
      verifies it.

    Rounds predating the phase produce no verdict."""
    if not rounds:
        return []
    ps = rounds[-1].get("partition_scaling")
    if not isinstance(ps, dict) or not ps:
        return []
    if "error" in ps:
        return [{"metric": "partition_scaling", "verdict": REGRESS,
                 "value": None, "expected": "phase completes",
                 "delta_pct": None,
                 "note": f"phase errored: {ps['error']}"}]
    out: List[dict] = []
    digest = ps.get("digest")
    if isinstance(digest, dict):
        if "agree_all" in digest:
            ok = bool(digest["agree_all"])
            out.append({
                "metric": "partition_scaling.digest_agree_all",
                "verdict": FLAT if ok else REGRESS, "value": ok,
                "expected": "true (replica digest parity)",
                "delta_pct": None,
                "note": f"{digest.get('windows', 0)} windows folded on "
                        f"{digest.get('devices', '?')} device(s)" if ok
                        else "cross-replica digest diverged — a replica "
                             "raced; see docs/DISTRIBUTED.md"})
        elif "skipped" in digest:
            out.append({
                "metric": "partition_scaling.digest_agree_all",
                "verdict": INFO, "value": None,
                "expected": "true (replica digest parity)",
                "delta_pct": None,
                "note": f"tap skipped: {digest['skipped']}"})
    speedup = ps.get("speedup_4x")
    if isinstance(speedup, (int, float)) and \
            not isinstance(speedup, bool):
        cores = ps.get("host_cores")
        out.append({
            "metric": "partition_scaling.speedup_4x",
            "verdict": INFO, "value": speedup,
            "expected": ">=2.5 on a multi-core host",
            "delta_pct": None,
            "note": f"4-partition storm vs 1-partition baseline on "
                    f"{cores} host core(s) — the ratio is core-bound, "
                    f"the absolute bar is the declared floor"})
    return out


def judge_read(rounds: List[dict]) -> List[dict]:
    """Gate on the newest round's ``read_fanout`` phase (ISSUE 20).

    Two structural gates — both are properties of the code, not the
    host, so they regress outright:

    - ``amortization_ratio_1024`` must stay <= 0.05: the per-subscriber
      marginal cost at 1024 subscribers as a fraction of the
      single-subscriber encode+deliver cost. Above the bar means the
      fanout is re-doing per-subscriber work the encode-once contract
      forbids;
    - ``catchup_speedup_4096`` must stay >= 5: the generation-diff
      catch-up vs full-tail replay at a 4096-op tail. Below the bar the
      device-computed diff stopped paying for itself.

    Staleness p99 is info-class here (the live SLO judges it against
    its bound); the absolute delivery throughput rides the
    ``read_delivery_ops_per_sec`` declared floor. Rounds predating the
    phase produce no verdict."""
    if not rounds:
        return []
    rf = rounds[-1].get("read_fanout")
    if not isinstance(rf, dict) or not rf or "skipped" in rf:
        return []
    if "error" in rf:
        return [{"metric": "read_fanout", "verdict": REGRESS,
                 "value": None, "expected": "phase completes",
                 "delta_pct": None,
                 "note": f"phase errored: {rf['error']}"}]
    out: List[dict] = []
    ratio = rf.get("amortization_ratio_1024")
    if isinstance(ratio, (int, float)) and not isinstance(ratio, bool):
        ok = ratio <= 0.05
        out.append({
            "metric": "read_fanout.amortization_ratio_1024",
            "verdict": FLAT if ok else REGRESS, "value": ratio,
            "expected": "<= 0.05 (encode-once contract)",
            "delta_pct": None,
            "note": "marginal per-subscriber cost is noise vs the "
                    "one-time encode" if ok else
                    "per-subscriber work crept into the fanout — a "
                    "copy or re-encode on the publish path"})
    speedup = rf.get("catchup_speedup_4096")
    if isinstance(speedup, (int, float)) and \
            not isinstance(speedup, bool):
        ok = speedup >= 5
        out.append({
            "metric": "read_fanout.catchup_speedup_4096",
            "verdict": FLAT if ok else REGRESS, "value": speedup,
            "expected": ">= 5x vs full-tail replay (4096-op tail)",
            "delta_pct": None,
            "note": "generation diff + short tail beats rehydration"
                    if ok else "the diff path lost its edge — gather "
                               "kernels or diff sizing regressed"})
    stale = rf.get("staleness_p99_s")
    if isinstance(stale, (int, float)) and not isinstance(stale, bool):
        out.append({
            "metric": "read_fanout.staleness_p99_s",
            "verdict": INFO, "value": stale,
            "expected": "< 2 s (read_staleness SLO bound)",
            "delta_pct": None,
            "note": "window delivery delay under the write storm with "
                    "64 live subscribers — the live SLO engine judges "
                    "the bound, this is the bench's sample"})
    return out


def judge_durability(rounds: List[dict],
                     spill_dir: Optional[str] = None) -> List[dict]:
    """Hard gate on durable-layer integrity (ISSUE 10): the newest
    round's ``durability`` phase reports ``chain_breaks`` from a scrub
    of its own spill — a correctness count like the resilience gate, so
    any nonzero value (or an errored phase, recorded as −1) regresses
    regardless of bands. With ``spill_dir`` the sentinel additionally
    runs the offline scrubber over that directory right now
    (``log_scrub --check`` semantics) and regresses on any break."""
    out: List[dict] = []
    if rounds:
        dur = rounds[-1].get("durability")
        if isinstance(dur, dict):
            v = dur.get("chain_breaks")
            if isinstance(v, (int, float)) and not isinstance(v, bool):
                ok = v == 0
                out.append({
                    "metric": "durability.chain_breaks",
                    "verdict": FLAT if ok else REGRESS, "value": v,
                    "expected": "0 (integrity invariant)",
                    "delta_pct": None,
                    "note": "bench spill verified clean" if ok
                    else ("durability phase errored" if v < 0
                          else "checksum chain broken — see "
                               "docs/DURABILITY.md")})
    if spill_dir:
        import log_scrub
        summary = log_scrub.summarize_reports(
            log_scrub.scrub_tree(spill_dir))
        ok = summary["chain_breaks"] == 0
        out.append({
            "metric": "scrub.chain_breaks",
            "verdict": FLAT if ok else REGRESS,
            "value": summary["chain_breaks"],
            "expected": "0 (integrity invariant)", "delta_pct": None,
            "note": f"scrubbed {summary['files']} files / "
                    f"{summary['records']} records in {spill_dir}"})
    return out


def has_regression(verdicts: List[dict]) -> bool:
    return any(v["verdict"] == REGRESS for v in verdicts)


def render_table(verdicts: List[dict], rounds: List[dict]) -> str:
    """Fixed-width verdict table, regressions first."""
    order = {REGRESS: 0, IMPROVE: 1, STALE: 2, NEW: 3, INFO: 4, FLAT: 5}
    rows = sorted(verdicts, key=lambda v: (order[v["verdict"]],
                                           v["metric"]))
    newest = rounds[-1]["_round"] if rounds else "?"
    head = (f"perf sentinel: {newest} vs {len(rounds) - 1} prior "
            f"round(s)")
    out = [head, "=" * len(head),
           f"{'METRIC':<36s} {'VERDICT':<8s} {'VALUE':>14s} "
           f"{'Δ%':>8s}  EXPECTED"]
    for v in rows:
        val = v["value"]
        val_s = f"{val:g}" if isinstance(val, float) else str(val)
        d = v["delta_pct"]
        out.append(
            f"{v['metric']:<36s} {v['verdict']:<8s} {val_s:>14s} "
            f"{'' if d is None else format(d, '+.1f'):>8s}  "
            f"{v['expected'] or v['note']}")
    counts: Dict[str, int] = {}
    for v in verdicts:
        counts[v["verdict"]] = counts.get(v["verdict"], 0) + 1
    out.append("-- " + "  ".join(f"{k}:{counts[k]}"
                                 for k in sorted(counts)))
    return "\n".join(out) + "\n"


# --------------------------------------------------------- BENCHES.md

TRAJECTORY_HEADING = "## Trajectory"


def trajectory_block(rounds: List[dict], verdicts: List[dict]) -> str:
    """One-line JSON per round (headline metrics only) + the newest
    round's non-flat verdicts — the fenced block under ## Trajectory."""
    lines = []
    for r in rounds:
        lines.append(json.dumps({
            "round": r["_round"],
            **{k: r[k] for k in ("value", "serving_ops_per_sec",
                                 "ack_p99_ms", "digest_parity")
               if k in r}}))
    notable = [v for v in verdicts if v["verdict"] not in (FLAT, NEW)]
    lines.append(json.dumps({
        "sentinel": {"regressions": [v["metric"] for v in notable
                                     if v["verdict"] == REGRESS],
                     "improvements": [v["metric"] for v in notable
                                      if v["verdict"] == IMPROVE]}}))
    return "\n".join(lines)


def write_md(root: Path, rounds: List[dict],
             verdicts: List[dict]) -> None:
    benches = root / "BENCHES.md"
    md = benches.read_text()
    if TRAJECTORY_HEADING not in md:
        md = md.rstrip("\n") + (
            f"\n\n{TRAJECTORY_HEADING} — sentinel view of all rounds"
            "\n\nRegenerated by `python tools/perf_sentinel.py "
            "--write-md`; one line per round, newest verdicts last.\n\n"
            "```json\n{}\n```\n")
    md = bench_report.update_section(
        md, TRAJECTORY_HEADING, trajectory_block(rounds, verdicts))
    benches.write_text(md)


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", type=Path,
                    default=Path(__file__).parent.parent)
    ap.add_argument("--rel-band", type=float, default=0.10,
                    help="relative band around the prior median")
    ap.add_argument("--k-sigma", type=float, default=3.0,
                    help="stdev multiplier for the variance band")
    ap.add_argument("--check", action="store_true",
                    help="quiet tier-1 mode: table only on regression")
    ap.add_argument("--write-md", action="store_true",
                    help="refresh the ## Trajectory section in BENCHES.md")
    ap.add_argument("--json", action="store_true",
                    help="print verdicts as JSON instead of the table")
    ap.add_argument("--spill-dir", default=None,
                    help="also scrub this spill directory now and fail "
                         "on any checksum-chain break")
    args = ap.parse_args(argv)

    rounds = load_trajectory(args.root)
    if len(rounds) < 2:
        print("perf_sentinel: fewer than 2 readable rounds; nothing to "
              "judge", file=sys.stderr)
        return 0
    verdicts = judge(rounds, rel_band=args.rel_band,
                     k_sigma=args.k_sigma)
    verdicts += judge_floors(rounds)
    verdicts += judge_staleness(rounds)
    verdicts += judge_resilience(rounds)
    verdicts += judge_overload(rounds)
    verdicts += judge_partition(rounds)
    verdicts += judge_read(rounds)
    verdicts += judge_durability(rounds, spill_dir=args.spill_dir)
    failed = has_regression(verdicts)
    if args.json:
        print(json.dumps(verdicts, indent=2))
    elif not args.check or failed:
        print(render_table(verdicts, rounds), end="")
    if args.write_md:
        write_md(args.root, rounds, verdicts)
        print(f"BENCHES.md {TRAJECTORY_HEADING!r} refreshed",
              file=sys.stderr)
    if args.check and not failed:
        # stale-record is info-class but must stay VISIBLE in the quiet
        # tier-1 mode: an unverified floor silently passing is the
        # failure mode this verdict exists to prevent
        for v in verdicts:
            if v["verdict"] == STALE:
                print(f"perf_sentinel: {STALE} — {v['metric']}: "
                      f"{v['note']}")
        print(f"perf_sentinel: OK — {len(verdicts)} metrics within band "
              f"across {len(rounds)} rounds")
    return 1 if failed else 0


if __name__ == "__main__":
    raise SystemExit(main())
