#!/usr/bin/env python
"""Randomized chaos soak over the resilience plane (ISSUE 9 tentpole).

One seeded run drives the full session-resilience story end to end:

- a real ingress service (``AlfredServer`` over a ``LocalService`` with a
  JSONL spill) on a fixed port,
- several :class:`drivers.resilient.ResilientConnection` clients — one
  doc each (single-writer), mixed op families (string / tree / matrix
  contents from ``testing.chaos.OpGen``), every op's contents stamped
  with a unique marker,
- a randomized fault scheduler, all draws from ONE seeded rng so a run
  replays exactly:

  * **connection kills** — a random client's socket is hard-closed
    mid-traffic (the reconnect/resubmit path),
  * **process crash-restarts** — the server thread is torn down, the
    service recovered from its spill (``LocalService.recover``) and
    re-served on the SAME port (the durable-dedup + resync-renumber
    path; every client rides across the restart),
  * **probabilistic faultpoints** — ``deli.sequence.mid_window`` armed
    with a small crash probability (burned clientSeqs) and a stall
    probability (delayed acks) via
    :class:`utils.faultpoints.ProbabilisticPlan`.

After the storm every client drains (``wait_idle``) and the durable
deltas stream is audited against each client's own ledger:

1. **exactly-once**: every acked op's marker appears in the durable
   stream exactly once, at exactly the seq the ack reported — a lost
   acked op or a double-applied resubmit both fail here;
2. **no strays**: the durable op set equals the acked set (single-writer
   docs + full drain ⇒ nothing else may appear);
3. **order**: per doc, seqs are strictly increasing and the marker
   sequence equals the client's submission order — the same digest a
   fault-free run produces, which is the digest-parity acceptance check
   without needing a second run;
4. **monotone seq space**: no seq is ever reused across the restarts.

The first violation increments ``soak_invariant_violations_total``,
notes + dumps the flight recorder (``chaos_soak``), and raises
:class:`SoakViolation` with the evidence. A clean run returns a report
dict (ops, acks, reconnects, resubmits, dup-acks, restarts, faultpoint
fires/stalls, per-doc digests).

Usage::

    python tools/chaos_soak.py --seed 7 --steps 400 --clients 4
    python tools/chaos_soak.py --seed 7 --quick      # the tier-1 profile
    python tools/chaos_soak.py --quick --corrupt     # + seeded disk rot
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import tempfile
import threading
import time
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
# sibling tools (log_scrub) are importable regardless of how this module
# was loaded (CLI, pytest importlib spec, bench subprocess)
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

from fluidframework_tpu.core.protocol import MessageType          # noqa: E402
from fluidframework_tpu.drivers.resilient import ResilientConnection  # noqa: E402,E501
from fluidframework_tpu.server.ingress import AlfredServer        # noqa: E402
from fluidframework_tpu.server.oplog import (                     # noqa: E402
    OplogCorruptionError, scan_chained_spill,
)
from fluidframework_tpu.server.tinylicious import LocalService    # noqa: E402
from fluidframework_tpu.testing.chaos import OpGen                # noqa: E402
from fluidframework_tpu.utils import flight_recorder              # noqa: E402
from fluidframework_tpu.utils.faultpoints import (                # noqa: E402
    SITE_DELI_MID_WINDOW, ProbabilisticPlan, armed, corrupt_file,
)
from fluidframework_tpu.utils.telemetry import REGISTRY           # noqa: E402

#: op families cycled across the soak's clients
FAMILIES = ("string", "tree", "matrix")


class SoakViolation(AssertionError):
    """An invariant the resilience plane guarantees was broken."""


def _violate(kind: str, **evidence) -> None:
    REGISTRY.inc("soak_invariant_violations_total")
    flight_recorder.note("soak_invariant_violation", kind=kind,
                         **{k: v for k, v in evidence.items()
                            if isinstance(v, (int, float, str, bool))})
    try:
        flight_recorder.dump("chaos_soak", extra={"kind": kind})
    except OSError:
        pass
    raise SoakViolation(f"{kind}: {evidence}")


def _inject_raw_corruption(spill_dir: str, rng: random.Random) -> dict:
    """Corrupt ONE random raw-deltas spill segment (seeded) and assert
    the checksum chain SEES it before anything could apply it.

    Only the RAW log is targeted: its backlog is never re-fed on
    recovery, so repair-by-truncation cannot lose an acked op and the
    exactly-once audit stays meaningful. Only bitflip/splice are drawn —
    a random truncation can land exactly on a line boundary, which is
    indistinguishable from a benign crash torn-tail by design (the
    summary chain anchor, not the local scan, owns that case).

    Returns the evidence dict (kind, path, detected) — or ``detected:
    None`` when no non-empty raw segment exists yet to corrupt."""
    targets = sorted(
        p for p in (os.path.join(spill_dir, n)
                    for n in os.listdir(spill_dir)
                    if n.startswith("rawdeltas-p") and n.endswith(".jsonl"))
        if os.path.getsize(p) > 0)
    if not targets:
        return {"kind": None, "path": None, "detected": None}
    path = targets[rng.randrange(len(targets))]
    kind = ("bitflip", "splice")[rng.randrange(2)]
    ev = corrupt_file(path, kind, rng)
    if ev.get("skipped"):
        return {**ev, "detected": None}
    scan = scan_chained_spill(path)
    detected = bool(scan["problems"]) or scan["torn"]
    if detected:
        REGISTRY.inc("soak_corruption_detected_total")
    else:
        # the whole point of the chain: injected rot MUST be visible
        _violate("corruption_undetected", **{
            k: v for k, v in ev.items()
            if isinstance(v, (int, float, str, bool))})
    return {**ev, "detected": detected}


class _Cluster:
    """The server side of the soak: one LocalService + AlfredServer on a
    fixed port, restartable in place (crash + recover-from-spill)."""

    def __init__(self, spill_dir: str, n_partitions: int = 2,
                 corrupt_mode: bool = False):
        self.spill_dir = spill_dir
        self.n_partitions = n_partitions
        self.corrupt_mode = corrupt_mode
        self.corruption_repairs = 0
        self.service = LocalService(n_partitions=n_partitions,
                                    spill_dir=spill_dir)
        self.server = AlfredServer(self.service).start_in_thread()
        self.port = self.server.port
        self.restarts = 0

    def crash_restart(self) -> None:
        """Kill the serving process (thread) without any shutdown
        courtesy, then recover the service from its spill and re-serve
        on the same port — what a supervisor restart looks like to the
        clients (dead sockets, then a resync against a higher epoch).

        In ``--corrupt`` mode a recovery refused for a checksum-chain
        break (OplogCorruptionError — the injected rot was DETECTED, not
        applied) runs the offline scrubber with ``--repair`` semantics
        over the spill, then recovers again; outside corrupt mode the
        error propagates (a clean soak must never see one)."""
        # the heavy-hitter sketch outlives the incarnation: the ops
        # plane (if attached) keeps one whole-soak hot-doc view instead
        # of resetting on every supervisor restart
        hotdocs = self.server.hotdocs
        self.server.stop()
        self.service.close()
        try:
            self.service = LocalService.recover(
                self.spill_dir, n_partitions=self.n_partitions)
        except OplogCorruptionError:
            if not self.corrupt_mode:
                raise
            import log_scrub
            reports = log_scrub.scrub_tree(self.spill_dir, repair=True)
            self.corruption_repairs += sum(
                1 for r in reports if r.get("repaired"))
            self.service = LocalService.recover(
                self.spill_dir, n_partitions=self.n_partitions)
        self.server = AlfredServer(
            self.service, port=self.port).start_in_thread()
        self.server.hotdocs = hotdocs
        self.restarts += 1

    def stop(self) -> None:
        self.server.stop()
        self.service.close()


def run_soak(seed: int = 0, steps: int = 400, n_clients: int = 4,
             kill_p: float = 0.01, restarts: int = 3,
             crash_p: float = 0.002, stall_p: float = 0.01,
             stall_s: float = 0.005, spill_dir: Optional[str] = None,
             idle_timeout: float = 30.0, corrupt: bool = False,
             ops_port: Optional[int] = None) -> dict:
    """Run one seeded soak; returns the report dict or raises
    :class:`SoakViolation` / :class:`TimeoutError`. ``ops_port``
    attaches a live :class:`server.opsd.OpsServer` (ticker ON — the
    soak has no control loop of its own) that rides across every
    crash-restart."""
    rng = random.Random(seed)
    tmp = None
    if spill_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="chaos_soak_")
        spill_dir = tmp.name
    cluster = _Cluster(spill_dir, corrupt_mode=corrupt)
    ops = None
    if ops_port is not None:
        from fluidframework_tpu.server import opsd
        ops = opsd.OpsServer(port=ops_port, registry=REGISTRY)
        ops.add_hotdocs(cluster.server.hotdocs)
        ops.start()
    # restart schedule: distinct step indices drawn up front so the
    # run is replayable and the restart count is exact, not expected
    restart_at = set(rng.sample(range(steps // 4, steps),
                                min(restarts, max(1, steps - steps // 4))))
    plan = ProbabilisticPlan(rng=random.Random(rng.randrange(2**31)))
    plan.arm(SITE_DELI_MID_WINDOW, crash_p)
    plan.arm_stall(SITE_DELI_MID_WINDOW, stall_p, stall_s)

    clients: List[ResilientConnection] = []
    gens: Dict[str, OpGen] = {}
    submitted: Dict[str, List[str]] = {}     # doc → markers, in order
    uid_marker: Dict[str, Dict[int, str]] = {}   # doc → uid → marker
    t0 = time.perf_counter()
    kills = 0
    corruptions: List[dict] = []
    try:
        with armed(plan):
            for i in range(n_clients):
                doc = f"soak-{i}"
                fam = FAMILIES[i % len(FAMILIES)]
                gens[doc] = OpGen(random.Random(rng.randrange(2**31)),
                                  fam, [doc])
                submitted[doc] = []
                uid_marker[doc] = {}
                clients.append(ResilientConnection(
                    "127.0.0.1", cluster.port, doc,
                    rng=random.Random(rng.randrange(2**31)),
                    attempts=12))
            for step in range(steps):
                ci = rng.randrange(n_clients)
                conn = clients[ci]
                doc = conn.doc_id
                marker = f"{doc}:{step}"
                op = dict(gens[doc].op(doc), u=marker)
                uid = conn.submit(op)
                submitted[doc].append(marker)
                uid_marker[doc][uid] = marker
                if rng.random() < kill_p:
                    kills += 1
                    clients[rng.randrange(n_clients)].kill_socket()
                if step in restart_at:
                    # let in-flight traffic settle a beat so the restart
                    # catches a mix of durable and in-flight ops
                    time.sleep(0.02)
                    if corrupt:
                        # rot the raw spill between the crash and the
                        # recover — the window real disk damage lives in
                        ev = _inject_raw_corruption(spill_dir, rng)
                        if ev["detected"] is not None:
                            corruptions.append(ev)
                    cluster.crash_restart()
            # drain: every submitted op must end acked (resubmission
            # across kills/restarts is the plane under test)
            for conn in clients:
                if not conn.wait_idle(timeout=idle_timeout):
                    _violate("drain_timeout", doc=conn.doc_id,
                             pending=conn.pending_count,
                             reconnects=conn.reconnects)
                if conn.nacks:
                    _violate("genuine_nack", doc=conn.doc_id,
                             n=len(conn.nacks))
        _audit(cluster.service, clients, submitted, uid_marker)
        lat = sorted(t for c in clients for t in c.reconnect_latencies)
        report = {
            "seed": seed, "steps": steps, "clients": n_clients,
            "ops_submitted": sum(len(v) for v in submitted.values()),
            "ops_acked": sum(len(c.op_acks) for c in clients),
            "reconnects": sum(c.reconnects for c in clients),
            "resubmits": sum(c.resubmits for c in clients),
            "dup_acked": sum(c.dup_acked for c in clients),
            "socket_kills": kills,
            "restarts": cluster.restarts,
            "faultpoint_fires": sum(plan.fires.values()),
            "faultpoint_stalls": sum(plan.stalls.values()),
            "corruptions_injected": len(corruptions),
            "corruptions_detected": sum(
                1 for ev in corruptions if ev["detected"]),
            "corruption_repairs": cluster.corruption_repairs,
            "final_epoch": max(c.epoch for c in clients),
            "violations": 0,
            "elapsed_s": round(time.perf_counter() - t0, 3),
            "reconnect_p50_ms": round(
                lat[len(lat) // 2] * 1000, 2) if lat else 0.0,
            "reconnect_p99_ms": round(
                lat[min(len(lat) - 1, int(len(lat) * 0.99))] * 1000,
                2) if lat else 0.0,
            "digest": {d: len(v) for d, v in submitted.items()},
        }
        return report
    finally:
        for conn in clients:
            conn.close()
        if ops is not None:
            ops.stop()
        cluster.stop()
        if tmp is not None:
            tmp.cleanup()


def _audit(service: LocalService, clients, submitted, uid_marker) -> None:
    """Audit the durable stream against every client's own ledger."""
    for conn in clients:
        doc = conn.doc_id
        durable = [m for m in service.get_deltas(doc, 0)
                   if m.type == MessageType.OP]
        seqs = [m.seq for m in durable]
        if any(b <= a for a, b in zip(seqs, seqs[1:])):
            _violate("seq_not_monotone", doc=doc)
        markers = [(m.contents or {}).get("u") for m in durable]
        if len(set(markers)) != len(markers):
            dup = sorted(m for m in set(markers)
                         if markers.count(m) > 1)[0]
            _violate("double_applied", doc=doc, marker=str(dup))
        acked = {uid_marker[doc][uid]: seq
                 for uid, seq in conn.op_acks.items()}
        for m, seq in zip(markers, seqs):
            if m not in acked:
                _violate("stray_unacked_op", doc=doc, marker=str(m))
            if acked[m] != seq:
                _violate("ack_seq_mismatch", doc=doc, marker=str(m),
                         acked_seq=acked[m], durable_seq=seq)
        lost = sorted(set(acked) - set(markers))
        if lost:
            _violate("lost_acked_op", doc=doc, marker=lost[0],
                     n_lost=len(lost))
        # fault-free digest parity: single-writer doc + full drain ⇒ the
        # durable marker sequence IS the submission order
        if markers != submitted[doc]:
            _violate("order_divergence", doc=doc,
                     durable=len(markers), expected=len(submitted[doc]))


def run_partition_drill(seed: int = 0, n_partitions: int = 4,
                        docs_per_partition: int = 8, waves: int = 6,
                        n_clients: int = 3,
                        spill_dir: Optional[str] = None) -> dict:
    """Partitioned-serving failover drill (ISSUE 18): kill ONE Deli
    partition mid-storm, promote its ``OplogFollower``, and audit that

    1. the surviving partitions kept sequencing during the outage (no
       global stall — their waves ack while the victim is dead),
    2. exactly-once holds per (doc, cseq) across the promotion (acks
       arrive once, seq > 0, no marker applies twice),
    3. per-session clientSeq contiguity holds ACROSS partition
       boundaries: every client writes docs on several partitions
       through one socket, and after the failover each doc's dedup
       cursor (join-time ``lcs``) equals exactly the waves acked — the
       per-partition dedup ledgers never tore a session,
    4. per-doc ordering matches submission order (durable stream parity
       with the oracle text), and seqs stay strictly monotone,
    5. the deposed leader is FENCED (its next durable append raises).

    Deterministic by construction: one socket per client, waves drained
    in phases (pre-kill / outage / post-promotion), the pipelined
    executors still overlap N partitions' sequencing inside each phase.
    """
    import numpy as np
    from fluidframework_tpu.server.columnar_ingress import (
        _OP_DTYPE, ColumnarAlfred, ColumnarClient)
    from fluidframework_tpu.server.oplog import FencedWriterError
    from fluidframework_tpu.server.partitioned import (
        PartitionedStringServing)

    rng = random.Random(seed)
    tmp = None
    if spill_dir is None:
        tmp = tempfile.TemporaryDirectory(prefix="partition_drill_")
        spill_dir = tmp.name
    svc = PartitionedStringServing(n_partitions=n_partitions,
                                   docs_per_partition=docs_per_partition,
                                   capacity=1024, spill_dir=spill_dir)
    door = ColumnarAlfred(svc, window_min_rows=16, window_ms=2.0,
                          pipeline_depth=2).start_in_thread()
    victim = rng.randrange(n_partitions)
    # every client owns docs touching EVERY partition (cross-partition
    # sessions are the point); single writer per doc keeps the ordering
    # audit exact
    docs_of: Dict[int, List[str]] = {}
    names = iter(f"pd-{seed}-{i}" for i in range(10 ** 6))
    for c in range(n_clients):
        mine: List[str] = []
        need = set(range(n_partitions))
        while need:
            d = next(names)
            p = svc.partition_of_doc(d)
            if p in need:
                need.discard(p)
                mine.append(d)
        docs_of[c] = mine
    clients = [ColumnarClient("127.0.0.1", door.port)
               for _ in range(n_clients)]
    rows_of = [cl.join(docs_of[c]) for c, cl in enumerate(clients)]
    acks: Dict[Tuple[int, str], Dict[int, int]] = {
        (c, d): {} for c in range(n_clients) for d in docs_of[c]}
    sent: Dict[Tuple[int, str], int] = {k: 0 for k in acks}
    t0 = time.perf_counter()

    def send_wave(c: int, w: int, docs: List[str]) -> None:
        ops = np.zeros(len(docs), _OP_DTYPE)
        for i, d in enumerate(docs):
            ops[i] = (rows_of[c][d], 0, 0, 0, 0, sent[(c, d)] + 1, 0)
            sent[(c, d)] += 1
        clients[c].send_ops([f"w{w}_"], ops)

    def drain(c: int, expect: int) -> None:
        got = 0
        deadline = time.time() + 30
        while got < expect:
            if time.time() > deadline:
                _violate("partition_drain_timeout", client=c,
                         expected=expect, got=got)
            fr = clients[c].recv_json()
            if fr.get("t") != "acks":
                _violate("partition_unexpected_frame", client=c,
                         frame=str(fr.get("t")))
            row_doc = {rows_of[c][d]: d for d in docs_of[c]}
            for (cs, seq), r in zip(fr["acks"], fr["rows"]):
                d = row_doc[r]
                if seq <= 0:
                    _violate("partition_nack", client=c, doc=d,
                             cseq=int(cs), code=int(seq))
                if cs in acks[(c, d)]:
                    _violate("partition_double_ack", client=c, doc=d,
                             cseq=int(cs))
                acks[(c, d)][int(cs)] = int(seq)
                got += 1

    pre = waves // 2
    for w in range(pre):
        for c in range(n_clients):
            send_wave(c, w, docs_of[c])
    for c in range(n_clients):
        drain(c, pre * len(docs_of[c]))

    # --- outage: kill the victim partition's leader mid-storm --------
    svc.attach_follower(victim)
    deposed = svc.engines[victim]
    svc.kill_partition(victim)
    outage_waves = 2
    survivors = {c: [d for d in docs_of[c]
                     if svc.partition_of_doc(d) != victim]
                 for c in range(n_clients)}
    for w in range(pre, pre + outage_waves):
        for c in range(n_clients):
            send_wave(c, w, survivors[c])
    for c in range(n_clients):
        # no global stall: the surviving partitions' acks arrive while
        # the victim is dead
        drain(c, outage_waves * len(survivors[c]))

    # --- failover: fence the deposed leader, promote the follower ----
    svc.promote(victim)
    door.rebind_executor(victim)
    try:
        deposed.log.open_for_append(deposed.writer_epoch)
        _violate("deposed_leader_not_fenced", partition=victim)
    except FencedWriterError:
        pass

    for w in range(pre + outage_waves, waves + outage_waves):
        for c in range(n_clients):
            send_wave(c, w, docs_of[c])
    for c in range(n_clients):
        drain(c, (waves - pre) * len(docs_of[c]))

    # --- audits ------------------------------------------------------
    for c in range(n_clients):
        for d in docs_of[c]:
            got = acks[(c, d)]
            want = sent[(c, d)]
            # exactly-once + per-session cseq contiguity: every cseq
            # 1..N acked exactly once, across the partition boundary
            if sorted(got) != list(range(1, want + 1)):
                _violate("cseq_gap", client=c, doc=d, acked=len(got),
                         submitted=want)
            seqs = [got[cs] for cs in sorted(got)]
            if any(b <= a for a, b in zip(seqs, seqs[1:])):
                _violate("seq_not_monotone", doc=d)
            # ordering parity: inserts at 0 ⇒ the oracle text is the
            # wave markers in reverse submission order
            ws = [w for w in range(waves + outage_waves)
                  if not (pre <= w < pre + outage_waves
                          and d not in survivors[c])]
            expect = "".join(f"w{w}_" for w in reversed(ws))
            txt = svc.read_text(d)
            if txt != expect:
                _violate("order_divergence", doc=d, got=txt,
                         expected=expect)
    # dedup-ledger continuity: a resumed session sees lcs == waves acked
    # per doc, including docs on the promoted partition
    probe = ColumnarClient("127.0.0.1", door.port)
    probe.join(docs_of[0], client_id=clients[0].client_id)
    for d in docs_of[0]:
        if probe.lcs.get(d, 0) != sent[(0, d)]:
            _violate("dedup_cursor_lost", doc=d,
                     lcs=int(probe.lcs.get(d, 0)),
                     submitted=sent[(0, d)])
    probe.close()
    report = {
        "seed": seed, "partitions": n_partitions, "victim": victim,
        "clients": n_clients, "waves": waves + outage_waves,
        "ops_submitted": sum(sent.values()),
        "ops_acked": sum(len(v) for v in acks.values()),
        "outage_acked_ops": outage_waves * sum(
            len(survivors[c]) for c in range(n_clients)),
        "promotions": 1, "violations": 0,
        "elapsed_s": round(time.perf_counter() - t0, 3),
    }
    for cl in clients:
        cl.close()
    door.stop()
    if tmp is not None:
        tmp.cleanup()
    return report


def main() -> None:
    ap = argparse.ArgumentParser(
        description="randomized resilience soak (see module docstring)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--steps", type=int, default=400)
    ap.add_argument("--clients", type=int, default=4)
    ap.add_argument("--restarts", type=int, default=3)
    ap.add_argument("--kill-p", type=float, default=0.01)
    ap.add_argument("--crash-p", type=float, default=0.002)
    ap.add_argument("--quick", action="store_true",
                    help="tier-1 profile: small, seeded, ~seconds")
    ap.add_argument("--corrupt", action="store_true",
                    help="inject seeded disk rot (bitflip/splice) into "
                         "the raw spill before each restart; the run "
                         "fails unless every corruption is detected by "
                         "the checksum chain before apply")
    ap.add_argument("--ops-port", type=int, default=None,
                    help="serve the live ops plane (/metrics, /healthz, "
                         "/debug/flights, ...) on this port; it rides "
                         "across crash-restarts (0 = ephemeral)")
    ap.add_argument("--partitions", type=int, default=None,
                    help="run the partitioned-serving failover drill "
                         "(ISSUE 18) over N Deli partitions: kill one "
                         "partition mid-storm, promote its "
                         "OplogFollower, audit exactly-once/ordering/"
                         "cseq-contiguity while the peers keep serving")
    args = ap.parse_args()
    if args.quick:
        args.steps, args.clients, args.restarts = 150, 3, 3
    if args.partitions is not None:
        report = run_partition_drill(seed=args.seed,
                                     n_partitions=args.partitions,
                                     n_clients=args.clients)
        print(json.dumps(report, indent=2, sort_keys=True))
        return
    report = run_soak(seed=args.seed, steps=args.steps,
                      n_clients=args.clients, restarts=args.restarts,
                      kill_p=args.kill_p, crash_p=args.crash_p,
                      corrupt=args.corrupt, ops_port=args.ops_port)
    print(json.dumps(report, indent=2, sort_keys=True))


if __name__ == "__main__":
    main()
