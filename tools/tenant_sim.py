#!/usr/bin/env python
"""Multi-tenant overload simulator + soak gate (ISSUE 16 tentpole).

Where ``tools/chaos_soak.py`` proves the resilience plane under faults,
this drives the ADMISSION plane under load: several tenants — mixed
writer/reader roles, one op family each (string / map / matrix / tree
from ``testing.chaos.OpGen``) — push paced traffic through resilient
clients into a real ingress service fronted by a
:class:`server.admission.AdmissionController`, with the
:class:`~fluidframework_tpu.server.admission.ControlPolicy` AIMD loop
ticking against a live SLO scorecard the whole time. One tenant is
ABUSIVE: it offers a multiple of its declared budget (default 5×), so
aggregate offered load lands near 2× aggregate capacity.

Traffic shape:

- **Zipf doc popularity** — each session picks its document from a
  seeded Zipf draw, so a few hot docs absorb most sessions (the shape
  that makes per-doc budgets meaningful).
- **bursty arrival/churn** — sessions churn mid-storm (an idle writer
  retires and a fresh session joins on a new doc draw) and one seeded
  arrival burst adds sessions to a random tenant; readers churn too.
- **closed control loop** — a ``TimeSeriesStore`` samples the registry
  (including the sim's live ``ack_p99_ms`` gauge over recently-acked
  never-throttled ops) and ``ControlPolicy.tick`` moves the budget
  scale / shed probability on SLO burn. Only ``scorecard()`` is
  consulted — the control loop itself never fires breach flight dumps.

After the storm the abusive tenant's budget is re-declared at its
offered rate (the operator lifting the brake) and every session drains.
The audit then holds the admission plane to the resilience plane's bar:

1. **zero silent drops** — every offered op is eventually acked; shed
   ops were parked behind ``throttled`` frames and resubmitted with the
   SAME clientSeq, never lost, never renumbered;
2. **exactly-once, in order** — per doc: seqs strictly increasing, no
   marker appears twice, the durable set equals the acked set, and each
   session's durable subsequence equals its submission order;
3. **abusive overage visibly shed** — the abusive tenant saw throttled
   frames and the controller's per-tenant ledger shows its shed count;
4. **admitted traffic met its SLO** — p99 ack latency of never-
   throttled ops is under the objective, and compliant tenants' goodput
   at storm end is at least ``goodput_min`` of what they offered.

Violations go through ``chaos_soak._violate`` (counter + flight dump +
:class:`chaos_soak.SoakViolation`). A clean run returns a report dict;
``--check`` exits 1 unless every gate passes.

Usage::

    python tools/tenant_sim.py --seed 7 --duration 6
    python tools/tenant_sim.py --quick --check     # the tier-1 profile
"""

from __future__ import annotations

import argparse
import json
import os
import random
import sys
import time
from dataclasses import dataclass, field
from typing import Dict, List, Optional

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
# sibling tools (chaos_soak's violation machinery) are importable
# regardless of how this module was loaded (CLI, pytest, bench)
sys.path.insert(1, os.path.dirname(os.path.abspath(__file__)))

import chaos_soak                                                  # noqa: E402
from fluidframework_tpu.core.protocol import MessageType           # noqa: E402
from fluidframework_tpu.drivers.resilient import ResilientConnection  # noqa: E402,E501
from fluidframework_tpu.server.admission import (                  # noqa: E402
    AdmissionController, ControlPolicy,
)
from fluidframework_tpu.server.ingress import AlfredServer         # noqa: E402
from fluidframework_tpu.server.tinylicious import LocalService     # noqa: E402
from fluidframework_tpu.testing.chaos import FAMILIES, OpGen       # noqa: E402
from fluidframework_tpu.utils import slo as slo_mod                # noqa: E402
from fluidframework_tpu.utils.telemetry import REGISTRY            # noqa: E402
from fluidframework_tpu.utils.timeseries import TimeSeriesStore    # noqa: E402

_violate = chaos_soak._violate
SoakViolation = chaos_soak.SoakViolation


@dataclass
class TenantSpec:
    """One tenant's declared budget and traffic shape. ``load`` is the
    offered-rate multiplier over the budget: 1.0 is a compliant tenant,
    anything above deliberately overdrives its bucket (the abusive
    tenant runs at 5×). ``role`` ``reader`` sessions never submit —
    they ride the broadcast stream of their Zipf-drawn doc."""

    name: str
    rate: float                  # declared budget, ops/sec
    clients: int = 1
    family: str = "string"
    role: str = "writer"         # "writer" | "reader"
    load: float = 1.0

    @property
    def offered_rate(self) -> float:
        return self.rate * self.load if self.role == "writer" else 0.0


class _Session:
    """One resilient client session plus its audit ledger."""

    _next = 0

    def __init__(self, spec: TenantSpec, doc: str, port: int,
                 rng: random.Random):
        _Session._next += 1
        self.key = f"{spec.name}.s{_Session._next}"
        self.spec = spec
        self.doc = doc
        self.gen = OpGen(random.Random(rng.randrange(2 ** 31)),
                         spec.family, [doc])
        self.submitted: List[str] = []       # markers, in order
        self.uid_marker: Dict[int, str] = {}
        self.submit_t: Dict[int, float] = {}
        self.ack_t: Dict[int, float] = {}
        self.ops_observed = 0                # reader-side broadcasts
        self.credit = 0.0
        on_op = (lambda msg: setattr(
            self, "ops_observed", self.ops_observed + 1)) \
            if spec.role == "reader" else None
        self.conn = ResilientConnection(
            "127.0.0.1", port, doc,
            rng=random.Random(rng.randrange(2 ** 31)),
            tenant=spec.name, on_op=on_op,
            on_ack=lambda uid, seq: self.ack_t.setdefault(
                uid, time.monotonic()))

    def offer(self, n: int) -> None:
        for _ in range(n):
            i = len(self.submitted)
            marker = f"{self.key}#{i}"
            op = dict(self.gen.op(self.doc), u=marker)
            t0 = time.monotonic()
            uid = self.conn.submit(op)
            self.submitted.append(marker)
            self.uid_marker[uid] = marker
            self.submit_t[uid] = t0

    def admitted_latencies_ms(self) -> List[float]:
        """Ack latencies of ops that were NEVER throttled — the
        admitted-traffic view the latency SLO judges (a shed op's
        latency includes the deliberate backoff by design)."""
        shed = self.conn.throttled_uids
        return [(self.ack_t[u] - self.submit_t[u]) * 1000.0
                for u in self.ack_t
                if u not in shed and u in self.submit_t]


def _zipf_picker(n_docs: int, exponent: float, rng: random.Random):
    """Seeded Zipf draw over doc indices: P(k) ∝ 1/(k+1)^s."""
    weights = [1.0 / (k + 1) ** exponent for k in range(n_docs)]
    total = sum(weights)
    cumulative = []
    acc = 0.0
    for w in weights:
        acc += w / total
        cumulative.append(acc)

    def pick() -> str:
        r = rng.random()
        for k, c in enumerate(cumulative):
            if r <= c:
                return f"ts-{k}"
        return f"ts-{n_docs - 1}"
    return pick


def _p99(values: List[float]) -> float:
    if not values:
        return 0.0
    ordered = sorted(values)
    return ordered[min(len(ordered) - 1, int(len(ordered) * 0.99))]


@dataclass
class _Gates:
    """Acceptance thresholds the --check mode enforces."""

    goodput_min: float = 0.8
    slo_ms: float = 200.0
    failures: List[str] = field(default_factory=list)

    def expect(self, ok: bool, what: str) -> None:
        if not ok:
            self.failures.append(what)


def default_tenants(quick: bool) -> List[TenantSpec]:
    """Three compliant writers (distinct op families), one reader
    tenant, one abusive writer at 5× budget: aggregate offered load =
    (3 + 5) / 4 = 2× aggregate declared capacity."""
    rate = 60.0 if quick else 150.0
    return [
        TenantSpec("acme", rate, clients=2, family=FAMILIES[0]),
        TenantSpec("blue", rate, clients=2, family=FAMILIES[1]),
        TenantSpec("casa", rate, clients=1, family=FAMILIES[2]),
        TenantSpec("dash", rate, clients=2, family=FAMILIES[3],
                   role="reader"),
        TenantSpec("evil", rate, clients=1, family=FAMILIES[0],
                   load=5.0),
    ]


def run_sim(seed: int = 0, duration_s: float = 6.0,
            tenants: Optional[List[TenantSpec]] = None,
            n_docs: int = 8, zipf_exponent: float = 1.2,
            slo_ms: float = 200.0, goodput_min: float = 0.8,
            control_every_s: float = 0.05, churn_p: float = 0.3,
            idle_timeout: float = 30.0, quick: bool = False,
            ops_port: Optional[int] = None,
            n_partitions: int = 4) -> dict:
    """Run one seeded storm; returns the report dict or raises
    :class:`SoakViolation` on an audit failure. ``ops_port`` attaches a
    live :class:`server.opsd.OpsServer` for the storm's duration —
    ticker disabled, since the sim's own control loop already samples
    the store (and the control loop must stay the only SLO judge)."""
    rng = random.Random(seed)
    tenants = tenants if tenants is not None else default_tenants(quick)
    writers = [t for t in tenants if t.role == "writer"]
    abusive = [t for t in writers if t.load > 1.0]
    adm = AdmissionController(
        tenants={t.name: t.rate for t in writers},
        rng=random.Random(rng.randrange(2 ** 31)))
    store = TimeSeriesStore(registry=REGISTRY)
    engine = slo_mod.SLOEngine(store, specs=[
        slo_mod.SLOSpec.parse(f"ack_p99_ms < {slo_ms}",
                              name="admitted_ack_p99",
                              fast_window_s=0.6, slow_window_s=2.0),
    ])
    policy = ControlPolicy(adm, engine)
    # --partitions N (ISSUE 18): width of the service's partitioned
    # oplogs — the exactly-once / per-session order audits must hold at
    # any partition count, since doc→partition fan-out changes which
    # appends contend but never the per-doc total order
    service = LocalService(n_partitions=n_partitions)
    server = AlfredServer(service, admission=adm).start_in_thread()
    ops = None
    if ops_port is not None:
        from fluidframework_tpu.server import opsd
        ops = opsd.OpsServer(port=ops_port, registry=REGISTRY,
                             store=store, slo_engine=engine,
                             tick_interval_s=0.0)
        ops.add_hotdocs(server.hotdocs)
        ops.start()
    pick_doc = _zipf_picker(n_docs, zipf_exponent, rng)

    sessions: Dict[str, List[_Session]] = {t.name: [] for t in tenants}
    retired: List[_Session] = []
    churns = 0
    bursts = 0
    policy_trace: List[dict] = []
    recent_lat: List[float] = []     # rolling admitted-ack window

    def spawn(spec: TenantSpec) -> None:
        doc = pick_doc()
        if spec.role == "reader" and not sessions[spec.name]:
            doc = "ts-0"     # first reader rides the hottest doc
        sessions[spec.name].append(_Session(spec, doc, server.port, rng))

    t0 = time.monotonic()
    try:
        for spec in tenants:
            for _ in range(spec.clients):
                spawn(spec)
        burst_at = t0 + duration_s * rng.uniform(0.3, 0.6)
        next_ctl = t0 + control_every_s
        last = time.monotonic()
        storm_acked: Dict[str, int] = {}
        storm_offered: Dict[str, int] = {}
        while True:
            now = time.monotonic()
            if now - t0 >= duration_s:
                break
            dt = now - last
            last = now
            for spec in writers:
                active = sessions[spec.name]
                if not active:
                    continue
                per_session = spec.offered_rate / len(active)
                for sess in active:
                    sess.credit += per_session * dt
                    n = int(sess.credit)
                    if n:
                        sess.credit -= n
                        sess.offer(n)
            if burst_at is not None and now >= burst_at:
                burst_at = None
                bursts += 1
                lucky = writers[rng.randrange(len(writers))]
                spawn(lucky)
                spawn(lucky)
            if now >= next_ctl:
                next_ctl = now + control_every_s
                fresh = [lat for sess_list in sessions.values()
                         for sess in sess_list
                         for lat in sess.admitted_latencies_ms()]
                recent_lat = fresh[-512:]
                REGISTRY.set_gauge("ack_p99_ms", _p99(recent_lat))
                if ops is not None:
                    # the hotdoc gauges ride the sim's own sampling beat
                    # (the OpsServer ticker is off in this host)
                    from fluidframework_tpu.server import opsd
                    opsd.publish_hotdoc_gauges([server.hotdocs])
                store.tick(now=now)
                policy_trace.append(policy.tick(now=now))
                if rng.random() < churn_p:
                    spec = tenants[rng.randrange(len(tenants))]
                    pool = sessions[spec.name]
                    idle = [s for s in pool
                            if s.conn.pending_count == 0]
                    if idle and len(pool) > 1:
                        churns += 1
                        gone = idle[rng.randrange(len(idle))]
                        pool.remove(gone)
                        gone.conn.close()
                        retired.append(gone)
                        spawn(spec)
            time.sleep(0.002)
        storm_s = time.monotonic() - t0
        everyone = retired + [s for pool in sessions.values()
                              for s in pool]
        for spec in tenants:
            mine = [s for s in everyone if s.spec is spec]
            storm_offered[spec.name] = sum(len(s.submitted)
                                           for s in mine)
            storm_acked[spec.name] = sum(len(s.conn.op_acks)
                                         for s in mine)
        # drain: the operator lifts the abusive tenant's brake so its
        # parked backlog clears at the offered rate — every shed op
        # must still land exactly once, with its ORIGINAL clientSeq
        for spec in abusive:
            adm.register_tenant(spec.name, spec.offered_rate * 2.0)
        adm.set_pressure(scale=1.0, shed_probability=0.0)
        live = [s for pool in sessions.values() for s in pool]
        for sess in live:
            if not sess.conn.wait_idle(timeout=idle_timeout):
                _violate("drain_timeout", session=sess.key,
                         pending=sess.conn.pending_count,
                         throttled=sess.conn.throttled)
        for sess in everyone:
            if sess.conn.nacks:
                _violate("genuine_nack", session=sess.key,
                         n=len(sess.conn.nacks),
                         first=sess.conn.nacks[0],
                         reconnects=sess.conn.reconnects,
                         resubmits=sess.conn.resubmits,
                         throttled=sess.conn.throttled,
                         dup_acked=sess.conn.dup_acked)
        _audit(service, everyone)
        return _report(seed, storm_s, tenants, everyone, adm, policy,
                       storm_offered, storm_acked, recent_lat, churns,
                       bursts, slo_ms, goodput_min, policy_trace)
    finally:
        for pool in sessions.values():
            for sess in pool:
                sess.conn.close()
        if ops is not None:
            ops.stop()
        server.stop()
        service.close()


def _audit(service: LocalService, everyone: List[_Session]) -> None:
    """Hold the durable stream to the exactly-once/order bar, with
    multiple writers per doc: global uniqueness + per-session order."""
    by_doc: Dict[str, List[_Session]] = {}
    for sess in everyone:
        by_doc.setdefault(sess.doc, []).append(sess)
    for doc, residents in by_doc.items():
        durable = [m for m in service.get_deltas(doc, 0)
                   if m.type == MessageType.OP]
        seqs = [m.seq for m in durable]
        if any(b <= a for a, b in zip(seqs, seqs[1:])):
            _violate("seq_not_monotone", doc=doc)
        markers = [(m.contents or {}).get("u") for m in durable]
        if len(set(markers)) != len(markers):
            dup = sorted(m for m in set(markers)
                         if markers.count(m) > 1)[0]
            _violate("double_applied", doc=doc, marker=str(dup))
        acked: Dict[str, int] = {}
        for sess in residents:
            for uid, seq in sess.conn.op_acks.items():
                acked[sess.uid_marker[uid]] = seq
        for m, seq in zip(markers, seqs):
            if m not in acked:
                _violate("stray_unacked_op", doc=doc, marker=str(m))
            if acked[m] != seq:
                _violate("ack_seq_mismatch", doc=doc, marker=str(m),
                         acked_seq=acked[m], durable_seq=seq)
        lost = sorted(set(acked) - set(markers))
        if lost:
            _violate("lost_acked_op", doc=doc, marker=lost[0],
                     n_lost=len(lost))
        for sess in residents:
            mine = [m for m in markers
                    if m.startswith(sess.key + "#")]
            if mine != sess.submitted:
                _violate("order_divergence", doc=doc, session=sess.key,
                         durable=len(mine),
                         expected=len(sess.submitted))


def _report(seed, storm_s, tenants, everyone, adm, policy,
            storm_offered, storm_acked, recent_lat, churns, bursts,
            slo_ms, goodput_min, policy_trace) -> dict:
    snap = adm.snapshot()
    compliant = [t for t in tenants
                 if t.role == "writer" and t.load <= 1.0]
    abusive = [t for t in tenants
               if t.role == "writer" and t.load > 1.0]
    offered = sum(len(s.submitted) for s in everyone)
    acked = sum(len(s.conn.op_acks) for s in everyone)
    c_off = sum(storm_offered[t.name] for t in compliant)
    c_ack = sum(storm_acked[t.name] for t in compliant)
    lat = [v for s in everyone for v in s.admitted_latencies_ms()]
    capacity = sum(t.rate for t in tenants if t.role == "writer")
    report = {
        "seed": seed,
        "storm_s": round(storm_s, 3),
        "capacity_ops_s": capacity,
        "offered_ops_s": round(offered / storm_s, 1),
        "ops_offered": offered,
        "ops_acked": acked,
        "silent_drops": offered - acked,
        "goodput_ratio": round(c_ack / c_off, 4) if c_off else 1.0,
        "admitted_ack_p99_ms": round(_p99(lat), 3),
        "slo_ms": slo_ms,
        "throttled_frames": sum(s.conn.throttled for s in everyone),
        "throttle_resubmits": sum(s.conn.throttle_resubmits
                                  for s in everyone),
        "shed_total": snap["shed_total"],
        "shed_ratio": round(snap["shed_total"]
                            / max(1, offered), 4),
        "abusive_throttled": sum(s.conn.throttled for s in everyone
                                 if s.spec.load > 1.0),
        "abusive_shed": sum(snap["tenants"].get(t.name, {})
                            .get("shed", 0) for t in abusive),
        "reader_ops_observed": sum(s.ops_observed for s in everyone
                                   if s.spec.role == "reader"),
        "session_churns": churns,
        "arrival_bursts": bursts,
        "sessions": len(everyone),
        "policy": {
            "ticks": policy.ticks,
            "breach_ticks": policy.breach_ticks,
            "min_scale": round(policy.min_scale_seen, 4),
            "max_shed_probability": round(policy.max_shed_seen, 4),
            "final": policy_trace[-1] if policy_trace else None,
        },
        "tenants": {
            t.name: {
                "role": t.role, "budget_ops_s": t.rate,
                "load": t.load,
                "offered_storm": storm_offered[t.name],
                "acked_storm": storm_acked[t.name],
                **snap["tenants"].get(t.name, {}),
            } for t in tenants
        },
        "violations": 0,
    }
    gates = _Gates(goodput_min=goodput_min, slo_ms=slo_ms)
    gates.expect(report["silent_drops"] == 0, "silent_drops != 0")
    gates.expect(report["goodput_ratio"] >= goodput_min,
                 f"goodput {report['goodput_ratio']} < {goodput_min}")
    gates.expect(report["admitted_ack_p99_ms"] <= slo_ms,
                 f"admitted ack p99 {report['admitted_ack_p99_ms']}ms "
                 f"> {slo_ms}ms")
    if abusive:
        gates.expect(report["abusive_throttled"] > 0,
                     "abusive tenant never saw a throttled frame")
        gates.expect(report["abusive_shed"] > 0,
                     "controller ledger shows no abusive shed")
    report["gate_failures"] = gates.failures
    return report


def main(argv: Optional[List[str]] = None) -> int:
    ap = argparse.ArgumentParser(
        description="multi-tenant overload sim (see module docstring)")
    ap.add_argument("--seed", type=int, default=0)
    ap.add_argument("--duration", type=float, default=6.0)
    ap.add_argument("--docs", type=int, default=8)
    ap.add_argument("--slo-ms", type=float, default=200.0)
    ap.add_argument("--goodput-min", type=float, default=0.8)
    ap.add_argument("--quick", action="store_true",
                    help="tier-1 profile: ~2s storm, lenient SLO for "
                         "one-core CI")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 unless every acceptance gate passes")
    ap.add_argument("--ops-port", type=int, default=None,
                    help="serve the live ops plane (/metrics, /healthz, "
                         "/debug/hotdocs, ...) on this port for the "
                         "storm's duration (0 = ephemeral)")
    ap.add_argument("--partitions", type=int, default=4,
                    help="partitioned-oplog width for the service under "
                         "storm (ISSUE 18); the audits must pass at any "
                         "width")
    args = ap.parse_args(argv)
    if args.quick:
        args.duration = min(args.duration, 1.6)
        args.slo_ms = max(args.slo_ms, 250.0)
    report = run_sim(seed=args.seed, duration_s=args.duration,
                     n_docs=args.docs, slo_ms=args.slo_ms,
                     goodput_min=args.goodput_min, quick=args.quick,
                     ops_port=args.ops_port,
                     n_partitions=args.partitions)
    print(json.dumps(report, indent=2, sort_keys=True))
    if args.check and report["gate_failures"]:
        print(f"GATE FAILURES: {report['gate_failures']}",
              file=sys.stderr)
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
