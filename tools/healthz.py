#!/usr/bin/env python
"""Text health dashboard over a TimeSeriesStore JSONL export.

The operator-facing face of the health plane (ISSUE 4): bench.py (and
any serving loop ticking a ``TimeSeriesStore`` with ``jsonl_path=``)
leaves a JSONL trail of metric samples; this tool re-loads it and
renders the two things an operator checks first:

- ``render_sparklines()`` — one line per active metric, recent shape +
  latest value + derived rate for counters;
- the SLO scorecard — every standing objective (``utils.slo.
  default_slos()`` plus any ``--slo "metric < threshold"`` extras)
  judged over the export's history with fast/slow burn windows.

Usage::

    python tools/healthz.py health.jsonl              # dashboard + SLOs
    python tools/healthz.py health.jsonl --names '*shard*'
    python tools/healthz.py --demo                    # synthetic sample
    python tools/healthz.py h.jsonl --slo "ops_ingested_rate > 100"
"""

from __future__ import annotations

import argparse
import fnmatch
import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
from fluidframework_tpu.utils import slo as slo_mod          # noqa: E402
from fluidframework_tpu.utils import telemetry, timeseries   # noqa: E402


def _demo_store() -> timeseries.TimeSeriesStore:
    """A synthetic ramp so the dashboard can be seen without a bench
    run: a counter ramping up, a latency gauge breaching its SLO."""
    reg = telemetry.MetricsRegistry()
    store = timeseries.TimeSeriesStore(registry=reg)
    for i in range(32):
        reg.inc("ops_ingested", 100 + 10 * i)
        reg.set_gauge("ack_p99_ms", 40 + (0 if i < 24 else 60 * (i - 23)))
        reg.set_gauge("digest_parity", 1.0)
        store.tick(now=float(i))
    return store


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", nargs="?", help="TimeSeriesStore export")
    ap.add_argument("--demo", action="store_true",
                    help="render a synthetic store instead of a file")
    ap.add_argument("--names", default=None,
                    help="fnmatch filter on metric names")
    ap.add_argument("--width", type=int, default=24)
    ap.add_argument("--all", action="store_true",
                    help="include all-zero flat series")
    ap.add_argument("--slo", action="append", default=[],
                    metavar="SPEC",
                    help='extra SLO, e.g. "ack_p99_ms < 200" (repeatable)')
    ap.add_argument("--no-slo", action="store_true",
                    help="skip the SLO scorecard")
    args = ap.parse_args(argv)

    if args.demo:
        store = _demo_store()
    elif args.jsonl:
        store = timeseries.TimeSeriesStore.from_jsonl(args.jsonl)
    else:
        ap.error("either a JSONL path or --demo is required")
    names = None
    if args.names:
        names = [n for n in store.names()
                 if fnmatch.fnmatchcase(n, args.names)]
    print(store.render_sparklines(names=names, width=args.width,
                                  active_only=not args.all), end="")
    if args.no_slo:
        return 0
    specs = slo_mod.default_slos() + [slo_mod.SLOSpec.parse(s)
                                      for s in args.slo]
    engine = slo_mod.SLOEngine(store, specs=specs,
                               registry=store.registry)
    rows = engine.scorecard()
    print()
    print(slo_mod.render_scorecard(rows), end="")
    # the dashboard reports; only an explicitly breaching scorecard row
    # fails the invocation (operators pipe this into CI gates)
    return 1 if any(not r["ok"] for r in rows) else 0


if __name__ == "__main__":
    raise SystemExit(main())
