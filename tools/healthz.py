#!/usr/bin/env python
"""Text health dashboard: JSONL exports or a live ops endpoint.

The operator-facing face of the health plane (ISSUE 4, live mode ISSUE
17): bench.py (and any serving loop ticking a ``TimeSeriesStore`` with
``jsonl_path=``) leaves a JSONL trail of metric samples; this tool
re-loads it and renders the two things an operator checks first:

- ``render_sparklines()`` — one line per active metric, recent shape +
  latest value + derived rate for counters;
- the SLO scorecard — every standing objective (``utils.slo.
  default_slos()`` plus any ``--slo "metric < threshold"`` extras)
  judged over the export's history with fast/slow burn windows.

With ``--url`` the same dashboard renders against a RUNNING server's
operations plane (``server.opsd.OpsServer``): ``/metrics`` is polled at
``--interval`` for ``--polls`` rounds to build the sparkline history,
and the scorecard comes from the server's own ``/healthz`` (its
SLOEngine has the full in-process history, not just our polls).

Usage::

    python tools/healthz.py health.jsonl              # dashboard + SLOs
    python tools/healthz.py health.jsonl --names '*shard*'
    python tools/healthz.py --demo                    # synthetic sample
    python tools/healthz.py h.jsonl --slo "ops_ingested_rate > 100"
    python tools/healthz.py --url http://127.0.0.1:9321 \
        --interval 1 --polls 10                       # live server
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import re
import sys
import time
import urllib.request

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))
from fluidframework_tpu.utils import slo as slo_mod          # noqa: E402
from fluidframework_tpu.utils import telemetry, timeseries   # noqa: E402

#: one exposition sample line: name, optional {labels}, value
_PROM_LINE = re.compile(
    r'^([A-Za-z_:][A-Za-z0-9_:]*)(?:\{(.*)\})?\s+([^\s]+)$')
#: one label pair inside the braces, value with text-format escapes
_PROM_LABEL = re.compile(r'(\w+)="((?:[^"\\]|\\.)*)"')


def _unescape(v: str) -> str:
    return (v.replace(r"\n", "\n").replace(r'\"', '"')
            .replace(r"\\", "\\"))


def parse_prometheus(text: str):
    """Parse a ``render_prometheus`` exposition back into the flat
    ``full_snapshot``-style key space: top-level samples keep their
    name, component-labeled samples become ``component.name`` (or
    ``component{k=v,...}.name`` with extra labels — the registry's
    component-key scheme). Histogram ``_bucket`` lines are skipped
    (the ``_sum``/``_count`` pair carries the trend). Returns
    ``(metrics, kinds)``."""
    metrics, kinds, types = {}, {}, {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            parts = line.split()
            if len(parts) == 4:
                types[parts[2]] = parts[3]
            continue
        if not line or line.startswith("#"):
            continue
        m = _PROM_LINE.match(line)
        if m is None:
            continue
        name, rawlabels, rawvalue = m.groups()
        if name.endswith("_bucket"):
            continue
        try:
            value = float(rawvalue)
        except ValueError:
            continue
        labels = {k: _unescape(v)
                  for k, v in _PROM_LABEL.findall(rawlabels or "")}
        comp = labels.pop("component", None)
        key = name
        if comp is not None:
            if labels:
                inner = ",".join(f"{k}={labels[k]}"
                                 for k in sorted(labels))
                key = f"{comp}{{{inner}}}.{name}"
            else:
                key = f"{comp}.{name}"
        metrics[key] = value
        typ = types.get(name)
        if typ is None and (name.endswith("_sum")
                            or name.endswith("_count")):
            base = name.rsplit("_", 1)[0]
            if types.get(base) == "histogram":
                typ = "counter"   # cumulative histogram accumulators
        if typ in ("counter", "gauge"):
            kinds[key] = typ
        elif typ == "histogram":
            kinds[key] = "counter"
    return metrics, kinds


def _fetch(url: str, timeout: float = 5.0) -> bytes:
    with urllib.request.urlopen(url, timeout=timeout) as resp:
        return resp.read()


def _demo_store() -> timeseries.TimeSeriesStore:
    """A synthetic ramp so the dashboard can be seen without a bench
    run: a counter ramping up, a latency gauge breaching its SLO."""
    reg = telemetry.MetricsRegistry()
    store = timeseries.TimeSeriesStore(registry=reg)
    for i in range(32):
        reg.inc("ops_ingested", 100 + 10 * i)
        reg.set_gauge("ack_p99_ms", 40 + (0 if i < 24 else 60 * (i - 23)))
        reg.set_gauge("digest_parity", 1.0)
        store.tick(now=float(i))
    return store


def _live_store(base_url: str, interval_s: float, polls: int
                ) -> timeseries.TimeSeriesStore:
    """Build sparkline history by polling a live ``/metrics`` endpoint."""
    store = timeseries.TimeSeriesStore(
        registry=telemetry.MetricsRegistry())
    for i in range(max(1, polls)):
        if i:
            time.sleep(interval_s)
        text = _fetch(base_url + "/metrics").decode("utf-8")
        metrics, kinds = parse_prometheus(text)
        store.ingest_sample(time.time(), metrics, kinds=kinds)
    return store


def _fmt_bytes(n) -> str:
    if n is None:
        return "?"
    n = float(n)
    for unit in ("B", "KiB", "MiB", "GiB"):
        if abs(n) < 1024 or unit == "GiB":
            return f"{n:.1f}{unit}" if unit != "B" else f"{int(n)}B"
        n /= 1024.0
    return f"{n:.1f}GiB"


def render_capacity(census=None, store=None) -> str:
    """Capacity panel (ISSUE 19). Live mode renders the full
    ``/debug/memory`` census (host/device split, headroom, heaviest +
    coldest docs); file/demo mode reconstructs the headline from the
    capacity gauges present in the metric store. Returns "" when the
    export predates the capacity plane."""
    lines = []
    if census is not None and "error" not in census:
        host = census.get("host", {})
        dev = census.get("device", {})
        docs = census.get("docs", {})
        idle = census.get("idle", {})
        lines.append("capacity")
        lines.append(
            f"  host {_fmt_bytes(host.get('total_bytes'))}"
            f"  device {_fmt_bytes(dev.get('total_bytes'))}"
            f"  docs {docs.get('resident', 0)}"
            f"  headroom {census.get('headroom', 1.0):.2f}"
            + (f"  budget {_fmt_bytes(census['budget_bytes'])}"
               if census.get("budget_bytes") else ""))
        by_owner = host.get("by_owner", {})
        for owner in sorted(by_owner, key=by_owner.get, reverse=True)[:6]:
            lines.append(f"    {owner:<32s} {_fmt_bytes(by_owner[owner])}")
        for heavy in (census.get("top", {}).get("heaviest") or [])[:4]:
            lines.append(f"  heavy {heavy.get('doc')}: "
                         f"{_fmt_bytes(heavy.get('bytes'))}")
        for cold in (census.get("top", {}).get("coldest") or [])[:4]:
            lines.append(f"  cold  {cold.get('doc', cold.get('row'))}: "
                         f"idle {cold.get('idle_s', 0):.1f}s")
        for owner, snap in sorted(idle.items()):
            p99 = snap.get("idle_p99_s")
            if p99 is not None:
                lines.append(f"  idle[{owner}] "
                             f"p50 {snap.get('idle_p50_s', 0):.1f}s"
                             f"  p99 {p99:.1f}s"
                             f"  max {snap.get('idle_max_s', 0):.1f}s")
    elif store is not None:
        vals = {n: store.latest(n)
                for n in ("doc_resident_bytes", "device_buffer_bytes",
                          "resident_docs_total", "memory_budget_headroom",
                          "doc_memory_budget_bytes")}
        if any(v is not None for v in vals.values()):
            lines.append("capacity")
            lines.append(
                f"  host {_fmt_bytes(vals['doc_resident_bytes'])}"
                f"  device {_fmt_bytes(vals['device_buffer_bytes'])}"
                f"  docs {int(vals['resident_docs_total'] or 0)}"
                f"  headroom "
                f"{(vals['memory_budget_headroom'] or 1.0):.2f}"
                + (f"  budget "
                   f"{_fmt_bytes(vals['doc_memory_budget_bytes'])}"
                   if vals["doc_memory_budget_bytes"] else ""))
    return "\n".join(lines) + ("\n" if lines else "")


def render_readers(census=None, store=None) -> str:
    """Readers panel (ISSUE 20). Live mode renders ``/debug/readers``
    (subscriber count, worst window lag, shed/park totals, staleness
    p99, the laggiest subscriber rows); file/demo mode reconstructs the
    headline from the read-plane gauges/counters in the metric store.
    Returns "" when the export predates the read plane."""
    lines = []
    if census is not None and "error" not in census:
        rows = census.get("readers") or []
        if census.get("subscribers") or rows:
            lines.append("readers")
            lines.append(
                f"  subscribers {census.get('subscribers', 0)}"
                f"  worst-lag {census.get('worst_lag_windows', 0)}w"
                f"  sheds {census.get('sheds', 0)}"
                f"  parked {census.get('parked', 0)}"
                f"  staleness-p99 "
                f"{census.get('staleness_p99_s', 0.0):.3f}s")
            laggy = sorted((r for r in rows if "sid" in r),
                           key=lambda r: r.get("lag_windows", 0),
                           reverse=True)
            for r in laggy[:6]:
                lines.append(
                    f"    {r.get('name', '?'):<24s}"
                    f" lag {r.get('lag_windows', 0)}w"
                    f"  ops {r.get('delivered_ops', 0)}"
                    f"  sheds {r.get('sheds', 0)}"
                    + ("  PARKED" if r.get("parked") else ""))
    elif store is not None:
        vals = {n: store.latest(n)
                for n in ("observer_subscribers",
                          "observer_delivery_ops_per_sec",
                          "read_staleness_p99_s",
                          "observer_sheds_total",
                          "read_windows_total")}
        if any(v is not None for v in vals.values()):
            lines.append("readers")
            lines.append(
                f"  subscribers {int(vals['observer_subscribers'] or 0)}"
                f"  delivery "
                f"{(vals['observer_delivery_ops_per_sec'] or 0.0):.0f}"
                f" ops/s"
                f"  windows {int(vals['read_windows_total'] or 0)}"
                f"  sheds {int(vals['observer_sheds_total'] or 0)}"
                f"  staleness-p99 "
                f"{(vals['read_staleness_p99_s'] or 0.0):.3f}s")
    return "\n".join(lines) + ("\n" if lines else "")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("jsonl", nargs="?", help="TimeSeriesStore export")
    ap.add_argument("--demo", action="store_true",
                    help="render a synthetic store instead of a file")
    ap.add_argument("--url", default=None, metavar="http://host:port",
                    help="poll a live ops endpoint instead of a file")
    ap.add_argument("--interval", type=float, default=1.0,
                    help="seconds between live polls (with --url)")
    ap.add_argument("--polls", type=int, default=10,
                    help="number of live polls to sample (with --url)")
    ap.add_argument("--names", default=None,
                    help="fnmatch filter on metric names")
    ap.add_argument("--width", type=int, default=24)
    ap.add_argument("--all", action="store_true",
                    help="include all-zero flat series")
    ap.add_argument("--slo", action="append", default=[],
                    metavar="SPEC",
                    help='extra SLO, e.g. "ack_p99_ms < 200" (repeatable)')
    ap.add_argument("--no-slo", action="store_true",
                    help="skip the SLO scorecard")
    args = ap.parse_args(argv)

    live_rows = None
    if args.url:
        base = args.url.rstrip("/")
        store = _live_store(base, args.interval, args.polls)
        if not args.no_slo:
            try:
                live_rows = json.loads(
                    _fetch(base + "/healthz")).get("rows") or []
            except (OSError, ValueError):
                live_rows = []
    elif args.demo:
        store = _demo_store()
    elif args.jsonl:
        store = timeseries.TimeSeriesStore.from_jsonl(args.jsonl)
    else:
        ap.error("a JSONL path, --demo, or --url is required")
    names = None
    if args.names:
        names = [n for n in store.names()
                 if fnmatch.fnmatchcase(n, args.names)]
    print(store.render_sparklines(names=names, width=args.width,
                                  active_only=not args.all), end="")
    census = None
    if args.url:
        try:
            census = json.loads(_fetch(base + "/debug/memory"))
        except (OSError, ValueError):
            census = None
    panel = render_capacity(census=census, store=store)
    if panel:
        print()
        print(panel, end="")
    readers = None
    if args.url:
        try:
            readers = json.loads(_fetch(base + "/debug/readers"))
        except (OSError, ValueError):
            readers = None
    panel = render_readers(census=readers, store=store)
    if panel:
        print()
        print(panel, end="")
    if args.no_slo:
        return 0
    if args.url:
        # the server's own scorecard: its SLOEngine judged the full
        # in-process history, not just the handful of polls we took
        rows = live_rows
    else:
        specs = slo_mod.default_slos() + [slo_mod.SLOSpec.parse(s)
                                          for s in args.slo]
        engine = slo_mod.SLOEngine(store, specs=specs,
                                   registry=store.registry)
        rows = engine.scorecard()
    print()
    print(slo_mod.render_scorecard(rows), end="")
    # the dashboard reports; only an explicitly breaching scorecard row
    # fails the invocation (operators pipe this into CI gates)
    return 1 if any(not r["ok"] for r in rows) else 0


if __name__ == "__main__":
    raise SystemExit(main())
