#!/usr/bin/env python
"""Regenerate BENCHES.md's driver-recorded sections from BENCH_r*.json.

The driver records every round's ``python bench.py`` run as
``BENCH_r{NN}.json``; BENCHES.md quotes the latest record's headline
block by hand, which drifts (stale numbers, missing new fields). This
tool makes the quote mechanical:

- finds the newest ``BENCH_r*.json`` under the repo root (or takes an
  explicit ``--json`` path),
- tolerates both record shapes: the bare bench JSON line, and the
  driver wrapper ``{"n", "cmd", "rc", "tail", "parsed"}`` whose
  ``tail`` is the run's stdout tail as a STRING (the bench JSON is its
  last line) and whose ``parsed`` may already hold the decoded dict,
- rewrites the fenced JSON block under the ``## Config #4`` heading
  with a curated, stable-ordered subset of the record (all headline
  throughputs, latency/stall accounting, and the variance bands the
  stall-proof phases emit),
- is a dry run by default (prints the regenerated section);
  ``--write`` edits BENCHES.md in place.

Usage::

    python tools/bench_report.py                 # dry run, latest record
    python tools/bench_report.py --write         # update BENCHES.md
    python tools/bench_report.py --json BENCH_r05.json
"""

from __future__ import annotations

import argparse
import json
import re
import sys
from pathlib import Path

#: curated key order for the Config #4 fenced block — scalars first,
#: then trial/band evidence; keys absent from the record are skipped so
#: the tool stays usable on older rounds
CONFIG4_KEYS = (
    "metric", "value", "unit", "vs_baseline", "docs", "total_ops",
    "serving_ops_per_sec", "serving_ops_per_sec_median",
    "serving_rich_ops_per_sec", "serving_rich_ops_per_sec_median",
    "serving_durable_ops_per_sec", "serving_durable_ops_per_sec_median",
    "serving_interval_ops_per_sec", "serving_interval_ops",
    "serving_interval_wire",
    "tree_serving_ops_per_sec", "tree_serving_ops_per_sec_median",
    "tree_flat_serving_ops_per_sec",
    "tree_kernel_ops_per_sec", "tree_kernel_trials",
    "headline_variance_band",
    "ack_p50_ms", "ack_p99_ms", "ack_sample_retries",
    "serving_read_ms",
    "apply_window_p50_ms", "apply_window_worst_ms",
    "apply_window_retries", "apply_window_stalled",
    "conflict_ops_per_sec", "digest_parity", "conflict_parity",
    "contended", "backend",
)


def find_latest_record(root: Path) -> Path:
    """Newest ``BENCH_r*.json`` by round number (lexicographic on the
    zero-padded round suffix equals numeric order)."""
    records = sorted(root.glob("BENCH_r*.json"))
    if not records:
        raise FileNotFoundError(f"no BENCH_r*.json under {root}")
    return records[-1]


def load_record(path: Path) -> dict:
    """The bench JSON dict from either record shape (see module doc).
    Raises ValueError on a failed run (wrapper ``rc`` != 0) or a record
    with no parsable bench line."""
    raw = json.loads(path.read_text())
    if "metric" in raw:            # bare bench output
        return raw
    if raw.get("rc", 0) != 0:
        raise ValueError(f"{path.name}: recorded run failed rc={raw['rc']}")
    parsed = raw.get("parsed")
    if isinstance(parsed, dict) and "metric" in parsed:
        return parsed
    tail = raw.get("tail")
    if isinstance(tail, str):
        # the bench JSON is the tail's last non-empty line
        for line in reversed(tail.splitlines()):
            line = line.strip()
            if line.startswith("{"):
                rec = json.loads(line)
                if "metric" in rec:
                    return rec
    raise ValueError(f"{path.name}: no bench JSON found in record")


def config4_block(rec: dict) -> str:
    """The curated one-line JSON for the Config #4 fenced block."""
    out = {k: rec[k] for k in CONFIG4_KEYS if k in rec}
    # the rich pack-stage p50 is the tentpole gate — surface it beside
    # the throughputs when the per-stage breakdown carries it
    stages = rec.get("ingest_stage_p50_ms")
    if isinstance(stages, dict):
        pack = stages.get("rich", {})
        if isinstance(pack, dict) and "pack" in pack:
            out["rich_pack_p50_ms"] = pack["pack"]
        elif "rich.pack" in stages:
            out["rich_pack_p50_ms"] = stages["rich.pack"]
    # pipelined-ingest evidence: the per-wave wall (inter-completion
    # gap) and each phase's overlap factor — sum(stage p50s) above the
    # wave wall means the stages genuinely ran concurrently
    if isinstance(rec.get("ingest_wave_wall_p50_ms"), dict):
        out["ingest_wave_wall_p50_ms"] = rec["ingest_wave_wall_p50_ms"]
    pipe = rec.get("ingest_pipeline")
    if isinstance(pipe, dict):
        out["pipeline_overlap"] = {
            name: round(st["overlap"], 3)
            for name, st in pipe.items()
            if isinstance(st, dict) and "overlap" in st}
    return json.dumps(out)


#: curated key orders for the driver-record sections the side-benches
#: used to own (ISSUE 6 satellite: the authoritative record carries the
#: matrix-serving and columnar-ingress numbers, with trials arrays)
MATRIX_KEYS = (
    "matrix_serving_ops_per_sec", "matrix_serving_ops_per_sec_median",
    "matrix_serving_trials",
)
INGRESS_KEYS = (
    "columnar_ingress_ops_per_sec",
    "columnar_ingress_ops_per_sec_median", "columnar_ingress_trials",
    "columnar_ingress_windows",
    # ISSUE 15 batch-decode evidence: drain-pass decode p50, drained
    # bytes per pass, and the decode tier that served (native/numpy)
    "ingress_decode_p50_ms", "ingress_drained_bytes_per_pass",
    "ingress_drain_passes", "ingress_decode_tier",
)
TREE_KEYS = (
    "tree_serving_ops_per_sec", "tree_serving_ops_per_sec_median",
    "tree_serving_trials", "tree_flat_serving_ops_per_sec",
    "tree_flat_trials", "tree_kernel_ops_per_sec", "tree_kernel_trials",
)


def matrix_block(rec: dict) -> str | None:
    """Matrix-serving fenced block, or None on records predating the
    folded-in phase."""
    if "matrix_serving_ops_per_sec" not in rec:
        return None
    out = {"metric": "matrix_serving_ops_per_sec", "unit": "ops/s"}
    out.update({k: rec[k] for k in MATRIX_KEYS if k in rec})
    return json.dumps(out)


def tree_block(rec: dict) -> str | None:
    """Tree-serving fenced block (general/flat/kernel splits plus the
    pipelined-ingest overlap evidence), or None on records predating the
    tree phase."""
    if "tree_serving_ops_per_sec" not in rec:
        return None
    out = {"metric": "tree_serving_ops_per_sec", "unit": "ops/s"}
    out.update({k: rec[k] for k in TREE_KEYS if k in rec})
    stages = rec.get("ingest_stage_p50_ms")
    if isinstance(stages, dict) and isinstance(stages.get("tree"), dict):
        out["stage_p50_ms"] = stages["tree"]
    walls = rec.get("ingest_wave_wall_p50_ms")
    if isinstance(walls, dict) and "tree" in walls:
        out["wave_wall_p50_ms"] = walls["tree"]
    pipe = rec.get("ingest_pipeline")
    if isinstance(pipe, dict) and isinstance(pipe.get("tree"), dict):
        out["pipeline"] = {
            k: (round(v, 3) if isinstance(v, float) else v)
            for k, v in pipe["tree"].items()
            if k in ("waves", "depth", "max_inflight", "overlap")}
    return json.dumps(out)


def ingress_block(rec: dict) -> str | None:
    """Columnar-ingress fenced block, or None on records predating the
    folded-in phase."""
    if "columnar_ingress_ops_per_sec" not in rec:
        return None
    out = {"metric": "columnar_ingress_ops_per_sec", "unit": "ops/s"}
    out.update({k: rec[k] for k in INGRESS_KEYS if k in rec})
    pipe = rec.get("columnar_ingress_pipeline")
    if isinstance(pipe, dict):
        out["pipeline"] = {k: (round(v, 3) if isinstance(v, float) else v)
                           for k, v in pipe.items()
                           if k in ("waves", "depth", "max_inflight",
                                    "overlap")}
    return json.dumps(out)


def _no_record(metric: str, unit: str, source: str) -> str:
    """Explicit placeholder row for a phase the newest COMMITTED record
    predates. The old hand-written "pending" fences never regenerated
    (the block functions returned None), so they silently went stale;
    this row is written BY the tool, names the record that was judged,
    and is replaced mechanically the moment a record carrying the phase
    lands."""
    return json.dumps({
        "metric": metric, "unit": unit,
        "status": "no committed record",
        "source": f"newest committed record ({source}) predates this "
                  "phase; tools/bench_report.py --write regenerates the "
                  "fence from the first record carrying it"})


def reconnect_storm_block(rec: dict, source: str = "?") -> str:
    """Reconnect-storm fenced block (ISSUE 9: resilience under load);
    an explicit no-committed-record row on records predating the
    phase."""
    storm = rec.get("reconnect_storm")
    if not isinstance(storm, dict):
        return _no_record("reconnect_storm_ops_per_sec", "ops/s", source)
    out = {"metric": "reconnect_storm_ops_per_sec", "unit": "ops/s"}
    out.update({k: storm[k] for k in (
        "ops_per_sec", "ops_acked", "reconnects", "reconnect_p50_ms",
        "reconnect_p99_ms", "resubmits", "dup_acked", "socket_kills",
        "restarts", "faultpoint_fires", "invariant_violations",
        "error") if k in storm})
    return json.dumps(out)


def overload_block(rec: dict, source: str = "?") -> str:
    """Overload-storm fenced block (ISSUE 16: admission control under
    2x-capacity multi-tenant load); an explicit no-committed-record row
    on records predating the phase."""
    storm = rec.get("overload_storm")
    if not isinstance(storm, dict):
        return _no_record("overload_goodput_ratio", "ratio", source)
    out = {"metric": "overload_goodput_ratio", "unit": "ratio"}
    out.update({k: storm[k] for k in (
        "goodput_ratio", "admitted_ack_p99_ms", "shed_ratio",
        "shed_total", "throttled_frames", "throttle_resubmits",
        "abusive_throttled", "abusive_shed", "ops_offered", "ops_acked",
        "policy_breach_ticks", "policy_min_scale", "silent_drops",
        "invariant_violations", "gate_failures",
        "error") if k in storm})
    return json.dumps(out)


def durability_block(rec: dict, source: str = "?") -> str:
    """Durability fenced block (ISSUE 10: recovery ladder timings + the
    scrub's chain-break count); an explicit no-committed-record row on
    records predating the phase."""
    dur = rec.get("durability")
    if not isinstance(dur, dict):
        return _no_record("recovery_ladder_ms", "ms", source)
    out = {"metric": "recovery_ladder_ms", "unit": "ms"}
    out.update({k: dur[k] for k in (
        "recovery_ladder_ms", "ladder_depths", "ops_replayed",
        "generations_kept", "chain_breaks", "records_scrubbed",
        "error") if k in dur})
    return json.dumps(out)


def partition_block(rec: dict, source: str = "?") -> str:
    """Partitioned-serving fenced block (ISSUE 18: the columnar storm at
    1/2/4/8 sequencer partitions, speedup vs the 1-partition baseline,
    and the per-window replica digest-parity verdict); an explicit
    no-committed-record row on records predating the phase."""
    ps = rec.get("partition_scaling")
    if not isinstance(ps, dict) or not ps:
        return _no_record("partition_columnar_ops_per_sec", "ops/s",
                          source)
    out = {"metric": "partition_columnar_ops_per_sec", "unit": "ops/s"}
    if "partition_columnar_ops_per_sec" in rec:
        out["value"] = rec["partition_columnar_ops_per_sec"]
    out.update({k: ps[k] for k in (
        "speedup_4x", "speedup_8x", "scaling_efficiency_4x",
        "host_cores", "error") if k in ps})
    widths = ps.get("widths")
    if isinstance(widths, dict):
        out["ops_per_sec_by_width"] = {
            w: row.get("ops_per_sec") for w, row in sorted(
                widths.items(), key=lambda kv: int(kv[0]))
            if isinstance(row, dict)}
    digest = ps.get("digest")
    if isinstance(digest, dict):
        out["digest"] = digest
    return json.dumps(out)


def read_plane_block(rec: dict, source: str = "?") -> str:
    """Read-plane fenced block (ISSUE 20: delivery ops/s by subscriber
    count, the encode-once amortization ratio, generation-diff catch-up
    vs full-tail replay, staleness p99 under the write storm); an
    explicit no-committed-record row on records predating the phase."""
    rf = rec.get("read_fanout")
    if not isinstance(rf, dict) or not rf or "skipped" in rf:
        return _no_record("read_delivery_ops_per_sec", "ops/s", source)
    out = {"metric": "read_delivery_ops_per_sec", "unit": "ops/s"}
    if rec.get("read_delivery_ops_per_sec") is not None:
        out["value"] = rec["read_delivery_ops_per_sec"]
    out.update({k: rf[k] for k in (
        "windows", "total_ops", "encode_ms_per_window",
        "marginal_us_per_sub_window_1024", "amortization_ratio_1024",
        "catchup_speedup_4096", "staleness_p99_s", "error") if k in rf})
    fanout = rf.get("fanout")
    if isinstance(fanout, dict):
        out["delivery_ops_per_sec_by_subs"] = {
            n: row.get("delivery_ops_per_sec") for n, row in sorted(
                fanout.items(), key=lambda kv: int(kv[0]))
            if isinstance(row, dict)}
    catchup = rf.get("catchup")
    if isinstance(catchup, dict):
        out["catchup_by_tail"] = catchup
    return json.dumps(out)


_FENCE_RE = re.compile(r"```json\n.*?\n```", re.S)


def update_section(md: str, heading: str, block: str) -> str:
    """Replace the first fenced JSON block after ``heading`` (up to the
    next ``## `` heading) with ``block``. Raises ValueError when the
    heading or its fence is missing — a silent no-op would let BENCHES.md
    drift while looking regenerated."""
    start = md.find(heading)
    if start < 0:
        raise ValueError(f"heading not found: {heading!r}")
    end = md.find("\n## ", start + len(heading))
    section = md[start:end] if end >= 0 else md[start:]
    new_section, n = _FENCE_RE.subn(
        "```json\n" + block + "\n```", section, count=1)
    if not n:
        raise ValueError(f"no fenced JSON block under {heading!r}")
    return md[:start] + new_section + (md[end:] if end >= 0 else "")


def regenerate(root: Path, json_path: Path | None = None,
               write: bool = False) -> str:
    """Regenerate the driver-recorded section(s) of BENCHES.md from the
    latest (or given) record; returns the regenerated Config #4 block.
    ``write=True`` rewrites BENCHES.md in place."""
    record_path = json_path or find_latest_record(root)
    rec = load_record(record_path)
    block = config4_block(rec)
    benches = root / "BENCHES.md"
    md = benches.read_text()
    updated = update_section(md, "## Config #4", block)
    # the committed-number sections regenerate only when the record
    # carries them (older rounds predate the matrix/ingress phases and
    # their fences hold real committed numbers); the storm/durability/
    # partition fences ALWAYS regenerate — a record predating the phase
    # writes the explicit no-committed-record row instead of leaving a
    # stale hand-written "pending" note
    src = record_path.name
    for heading, extra in (("## Matrix serving", matrix_block(rec)),
                           ("## Tree serving", tree_block(rec)),
                           ("## Columnar ingress", ingress_block(rec)),
                           ("## Reconnect storm",
                            reconnect_storm_block(rec, src)),
                           ("## Overload storm",
                            overload_block(rec, src)),
                           ("## Durability", durability_block(rec, src)),
                           ("## Partitioned serving",
                            partition_block(rec, src)),
                           ("## Read plane",
                            read_plane_block(rec, src))):
        if extra is not None:
            updated = update_section(updated, heading, extra)
    if write:
        benches.write_text(updated)
    return block


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--root", type=Path, default=Path(__file__).parent.parent,
                    help="repo root holding BENCHES.md and BENCH_r*.json")
    ap.add_argument("--json", type=Path, default=None,
                    help="explicit record path (default: newest BENCH_r*)")
    ap.add_argument("--write", action="store_true",
                    help="rewrite BENCHES.md (default: print the block)")
    args = ap.parse_args(argv)
    block = regenerate(args.root, args.json, write=args.write)
    print(block)
    if args.write:
        print(f"BENCHES.md updated from "
              f"{(args.json or find_latest_record(args.root)).name}",
              file=sys.stderr)
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
