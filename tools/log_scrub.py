#!/usr/bin/env python
"""Offline integrity scrubber for the durable layer (ISSUE 10).

Walks oplog spill segments and summary-generation manifests WITHOUT the
owning process, verifying every byte the durability plane claims to
protect:

- ``*.jsonl`` spills (``oplog.PartitionedLog``): re-runs the checksum
  chain (``<8-hex crc32 chain word> <json>``) line by line; reports the
  first break with its record index and byte offset.
- ``p*.log`` native segments (``native_oplog`` / ``native/oplog.cpp``):
  re-parses the ``[u32 len][u32 crc32][payload]`` framing, verifies each
  frame CRC, then the ``b"H"``-wrapped chain words across frames. This
  catches what a bare reopen would SILENTLY truncate (the C scan stops
  at the first bad frame and drops everything after it — acked records
  included); the scrubber reports it instead.
- summary generation stores (any directory holding
  ``gen-*.manifest.json``): SHA-256 of each blob against its manifest
  (``runtime.summarizer.SummaryGenerationStore``).

``--repair`` truncates a corrupt log segment back to its last verified
prefix (counting ``scrub_repairs_total``) and quarantines corrupt
summary generations (rename to ``*.quarantine`` — the recovery ladder
already skips unverifiable rungs; quarantining just makes the scrub
idempotent). Torn tails (unterminated trailing junk — a crash artifact,
not rot) are repaired the same way but reported separately.

Usage::

    python tools/log_scrub.py SPILL_DIR [...]
    python tools/log_scrub.py --check SPILL_DIR      # exit 1 on breaks
    python tools/log_scrub.py --repair SPILL_DIR
    python tools/log_scrub.py --json SPILL_DIR
"""

from __future__ import annotations

import argparse
import fnmatch
import json
import os
import struct
import sys
import zlib
from typing import List

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(
    __file__))))

from fluidframework_tpu.server.oplog import (             # noqa: E402
    chain_step, scan_chained_spill,
)
from fluidframework_tpu.runtime.summarizer import (       # noqa: E402
    SummaryGenerationStore,
)
from fluidframework_tpu.utils.telemetry import REGISTRY   # noqa: E402


def scrub_jsonl(path: str, repair: bool = False) -> dict:
    """Verify one JSONL spill's checksum chain; optionally truncate to
    the last verified prefix."""
    scan = scan_chained_spill(path)
    report = {
        "path": path, "format": "jsonl",
        "records": len(scan["records"]),
        "verified_bytes": scan["good_end"],
        "torn_tail": scan["torn"],
        "problems": list(scan["problems"]),
        "repaired": False,
    }
    if (scan["problems"] or scan["torn"]) and repair:
        with open(path, "r+b") as f:
            f.truncate(scan["good_end"])
        report["repaired"] = True
        REGISTRY.inc("scrub_repairs_total")
    return report


def scrub_native_segment(path: str, repair: bool = False) -> dict:
    """Verify one native segment's frame CRCs + chain words; optionally
    truncate to the last verified frame."""
    with open(path, "rb") as f:
        data = f.read()
    problems: List[dict] = []
    records = 0
    chain = 0
    good_end = 0
    torn = False
    off = 0
    while off < len(data):
        if off + 8 > len(data):
            torn = True  # partial trailing header: crash artifact
            break
        ln, crc = struct.unpack_from("<II", data, off)
        if off + 8 + ln > len(data):
            torn = True  # partial trailing payload
            break
        payload = data[off + 8:off + 8 + ln]
        if zlib.crc32(payload) & 0xFFFFFFFF != crc:
            problems.append({"index": records, "offset": off,
                             "reason": "frame crc mismatch"})
            break
        if payload[:1] == b"H":
            stored = int.from_bytes(payload[1:5], "little")
            if stored != chain_step(payload[5:], chain):
                problems.append({"index": records, "offset": off,
                                 "reason": "chain mismatch"})
                break
            chain = stored
        # pre-chain record: chain carries forward unverified
        records += 1
        off += 8 + ln
        good_end = off
    report = {
        "path": path, "format": "native",
        "records": records,
        "verified_bytes": good_end,
        "torn_tail": torn,
        "problems": problems,
        "repaired": False,
    }
    if (problems or torn) and repair:
        with open(path, "r+b") as f:
            f.truncate(good_end)
        report["repaired"] = True
        REGISTRY.inc("scrub_repairs_total")
    return report


def scrub_generations(directory: str, repair: bool = False) -> dict:
    """Verify every summary generation's manifest hash; optionally
    quarantine failing rungs."""
    store = SummaryGenerationStore(directory, keep=1 << 30)
    problems = store.verify_all()
    report = {
        "path": directory, "format": "generations",
        "records": len(store.generations()),
        "problems": problems,
        "repaired": False,
    }
    if problems and repair:
        for p in problems:
            gen = p["generation"]
            for fmt in (store._BLOB, store._MANIFEST):
                src = os.path.join(directory, fmt.format(gen))
                if os.path.exists(src):
                    os.replace(src, src + ".quarantine")
        report["repaired"] = True
        REGISTRY.inc("scrub_repairs_total")
    return report


def scrub_tree(root: str, repair: bool = False) -> List[dict]:
    """Walk ``root`` and scrub everything recognizable. A single file
    path is scrubbed directly by extension."""
    reports: List[dict] = []
    if os.path.isfile(root):
        if root.endswith(".jsonl"):
            return [scrub_jsonl(root, repair)]
        if root.endswith(".log"):
            return [scrub_native_segment(root, repair)]
        return []
    for dirpath, _dirnames, filenames in os.walk(root):
        if any(fnmatch.fnmatch(n, "gen-*.manifest.json")
               for n in filenames):
            reports.append(scrub_generations(dirpath, repair))
        for name in sorted(filenames):
            path = os.path.join(dirpath, name)
            if name.endswith(".jsonl"):
                reports.append(scrub_jsonl(path, repair))
            elif fnmatch.fnmatch(name, "p*.log"):
                reports.append(scrub_native_segment(path, repair))
    return reports


def summarize_reports(reports: List[dict]) -> dict:
    """Roll a scrub run up to the numbers CI gates on."""
    return {
        "files": len(reports),
        "records": sum(r.get("records", 0) for r in reports),
        "chain_breaks": sum(len(r.get("problems", [])) for r in reports),
        "torn_tails": sum(1 for r in reports if r.get("torn_tail")),
        "repaired": sum(1 for r in reports if r.get("repaired")),
    }


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description="offline oplog/summary integrity scrubber "
                    "(see module docstring)")
    ap.add_argument("paths", nargs="+",
                    help="spill dirs, segment files, or generation dirs")
    ap.add_argument("--repair", action="store_true",
                    help="truncate corrupt segments to the last verified "
                         "prefix; quarantine corrupt summary generations")
    ap.add_argument("--check", action="store_true",
                    help="exit 1 if any chain break was found")
    ap.add_argument("--json", action="store_true", dest="as_json",
                    help="machine-readable report on stdout")
    args = ap.parse_args(argv)
    reports: List[dict] = []
    for path in args.paths:
        reports.extend(scrub_tree(path, repair=args.repair))
    summary = summarize_reports(reports)
    if args.as_json:
        print(json.dumps({"summary": summary, "reports": reports},
                         indent=2, sort_keys=True))
    else:
        for r in reports:
            status = "OK"
            if r.get("problems"):
                p = r["problems"][0]
                status = (f"BREAK at record {p.get('index', '?')} "
                          f"byte {p.get('offset', '?')} "
                          f"({p.get('reason', '?')})")
            elif r.get("torn_tail"):
                status = "torn tail"
            if r.get("repaired"):
                status += " [repaired]"
            print(f"{r['path']}: {r.get('records', 0)} records, {status}")
        print(f"scrubbed {summary['files']} files, "
              f"{summary['records']} records: "
              f"{summary['chain_breaks']} chain breaks, "
              f"{summary['torn_tails']} torn tails, "
              f"{summary['repaired']} repaired")
    if args.check and summary["chain_breaks"]:
        return 1
    return 0


if __name__ == "__main__":
    sys.exit(main())
