"""BASELINE config #3: SharedMatrix 1k×1k concurrent cell-edit storm.

Merges sequenced set-cell batches into the device-resident sorted sparse
cell table (`ops.matrix_kernel`) — LWW conflict resolution for ~1M cells
with 64k-op batches, two multi-operand sorts per batch, no scatters.
Timed section ends with a device→host read (see `benches/__init__`).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time

import numpy as np


def main(rows: int = 1024, cols: int = 1024, ops_per_batch: int = 1 << 16,
         n_batches: int = 8, seed: int = 0):
    import jax
    import jax.numpy as jnp

    from fluidframework_tpu.ops.matrix_kernel import (
        MatrixCellState, apply_cells_batch_jit,
    )

    rng = np.random.default_rng(seed)
    O = ops_per_batch
    batches = []
    for b in range(n_batches):
        key = (rng.integers(0, rows, O) * cols
               + rng.integers(0, cols, O)).astype(np.int32)
        seq = (b * O + np.arange(1, O + 1)).astype(np.int32)
        val = rng.integers(1, 1 << 30, O, dtype=np.int32)
        batches.append(tuple(jnp.asarray(x) for x in (key, seq, val)))

    f = apply_cells_batch_jit
    cap = rows * cols + O
    state = MatrixCellState.create(cap)
    state = f(state, *batches[0], False)
    _ = np.asarray(state.count)          # warm + real sync

    state = MatrixCellState.create(cap)
    _ = np.asarray(state.count)
    t0 = time.perf_counter()
    for b in batches:
        state = f(state, *b, False)
    count = int(np.asarray(state.count))  # honest end sync
    total = time.perf_counter() - t0
    assert not np.asarray(state.overflow).any()

    n_ops = O * n_batches
    print(json.dumps({
        "metric": "config3_sharedmatrix_cell_merges_per_sec",
        "value": round(n_ops / total, 1),
        "unit": "ops/s",
        "vs_baseline": None,
        "grid": f"{rows}x{cols}",
        "total_ops": n_ops,
        "live_cells": count,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
