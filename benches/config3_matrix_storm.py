"""BASELINE config #3: SharedMatrix 1k×1k concurrent cell-edit storm.

Merges sequenced set-cell batches into the device-resident sorted sparse
cell table (`ops.matrix_kernel`) — LWW conflict resolution for ~1M cells
with 64k-op batches, two multi-operand sorts per batch, no scatters.
Timed section ends with a device→host read (see `benches/__init__`).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time

import numpy as np


def main(rows: int = 1024, cols: int = 1024, ops_per_batch: int = 1 << 16,
         n_batches: int = 8, seed: int = 0):
    import jax
    import jax.numpy as jnp

    from fluidframework_tpu.ops.matrix_kernel import (
        MatrixCellState, apply_cells_batch_jit,
    )

    rng = np.random.default_rng(seed)
    O = ops_per_batch
    batches = []
    for b in range(n_batches):
        key = (rng.integers(0, rows, O) * cols
               + rng.integers(0, cols, O)).astype(np.int32)
        seq = (b * O + np.arange(1, O + 1)).astype(np.int32)
        val = rng.integers(1, 1 << 30, O, dtype=np.int32)
        batches.append(tuple(jnp.asarray(x) for x in (key, seq, val)))

    f = apply_cells_batch_jit
    cap = rows * cols + O
    state = MatrixCellState.create(cap)
    state = f(state, *batches[0], False)
    _ = np.asarray(state.count)          # warm + real sync

    state = MatrixCellState.create(cap)
    _ = np.asarray(state.count)
    t0 = time.perf_counter()
    for b in batches:
        state = f(state, *b, False)
    count = int(np.asarray(state.count))  # honest end sync
    total = time.perf_counter() - t0
    assert not np.asarray(state.overflow).any()

    n_ops = O * n_batches

    # --- serving phase: the FULL matrix engine ---------------------------
    # columnar setCell ingest: one C++ sequencing call + one device
    # axis-resolve scan (position→key INSIDE the scan) + FWW filter +
    # one cell-table merge + one durable record per batch (r4:
    # VERDICT r3 missing #3 — no per-op Python on the volume path)
    from fluidframework_tpu.server import native_deli
    from fluidframework_tpu.server.serving import MatrixServingEngine
    serving_ops_per_sec = None
    n_serve = 0
    if native_deli.available():
        D, G = 64, 32       # docs; each doc a 32×32 grid, then cell storms
        eng = MatrixServingEngine(n_docs=D, cell_capacity=1 << 17,
                                  batch_window=10 ** 9, axis_capacity=128,
                                  sequencer="native")
        docs = [f"mx-{i}" for i in range(D)]
        srng = np.random.default_rng(7)
        cs = {d: 0 for d in docs}
        for d in docs:
            eng.connect(d, 7)
            for mx in ("insRow", "insCol"):
                cs[d] += 1
                _, nack = eng.submit(d, 7, cs[d], 0,
                                     {"mx": mx, "pos": 0, "count": G,
                                      "opKey": (7, cs[d])})
                assert nack is None
        eng.flush()

        def storm():
            ids, cseqs, rp, cp, vals = [], [], [], [], []
            for d in docs:
                for _ in range(64):
                    cs[d] += 1
                    ids.append(d)
                    cseqs.append(cs[d])
                    rp.append(int(srng.integers(0, G)))
                    cp.append(int(srng.integers(0, G)))
                    vals.append(int(srng.integers(0, 1 << 20)))
            return ids, cseqs, rp, cp, vals

        ids, cseqs, rp, cp, vals = storm()   # warmup (compiles the scan)
        eng.ingest_cells(ids, [7] * len(ids), cseqs, [0] * len(ids),
                         rp, cp, vals)
        _ = eng.dims(docs[0])
        t0 = time.perf_counter()
        for _w in range(6):
            ids, cseqs, rp, cp, vals = storm()
            res = eng.ingest_cells(ids, [7] * len(ids), cseqs,
                                   [0] * len(ids), rp, cp, vals)
            assert res["nacked"] == 0
            n_serve += len(ids)
        _ = eng.dims(docs[0])               # end sync (device read)
        serving_ops_per_sec = n_serve / (time.perf_counter() - t0)

    print(json.dumps({
        "metric": "config3_sharedmatrix_cell_merges_per_sec",
        "value": round(n_ops / total, 1),
        "unit": "ops/s",
        "vs_baseline": None,
        "grid": f"{rows}x{cols}",
        "total_ops": n_ops,
        "live_cells": count,
        "serving_ops_per_sec":
            round(serving_ops_per_sec, 1) if serving_ops_per_sec else None,
        "serving_ops": n_serve,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
