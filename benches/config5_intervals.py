"""BASELINE config #5 (stretch): rich text + IntervalCollection co-editing.

Simulates many co-editors on one document: interleaved text edits and
interval add/change/delete through the full `SharedString` DDS (interval
endpoints are merge-tree local references that slide on concurrent
removes — the ProseMirror-style workload). The 100k-co-editor scale of the
original config is reached by document sharding (each doc is independent —
SURVEY.md §2.14); this measures the per-document interval engine rate, so
docs/sec at fleet scale = this number × chips ÷ ops-per-doc.
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import random
import time

from fluidframework_tpu.models.shared_string import SharedString
from fluidframework_tpu.testing.mocks import MockSequencer, \
    create_connected_dds


def main(n_replicas: int = 8, n_ops: int = 3000, seed: int = 0):
    rng = random.Random(seed)
    seqr = MockSequencer()
    reps = [create_connected_dds(seqr, SharedString) for _ in range(n_replicas)]
    ivs = [r.get_interval_collection("comments") for r in reps]
    live_ids = []

    t0 = time.perf_counter()
    sent = 0
    for i in range(n_ops):
        k = rng.randrange(n_replicas)
        r = reps[k]
        ln = r.get_length()
        p = rng.random()
        if p < 0.55 or ln < 8:
            r.insert_text(rng.randint(0, ln), "lorem "[:rng.randint(1, 6)])
        elif p < 0.70:
            s = rng.randint(0, ln - 4)
            r.remove_text(s, s + rng.randint(1, 4))
        elif p < 0.85:
            s = rng.randint(0, ln - 6)
            live_ids.append((k, ivs[k].add(s, s + rng.randint(1, 5),
                                           {"author": k})))
        elif p < 0.95 and live_ids:
            owner, iid = live_ids[rng.randrange(len(live_ids))]
            s = rng.randint(0, max(0, reps[owner].get_length() - 4))
            ivs[owner].change(iid, start=s, end=s + 2)
        elif live_ids:
            owner, iid = live_ids.pop(rng.randrange(len(live_ids)))
            ivs[owner].delete(iid)
        sent += 1
        if rng.random() < 0.25:
            seqr.process_some(rng.randint(1, 6))
    seqr.process_all_messages()
    total = time.perf_counter() - t0

    assert len({r.get_text() for r in reps}) == 1, "text diverged"
    assert len({c.digest() for c in ivs}) == 1, "intervals diverged"
    applied = sent * n_replicas
    print(json.dumps({
        "metric": "config5_intervals_applies_per_sec",
        "value": round(applied / total, 1),
        "unit": "op-applies/s",
        "vs_baseline": None,
        "replicas": n_replicas,
        "ops_sequenced": sent,
        "intervals": len(ivs[0]),
        "backend": "cpu-oracle",
    }))


if __name__ == "__main__":
    main()
