"""Profile the tree serving paths post-redesign: dict ingest_batch,
pre-encoded ingest_records (serial and pipelined), the unified flat
path (pre-encoded leaf records through the SAME ingest_records
pipeline), kernel-only."""
import time

import numpy as np
import jax

from fluidframework_tpu.server.serving import TreeServingEngine
from fluidframework_tpu.server.tree_wire import (encode_leaf_records,
                                                 encode_tree_batch)
from fluidframework_tpu.server.ingest_pipeline import (
    PipelinedIngestExecutor,
)
from fluidframework_tpu.ops.tree_kernel import TreeState

n_docs = 8192
eng = TreeServingEngine(n_docs=n_docs, capacity=128,
                        batch_window=10 ** 9, sequencer="native")
tdocs = [f"t-{i}" for i in range(n_docs)]
for d in tdocs:
    eng.connect(d, 1)


def tree_ops(wave):
    ids, ops = [], []
    for d in tdocs:
        ids.append(d)
        if wave == 0:
            ops.append({"op": "insert", "parent": "root",
                        "field": "kids", "after": None,
                        "nodes": [{"id": f"{d}-n0", "type": "item",
                                   "value": 0}]})
        else:
            prev = f"{d}-n{wave - 1}"
            ops.append({"op": "transaction",
                        "constraints": [{"nodeExists": prev}],
                        "edits": [
                            {"op": "insert", "parent": "root",
                             "field": "kids", "after": prev,
                             "nodes": [{"id": f"{d}-n{wave}",
                                        "type": "item",
                                        "value": wave}]},
                            {"op": "setValue", "id": prev,
                             "value": wave * 10}]})
    return ids, ops


ones = [1] * n_docs

# warmup (dict path compiles the dispatch too)
ids, ops = tree_ops(0)
t0 = time.perf_counter()
eng.ingest_batch(ids, ones, ones, [0] * n_docs, ops)
print(f"warmup wave (incl compile): {(time.perf_counter()-t0)*1000:.0f}ms")
_ = np.asarray(eng.store.state.node_id)

# dict path: one wave
ids, ops = tree_ops(1)
t0 = time.perf_counter()
eng.ingest_batch(ids, ones, [2] * n_docs, [0] * n_docs, ops)
t_host = time.perf_counter() - t0
_ = np.asarray(eng.store.state.node_id)
t_sync = time.perf_counter() - t0
print(f"dict wave: host={t_host*1000:.1f}ms synced={t_sync*1000:.1f}ms "
      f"-> {n_docs/t_sync:.0f} ops/s (host-bound {n_docs/t_host:.0f})")

# pre-encoded path: encode outside the timed section (client work)
ids, ops = tree_ops(2)
t0 = time.perf_counter()
batch = encode_tree_batch(ops)
t_enc = time.perf_counter() - t0
print(f"client encode: {t_enc*1000:.1f}ms ({t_enc/n_docs*1e6:.2f}us/op), "
      f"recs={len(batch['rec_op'])}")

t0 = time.perf_counter()
eng.ingest_records(ids, ones, [3] * n_docs, [0] * n_docs, batch)
t_host = time.perf_counter() - t0
_ = np.asarray(eng.store.state.node_id)
t_sync = time.perf_counter() - t0
print(f"records wave: host={t_host*1000:.1f}ms synced={t_sync*1000:.1f}ms "
      f"-> {n_docs/t_sync:.0f} ops/s (host-bound {n_docs/t_host:.0f})")
snap = eng.metrics.snapshot()
print({k: round(v, 1) for k, v in snap.items() if "ingest_" in k and
       "p50" in k})

# pipelined: 4 pre-encoded waves through the staged executor (wave N+1
# prepacks/sequences under wave N's dispatch)
batches = []
for w in range(4, 8):
    ids, ops = tree_ops(w)
    batches.append(encode_tree_batch(ops))
ex = PipelinedIngestExecutor(eng, depth=3)
t0 = time.perf_counter()
for w, b in enumerate(batches):
    ex.submit(ids, ones, [w + 5] * n_docs, [0] * n_docs, b)
ex.drain()
_ = np.asarray(eng.store.state.node_id)
t_pipe = time.perf_counter() - t0
print(f"4 record waves pipelined: {t_pipe*1000:.1f}ms -> "
      f"{4*n_docs/t_pipe:.0f} ops/s overlap="
      f"{ex.stats()['overlap']:.2f}")
ex.close()

# flat path: pre-encoded leaf records through the SAME ingest_records
# pipeline (ingest_leaves is now a thin validated builder over this —
# hot callers pre-encode off the serving thread, as here)
n_leaf = 8192
leng = TreeServingEngine(n_docs=n_leaf, capacity=128,
                         batch_window=10 ** 9, sequencer="native")
ldocs = [f"f-{i}" for i in range(n_leaf)]
for d in ldocs:
    leng.connect(d, 1)
lones = [1] * n_leaf
leng.ingest_leaves(ldocs, lones, lones, [0] * n_leaf, ["root"] * n_leaf,
                   ["kids"] * n_leaf, [f"{d}-f0" for d in ldocs],
                   [0] * n_leaf)
_ = np.asarray(leng.store.state.node_id)
lrows = np.array([leng.doc_row(d) for d in ldocs], np.int32)
flat_batches = [
    encode_leaf_records(["root"] * n_leaf, ["kids"] * n_leaf,
                        [f"{d}-f{wave}" for d in ldocs],
                        [wave] * n_leaf, None,
                        [f"{d}-f{wave-1}" for d in ldocs])
    for wave in range(1, 5)]
lex = PipelinedIngestExecutor(leng, depth=3)
t0 = time.perf_counter()
for wave, b in enumerate(flat_batches, start=1):
    lex.submit(None, lones, [wave + 1] * n_leaf, [0] * n_leaf, b,
               rows=lrows)
lex.drain()
_ = np.asarray(leng.store.state.node_id)
t_flat = time.perf_counter() - t0
print(f"4 flat waves: {t_flat*1000:.1f}ms -> {4*n_leaf/t_flat:.0f} ops/s")
lex.close()

# kernel-only: pre-packed planes, pipelined applies
ids, ops = tree_ops(9)
batch = encode_tree_batch(ops)
rec_op = batch["rec_op"]
g = eng._map_records(batch["recs"], batch)
rows = np.arange(n_docs, dtype=np.int64)[rec_op]
seqs = np.full(len(rec_op), 50, np.int64)
planes = eng.store.pack_records(rows, g, seqs)
import jax.numpy as jnp
jp = jnp.asarray(planes)
from fluidframework_tpu.ops.tree_kernel import apply_tree_planes_jit
st = TreeState.create(n_docs, 128)
st = apply_tree_planes_jit(st, jp)
_ = np.asarray(st.overflow)
t0 = time.perf_counter()
for _i in range(8):
    st = apply_tree_planes_jit(st, jp)
_ = np.asarray(st.overflow)
t_k = time.perf_counter() - t0
print(f"kernel-only 8 applies (O={planes.shape[2]}): {t_k*1000:.1f}ms -> "
      f"{8*n_docs/t_k:.0f} ops/s")
