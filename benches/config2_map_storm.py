"""BASELINE config #2: SharedMap op storm across 1k containers on device.

A (doc × op) batch of sequenced set/delete/clear ops is merged for 1024
documents per jit'd call by the batched map kernel (`ops.map_kernel` —
the "minimum slice" of SURVEY.md §7.3). Timed section ends with a
device→host read (see `benches/__init__`).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time

import numpy as np


def main(n_docs: int = 1024, n_keys: int = 64, ops_per_batch: int = 64,
         n_batches: int = 64, seed: int = 0):
    import jax
    import jax.numpy as jnp

    from fluidframework_tpu.ops.map_kernel import MapState, apply_map_batch
    from fluidframework_tpu.ops.schema import OpKind

    rng = np.random.default_rng(seed)
    D, O = n_docs, ops_per_batch
    mix = [int(OpKind.MAP_SET)] * 8 + [int(OpKind.MAP_DELETE)] * 2 \
        + [int(OpKind.MAP_CLEAR)]

    batches = []
    seq0 = 1
    for _ in range(n_batches):
        kind = rng.choice(mix, size=(D, O)).astype(np.int32)
        a0 = rng.integers(0, n_keys, size=(D, O), dtype=np.int32)
        a1 = rng.integers(1, 1 << 20, size=(D, O), dtype=np.int32)
        seq = (seq0 + np.arange(O, dtype=np.int32)[None, :] * D
               + np.arange(D, dtype=np.int32)[:, None]).astype(np.int32)
        seq0 += D * O
        batches.append(tuple(jnp.asarray(x) for x in (kind, a0, a1, seq)))

    f = jax.jit(apply_map_batch, donate_argnums=0)
    state = MapState.create(D, n_keys)
    state = f(state, *batches[0])
    _ = np.asarray(state.present)        # warm + real sync

    state = MapState.create(D, n_keys)
    _ = np.asarray(state.present)
    t0 = time.perf_counter()
    for b in batches:
        state = f(state, *b)
    _ = np.asarray(state.present)        # honest end sync
    total = time.perf_counter() - t0

    n_ops = D * O * n_batches
    print(json.dumps({
        "metric": "config2_sharedmap_ops_per_sec",
        "value": round(n_ops / total, 1),
        "unit": "ops/s",
        "vs_baseline": None,
        "docs": D,
        "total_ops": n_ops,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
