"""BASELINE config #2: SharedMap op storm across 1k containers on device.

A (doc × op) batch of sequenced set/delete/clear ops is merged for 1024
documents per jit'd call by the batched map kernel (`ops.map_kernel` —
the "minimum slice" of SURVEY.md §7.3). Timed section ends with a
device→host read (see `benches/__init__`).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import time

import numpy as np


def main(n_docs: int = 1024, n_keys: int = 64, ops_per_batch: int = 64,
         n_batches: int = 64, seed: int = 0):
    import jax
    import jax.numpy as jnp

    from fluidframework_tpu.ops.map_kernel import MapState, apply_map_batch
    from fluidframework_tpu.ops.schema import OpKind

    rng = np.random.default_rng(seed)
    D, O = n_docs, ops_per_batch
    mix = [int(OpKind.MAP_SET)] * 8 + [int(OpKind.MAP_DELETE)] * 2 \
        + [int(OpKind.MAP_CLEAR)]

    batches = []
    seq0 = 1
    for _ in range(n_batches):
        kind = rng.choice(mix, size=(D, O)).astype(np.int32)
        a0 = rng.integers(0, n_keys, size=(D, O), dtype=np.int32)
        a1 = rng.integers(1, 1 << 20, size=(D, O), dtype=np.int32)
        seq = (seq0 + np.arange(O, dtype=np.int32)[None, :] * D
               + np.arange(D, dtype=np.int32)[:, None]).astype(np.int32)
        seq0 += D * O
        batches.append(tuple(jnp.asarray(x) for x in (kind, a0, a1, seq)))

    f = jax.jit(apply_map_batch, donate_argnums=0)
    state = MapState.create(D, n_keys)
    state = f(state, *batches[0])
    _ = np.asarray(state.present)        # warm + real sync

    state = MapState.create(D, n_keys)
    _ = np.asarray(state.present)
    t0 = time.perf_counter()
    for b in batches:
        state = f(state, *b)
    _ = np.asarray(state.present)        # honest end sync
    total = time.perf_counter() - t0

    n_ops = D * O * n_batches

    # --- serving phase: the FULL map engine (columnar ingest) -----------
    # raw ops → C++ Deli sequencing → whole-batch durable record → fused
    # unpack+apply dispatch (r4: the map fast path, VERDICT r3 missing #3)
    from fluidframework_tpu.server import native_deli
    from fluidframework_tpu.server.serving import MapServingEngine
    serving_ops_per_sec = None
    if native_deli.available():
        eng = MapServingEngine(n_docs=D, n_keys=n_keys,
                               batch_window=10 ** 9, sequencer="native")
        docs = [f"m-{i}" for i in range(D)]
        for d in docs:
            eng.connect(d, 1)
            eng.doc_row(d)
        rows_arr = np.array([eng.doc_row(d) for d in docs], np.int32)
        keys = [f"k{j}" for j in range(n_keys)]
        values = [f"v{j}" for j in range(64)]
        client = np.ones((D, O), np.int32)
        ref = np.zeros((D, O), np.int32)
        sbatches = []
        for b in range(12):
            kind = rng.choice(mix, size=(D, O)).astype(np.int32)
            kidx = rng.integers(0, n_keys, size=(D, O), dtype=np.int32)
            vidx = rng.integers(0, 64, size=(D, O), dtype=np.int32)
            cseq = np.broadcast_to(
                np.arange(b * O + 1, (b + 1) * O + 1, dtype=np.int32),
                (D, O))
            sbatches.append((kind, kidx, vidx, cseq))
        kind, kidx, vidx, cseq = sbatches[0]
        eng.ingest_planes(rows_arr, client, cseq, ref, kind, kidx, keys,
                          values, vidx)
        _ = np.asarray(eng.store.state.present)
        t0 = time.perf_counter()
        for kind, kidx, vidx, cseq in sbatches[1:]:
            res = eng.ingest_planes(rows_arr, client, cseq, ref, kind,
                                    kidx, keys, values, vidx)
            assert res["nacked"] == 0
        _ = np.asarray(eng.store.state.present)
        serving_ops_per_sec = D * O * (len(sbatches) - 1) / (
            time.perf_counter() - t0)

    print(json.dumps({
        "metric": "config2_sharedmap_ops_per_sec",
        "value": round(n_ops / total, 1),
        "unit": "ops/s",
        "vs_baseline": None,
        "docs": D,
        "total_ops": n_ops,
        "serving_ops_per_sec":
            round(serving_ops_per_sec, 1) if serving_ops_per_sec else None,
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
