"""BASELINE.md benchmark configs #1–#5.

Each script is standalone (`python benches/configN_*.py`) and prints ONE
JSON line in the same shape as the headline `bench.py` (which implements
config #4, the north-star metric, and is what the driver runs). No
published reference numbers exist (BASELINE.md: reference mount was empty,
`published: {}`), so `vs_baseline` is null except where BASELINE.json set
an explicit target.

Measurement honesty on the axon TPU platform: `jax.block_until_ready` does
not sync — timed sections end with a device→host read (see bench.py).
"""
