"""Multi-client columnar-ingress storm (VERDICT r4 missing #5): M real
TCP clients → binary op frames → windowed aggregation → batched
``ingest_planes`` dispatches on the serving engine. Measures the socket
fan-in + columnar fan-out COMPOSED (the JSON front door measures the
per-op protocol path instead)."""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import threading
import time

import numpy as np


def main(n_clients: int = 8, docs_per: int = 1024, waves: int = 24,
         window_rows: int = 4096, pipeline_depth: int = 3,
         decode: str = None):
    from fluidframework_tpu.server.columnar_ingress import (
        ColumnarAlfred, ColumnarClient, _OP_DTYPE,
    )
    from fluidframework_tpu.server.serving import StringServingEngine

    if decode is None:
        # FLUID_INGRESS_DECODE=numpy measures the always-available
        # fallback tier on its own (the 45k floor's subject)
        decode = os.environ.get("FLUID_INGRESS_DECODE", "auto")
    n_docs = n_clients * docs_per
    eng = StringServingEngine(n_docs=n_docs, capacity=256,
                              batch_window=10 ** 9, compact_every=10 ** 9,
                              sequencer="native")
    srv = ColumnarAlfred(eng, window_min_rows=window_rows,
                         window_ms=2.0, pipeline_depth=pipeline_depth,
                         decode=decode).start_in_thread()

    total = n_clients * docs_per * waves
    acked = [0] * n_clients
    done = threading.Barrier(n_clients + 1)

    def client_run(ci: int):
        cl = ColumnarClient("127.0.0.1", srv.port)
        docs = [f"c{ci}-d{j}" for j in range(docs_per)]
        rows = np.asarray(list(cl.join(docs).values()), np.uint16)

        def sender():
            for w in range(waves):
                ops = np.zeros(docs_per, _OP_DTYPE)
                ops["row"] = rows
                ops["kind"] = 0
                ops["a0"] = 0
                ops["tidx"] = 0
                ops["cseq"] = w + 1
                ops["ref"] = 0
                cl.send_ops([f"w{w}"], ops)

        st = threading.Thread(target=sender, daemon=True)
        st.start()
        want = docs_per * waves
        while acked[ci] < want:
            resp = cl.recv_json()
            assert resp["t"] == "acks", resp
            for _cs, seq in resp["acks"]:
                assert seq > 0
            acked[ci] += len(resp["acks"])
        st.join()
        cl.close()
        done.wait()

    threads = [threading.Thread(target=client_run, args=(ci,),
                                daemon=True) for ci in range(n_clients)]
    # warmup window shape: one tiny pre-wave through a throwaway client
    t0 = time.perf_counter()
    for t in threads:
        t.start()
    done.wait(timeout=600)
    elapsed = time.perf_counter() - t0

    ds = srv.drain_stats()
    ps = srv.pipeline_stats()
    print(json.dumps({
        "metric": "columnar_ingress_ops_per_sec",
        "value": round(total / elapsed, 1),
        "unit": "ops/s",
        "vs_baseline": None,
        "total_ops": total,
        "clients": n_clients,
        "windows": srv.windows_flushed,
        "ops_per_window": round(total / max(srv.windows_flushed, 1), 1),
        "evictions": srv.evictions,
        "decode_tier": ds["tier"],
        "decode_p50_ms": ds["decode_p50_ms"],
        "drained_bytes_per_pass": ds["bytes_per_pass_p50"],
        "drain_passes": ds["passes"],
        "pipeline_depth": pipeline_depth,
        "pipeline": ps,
        "transport": "tcp-localhost width-coded binary",
    }))
    srv.stop()


if __name__ == "__main__":
    main()
