"""BASELINE config #1: single-doc typing-trace replay — CPU reference point.

Replays a deterministic multi-client typing trace (interleaved inserts,
removes, annotates with crossing in-flight ops) through the pure-Python
oracle stack (`SequenceClient` + `MockSequencer` — the reference-semantics
spec everything else is tested against). This is the number the TPU
speedups are quoted against (BASELINE.md: "run config 1 on CPU to establish
the local reference number"). Reference analog: replaying a shared-text
trace through `merge-tree` `Client.applyMsg` (SURVEY.md §3.2, §2.18).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import random
import time

from fluidframework_tpu.models.merge_tree_client import SequenceClient
from fluidframework_tpu.testing.mocks import MockSequencer


def main(n_ops: int = 4000, n_clients: int = 3, seed: int = 0):
    rng = random.Random(seed)
    seqr = MockSequencer()
    clients = [SequenceClient(seqr.allocate_client_id())
               for _ in range(n_clients)]
    for c in clients:
        seqr.connect(c)

    t0 = time.perf_counter()
    sent = 0
    for i in range(n_ops):
        c = clients[rng.randrange(n_clients)]
        ln = c.get_length()
        r = rng.random()
        if r < 0.70 or ln < 4:
            pos = rng.randint(0, ln)
            seqr.submit(c, c.insert_text_local(pos, "abcd"[:rng.randint(1, 4)]))
        elif r < 0.90:
            start = rng.randint(0, ln - 2)
            seqr.submit(c, c.remove_range_local(start, start + 2))
        else:
            start = rng.randint(0, ln - 2)
            seqr.submit(c, c.annotate_range_local(start, start + 2,
                                                  {"b": True}))
        sent += 1
        if rng.random() < 0.3:          # let ops cross in flight
            seqr.process_some(rng.randint(1, 4))
    seqr.process_all_messages()
    total = time.perf_counter() - t0

    texts = {c.get_text() for c in clients}
    assert len(texts) == 1, "replicas diverged"
    # every submitted op is applied once per replica
    applied = sent * n_clients
    print(json.dumps({
        "metric": "config1_typing_replay_applies_per_sec",
        "value": round(applied / total, 1),
        "unit": "op-applies/s",
        "vs_baseline": None,
        "ops_sequenced": sent,
        "replicas": n_clients,
        "final_len": clients[0].get_length(),
        "backend": "cpu-oracle",
    }))


if __name__ == "__main__":
    main()
