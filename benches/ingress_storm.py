"""Ingress-tier throughput: raw ops through the REAL socket front door
(Alfred analog) — framed-JSON TCP → LocalService pipeline (Kafka-role
log → Deli → Broadcaster) → sequenced broadcast back to the client.
Measures the wire + ordering-service tier itself (the device merge is
not in this path; see bench.py / BENCHES.md for the engine numbers).
"""

import os
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

import json
import socket
import threading
import time


def main(n_ops: int = 20000, pipeline: int = 256):
    from fluidframework_tpu.server import wire
    from fluidframework_tpu.server.ingress import AlfredServer

    srv = AlfredServer(port=0).start_in_thread()
    sock = socket.create_connection(("127.0.0.1", srv.port))
    # all receives go through one buffered reader: recv_frame's 2+
    # reads per frame then cost one syscall per READ_CHUNK of broadcast
    # traffic instead of 2+ per frame
    rd = wire.BufferedSocketReader(sock)
    wire.send_frame(sock, {"t": "connect", "doc": "storm"})
    assert wire.recv_frame(rd)["t"] == "connected"

    got = [0]
    done = threading.Event()

    def reader():
        while got[0] < n_ops:
            if wire.recv_frame(rd).get("t") == "op":
                got[0] += 1
        done.set()

    t = threading.Thread(target=reader, daemon=True)
    t0 = time.perf_counter()
    t.start()
    for i in range(n_ops):
        wire.send_frame(sock, {"t": "op", "client_seq": i + 1,
                               "contents": {"mt": "insert", "kind": 0,
                                            "pos": 0, "text": "ab"},
                               "ref_seq": 0})
        while got[0] < i - pipeline:   # bounded in-flight window
            time.sleep(0.0005)
    assert done.wait(timeout=120), f"only {got[0]}/{n_ops} acked"
    total = time.perf_counter() - t0
    sock.close()
    srv.stop()

    print(json.dumps({
        "metric": "ingress_ops_per_sec",
        "value": round(n_ops / total, 1),
        "unit": "ops/s",
        "vs_baseline": None,
        "total_ops": n_ops,
        "pipeline_window": pipeline,
        "transport": "tcp-localhost framed-JSON",
    }))


if __name__ == "__main__":
    main()
