"""Headline benchmark: SharedString ops/sec merged across a 10k-doc batch.

BASELINE.md config #4 (Deli replay across many docs, the north-star metric):
a synthetic multi-doc typing storm is sequenced round-robin and merged by the
batched merge-tree kernel on the real chip, with zamboni compaction between
batches. Prints ONE JSON line; vs_baseline is against the 1M ops/sec target
(no published reference numbers exist — BASELINE.md).
"""

import json
import time

import numpy as np


def main():
    import jax
    import jax.numpy as jnp

    from fluidframework_tpu.ops.merge_tree_kernel import (
        StringState, apply_string_batch, compact_string_state,
    )
    from fluidframework_tpu.testing.synthetic import typing_storm

    n_docs = 8192
    capacity = 1024
    ops_per_batch = 64
    n_batches = 4
    order = ("kind", "a0", "a1", "a2", "seq", "client", "ref_seq")

    batches = []
    seq = 1
    for b in range(n_batches):
        planes, seq = typing_storm(n_docs, ops_per_batch, seed=b,
                                   start_seq=seq)
        batches.append(tuple(jnp.asarray(planes[k]) for k in order))

    apply_fn = jax.jit(apply_string_batch, donate_argnums=0)
    compact_fn = jax.jit(compact_string_state, donate_argnums=0)

    # warmup / compile on a throwaway state
    state = StringState.create(n_docs, capacity)
    state = apply_fn(state, *batches[0])
    state = compact_fn(state, jnp.zeros((n_docs,), jnp.int32))
    jax.block_until_ready(state)

    state = StringState.create(n_docs, capacity)
    lat = []
    t0 = time.perf_counter()
    done_seq = 0
    for b, batch in enumerate(batches):
        tb = time.perf_counter()
        state = apply_fn(state, *batch)
        done_seq += n_docs * ops_per_batch
        state = compact_fn(state,
                           jnp.full((n_docs,), done_seq, jnp.int32))
        jax.block_until_ready(state)
        lat.append(time.perf_counter() - tb)
    total = time.perf_counter() - t0

    assert not np.asarray(state.overflow).any(), "capacity overflow in bench"
    n_ops = n_docs * ops_per_batch * n_batches
    ops_per_sec = n_ops / total
    batch_p99_ms = float(np.percentile(lat, 99) * 1000)

    print(json.dumps({
        "metric": "sharedstring_ops_per_sec_merged",
        "value": round(ops_per_sec, 1),
        "unit": "ops/s",
        "vs_baseline": round(ops_per_sec / 1_000_000, 4),
        "docs": n_docs,
        "total_ops": n_ops,
        "batch_p99_ms": round(batch_p99_ms, 2),
        "backend": jax.default_backend(),
    }))


if __name__ == "__main__":
    main()
