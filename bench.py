"""Headline benchmark: SharedString ops/sec merged across a 10k-doc batch.

BASELINE.md config #4 (Deli replay across many docs, the north-star metric):
a synthetic multi-doc typing storm is sequenced round-robin and merged by the
batched merge-tree kernel on the real chip, with zamboni compaction between
batches. Prints ONE JSON line; vs_baseline is against the 1M ops/sec target
(no published reference numbers exist — BASELINE.md).

Measurement honesty: on the axon TPU platform ``jax.block_until_ready``
returns without actually syncing (and without surfacing device faults), so
timed sections end with a device→host read of the per-doc overflow flags —
the same read a real sequencer ack path would do. Any dispatch whose result
the host waits on pays a fixed ~100 ms tunnel round-trip (measured and
reported as ``dispatch_rtt_ms``); a production deployment with a locally
attached host pays microseconds. Latency metric: ``apply_window_worst_ms``
is the WORST of 8 individually-synced 64-op-scan dispatches divided by the
64 sequential windows each dispatch applies — an upper bound on per-window
device apply latency, and therefore on its p99 (each sample's full tunnel
RTT is charged to its 64 windows). It is NOT the latency of dispatching one
1-op batch from this host, which is RTT-floored at ~100 ms by the test
tunnel alone.

The workload runs in a child process with up to 3 attempts because the
experimental axon platform can transiently crash the TPU worker; the parent
re-prints the child's final JSON line.

``--phases "serving broadcast,ack latency"`` re-runs a subset of phases
(plus their recorded dependencies) without the full multi-hour sweep;
skipped phases keep zero/skipped defaults in the record, the record's
``phases_run``/``phases_skipped`` say which ran, and the perf sentinel
only judges full sweeps. Every phase boundary also takes a capacity
census (ISSUE 19): per-phase ``census_ms`` + resident/device bytes ride
in ``phase_capacity``.
"""

import json
import subprocess
import sys
import threading
import time


class RttMonitor:
    """Continuous tunnel-RTT sampler on a background thread.

    The phase-boundary snapshots (``rtt_phases``) can only say "the
    tunnel was slow at SOME point in this phase"; a transient stall
    inside a timed section is invisible there yet silently inflates that
    phase's number. This thread dispatches one tiny jitted tick + D2H
    read every ``interval`` seconds for the whole run — its own device
    buffer, never shared with foreground phases — and records every
    sample. Samples past ``stall_factor`` × the starting baseline (with
    an absolute floor) become stall EVENTS with run-relative timestamps,
    so contention windows land in the bench record itself."""

    def __init__(self, baseline_ms: float, interval: float = 0.5,
                 stall_factor: float = 3.0, floor_ms: float = 250.0,
                 keep_events: int = 64):
        import jax
        import jax.numpy as jnp
        import numpy as np
        self._np = np
        self._tick = jax.jit(lambda v: v + 1)
        self._buf = self._tick(jnp.zeros((1,), jnp.int32))
        _ = np.asarray(self._buf)  # compile outside the sampling loop
        self.interval = interval
        self.threshold_ms = max(stall_factor * baseline_ms, floor_ms)
        self.keep_events = keep_events
        self.samples_ms: list = []
        self._sample_at: list = []   # run-relative timestamps, parallel
        self.phase_marks: list = []  # (at_s, phase-name), caller-fed
        self.stall_events: list = []
        self._stop = threading.Event()
        self._t0 = time.perf_counter()
        self._thread = threading.Thread(target=self._loop, daemon=True)

    def start(self) -> "RttMonitor":
        self._thread.start()
        return self

    def mark_phase(self, name: str) -> None:
        """Date a phase boundary so ``phases()`` can attribute every
        sample (and stall) to the phase it happened INSIDE of."""
        self.phase_marks.append(
            (time.perf_counter() - self._t0, name))

    def phases(self) -> dict:
        """Per-phase canary verdicts: worst in-phase RTT + a contended
        flag when any sample INSIDE the phase crossed the stall
        threshold — the attribution the phase-boundary snapshots can't
        give (an outlier like apply_window_worst_ms ≈ 983ms is now
        datable to its phase in the record that counts)."""
        out = {}
        marks = self.phase_marks
        for i, (t_start, name) in enumerate(marks):
            t_end = marks[i + 1][0] if i + 1 < len(marks) \
                else float("inf")
            ms = [m for at, m in zip(self._sample_at, self.samples_ms)
                  if t_start <= at < t_end]
            worst = max(ms) if ms else None
            out[name] = {
                "n": len(ms),
                "worst_ms": round(worst, 1) if worst is not None else None,
                "contended": bool(worst is not None
                                  and worst > self.threshold_ms),
            }
        return out

    def _loop(self) -> None:
        while not self._stop.is_set():
            tb = time.perf_counter()
            self._buf = self._tick(self._buf)
            _ = self._np.asarray(self._buf)
            ms = (time.perf_counter() - tb) * 1000
            self.samples_ms.append(ms)
            self._sample_at.append(tb - self._t0)
            if ms > self.threshold_ms and \
                    len(self.stall_events) < self.keep_events:
                self.stall_events.append(
                    {"at_s": round(time.perf_counter() - self._t0, 1),
                     "ms": round(ms, 1)})
            self._stop.wait(self.interval)

    def stop(self) -> None:
        self._stop.set()
        self._thread.join(timeout=10)

    def summary(self) -> dict:
        s = sorted(self.samples_ms)
        if not s:
            return {"n": 0}
        return {"n": len(s),
                "p50_ms": round(s[len(s) // 2], 1),
                "p95_ms": round(s[int(len(s) * 0.95)], 1),
                "max_ms": round(s[-1], 1),
                "threshold_ms": round(self.threshold_ms, 1),
                "stalls": self.stall_events}


#: every phase marker in run(), in execution order. --phases selects a
#: comma-separated subset; _PHASE_DEPS pulls in what a phase needs from
#: earlier ones (corpora, engines) so any single phase can re-run alone
#: without the full 2-3h sweep. The scorecard phase always runs.
ALL_PHASES = (
    "throughput", "conflict", "serving broadcast", "serving rich",
    "serving durable", "serving tree", "tree kernel", "serving intervals",
    "matrix serving", "columnar ingress", "partition scaling",
    "read_fanout",
    "small-window ack", "ack latency", "apply-window latency",
    "reconnect_storm", "overload_storm", "durability",
)

#: phase → phases it reads state from (engines/corpora defined there)
_PHASE_DEPS = {
    "serving rich": ("serving broadcast",),
    "serving durable": ("serving broadcast",),
    "ack latency": ("serving broadcast", "serving rich"),
    "tree kernel": ("serving tree",),
}


def select_phases(spec):
    """``--phases`` spec → the closed set of phases to run (requested +
    transitive deps). ``None``/empty → all phases."""
    if not spec:
        return set(ALL_PHASES)
    want = [p.strip() for p in spec.split(",") if p.strip()]
    unknown = sorted(set(want) - set(ALL_PHASES))
    if unknown:
        raise SystemExit(
            f"unknown phases {unknown}; known: {', '.join(ALL_PHASES)}")
    selected = set(want)
    frontier = list(selected)
    while frontier:
        for dep in _PHASE_DEPS.get(frontier.pop(), ()):
            if dep not in selected:
                selected.add(dep)
                frontier.append(dep)
    return selected


def run(phases=None):
    import numpy as np
    import jax
    import jax.numpy as jnp

    _run_t0 = time.perf_counter()

    _selected = select_phases(phases)

    def _want(name):
        return name in _selected

    # health plane (ISSUE 4): a caller-ticked time-series over the process
    # registry, sampled at every phase boundary, judged by the standing
    # SLOs; the scorecard + perf-sentinel verdict ride in the bench record.
    # Guarded throughout — the health plane must never kill a bench run.
    from fluidframework_tpu.utils import slo as _slo
    from fluidframework_tpu.utils import timeseries as _timeseries
    from fluidframework_tpu.utils.telemetry import REGISTRY as _registry
    _health = _timeseries.TimeSeriesStore(registry=_registry)
    _slo_engine = _slo.SLOEngine(_health, specs=_slo.default_slos(),
                                 registry=_registry)

    _rtt_mon: list = []   # filled once the continuous canary starts

    # capacity plane (ISSUE 19): one full census per phase boundary —
    # census_ms + resident-doc/device bytes per phase land in the record;
    # entering phase N+1 closes phase N (its peak = max of entry/exit).
    from fluidframework_tpu.utils import capacity as _capacity
    _phase_capacity: dict = {}
    _phase_order: list = []

    def _phase(name):
        # stderr progress marks: the driver keeps stdout to the one JSON
        # line, but when an attempt times out the stderr tail says WHERE
        sys.stderr.write(
            f"[bench +{time.perf_counter() - _run_t0:7.1f}s] {name}\n")
        sys.stderr.flush()
        if _rtt_mon:
            _rtt_mon[0].mark_phase(name)
        try:
            _health.tick()
            _slo_engine.check()
        except Exception as e:   # noqa: BLE001 — observability only
            sys.stderr.write(f"[bench] health tick failed: {e!r}\n")
        try:
            _c = _capacity.LEDGER.census(top_k=4)
            snap = {"census_ms": round(_c["census_ms"], 2),
                    "doc_resident_bytes": _c["host"]["total_bytes"],
                    "device_buffer_bytes": _c["device"]["total_bytes"]}
            if _phase_order:
                prev = _phase_capacity[_phase_order[-1]]
                prev["doc_resident_bytes_peak"] = max(
                    prev["doc_resident_bytes"],
                    snap["doc_resident_bytes"])
            _phase_order.append(name)
            _phase_capacity[name] = snap
        except Exception as e:   # noqa: BLE001 — observability only
            sys.stderr.write(f"[bench] capacity census failed: {e!r}\n")

    from fluidframework_tpu.ops.merge_tree_kernel import (
        StringState, apply_string_batch, compact_string_state,
    )
    from fluidframework_tpu.testing.synthetic import typing_storm
    # shared across several gated phases (broadcast, durable, intervals,
    # small-window ack, ack latency): hoisted so a phase subset that
    # skips "serving broadcast" still resolves them
    from fluidframework_tpu.server.ingest_pipeline import (
        PipelinedIngestExecutor,
    )
    from fluidframework_tpu.server.serving import StringServingEngine

    n_docs = 10240
    capacity = 384
    ops_per_batch = 64
    n_batches = 4        # kernel-phase corpus (chained seq/ref planes)
    n_serve_batches = 5  # serving corpus: 4 measured after the warmup batch
    serve_capacity = 512  # the 5-batch serving corpus peaks past 384 slots;
    n_suites = 4          # the Pallas tile auto-halves to fit VMEM at S=512
    n_ops = n_docs * ops_per_batch * n_batches * n_suites
    order = ("kind", "a0", "a1", "a2", "seq", "client", "ref_seq")

    batches = []
    seq = 1
    for b in range(n_batches):
        planes, seq = typing_storm(n_docs, ops_per_batch, seed=b,
                                   start_seq=seq)
        batches.append(tuple(jnp.asarray(planes[k]) for k in order))

    # no-props mode: the typing corpus carries no annotates, so the store
    # runs the annotate-free kernel variant (the mode a production store is
    # in until its first annotate; see TensorStringStore._has_props).
    # On TPU the Pallas VMEM-resident kernel applies the whole 64-op batch
    # with one HBM round-trip of the state (~2.2x the XLA scan); elsewhere
    # (CPU mesh runs) fall back to the XLA path.
    import functools
    on_tpu = jax.devices()[0].platform == "tpu"
    if on_tpu:
        from fluidframework_tpu.ops.pallas_string_kernel import (
            apply_string_batch_pallas,
        )
        # fused apply+zamboni: ONE dispatch per batch, planes stay in VMEM
        apply_fn = jax.jit(apply_string_batch_pallas, donate_argnums=0)
        step_fn = apply_fn
    else:
        apply_fn = jax.jit(
            functools.partial(apply_string_batch, with_props=False),
            donate_argnums=0)
        step_fn = None
        compact_fn = jax.jit(
            functools.partial(compact_string_state, with_props=False),
            donate_argnums=0)

    # warmup / compile on a throwaway state (BOTH variants: the fused
    # apply+compact used in the throughput loop and the plain apply used in
    # the latency phase — compiling inside a timed section would be counted)
    state = StringState.create(n_docs, capacity)
    state = apply_fn(state, *batches[0])
    if on_tpu:
        state = step_fn(state, *batches[1],
                        min_seq=jnp.zeros((n_docs,), jnp.int32))
    else:
        state = compact_fn(state, jnp.zeros((n_docs,), jnp.int32))
    _ = np.asarray(state.overflow)  # real sync (see module docstring)

    # on-device digest parity: the Mosaic-compiled kernel must produce the
    # same merged state as the XLA scan ON THE REAL CHIP (the CPU tests only
    # cover the Pallas interpreter; VERDICT r1 weak #2). Full-plane check,
    # not just the digest.
    digest_parity = None
    if on_tpu:
        from fluidframework_tpu.ops.merge_tree_kernel import (
            string_state_digest,
        )
        xla_fn = jax.jit(functools.partial(apply_string_batch,
                                           with_props=False))
        s_x = xla_fn(StringState.create(n_docs, capacity), *batches[0])
        s_p = apply_fn(StringState.create(n_docs, capacity), *batches[0])
        digest_parity = bool(np.array_equal(
            np.asarray(string_state_digest(s_x)),
            np.asarray(string_state_digest(s_p))))
        for plane in ("seq", "client", "removed_seq", "removers", "length",
                      "handle_op", "handle_off", "count", "overflow"):
            digest_parity &= bool(np.array_equal(
                np.asarray(getattr(s_x, plane)),
                np.asarray(getattr(s_p, plane))))
        assert digest_parity, "Pallas/XLA divergence on device"
        del s_x, s_p

    # measure the tunnel's fixed dispatch→result round-trip; re-sampled at
    # phase boundaries as the CONTENTION canary (VERDICT r4 weak #1: a
    # contended host/tunnel silently halves phase numbers — make it
    # visible in the record that counts)
    tick = jax.jit(lambda v: v + 1)
    _rtt_x = [jnp.zeros((1,), jnp.int32)]
    _ = np.asarray(tick(_rtt_x[0]))

    def rtt_now() -> float:
        rtts = []
        for _i in range(3):
            tr = time.perf_counter()
            _rtt_x[0] = tick(_rtt_x[0])
            _ = np.asarray(_rtt_x[0])
            rtts.append(time.perf_counter() - tr)
        return float(sorted(rtts)[1] * 1000)

    rtt_ms = rtt_now()
    rtt_phases = {"start": round(rtt_ms, 1)}
    # continuous canary: samples the tunnel for the WHOLE run so stalls
    # inside timed sections (invisible to the phase-boundary snapshots)
    # show up as dated events in the record
    rtt_monitor = RttMonitor(baseline_ms=rtt_ms).start()
    _rtt_mon.append(rtt_monitor)
    import os as _os
    load_start = _os.getloadavg()[0]

    # defaults for every record field a skipped phase would have filled:
    # a --phases subset still emits the full record shape (zeros/None/
    # skipped markers), so downstream readers never KeyError
    ops_per_sec = 0.0
    headline_trials = []
    headline_band = {}
    conflict_ops_per_sec = 0.0
    conflict_parity = None
    engine = rich_engine = tree_eng = None
    serving_trials, serving_pipe_stats = [], None
    serving_ops_per_sec = serving_ops_per_sec_median = 0.0
    serving_read_ms, read_rtts = 0.0, None
    rich_trials, rich_pipe_stats = [], None
    rich_ops_per_sec = rich_ops_per_sec_median = 0.0
    durable_ops_per_sec = durable_ops_per_sec_median = None
    durable_trials = []
    tree_trials, tree_pipe_stats = [], None
    tree_ops_per_sec = tree_ops_per_sec_median = 0.0
    tree_flat_ops_per_sec, leaf_trials = 0.0, []
    tree_kernel_ops_per_sec, tree_kernel_trials = 0.0, []
    interval_ops_per_sec, iv_seg_waves, interval_wire = 0.0, [], None
    n_iv_docs = iv_ow = iv_waves = 0
    matrix_serving_ops_per_sec, matrix_trials = 0.0, [0.0]
    columnar_ingress_ops_per_sec = 0.0
    ingress_trials, ingress_stats, ingress_windows = [0.0], None, 0
    ingress_drain = {"decode_p50_ms": None, "bytes_per_pass_p50": None,
                     "passes": 0, "tier": None}
    ops_plane = None
    partition_scaling = {"skipped": True}
    partition_columnar_ops_per_sec = None
    read_fanout = {"skipped": True}
    read_delivery_ops_per_sec = None
    small_window_ack = {}
    ack_p50_ms = ack_p99_ms = 0.0
    ack_retries = 0
    worst_ms = apply_window_p50_ms = 0.0
    apply_window_retries, apply_window_stalled = 0, False
    reconnect_storm = {"skipped": True}
    overload_storm = {"skipped": True}
    durability = {"skipped": True}

    if _want("throughput"):
        _phase("throughput")
        # --- throughput phase: 64-op batches, compact per batch -----------------
        # Dispatches are pipelined (as a production sequencer host would); each
        # suite's end sync covers its batches' device work. Every suite is an
        # independent trial: the per-suite rates + variance band make cross-
        # round drift (7.98M -> 7.28M between r4 and r5, unremarked) visible
        # inside a single record instead of only between records.
        headline_trials = []
        t0 = time.perf_counter()
        for _suite in range(n_suites):
            ts = time.perf_counter()
            state = StringState.create(n_docs, capacity)
            done_seq = 0
            for batch in batches:
                done_seq += n_docs * ops_per_batch
                ms = jnp.full((n_docs,), done_seq, jnp.int32)
                if on_tpu:
                    state = step_fn(state, *batch, min_seq=ms)
                else:
                    state = apply_fn(state, *batch)
                    state = compact_fn(state, ms)
            overflow = np.asarray(state.overflow)  # honest end sync (D2H)
            assert not overflow.any(), "capacity overflow in bench"
            headline_trials.append(
                n_docs * ops_per_batch * n_batches /
                (time.perf_counter() - ts))
        total = time.perf_counter() - t0
        n_ops = n_docs * ops_per_batch * n_batches * n_suites
        ops_per_sec = n_ops / total
        headline_sorted = sorted(headline_trials)
        headline_band = {
            "min": round(headline_sorted[0], 1),
            "median": round(headline_sorted[len(headline_sorted) // 2], 1),
            "max": round(headline_sorted[-1], 1),
            "spread_pct": round(
                100 * (headline_sorted[-1] - headline_sorted[0]) /
                headline_sorted[-1], 1),
        }

    if _want("conflict"):
        _phase("conflict")
        # --- conflict phase: multi-client, annotate-bearing corpus --------------
        # VERDICT r1 weak #3: the typing storm is single-writer and annotate-
        # free. This phase measures the props-mode Pallas kernel on divergent
        # perspectives + overlapping removes + annotates, with on-device digest
        # parity against the XLA props path.
        from fluidframework_tpu.testing.synthetic import conflict_storm
        from fluidframework_tpu.ops.merge_tree_kernel import (
            compact_string_state as compact_raw, string_state_digest,
        )

        c_batches = []
        seq = 1
        for b in range(n_batches):
            planes, seq = conflict_storm(n_docs, ops_per_batch, seed=100 + b,
                                         start_seq=seq)
            c_batches.append(tuple(jnp.asarray(planes[k]) for k in order))
        if on_tpu:
            from fluidframework_tpu.ops.pallas_string_kernel import (
                apply_string_batch_pallas,
            )
            conflict_fn = jax.jit(functools.partial(
                apply_string_batch_pallas, tile=64, with_props=True),
                donate_argnums=0)
        else:
            conflict_fn = jax.jit(functools.partial(
                apply_string_batch, with_props=True), donate_argnums=0)
        conflict_compact = jax.jit(functools.partial(
            compact_raw, with_props=True), donate_argnums=0)

        # warmup + digest parity (props kernel vs XLA props scan, on device)
        xla_props = jax.jit(functools.partial(apply_string_batch,
                                              with_props=True))
        s_c = conflict_fn(StringState.create(n_docs, capacity), *c_batches[0])
        s_x = xla_props(StringState.create(n_docs, capacity), *c_batches[0])
        conflict_parity = bool(np.array_equal(
            np.asarray(string_state_digest(s_c)),
            np.asarray(string_state_digest(s_x)))) and bool(np.array_equal(
                np.asarray(s_c.prop_val), np.asarray(s_x.prop_val)))
        assert conflict_parity, "props kernel divergence on device"
        del s_c, s_x

        # warmup the fused apply+zamboni variant (TPU path)
        if on_tpu:
            s_w = conflict_fn(StringState.create(n_docs, capacity),
                              *c_batches[0],
                              min_seq=jnp.zeros((n_docs,), jnp.int32))
            _ = np.asarray(s_w.overflow)
            del s_w

        t0 = time.perf_counter()
        for _suite in range(n_suites):
            state = StringState.create(n_docs, capacity)
            done_seq = 0
            for batch in c_batches:
                done_seq += n_docs * ops_per_batch
                ms = jnp.full((n_docs,), done_seq, jnp.int32)
                if on_tpu:  # fused apply+zamboni: ONE dispatch (the sort-based
                    state = conflict_fn(state, *batch, min_seq=ms)  # props
                else:       # compact costs more than the apply itself)
                    state = conflict_fn(state, *batch)
                    state = conflict_compact(state, ms)
            overflow = np.asarray(state.overflow)
            assert not overflow.any(), "conflict bench overflow"
        conflict_s = time.perf_counter() - t0
        conflict_ops_per_sec = n_ops / conflict_s

    if _want("serving broadcast"):
        _phase("serving broadcast")
        # --- serving phase: the FULL engine end-to-end ---------------------------
        # StringServingEngine ingest→sequence(C++ Deli)→durable log→device merge
        # →read, via the columnar pipeline (VERDICT r1 weak #1: the product
        # stack, not a kernel microbench). Same corpus shape; per-doc dense seqs.
        from fluidframework_tpu.server.serving import StringServingEngine

        docs = [f"doc-{i}" for i in range(n_docs)]

        def fresh_string_engine():
            eng = StringServingEngine(
                n_docs=n_docs, capacity=serve_capacity, batch_window=10 ** 9,
                compact_every=1, sequencer="native")
            for d in docs:
                eng.connect(d, 1)
            return eng

        engine = fresh_string_engine()
        assert type(engine.deli).__name__ == "NativeDeliAdapter", \
            "native sequencer must be available for the serving bench"
        serve_batches = []
        for b in range(n_serve_batches):
            planes, _ = typing_storm(n_docs, ops_per_batch, seed=b)
            cseq = np.broadcast_to(
                np.arange(b * ops_per_batch + 1, (b + 1) * ops_per_batch + 1,
                          dtype=np.int32), (n_docs, ops_per_batch))
            # client saw everything sequenced so far: op g sees seq g+1 (join=1)
            ref = cseq  # == global per-doc op count before this op, + 1
            serve_batches.append((planes["kind"], planes["a0"], planes["a1"],
                                  cseq, ref))
        client_plane = np.ones((n_docs, ops_per_batch), np.int32)

        # warmup batch compiles the serving dispatch shape, then measure.
        # THREE independent trials (fresh engine each), best reported: single
        # trials swing ±30% with the test tunnel's latency noise. Waves go
        # through the PipelinedIngestExecutor (the production ingest path):
        # wave N+1 prepacks/sequences while wave N's dispatch is on device
        # and N−1's durable append completes in the background; drain() ends
        # the timed section at the last wave's ack-safe point.
        from fluidframework_tpu.server.ingest_pipeline import (
            PipelinedIngestExecutor,
        )

        def _serving_trial(eng):
            trows = np.array([eng.doc_row(d) for d in docs], np.int32)
            kind, a0, a1, cseq, ref = serve_batches[0]
            eng.ingest_planes(trows, client_plane, cseq, ref, kind, a0, a1,
                              "abcd")
            _ = np.asarray(eng.store.state.overflow)
            ex = PipelinedIngestExecutor(eng, depth=3)
            t0 = time.perf_counter()
            tickets = [ex.submit(trows, client_plane, cseq, ref, kind, a0,
                                 a1, text="abcd")
                       for kind, a0, a1, cseq, ref in serve_batches[1:]]
            ex.drain()
            overflow = np.asarray(eng.store.state.overflow)  # end sync
            elapsed = time.perf_counter() - t0
            n = 0
            for tk in tickets:
                res = tk.result()
                assert res["nacked"] == 0
                n += n_docs * ops_per_batch - res["nacked"]
            pipe_stats = ex.stats()
            ex.close()
            assert not overflow.any(), "serving overflow"
            return n / elapsed, pipe_stats

        serving_trials, serving_pipe_stats = [], None
        for _t in range(3):
            eng_t = engine if _t == 0 else fresh_string_engine()
            rate, pstats = _serving_trial(eng_t)
            serving_trials.append(rate)
            if rate >= max(serving_trials):
                serving_pipe_stats = pstats
            if eng_t is not engine:
                del eng_t   # transient: freed after its trial
        serving_trials.sort()
        serving_ops_per_sec = serving_trials[-1]
        serving_ops_per_sec_median = serving_trials[len(serving_trials) // 2]
        rtt_phases["after_serving"] = round(rtt_now(), 1)

        # read path timed separately. A read = flush (no device work when the
        # queue is empty) + ONE fused gather+transfer — a 1-round-trip budget,
        # asserted from the store's device-read counter. The warmup read pays
        # the gather program's compile + the pipeline drain OUTSIDE the timed
        # section (a production server's steady state).
        _ = engine.read_text(docs[1])
        before_reads = engine.store.device_reads
        tr = time.perf_counter()
        _ = [engine.read_text(docs[i])
             for i in (0, n_docs // 2, 7, n_docs - 1)]
        serving_read_ms = (time.perf_counter() - tr) * 1000 / 4
        read_rtts = (engine.store.device_reads - before_reads) / 4
        assert read_rtts == 1.0, read_rtts

    if _want("serving rich"):
        _phase("serving rich")
        # --- serving: distinct payloads + annotates (rich corpus) ---------------
        # The columnar path with per-op payload handles and single-key annotate
        # slots (VERDICT r2 weak #4: real text is not a broadcast payload).
        from fluidframework_tpu.testing.synthetic import rich_storm
        from fluidframework_tpu.core.protocol import (
            MessageType, SequencedDocumentMessage,
        )
        from fluidframework_tpu.ops.string_store import TensorStringStore
        from fluidframework_tpu.ops.schema import OpKind
        rich_engine = fresh_string_engine()
        rich_batches = []
        for b in range(n_serve_batches):
            planes, texts, rprops, _ = rich_storm(n_docs, ops_per_batch, seed=b)
            cseq = np.broadcast_to(
                np.arange(b * ops_per_batch + 1, (b + 1) * ops_per_batch + 1,
                          dtype=np.int32), (n_docs, ops_per_batch))
            rich_batches.append((planes, texts, rprops, cseq))
        def _rich_trial(eng):
            trows = np.array([eng.doc_row(d) for d in docs], np.int32)
            planes, texts, rprops, cseq = rich_batches[0]
            eng.ingest_planes(trows, client_plane, cseq, cseq,
                              planes["kind"], planes["a0"], planes["a1"],
                              texts=texts, tidx=planes["tidx"], props=rprops)
            _ = np.asarray(eng.store.state.overflow)
            # pipelined: the rich interner/table build (the 100ms p50 `pack`
            # VERDICT r5 pinned) prepacks on the pack worker CONCURRENT with
            # the previous wave's device dispatch — off the critical path
            ex = PipelinedIngestExecutor(eng, depth=3)
            t0 = time.perf_counter()
            tickets = [ex.submit(trows, client_plane, cseq, cseq,
                                 planes["kind"], planes["a0"], planes["a1"],
                                 texts=texts, tidx=planes["tidx"],
                                 props=rprops)
                       for planes, texts, rprops, cseq in rich_batches[1:]]
            ex.drain()
            overflow = np.asarray(eng.store.state.overflow)
            elapsed = time.perf_counter() - t0
            for tk in tickets:
                assert tk.result()["nacked"] == 0
            pipe_stats = ex.stats()
            ex.close()
            assert not overflow.any(), "rich serving overflow"
            return (n_docs * ops_per_batch * (n_serve_batches - 1) / elapsed,
                    pipe_stats)

        rich_trials, rich_pipe_stats = [], None
        for _t in range(3):  # rich is hit hardest by noisy tunnel windows
            eng_t = rich_engine if _t == 0 else fresh_string_engine()
            rate, pstats = _rich_trial(eng_t)
            rich_trials.append(rate)
            if rate >= max(rich_trials):
                rich_pipe_stats = pstats
            if eng_t is not rich_engine:
                del eng_t   # transient: freed after its trial
        rich_trials.sort()
        rich_ops_per_sec = rich_trials[-1]
        rich_ops_per_sec_median = rich_trials[len(rich_trials) // 2]
        rtt_phases["after_rich"] = round(rtt_now(), 1)
        # parity: per-op message path on a fresh single-doc store
        for check_doc in (1, n_docs - 1):
            ref_store = TensorStringStore(n_docs=1, capacity=serve_capacity)
            msgs = []
            seq = 1
            for planes, texts, rprops, cseq in rich_batches:
                for o in range(ops_per_batch):
                    seq += 1
                    k = planes["kind"][check_doc, o]
                    if k == OpKind.STR_INSERT:
                        contents = {"mt": "insert", "kind": 0,
                                    "pos": int(planes["a0"][check_doc, o]),
                                    "text": texts[int(planes["tidx"]
                                                     [check_doc, o])]}
                    elif k == OpKind.STR_ANNOTATE:
                        contents = {"mt": "annotate",
                                    "start": int(planes["a0"][check_doc, o]),
                                    "end": int(planes["a1"][check_doc, o]),
                                    "props": rprops[int(planes["tidx"]
                                                        [check_doc, o])]}
                    else:
                        contents = {"mt": "remove",
                                    "start": int(planes["a0"][check_doc, o]),
                                    "end": int(planes["a1"][check_doc, o])}
                    msgs.append((0, SequencedDocumentMessage(
                        doc_id="x", client_id=1,
                        client_seq=int(cseq[check_doc, o]),
                        ref_seq=int(cseq[check_doc, o]), seq=seq,
                        min_seq=0, type=MessageType.OP, contents=contents)))
            ref_store.apply_messages(msgs)  # one batched device apply
            assert rich_engine.read_text(docs[check_doc]) == \
                ref_store.read_text(0), f"rich divergence doc {check_doc}"

    if _want("serving durable"):
        _phase("serving durable")
        # --- serving: fsync'd durable log (group commit per batch) --------------
        # Same pipeline with the C++ durable log ON and an fsync barrier after
        # every batch — "durable" is in the measured path (VERDICT r2 weak #3).
        import tempfile
        from fluidframework_tpu.server import native_oplog
        durable_ops_per_sec = None
        durable_ops_per_sec_median = None
        durable_trials = []
        if native_oplog.available():
            def _durable_trial():
                with tempfile.TemporaryDirectory() as dlog_dir:
                    dlog = native_oplog.NativePartitionedLog(dlog_dir, 8)
                    dur_engine = StringServingEngine(
                        n_docs=n_docs, capacity=serve_capacity,
                        batch_window=10 ** 9, compact_every=1,
                        sequencer="native", log=dlog)
                    for d in docs:
                        dur_engine.connect(d, 1)
                    drows = np.array([dur_engine.doc_row(d) for d in docs],
                                     np.int32)
                    kind, a0, a1, cseq, ref = serve_batches[0]
                    dur_engine.ingest_planes(drows, client_plane, cseq, ref,
                                             kind, a0, a1, "abcd")
                    dlog.sync()
                    _ = np.asarray(dur_engine.store.state.overflow)
                    t0 = time.perf_counter()
                    for kind, a0, a1, cseq, ref in serve_batches[1:]:
                        res = dur_engine.ingest_planes(drows, client_plane,
                                                       cseq, ref, kind, a0,
                                                       a1, "abcd")
                        dlog.sync()  # group commit: ack is durable
                        assert res["nacked"] == 0
                    overflow = np.asarray(dur_engine.store.state.overflow)
                    durable_s = time.perf_counter() - t0
                    assert not overflow.any()
                    dlog.close()
                    return (n_docs * ops_per_batch * (n_serve_batches - 1) /
                            durable_s)

            # >=3 trials, like the broadcast/rich phases above: a single-trial
            # durable number landing ABOVE broadcast (2.72M vs 2.56M in r5)
            # is tunnel-noise luck, not physics — the trials array lets the
            # record say which (compare medians, not bests)
            for _t in range(3):
                durable_trials.append(_durable_trial())
            durable_trials.sort()
            durable_ops_per_sec = durable_trials[-1]
            durable_ops_per_sec_median = durable_trials[len(durable_trials) // 2]

    if _want("serving tree"):
        _phase("serving tree")
        # --- serving: SharedTree columnar records --------------------------------
        # The largest DDS's serving number (VERDICT r4 missing #1): GENERAL
        # tree edits (constrained transactions: insert-after + setValue) in
        # the columnar record wire format (server/tree_wire.py) with numeric
        # ids (the id-compressor hot path) — one C++ sequencing call, one
        # width-coded device upload, one batched apply, one raw-plane durable
        # record per wave. Clients pre-encode (their serialization cost, as
        # with ingest_planes' packing); oracle parity asserted from the log.
        from fluidframework_tpu.server.serving import TreeServingEngine
        from fluidframework_tpu.server.tree_wire import (encode_leaf_records,
                                                         encode_tree_batch)
        n_tree_docs = 8192
        tree_opd = 8            # transactions per doc per wave
        n_tree_waves = 6        # measured waves per trial (after warmup;
        #                         6 waves through a depth-3 pipeline reach
        #                         steady-state overlap — 3 barely fill it)
        tdocs = [f"t-{i}" for i in range(n_tree_docs)]
        tree_n_ops = n_tree_docs * tree_opd

        def fresh_tree_engine():
            eng = TreeServingEngine(n_docs=n_tree_docs, capacity=128,
                                    batch_window=10 ** 9, sequencer="native")
            for d in tdocs:
                eng.connect(d, 1)
            return eng

        def tree_batches(eng):
            """Client-side: encode warmup + measured waves of transactions
            (chained inserts + value updates on the previous node)."""
            base = eng.allocate_node_ids(tree_n_ops * (n_tree_waves + 1))

            def nid(di, k):
                return f"#{base + di * tree_opd * (n_tree_waves + 1) + k}"

            out = []
            for wave in range(n_tree_waves + 1):
                ops = []
                for di in range(n_tree_docs):
                    for j in range(tree_opd):
                        k = wave * tree_opd + j
                        prev = nid(di, k - 1)
                        ops.append(
                            {"op": "transaction",
                             "constraints":
                                 [{"nodeExists": prev}] if k else [],
                             "edits": [
                                 {"op": "insert", "parent": "root",
                                  "field": "kids",
                                  "after": prev if k else None,
                                  "nodes": [{"id": nid(di, k),
                                             "type": "item", "value": k}]},
                                 {"op": "setValue",
                                  "id": prev if k else "root",
                                  "value": k * 10}]})
                out.append(encode_tree_batch(ops))
            return out

        def tree_cseqs(wave):
            return np.repeat(
                np.arange(1, tree_opd + 1)[None, :] + wave * tree_opd,
                n_tree_docs, axis=0).reshape(-1)

        tree_zero = np.zeros(tree_n_ops, np.int32)
        tree_ones = np.ones(tree_n_ops, np.int32)

        def _tree_trial():
            """Pipelined trial (the string serving phases' executor idiom):
            wave N+1's wire prepack + sequencing overlap wave N's device
            dispatch while N−1's durable append completes in the background;
            drain() ends the timed section at the last wave's ack-safe
            point."""
            eng = fresh_tree_engine()
            batches = tree_batches(eng)
            trows = np.repeat(
                np.array([eng.doc_row(d) for d in tdocs], np.int32),
                tree_opd)
            eng.ingest_records(None, tree_ones, tree_cseqs(0), tree_zero,
                               batches[0], rows=trows)   # warmup + compile
            _ = eng.sync()
            ex = PipelinedIngestExecutor(eng, depth=3)
            t0 = time.perf_counter()
            tickets = [ex.submit(None, tree_ones, tree_cseqs(w + 1),
                                 tree_zero, b, rows=trows)
                       for w, b in enumerate(batches[1:])]
            ex.drain()
            ovf = eng.sync()
            rate = n_tree_waves * tree_n_ops / (time.perf_counter() - t0)
            assert not ovf.any(), "tree capacity overflow in bench"
            for tk in tickets:
                assert tk.result()["nacked"] == 0
            pipe_stats = ex.stats()
            ex.close()
            return eng, rate, pipe_stats

        tree_trials = []
        tree_eng = None
        tree_pipe_stats = None
        for _t in range(3):
            eng_t, rate, pstats = _tree_trial()
            tree_trials.append(rate)
            if rate >= max(tree_trials):
                tree_eng = eng_t
                tree_pipe_stats = pstats
            else:
                del eng_t
        tree_trials.sort()
        tree_ops_per_sec = tree_trials[-1]
        tree_ops_per_sec_median = tree_trials[len(tree_trials) // 2]

        # the tree VOLUME path: flat single-node inserts, ONE solo record per
        # op, pre-encoded by clients (``encode_leaf_records`` — their
        # serialization cost, exactly like the general phase's
        # ``encode_tree_batch``) and ingested through the SAME
        # ``ingest_records`` pipeline the general path uses. One record per
        # op instead of the transaction path's three, so flat ≥ general by
        # construction. 8 leaves/doc/wave matches the general phase's op
        # volume (65536 ops/wave).
        n_leaf_docs = n_tree_docs
        leaf_opd = tree_opd
        ldocs = [f"tf-{i}" for i in range(n_leaf_docs)]
        n_leaf_waves = n_tree_waves
        leaf_n_ops = n_leaf_docs * leaf_opd
        leaf_ones = np.ones(leaf_n_ops, np.int32)
        leaf_zero = np.zeros(leaf_n_ops, np.int32)

        def leaf_batches(eng):
            lbase = eng.allocate_node_ids(leaf_n_ops * (n_leaf_waves + 1))

            def lid(i, k):
                return f"#{lbase + i * leaf_opd * (n_leaf_waves + 1) + k}"

            out = []
            for wave in range(n_leaf_waves + 1):
                nids, values, afters = [], [], []
                for i in range(n_leaf_docs):
                    for j in range(leaf_opd):
                        k = wave * leaf_opd + j
                        nids.append(lid(i, k))
                        values.append(k)
                        afters.append(lid(i, k - 1) if k else None)
                out.append(encode_leaf_records(
                    ["root"] * leaf_n_ops, ["kids"] * leaf_n_ops, nids,
                    values, ["leaf"] * leaf_n_ops, afters))
            return out

        def leaf_cseqs(wave):
            return np.repeat(
                np.arange(1, leaf_opd + 1)[None, :] + wave * leaf_opd,
                n_leaf_docs, axis=0).reshape(-1)

        def _leaves_trial():
            eng = TreeServingEngine(n_docs=n_leaf_docs, capacity=128,
                                    batch_window=10 ** 9, sequencer="native")
            for d in ldocs:
                eng.connect(d, 1)
            lbs = leaf_batches(eng)
            lrows = np.repeat(
                np.array([eng.doc_row(d) for d in ldocs], np.int32),
                leaf_opd)
            eng.ingest_records(None, leaf_ones, leaf_cseqs(0), leaf_zero,
                               lbs[0], rows=lrows)   # warmup + compile
            _ = eng.sync()
            ex = PipelinedIngestExecutor(eng, depth=3)
            t0 = time.perf_counter()
            tickets = [ex.submit(None, leaf_ones, leaf_cseqs(w + 1),
                                 leaf_zero, b, rows=lrows)
                       for w, b in enumerate(lbs[1:])]
            ex.drain()
            _ = eng.sync()
            rate = n_leaf_waves * leaf_n_ops / (time.perf_counter() - t0)
            for tk in tickets:
                assert tk.result()["nacked"] == 0
            ex.close()
            return eng, rate

        leaf_trials = []
        leaves_eng = None
        for _t in range(3):
            eng_t, rate = _leaves_trial()
            leaf_trials.append(rate)
            if rate >= max(leaf_trials):
                leaves_eng = eng_t
            else:
                del eng_t
        leaf_trials.sort()
        tree_flat_ops_per_sec = leaf_trials[-1]
        # parity: the flat path's log must rebuild the oracle state too
        from fluidframework_tpu.models.shared_tree import SharedTree
        probe_f = ldocs[7]
        oracle_f = SharedTree(probe_f, 999)
        for m in leaves_eng._doc_log_messages(probe_f):
            oracle_f.process_core(m, local=False)
        assert leaves_eng.to_dict(probe_f) == oracle_f.to_dict(), \
            "tree flat-ingest divergence vs oracle"
        del leaves_eng

        # oracle parity: replay the sampled doc's full log history through the
        # pure-Python SharedTree oracle
        probe = tdocs[n_tree_docs // 2]
        oracle = SharedTree(probe, 999)
        for m in tree_eng._doc_log_messages(probe):
            oracle.process_core(m, local=False)
        assert tree_eng.to_dict(probe) == oracle.to_dict(), \
            "tree serving divergence vs oracle"

    if _want("tree kernel"):
        _phase("tree kernel")
        # --- tree kernel-only: device-resident wire applies ----------------------
        # Splits kernel cost from host/upload cost (VERDICT r4 missing #1:
        # "no tree-kernel-only number is recorded anywhere"): the same wire
        # program, arguments already resident, back-to-back donated applies.
        import jax.numpy as _jnp
        from fluidframework_tpu.ops.tree_kernel import (
            TreeState as _TreeState, apply_tree_wire_jit as _wire_jit)
        from fluidframework_tpu.ops.tree_store import pack_wire_records
        kr = np.repeat(np.arange(n_tree_docs, dtype=np.int64), tree_opd)
        kbatch = tree_batches(fresh_tree_engine())[1]
        krec = kbatch["recs"]
        krec_op = kbatch["rec_op"]
        # the SAME packing the serving dispatch uses (one shared layout,
        # id/value lanes width-coded u16 → u32 when a table outgrows u16 —
        # the old unconditional u16 silently truncated this wave's ~74k-id
        # table, wrapping indices instead of exercising the real layout)
        kcols, kids, kvals, krow, kposb, ko = pack_wire_records(
            krec, krec_op, kr[krec_op],
            id_t=np.uint16 if len(kbatch["ids"]) < 0xFFFF else np.uint32,
            val_t=np.uint16 if len(kbatch["values"]) < 0xFFFF else np.uint32)
        kbase = np.full(n_tree_docs, 2, np.int32)
        kmaps = [np.pad(np.asarray(
            [e if isinstance(e, int) else 1 for e in kbatch["ids"]],
            np.int32), (1, 0)),
            np.arange(len(kbatch["fields"]) + 1, dtype=np.int32),
            np.arange(len(kbatch["types"]) + 1, dtype=np.int32),
            np.arange(len(kbatch["values"]) + 1, dtype=np.int32)]
        kargs = [_jnp.asarray(x) for x in
                 (kcols, kids, kvals, krow, kposb, kbase, *kmaps)]
        kst = _TreeState.create(n_tree_docs, 128)
        kst = _wire_jit(kst, *kargs, o=ko)
        _ = np.asarray(kst.overflow)
        # 3 back-to-back measurements of the same resident dispatch loop: the
        # kernel number's run-to-run variance band lands in the record (drift
        # between rounds was previously indistinguishable from regression)
        k_reps = 6
        tree_kernel_trials = []
        for _t in range(3):
            t0 = time.perf_counter()
            for _i in range(k_reps):
                kst = _wire_jit(kst, *kargs, o=ko)
            _ = np.asarray(kst.overflow)
            tree_kernel_trials.append(
                k_reps * tree_n_ops / (time.perf_counter() - t0))
        tree_kernel_trials.sort()
        tree_kernel_ops_per_sec = tree_kernel_trials[-1]
        del kst, kargs

    if _want("serving intervals"):
        _phase("serving intervals")
        # --- serving: interval-holding docs (config #5's serving form) -----------
        # An interval-heavy corpus (annotates + inserts + removes sliding the
        # anchors) through StringServingEngine at 1k docs ≈ 1k simulated
        # editors (VERDICT r4 missing #4). Interval-holding docs now ride the
        # COLUMNAR fast path: the ingress hands apply_planes the per-op MSN
        # plane, the host scan splits each window at tombstone-crossing
        # boundaries, and anchors slide in ONE fused device gather per
        # boundary (docs/INTERVALS.md). Endpoints are asserted against the
        # oracle IntervalCollection on sampled docs — the same gate the old
        # per-op escape hatch had, minus its ~1000x Python round-trip tax.
        import random as _random
        from fluidframework_tpu.models.merge_tree import LOCAL_VIEW
        from fluidframework_tpu.models.interval_collection import (
            IntervalCollection,
        )
        from fluidframework_tpu.models.shared_string import SharedString
        # 4096-doc batch: each wave costs a near-constant ~2 dispatches + 1
        # slide gather (tunnel-RTT floored), so throughput scales with the
        # doc axis — 1024 docs leaves the phase RTT-bound under the 100k bar
        n_iv_docs = 4096
        iv_ow = 16              # ops per doc per wave (window width)
        iv_warm = 2             # untimed: compiles the split/slide shapes
        iv_waves = 8            # timed waves
        iv_rng = _random.Random(5)
        # compact_every=inf at the ENGINE: zamboni already rides inside the
        # apply itself (interval docs disable the fused min_seq path, so
        # apply_planes compacts after the reanchor scan every window); an
        # engine-cadence compact on top would just dispatch it twice
        iv_eng = StringServingEngine(n_docs=n_iv_docs, capacity=256,
                                     batch_window=10 ** 9,
                                     compact_every=10 ** 9,
                                     sequencer="native")
        iv_docs = [f"iv-{i}" for i in range(n_iv_docs)]
        base_text = "the quick brown fox jumps over the dazed dog"
        for d in iv_docs:
            iv_eng.connect(d, 1)
            _, nack = iv_eng.submit(d, 1, 1, 0, {"mt": "insert", "kind": 0,
                                                 "pos": 0, "text": base_text,
                                                 "clientSeq": 1})
            assert nack is None
        iv_eng.flush()
        req = {}
        for d in iv_docs:
            row = iv_eng.doc_row(d)
            spans = []
            for _k in range(3):
                s = iv_rng.randrange(len(base_text) - 8)
                e = s + 2 + iv_rng.randrange(5)
                spans.append((s, e, None))
            req[row] = spans
        # ONE fused gather anchors the whole corpus (add_interval pays >=2
        # tunnel round trips per call)
        iv_ids = iv_eng.store.add_intervals_bulk(req)
        iv_spans = []
        for d in iv_docs:
            row = iv_eng.doc_row(d)
            iv_spans.append([(s, e, sid) for (s, e, _), sid in
                             zip(req[row], iv_ids[row])])
        iv_lengths = [len(base_text)] * n_iv_docs
        # plane-shaped waves: ~50% annotate / 30% insert / 20% remove. Every
        # op is client 1's, so positions are generated against the doc's full
        # evolving text (the client's local perspective sees its own ops).
        iv_texts = ["XY"]
        iv_props = [{"bold": True}, {"bold": False}]
        iv_batches = []
        for w in range(iv_warm + iv_waves):
            kind = np.zeros((n_iv_docs, iv_ow), np.int32)
            a0 = np.zeros((n_iv_docs, iv_ow), np.int32)
            a1 = np.zeros((n_iv_docs, iv_ow), np.int32)
            tix = np.zeros((n_iv_docs, iv_ow), np.int32)
            for di in range(n_iv_docs):
                ln = iv_lengths[di]
                for c in range(iv_ow):
                    roll = iv_rng.random()
                    if roll < 0.5 and ln >= 6:
                        s = iv_rng.randrange(ln - 4)
                        kind[di, c] = OpKind.STR_ANNOTATE
                        a0[di, c], a1[di, c] = s, s + 2
                        tix[di, c] = iv_rng.randrange(2)
                    elif roll < 0.8 or ln < 16:
                        kind[di, c] = OpKind.STR_INSERT
                        a0[di, c], a1[di, c] = iv_rng.randrange(ln + 1), 2
                        ln += 2
                    else:
                        s = iv_rng.randrange(ln - 3)
                        kind[di, c] = OpKind.STR_REMOVE
                        a0[di, c], a1[di, c] = s, s + 2
                        ln -= 2
                iv_lengths[di] = ln
            # clientSeq 1 was the base insert; ref = everything the client has
            # seen sequenced = join(1) + base(1) + all prior waves. The
            # constant-per-wave ref advances the MSN floor past the PREVIOUS
            # wave's tombstones at column 0, so every post-warmup wave
            # exercises a real crossing (segment split + device anchor slide).
            cseq = np.broadcast_to(
                np.arange(2 + w * iv_ow, 2 + (w + 1) * iv_ow, dtype=np.int32),
                (n_iv_docs, iv_ow))
            ref = np.full((n_iv_docs, iv_ow), 2 + w * iv_ow, np.int32)
            iv_batches.append((kind, a0, a1, tix, cseq, ref))
        iv_rows = np.array([iv_eng.doc_row(d) for d in iv_docs], np.int32)
        iv_client = np.ones((n_iv_docs, iv_ow), np.int32)
        iv_seg_waves = []
        t0 = time.perf_counter()
        for w, (kind, a0, a1, tix, cseq, ref) in enumerate(iv_batches):
            if w == iv_warm:     # split/slide/compact shapes compiled; go
                _ = np.asarray(iv_eng.store.state.overflow)
                t0 = time.perf_counter()
            res = iv_eng.ingest_planes(iv_rows, iv_client, cseq, ref,
                                       kind, a0, a1, texts=iv_texts,
                                       tidx=tix, props=iv_props)
            assert res["nacked"] == 0
            iv_seg_waves.append(iv_eng.store.last_apply_stats["segments"])
        _ = np.asarray(iv_eng.store.state.overflow)
        interval_ops_per_sec = n_iv_docs * iv_ow * iv_waves / \
            (time.perf_counter() - t0)
        # regression pin: the waves went through the columnar apply (the old
        # per-op fallback kept no segment accounting) AND the MSN floor really
        # crossed tombstones mid-window (>= 2 segments per post-warmup wave)
        assert all(s >= 2 for s in iv_seg_waves[1:]), iv_seg_waves
        interval_wire = iv_eng.store.last_rich_wire
        # oracle parity: replay sampled docs' sequenced ops through the
        # oracle, anchor the same spans, compare endpoint positions
        for di in (7, n_iv_docs // 2):
            d = iv_docs[di]
            oracle = SharedString(d, 999)
            msgs = [m for m in iv_eng._doc_log_messages(d)]
            base_msgs = [m for m in msgs if m.client_seq == 1]
            tail_msgs = [m for m in msgs if m.client_seq > 1]
            # apply_msg (not bare process_core): the oracle must zamboni at
            # min-seq crossings exactly like the reference client, or slid
            # anchors diverge from the device's crossing-driven slides
            for m in base_msgs:
                oracle.apply_msg(m)
            coll = IntervalCollection("c", oracle.tree)
            row = iv_eng.doc_row(d)
            for k, (s, e, sid) in enumerate(iv_spans[di]):
                coll.apply_add(f"o{k}", s, e, {}, LOCAL_VIEW, 999)
            for m in tail_msgs:
                oracle.apply_msg(m)
            assert iv_eng.read_text(d) == oracle.get_text(), d
            for k, (s, e, sid) in enumerate(iv_spans[di]):
                want = coll.endpoints(coll.get(f"o{k}"))
                got = iv_eng.store.interval_endpoints(row, sid)
                assert got == want, (d, k, got, want)
        del iv_eng
        rtt_phases["after_intervals"] = round(rtt_now(), 1)

    if _want("matrix serving"):
        _phase("matrix serving")
        # --- matrix serving: folded into THE authoritative record ----------------
        # The config #3 side-bench's serving phase (columnar setCell ingest:
        # one C++ sequencing call + one device axis-resolve scan + FWW filter
        # + one cell-table merge + durable record per batch), re-run here so
        # BENCH_r*.json carries matrix_serving_ops_per_sec with a trials
        # array (VERDICT r5: "claims and the record disagree").
        from fluidframework_tpu.server.serving import MatrixServingEngine

        def _matrix_trial():
            D, G = 64, 32   # docs; each a 32x32 grid, then cell storms
            eng = MatrixServingEngine(n_docs=D, cell_capacity=1 << 17,
                                      batch_window=10 ** 9, axis_capacity=128,
                                      sequencer="native")
            mdocs = [f"mx-{i}" for i in range(D)]
            srng = np.random.default_rng(7)
            mcs = {d: 0 for d in mdocs}
            for d in mdocs:
                eng.connect(d, 7)
                for mx in ("insRow", "insCol"):
                    mcs[d] += 1
                    _, nack = eng.submit(d, 7, mcs[d], 0,
                                         {"mx": mx, "pos": 0, "count": G,
                                          "opKey": (7, mcs[d])})
                    assert nack is None
            eng.flush()

            def storm():
                ids, cseqs, rp, cp, vals = [], [], [], [], []
                for d in mdocs:
                    for _ in range(64):
                        mcs[d] += 1
                        ids.append(d)
                        cseqs.append(mcs[d])
                        rp.append(int(srng.integers(0, G)))
                        cp.append(int(srng.integers(0, G)))
                        vals.append(int(srng.integers(0, 1 << 20)))
                return ids, cseqs, rp, cp, vals

            # storms pre-generated OUTSIDE the timed section: the rng loop
            # is the simulated clients' op authoring, not serving work —
            # the same treatment the string/tree phases give their
            # pre-encoded waves (client serialization happens client-side)
            waves = [storm() for _w in range(7)]
            ids, cseqs, rp, cp, vals = waves[0]  # warmup (compiles the scan)
            eng.ingest_cells(ids, [7] * len(ids), cseqs, [0] * len(ids),
                             rp, cp, vals)
            _ = eng.dims(mdocs[0])
            n_serve = 0
            t0 = time.perf_counter()
            for ids, cseqs, rp, cp, vals in waves[1:]:
                res = eng.ingest_cells(ids, [7] * len(ids), cseqs,
                                       [0] * len(ids), rp, cp, vals)
                assert res["nacked"] == 0
                n_serve += len(ids)
            _ = eng.dims(mdocs[0])               # end sync (device read)
            rate = n_serve / (time.perf_counter() - t0)
            del eng
            return rate

        matrix_trials = sorted(_matrix_trial() for _t in range(3))
        matrix_serving_ops_per_sec = matrix_trials[-1]
        rtt_phases["after_matrix"] = round(rtt_now(), 1)

    if _want("columnar ingress"):
        _phase("columnar ingress")
        # --- columnar ingress: M TCP clients → the PIPELINED front door ----------
        # benches/columnar_ingress_storm.py folded into the authoritative
        # record: real sockets, width-coded binary frames, windowed
        # aggregation — now feeding the pipelined executor (depth 3), so the
        # flusher aggregates the next window while the previous ones are in
        # flight and acks fan back only after each wave's durable append.
        from fluidframework_tpu.server.columnar_ingress import (
            ColumnarAlfred, ColumnarClient, _OP_DTYPE,
        )

        def _ingress_trial(n_clients=8, docs_per=1024, waves=24,
                           window_rows=4096, with_ops=False):
            ing_eng = StringServingEngine(
                n_docs=n_clients * docs_per, capacity=256,
                batch_window=10 ** 9, compact_every=10 ** 9,
                sequencer="native")
            srv = ColumnarAlfred(ing_eng, window_min_rows=window_rows,
                                 window_ms=2.0,
                                 pipeline_depth=3).start_in_thread()
            # scrape-overhead acceptance (ISSUE 17): attach the live ops
            # plane and hit /metrics at 1 Hz for the whole storm — the
            # scraped trial's rate vs the unscraped median is the overhead
            ops = None
            scrape_stop = threading.Event()
            scrapes = [0]
            if with_ops:
                import urllib.request as _url
                ops = srv.start_ops(tick_interval_s=1.0)

                def _scraper():
                    while not scrape_stop.is_set():
                        with _url.urlopen(ops.url + "/metrics",
                                          timeout=30) as r:
                            r.read()
                        scrapes[0] += 1
                        scrape_stop.wait(1.0)

                threading.Thread(target=_scraper, daemon=True).start()
            total = n_clients * docs_per * waves
            acked = [0] * n_clients
            done = threading.Barrier(n_clients + 1)

            def client_run(ci):
                cl = ColumnarClient("127.0.0.1", srv.port)
                cdocs = [f"c{ci}-d{j}" for j in range(docs_per)]
                crow = np.asarray(list(cl.join(cdocs).values()), np.uint16)

                def sender():
                    for w in range(waves):
                        ops = np.zeros(docs_per, _OP_DTYPE)
                        ops["row"] = crow
                        ops["cseq"] = w + 1
                        cl.send_ops([f"w{w}"], ops)

                st = threading.Thread(target=sender, daemon=True)
                st.start()
                want = docs_per * waves
                while acked[ci] < want:
                    resp = cl.recv_json()
                    assert resp["t"] == "acks", resp
                    for _cs, seq in resp["acks"]:
                        assert seq > 0
                    acked[ci] += len(resp["acks"])
                st.join()
                cl.close()
                done.wait()

            cthreads = [threading.Thread(target=client_run, args=(ci,),
                                         daemon=True)
                        for ci in range(n_clients)]
            t0 = time.perf_counter()
            for t in cthreads:
                t.start()
            done.wait(timeout=600)
            rate = total / (time.perf_counter() - t0)
            pstats = srv.pipeline_stats()
            dstats = srv.drain_stats()
            windows = srv.windows_flushed
            opsinfo = None
            if with_ops:
                import json as _json
                import urllib.request as _url
                scrape_stop.set()
                with _url.urlopen(ops.url + "/debug/latency",
                                  timeout=30) as r:
                    breakdown = _json.loads(r.read())
                opsinfo = {"scrapes": scrapes[0], "breakdown": breakdown}
            srv.stop()
            del ing_eng
            return rate, pstats, dstats, windows, opsinfo

        ingress_trials, ingress_stats, ingress_windows = [], None, 0
        ingress_drain = None
        for _t in range(3):
            rate, pstats, dstats, windows, _ = _ingress_trial()
            ingress_trials.append(rate)
            if rate >= max(ingress_trials):
                ingress_stats, ingress_windows = pstats, windows
                ingress_drain = dstats
        ingress_trials.sort()
        columnar_ingress_ops_per_sec = ingress_trials[-1]
        # three more storms with the ops endpoint attached and scraped at
        # 1 Hz (ISSUE 17 acceptance: < 1% throughput loss vs unscraped, and
        # the per-stage breakdown sums to the observed e2e ack latency).
        # Median-of-3 vs median-of-3: single-trial spread on a contended
        # host is ±5-7%, far above the real scrape cost — one draw against
        # the unscraped median reads noise as overhead.
        scraped_trials, opsinfo = [], None
        for _t in range(3):
            s_rate, _, _, _, s_info = _ingress_trial(with_ops=True)
            scraped_trials.append(s_rate)
            if s_rate >= max(scraped_trials):
                opsinfo = s_info
        scraped_trials.sort()
        scraped_rate = scraped_trials[len(scraped_trials) // 2]
        _unscraped = ingress_trials[len(ingress_trials) // 2]
        _bd = opsinfo["breakdown"]
        ops_plane = {
            "scraped_ops_per_sec": round(scraped_rate, 1),
            "scraped_trials": [round(t, 1) for t in scraped_trials],
            "unscraped_median_ops_per_sec": round(_unscraped, 1),
            "scrape_overhead_pct": round(
                (_unscraped - scraped_rate) / _unscraped * 100.0, 2),
            "scrapes": opsinfo["scrapes"],
            "stage_breakdown_coverage": round(_bd["coverage"], 4),
            "stage_e2e_mean_ms": round(_bd["e2e_mean_ms"], 3),
            # p99 is None when it fell off the histogram grid (the route's
            # JSON hygiene maps inf -> null); keep the record strict-JSON
            "stage_e2e_p99_ms": round(_bd["e2e_p99_ms"], 3)
            if _bd["e2e_p99_ms"] is not None else None,
            "stage_shares": {name: round(row["share"], 4)
                             for name, row in _bd["stages"].items()},
            "windows_attributed": _bd["windows"],
        }
        rtt_phases["after_ingress"] = round(rtt_now(), 1)

    if _want("partition scaling"):
        _phase("partition scaling")
        # --- partitioned serving (ISSUE 18): shard the sequencer -----------------
        # The same columnar storm against PartitionedStringServing at 1/2/4/8
        # Deli partitions: the door carves per-partition windows in its drain
        # pass and runs one PipelinedIngestExecutor per partition (N
        # concurrent native sequencers). Three trials per width; speedup and
        # scaling efficiency are best-vs-best against the 1-partition
        # baseline. host_cores rides along because the ratio measures the
        # HOST as much as the code: the seq_dispatch stage is CPU-bound, so a
        # 1-core host serializes the partitions (ratio ~1.0) while a TPU-host
        # core budget lets them genuinely overlap. One extra trial at 4
        # partitions attaches a ReplicaDigestTap on the virtual device mesh:
        # every sequenced window is folded into the replicated shadow via the
        # shard_map step and cross-replica digest agreement is asserted
        # per window.
        partition_scaling = {}
        try:
            # re-imported locally: this phase must run standalone under
            # --phases without the "columnar ingress" phase's imports
            from fluidframework_tpu.server.columnar_ingress import (
                ColumnarAlfred, ColumnarClient, _OP_DTYPE,
            )
            from fluidframework_tpu.server.partitioned import (
                PartitionedStringServing, ReplicaDigestTap,
            )

            def _partition_trial(n_parts, tap=None, n_clients=4,
                                 docs_per=256, waves=10, window_rows=1024):
                total_docs = n_clients * docs_per
                # 2x headroom over the even split: hash routing is not
                # perfectly balanced, and a full partition would nack joins
                dpp = -(-total_docs * 2 // n_parts)
                svc = PartitionedStringServing(
                    n_partitions=n_parts, docs_per_partition=dpp,
                    capacity=256, batch_window=10 ** 9,
                    compact_every=10 ** 9, sequencer="native")
                srv = ColumnarAlfred(svc, window_min_rows=window_rows,
                                     window_ms=2.0,
                                     pipeline_depth=3).start_in_thread()
                srv.digest_tap = tap
                total = n_clients * docs_per * waves
                acked = [0] * n_clients
                done = threading.Barrier(n_clients + 1)

                def client_run(ci):
                    cl = ColumnarClient("127.0.0.1", srv.port)
                    cdocs = [f"ps{n_parts}-{ci}-d{j}"
                             for j in range(docs_per)]
                    crow = np.asarray(list(cl.join(cdocs).values()),
                                      np.uint16)

                    def sender():
                        for w in range(waves):
                            pops = np.zeros(docs_per, _OP_DTYPE)
                            pops["row"] = crow
                            pops["cseq"] = w + 1
                            cl.send_ops([f"w{w}"], pops)

                    st = threading.Thread(target=sender, daemon=True)
                    st.start()
                    want = docs_per * waves
                    while acked[ci] < want:
                        resp = cl.recv_json()
                        assert resp["t"] == "acks", resp
                        acked[ci] += len(resp["acks"])
                    st.join()
                    cl.close()
                    done.wait()

                cthreads = [threading.Thread(target=client_run, args=(ci,),
                                             daemon=True)
                            for ci in range(n_clients)]
                pt0 = time.perf_counter()
                for t in cthreads:
                    t.start()
                done.wait(timeout=600)
                rate = total / (time.perf_counter() - pt0)
                occ = srv.pipeline_stats().get("stage_occupancy")
                srv.stop()
                del svc
                return rate, occ

            widths = {}
            best_by_width = {}
            for n_parts in (1, 2, 4, 8):
                p_trials, p_occ = [], None
                for _t in range(3):
                    p_rate, occ = _partition_trial(n_parts)
                    p_trials.append(p_rate)
                    if p_rate >= max(p_trials):
                        p_occ = occ
                p_trials.sort()
                best_by_width[n_parts] = p_trials[-1]
                widths[str(n_parts)] = {
                    "ops_per_sec": round(p_trials[-1], 1),
                    "ops_per_sec_median":
                        round(p_trials[len(p_trials) // 2], 1),
                    "trials": [round(t, 1) for t in p_trials],
                    "seq_dispatch_occupancy":
                        round(p_occ["seq_dispatch"], 4) if p_occ else None,
                }
            base = best_by_width[1]
            # digest-parity trial: the tap needs >= 2 devices for a replica
            # axis (CPU: XLA_FLAGS=--xla_force_host_platform_device_count=8
            # gives the virtual 8-device mesh); fewer devices skip it with
            # the reason on the record
            digest = {"skipped": f"{jax.device_count()} device(s) — "
                                 "replica axis needs >= 2"}
            if jax.device_count() >= 2:
                from fluidframework_tpu.parallel.mesh import make_mesh
                tap = ReplicaDigestTap(make_mesh(jax.device_count()))
                t_rate, _ = _partition_trial(4, tap=tap)
                digest = {
                    "devices": jax.device_count(),
                    "replicas": tap.n_replicas,
                    "windows": tap.windows,
                    "agree_all": bool(tap.agree_all),
                    "tapped_ops_per_sec": round(t_rate, 1),
                }
            partition_scaling = {
                "widths": widths,
                "speedup_4x": round(best_by_width[4] / base, 3),
                "speedup_8x": round(best_by_width[8] / base, 3),
                "scaling_efficiency_4x":
                    round(best_by_width[4] / base / 4, 3),
                "host_cores": _os.cpu_count(),
                "digest": digest,
            }
            partition_columnar_ops_per_sec = max(
                best_by_width[4], best_by_width[8])
        except Exception as e:   # noqa: BLE001 — the record must still emit
            partition_scaling = {"error": repr(e)}
            partition_columnar_ops_per_sec = None
        rtt_phases["after_partition_scaling"] = round(rtt_now(), 1)

    if _want("read_fanout"):
        _phase("read_fanout")
        # --- read plane (ISSUE 20): encode-once observer fanout ------------------
        # Three measurements, one corpus: (a) delivery ops/s and the
        # encode-once amortization ratio at 1/64/256/1024 in-process
        # subscribers — the window bytes are encoded ONCE and the hub
        # fans the identical object, so the per-subscriber marginal cost
        # must be a vanishing fraction of the single-subscriber
        # encode+deliver cost (acceptance: <= 5% at 1024); (b) catch-up
        # latency — generation diff + short tail vs full-tail replay at
        # 512/2048/4096-op tails (acceptance: diff beats full p50 by >=
        # 5x at 4096); (c) staleness p99 under the write storm itself
        # (the plane pumps inline at ingest pace with 64 live
        # subscribers attached).
        read_fanout = {}
        try:
            from fluidframework_tpu.server.observer import ObserverHub
            from fluidframework_tpu.server.read_plane import (
                ReadPlane, StalenessTracker, apply_generation_diff,
                build_generation_diff, encode_window,
            )
            from fluidframework_tpu.testing.chaos import engine_class

            RF_R, RF_O, RF_WAVES = 64, 8, 24

            def _rf_engine(n_docs=RF_R, capacity=2048):
                eng = StringServingEngine(
                    n_docs=n_docs, capacity=capacity,
                    batch_window=10 ** 9, compact_every=10 ** 9,
                    sequencer="native")
                docs = [f"rf-d{i}" for i in range(n_docs)]
                for d in docs:
                    eng.connect(d, 1)
                rows = np.asarray([eng.doc_row(d) for d in docs],
                                  np.int32)
                return eng, docs, rows

            def _rf_wave(eng, rows, w, o=RF_O):
                r = len(rows)
                shape = (r, o)
                client = np.ones(shape, np.int32)
                cseq = np.broadcast_to(
                    np.arange(o, dtype=np.int32) + np.int32(w * o + 1),
                    shape).copy()
                ref = np.zeros(shape, np.int32)
                kind = np.zeros(shape, np.int32)      # STR_INSERT
                a0 = np.zeros(shape, np.int32)
                a1 = np.zeros(shape, np.int32)
                res = eng.ingest_planes(rows, client, cseq, ref,
                                        kind, a0, a1, text=f"w{w:03d}")
                assert res["nacked"] == 0, res

            # --- (c) staleness under the storm: live plane, 64 subs
            rf_tracker = StalenessTracker()
            rf_hub = ObserverHub(ring=RF_WAVES + 8, tracker=rf_tracker)
            for _i in range(64):
                rf_hub.subscribe(lambda _b: None)
            rf_eng, rf_docs, rf_rows = _rf_engine()
            rf_plane = ReadPlane(rf_eng, rf_hub)
            rf_eng.attach_read_plane(rf_plane)
            rf_log = rf_eng.log
            rf_offsets = [0] * rf_log.n_partitions
            wave_records = []
            for w in range(RF_WAVES):
                _rf_wave(rf_eng, rf_rows, w)
                recs = []
                for p in range(rf_log.n_partitions):
                    size = rf_log.size(p)
                    if size > rf_offsets[p]:
                        recs.extend(rf_log.read(
                            p, from_offset=rf_offsets[p],
                            to_offset=size))
                        rf_offsets[p] = size
                wave_records.append(recs)
            staleness_p99_s = rf_tracker.p99()

            # --- (a) encode once, fan to N: pre-encode the windows,
            # then time publish-only at each width (REPS passes so the
            # per-window publish cost is above timer noise)
            REPS = 5
            t0 = time.perf_counter()
            for _rep in range(REPS):
                windows = [encode_window(recs, i + 1)
                           for i, recs in enumerate(wave_records)]
            encode_s = (time.perf_counter() - t0) / REPS
            total_ops = sum(n for _p, n in windows)
            n_windows = len(windows)

            def _publish_time(n_subs):
                hub = ObserverHub(ring=8,
                                  tracker=StalenessTracker())
                sink = lambda _b: None  # noqa: E731 — shared no-op
                for _i in range(n_subs):
                    hub.subscribe(sink)
                t0 = time.perf_counter()
                for _rep in range(REPS):
                    for payload, n_ops in windows:
                        hub.publish(hub.next_wid(), payload, n_ops)
                return (time.perf_counter() - t0) / REPS

            fanout = {}
            pub_s = {}
            for n_subs in (1, 64, 256, 1024):
                best = min(_publish_time(n_subs) for _t in range(3))
                pub_s[n_subs] = best
                fanout[str(n_subs)] = {
                    "delivery_ops_per_sec":
                        round(total_ops * n_subs / best, 1),
                    "publish_ms_per_window":
                        round(best * 1e3 / n_windows, 4),
                }
            # single-subscriber cost = encode once + deliver to 1;
            # marginal = extra cost per additional subscriber
            single_sub_s = (encode_s + pub_s[1]) / n_windows
            marginal_s = (pub_s[1024] - pub_s[1]) / (1023 * n_windows)
            amortization_ratio = marginal_s / single_sub_s \
                if single_sub_s > 0 else None
            read_delivery_ops_per_sec = \
                fanout["1024"]["delivery_ops_per_sec"]

            # --- (b) catch-up: generation diff vs full-tail replay
            catchup = {}
            for tail in (512, 2048, 4096):
                ce, cdocs, crows = _rf_engine(
                    capacity=max(2048, tail // RF_R + 256))
                _rf_wave(ce, crows, 0)
                s_from = ce.summarize()
                waves = tail // (RF_R * RF_O)
                for w in range(1, waves + 1):
                    _rf_wave(ce, crows, w)
                s_to = ce.summarize()
                t_diff, t_full = [], []
                for _t in range(3):
                    t0 = time.perf_counter()
                    diff = build_generation_diff("string", s_from, s_to)
                    e_diff = apply_generation_diff("string", diff,
                                                   s_from, ce.log)
                    t_diff.append(time.perf_counter() - t0)
                    t0 = time.perf_counter()
                    e_full = engine_class("string").load(s_from, ce.log)
                    t_full.append(time.perf_counter() - t0)
                    # parity spot-check rides every trial
                    d0 = e_diff.read_text(cdocs[0])
                    assert d0 == e_full.read_text(cdocs[0])
                t_diff.sort()
                t_full.sort()
                catchup[str(tail)] = {
                    "tail_ops": waves * RF_R * RF_O,
                    "diff_p50_ms": round(t_diff[1] * 1e3, 2),
                    "full_replay_p50_ms": round(t_full[1] * 1e3, 2),
                    "speedup": round(t_full[1] / t_diff[1], 2),
                }
                del ce

            read_fanout = {
                "windows": n_windows,
                "total_ops": total_ops,
                "fanout": fanout,
                "encode_ms_per_window":
                    round(encode_s * 1e3 / n_windows, 4),
                "marginal_us_per_sub_window_1024":
                    round(marginal_s * 1e6, 4),
                "amortization_ratio_1024":
                    round(amortization_ratio, 5)
                    if amortization_ratio is not None else None,
                "catchup": catchup,
                "catchup_speedup_4096": catchup["4096"]["speedup"],
                "staleness_p99_s": round(staleness_p99_s, 6),
            }
            del rf_eng
        except Exception as e:   # noqa: BLE001 — the record must still emit
            read_fanout = {"error": repr(e)}
            read_delivery_ops_per_sec = None
        rtt_phases["after_read_fanout"] = round(rtt_now(), 1)

    if _want("small-window ack"):
        _phase("small-window ack")
        # --- small-window ack latency (VERDICT r4 weak #6) -----------------------
        # ack_p50/p99 at 64- and 256-doc windows with TWO concurrent clients
        # per doc; the explicit budget: an ack blocks on ZERO device reads
        # (sequencing + durable append are host work, the merge dispatches
        # async), so its floor is pure host time.
        small_window_ack = {}
        for nd in (64, 256):
            se = StringServingEngine(n_docs=nd, capacity=256,
                                     batch_window=10 ** 9, compact_every=10 ** 9,
                                     sequencer="native")
            sdocs = [f"sw{nd}-{i}" for i in range(nd)]
            for d in sdocs:
                se.connect(d, 1)
                se.connect(d, 2)
            srows = np.array([se.doc_row(d) for d in sdocs], np.int32)
            OW = 8
            # alternating clients per op column; per-client contiguous cseqs
            cl_plane = np.broadcast_to(
                (np.arange(OW, dtype=np.int32) % 2) + 1, (nd, OW))
            samples = []
            base = np.zeros(2, np.int64)
            for c in range(25):
                cseq = np.empty((nd, OW), np.int32)
                for k in range(OW):
                    cseq[:, k] = base[k % 2] + (k // 2) + 1
                base += OW // 2
                planes, _ = typing_storm(nd, OW, seed=40 + c)
                tb = time.perf_counter()
                res = se.ingest_planes(srows, cl_plane, cseq, cseq,
                                       planes["kind"], planes["a0"],
                                       planes["a1"], "abcd")
                samples.append(time.perf_counter() - tb)
                assert res["nacked"] == 0
            samples = samples[1:]   # first sample compiles the OW shape
            samples.sort()
            snap = se.metrics.snapshot()
            small_window_ack[str(nd)] = {
                "p50_ms": round(samples[len(samples) // 2] * 1000, 2),
                "p99_ms": round(samples[-1] * 1000, 2),
                # WHERE the ack wall goes (stage p50s over this window
                # size's samples): C++ sequencing vs host plane prep/pack
                # vs the async device dispatch vs the durable append — the
                # split that shows whether a regression is sequencer, host
                # packing, or log I/O before anyone stares at a profiler
                "split_p50_ms": {
                    k.replace("ingest_", "").replace("_ms", ""):
                        round(snap.get(f"{k}_p50_ms", 0), 3)
                    for k in ("ingest_seq_ms", "ingest_prep_ms",
                              "ingest_pack_ms", "ingest_dispatch_ms",
                              "ingest_log_ms")},
                # the same p50 wall as a per-op budget across the window
                "per_op_us": round(
                    samples[len(samples) // 2] * 1e6 / (nd * OW), 2),
            }
            del se
        small_window_ack["budget"] = {
            "device_reads": 0, "device_round_trips": 0,
            "note": "ack = C++ sequencing + durable append + async device "
                    "dispatch; floor is host time, no link RTT in the path"}

        # genuinely CONCURRENT two-submitter variant: the loops above
        # measure an UNCONTENDED ack (one thread, engine idle between
        # windows); production front doors race. Two submitter threads
        # share the 256-doc engine behind one lock (the ingest path is
        # single-writer by design — the lock IS the sequencer front door);
        # each sample is submit-intent → ack wall, so time queued behind
        # the other submitter's window is counted in the percentile.
        se2 = StringServingEngine(n_docs=256, capacity=256,
                                  batch_window=10 ** 9,
                                  compact_every=10 ** 9, sequencer="native")
        s2docs = [f"sw2-{i}" for i in range(256)]
        for d in s2docs:
            se2.connect(d, 1)
            se2.connect(d, 2)
        s2rows = np.array([se2.doc_row(d) for d in s2docs], np.int32)
        OW = 8
        ins_kind = np.full((256, OW), int(OpKind.STR_INSERT), np.int32)
        zeros_p = np.zeros((256, OW), np.int32)
        se2.ingest_planes(  # warmup: compiles this engine's window shape
            s2rows, np.ones((256, OW), np.int32),
            np.broadcast_to(np.arange(1, OW + 1, dtype=np.int32), (256, OW)),
            zeros_p, ins_kind, zeros_p, zeros_p, "abcd")
        front_door = threading.Lock()
        conc_walls: list = []
        conc_lock = threading.Lock()
        conc_start = threading.Barrier(2)
        N_WIN2 = 12

        def _submitter(cid, cseq_base):
            cl_pl = np.full((256, OW), cid, np.int32)
            for c in range(N_WIN2):
                cseq = np.broadcast_to(
                    np.arange(cseq_base + c * OW + 1,
                              cseq_base + c * OW + OW + 1,
                              dtype=np.int32), (256, OW))
                if c == 0:
                    conc_start.wait()
                tb = time.perf_counter()
                with front_door:
                    res = se2.ingest_planes(s2rows, cl_pl, cseq, zeros_p,
                                            ins_kind, zeros_p, zeros_p,
                                            "abcd")
                dt = time.perf_counter() - tb
                assert res["nacked"] == 0
                with conc_lock:
                    conc_walls.append(dt)

        _subs = [threading.Thread(target=_submitter, args=(1, OW)),
                 threading.Thread(target=_submitter, args=(2, 0))]
        for _t2 in _subs:
            _t2.start()
        for _t2 in _subs:
            _t2.join()
        conc_walls.sort()
        small_window_ack["256_two_submitters"] = {
            "p50_ms": round(conc_walls[len(conc_walls) // 2] * 1000, 2),
            "p99_ms": round(conc_walls[-1] * 1000, 2),
            "windows": len(conc_walls),
            "note": "two front-door threads racing one engine lock; each "
                    "wall includes queueing behind the other submitter"}
        del se2

    if _want("ack latency"):
        _phase("ack latency")
        # --- ingest→ack latency distribution ------------------------------------
        # Per-call wall time of ingest_planes (sequencing + durable append +
        # device dispatch — the ack path) on small 8-op windows; the tunnel
        # RTT floors this at ~100 ms (local attach pays PCIe microseconds).
        lat_engine = StringServingEngine(
            n_docs=n_docs, capacity=serve_capacity, batch_window=10 ** 9,
            compact_every=1, sequencer="native")
        for d in docs:
            lat_engine.connect(d, 1)
        lrows = np.array([lat_engine.doc_row(d) for d in docs], np.int32)
        OW = 8
        lat_samples = []
        lcseq_base = 0
        lat_client = np.ones((n_docs, OW), np.int32)
        # unmeasured warmup: the OW-shaped dispatch compiles here, not in a
        # timed sample (a compile in the first sample would masquerade as p99)
        wplanes, _ = typing_storm(n_docs, OW, seed=99)
        lat_engine.ingest_planes(
            lrows, lat_client,
            np.broadcast_to(np.arange(1, OW + 1, dtype=np.int32),
                            (n_docs, OW)),
            np.broadcast_to(np.arange(1, OW + 1, dtype=np.int32),
                            (n_docs, OW)),
            wplanes["kind"], wplanes["a0"], wplanes["a1"], "abcd")
        _ = np.asarray(lat_engine.store.state.overflow)
        lcseq_base = OW
        # stall guard: a window >10x the running median is a host/tunnel
        # hiccup, not ack latency — re-sample a FRESH window (seqs are
        # consumed; the stalled one stays excluded) and count the retry so
        # the record shows how often the run had to dodge
        ack_retries = 0
        c = 0
        while len(lat_samples) < 24:
            planes, _ = typing_storm(n_docs, OW, seed=c)
            c += 1
            cseq = np.broadcast_to(
                np.arange(lcseq_base + 1, lcseq_base + OW + 1,
                          dtype=np.int32), (n_docs, OW))
            lcseq_base += OW
            tb = time.perf_counter()
            lat_engine.ingest_planes(lrows, lat_client, cseq, cseq,
                                     planes["kind"], planes["a0"],
                                     planes["a1"], "abcd")
            dt = time.perf_counter() - tb
            med = (sorted(lat_samples)[len(lat_samples) // 2]
                   if lat_samples else None)
            if med is not None and dt > 10 * med and ack_retries < 8:
                ack_retries += 1
                continue
            lat_samples.append(dt)
        lat_samples.sort()
        ack_p50_ms = float(lat_samples[len(lat_samples) // 2] * 1000)
        ack_p99_ms = float(lat_samples[-1] * 1000)  # max of 24 ≈ p99 bound

        # honesty check: an independently-merged doc (per-op message path on a
        # fresh store) must read identically to the engine's columnar result
        for check_doc in (0, n_docs // 2):
            ref_store = TensorStringStore(n_docs=1, capacity=serve_capacity)
            msgs = []
            seq = 1  # join consumed seq 1
            for kind, a0, a1, cseq, refp in serve_batches:
                for o in range(ops_per_batch):
                    seq += 1
                    if kind[check_doc, o] == OpKind.STR_INSERT:
                        contents = {"mt": "insert", "kind": 0,
                                    "pos": int(a0[check_doc, o]), "text": "abcd"}
                    else:
                        contents = {"mt": "remove",
                                    "start": int(a0[check_doc, o]),
                                    "end": int(a1[check_doc, o])}
                    msgs.append((0, SequencedDocumentMessage(
                        doc_id="x", client_id=1, client_seq=int(cseq[check_doc, o]),
                        ref_seq=int(refp[check_doc, o]), seq=seq,
                        min_seq=int(refp[check_doc, o]), type=MessageType.OP,
                        contents=contents)))
            ref_store.apply_messages(msgs)  # one batched device apply
            want = ref_store.read_text(0)
            got = engine.read_text(docs[check_doc])
            assert got == want, f"serving divergence doc {check_doc}"

    if _want("apply-window latency"):
        _phase("apply-window latency")
        # --- latency phase: per-window apply latency -----------------------------
        # The op axis is time-sequential: each step of the 64-op scan is one
        # apply window over all 10k docs. Sample individually-synced dispatches;
        # worst sample / windows-per-dispatch bounds per-window device latency
        # from above — and hence its p99 (see module docstring for exactly what
        # this does and does not measure).
        # Stall-proofing (VERDICT weak #2: a transient 63 s axon stall once
        # printed apply_window_worst_ms: 983 with nothing in the record saying
        # the HOST stalled): unmeasured warmup, each sample is the MEDIAN of 3
        # dispatches, and a sample >10x the running median is re-sampled
        # (bounded) with the retry count recorded. A worst_ms that survives
        # all three layers is device latency, not a scheduler hiccup — and if
        # the stall is persistent the sample is kept but FLAGGED.
        wstate = StringState.create(n_docs, capacity)
        _ = np.asarray(wstate.count)
        wstate = apply_fn(wstate, *batches[0])
        _ = np.asarray(wstate.overflow)
        del wstate
        samples = []
        apply_window_retries = 0
        apply_window_stalled = False
        c = 0
        while len(samples) < 8:
            inner = []
            for _r in range(3):
                state = StringState.create(n_docs, capacity)
                _ = np.asarray(state.count)
                tb = time.perf_counter()
                state = apply_fn(state, *batches[c % n_batches])
                _ = np.asarray(state.overflow)
                inner.append(time.perf_counter() - tb)
            dt = sorted(inner)[1]       # median-of-3: one hiccup never wins
            med = sorted(samples)[len(samples) // 2] if samples else None
            if med is not None and dt > 10 * med:
                if apply_window_retries < 8:
                    apply_window_retries += 1
                    continue
                apply_window_stalled = True
            samples.append(dt)
            c += 1
        worst_ms = float(max(samples) * 1000 / ops_per_batch)
        apply_window_p50_ms = float(
            sorted(samples)[len(samples) // 2] * 1000 / ops_per_batch)

    rtt_monitor.stop()

    # -------------------------------------------------- reconnect storm
    # the resilience plane under measured load (ISSUE 9): a seeded soak
    # (socket kills + injected sequencer crashes + service restarts over
    # resilient clients) reported as throughput, reconnect latency
    # percentiles, resubmit/dup-ack counts — and the invariant-violation
    # count the perf sentinel gates on (any nonzero fails --check)
    if _want("reconnect_storm"):
        _phase("reconnect_storm")
        try:
            import importlib.util as _ilu
            _spec = _ilu.spec_from_file_location(
                "chaos_soak", _os.path.join(
                    _os.path.dirname(_os.path.abspath(__file__)),
                    "tools", "chaos_soak.py"))
            _soak = _ilu.module_from_spec(_spec)
            _spec.loader.exec_module(_soak)
            _storm = _soak.run_soak(seed=123, steps=300, n_clients=4,
                                    restarts=3, kill_p=0.02, crash_p=0.005)
            reconnect_storm = {
                "ops_per_sec": round(
                    _storm["ops_acked"] / max(_storm["elapsed_s"], 1e-9), 1),
                "ops_acked": _storm["ops_acked"],
                "reconnects": _storm["reconnects"],
                "reconnect_p50_ms": _storm["reconnect_p50_ms"],
                "reconnect_p99_ms": _storm["reconnect_p99_ms"],
                "resubmits": _storm["resubmits"],
                "dup_acked": _storm["dup_acked"],
                "socket_kills": _storm["socket_kills"],
                "restarts": _storm["restarts"],
                "faultpoint_fires": _storm["faultpoint_fires"],
                "invariant_violations": _storm["violations"],
            }
        except Exception as e:   # noqa: BLE001 — the record must still emit
            reconnect_storm = {"error": repr(e), "invariant_violations": -1}

        # -------------------------------------------------- overload storm
        # the admission plane under 2x-capacity load (ISSUE 16): the
        # multi-tenant simulator's quick profile — one abusive tenant at 5x
        # budget, AIMD policy live — reported as goodput/shed/latency, and
        # the two correctness counts the perf sentinel hard-gates on:
        # invariant_violations (exactly-once/order audits) and silent_drops
    if _want("overload_storm"):
        _phase("overload_storm")
        try:
            import importlib.util as _ilu
            _spec = _ilu.spec_from_file_location(
                "tenant_sim", _os.path.join(
                    _os.path.dirname(_os.path.abspath(__file__)),
                    "tools", "tenant_sim.py"))
            _tsim = _ilu.module_from_spec(_spec)
            # registered BEFORE exec: its dataclasses resolve string
            # annotations through sys.modules[cls.__module__]
            sys.modules["tenant_sim"] = _tsim
            _spec.loader.exec_module(_tsim)
            # lenient latency/goodput floors (shared bench boxes vary);
            # the sentinel gates only the correctness counts
            _rep = _tsim.run_sim(seed=123, duration_s=1.2, slo_ms=1000.0,
                                 goodput_min=0.3, quick=True)
            overload_storm = {
                "goodput_ratio": _rep["goodput_ratio"],
                "admitted_ack_p99_ms": _rep["admitted_ack_p99_ms"],
                "shed_ratio": _rep["shed_ratio"],
                "shed_total": _rep["shed_total"],
                "throttled_frames": _rep["throttled_frames"],
                "throttle_resubmits": _rep["throttle_resubmits"],
                "abusive_throttled": _rep["abusive_throttled"],
                "abusive_shed": _rep["abusive_shed"],
                "ops_offered": _rep["ops_offered"],
                "ops_acked": _rep["ops_acked"],
                "policy_breach_ticks": _rep["policy"]["breach_ticks"],
                "policy_min_scale": _rep["policy"]["min_scale"],
                "silent_drops": _rep["silent_drops"],
                "invariant_violations": _rep["violations"],
                "gate_failures": _rep["gate_failures"],
            }
        except Exception as e:   # noqa: BLE001 — the record must still emit
            overload_storm = {"error": repr(e), "invariant_violations": -1,
                              "silent_drops": -1}

        # ------------------------------------------------------- durability
        # the recovery ladder under the clock (ISSUE 10): summary load + tail
        # replay timed at ladder depth 0 (newest generation verifies) and
        # depth 1 (newest rotted → fall back a rung, replay a longer tail),
        # then an offline scrub of the phase's own spill — chain_breaks is
        # the integrity count the perf sentinel hard-gates on
    if _want("durability"):
        _phase("durability")
        try:
            import random as _random
            import tempfile as _tempfile
            from fluidframework_tpu.runtime.summarizer import (
                SummaryGenerationStore as _GenStore,
            )
            from fluidframework_tpu.server.oplog import PartitionedLog as _PLog
            from fluidframework_tpu.server.serving import (
                StringServingEngine as _StrEngine,
            )
            from fluidframework_tpu.utils.faultpoints import (
                corrupt_bitflip as _corrupt_bitflip,
            )
            import importlib.util as _ilu2
            _spec2 = _ilu2.spec_from_file_location(
                "log_scrub", _os.path.join(
                    _os.path.dirname(_os.path.abspath(__file__)),
                    "tools", "log_scrub.py"))
            _scrub = _ilu2.module_from_spec(_spec2)
            _spec2.loader.exec_module(_scrub)
            with _tempfile.TemporaryDirectory(prefix="bench_dur_") as _dd:
                _spill = _os.path.join(_dd, "spill")
                _gen_dir = _os.path.join(_dd, "gens")
                _os.mkdir(_spill)
                _dlog = _PLog(2, _spill, "deltas")
                _deng = _StrEngine(n_docs=4, capacity=1024, batch_window=16,
                                   n_partitions=2, log=_dlog)
                _store = _GenStore(_gen_dir, keep=3)
                _deng.connect("bench-doc", 1)
                _n_dur = 512
                _seq = 0
                for _i in range(_n_dur):
                    _m, _nk = _deng.submit(
                        "bench-doc", 1, _i + 1, 0,
                        {"mt": "insert", "kind": 0, "pos": 0, "text": "x"})
                    _seq = _m.seq
                    # two generations: mid-run and at 3/4 — depth 1 falls
                    # back to the older one and replays the longer tail
                    if _i in (_n_dur // 2 - 1, _n_dur * 3 // 4 - 1):
                        _deng.flush()
                        _store.save(_deng.summarize(), _seq)
                _deng.flush()
                _dlog.close()

                def _ladder_trial():
                    _t0 = time.perf_counter()
                    _s, _sq, _depth = _store.load_latest()
                    _rlog = _PLog.recover(2, _spill, "deltas")
                    _e2 = _StrEngine.load(_s, _rlog)
                    _e2.flush()
                    _dt = (time.perf_counter() - _t0) * 1000
                    _rlog.close()
                    return _dt, _depth

                _trials0 = [_ladder_trial() for _ in range(5)]
                # scrub the spill while it is pristine: the ladder trials are
                # read-only, so any break here is a writer-path bug
                _dsum = _scrub.summarize_reports(_scrub.scrub_tree(_spill))
                _gens = _store.generations()
                _corrupt_bitflip(
                    _os.path.join(_gen_dir, _store._BLOB.format(_gens[-1])),
                    _random.Random(17))
                _trials1 = [_ladder_trial() for _ in range(5)]
                _p50 = lambda ts: sorted(t for t, _ in ts)[len(ts) // 2]  # noqa: E731,E501
                durability = {
                    "recovery_ladder_ms": {
                        "depth0_p50": round(_p50(_trials0), 2),
                        "depth1_p50": round(_p50(_trials1), 2),
                    },
                    "ladder_depths": [_trials0[0][1], _trials1[0][1]],
                    "ops_replayed": _n_dur,
                    "generations_kept": len(_gens),
                    "chain_breaks": _dsum["chain_breaks"],
                    "records_scrubbed": _dsum["records"],
                }
        except Exception as e:   # noqa: BLE001 — the record must still emit
            durability = {"error": repr(e), "chain_breaks": -1}

    # observability ride-along: the unified registry's process-wide view
    # (device dispatches, jit compiles vs cache hits, oplog appends, ...)
    # plus ONE sampled span timeline from the run's newest trace, so a
    # bench record alone shows where a batch's wall time went
    from fluidframework_tpu.utils import tracing as _tracing
    from fluidframework_tpu.utils.telemetry import REGISTRY as _registry
    _tids = _tracing.TRACER.trace_ids()
    _trace_sample = None
    if _tids:
        _tid = _tids[-1]
        _trace_sample = {
            "trace_id": _tid,
            "spans": [{"name": e["name"], "dur_ms": round(e["dur"] / 1e3, 3),
                       "parent_id": e["parent_id"], "span_id": e["span_id"],
                       "args": {k: v for k, v in e.get("args", {}).items()
                                if isinstance(v, (int, float, str, bool))}}
                      for e in _tracing.TRACER.events(_tid)[:32]],
        }

    record = {
        "metric": "sharedstring_ops_per_sec_merged",
        "value": round(ops_per_sec, 1),
        "unit": "ops/s",
        "vs_baseline": round(ops_per_sec / 1_000_000, 4),
        "docs": n_docs,
        "total_ops": n_ops,
        # headline per-suite trials + band (satellite: drift visibility)
        "headline_trials": [round(t, 1) for t in headline_trials],
        "headline_variance_band": headline_band,
        "apply_window_worst_ms": round(worst_ms, 2),
        "apply_window_p50_ms": round(apply_window_p50_ms, 2),
        # stall/retry accounting: how many samples the >10x-median guard
        # re-drew, and whether a stall persisted past the retry budget
        # (a flagged run's worst_ms is a host event, not device latency)
        "apply_window_retries": apply_window_retries,
        "apply_window_stalled": apply_window_stalled,
        "ack_sample_retries": ack_retries,
        "dispatch_rtt_ms": round(rtt_ms, 1),
        "digest_parity": digest_parity,
        "serving_ops_per_sec": round(serving_ops_per_sec, 1),
        "serving_ops_per_sec_median": round(serving_ops_per_sec_median, 1),
        "serving_trials": [round(t, 1) for t in serving_trials],
        "serving_rich_ops_per_sec": round(rich_ops_per_sec, 1),
        "serving_rich_ops_per_sec_median":
            round(rich_ops_per_sec_median, 1),
        "serving_rich_trials": [round(t, 1) for t in rich_trials],
        "serving_interval_ops_per_sec": round(interval_ops_per_sec, 1),
        # columnar-path proof: >=2 apply segments per post-warmup wave
        # means the MSN floor crossed tombstones mid-window and anchors
        # slid on-device (the old per-op fallback recorded no segments)
        "serving_interval_segments_per_wave": iv_seg_waves,
        "serving_interval_wire": interval_wire,
        "serving_interval_ops": n_iv_docs * iv_ow * iv_waves,
        "ack_small_windows": small_window_ack,
        # contention canary: the tunnel round-trip re-sampled at phase
        # boundaries + host load; inflated values mean the phase numbers
        # ran under contention (read medians, not bests)
        "rtt_phases": rtt_phases,
        # whole-run RTT distribution + dated stall events from the
        # background sampler (see RttMonitor)
        "rtt_monitor": rtt_monitor.summary(),
        "host_load_start_end": [round(load_start, 2),
                                round(_os.getloadavg()[0], 2)],
        "contended": bool(max(rtt_phases.values()) >
                          2 * max(rtt_phases["start"], 60.0)
                          or bool(rtt_monitor.stall_events)),
        # host-side wall per ingest batch, by stage (p50; device time is
        # the remainder of the batch wall — it overlaps the next batch's
        # host work): C++ sequencing / plane prep / wire packing /
        # worker-side prepack / async dispatch / durable-log append.
        # wave_wall is the PIPELINE's inter-completion gap: with stages
        # overlapped it tracks the max stage, so sum(stage p50s) >
        # wave_wall p50 is the overlap evidence the record carries.
        "ingest_stage_p50_ms": {
            eng_name: {
                k.replace("ingest_", "").replace("_ms", ""):
                    round(e.metrics.snapshot().get(f"{k}_p50_ms", 0), 1)
                for k in ("ingest_seq_ms", "ingest_prep_ms",
                          "ingest_pack_ms", "ingest_prepack_ms",
                          "ingest_dispatch_ms", "ingest_log_ms")}
            for eng_name, e in (("broadcast", engine),
                                ("rich", rich_engine),
                                ("tree", tree_eng)) if e is not None},
        "ingest_wave_wall_p50_ms": {
            eng_name: round(e.metrics.snapshot().get(
                "ingest_wave_wall_ms_p50_ms", 0), 1)
            for eng_name, e in (("broadcast", engine),
                                ("rich", rich_engine),
                                ("tree", tree_eng)) if e is not None},
        # executor occupancy/overlap from each phase's best trial
        # (overlap > 1.0 == stages genuinely ran concurrently)
        "ingest_pipeline": {"broadcast": serving_pipe_stats,
                            "rich": rich_pipe_stats,
                            "tree": tree_pipe_stats},
        "matrix_serving_ops_per_sec": round(matrix_serving_ops_per_sec, 1),
        "matrix_serving_ops_per_sec_median":
            round(matrix_trials[len(matrix_trials) // 2], 1),
        "matrix_serving_trials": [round(t, 1) for t in matrix_trials],
        "columnar_ingress_ops_per_sec":
            round(columnar_ingress_ops_per_sec, 1),
        "columnar_ingress_ops_per_sec_median":
            round(ingress_trials[len(ingress_trials) // 2], 1),
        "columnar_ingress_trials": [round(t, 1) for t in ingress_trials],
        "columnar_ingress_windows": ingress_windows,
        "columnar_ingress_pipeline": ingress_stats,
        # whole-buffer batch decode evidence (ISSUE 15): decode-stage
        # p50 per drain pass, bytes drained per pass, and which tier
        # (native libingress.so vs numpy fallback) served
        "ingress_decode_p50_ms": ingress_drain["decode_p50_ms"],
        "ingress_drained_bytes_per_pass":
            ingress_drain["bytes_per_pass_p50"],
        "ingress_drain_passes": ingress_drain["passes"],
        "ingress_decode_tier": ingress_drain["tier"],
        # live operations plane (ISSUE 17): scrape overhead of the 1 Hz
        # /metrics poller against the columnar storm, plus the stage
        # attribution's coverage (stage sum / e2e ack — 1.0 = the
        # breakdown fully explains the observed latency)
        "ops_plane": ops_plane,
        # partitioned serving (ISSUE 18): the columnar storm at 1/2/4/8
        # sequencer partitions — speedup/efficiency vs the 1-partition
        # baseline (host_cores qualifies the ratio), the per-window
        # digest-parity tap's verdict, and the declared-floor scalar
        # (best rate at >= 4 partitions) the sentinel judges
        "partition_scaling": partition_scaling,
        "partition_columnar_ops_per_sec":
            round(partition_columnar_ops_per_sec, 1)
            if partition_columnar_ops_per_sec else None,
        # read plane (ISSUE 20): encode-once fanout economics (delivery
        # ops/s at 1/64/256/1024 subscribers, the per-subscriber
        # marginal-cost ratio), generation-diff catch-up vs full-tail
        # replay at three tail lengths, and staleness p99 under the
        # write storm — plus the declared-floor scalar (delivery ops/s
        # at 1024 subscribers) the sentinel judges
        "read_fanout": read_fanout,
        "read_delivery_ops_per_sec":
            round(read_delivery_ops_per_sec, 1)
            if read_delivery_ops_per_sec else None,
        # resilience under load (ISSUE 9): the seeded reconnect storm's
        # throughput/latency plus the invariant-violation count the
        # perf sentinel gates on
        "reconnect_storm": reconnect_storm,
        # overload protection under 2x-capacity multi-tenant load
        # (ISSUE 16): goodput/shed split plus the correctness counts
        # (invariant_violations, silent_drops) the sentinel gates on
        "overload_storm": overload_storm,
        # durable-layer integrity under the clock (ISSUE 10): recovery
        # ladder p50 at depth 0/1 + the scrub's chain-break count the
        # perf sentinel hard-gates on
        "durability": durability,
        # continuous canary, attributed per phase: worst in-phase RTT +
        # contended flag (samples taken DURING the phase, not only at
        # its boundaries)
        "rtt_in_phase": rtt_monitor.phases(),
        "serving_durable_ops_per_sec":
            round(durable_ops_per_sec, 1) if durable_ops_per_sec else None,
        "serving_durable_ops_per_sec_median":
            round(durable_ops_per_sec_median, 1)
            if durable_ops_per_sec_median else None,
        "serving_durable_trials": [round(t, 1) for t in durable_trials],
        "tree_serving_ops_per_sec": round(tree_ops_per_sec, 1),
        "tree_serving_ops_per_sec_median":
            round(tree_ops_per_sec_median, 1),
        "tree_serving_trials": [round(t, 1) for t in tree_trials],
        "tree_flat_serving_ops_per_sec": round(tree_flat_ops_per_sec, 1),
        "tree_flat_trials": [round(t, 1) for t in leaf_trials],
        "tree_kernel_ops_per_sec": round(tree_kernel_ops_per_sec, 1),
        "tree_kernel_trials": [round(t, 1) for t in tree_kernel_trials],
        "ack_p50_ms": round(ack_p50_ms, 1),
        "ack_p99_ms": round(ack_p99_ms, 1),
        "serving_read_ms": round(serving_read_ms, 1),
        # round-trip budgets (VERDICT r3 weak #6/#7): a read is ONE fused
        # gather+transfer (asserted via the store's device-read counter);
        # an ingest ack blocks on ZERO device reads — sequencing + the
        # durable append are host-side, the merge is dispatched async and
        # the overflow check is a deferred async copy
        "read_device_round_trips": read_rtts,
        "ack_device_round_trips": 0,
        "conflict_ops_per_sec": round(conflict_ops_per_sec, 1),
        "conflict_parity": conflict_parity,
        # unified metrics registry snapshot (counters + gauges + histogram
        # percentiles, own + attached components) and one sampled span
        # timeline — see utils.telemetry / utils.tracing
        "metrics": _registry.full_snapshot(),
        "trace_sample": _trace_sample,
        "backend": jax.default_backend(),
        # phase selector (ISSUE 19 satellite): which phases this record
        # actually measured — a --phases subset leaves the rest at their
        # zero/skipped defaults above
        "phases_run": [p for p in ALL_PHASES if p in _selected],
        "phases_skipped": [p for p in ALL_PHASES if p not in _selected],
        # capacity plane (ISSUE 19): per-phase boundary census — census
        # cost, resident host bytes at entry, peak across entry/exit
        "phase_capacity": _phase_capacity,
        "capacity_census_ms": round(max(
            (v["census_ms"] for v in _phase_capacity.values()),
            default=0.0), 2),
        "doc_resident_bytes_peak": max(
            (v.get("doc_resident_bytes_peak", v["doc_resident_bytes"])
             for v in _phase_capacity.values()), default=0),
    }

    # final health sample: feed the record's own headline numbers to the
    # SLO gauges (ack_p99_ms, digest_parity) so the scorecard judges the
    # run the way docs/OBSERVABILITY.md declares the objectives, then
    # embed the scorecard and the sentinel's verdict vs the committed
    # BENCH_r*.json trajectory. All guarded: a broken health plane
    # degrades the record, never the bench.
    _phase("health scorecard + perf sentinel")
    try:
        _registry.set_gauge("ack_p99_ms", ack_p99_ms)
        _registry.set_gauge("digest_parity",
                            1.0 if digest_parity else 0.0)
        _health.tick()
        record["slo_scorecard"] = _slo_engine.scorecard()
        record["slo_breaches"] = [
            {k: b.get(k) for k in ("slo", "series", "worst", "trace_id")}
            for b in _slo_engine.breaches]
    except Exception as e:   # noqa: BLE001
        record["slo_scorecard"] = {"error": repr(e)}
    if record["phases_skipped"]:
        # a --phases subset leaves skipped phases at their zero
        # defaults; the sentinel would read those as regressions, so it
        # only judges full sweeps
        record["sentinel"] = {"skipped": "partial run (--phases)"}
    else:
        try:
            import importlib.util as _ilu
            from pathlib import Path as _Path
            _root = _Path(__file__).resolve().parent
            _spec = _ilu.spec_from_file_location(
                "perf_sentinel", _root / "tools" / "perf_sentinel.py")
            _ps = _ilu.module_from_spec(_spec)
            _spec.loader.exec_module(_ps)
            _rounds = _ps.load_trajectory(_root)
            _rounds.append({**{k: v for k, v in record.items()
                               if isinstance(v, (int, float, bool))},
                            "_round": "current"})
            _verdicts = _ps.judge(_rounds) + _ps.judge_floors(_rounds)
            record["sentinel"] = {
                "rounds": len(_rounds) - 1,
                "regressions": [v["metric"] for v in _verdicts
                                if v["verdict"] == _ps.REGRESS],
                "improvements": [v["metric"] for v in _verdicts
                                 if v["verdict"] == _ps.IMPROVE],
                "verdicts": _verdicts,
            }
        except Exception as e:   # noqa: BLE001
            record["sentinel"] = {"error": repr(e)}

    print(json.dumps(record))


def _phases_arg(argv):
    """Extract a ``--phases LIST`` / ``--phases=LIST`` argument."""
    for i, a in enumerate(argv):
        if a == "--phases" and i + 1 < len(argv):
            return argv[i + 1]
        if a.startswith("--phases="):
            return a.split("=", 1)[1]
    return None


def main(phases=None):
    import os
    env = dict(os.environ)
    # CPU runs need the virtual 8-device mesh for the partition-scaling
    # digest tap (and any other mesh phase); a TPU run ignores the host
    # platform flag entirely, and an explicit XLA_FLAGS wins
    if env.get("JAX_PLATFORMS", "").lower() == "cpu" and \
            "xla_force_host_platform_device_count" not in \
            env.get("XLA_FLAGS", ""):
        env["XLA_FLAGS"] = (env.get("XLA_FLAGS", "") +
                            " --xla_force_host_platform_device_count=8"
                            ).strip()
    child_argv = [sys.executable, __file__, "--child"]
    if phases:
        select_phases(phases)   # fail fast on unknown names
        child_argv += ["--phases", phases]
    for attempt in range(3):
        try:
            proc = subprocess.run(
                child_argv,
                capture_output=True, text=True, timeout=1800, env=env)
        except subprocess.TimeoutExpired:
            sys.stderr.write(f"bench attempt {attempt + 1} timed out\n")
            continue
        lines = [l for l in proc.stdout.strip().splitlines()
                 if l.startswith("{")]
        if proc.returncode == 0 and lines:
            print(lines[-1])
            return
        sys.stderr.write(f"bench attempt {attempt + 1} failed "
                         f"(rc={proc.returncode}):\n{proc.stderr[-2000:]}\n")
    sys.exit(1)


if __name__ == "__main__":
    if "--child" in sys.argv:
        run(phases=_phases_arg(sys.argv))
    else:
        main(phases=_phases_arg(sys.argv))
