"""Inbound inverse of the outbox: reassemble → decompress → ungroup.

Reference counterpart: ``RemoteMessageProcessor`` (+ ``OpDecompressor``,
``OpGroupingManager`` ungroup path) in ``@fluidframework/container-runtime``
— SURVEY.md §2.8, §3.2 (mount empty). One sequenced wire message expands to
zero (buffered chunk) or more runtime messages. Ungrouped ops from a grouped
batch share the envelope's sequence number; client-visible ordering within
the envelope is positional, and each inner op is delivered with its own
clientSeq-space intact via per-op metadata.
"""

from __future__ import annotations

import base64
import dataclasses
import json
import zlib
from typing import Dict, List, Tuple

from ..core.protocol import MessageType, SequencedDocumentMessage
from .outbox import CHUNKED, COMPRESSED, GROUPED_BATCH


class RemoteMessageProcessor:
    def __init__(self):
        # (client_id, chunk_id) -> list of received pieces
        self._chunks: Dict[Tuple[int, int], List[str]] = {}

    def process(self, msg: SequencedDocumentMessage
                ) -> List[SequencedDocumentMessage]:
        """Expand one sequenced wire message into runtime messages, in
        apply order. Non-envelope messages pass through unchanged."""
        if msg.type != MessageType.OP or not isinstance(msg.contents, dict):
            return [msg]
        contents = msg.contents
        kind = contents.get("type")
        if kind == "withMeta":
            # outermost wrapper: per-op metadata folded into wire contents
            # by ContainerRuntime._send_wire_op
            msg = dataclasses.replace(msg, contents=contents["contents"],
                                      metadata=contents["metadata"])
            contents = msg.contents
            if not isinstance(contents, dict):
                return [msg]
            kind = contents.get("type")
        if kind == CHUNKED:
            whole = self._accept_chunk(msg, contents)
            if whole is None:
                return []
            contents = whole
            kind = contents.get("type")
        if kind == COMPRESSED:
            contents = self._decompress(contents)
            kind = contents.get("type") if isinstance(contents, dict) else None
        if kind == GROUPED_BATCH:
            return self._ungroup(msg, contents)
        if contents is msg.contents:
            return [msg]
        return [dataclasses.replace(msg, contents=contents)]

    # ----------------------------------------------------------------- stages

    def _accept_chunk(self, msg: SequencedDocumentMessage, contents: dict):
        key = (msg.client_id, contents["chunkId"])
        pieces = self._chunks.setdefault(key, [])
        assert contents["chunkIndex"] == len(pieces), \
            "chunks arrive in sequence order (total-order broadcast)"
        pieces.append(contents["payload"])
        if len(pieces) < contents["totalChunks"]:
            return None
        del self._chunks[key]
        payload = "".join(pieces)
        return {"type": COMPRESSED, "payload": payload}

    @staticmethod
    def _decompress(contents: dict) -> dict:
        raw = zlib.decompress(base64.b64decode(contents["payload"]))
        return json.loads(raw)

    @staticmethod
    def _ungroup(msg: SequencedDocumentMessage, contents: dict
                 ) -> List[SequencedDocumentMessage]:
        out = []
        for op in contents["contents"]:
            out.append(dataclasses.replace(
                msg, contents=op["contents"], metadata=op["metadata"]))
        return out
