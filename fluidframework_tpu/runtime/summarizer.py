"""Summarizer: election, heuristics, summarize → upload → ack protocol.

Reference counterpart: ``SummaryManager`` + ``OrderedClientElection`` +
``RunningSummarizer`` / ``Summarizer`` in ``@fluidframework/container-runtime``
(SURVEY.md §2.8, §3.4; mount empty). Flow preserved from the reference:

1. **Election**: the oldest connected interactive client (first in quorum
   join order) is the summarizer-elect; every client computes the same
   election from the same quorum, no extra coordination ops needed.
2. **Heuristics**: the elected client summarizes when enough ops have
   accumulated since the last acked summary (``max_ops``) or enough time has
   passed (``max_time_s``, injected clock), with a minimum op floor so idle
   documents don't churn.
3. **Protocol**: build the full summary tree (protocol snapshot + runtime
   subtree) → upload to summary storage → submit a SUMMARIZE op carrying the
   storage handle → the service's Scribe validates and sequences a
   SUMMARY_ACK (or NACK) → on ack, the collaboration window trims (new
   clients load the summary and replay only the tail — §3.1).

The reference spawns a hidden non-interactive summarizer container; in this
host-driven design the elected client's manager summarizes in-process — the
same single-writer guarantee comes from election + Scribe's monotone
last-summary check.

TPU-first note: ``ContainerRuntime.summarize`` gathers device-resident DDS
state (e.g. compacted merge-tree segment arrays at the MSN) — the snapshot
IS the device→host gather, reusing the same kernels as catch-up (north
star; SURVEY.md §7.7).
"""

from __future__ import annotations

import dataclasses
import hashlib
import os
import pickle
import time
from typing import Callable, List, Optional, Tuple

from ..core.protocol import MessageType, SequencedDocumentMessage
from ..utils import tracing
from ..utils.faultpoints import SITE_SUMMARIZER_POST_UPLOAD, fault_point
from ..utils.telemetry import REGISTRY


class SummaryIntegrityError(RuntimeError):
    """No summary generation survived manifest verification — the ladder
    ran out of rungs (recovery must fall back to full-log replay)."""


class SummaryGenerationStore:
    """Multi-generation summary store with hashed manifests — the
    recovery ladder (ISSUE 10).

    Each ``save()`` writes one GENERATION: the summary blob (pickle —
    summaries carry numpy planes that JSON cannot round-trip losslessly)
    plus a small JSON manifest recording the blob's SHA-256, size, base
    seq, and generation number. The last ``keep`` generations are
    retained; older ones are pruned.

    ``load_latest()`` is the ladder: walk generations newest → oldest,
    verify each blob against its manifest BEFORE unpickling (a corrupt
    blob is never deserialized), and return the first generation that
    verifies, together with its ladder DEPTH (0 = newest). A deeper rung
    means an older summary — recovery still converges because the log
    tail replay is correspondingly longer (the summary's ``log_offsets``
    are older). Emits the ``recovery_ladder_depth`` gauge and counts
    ``summary_manifest_verify_failures_total`` per rejected rung; raises
    :class:`SummaryIntegrityError` when every rung fails.
    """

    _BLOB = "gen-{:08d}.summary.pkl"
    _MANIFEST = "gen-{:08d}.manifest.json"

    def __init__(self, directory: str, keep: int = 3):
        if keep < 1:
            raise ValueError("keep must be >= 1")
        self.directory = directory
        self.keep = keep
        os.makedirs(directory, exist_ok=True)
        # capacity plane (ISSUE 19): the generation store owns disk,
        # not heap — its census charge is the kept blobs' recorded
        # sizes (manifest reads, O(keep))
        from ..utils import capacity as _cap
        self._capacity_key = _cap.LEDGER.register(
            "SummaryGenerationStore", self.capacity_stats)

    def capacity_stats(self) -> dict:
        """Capacity report: bytes of every kept generation blob, from
        the manifests' recorded sizes (no blob reads)."""
        from ..utils.atomicfile import read_json
        total = 0
        gens = self.generations()
        for gen in gens:
            try:
                m = read_json(os.path.join(self.directory,
                                           self._MANIFEST.format(gen)))
                total += int(m.get("size", 0))
            except (OSError, ValueError):
                continue
        return {"host": {"summary_disk": total},
                "device": {}, "docs": 0,
                "generations": len(gens), "heaviest": []}

    # ------------------------------------------------------------- save
    def generations(self) -> List[int]:
        """Generation numbers with a manifest on disk, ascending."""
        out = []
        for name in os.listdir(self.directory):
            if name.startswith("gen-") and name.endswith(".manifest.json"):
                try:
                    out.append(int(name[4:12]))
                except ValueError:
                    continue
        return sorted(out)

    def save(self, summary: dict, seq: int) -> int:
        """Persist one generation (blob first, manifest last — a crash
        between the two leaves a manifest-less blob the ladder ignores).
        Returns the generation number."""
        gens = self.generations()
        gen = (gens[-1] + 1) if gens else 0
        blob = pickle.dumps(summary, protocol=pickle.HIGHEST_PROTOCOL)
        blob_path = os.path.join(self.directory, self._BLOB.format(gen))
        tmp = blob_path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(blob)
            f.flush()
            os.fsync(f.fileno())
        os.replace(tmp, blob_path)
        manifest = {"generation": gen, "seq": int(seq),
                    "sha256": hashlib.sha256(blob).hexdigest(),
                    "size": len(blob)}
        from ..utils.atomicfile import atomic_write_json
        atomic_write_json(
            os.path.join(self.directory, self._MANIFEST.format(gen)),
            manifest)
        for old in self.generations()[:-self.keep]:
            self._remove(old)
        REGISTRY.inc("summary_generations_written_total")
        return gen

    def _remove(self, gen: int) -> None:
        for fmt in (self._BLOB, self._MANIFEST):
            try:
                os.remove(os.path.join(self.directory, fmt.format(gen)))
            except OSError:
                pass

    # ------------------------------------------------------------- load
    def _verify_generation(self, gen: int) -> Tuple[Optional[bytes],
                                                    Optional[dict], str]:
        """(blob bytes, manifest, "") on success; (None, maybe-manifest,
        reason) on failure. Never unpickles an unverified blob."""
        from ..utils.atomicfile import read_json
        try:
            manifest = read_json(
                os.path.join(self.directory, self._MANIFEST.format(gen)))
        except (OSError, ValueError) as e:
            return None, None, f"manifest unreadable: {e}"
        try:
            with open(os.path.join(self.directory,
                                   self._BLOB.format(gen)), "rb") as f:
                blob = f.read()
        except OSError as e:
            return None, manifest, f"blob unreadable: {e}"
        if len(blob) != int(manifest.get("size", -1)):
            return None, manifest, (
                f"blob size {len(blob)} != manifest {manifest.get('size')}")
        digest = hashlib.sha256(blob).hexdigest()
        if digest != manifest.get("sha256"):
            return None, manifest, "sha256 mismatch"
        return blob, manifest, ""

    def load_generation(self, gen: int) -> Tuple[dict, int]:
        """Load + verify ONE generation; raises on any integrity failure."""
        blob, manifest, reason = self._verify_generation(gen)
        if blob is None:
            REGISTRY.inc("summary_manifest_verify_failures_total")
            raise SummaryIntegrityError(
                f"generation {gen} in {self.directory}: {reason}")
        return pickle.loads(blob), int(manifest["seq"])

    def load_latest(self) -> Tuple[dict, int, int]:
        """The recovery ladder: newest verified generation wins. Returns
        ``(summary, seq, depth)`` — depth 0 is the newest generation,
        each corrupt rung adds 1 (and a correspondingly longer tail
        replay for the caller). Raises :class:`SummaryIntegrityError`
        when no rung verifies."""
        gens = self.generations()
        reasons = []
        for depth, gen in enumerate(reversed(gens)):
            blob, manifest, reason = self._verify_generation(gen)
            if blob is None:
                REGISTRY.inc("summary_manifest_verify_failures_total")
                reasons.append(f"gen {gen}: {reason}")
                continue
            REGISTRY.set_gauge("recovery_ladder_depth", float(depth))
            if depth:
                from ..utils import flight_recorder
                flight_recorder.note("recovery_ladder_fallback",
                                     depth=depth, generation=gen)
            return pickle.loads(blob), int(manifest["seq"]), depth
        raise SummaryIntegrityError(
            f"no verifiable summary generation in {self.directory} "
            f"({len(gens)} tried): {'; '.join(reasons) or 'empty store'}")

    def verify_all(self) -> List[dict]:
        """Scrubber hook: verify every generation without loading any.
        Returns one problem dict per failing rung (empty = clean)."""
        problems = []
        for gen in self.generations():
            blob, _manifest, reason = self._verify_generation(gen)
            if blob is None:
                problems.append({"generation": gen, "reason": reason,
                                 "path": os.path.join(
                                     self.directory,
                                     self._BLOB.format(gen))})
        return problems


@dataclasses.dataclass
class SummaryConfig:
    """Reference: ISummaryConfiguration (§5.6)."""

    max_ops: int = 100            # ops since last ack that force a summary
    min_ops: int = 1              # never summarize with fewer new ops
    max_time_s: float = 60.0      # time since last ack that forces a summary
    max_attempts: int = 3         # consecutive nacks before giving up
    #: channel-handle reuse: unchanged channels upload a handle node
    #: referencing the last ACKED summary (storage materializes it)
    incremental: bool = True


class SummaryManager:
    """Per-container summarization agent. Wire one to a loaded container:
    ``SummaryManager(container)``; it listens to the op stream, and on the
    elected client runs the summarize protocol automatically. Works with
    both the synchronous local driver (echo + ack are processed reentrantly
    inside ``submit``) and an async stream (they arrive later)."""

    def __init__(self, container,
                 config: Optional[SummaryConfig] = None,
                 clock: Optional[Callable[[], float]] = None,
                 generation_store: Optional[SummaryGenerationStore] = None):
        self.container = container
        self.config = config or SummaryConfig()
        self.clock = clock or time.monotonic
        #: optional recovery-ladder sink: every uploaded summary is also
        #: persisted as a hashed generation (ISSUE 10)
        self.generation_store = generation_store
        self.last_ack_seq = container.base_seq
        self.last_ack_time = self.clock()
        self._in_flight = False
        self._inflight_capture = None   # channel seqs of the upload
        self.pending_proposal: Optional[int] = None  # seq of our SUMMARIZE op
        self.failed_attempts = 0
        self.summaries_acked = 0
        self.summaries_nacked = 0
        container.on("op", self._on_op)
        # a proposal in flight when the connection drops is lost (the op
        # never sequences for a dead client) — reset so the next elected
        # window can try again
        container.on("disconnected", self._on_disconnected)

    def _on_disconnected(self, _reason: str) -> None:
        self._in_flight = False
        self.pending_proposal = None

    # --------------------------------------------------------------- election

    @property
    def elected_client(self) -> Optional[int]:
        """Oldest quorum member (join order) — reference:
        OrderedClientElection."""
        members = self.container.quorum.members
        return next(iter(members), None)

    @property
    def is_elected(self) -> bool:
        cid = self.container.client_id
        return cid is not None and cid == self.elected_client

    # -------------------------------------------------------------- op stream

    def _on_op(self, msg: SequencedDocumentMessage) -> None:
        if msg.type == MessageType.SUMMARIZE:
            if self._in_flight and msg.is_from(self.container.client_id) \
                    and self.pending_proposal is None:
                self.pending_proposal = msg.seq
            return
        if msg.type == MessageType.SUMMARY_ACK:
            self.last_ack_seq = msg.contents["summaryProposal"]
            self.last_ack_time = self.clock()
            if self._in_flight \
                    and msg.contents["summaryProposal"] == \
                    self.pending_proposal:
                self._in_flight = False
                self.pending_proposal = None
                self.failed_attempts = 0
                self.summaries_acked += 1
                # unchanged channels may now reference this summary by
                # handle (channel-handle reuse, SURVEY.md §2.16); the
                # baseline is the capture taken at UPLOAD time, immune
                # to out-of-band summarize() calls in between
                self.container.runtime.on_summary_ack(
                    self._inflight_capture)
                self._inflight_capture = None
            return
        if msg.type == MessageType.SUMMARY_NACK:
            if self._in_flight \
                    and msg.contents.get("summaryProposal") == \
                    self.pending_proposal:
                self._in_flight = False
                self.pending_proposal = None
                self.failed_attempts += 1
                self.summaries_nacked += 1
            return
        self.maybe_summarize()

    # ------------------------------------------------------------- heuristics

    def should_summarize(self) -> bool:
        """RunningSummarizer heuristics (§3.4)."""
        if not self.is_elected or not self.container.connected:
            return False
        if self._in_flight:
            return False              # one in-flight proposal at a time
        if self.failed_attempts >= self.config.max_attempts:
            return False              # give up until the next ack resets us
        new_ops = self.container.protocol.seq - self.last_ack_seq
        if new_ops < self.config.min_ops:
            return False
        if new_ops >= self.config.max_ops:
            return True
        return (self.clock() - self.last_ack_time) >= self.config.max_time_s

    def maybe_summarize(self) -> bool:
        if not self.should_summarize():
            return False
        self.summarize_now()
        return True

    # ---------------------------------------------------------------- the act

    def summarize_now(self) -> int:
        """Run one summarize attempt; returns the summary's base seq.
        (Callable directly for on-demand summaries — reference:
        summarizeOnDemand.)"""
        container = self.container
        seq = container.protocol.seq
        with tracing.span("summarize", seq=seq) as sp:
            with tracing.span("summarize.build"):
                summary = {
                    "protocol": container.protocol.snapshot(),
                    # incremental is a no-op until the first ack
                    # establishes the handle-reuse baseline (summarize
                    # falls back to full)
                    "runtime": container.runtime.summarize(
                        incremental=self.config.incremental),
                }
            self._inflight_capture = \
                container.runtime.take_summary_capture()
            t0 = time.perf_counter()
            handle = container.service.summary_storage.upload_summary(
                summary, seq)
            REGISTRY.inc("summary_uploads")
            REGISTRY.observe("summary_upload_ms",
                             (time.perf_counter() - t0) * 1000)
            if self.generation_store is not None:
                # recovery-ladder rung: same summary, hashed manifest
                self.generation_store.save(summary, seq)
            sp.annotate(handle=handle)
            # crash here = summary uploaded but the SUMMARIZE proposal
            # never sequenced: the upload is an orphan blob, no ack ever
            # references it, and a restarted summarizer must re-propose
            # from the last ACKED summary (never resume this one)
            fault_point(SITE_SUMMARIZER_POST_UPLOAD, seq=seq,
                        handle=handle)
            # mark in-flight BEFORE submit: the synchronous local
            # pipeline processes the echo (which records
            # pending_proposal) and the ack reentrantly inside this call
            self._in_flight = True
            self.pending_proposal = None
            REGISTRY.inc("summary_proposals")
            container.submit({"handle": handle, "summarySeq": seq},
                             MessageType.SUMMARIZE)
        return seq
