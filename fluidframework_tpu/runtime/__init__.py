"""Container runtime layer (reference: @fluidframework/container-runtime,
datastore, id-compressor — SURVEY.md §2.8/§2.9/§2.11)."""

from .container_runtime import (
    ContainerRuntime,
    ContainerRuntimeOptions,
    DEFAULT_DATASTORE,
)
from .datastore import FluidDataStoreRuntime
from .gc import GarbageCollector, collect_handles, fluid_handle, is_handle
from .id_compressor import IdCompressor, IdCreationRange, stable_id
from .outbox import Outbox
from .pending_state import PendingStateManager
from .remote_message_processor import RemoteMessageProcessor
from .summarizer import SummaryConfig, SummaryManager

__all__ = [
    "ContainerRuntime",
    "ContainerRuntimeOptions",
    "DEFAULT_DATASTORE",
    "FluidDataStoreRuntime",
    "GarbageCollector",
    "collect_handles",
    "fluid_handle",
    "is_handle",
    "IdCompressor",
    "IdCreationRange",
    "stable_id",
    "Outbox",
    "PendingStateManager",
    "RemoteMessageProcessor",
    "SummaryConfig",
    "SummaryManager",
]
