"""FluidDataStoreRuntime: per-datastore channel registry and routing.

Reference counterpart: ``@fluidframework/datastore``
(``FluidDataStoreRuntime``, ``LocalChannelContext``/``RemoteChannelContext``)
+ the addressing scheme of ``runtime-definitions`` — SURVEY.md §2.9, §3.2
(mount empty). A datastore owns a set of channels (DDS instances) addressed
``/dataStoreId/channelId``; the container runtime routes the outer envelope,
the datastore routes the inner one. Channels are realized lazily from the
datastore's summary on first access (reference: RemoteChannelContext).
"""

from __future__ import annotations

import dataclasses
from typing import Any, Callable, Dict, Optional

from ..core.protocol import SequencedDocumentMessage
from ..models.shared_object import ChannelRegistry, SharedObject


class FluidDataStoreRuntime:
    def __init__(self, ds_id: str, registry: ChannelRegistry,
                 client_id: int,
                 submit_fn: Callable[[dict, Optional[dict]], None],
                 on_channel_create: Optional[
                     Callable[["FluidDataStoreRuntime", SharedObject],
                              None]] = None):
        """``submit_fn(inner_envelope, metadata)`` forwards to the container
        runtime, which wraps it in the outer ``{address: ds_id}`` envelope.
        ``on_channel_create(ds, channel)`` fires for every locally-created
        channel — the runtime uses it to announce channels to remote
        replicas (channel attach ops), so it must be wired on every
        construction path."""
        self.id = ds_id
        self.registry = registry
        self.client_id = client_id
        self._submit = submit_fn
        self._on_channel_create = on_channel_create
        self._channels: Dict[str, SharedObject] = {}
        # channelId -> summary not yet realized into a live channel
        self._pending_summaries: Dict[str, dict] = {}

    # --------------------------------------------------------------- channels

    def create_channel(self, channel_id: str, type_name: str) -> SharedObject:
        assert channel_id not in self._channels \
            and channel_id not in self._pending_summaries, \
            f"channel {channel_id!r} already exists"
        channel = self.registry.get(type_name).create(channel_id,
                                                      self.client_id)
        self._wire(channel)
        self._channels[channel_id] = channel
        if self._on_channel_create is not None:
            self._on_channel_create(self, channel)
        return channel

    def get_channel(self, channel_id: str) -> SharedObject:
        """Realize-on-demand (reference: RemoteChannelContext.getChannel)."""
        if channel_id not in self._channels:
            summary = self._pending_summaries.pop(channel_id)
            channel = self.registry.get(summary["type"]).load(
                channel_id, self.client_id, summary,
                summary.get("baseSeq", 0))
            self._wire(channel)
            self._channels[channel_id] = channel
        return self._channels[channel_id]

    def has_channel(self, channel_id: str) -> bool:
        return channel_id in self._channels \
            or channel_id in self._pending_summaries

    def channel_ids(self):
        return sorted(set(self._channels) | set(self._pending_summaries))

    def _wire(self, channel: SharedObject) -> None:
        channel.connect(lambda contents, _id=channel.id:
                        self._submit({"address": _id, "contents": contents},
                                     None))

    def set_client_id(self, client_id: int) -> None:
        """New connection: channels stamp local ops with the new id."""
        self.client_id = client_id
        for ch in self._channels.values():
            ch.on_client_id_changed(client_id)

    # ---------------------------------------------------------------- inbound

    def process(self, msg: SequencedDocumentMessage, local: bool) -> None:
        """Route the inner envelope ``{address, contents}`` to its channel
        (``msg.contents`` is the outer ``{address: ds_id, contents: inner}``
        envelope the container runtime routed by)."""
        inner = msg.contents["contents"]
        channel = self.get_channel(inner["address"])
        channel.deliver(
            dataclasses.replace(msg, contents=inner["contents"],
                                address=channel.id),
            local)

    def resubmit(self, inner: dict, metadata: Optional[dict] = None) -> None:
        """Reconnect path: let the channel rebase, then resend with the
        original local-op metadata preserved (§3.3). A rebase may drop the
        op (None) or split it into several (list)."""
        channel = self.get_channel(inner["address"])
        rebased = channel.rebase_op(inner["contents"])
        if rebased is None:
            return
        if isinstance(rebased, dict):
            rebased = [rebased]
        for contents in rebased:
            self._submit({"address": channel.id, "contents": contents},
                         metadata)

    def on_min_seq(self, min_seq: int) -> None:
        for ch in self._channels.values():
            ch.on_min_seq(min_seq)

    # -------------------------------------------------------------- summaries

    def summarize(self, prev_channel_seqs: Optional[Dict[str, int]] = None
                  ) -> dict:
        """Summary subtree: one entry per channel (realized channels
        summarize live; unrealized ones pass their loaded summary through —
        reference: summarizer handle reuse for unchanged subtrees).

        ``prev_channel_seqs`` ({channel id → baseSeq at the last ACKED
        summary}) enables channel-handle reuse: a channel that processed
        no op since then emits a ``__handle__`` node referencing its
        subtree in the prior summary instead of re-serializing — the
        storage service materializes it at upload (SURVEY.md §2.16:
        incremental via handle reuse)."""
        # baseSeq records each channel's capture point (reference: the
        # .attributes sequence number) so realization restores the base
        # perspective; unrealized passthrough summaries keep their original
        channels = {}
        for cid, ch in self._channels.items():
            base = ch.last_processed_seq
            if prev_channel_seqs is not None \
                    and prev_channel_seqs.get(cid) == base:
                # structural (ds, channel) path: ids may contain any
                # character, so no string splitting at resolution
                channels[cid] = {
                    "__handle__": [self.id, cid], "baseSeq": base}
            else:
                channels[cid] = dict(ch.summarize(), baseSeq=base)
        channels.update(self._pending_summaries)
        return {"channels": channels}

    def channel_seqs(self) -> Dict[str, int]:
        """{channel id → last processed seq} (handle-reuse baselines)."""
        return {cid: ch.last_processed_seq
                for cid, ch in self._channels.items()}

    @classmethod
    def load(cls, ds_id: str, registry: ChannelRegistry, client_id: int,
             submit_fn, summary: dict,
             on_channel_create=None) -> "FluidDataStoreRuntime":
        ds = cls(ds_id, registry, client_id, submit_fn,
                 on_channel_create=on_channel_create)
        ds._pending_summaries = dict(summary.get("channels", {}))
        return ds
